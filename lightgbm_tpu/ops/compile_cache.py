"""Process-level compiled-program cache shared across trees, boosters
and repeated ``train()`` calls.

The round-7 orchestration problem (ISSUE 7, ROADMAP item 1): every
compiled round body the package builds per *call* — the fused round
runner ``jax.jit``-ed inside ``GBDT.train_fused``, the ``shard_map``
wrappers rebuilt per tree in ``parallel/data_parallel.py``, the GSPMD
fused-scan entry — dies with the object that built it.  Back-to-back
``train()`` calls in one process each paid the full XLA compile again
(the old ``GBDT._fused_cache`` dict lived on the booster, reset by
``_derive_learner_state``), and every tree of a distributed run re-ran
Python tracing for a program whose compiled executable already existed.

This registry is the single process-level home for such programs:

  * **Keyed on meaning, not identity** — a cache key is (entry name,
    shape signature, hyper signature, kernel/mode statics).  Helper
    builders (:func:`sig`, :func:`mesh_signature`) render arrays as
    (shape, dtype) and meshes as (axes, device grid) so two callers
    with the same program geometry share one compiled runner.
  * **Weakly anchored** — entries whose compiled closure captures a
    Dataset's device arrays register the dataset as an *anchor*: the
    entry is evicted the moment the dataset is garbage-collected, so
    the cache never pins a dead dataset's HBM.  Anchor tokens are
    monotonic (never recycled), so an ``id()`` reused by a new object
    can never alias a dead key.
  * **Bounded** — LRU beyond ``max_entries``
    (``LGBMTPU_COMPILE_CACHE_SIZE`` overrides; the compiled runners a
    training process legitimately alternates between number in the
    single digits).
  * **Counted** — every lookup bumps ``round_compile_hits`` /
    ``round_compile_misses`` (obs/metrics.py), per-booster and
    process-global, which is what the tier-1 compile-count regression
    gate asserts on: a second ``train()`` over identical shapes must
    show zero misses.

Counter bumps happen on the host at build/lookup time only — never
inside jitted code (a traced bump would count compilations, not
executions; obs/metrics.py module contract).
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, Hashable, Iterable, Optional, Tuple

from ..obs.metrics import MetricsRegistry, count_event

#: default LRU bound; override with LGBMTPU_COMPILE_CACHE_SIZE
DEFAULT_MAX_ENTRIES = 64


def _max_entries_from_env() -> int:
    try:
        return max(1, int(os.environ.get("LGBMTPU_COMPILE_CACHE_SIZE",
                                         DEFAULT_MAX_ENTRIES)))
    except ValueError:
        return DEFAULT_MAX_ENTRIES


def sig(x: Any) -> Hashable:
    """Hashable *shape signature* of a pytree of arrays.

    Arrays (anything with ``.shape``/``.dtype``) render as
    ``("arr", shape, dtype)``; ``None`` stays ``None``; containers
    recurse (namedtuples keep their type name so two different record
    layouts with identical leaves cannot collide); scalars pass through
    when hashable.  Only GEOMETRY is captured — array *contents* must be
    either traced arguments of the cached program or covered by an
    anchor/key component the caller supplies.
    """
    if x is None:
        return None
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return ("arr", tuple(x.shape), str(x.dtype))
    if isinstance(x, tuple) and hasattr(x, "_fields"):  # namedtuple
        return (type(x).__name__,) + tuple(sig(v) for v in x)
    if isinstance(x, (tuple, list)):
        return ("seq",) + tuple(sig(v) for v in x)
    if isinstance(x, dict):
        return ("map",) + tuple(sorted((k, sig(v)) for k, v in x.items()))
    try:
        hash(x)
        return x
    except TypeError:
        return repr(x)


def mesh_signature(mesh: Any) -> Hashable:
    """Signature of a jax ``Mesh``: axis names, device-grid shape and the
    (platform, id) of every device — two meshes over the same physical
    devices share compiled programs, a changed topology cannot."""
    if mesh is None:
        return None
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape),
            tuple((d.platform, d.id) for d in mesh.devices.flat))


class CompileCache:
    """Bounded, weakly-anchored LRU of built callables (usually
    ``jax.jit`` wrappers).  Thread-safe; builders run outside the lock
    (building is cheap — the XLA compile itself happens lazily on first
    call of the returned wrapper, under jax's own locking)."""

    def __init__(self, max_entries: Optional[int] = None) -> None:
        self._entries: "OrderedDict[Hashable, Callable]" = OrderedDict()
        self._anchor_tokens: "weakref.WeakKeyDictionary[Any, int]" = \
            weakref.WeakKeyDictionary()
        self._anchor_keys: Dict[int, set] = {}
        self._next_token = 0
        self._lock = threading.RLock()
        self.max_entries = max_entries or _max_entries_from_env()
        self._hits = 0
        self._misses = 0

    # ------------------------------------------------------------ anchors
    def anchor_token(self, obj: Any) -> Optional[int]:
        """Monotonic token for ``obj``'s lifetime.  Include it in a cache
        key to bind the entry to the object's identity; entries tagged
        with it (via ``get_or_build(anchors=...)``) are evicted when the
        object is collected.  ``None`` passes through."""
        if obj is None:
            return None
        with self._lock:
            tok = self._anchor_tokens.get(obj)
            if tok is None:
                tok = self._next_token
                self._next_token += 1
                self._anchor_tokens[obj] = tok
                weakref.finalize(obj, self._drop_anchor, tok)
            return tok

    def _drop_anchor(self, tok: int) -> None:
        with self._lock:
            for key in self._anchor_keys.pop(tok, ()):
                self._entries.pop(key, None)

    # ------------------------------------------------------------- lookup
    def get_or_build(self, key: Hashable, builder: Callable[[], Callable],
                     *, anchors: Iterable[Any] = (),
                     metrics: Optional[MetricsRegistry] = None,
                     counter_ns: str = "round", store=None,
                     aot_args: Optional[tuple] = None) -> Callable:
        """Return the cached callable for ``key``, building (and
        counting a miss) when absent.  ``anchors``: objects whose device
        arrays the built callable closes over — their tokens both extend
        the key (so a *different* dataset with identical shapes can
        never reuse a closure over the old one's arrays) and bound the
        entry's lifetime to theirs.  ``counter_ns`` picks the telemetry
        namespace: ``"round"`` (training round bodies, the default),
        ``"serve"`` (serving-tier predict programs) or ``"rank"``
        (query-length-bucketed ranking programs) — spelled as literal
        branches below because the OBS301 lint contract requires counter
        names to appear as string literals at the bump site.

        ``store``/``aot_args`` add the DISK tier (memory -> disk ->
        build): with an :class:`~..ops.aot_store.AOTStore` and the
        concrete call arguments, a memory miss first tries to
        deserialize a previously persisted executable (zero lowerings),
        and a disk miss AOT-compiles ``builder()``'s callable at
        ``aot_args`` and persists it for every later process.  The
        builder must then return a plain positional callable over
        exactly ``aot_args`` (statics closed over).  The store key is
        ``key`` alone — anchor tokens are process identities and never
        reach disk; array contents are ARGUMENTS of the compiled
        program, so geometry-identical callers correctly share one
        artifact."""
        toks = tuple(self.anchor_token(a) for a in anchors)
        full_key = (key, toks)
        with self._lock:
            fn = self._entries.get(full_key)
            if fn is not None:
                self._entries.move_to_end(full_key)
                self._hits += 1
        if fn is not None:
            if counter_ns == "serve":
                count_event("serve_compile_hits", 1, metrics)
            elif counter_ns == "rank":
                count_event("rank_compile_hits", 1, metrics)
            else:
                count_event("round_compile_hits", 1, metrics)
            return fn
        fn = None
        if store is not None and aot_args is not None:
            fn = store.load(key)
            if fn is None:
                fn = store.compile_and_save(key, builder(), aot_args)
        if fn is None:
            fn = builder()
        if counter_ns == "serve":
            count_event("serve_compile_misses", 1, metrics)
        elif counter_ns == "rank":
            count_event("rank_compile_misses", 1, metrics)
        else:
            count_event("round_compile_misses", 1, metrics)
        with self._lock:
            self._misses += 1
            # a racing builder may have landed first; last write wins —
            # both callables trace to the same program
            self._entries[full_key] = fn
            self._entries.move_to_end(full_key)
            for tok in toks:
                if tok is not None:
                    self._anchor_keys.setdefault(tok, set()).add(full_key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
        return fn

    # -------------------------------------------------------------- admin
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._entries), "hits": self._hits,
                    "misses": self._misses,
                    "max_entries": self.max_entries}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._anchor_keys.clear()
            self._hits = 0
            self._misses = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


#: the process-wide cache every round-body entry shares (fused runners,
#: shard_map wrappers, GSPMD entries, device predict programs)
GLOBAL_COMPILE_CACHE = CompileCache()


def get_or_build(key: Hashable, builder: Callable[[], Callable], *,
                 anchors: Iterable[Any] = (),
                 metrics: Optional[MetricsRegistry] = None,
                 counter_ns: str = "round", store=None,
                 aot_args: Optional[tuple] = None) -> Callable:
    """Module-level convenience over :data:`GLOBAL_COMPILE_CACHE`."""
    return GLOBAL_COMPILE_CACHE.get_or_build(key, builder, anchors=anchors,
                                             metrics=metrics,
                                             counter_ns=counter_ns,
                                             store=store,
                                             aot_args=aot_args)
