"""Small-table row lookups without lane-dim gathers.

``table[idx]`` for a [n]-sized index vector is the slowest primitive on TPU
(~8 ms per 1M rows through XLA's gather, docs/PERF_NOTES.md) yet the GBDT
score update needs exactly that: ``scores += lr * leaf_value[leaf_of_row]``
(reference score_updater.hpp:21 AddScore).  For tables bounded by num_leaves
(<= a few hundred) the lookup is reformulated as a VMEM one-hot contraction:
per row block, onehot(idx) @ table rides the MXU and costs ~0.3 ms/1M —
~25x faster than the gather.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax.experimental import pallas as pl
    from .hist_pallas import HAS_PALLAS, _round_up
except ImportError:  # pragma: no cover
    HAS_PALLAS = False

# keep the in-kernel one-hot under ~4 MB so the scoped-VMEM budget holds at
# any admitted table size
_ONEHOT_BUDGET = 1 << 20  # f32 elements


@functools.partial(jax.jit, static_argnames=("rows_per_block", "interpret"))
def _take_pallas(idx: jax.Array, table: jax.Array, *,
                 rows_per_block: int = 8192,
                 interpret: bool = False) -> jax.Array:
    n = idx.shape[0]
    t = table.shape[0]
    t_pad = _round_up(max(t, 1), 128)
    if t_pad != t:
        table = jnp.pad(table, (0, t_pad - t))
    blk = min(rows_per_block, max(128, _ONEHOT_BUDGET // t_pad // 128 * 128))
    blk = min(blk, max(128, _round_up(n, 128)))
    n_pad = _round_up(max(n, 1), blk)
    if n_pad != n:
        idx = jnp.pad(idx, (0, n_pad - n))
    nb = n_pad // blk
    idx2 = idx[None, :]
    table2 = table.reshape(t_pad // 16, 16)   # radix rows (hi, lo)

    nhi = t_pad // 16

    def kernel(idx_ref, tab_ref, out_ref):
        ix = idx_ref[0, :]                                   # [blk] i32
        # radix-split lookup: idx = 16*hi + lo.  tmp = oh_hi @ TAB[nhi, 16]
        # then a 16-wide elementwise select on lo — 2*(nhi+16) one-hot
        # elements per row instead of t_pad (same trick as the histogram
        # radix kernels; measured ~5x on the 1M-row score update).
        # HIGHEST precision: the one-hot payload must come through exact.
        hi = ix >> 4
        lo = ix & 15
        iota_h = lax.iota(jnp.int32, nhi)
        oh_hi = (hi[:, None] == iota_h[None, :]).astype(jnp.float32)
        tmp = lax.dot_general(
            oh_hi, tab_ref[:, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST)                 # [blk, 16]
        iota_l = lax.iota(jnp.int32, 16)
        sel = (lo[:, None] == iota_l[None, :]).astype(jnp.float32)
        out_ref[0, :] = jnp.sum(tmp * sel, axis=1)

    out = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((t_pad // 16, 16), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        interpret=interpret,
    )(idx2, table2)
    return out[0, :n]


def take_small_table(table: jax.Array, idx: jax.Array) -> jax.Array:
    """``table[idx]`` for f32 ``table`` [T<=2048] and i32 ``idx`` [n].

    Out-of-range indices (e.g. -1) return 0.0.
    """
    if (HAS_PALLAS and jax.default_backend() == "tpu"
            and table.shape[0] <= 2048 and idx.ndim == 1):
        return _take_pallas(jnp.asarray(idx, jnp.int32),
                            jnp.asarray(table, jnp.float32))
    safe = jnp.clip(idx, 0, table.shape[0] - 1)
    ok = (idx >= 0) & (idx < table.shape[0])
    return jnp.where(ok, table[safe], 0.0)
