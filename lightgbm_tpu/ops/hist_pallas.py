"""Pallas TPU histogram kernel.

TPU-native equivalent of the reference's hot CUDA kernel (reference:
src/treelearner/cuda/cuda_histogram_constructor.cu:18
``CUDAConstructHistogramDenseKernel`` — per-block shared-memory atomic
scatter-add then global flush).  The TPU has no fast scatter, so the kernel
reformulates the histogram as MXU one-hot contractions with the one-hot
existing ONLY in VMEM (never materialized to HBM — the reason a plain XLA
einsum can't be used on the hot path):

  grid step = one row block; per feature chunk:
    onehot[fc*B, R] = (bins[fc, r] == iota_B)    built in VMEM, bf16
    out[C, fc*B]   += vals[C, R] @ onehot^T      MXU, f32 accumulation

Layouts put the row dimension last (lane dim, 128-aligned):
  bins_T [F, n] uint8, vals_T [C, n] f32, out [C, F*B] f32.
The sequential TPU grid revisits the same output block, giving cheap
cross-block accumulation (zeroed at step 0 via pl.when).

The contraction dtype defaults to float32 for split-decision parity with
the reference (its CUDA learner accumulates fp64 by default, config.h:1129
``gpu_use_dp``).  Set ``tpu_hist_dtype=bfloat16`` in the Config to run the
MXU contraction at ~8x rate: the one-hot stays exact and accumulation is
f32, only grad/hess suffer ~2^-9 relative input rounding — the count
channel stays exact since 1.0 is representable.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:  # Pallas TPU backend
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
except ImportError:  # pragma: no cover
    HAS_PALLAS = False


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _pick_fc(num_f: int, requested: int = 0) -> int:
    """Feature-chunk size minimizing feature padding (0 = auto).

    The kernel pads F up to a multiple of the chunk; a chunk that divides F
    exactly (e.g. 14 for Higgs' 28 features instead of a fixed 8, which
    padded to 32) cuts ~15% of one-hot work — measured ~5.5 vs ~7.3 ms per
    full 1M-row pass (docs/PERF_NOTES.md).
    """
    if requested:
        return min(requested, num_f)
    if num_f <= 16:
        return num_f
    best, best_pad = 8, _round_up(num_f, 8)
    for fc in (16, 15, 14, 13, 12, 11, 10, 9, 8, 7, 6, 5, 4):
        pad = _round_up(num_f, fc)
        if pad < best_pad or (pad == best_pad and fc > best):
            best, best_pad = fc, pad
    return best


def _prec(compute_dtype):
    """MXU precision for the one-hot contraction.

    The TPU's default f32 matmul runs ONE bf16 pass (~2^-8 product
    rounding), which silently breaks the float32 split-parity contract
    (docs/PERF_NOTES.md).  HIGHEST makes products exact so f32 accumulation
    is the only rounding left (Mosaic supports only DEFAULT/HIGHEST).
    """
    return (lax.Precision.HIGHEST if jnp.dtype(compute_dtype) == jnp.float32
            else lax.Precision.DEFAULT)


def _oh_contract(vals, oh_b, compute_dtype):
    """vals [C, blk] (compute-dtype for float modes, int8 for int mode)
    x bool one-hot [M, blk] -> [C, M] in the ACCUMULATOR dtype
    (``_acc_dtype``): int32 for int8 mode, f32 otherwise.  The shared
    int8/float dot used by the flat masked, payload and plain kernels.

    int8 keeps the accumulator INTEGER end-to-end: f32 `+=` across row
    blocks rounds beyond 2^24 (at Higgs 10.5M rows the per-node error
    random-walks to ~1e2 level units and histogram SUBTRACTION hands
    that error to small children — measured as a 0.04 AUC drop at 10.5M
    x 500 iters, round 4); i32 is exact to 2^31 with ONE deterministic
    f32 rounding at kernel exit."""
    if _is_int8(compute_dtype):
        oh = oh_b.astype(jnp.int8)
        return lax.dot_general(
            vals, oh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
    oh = oh_b.astype(compute_dtype)
    return lax.dot_general(vals, oh, (((1,), (1,)), ((), ())),
                           preferred_element_type=jnp.float32,
                           precision=_prec(compute_dtype))


def _acc_dtype(compute_dtype):
    return jnp.int32 if _is_int8(compute_dtype) else jnp.float32


def _is_int8(compute_dtype) -> bool:
    """int8 MXU mode: quantized-gradient levels ride the int8 systolic
    path (~1.6x the bf16 rate measured on v5e, docs/PERF_NOTES.md round
    4).  Valid ONLY when grad/hess carry small-integer values (the
    ``use_quantized_grad`` contract, ops/quantize.py): products are
    exact int32 and the f32 accumulation bound matches the bf16 mode's.
    Mosaic legalizes bool->i8 and i32<->i8 casts and i8 dots on this
    toolchain (the round-3 note claiming otherwise predates it); i8
    elementwise multiplies still do NOT legalize, so masked values are
    built in i32 and cast to i8 just before the dot."""
    return jnp.dtype(compute_dtype) == jnp.int8


@functools.partial(jax.jit,
                   static_argnames=("n_bins", "rows_per_block",
                                    "feats_per_chunk", "compute_dtype",
                                    "interpret"))
def histogram_pallas(bins_t: jax.Array, vals_t: jax.Array, *, n_bins: int,
                     rows_per_block: int = 2048, feats_per_chunk: int = 0,
                     compute_dtype=jnp.bfloat16,
                     interpret: bool = False) -> jax.Array:
    """hist[f, b, c] from transposed operands.

    bins_t: uint8 [F, n] (row dim last); vals_t: f32 [C, n] (masked rows
    carry zeros).  Returns f32 [F, n_bins, C].
    """
    num_f, n = bins_t.shape
    c = vals_t.shape[0]
    blk = min(rows_per_block, max(128, _round_up(n, 128)))
    n_pad = _round_up(max(n, 1), blk)
    if n_pad != n:
        bins_t = jnp.pad(bins_t, ((0, 0), (0, n_pad - n)))
        vals_t = jnp.pad(vals_t, ((0, 0), (0, n_pad - n)))
    fc = _pick_fc(num_f, feats_per_chunk)
    f_pad = _round_up(num_f, fc)
    if f_pad != num_f:
        bins_t = jnp.pad(bins_t, ((0, f_pad - num_f), (0, 0)))
    nb = n_pad // blk

    def kernel(bins_ref, vals_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        b_blk = bins_ref[:].astype(jnp.int32)          # [f_pad, blk]
        if _is_int8(compute_dtype):
            v_blk = vals_ref[:].astype(jnp.int32).astype(jnp.int8)
        else:
            v_blk = vals_ref[:].astype(compute_dtype)  # [c, blk]
        iota = lax.iota(jnp.int32, n_bins)
        for f0 in range(0, f_pad, fc):
            chunk = b_blk[f0:f0 + fc]                  # [fc, blk]
            oh_b = (chunk[:, None, :] == iota[None, :, None]
                    ).reshape(fc * n_bins, blk)
            acc = _oh_contract(v_blk, oh_b, compute_dtype)     # [c, fc*B]
            out_ref[:, f0 * n_bins:(f0 + fc) * n_bins] += acc

    out = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((f_pad, blk), lambda i: (0, i)),
            pl.BlockSpec((c, blk), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((c, f_pad * n_bins), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((c, f_pad * n_bins),
                                       _acc_dtype(compute_dtype)),
        interpret=interpret,
    )(bins_t, vals_t)
    out = out.astype(jnp.float32)
    # [C, F*B] -> [F, B, C]
    out = out.reshape(c, f_pad, n_bins).transpose(1, 2, 0)
    return out[:num_f]


@functools.partial(jax.jit,
                   static_argnames=("n_bins", "rows_per_block",
                                    "feats_per_chunk", "compute_dtype",
                                    "rows_major", "interpret"))
def _histogram_leaves_impl(bins: jax.Array, grad: jax.Array,
                           hess: jax.Array, leaf_of_row: jax.Array,
                           leaves: jax.Array, *, n_bins: int,
                           rows_per_block: int = 2048,
                           feats_per_chunk: int = 0,
                           compute_dtype=jnp.bfloat16,
                           rows_major: bool = False,
                           interpret: bool = False) -> jax.Array:
    """Fused masked multi-leaf histogram: f32 [K, F, n_bins, 4].

    Builds the per-leaf (grad, hess, count) value channels INSIDE the kernel
    (sel masks live only in VMEM), so K leaves cost one one-hot pass with no
    [3K, n] HBM materialization — the separate mask+stack stage measured
    ~12 ms/round at K=16 on 1M rows, ~2x the whole kernel (docs/PERF_NOTES.md).

    ``bins``: u8 [F, n] transposed (``rows_major=False``, the resident
    training layout) or u8 [S, F] row-major (``rows_major=True``, the layout
    a compacted-frontier row gather produces — row gathers from [n, F] are
    contiguous DMAs; lane-dim gathers from [F, n] are the slowest TPU
    primitive).  grad/hess: f32 [n]; leaf_of_row: i32 [n] (-1 = excluded
    row, e.g. bagging); leaves: i32 [K] (dummy slots may repeat).  Channel 3
    of the output is zero padding for API parity.
    """
    if rows_major:
        n, num_f = bins.shape
    else:
        num_f, n = bins.shape
    K = leaves.shape[0]
    blk = min(rows_per_block, max(128, _round_up(n, 128)))
    n_pad = _round_up(max(n, 1), blk)
    if n_pad != n:
        row_pad = ((0, n_pad - n), (0, 0)) if rows_major \
            else ((0, 0), (0, n_pad - n))
        bins = jnp.pad(bins, row_pad)
        grad = jnp.pad(grad, (0, n_pad - n))
        hess = jnp.pad(hess, (0, n_pad - n))
        leaf_of_row = jnp.pad(leaf_of_row, (0, n_pad - n),
                              constant_values=-1)
    fc = _pick_fc(num_f, feats_per_chunk)
    f_pad = _round_up(num_f, fc)
    if f_pad != num_f:
        feat_pad = ((0, 0), (0, f_pad - num_f)) if rows_major \
            else ((0, f_pad - num_f), (0, 0))
        bins = jnp.pad(bins, feat_pad)
    nb = n_pad // blk
    grad2 = grad[None, :]
    hess2 = hess[None, :]
    lor2 = leaf_of_row[None, :]
    leaves2 = leaves[None, :]

    def kernel(bins_ref, g_ref, h_ref, lor_ref, leaves_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        lor_b = lor_ref[0, :]                               # [blk] i32
        sel = lor_b[None, :] == leaves_ref[0, :][:, None]   # [K, blk]
        if _is_int8(compute_dtype):
            # integer masking by multiply is NaN-safe (0 * anything = 0
            # in int); levels are small ints so f32->i32 is exact
            seli = sel.astype(jnp.int32)
            gm = seli * g_ref[0, :][None, :].astype(jnp.int32)
            hm = seli * h_ref[0, :][None, :].astype(jnp.int32)
            vals = jnp.concatenate([gm, hm, seli], axis=0).astype(jnp.int8)
        else:
            m = sel.astype(jnp.float32)
            # where(), not multiply: 0 * NaN = NaN would let one bad row
            # (e.g. a custom objective emitting NaN on an excluded row)
            # poison sums
            gm = jnp.where(sel, g_ref[0, :][None, :], 0.0)  # [K, blk]
            hm = jnp.where(sel, h_ref[0, :][None, :], 0.0)
            vals = jnp.concatenate([gm, hm, m], axis=0).astype(compute_dtype)
        b_blk = bins_ref[:].astype(jnp.int32)
        iota = lax.iota(jnp.int32, n_bins)
        for f0 in range(0, f_pad, fc):
            # the one-hot is always built in the [fc*B, blk] orientation —
            # for row-major input the small [blk, fc] chunk is transposed
            # in-VMEM (building [blk, fc*B] instead needs a relayout copy of
            # the one-hot that blows the VMEM scoped-allocation budget)
            if rows_major:
                chunk = b_blk[:, f0:f0 + fc].T              # [fc, blk]
            else:
                chunk = b_blk[f0:f0 + fc]                   # [fc, blk]
            oh_b = (chunk[:, None, :] == iota[None, :, None]
                    ).reshape(fc * n_bins, blk)
            acc = _oh_contract(vals, oh_b, compute_dtype)      # [3K, fc*B]
            out_ref[:, f0 * n_bins:(f0 + fc) * n_bins] += acc

    bins_spec = pl.BlockSpec((blk, f_pad), lambda i: (i, 0)) if rows_major \
        else pl.BlockSpec((f_pad, blk), lambda i: (0, i))
    out = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            bins_spec,
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, K), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((3 * K, f_pad * n_bins), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((3 * K, f_pad * n_bins),
                                       _acc_dtype(compute_dtype)),
        interpret=interpret,
    )(bins, grad2, hess2, lor2, leaves2)
    out = out.astype(jnp.float32)
    # [3K, F*B] -> [K, F, B, 3] -> pad channel dim to 4
    out = out.reshape(3, K, f_pad, n_bins)[:, :, :num_f]
    out = out.transpose(1, 2, 3, 0)
    return jnp.pad(out, ((0, 0), (0, 0), (0, 0), (0, 1)))


def histogram_leaves_pallas(bins_t, grad, hess, leaf_of_row, leaves, **kw):
    """Fused masked multi-leaf histogram from TRANSPOSED [F, n] bins."""
    return _histogram_leaves_impl(bins_t, grad, hess, leaf_of_row, leaves,
                                  rows_major=False, **kw)


def histogram_leaves_rows_pallas(bins_rows, grad, hess, leaf_of_row, leaves,
                                 **kw):
    """Fused masked multi-leaf histogram from ROW-major [S, F] bins."""
    return _histogram_leaves_impl(bins_rows, grad, hess, leaf_of_row, leaves,
                                  rows_major=True, **kw)


@functools.partial(jax.jit,
                   static_argnames=("num_f", "n_bins", "rows_per_block",
                                    "compute_dtype", "interpret"))
def histogram_payload_pallas(payload: jax.Array, leaves: jax.Array,
                             cnt: jax.Array, *, num_f: int, n_bins: int,
                             rows_per_block: int = 1024,
                             compute_dtype=jnp.bfloat16,
                             interpret: bool = False) -> jax.Array:
    """Masked multi-leaf histogram CONSUMING the compaction payload
    directly: f32 [K, F, n_bins, 4] from i32 words.

    ``payload``: i32 [S, W+3] with W = ceil(num_f/4) — each word packs 4
    bin bytes (little-endian, a bitcast view of the row-major u8 bin
    matrix), then one grad, one hess and one leaf word per row.  Rows at
    positions >= ``cnt`` (i32 [1]) are clipped sort duplicates and are
    excluded in-kernel, so the caller hands the gather output straight in
    — no [S, F] slice copy, no bitcast unpack, no where() masking in XLA
    between the gather and the kernel (VERDICT r3 perf item (c); the
    unpack copies measured ~1 ms/compacted round).

    Equivalent to ``histogram_leaves_rows_pallas`` on the unpacked
    operands; the contraction runs per word (fc = 4 features).
    """
    S, wp3 = payload.shape
    W = wp3 - 3
    assert W * 4 >= num_f
    K = leaves.shape[0]
    blk = min(rows_per_block, max(128, _round_up(S, 128)))
    s_pad = _round_up(max(S, 1), blk)
    if s_pad != S:
        # pad rows land at positions >= S >= cnt: excluded by the
        # position guard regardless of content
        payload = jnp.pad(payload, ((0, s_pad - S), (0, 0)))
    nb = s_pad // blk
    f_pad = 4 * W
    prec = _prec(compute_dtype)

    def kernel(cnt_ref, payload_ref, leaves_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        pt = payload_ref[:].T                               # [W+3, blk] i32
        g = lax.bitcast_convert_type(pt[W], jnp.float32)    # [blk]
        h = lax.bitcast_convert_type(pt[W + 1], jnp.float32)
        lor_b = pt[W + 2]
        iota_r = lax.iota(jnp.int32, blk)
        pos_ok = step * blk + iota_r < cnt_ref[0]           # [blk]
        sel = (lor_b[None, :] == leaves_ref[0, :][:, None]) \
            & pos_ok[None, :]                               # [K, blk]
        if _is_int8(compute_dtype):
            # int multiply masking is NaN-safe; levels fit int8
            seli = sel.astype(jnp.int32)
            gm = seli * g[None, :].astype(jnp.int32)
            hm = seli * h[None, :].astype(jnp.int32)
            vals = jnp.concatenate([gm, hm, seli], axis=0).astype(jnp.int8)
        else:
            m = sel.astype(jnp.float32)
            # where(), not multiply: clipped-duplicate rows can carry NaN
            gm = jnp.where(sel, g[None, :], 0.0)
            hm = jnp.where(sel, h[None, :], 0.0)
            vals = jnp.concatenate([gm, hm, m], axis=0).astype(compute_dtype)
        iota = lax.iota(jnp.int32, n_bins)
        # (a 4-words-per-dot widening was tried in round 4 and measured
        # neutral: this kernel is bound by the [blk, W+3] VMEM transpose
        # + byte unpack, not dot width)
        for j in range(W):
            w = pt[j]                                       # [blk] i32
            chunk = jnp.stack([w & 255, (w >> 8) & 255,
                               (w >> 16) & 255, (w >> 24) & 255])  # [4, blk]
            oh_b = (chunk[:, None, :] == iota[None, :, None]
                    ).reshape(4 * n_bins, blk)
            acc = _oh_contract(vals, oh_b, compute_dtype)      # [3K, 4B]
            out_ref[:, j * 4 * n_bins:(j + 1) * 4 * n_bins] += acc

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((blk, wp3), lambda i, c: (i, 0)),
            pl.BlockSpec((1, K), lambda i, c: (0, 0)),
        ],
        out_specs=pl.BlockSpec((3 * K, f_pad * n_bins), lambda i, c: (0, 0)),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((3 * K, f_pad * n_bins),
                                       _acc_dtype(compute_dtype)),
        interpret=interpret,
    )(jnp.asarray(cnt, jnp.int32).reshape(1), payload, leaves[None, :])
    out = out.astype(jnp.float32)
    out = out.reshape(3, K, f_pad, n_bins)[:, :, :num_f]
    out = out.transpose(1, 2, 3, 0)
    return jnp.pad(out, ((0, 0), (0, 0), (0, 0), (0, 1)))


def _swar_byte_eq_planes(word: jax.Array, iota_bins: jax.Array):
    """Per-byte equality one-hot planes from PACKED bin words.

    ``word``: i32 [blk], 4 feature bins per lane (little-endian);
    ``iota_bins``: i32 [B].  Returns i32 0/1 [4, B, blk] — plane k is the
    one-hot of feature k's bin.

    The round-5 floor analysis pinned the flat kernel at ~21% of int8
    peak because the one-hot BUILD runs 32-bit vector compares — one
    compare per (feature, bin, row) element, and v5e has no sub-32-bit
    vector cmp (round-4 probe: "Target does not support this
    comparison").  Packing 4 bins per lane makes each 32-bit op carry 4
    features: XOR against the replicated-bin pattern ``b * 0x01010101``
    and an exact SWAR zero-byte detect (the carry-free
    ``~(((x & 0x7f..) + 0x7f..) | x | 0x7f..)`` form — per-byte exact,
    unlike the borrow-propagating ``x - 0x01010101`` variant) compress
    the 4 compares into 2 lane ops; the per-feature bit extraction is
    shifts/masks, which the VPU issues independently of the compare
    port.  Compare-op count per (word, bin, row): 2 vs the flat
    kernel's 4 — the "packed" mode's throughput claim (chip A/B pends a
    device window; docs/PERF_NOTES.md round 6)."""
    rep = jnp.int32(0x01010101)
    low7 = jnp.int32(0x7F7F7F7F)
    x = word[None, :] ^ (iota_bins * rep)[:, None]          # [B, blk]
    z = ~(((x & low7) + low7) | x | low7)   # byte k high bit <=> byte k == 0
    planes = [((z >> (8 * k + 7)) & 1) for k in range(4)]   # i32 0/1 [B, blk]
    return jnp.stack(planes)                                # [4, B, blk]


@functools.partial(jax.jit,
                   static_argnames=("num_f", "n_bins", "rows_per_block",
                                    "compute_dtype", "interpret"))
def histogram_leaves_packed_pallas(words_t: jax.Array, grad: jax.Array,
                                   hess: jax.Array, leaf_of_row: jax.Array,
                                   leaves: jax.Array, *, num_f: int,
                                   n_bins: int, rows_per_block: int = 2048,
                                   compute_dtype=jnp.bfloat16,
                                   interpret: bool = False) -> jax.Array:
    """Masked multi-leaf histogram from the PACKED-word bin mirror:
    f32 [K, F, n_bins, 4].

    ``words_t``: i32 [W, n] transposed packed mirror (4 uint8 bins per
    word, little-endian — ``ops/histogram.bins_to_words(bins).T``; kept
    resident by the dataset/grower so no per-call bitcast happens).
    Equivalent to ``histogram_leaves_pallas`` on the unpacked operands —
    same masked value channels, same accumulator dtype contract — with
    the one-hot built 4-features-per-lane (``_swar_byte_eq_planes``).
    """
    W, n = words_t.shape
    assert 4 * W >= num_f
    K = leaves.shape[0]
    blk = min(rows_per_block, max(128, _round_up(n, 128)))
    n_pad = _round_up(max(n, 1), blk)
    if n_pad != n:
        # pad rows carry word 0 and lor -1: excluded by the sel mask
        words_t = jnp.pad(words_t, ((0, 0), (0, n_pad - n)))
        grad = jnp.pad(grad, (0, n_pad - n))
        hess = jnp.pad(hess, (0, n_pad - n))
        leaf_of_row = jnp.pad(leaf_of_row, (0, n_pad - n),
                              constant_values=-1)
    nb = n_pad // blk
    f_pad = 4 * W

    def kernel(words_ref, g_ref, h_ref, lor_ref, leaves_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        lor_b = lor_ref[0, :]                               # [blk] i32
        sel = lor_b[None, :] == leaves_ref[0, :][:, None]   # [K, blk]
        if _is_int8(compute_dtype):
            # integer masking by multiply is NaN-safe post-cast
            seli = sel.astype(jnp.int32)
            gm = seli * g_ref[0, :][None, :].astype(jnp.int32)
            hm = seli * h_ref[0, :][None, :].astype(jnp.int32)
            vals = jnp.concatenate([gm, hm, seli], axis=0).astype(jnp.int8)
        else:
            m = sel.astype(jnp.float32)
            # where(), not multiply: 0 * NaN = NaN would poison sums
            gm = jnp.where(sel, g_ref[0, :][None, :], 0.0)
            hm = jnp.where(sel, h_ref[0, :][None, :], 0.0)
            vals = jnp.concatenate([gm, hm, m], axis=0).astype(compute_dtype)
        iota = lax.iota(jnp.int32, n_bins)
        for j in range(W):
            planes = _swar_byte_eq_planes(words_ref[j], iota)  # [4, B, blk]
            oh_i = planes.reshape(4 * n_bins, blk)
            if _is_int8(compute_dtype):
                oh = oh_i.astype(jnp.int8)
                acc = lax.dot_general(vals, oh, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.int32)
            else:
                oh = oh_i.astype(compute_dtype)
                acc = lax.dot_general(vals, oh, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32,
                                      precision=_prec(compute_dtype))
            out_ref[:, j * 4 * n_bins:(j + 1) * 4 * n_bins] += acc

    out = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((W, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, K), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((3 * K, f_pad * n_bins), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((3 * K, f_pad * n_bins),
                                       _acc_dtype(compute_dtype)),
        interpret=interpret,
    )(words_t, grad[None, :], hess[None, :], leaf_of_row[None, :],
      leaves[None, :])
    out = out.astype(jnp.float32)
    out = out.reshape(3, K, f_pad, n_bins)[:, :, :num_f]
    out = out.transpose(1, 2, 3, 0)
    return jnp.pad(out, ((0, 0), (0, 0), (0, 0), (0, 1)))


#: VMEM budget for the radix2 accumulator (f32/i32 [p*nhi, nch*3K*p*nlo]);
#: beyond it the dispatcher falls back to the flat kernel.  The flat
#: kernel's [3K, F*B] accumulator at the shipped K=42/255-bin config is
#: ~4 MB and already crowds double-buffering at blk=2048 (round-4 note);
#: radix2 multiplies that by its diagonal-waste factor p.
_RADIX2_ACC_BYTES = 8 << 20


def radix2_pick_p(num_f: int, K: int, n_bins: int) -> int:
    """Feature group width for the shared-radix kernel: largest p in
    (4, 2) whose accumulator fits ``_RADIX2_ACC_BYTES``; 0 = does not
    fit (caller falls back to the flat kernel)."""
    for p in (4, 2):
        f_pad = _round_up(num_f, p)
        if 3 * K * f_pad * n_bins * p * 4 <= _RADIX2_ACC_BYTES:
            return p
    return 0


@functools.partial(jax.jit,
                   static_argnames=("n_bins", "rows_per_block", "p",
                                    "compute_dtype", "interpret"))
def histogram_leaves_radix2_pallas(bins_t: jax.Array, grad: jax.Array,
                                   hess: jax.Array, leaf_of_row: jax.Array,
                                   leaves: jax.Array, *, n_bins: int,
                                   rows_per_block: int = 1024, p: int = 2,
                                   compute_dtype=jnp.bfloat16,
                                   interpret: bool = False) -> jax.Array:
    """SHARED-radix masked multi-leaf histogram: f32 [K, F, n_bins, 4].

    The flat masked kernel builds a B-wide one-hot per feature (the
    32-bit-compare floor, ~2 VPU ops per (feature, bin, row)); the joint
    radix kernel's (leaf, hi) build scales with K and loses above K=4
    (docs/PERF_NOTES.md round 3).  This kernel splits bin = 16*hi + lo
    and builds BOTH nibble one-hots ONCE per row block — nhi + nlo = 32
    compare elements per feature-row instead of 256, K-independent — then
    rides the K split-batch leaf channels on the rhs as value-masked lo
    planes:

        acc[(f, hi), (ch, f', lo)] = sum_r hi_oh[f,hi,r] * (vals[ch,r] * lo_oh[f',lo,r])

    keeping only the f == f' diagonal.  The p-fold off-diagonal waste is
    the price of full MXU tiles (same trade the single/joint radix
    kernels shipped); ``radix2_pick_p`` bounds the accumulator.  Bit
    contract identical to the flat kernel (int8 -> exact i32, float ->
    f32 accumulation over the same row axis).
    """
    num_f, n = bins_t.shape
    K = leaves.shape[0]
    nhi, nlo = n_bins // 16, 16
    M = p * nhi
    NW = 3 * K * p * nlo
    blk = min(rows_per_block, max(128, _round_up(n, 128)))
    n_pad = _round_up(max(n, 1), blk)
    if n_pad != n:
        bins_t = jnp.pad(bins_t, ((0, 0), (0, n_pad - n)))
        grad = jnp.pad(grad, (0, n_pad - n))
        hess = jnp.pad(hess, (0, n_pad - n))
        leaf_of_row = jnp.pad(leaf_of_row, (0, n_pad - n),
                              constant_values=-1)
    f_pad = _round_up(num_f, p)
    if f_pad != num_f:
        bins_t = jnp.pad(bins_t, ((0, f_pad - num_f), (0, 0)))
    nch = f_pad // p
    nb = n_pad // blk
    prec = _prec(compute_dtype)

    def kernel(bins_ref, g_ref, h_ref, lor_ref, leaves_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        lor_b = lor_ref[0, :]
        sel = lor_b[None, :] == leaves_ref[0, :][:, None]   # [K, blk]
        int8_mode = _is_int8(compute_dtype)
        if int8_mode:
            seli = sel.astype(jnp.int32)
            gm = seli * g_ref[0, :][None, :].astype(jnp.int32)
            hm = seli * h_ref[0, :][None, :].astype(jnp.int32)
            vals = jnp.concatenate([gm, hm, seli], axis=0)  # [3K, blk] i32
        else:
            m = sel.astype(jnp.float32)
            gm = jnp.where(sel, g_ref[0, :][None, :], 0.0)
            hm = jnp.where(sel, h_ref[0, :][None, :], 0.0)
            vals = jnp.concatenate([gm, hm, m], axis=0) \
                .astype(compute_dtype)                      # [3K, blk]
        b_blk = bins_ref[:].astype(jnp.int32)
        iota_h = lax.iota(jnp.int32, nhi)
        iota_l = lax.iota(jnp.int32, nlo)
        for c0 in range(nch):
            chunk = b_blk[c0 * p:(c0 + 1) * p]              # [p, blk]
            hi = chunk >> 4
            lo = chunk & 15
            if int8_mode:
                # i8 elementwise multiplies don't legalize in Mosaic:
                # mask in i32, cast both dot operands to i8 pre-dot
                hi_oh = (hi[:, None, :] == iota_h[None, :, None]
                         ).astype(jnp.int8).reshape(M, blk)
                lo_ohi = (lo[:, None, :] == iota_l[None, :, None]
                          ).astype(jnp.int32).reshape(p * nlo, blk)
                vlo = (vals[:, None, :] * lo_ohi[None, :, :]
                       ).reshape(NW, blk).astype(jnp.int8)
                acc = lax.dot_general(hi_oh, vlo, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.int32)
            else:
                hi_oh = (hi[:, None, :] == iota_h[None, :, None]
                         ).astype(compute_dtype).reshape(M, blk)
                lo_oh = (lo[:, None, :] == iota_l[None, :, None]
                         ).astype(compute_dtype).reshape(p * nlo, blk)
                vlo = (vals[:, None, :] * lo_oh[None, :, :]).reshape(NW, blk)
                acc = lax.dot_general(hi_oh, vlo, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32,
                                      precision=prec)       # [M, NW]
            out_ref[:, c0 * NW:(c0 + 1) * NW] += acc

    out = pl.pallas_call(
        kernel, grid=(nb,),
        in_specs=[
            pl.BlockSpec((f_pad, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, K), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((M, nch * NW), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((M, nch * NW),
                                       _acc_dtype(compute_dtype)),
        interpret=interpret,
    )(bins_t, grad[None, :], hess[None, :], leaf_of_row[None, :],
      leaves[None, :])
    out = out.astype(jnp.float32)
    # rows (p_l, nhi); cols (nch, 3K-ch, p_r, nlo) — keep the f == f' diag
    out = out.reshape(p, nhi, nch, 3 * K, p, nlo)
    idx = jnp.arange(p)
    out = out[idx, :, :, :, idx]            # [p, nhi, nch, 3K, nlo]
    out = out.transpose(3, 2, 0, 1, 4)      # [3K, nch, p, nhi, nlo]
    out = out.reshape(3, K, f_pad, n_bins)[:, :, :num_f]
    out = out.transpose(1, 2, 3, 0)
    return jnp.pad(out, ((0, 0), (0, 0), (0, 0), (0, 1)))


def _radix_shapes(n_bins: int, p: int):
    """Radix split of the bin axis: bin = hi * nlo + lo with nlo = 16.

    Valid only when ``n_bins`` is a multiple of 16 (the production 256-bin
    layout); callers fall back to the flat kernels otherwise.
    """
    nlo = 16
    nhi = n_bins // nlo
    return nhi, nlo, p * nhi, 3 * p * nlo


def _radix_chunk_accum(chunk_i32, vals3, *, nhi, nlo, p, blk, compute_dtype,
                       prec):
    """One radix feature-chunk contraction: [p*nhi, 3*p*nlo] f32.

    The 256-wide one-hot of the flat kernel costs ~2 VPU ops per
    (feature, bin, row) element; splitting bin = 16*hi + lo builds two
    16-wide one-hots instead (32 elements per feature-row instead of 256)
    and recovers the joint histogram as an outer product ridden by one
    MXU contraction per chunk:

        acc[(f, hi), (c, f', lo)] = sum_r hi_oh[f,hi,r] * vals[c,r] * lo_oh[f',lo,r]

    Only the f == f' diagonal blocks are kept (callers extract them); the
    off-diagonal waste buys full 128-wide MXU tiles, which measured ~1.7x
    faster than both the flat kernel and per-feature small matmuls
    (docs/PERF_NOTES.md round-3 table).
    """
    hi = chunk_i32 >> 4                                     # [p, blk]
    lo = chunk_i32 & 15
    iota_h = lax.iota(jnp.int32, nhi)
    iota_l = lax.iota(jnp.int32, nlo)
    if _is_int8(compute_dtype):
        # i8 elementwise multiply doesn't legalize in Mosaic: build the
        # masked lo-side channels in i32 and cast both dot operands to i8
        # (values <= 127 by the quantized-levels contract)
        hi_oh = (hi[:, None, :] == iota_h[None, :, None]
                 ).astype(jnp.int8).reshape(p * nhi, blk)
        lo_ohi = (lo[:, None, :] == iota_l[None, :, None]
                  ).astype(jnp.int32).reshape(p * nlo, blk)
        vlo = jnp.concatenate([lo_ohi * vals3[0][None, :],
                               lo_ohi * vals3[1][None, :],
                               lo_ohi * vals3[2][None, :]],
                              axis=0).astype(jnp.int8)
        return lax.dot_general(hi_oh, vlo, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.int32
                               )                            # [p*nhi, 3*p*nlo]
    hi_oh = (hi[:, None, :] == iota_h[None, :, None]
             ).astype(compute_dtype).reshape(p * nhi, blk)
    lo_oh = (lo[:, None, :] == iota_l[None, :, None]
             ).astype(compute_dtype).reshape(p * nlo, blk)
    vlo = jnp.concatenate([lo_oh * vals3[0][None, :],
                           lo_oh * vals3[1][None, :],
                           lo_oh * vals3[2][None, :]], axis=0)
    return lax.dot_general(hi_oh, vlo, (((1,), (1,)), ((), ())),
                           preferred_element_type=jnp.float32,
                           precision=prec)                  # [p*nhi, 3*p*nlo]


def _radix_unpack(out: jax.Array, *, n_groups, num_f, f_pad, p, nhi, nlo,
                  n_bins):
    """[G, p*nhi, nch*3*p*nlo] -> [G, F, n_bins, 4] diagonal extraction."""
    nch = f_pad // p
    out = out.reshape(n_groups, p, nhi, nch, 3, p, nlo)
    idx = jnp.arange(p)
    # diag p_lhs == p_rhs -> leading axis p (vmapped-gather semantics)
    out = out[:, idx, :, :, :, idx]          # [p, G, nhi, nch, 3, nlo]
    out = out.transpose(1, 3, 0, 2, 5, 4)    # [G, nch, p, nhi, nlo, 3]
    out = out.reshape(n_groups, f_pad, n_bins, 3)[:, :num_f]
    return jnp.pad(out, ((0, 0), (0, 0), (0, 0), (0, 1)))


@functools.partial(jax.jit,
                   static_argnames=("n_bins", "rows_per_block", "p",
                                    "compute_dtype", "interpret"))
def histogram_radix_single_pallas(bins_t: jax.Array, grad: jax.Array,
                                  hess: jax.Array, lor: jax.Array, *,
                                  n_bins: int, rows_per_block: int = 2048,
                                  p: int = 4, compute_dtype=jnp.bfloat16,
                                  interpret: bool = False) -> jax.Array:
    """Single-group full-data radix histogram: f32 [F, n_bins, 4].

    The root-pass kernel (reference cuda_histogram_constructor.cu:18 builds
    the root the same way it builds leaves; here the root gets the cheaper
    radix formulation since it has no grouping to steer).  ``lor`` < 0
    excludes a row (bagging mask); all other rows contribute.
    """
    num_f, n = bins_t.shape
    nhi, nlo, M, NW = _radix_shapes(n_bins, p)
    blk = min(rows_per_block, max(128, _round_up(n, 128)))
    n_pad = _round_up(max(n, 1), blk)
    if n_pad != n:
        bins_t = jnp.pad(bins_t, ((0, 0), (0, n_pad - n)))
        grad = jnp.pad(grad, (0, n_pad - n))
        hess = jnp.pad(hess, (0, n_pad - n))
        lor = jnp.pad(lor, (0, n_pad - n), constant_values=-1)
    f_pad = _round_up(num_f, p)
    if f_pad != num_f:
        bins_t = jnp.pad(bins_t, ((0, f_pad - num_f), (0, 0)))
    nch = f_pad // p
    nb = n_pad // blk
    prec = _prec(compute_dtype)

    def kernel(bins_ref, g_ref, h_ref, lor_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        valid = lor_ref[0, :] >= 0
        if _is_int8(compute_dtype):
            vi = valid.astype(jnp.int32)
            gm = vi * g_ref[0, :].astype(jnp.int32)
            hm = vi * h_ref[0, :].astype(jnp.int32)
            mm = vi
        else:
            gm = jnp.where(valid, g_ref[0, :], 0.0).astype(compute_dtype)
            hm = jnp.where(valid, h_ref[0, :], 0.0).astype(compute_dtype)
            mm = jnp.where(valid, 1.0, 0.0).astype(compute_dtype)
        b_blk = bins_ref[:].astype(jnp.int32)
        for c0 in range(nch):
            acc = _radix_chunk_accum(
                b_blk[c0 * p:(c0 + 1) * p], (gm, hm, mm), nhi=nhi, nlo=nlo,
                p=p, blk=blk, compute_dtype=compute_dtype, prec=prec)
            out_ref[:, c0 * NW:(c0 + 1) * NW] += acc

    out = pl.pallas_call(
        kernel, grid=(nb,),
        in_specs=[
            pl.BlockSpec((f_pad, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((M, nch * NW), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((M, nch * NW),
                                       _acc_dtype(compute_dtype)),
        interpret=interpret,
    )(bins_t, grad[None, :], hess[None, :], lor[None, :])
    out = out.astype(jnp.float32)
    return _radix_unpack(out[None], n_groups=1, num_f=num_f, f_pad=f_pad,
                         p=p, nhi=nhi, nlo=nlo, n_bins=n_bins)[0]


@functools.partial(jax.jit,
                   static_argnames=("n_bins", "rows_per_block", "p",
                                    "compute_dtype", "interpret"))
def histogram_radix_joint_pallas(bins_t: jax.Array, grad: jax.Array,
                                 hess: jax.Array, lor: jax.Array,
                                 leaves: jax.Array, *, n_bins: int,
                                 rows_per_block: int = 2048, p: int = 4,
                                 compute_dtype=jnp.bfloat16,
                                 interpret: bool = False) -> jax.Array:
    """Masked MULTI-leaf radix histogram: f32 [G, F, n_bins, 4], full-data
    pass, no compaction.

    The leaf dimension rides the matmul M side as a joint (leaf, hi)
    one-hot — lhs rows = G*p*nhi — while the rhs keeps the 3 value
    channels.  Profitable while G*p*nhi stays within a few MXU tiles
    (warmup rounds, G <= ~16); beyond that the flat masked kernel's
    K-independent cost wins.  ``leaves`` i32 [G]; duplicate slots receive
    identical histogram copies (same as the flat masked kernel).
    """
    num_f, n = bins_t.shape
    G = leaves.shape[0]
    nhi, nlo, M1, NW = _radix_shapes(n_bins, p)
    M = G * M1
    blk = min(rows_per_block, max(128, _round_up(n, 128)))
    n_pad = _round_up(max(n, 1), blk)
    if n_pad != n:
        bins_t = jnp.pad(bins_t, ((0, 0), (0, n_pad - n)))
        grad = jnp.pad(grad, (0, n_pad - n))
        hess = jnp.pad(hess, (0, n_pad - n))
        lor = jnp.pad(lor, (0, n_pad - n), constant_values=-1)
    f_pad = _round_up(num_f, p)
    if f_pad != num_f:
        bins_t = jnp.pad(bins_t, ((0, f_pad - num_f), (0, 0)))
    nch = f_pad // p
    nb = n_pad // blk
    prec = _prec(compute_dtype)

    def kernel(bins_ref, g_ref, h_ref, lor_ref, leaves_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        lor_b = lor_ref[0, :]
        lv = leaves_ref[0, :]
        eq = lor_b[None, :] == lv[:, None]                  # [G, blk]
        int8_mode = _is_int8(compute_dtype)
        if int8_mode:
            gohi = eq.astype(jnp.int32)                     # [G, blk]
            seli = jnp.sign(jnp.sum(gohi, axis=0))          # 0/1 [blk]
            gm = seli * g_ref[0, :].astype(jnp.int32)
            hm = seli * h_ref[0, :].astype(jnp.int32)
            mm = seli
        else:
            goh = eq.astype(compute_dtype)                  # [G, blk]
            sel = jnp.any(eq, axis=0)
            gm = jnp.where(sel, g_ref[0, :], 0.0).astype(compute_dtype)
            hm = jnp.where(sel, h_ref[0, :], 0.0).astype(compute_dtype)
            mm = jnp.where(sel, 1.0, 0.0).astype(compute_dtype)
        b_blk = bins_ref[:].astype(jnp.int32)
        iota_h = lax.iota(jnp.int32, nhi)
        iota_l = lax.iota(jnp.int32, nlo)
        for c0 in range(nch):
            chunk = b_blk[c0 * p:(c0 + 1) * p]
            if int8_mode:
                hi_ohi = ((chunk >> 4)[:, None, :] == iota_h[None, :, None]
                          ).astype(jnp.int32)               # [p, nhi, blk]
                lo_ohi = ((chunk & 15)[:, None, :] == iota_l[None, :, None]
                          ).astype(jnp.int32).reshape(p * nlo, blk)
                joint = (gohi[:, None, None, :] * hi_ohi[None, :, :, :]
                         ).reshape(M, blk).astype(jnp.int8)
                vlo = jnp.concatenate([lo_ohi * gm[None, :],
                                       lo_ohi * hm[None, :],
                                       lo_ohi * mm[None, :]],
                                      axis=0).astype(jnp.int8)
                acc = lax.dot_general(joint, vlo, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.int32)
            else:
                hi_oh = ((chunk >> 4)[:, None, :] == iota_h[None, :, None]
                         ).astype(compute_dtype)            # [p, nhi, blk]
                lo_oh = ((chunk & 15)[:, None, :] == iota_l[None, :, None]
                         ).astype(compute_dtype).reshape(p * nlo, blk)
                joint = (goh[:, None, None, :] * hi_oh[None, :, :, :]
                         ).reshape(M, blk)                  # [(G,p,hi), blk]
                vlo = jnp.concatenate([lo_oh * gm[None, :],
                                       lo_oh * hm[None, :],
                                       lo_oh * mm[None, :]], axis=0)
                acc = lax.dot_general(joint, vlo, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32,
                                      precision=prec)       # [M, NW]
            out_ref[:, c0 * NW:(c0 + 1) * NW] += acc

    out = pl.pallas_call(
        kernel, grid=(nb,),
        in_specs=[
            pl.BlockSpec((f_pad, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, blk), lambda i: (0, i)),
            pl.BlockSpec((1, G), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((M, nch * NW), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((M, nch * NW),
                                       _acc_dtype(compute_dtype)),
        interpret=interpret,
    )(bins_t, grad[None, :], hess[None, :], lor[None, :], leaves[None, :])
    out = out.astype(jnp.float32)
    # rows (G, p_l, nhi); cols (nch, 3c, p_r, nlo)
    out = out.reshape(G, M1, nch * NW)
    return _radix_unpack(out, n_groups=G, num_f=num_f, f_pad=f_pad, p=p,
                         nhi=nhi, nlo=nlo, n_bins=n_bins)
