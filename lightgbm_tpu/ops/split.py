"""Best-split finding from histograms.

TPU-native re-design of the reference split finder (reference:
src/treelearner/feature_histogram.hpp:832 ``FindBestThresholdSequentially``
CPU scans; src/treelearner/cuda/cuda_best_split_finder.cu:772
``FindBestSplitsForLeafKernel`` — one thread-block per (feature, direction)
with in-block prefix scans + arg-reduction).

On TPU the whole thing is a handful of vector ops over the [F, B] histogram:
cumulative sums along the bin axis give every threshold's left-side stats at
once, both missing-value default directions are evaluated as a 2-wide variant
axis (the reference's forward/backward scans), one-hot categorical candidates
ride the same argmax, and a single flat argmax picks the winner.  Bins beyond
a feature's ``num_bin`` and the dedicated NaN bin are masked, replacing the
reference's per-feature loop bounds.

Gain/regularization semantics mirror feature_histogram.hpp:
``ThresholdL1`` soft-shrink, gain = GL'^2/(HL+l2) + GR'^2/(HR+l2), validity =
min_data_in_leaf / min_sum_hessian_in_leaf on both children, reported gain is
the improvement over the parent minus ``min_gain_to_split``.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class SplitHyper:
    """Static split/growth hyperparameters (subset of reference Config used by
    the learner; config.h learning-control block)."""
    num_leaves: int = 31
    max_depth: int = -1
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100
    # monotone constraints (basic method, monotone_constraints.hpp:465);
    # use_monotone is the static gate — the per-feature direction vector is a
    # runtime array argument
    use_monotone: bool = False
    monotone_penalty: float = 0.0
    # "basic": midpoint bounds inherited down the path
    # (monotone_constraints.hpp:465); "intermediate": per-leaf bounds from
    # actual adjacent-leaf outputs via dense box adjacency, refreshed every
    # split (learner/monotone.py; reference :516 IntermediateLeafConstraints)
    monotone_method: str = "basic"
    # extra-trees mode: one random threshold per (feature, node)
    # (reference USE_RAND template paths in feature_histogram)
    extra_trees: bool = False
    feature_fraction_bynode: float = 1.0
    # static gate: skip the categorical argsort/cumsum machinery entirely
    # on all-numeric datasets (argsort is expensive on TPU)
    has_categorical: bool = False
    n_bins: int = 256
    rows_per_block: int = 4096
    path_smooth: float = 0.0
    # MXU contraction dtype.  "float32" (default): fully exact products via
    # multi-pass MXU (Precision.HIGHEST) — the split-parity mode matching
    # the reference's fp64 histograms bit-for-metric.  "bfloat16": exact
    # {0,1} one-hot, f32 accumulation, only grad/hess products take ~2^-9
    # input rounding (measured ~1.1e-4 AUC drift, ~3x faster kernels —
    # docs/PERF_NOTES.md; the speed mode the benchmark uses, analogous to
    # the reference GPU docs recommending single precision).
    hist_dtype: str = "float32"
    # histogram-build formulation (ops/histogram.py HIST_KERNELS):
    # "auto" = measured dispatch incl. the round-6 packed / shared-radix
    # kernels, "onehot" = the flat one-hot reference path, "packed" /
    # "radix2" = force a formulation.  All modes are bit-identical.
    hist_kernel: str = "auto"
    # per-leaf histogram strategy: "masked" = flat full-data pass with
    # non-leaf rows zeroed (no compaction; TPU-friendly), "bucketed" =
    # nonzero+gather into power-of-two buckets (wins only when leaves are
    # tiny relative to n AND gathers are cheap)
    leaf_hist: str = "masked"
    # bounded histogram pool (reference feature_histogram.hpp:1367
    # HistogramPool, serial_tree_learner.cpp:36-47 histogram_pool_size):
    # 0 = one resident histogram per leaf ([L, F, B, 4]); > 0 = that many
    # pool slots with lowest-cached-gain eviction — split parents whose
    # histogram was evicted get BOTH children histogrammed directly
    # (jit-friendly replacement for the reference's LRU + re-fetch).
    # Batched grower only.
    hist_pool_slots: int = 0


#: candidate-variant indices along the last axis of the gain tensor
VAR_NUM_RIGHT = 0    # numerical, missing goes right
VAR_NUM_LEFT = 1     # numerical, missing goes left
VAR_CAT_ONEHOT = 2   # categorical one-hot: {bin == t} left
VAR_CAT_FWD = 3      # categorical sorted-subset, ascending-score prefix
VAR_CAT_BWD = 4      # categorical sorted-subset, descending-score prefix
NUM_VARIANTS = 5


class SplitResult(NamedTuple):
    """Chosen split for one leaf (reference split_info.hpp:294 ``SplitInfo``)."""
    gain: jax.Array          # f32 — improvement; <= 0 means "don't split"
    feature: jax.Array       # i32 packed feature index
    threshold: jax.Array     # i32 bin threshold (left = bin <= threshold);
                             # for sorted-subset variants: prefix length - 1
    default_left: jax.Array  # bool — missing goes left
    is_categorical: jax.Array  # bool — any categorical variant
    variant: jax.Array       # i32 VAR_* of the winner
    left_sum_g: jax.Array
    left_sum_h: jax.Array
    left_count: jax.Array
    right_sum_g: jax.Array
    right_sum_h: jax.Array
    right_count: jax.Array


def _cumsum_bins(x: jax.Array, exact: bool) -> jax.Array:
    """Cumulative sum over the bin axis (last).

    ``exact=False`` (the speed modes — quantized levels make any
    summation order exact) rides an upper-triangular f32 MXU matmul:
    XLA lowers jnp.cumsum to reduce-window, which profiled at ~4.8
    ms/tree across the three split-scan cumsums at K=28 (round 4) while
    the [B, B] matmul is noise.  ``exact=True`` (float32 split-parity
    mode) keeps the sequential cumsum so CPU<->TPU dual parity stays
    bit-identical.  HIGHEST precision: bin sums are integer-valued in
    quantized mode and can exceed bf16's 2^8 mantissa."""
    if exact:
        return jnp.cumsum(x, axis=-1)
    b = x.shape[-1]
    tri = jnp.triu(jnp.ones((b, b), jnp.float32))
    return lax.dot_general(x, tri, (((x.ndim - 1,), (0,)), ((), ())),
                           precision=lax.Precision.HIGHEST)


def threshold_l1(s: jax.Array, l1: float) -> jax.Array:
    """Soft-threshold (reference feature_histogram.hpp ThresholdL1)."""
    if l1 <= 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_gain(g: jax.Array, h: jax.Array, l1: float, l2: float) -> jax.Array:
    t = threshold_l1(g, l1)
    return (t * t) / (h + l2 + 1e-15)


def leaf_output(g: jax.Array, h: jax.Array, l1: float, l2: float,
                max_delta_step: float = 0.0) -> jax.Array:
    """CalculateSplittedLeafOutput (feature_histogram.hpp static)."""
    out = -threshold_l1(g, l1) / (h + l2 + 1e-15)
    if max_delta_step > 0.0:
        out = jnp.clip(out, -max_delta_step, max_delta_step)
    return out


def gain_given_output(g: jax.Array, h: jax.Array, out: jax.Array,
                      l1: float, l2: float) -> jax.Array:
    """GetLeafGainGivenOutput (feature_histogram.hpp): the split objective
    evaluated at an arbitrary (clipped / smoothed) output."""
    return -(2.0 * threshold_l1(g, l1) * out + (h + l2) * out * out)


def smoothed_output(g: jax.Array, h: jax.Array, n: jax.Array,
                    parent_output, l1: float, l2: float,
                    hp: "SplitHyper") -> jax.Array:
    """Leaf output with max_delta_step clipping and path smoothing toward the
    parent (feature_histogram.hpp CalculateSplittedLeafOutput USE_SMOOTHING:
    out' = (n*out + path_smooth*parent) / (n + path_smooth))."""
    out = leaf_output(g, h, l1, l2, hp.max_delta_step)
    if hp.path_smooth > 0.0:
        w = n / (n + hp.path_smooth)
        out = out * w + parent_output * (1.0 - w)
    return out


def find_best_split(hist: jax.Array, sum_g: jax.Array, sum_h: jax.Array,
                    count: jax.Array, num_bins: jax.Array, nan_bin: jax.Array,
                    is_cat: jax.Array, feature_mask: Optional[jax.Array],
                    hp: SplitHyper,
                    monotone: Optional[jax.Array] = None,
                    parent_output=0.0,
                    leaf_min=None, leaf_max=None,
                    depth=None,
                    rng_key: Optional[jax.Array] = None,
                    per_feature_out: Optional[list] = None,
                    gain_penalty: Optional[jax.Array] = None,
                    adv_bounds=None) -> SplitResult:
    """Pick the best (feature, threshold, default-dir) for one leaf.

    hist: f32 [F, B, C>=3] (grad, hess, count); sum_g/sum_h/count: leaf totals.
    num_bins/nan_bin: i32 [F]; is_cat: bool [F]; feature_mask: bool [F] or None.
    monotone: i8/i32 [F] direction per feature (0 none; categorical features
    MUST be 0) when ``hp.use_monotone``; leaf_min/leaf_max: this leaf's output
    bounds (basic-method constraint entry); parent_output: this leaf's own
    output (path smoothing target); depth: leaf depth (monotone penalty).
    """
    num_f, n_b = hist.shape[0], hist.shape[1]
    g, h, n = hist[..., 0], hist[..., 1], hist[..., 2]
    bin_idx = lax.iota(jnp.int32, n_b)[None, :]                  # [1, B]
    valid_bin = bin_idx < num_bins[:, None]                      # [F, B]
    is_nan = bin_idx == nan_bin[:, None]                         # [F, B]

    # base cumulatives exclude the missing bin; its stats ride the variant axis
    gz = jnp.where(is_nan, 0.0, g)
    hz = jnp.where(is_nan, 0.0, h)
    nz = jnp.where(is_nan, 0.0, n)
    exact_scan = hp.hist_dtype == "float32"
    gl = _cumsum_bins(gz, exact_scan)
    hl = _cumsum_bins(hz, exact_scan)
    nl = _cumsum_bins(nz, exact_scan)
    gm = jnp.sum(jnp.where(is_nan, g, 0.0), axis=1, keepdims=True)  # [F, 1]
    hm = jnp.sum(jnp.where(is_nan, h, 0.0), axis=1, keepdims=True)
    nm = jnp.sum(jnp.where(is_nan, n, 0.0), axis=1, keepdims=True)
    has_missing = nan_bin[:, None] >= 0

    l1, l2 = hp.lambda_l1, hp.lambda_l2
    # the closed form g²/(h+l2) is exact only when the output is the
    # unconstrained optimum; smoothing / clipping force the evaluated form.
    # The parent-side gain shift must be evaluated the same way, at the
    # parent's ACTUAL output (feature_histogram.hpp gain_shift: given-output
    # under smoothing, clipped GetLeafGain under max_delta_step) — otherwise
    # a clipped parent looks artificially good and no split ever clears it.
    output_path = (hp.use_monotone or hp.path_smooth > 0.0
                   or hp.max_delta_step > 0.0)
    if hp.path_smooth > 0.0:
        parent_gain = gain_given_output(sum_g, sum_h, parent_output, l1, l2)
    elif hp.max_delta_step > 0.0:
        po = leaf_output(sum_g, sum_h, l1, l2, hp.max_delta_step)
        parent_gain = gain_given_output(sum_g, sum_h, po, l1, l2)
    else:
        parent_gain = leaf_gain(sum_g, sum_h, l1, l2)
    min_shift = parent_gain + hp.min_gain_to_split

    def variant_gain(gl_v, hl_v, nl_v, l2_v, bnds=None):
        gr = sum_g - gl_v
        hr = sum_h - hl_v
        nr = count - nl_v
        if not output_path:
            gain = leaf_gain(gl_v, hl_v, l1, l2_v) + leaf_gain(gr, hr, l1, l2_v)
        else:
            lo = smoothed_output(gl_v, hl_v, nl_v, parent_output, l1, l2_v, hp)
            ro = smoothed_output(gr, hr, nr, parent_output, l1, l2_v, hp)
            if hp.use_monotone and bnds is not None:
                # advanced method (monotone_constraints.hpp:858): the
                # per-(feature, threshold) bounds REPLACE the whole-leaf
                # bounds — a neighbor that does not overlap a child's
                # subrange imposes nothing on that child, which is exactly
                # the refinement (intersecting with leaf_min/leaf_max would
                # cancel it: the leaf bound is the min over the superset)
                bmin_l, bmax_l, bmin_r, bmax_r = bnds
                lo = jnp.clip(lo, bmin_l, bmax_l)
                ro = jnp.clip(ro, bmin_r, bmax_r)
            elif hp.use_monotone:
                lo = jnp.clip(lo, leaf_min, leaf_max)
                ro = jnp.clip(ro, leaf_min, leaf_max)
            gain = (gain_given_output(gl_v, hl_v, lo, l1, l2_v)
                    + gain_given_output(gr, hr, ro, l1, l2_v))
            if hp.use_monotone:
                # monotone direction violated → split forbidden
                # (feature_histogram.hpp:788-791 returns 0 = below gain_shift)
                mono = monotone[:, None] if gl_v.ndim == 2 else monotone
                bad = ((mono > 0) & (lo > ro)) | ((mono < 0) & (lo < ro))
                gain = jnp.where(bad, NEG_INF, gain)
        ok = ((nl_v >= hp.min_data_in_leaf) & (nr >= hp.min_data_in_leaf)
              & (hl_v >= hp.min_sum_hessian_in_leaf)
              & (hr >= hp.min_sum_hessian_in_leaf))
        return jnp.where(ok, gain, NEG_INF)

    # numerical thresholds: t splits {bin <= t} | {bin > t}; t == last real bin
    # only splits off the missing bin, t at the nan bin itself is invalid
    thr_ok = valid_bin & (bin_idx < num_bins[:, None] - 1) & ~is_nan
    thr_ok = thr_ok & ~is_cat[:, None]
    gain_right = jnp.where(thr_ok, variant_gain(gl, hl, nl, l2,
                                                bnds=adv_bounds), NEG_INF)
    gain_left = jnp.where(thr_ok & has_missing,
                          variant_gain(gl + gm, hl + hm, nl + nm, l2,
                                       bnds=adv_bounds), NEG_INF)

    if hp.has_categorical:
        # one-hot categorical: {bin == t} goes left, gated to low-cardinality
        # features (reference feature_histogram.cpp:179 ``use_onehot =
        # num_bin <= max_cat_to_onehot``; plain lambda_l2 in this branch)
        onehot_ok = is_cat[:, None] & (num_bins[:, None]
                                       <= hp.max_cat_to_onehot)
        gain_cat = jnp.where(valid_bin & onehot_ok,
                             variant_gain(g, h, n, l2), NEG_INF)

        # sorted-subset categorical (reference feature_histogram.cpp:241-340):
        # candidate bins with count >= cat_smooth, sorted by
        # g/(h+cat_smooth); prefixes of the ascending and descending orders
        # are the left sets, capped at max_cat_threshold, evaluated with
        # l2 + cat_l2 and gated by min_data_per_group.  Vectorized: argsort +
        # cumsum per direction, the reference's sequential ``cnt_cur_group``
        # reset becoming "left count crosses a multiple of
        # min_data_per_group" (a static approximation of the same evaluation
        # density).
        l2c = l2 + hp.cat_l2
        subset_feat_ok = is_cat & (num_bins > hp.max_cat_to_onehot)   # [F]
        cand_bin = valid_bin & subset_feat_ok[:, None] & (n >= hp.cat_smooth)
        used_bin = jnp.sum(cand_bin, axis=1)                          # [F]
        max_num_cat = jnp.minimum(hp.max_cat_threshold, (used_bin + 1) // 2)
        k_limit = jnp.minimum(used_bin, max_num_cat)[:, None]         # [F, 1]
        score = g / (h + hp.cat_smooth)
        INF = jnp.float32(1e30)

        def subset_scan(descending: bool):
            key = jnp.where(cand_bin, -score if descending else score, INF)
            order = jnp.argsort(key, axis=1)                          # [F, B]
            gs = jnp.take_along_axis(g * cand_bin, order, axis=1)
            hs = jnp.take_along_axis(h * cand_bin, order, axis=1)
            ns = jnp.take_along_axis(n * cand_bin, order, axis=1)
            glv = _cumsum_bins(gs, exact_scan)
            hlv = _cumsum_bins(hs, exact_scan)
            nlv = _cumsum_bins(ns, exact_scan)
            ok = bin_idx < k_limit
            if hp.min_data_per_group > 1:
                mdpg = jnp.float32(hp.min_data_per_group)
                crossed = jnp.floor(nlv / mdpg) > jnp.floor((nlv - ns) / mdpg)
                ok = ok & crossed & ((count - nlv) >= mdpg)
            gain = jnp.where(ok, variant_gain(glv, hlv, nlv, l2c), NEG_INF)
            return gain, glv, hlv, nlv

        gain_fwd, gl_f, hl_f, nl_f = subset_scan(False)
        gain_bwd, gl_b, hl_b, nl_b = subset_scan(True)
    else:
        neg = jnp.full((num_f, n_b), NEG_INF)
        gain_cat = gain_fwd = gain_bwd = neg
        gl_f = hl_f = nl_f = gl_b = hl_b = nl_b = jnp.zeros_like(g)
        used_bin = max_num_cat = jnp.zeros((num_f,), jnp.int32)

    if hp.extra_trees and rng_key is not None:
        # extremely-randomized mode: per (feature, node) keep exactly ONE
        # random candidate threshold per variant family (reference
        # feature_histogram.cpp USE_RAND rand_threshold draws)
        kn, kc, ks = jax.random.split(rng_key, 3)
        u_num = jax.random.uniform(kn, (num_f,))
        rand_num = jnp.floor(
            u_num * jnp.maximum(num_bins - 1, 1).astype(jnp.float32)
        ).astype(jnp.int32)
        keep_num = bin_idx == rand_num[:, None]
        gain_right = jnp.where(keep_num, gain_right, NEG_INF)
        gain_left = jnp.where(keep_num, gain_left, NEG_INF)
        if hp.has_categorical:
            u_cat = jax.random.uniform(kc, (num_f,))
            rand_cat = jnp.floor(
                u_cat * num_bins.astype(jnp.float32)).astype(jnp.int32)
            gain_cat = jnp.where(bin_idx == rand_cat[:, None], gain_cat,
                                 NEG_INF)
            u_sub = jax.random.uniform(ks, (num_f,))
            max_thr = jnp.maximum(jnp.minimum(max_num_cat, used_bin) - 1, 0)
            rand_k = jnp.floor(
                u_sub * (max_thr + 1).astype(jnp.float32)).astype(jnp.int32)
            keep_sub = bin_idx == rand_k[:, None]
            gain_fwd = jnp.where(keep_sub, gain_fwd, NEG_INF)
            gain_bwd = jnp.where(keep_sub, gain_bwd, NEG_INF)

    cand = jnp.stack([gain_right, gain_left, gain_cat, gain_fwd, gain_bwd],
                     axis=-1)                                  # [F, B, V]
    if feature_mask is not None:
        cand = jnp.where(feature_mask[:, None, None], cand, NEG_INF)
    if gain_penalty is not None:
        # CEGB: per-feature acquisition cost subtracted from the split gain
        # before the argmax (cost_effective_gradient_boosting.hpp DeltaGain)
        cand = jnp.where(cand > NEG_INF / 2,
                         cand - gain_penalty[:, None, None], cand)

    if per_feature_out is not None:
        # voting-parallel hook: per-feature best gain before the global
        # argmax (reference voting_parallel_tree_learner.cpp:344 votes on
        # per-feature local split gains)
        per_feature_out.append(jnp.max(cand, axis=(1, 2)) - min_shift)

    if hp.use_monotone and hp.monotone_penalty > 0.0:
        # depth-decaying gain penalty on monotone features, applied to the
        # FINAL gain before cross-feature argmax (serial_tree_learner.cpp:994,
        # monotone_constraints.hpp:357 ComputeMonotoneSplitGainPenalty)
        d = jnp.float32(0 if depth is None else depth)
        p = jnp.float32(hp.monotone_penalty)
        eps = jnp.float32(1e-10)
        pen = jnp.where(p >= d + 1.0, eps,
                        jnp.where(p <= 1.0, 1.0 - p / (2.0 ** d) + eps,
                                  1.0 - 2.0 ** (p - 1.0 - d) + eps))
        pen_f = jnp.where(monotone != 0, pen, 1.0)[:, None, None]
        final = cand - min_shift
        cand = jnp.where(final > 0, final * pen_f, NEG_INF)
        min_shift = jnp.float32(0.0)

    flat = cand.reshape(-1)
    best = jnp.argmax(flat)
    best_gain_raw = flat[best]
    feat = (best // (n_b * NUM_VARIANTS)).astype(jnp.int32)
    rem = best % (n_b * NUM_VARIANTS)
    thr = (rem // NUM_VARIANTS).astype(jnp.int32)
    variant = (rem % NUM_VARIANTS).astype(jnp.int32)

    # recover the winner's left-side stats
    glw = jnp.stack([gl[feat, thr], gl[feat, thr] + gm[feat, 0], g[feat, thr],
                     gl_f[feat, thr], gl_b[feat, thr]])
    hlw = jnp.stack([hl[feat, thr], hl[feat, thr] + hm[feat, 0], h[feat, thr],
                     hl_f[feat, thr], hl_b[feat, thr]])
    nlw = jnp.stack([nl[feat, thr], nl[feat, thr] + nm[feat, 0], n[feat, thr],
                     nl_f[feat, thr], nl_b[feat, thr]])
    lg = glw[variant]
    lh = hlw[variant]
    ln = nlw[variant]

    gain = best_gain_raw - min_shift
    return SplitResult(
        gain=jnp.where(best_gain_raw <= NEG_INF / 2, jnp.float32(NEG_INF), gain),
        feature=feat,
        threshold=thr,
        default_left=(variant == VAR_NUM_LEFT),
        is_categorical=(variant >= VAR_CAT_ONEHOT),
        variant=variant,
        left_sum_g=lg, left_sum_h=lh, left_count=ln,
        right_sum_g=sum_g - lg, right_sum_h=sum_h - lh, right_count=count - ln,
    )


def categorical_left_bitset(hist_f: jax.Array, num_bins_f: jax.Array,
                            variant: jax.Array, threshold: jax.Array,
                            hp: SplitHyper) -> jax.Array:
    """Materialize the set of bins going LEFT for a categorical split.

    hist_f: f32 [B, C] — the PARENT leaf's histogram of the split feature;
    variant/threshold: the winning ``SplitResult`` fields.  Returns bool [B].
    For one-hot the set is {threshold}; for sorted-subset it re-derives the
    score ordering (deterministic given the histogram) and takes the first
    ``threshold + 1`` bins of the winning direction — the device-side twin of
    the reference's ``output->cat_threshold`` bitset write
    (feature_histogram.cpp:354-377).
    """
    n_b = hist_f.shape[0]
    g, h, n = hist_f[..., 0], hist_f[..., 1], hist_f[..., 2]
    bin_idx = lax.iota(jnp.int32, n_b)
    cand = (bin_idx < num_bins_f) & (n >= hp.cat_smooth)
    score = g / (h + hp.cat_smooth)
    INF = jnp.float32(1e30)
    key_f = jnp.where(cand, score, INF)
    key_b = jnp.where(cand, -score, INF)
    order = jnp.where(variant == VAR_CAT_BWD, jnp.argsort(key_b),
                      jnp.argsort(key_f))
    rank = jnp.zeros((n_b,), jnp.int32).at[order].set(bin_idx)
    subset_bits = (rank <= threshold) & cand
    onehot_bits = bin_idx == threshold
    return jnp.where(variant == VAR_CAT_ONEHOT, onehot_bits, subset_bits)
