"""Best-split finding from histograms.

TPU-native re-design of the reference split finder (reference:
src/treelearner/feature_histogram.hpp:832 ``FindBestThresholdSequentially``
CPU scans; src/treelearner/cuda/cuda_best_split_finder.cu:772
``FindBestSplitsForLeafKernel`` — one thread-block per (feature, direction)
with in-block prefix scans + arg-reduction).

On TPU the whole thing is a handful of vector ops over the [F, B] histogram:
cumulative sums along the bin axis give every threshold's left-side stats at
once, both missing-value default directions are evaluated as a 2-wide variant
axis (the reference's forward/backward scans), one-hot categorical candidates
ride the same argmax, and a single flat argmax picks the winner.  Bins beyond
a feature's ``num_bin`` and the dedicated NaN bin are masked, replacing the
reference's per-feature loop bounds.

Gain/regularization semantics mirror feature_histogram.hpp:
``ThresholdL1`` soft-shrink, gain = GL'^2/(HL+l2) + GR'^2/(HR+l2), validity =
min_data_in_leaf / min_sum_hessian_in_leaf on both children, reported gain is
the improvement over the parent minus ``min_gain_to_split``.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class SplitHyper:
    """Static split/growth hyperparameters (subset of reference Config used by
    the learner; config.h learning-control block)."""
    num_leaves: int = 31
    max_depth: int = -1
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    n_bins: int = 256
    rows_per_block: int = 4096
    path_smooth: float = 0.0
    hist_dtype: str = "float32"   # MXU contraction dtype; "bfloat16" opts into 8x MXU rate


class SplitResult(NamedTuple):
    """Chosen split for one leaf (reference split_info.hpp:294 ``SplitInfo``)."""
    gain: jax.Array          # f32 — improvement; <= 0 means "don't split"
    feature: jax.Array       # i32 packed feature index
    threshold: jax.Array     # i32 bin threshold (left = bin <= threshold)
    default_left: jax.Array  # bool — missing goes left
    is_categorical: jax.Array  # bool — one-hot categorical split (bin == thr)
    left_sum_g: jax.Array
    left_sum_h: jax.Array
    left_count: jax.Array
    right_sum_g: jax.Array
    right_sum_h: jax.Array
    right_count: jax.Array


def threshold_l1(s: jax.Array, l1: float) -> jax.Array:
    """Soft-threshold (reference feature_histogram.hpp ThresholdL1)."""
    if l1 <= 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_gain(g: jax.Array, h: jax.Array, l1: float, l2: float) -> jax.Array:
    t = threshold_l1(g, l1)
    return (t * t) / (h + l2 + 1e-15)


def leaf_output(g: jax.Array, h: jax.Array, l1: float, l2: float,
                max_delta_step: float = 0.0) -> jax.Array:
    """CalculateSplittedLeafOutput (feature_histogram.hpp static)."""
    out = -threshold_l1(g, l1) / (h + l2 + 1e-15)
    if max_delta_step > 0.0:
        out = jnp.clip(out, -max_delta_step, max_delta_step)
    return out


def find_best_split(hist: jax.Array, sum_g: jax.Array, sum_h: jax.Array,
                    count: jax.Array, num_bins: jax.Array, nan_bin: jax.Array,
                    is_cat: jax.Array, feature_mask: Optional[jax.Array],
                    hp: SplitHyper) -> SplitResult:
    """Pick the best (feature, threshold, default-dir) for one leaf.

    hist: f32 [F, B, C>=3] (grad, hess, count); sum_g/sum_h/count: leaf totals.
    num_bins/nan_bin: i32 [F]; is_cat: bool [F]; feature_mask: bool [F] or None.
    """
    num_f, n_b = hist.shape[0], hist.shape[1]
    g, h, n = hist[..., 0], hist[..., 1], hist[..., 2]
    bin_idx = lax.iota(jnp.int32, n_b)[None, :]                  # [1, B]
    valid_bin = bin_idx < num_bins[:, None]                      # [F, B]
    is_nan = bin_idx == nan_bin[:, None]                         # [F, B]

    # base cumulatives exclude the missing bin; its stats ride the variant axis
    gz = jnp.where(is_nan, 0.0, g)
    hz = jnp.where(is_nan, 0.0, h)
    nz = jnp.where(is_nan, 0.0, n)
    gl = jnp.cumsum(gz, axis=1)
    hl = jnp.cumsum(hz, axis=1)
    nl = jnp.cumsum(nz, axis=1)
    gm = jnp.sum(jnp.where(is_nan, g, 0.0), axis=1, keepdims=True)  # [F, 1]
    hm = jnp.sum(jnp.where(is_nan, h, 0.0), axis=1, keepdims=True)
    nm = jnp.sum(jnp.where(is_nan, n, 0.0), axis=1, keepdims=True)
    has_missing = nan_bin[:, None] >= 0

    l1, l2 = hp.lambda_l1, hp.lambda_l2
    parent_gain = leaf_gain(sum_g, sum_h, l1, l2)
    min_shift = parent_gain + hp.min_gain_to_split

    def variant_gain(gl_v, hl_v, nl_v):
        gr = sum_g - gl_v
        hr = sum_h - hl_v
        nr = count - nl_v
        gain = leaf_gain(gl_v, hl_v, l1, l2) + leaf_gain(gr, hr, l1, l2)
        ok = ((nl_v >= hp.min_data_in_leaf) & (nr >= hp.min_data_in_leaf)
              & (hl_v >= hp.min_sum_hessian_in_leaf)
              & (hr >= hp.min_sum_hessian_in_leaf))
        return jnp.where(ok, gain, NEG_INF)

    # numerical thresholds: t splits {bin <= t} | {bin > t}; t == last real bin
    # only splits off the missing bin, t at the nan bin itself is invalid
    thr_ok = valid_bin & (bin_idx < num_bins[:, None] - 1) & ~is_nan
    thr_ok = thr_ok & ~is_cat[:, None]
    gain_right = jnp.where(thr_ok, variant_gain(gl, hl, nl), NEG_INF)
    gain_left = jnp.where(thr_ok & has_missing,
                          variant_gain(gl + gm, hl + hm, nl + nm), NEG_INF)

    # one-hot categorical: {bin == t} goes left (reference
    # FindBestThresholdCategoricalInner one-hot branch, l2 += cat_l2)
    l2c = l2 + hp.cat_l2
    gl_cat, hl_cat, nl_cat = g, h, n

    def cat_gain():
        gr = sum_g - gl_cat
        hr = sum_h - hl_cat
        nr = count - nl_cat
        gain = leaf_gain(gl_cat, hl_cat, l1, l2c) + leaf_gain(gr, hr, l1, l2c)
        ok = ((nl_cat >= hp.min_data_in_leaf) & (nr >= hp.min_data_in_leaf)
              & (hl_cat >= hp.min_sum_hessian_in_leaf)
              & (hr >= hp.min_sum_hessian_in_leaf))
        return jnp.where(ok, gain, NEG_INF)

    gain_cat = jnp.where(valid_bin & is_cat[:, None], cat_gain(), NEG_INF)

    cand = jnp.stack([gain_right, gain_left, gain_cat], axis=-1)  # [F, B, 3]
    if feature_mask is not None:
        cand = jnp.where(feature_mask[:, None, None], cand, NEG_INF)

    flat = cand.reshape(-1)
    best = jnp.argmax(flat)
    best_gain_raw = flat[best]
    feat = (best // (n_b * 3)).astype(jnp.int32)
    rem = best % (n_b * 3)
    thr = (rem // 3).astype(jnp.int32)
    variant = (rem % 3).astype(jnp.int32)

    # recover the winner's left-side stats
    glw = jnp.stack([gl[feat, thr], gl[feat, thr] + gm[feat, 0], g[feat, thr]])
    hlw = jnp.stack([hl[feat, thr], hl[feat, thr] + hm[feat, 0], h[feat, thr]])
    nlw = jnp.stack([nl[feat, thr], nl[feat, thr] + nm[feat, 0], n[feat, thr]])
    lg = glw[variant]
    lh = hlw[variant]
    ln = nlw[variant]

    gain = best_gain_raw - min_shift
    return SplitResult(
        gain=jnp.where(best_gain_raw <= NEG_INF / 2, jnp.float32(NEG_INF), gain),
        feature=feat,
        threshold=thr,
        default_left=(variant == 1),
        is_categorical=(variant == 2),
        left_sum_g=lg, left_sum_h=lh, left_count=ln,
        right_sum_g=sum_g - lg, right_sum_h=sum_h - lh, right_count=count - ln,
    )
