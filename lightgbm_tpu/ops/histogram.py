"""Gradient/hessian histogram construction.

TPU-native re-design of the reference's hot kernel (reference:
src/treelearner/cuda/cuda_histogram_constructor.cu:18
``CUDAConstructHistogramDenseKernel`` — shared-memory atomic scatter-add; CPU
path src/io/dense_bin.hpp ``ConstructHistogram`` 4-way unrolled loops).

TPUs have no fast scatter-add, so the histogram is reformulated as a
contraction the MXU can run: for a block of rows, the per-feature one-hot of
the bin index contracted against the per-row value channels

    hist[f, b, c] = sum_r onehot(bins[r, f] == b) * vals[r, c]

which is ``dot_general`` with contracting dim r (one matmul per row block,
accumulated with ``lax.scan`` so the one-hot only ever exists for one block).
Channels are (grad, hess, count, pad) so a single contraction produces the
(g, h, n) triple the split finder needs — the reference interleaves grad/hess
the same way (train_share_states.h ordered gradients).

Leaf masking happens in ``vals`` (masked rows carry zeros), so one op serves
both the root pass and per-leaf passes; the caller implements the reference's
histogram-subtraction trick (serial_tree_learner.cpp:364-378) on top.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NUM_CHANNELS = 4  # grad, hess, count, pad


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(jax.jit, static_argnames=("n_bins", "rows_per_block",
                                             "feats_per_chunk"))
def build_histogram(bins: jax.Array, vals: jax.Array, *, n_bins: int = 256,
                    rows_per_block: int = 4096,
                    feats_per_chunk: int = 8) -> jax.Array:
    """hist[f, b, c] = sum over rows of onehot(bin) * vals.

    bins: uint8/int32 [n, F]; vals: f32 [n, C] (masked rows must be zero).
    Returns f32 [F, n_bins, C].
    """
    n, num_feat = bins.shape
    c = vals.shape[1]
    blk = min(rows_per_block, _round_up(max(n, 1), 128))
    n_pad = _round_up(max(n, 1), blk)
    if n_pad != n:
        bins = jnp.pad(bins, ((0, n_pad - n), (0, 0)))
        vals = jnp.pad(vals, ((0, n_pad - n), (0, 0)))  # zero vals: no effect
    nb = n_pad // blk
    fc = min(feats_per_chunk, num_feat)
    f_pad = _round_up(num_feat, fc)
    if f_pad != num_feat:
        bins = jnp.pad(bins, ((0, 0), (0, f_pad - num_feat)))
    bins_b = bins.astype(jnp.int32).reshape(nb, blk, f_pad)
    vals_b = vals.reshape(nb, blk, c)
    iota = lax.iota(jnp.int32, n_bins)

    def block_step(acc, xs):
        b_blk, v_blk = xs  # [blk, f_pad], [blk, c]
        parts = []
        for f0 in range(0, f_pad, fc):
            chunk = b_blk[:, f0:f0 + fc]                     # [blk, fc]
            onehot = (chunk[:, :, None] == iota).astype(vals.dtype)  # [blk, fc, B]
            lhs = onehot.reshape(blk, fc * n_bins)
            h = lax.dot_general(lhs, v_blk, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            parts.append(h.reshape(fc, n_bins, c))
        return acc + jnp.concatenate(parts, axis=0), None

    acc0 = jnp.zeros((f_pad, n_bins, c), dtype=jnp.float32)
    hist, _ = lax.scan(block_step, acc0, (bins_b, vals_b))
    return hist[:num_feat]


def histogram_for_leaf(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                       leaf_of_row: jax.Array, leaf: jax.Array,
                       row_mask: Optional[jax.Array] = None, *,
                       n_bins: int = 256, rows_per_block: int = 4096,
                       axis_name: Optional[str] = None) -> jax.Array:
    """Histogram of one leaf's rows via masking (dense row→leaf map — the
    TPU answer to CUDADataPartition: no data movement, rows never reorder)."""
    mask = (leaf_of_row == leaf)
    if row_mask is not None:
        mask = mask & row_mask
    m = mask.astype(grad.dtype)
    vals = jnp.stack([grad * m, hess * m, m, jnp.zeros_like(m)], axis=1)
    hist = build_histogram(bins, vals, n_bins=n_bins, rows_per_block=rows_per_block)
    if axis_name is not None:
        hist = lax.psum(hist, axis_name)
    return hist


def root_histogram(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                   row_mask: Optional[jax.Array] = None, *,
                   n_bins: int = 256, rows_per_block: int = 4096,
                   axis_name: Optional[str] = None) -> jax.Array:
    m = jnp.ones_like(grad) if row_mask is None else row_mask.astype(grad.dtype)
    vals = jnp.stack([grad * m, hess * m, m, jnp.zeros_like(m)], axis=1)
    hist = build_histogram(bins, vals, n_bins=n_bins, rows_per_block=rows_per_block)
    if axis_name is not None:
        hist = lax.psum(hist, axis_name)
    return hist
