"""Gradient/hessian histogram construction.

TPU-native re-design of the reference's hot kernel (reference:
src/treelearner/cuda/cuda_histogram_constructor.cu:18
``CUDAConstructHistogramDenseKernel`` — shared-memory atomic scatter-add; CPU
path src/io/dense_bin.hpp ``ConstructHistogram`` 4-way unrolled loops).

TPUs have no fast scatter-add, so the histogram is reformulated as a
contraction the MXU can run: for a block of rows, the per-feature one-hot of
the bin index contracted against the per-row value channels

    hist[f, b, c] = sum_r onehot(bins[r, f] == b) * vals[r, c]

which is ``dot_general`` with contracting dim r (one matmul per row block,
accumulated with ``lax.scan`` so the one-hot only ever exists for one block).
Channels are (grad, hess, count, pad) so a single contraction produces the
(g, h, n) triple the split finder needs — the reference interleaves grad/hess
the same way (train_share_states.h ordered gradients).

Leaf masking happens in ``vals`` (masked rows carry zeros), so one op serves
both the root pass and per-leaf passes; the caller implements the reference's
histogram-subtraction trick (serial_tree_learner.cpp:364-378) on top.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NUM_CHANNELS = 4  # grad, hess, count, pad

#: histogram-build formulations selectable via the ``hist_kernel`` config
#: key (round 6 — VERDICT r5 #1: the one-hot contraction is
#: formulation-bound, so the comparison itself must change).  All modes
#: are bit-identical on the same inputs; only the kernel arithmetic
#: differs:
#:   auto   — measured dispatch: radix single/joint where round-3 data
#:            says they win, the new packed/radix2 formulations where
#:            the one-hot build floor binds (see _masked_kernel_for);
#:   onehot — the flat one-hot kernels everywhere (the bit-identity
#:            reference path);
#:   packed — 4 bins per i32 lane, SWAR compares
#:            (hist_pallas.histogram_leaves_packed_pallas);
#:   radix2 — shared hi/lo nibble planes reused across all K leaf
#:            channels (hist_pallas.histogram_leaves_radix2_pallas).
HIST_KERNELS = ("auto", "onehot", "packed", "radix2")


def resolve_hist_kernel(name) -> str:
    """Validate a ``hist_kernel`` value; LightGBMError names the key."""
    n = str(name or "auto").strip().lower()
    if n not in HIST_KERNELS:
        from ..utils import log
        log.fatal("unknown hist_kernel=%r (expected one of %s)"
                  % (name, "/".join(HIST_KERNELS)))
    return n


# test hook: lets the CPU suite exercise the mode kernels through the
# Pallas interpreter (use_pallas() is False off-TPU)
_MODE_TEST_INTERPRET = False


def wants_packed_mirror(hist_kernel, n_bins: int) -> bool:
    """True when the resolved masked-pass kernel may consume the packed
    word mirror — the callers' cue to keep ``bins_words_t`` resident."""
    hk = resolve_hist_kernel(hist_kernel)
    if hk == "packed":
        return True
    return hk == "auto" and not _radix_ok(n_bins) and not _no_packed()


def ladder_profitable(hist_kernel, n_bins: int) -> bool:
    """True when the batched grower's width-matched warmup ladder still
    pays: only where the K<=4 masked pass takes the radix-JOINT kernel,
    whose build scales with the leaf count (auto dispatch at >= 128
    bins).  Every other mode's masked kernel is K-independent below one
    MXU channel tile (round-3 measurement; packed/onehot/radix2 share
    one build per block), so those configs seed the round loop at full
    width straight from the root histogram instead — identical
    selections (widths always cover the frontier), fewer compiled round
    bodies (docs/PERF_NOTES.md round 6)."""
    return resolve_hist_kernel(hist_kernel) == "auto" and _radix_ok(n_bins)


def _no_packed() -> bool:
    import os
    return bool(os.environ.get("LGBMTPU_NO_PACKED"))  # perf A/B hatch


def _no_radix2() -> bool:
    import os
    return bool(os.environ.get("LGBMTPU_NO_RADIX2"))  # perf A/B hatch


def _no_overlap() -> bool:
    import os
    return bool(os.environ.get("LGBMTPU_NO_OVERLAP"))  # perf A/B hatch


def overlap_enabled(overlap: bool) -> bool:
    """Trace-time resolution of the overlapped-collective request:
    the caller's ``overlap`` flag gated by the ``LGBMTPU_NO_OVERLAP``
    A/B hatch.  Shared by :func:`reduce_hist` and the growers' scalar
    root reductions so one env var kills every overlapped schedule."""
    return bool(overlap) and not _no_overlap()


def reduce_hist(hist: jax.Array, axis_name: Optional[str],
                overlap: bool = False) -> jax.Array:
    """All-reduce a histogram across ``axis_name`` (no-op when serial).

    The single sink every histogram builder's cross-device reduction
    flows through (``collective_overlap``, ISSUE 7).  With ``overlap``
    off this is exactly the blocking ``lax.psum`` the builders always
    issued.  With it on (and a leading axis to split), the reduction is
    issued as TWO independent psums over disjoint leading-axis halves,
    concatenated back together.  Bit-identical to the single psum: the
    halves are disjoint slices, and each element still sums the same
    per-device contributions in the same deterministic all-reduce order
    — only the *scheduling* changes.  Two independent collective
    start/done pairs give XLA's latency-hiding scheduler (TPU) a window
    to overlap the first half's wire time with the second half's local
    compute, instead of one monolithic blocking all-reduce.

    ``LGBMTPU_NO_OVERLAP`` is the trace-time A/B hatch (same contract
    as ``LGBMTPU_NO_PACKED``): set it to force the single-psum schedule
    regardless of config.
    """
    if axis_name is None:
        return hist
    if overlap_enabled(overlap) and hist.ndim >= 1 \
            and int(hist.shape[0]) >= 2:
        k = int(hist.shape[0]) // 2
        lo = lax.psum(hist[:k], axis_name)
        hi = lax.psum(hist[k:], axis_name)
        return jnp.concatenate([lo, hi], axis=0)
    return lax.psum(hist, axis_name)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def use_pallas() -> bool:
    """Pallas kernel on TPU; XLA one-hot contraction elsewhere (CPU tests,
    fallback)."""
    try:
        from .hist_pallas import HAS_PALLAS
        return HAS_PALLAS and jax.default_backend() == "tpu"
    except ImportError:  # pragma: no cover
        return False


def _pallas_blk(hist_dtype: str, n_bins: int = 256,
                float_cap: int = 1024) -> int:
    """Row-block cap for the flat/payload Pallas kernels.

    Round-4 tuning: ISOLATED int8 kernels run ~1.7x faster at blk=2048
    (flat 11.7/6.8/12.3 ms per 1M-row pass at 1024/2048/4096; payload
    13.4 -> 8.2 at a 250k bucket, K=28) — but IN CONTEXT at K=42 the
    2048 clamp regressed the tree loop 76.9 -> 84.9 ms/tree: the
    [3K, F*B] f32 accumulator plus the wider one-hot crowd VMEM and
    stall the grid's double buffering.  Standalone wins do not survive
    composition here; stay at 1024 until a K-aware model is measured.

    At <= 64 bins (the reference GPU docs' speed configuration) the
    accumulator and one-hot are 4x smaller, VMEM pressure disappears and
    the wider block wins in context too (round-5 measurement).
    """
    if n_bins <= 64:
        return 2048
    return float_cap


def histogram_rows(bins: jax.Array, vals: jax.Array, *, n_bins: int,
                   rows_per_block: int = 4096,
                   hist_dtype: str = "float32") -> jax.Array:
    """Backend-dispatched histogram over a row set.

    bins: uint8 [S, F]; vals: f32 [S, C] (masked rows zero).
    Returns f32 [F, n_bins, C].
    """
    return histogram_rows_t(bins.T, vals.T, n_bins=n_bins,
                            rows_per_block=rows_per_block,
                            hist_dtype=hist_dtype)


def histogram_rows_t(bins_t: jax.Array, vals_t: jax.Array, *, n_bins: int,
                     rows_per_block: int = 4096,
                     hist_dtype: str = "float32") -> jax.Array:
    """Histogram from TRANSPOSED operands — the layout the TPU kernel wants
    (row dim on lanes).  Callers on the hot path keep ``bins_t`` [F, n]
    resident so no per-call 28-byte-strided transpose happens.

    bins_t: uint8 [F, S]; vals_t: f32 [C, S].  Returns f32 [F, n_bins, C].
    """
    if use_pallas():
        from .hist_pallas import histogram_pallas
        return histogram_pallas(bins_t, vals_t, n_bins=n_bins,
                                rows_per_block=min(rows_per_block,
                                                   _pallas_blk(hist_dtype, n_bins)),
                                compute_dtype=jnp.dtype(hist_dtype).type)
    return build_histogram(bins_t.T, vals_t.T, n_bins=n_bins,
                           rows_per_block=rows_per_block)


@functools.partial(jax.jit, static_argnames=("n_bins", "rows_per_block",
                                             "feats_per_chunk"))
def build_histogram(bins: jax.Array, vals: jax.Array, *, n_bins: int = 256,
                    rows_per_block: int = 4096,
                    feats_per_chunk: int = 8) -> jax.Array:
    """hist[f, b, c] = sum over rows of onehot(bin) * vals.

    bins: uint8/int32 [n, F]; vals: f32 [n, C] (masked rows must be zero).
    Returns f32 [F, n_bins, C].
    """
    n, num_feat = bins.shape
    c = vals.shape[1]
    blk = min(rows_per_block, _round_up(max(n, 1), 128))
    n_pad = _round_up(max(n, 1), blk)
    if n_pad != n:
        bins = jnp.pad(bins, ((0, n_pad - n), (0, 0)))
        vals = jnp.pad(vals, ((0, n_pad - n), (0, 0)))  # zero vals: no effect
    nb = n_pad // blk
    fc = min(feats_per_chunk, num_feat)
    f_pad = _round_up(num_feat, fc)
    if f_pad != num_feat:
        bins = jnp.pad(bins, ((0, 0), (0, f_pad - num_feat)))
    bins_b = bins.astype(jnp.int32).reshape(nb, blk, f_pad)
    vals_b = vals.reshape(nb, blk, c)
    iota = lax.iota(jnp.int32, n_bins)

    def block_step(acc, xs):
        b_blk, v_blk = xs  # [blk, f_pad], [blk, c]
        parts = []
        for f0 in range(0, f_pad, fc):
            chunk = b_blk[:, f0:f0 + fc]                     # [blk, fc]
            onehot = (chunk[:, :, None] == iota).astype(vals.dtype)  # [blk, fc, B]
            lhs = onehot.reshape(blk, fc * n_bins)
            h = lax.dot_general(lhs, v_blk, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=lax.Precision.HIGHEST)
            parts.append(h.reshape(fc, n_bins, c))
        return acc + jnp.concatenate(parts, axis=0), None

    acc0 = jnp.zeros((f_pad, n_bins, c), dtype=jnp.float32)
    hist, _ = lax.scan(block_step, acc0, (bins_b, vals_b))
    return hist[:num_feat]


def _radix_ok(n_bins: int) -> bool:
    """The radix kernels decompose bin = 16*hi + lo (ops/hist_pallas.py
    ``_radix_shapes``); any other bin width falls back to the flat kernel.
    ``LGBMTPU_NO_RADIX=1`` disables them (perf A/B escape hatch).

    Below 128 bins the flat kernel wins outright: the radix build cost is
    nibble-bound (nhi + nlo one-hot elements — 20 at 64 bins vs the flat
    kernel's 64) but its small [p*nhi, 3*p*nlo] matmul tiles waste the
    MXU, measured 2.4 ms (radix joint) vs 1.7 ms (flat, full 63-bin K=42
    masked pass) on the live chip in round 5."""
    import os
    if os.environ.get("LGBMTPU_NO_RADIX"):
        return False
    return n_bins % 16 == 0 and n_bins >= 128


def histogram_for_leaf_masked(bins_t: jax.Array, grad: jax.Array,
                              hess: jax.Array, leaf_of_row: jax.Array,
                              leaf: jax.Array,
                              row_mask: Optional[jax.Array] = None, *,
                              n_bins: int = 256, rows_per_block: int = 4096,
                              hist_dtype: str = "float32",
                              axis_name: Optional[str] = None,
                              hist_kernel: str = "auto",
                              bins_words_t: Optional[jax.Array] = None,
                              overlap: bool = False
                              ) -> jax.Array:
    """Leaf histogram by masking: one full-data pass with non-leaf rows
    zeroed.  O(n) per call but with NO compaction machinery.  Under
    ``hist_kernel=auto`` on TPU the single-group radix kernel carries it
    (~1.7x the flat one-hot kernel, docs/PERF_NOTES.md round 3);
    ``bins_t`` is the TRANSPOSED [F, n] matrix."""
    hk = resolve_hist_kernel(hist_kernel)
    if (use_pallas() or _MODE_TEST_INTERPRET) and hk == "auto" \
            and _radix_ok(n_bins):
        from .hist_pallas import histogram_radix_single_pallas
        lor = jnp.asarray(leaf_of_row, jnp.int32)
        sel = lor == jnp.asarray(leaf, jnp.int32)
        if row_mask is not None:
            sel = sel & row_mask
        lor1 = jnp.where(sel, 0, -1)
        hist = histogram_radix_single_pallas(
            bins_t, grad, hess, lor1, n_bins=n_bins,
            rows_per_block=min(rows_per_block, 2048),
            compute_dtype=jnp.dtype(hist_dtype).type,
            interpret=not use_pallas())
        return reduce_hist(hist, axis_name, overlap)
    leaf_arr = jnp.asarray(leaf, jnp.int32).reshape(1)
    hist = histogram_for_leaves_masked(
        bins_t, grad, hess, leaf_of_row, leaf_arr, row_mask, n_bins=n_bins,
        rows_per_block=rows_per_block, hist_dtype=hist_dtype,
        axis_name=axis_name, hist_kernel=hk, bins_words_t=bins_words_t,
        overlap=overlap)
    return hist[0]


def _masked_kernel_for(hk: str, n_bins: int, K: int, num_f: int,
                       have_words: bool) -> str:
    """Resolve the masked-pass kernel for a mode: one of
    flat / packed / radix2 / radix_joint.

    auto keeps the round-3 measured dispatch (radix joint at K<=4 and
    >= 128 bins) and routes the two cases the round-5 floor analysis
    proved formulation-bound to the new kernels: the >= 128-bin K>4
    masked pass (256-wide one-hot build, ~21% of int8 peak) to the
    shared-radix kernel, and the sub-128-bin masked pass (build-phase
    share grows as the dot shrinks, ~17% peak at 63 bins) to the
    packed-compare kernel.  Explicit modes force their kernel where its
    shape constraints hold and fall back to flat (bit-identical) where
    they don't."""
    from .hist_pallas import radix2_pick_p
    radix2_fits = (n_bins % 16 == 0 and n_bins >= 16
                   and radix2_pick_p(num_f, K, n_bins) > 0)
    if hk == "packed":
        return "packed" if have_words else "flat"
    if hk == "radix2":
        return "radix2" if radix2_fits else "flat"
    if hk == "auto":
        if _radix_ok(n_bins):
            if K <= 4:
                return "radix_joint"
            if radix2_fits and not _no_radix2():
                return "radix2"
        elif have_words and not _no_packed():
            return "packed"
    return "flat"


def histogram_for_leaves_masked(bins_t: jax.Array, grad: jax.Array,
                                hess: jax.Array, leaf_of_row: jax.Array,
                                leaves: jax.Array,
                                row_mask: Optional[jax.Array] = None, *,
                                n_bins: int = 256,
                                rows_per_block: int = 4096,
                                hist_dtype: str = "float32",
                                axis_name: Optional[str] = None,
                                hist_kernel: str = "auto",
                                bins_words_t: Optional[jax.Array] = None,
                                overlap: bool = False
                                ) -> jax.Array:
    """Histograms of K leaves in ONE data pass -> f32 [K, F, B, C].

    The one-hot construction (the TPU kernel's dominant cost) is built once
    and contracted against K x C masked value channels, so K leaves cost
    barely more than one — the enabler of batched split rounds
    (learner/batch_grower.py).  Widening channels also fills the MXU's
    sublane dimension (M = 4K instead of 4).  ``leaves``: i32 [K]; invalid
    slots may repeat a leaf (their histograms are simply unused).

    ``hist_kernel`` selects the build formulation (``HIST_KERNELS``; all
    modes bit-identical); ``bins_words_t`` is the resident packed-word
    mirror [W, n] the packed mode consumes (io/dataset.py
    ``packed_mirror``).
    """
    hk = resolve_hist_kernel(hist_kernel)
    K = leaves.shape[0]
    num_f = bins_t.shape[0]
    leaves = jnp.asarray(leaves, jnp.int32)
    lor = jnp.asarray(leaf_of_row, jnp.int32)
    if row_mask is not None:
        lor = jnp.where(row_mask, lor, -1)
    kern_active = use_pallas() or _MODE_TEST_INTERPRET
    kern = _masked_kernel_for(hk, n_bins, K, num_f,
                              bins_words_t is not None) \
        if kern_active else "xla"
    interp = not use_pallas()
    if kern == "radix_joint":
        # joint (leaf, hi) radix kernel: measured 4.0/5.0/7.5 ms per 1M-row
        # pass at K=1/2/4 vs the flat kernel's K-independent ~9.8
        # (docs/PERF_NOTES.md round 3) — the warmup-round accelerator
        from .hist_pallas import histogram_radix_joint_pallas
        hist = histogram_radix_joint_pallas(
            bins_t, grad, hess, lor, leaves, n_bins=n_bins,
            rows_per_block=min(rows_per_block, 2048),
            compute_dtype=jnp.dtype(hist_dtype).type, interpret=interp)
        return reduce_hist(hist, axis_name, overlap)
    if kern == "radix2":
        from .hist_pallas import (histogram_leaves_radix2_pallas,
                                  radix2_pick_p)
        hist = histogram_leaves_radix2_pallas(
            bins_t, grad, hess, lor, leaves, n_bins=n_bins,
            rows_per_block=min(rows_per_block, 1024),
            p=radix2_pick_p(num_f, K, n_bins),
            compute_dtype=jnp.dtype(hist_dtype).type, interpret=interp)
        return reduce_hist(hist, axis_name, overlap)
    if kern == "packed":
        from .hist_pallas import histogram_leaves_packed_pallas
        hist = histogram_leaves_packed_pallas(
            bins_words_t, grad, hess, lor, leaves, num_f=num_f,
            n_bins=n_bins,
            rows_per_block=min(rows_per_block, _pallas_blk(hist_dtype, n_bins)),
            compute_dtype=jnp.dtype(hist_dtype).type, interpret=interp)
        return reduce_hist(hist, axis_name, overlap)
    if kern == "flat":
        from .hist_pallas import histogram_leaves_pallas
        hist = histogram_leaves_pallas(
            bins_t, grad, hess, lor, leaves, n_bins=n_bins,
            rows_per_block=min(rows_per_block, _pallas_blk(hist_dtype, n_bins)),
            compute_dtype=jnp.dtype(hist_dtype).type,
            interpret=interp)                                 # [K, F, B, C]
    else:
        sel = lor[None, :] == leaves[:, None]                 # [K, n]
        m = sel.astype(grad.dtype)
        # where(), not multiply: 0 * NaN = NaN would let one bad excluded
        # row poison the sums (matches the Pallas kernel's masking)
        vals_t = jnp.stack([jnp.where(sel, grad[None, :], 0.0),
                            jnp.where(sel, hess[None, :], 0.0), m,
                            jnp.zeros_like(m)], axis=0)
        C = vals_t.shape[0]
        vals_t = vals_t.reshape(C * K, -1)
        hist = histogram_rows_t(bins_t, vals_t, n_bins=n_bins,
                                rows_per_block=rows_per_block,
                                hist_dtype=hist_dtype)        # [F, B, C*K]
        F, B = hist.shape[0], hist.shape[1]
        hist = hist.reshape(F, B, C, K).transpose(3, 0, 1, 2)  # [K, F, B, C]
    return reduce_hist(hist, axis_name, overlap)


def _rows_leaves_hist(bins_rows: jax.Array, grad: jax.Array,
                      hess: jax.Array, lor: jax.Array, leaves: jax.Array, *,
                      n_bins: int, rows_per_block: int,
                      hist_dtype: str) -> jax.Array:
    """[K, F, B, C] histograms from row-major bins (backend-dispatched)."""
    if use_pallas():
        from .hist_pallas import histogram_leaves_rows_pallas
        return histogram_leaves_rows_pallas(
            bins_rows, grad, hess, lor, leaves, n_bins=n_bins,
            rows_per_block=min(rows_per_block, _pallas_blk(hist_dtype, n_bins)),
            compute_dtype=jnp.dtype(hist_dtype).type)
    return histogram_for_leaves_masked(
        jnp.asarray(bins_rows).T, grad, hess, lor, leaves, None,
        n_bins=n_bins, rows_per_block=rows_per_block, hist_dtype=hist_dtype,
        hist_kernel="onehot")


# test hook: lets the CPU suite exercise the payload Pallas kernel via the
# interpreter (use_pallas() is False off-TPU)
_PAYLOAD_TEST_INTERPRET = False


def _use_payload_kernel() -> bool:
    import os
    if os.environ.get("LGBMTPU_NO_PAYLOAD_KERNEL"):  # perf A/B escape hatch
        return False
    return use_pallas() or _PAYLOAD_TEST_INTERPRET


def bins_to_words(bins_rows: jax.Array) -> jax.Array:
    """u8 [n, F] row-major bins -> i32 [n, ceil(F/4)] word view (each word
    packs 4 bin bytes little-endian).  Tree-invariant: built once and
    reused by every compacted round's payload concat."""
    n, num_f = bins_rows.shape
    pad = (-num_f) % 4
    if pad:
        bins_rows = jnp.pad(bins_rows, ((0, 0), (0, pad)))
    w = (num_f + pad) // 4
    return lax.bitcast_convert_type(
        bins_rows.reshape(n, w, 4), jnp.int32)


def histogram_for_leaves_auto(bins_rows: jax.Array, bins_t: jax.Array,
                              grad: jax.Array, hess: jax.Array,
                              leaf_of_row: jax.Array, leaves: jax.Array,
                              row_mask: Optional[jax.Array] = None, *,
                              n_bins: int = 256, rows_per_block: int = 2048,
                              hist_dtype: str = "float32",
                              axis_name: Optional[str] = None,
                              buckets=(4, 8, 16, 64),
                              counts: Optional[jax.Array] = None,
                              bins_words: Optional[jax.Array] = None,
                              sort_key: Optional[jax.Array] = None,
                              hist_kernel: str = "auto",
                              bins_words_t: Optional[jax.Array] = None,
                              payload: Optional[jax.Array] = None,
                              overlap: bool = False
                              ) -> jax.Array:
    """K-leaf histograms with frontier compaction -> f32 [K, F, B, C].

    The TPU reformulation of the reference's O(smaller-child) histogram cost
    (serial_tree_learner.cpp:364-378 iterates only the leaf's data indices):
    when the rows belonging to ``leaves`` fit a power-of-two bucket, they are
    compacted with a packed single sort + contiguous row gather of an i32
    WORD payload (4 bin bytes per word + grad/hess/leaf words — same 40
    bytes/row as the old u8 layout) and the payload kernel runs on the
    bucket; otherwise one full masked pass (``histogram_for_leaves_masked``).
    Total histogram work per tree drops from O(n x rounds) to ~O(n log L),
    which the flat masked pass cannot do.  Exact: the same rows contribute
    either way.

    A leaf-GROUPED compaction variant (rows sorted by leaf, block->leaf
    scalar-prefetch steering) was built and measured slower end-to-end in
    round 3 — the K-channel MXU multiplier it removes does not exist below
    128 output channels, while its layout glue is real — and was deleted
    (docs/PERF_NOTES.md round 3).

    ``bins_rows``: u8 [n, F] row-major; ``bins_t``: u8 [F, n] transposed.

    ``counts`` (f32 [K], optional): the caller's known masked row count per
    leaf slot (0 for dummy slots); saves the [K, n] membership reduction.
    ``bins_words`` (i32 [n, ceil(F/4)], optional): ``bins_to_words`` result
    hoisted out of the round loop by the caller.
    ``sort_key`` (i32 [n], optional): precomputed (selected ? row :
    row | 2^30) keys from the fused partition kernel (ops/round_fuse.py);
    built here from the membership mask otherwise.
    ``payload`` (i32 [n, W+3], optional): the full compaction payload
    already emitted by the payload-fused partition kernel
    (ops/round_fuse.py ``partition_payload_pallas``) — skips the XLA
    concat entirely (round-6 glue elimination).
    ``hist_kernel``/``bins_words_t``: masked-pass formulation + packed
    mirror, forwarded to ``histogram_for_leaves_masked``.
    """
    hist_kernel = resolve_hist_kernel(hist_kernel)
    n = grad.shape[0]
    leaves = jnp.asarray(leaves, jnp.int32)
    lor = jnp.asarray(leaf_of_row, jnp.int32)
    if row_mask is not None:
        lor = jnp.where(row_mask, lor, -1)
    assert n < (1 << 30), "compaction packing needs n < 2^30 rows per shard"
    num_f = bins_rows.shape[1]

    if counts is not None:
        cnt = jnp.sum(counts).astype(jnp.int32)
    else:
        sel = jnp.any(lor[None, :] == leaves[:, None], axis=0)    # [n]
        cnt = jnp.sum(sel.astype(jnp.int32))
    if sort_key is None:
        if counts is not None:
            sel = jnp.any(lor[None, :] == leaves[:, None], axis=0)
        # pack (selected?, row) into ONE i32 and single-sort in the
        # branch — the first ``cnt`` sorted entries are exactly the
        # selected rows in order.  A non-stable single-operand sort costs
        # ~0.4 ms/1M on TPU vs ~1.4 ms for stable argsort and ~9 ms for
        # sized ``nonzero`` (docs/PERF_NOTES.md).
        iota_n = lax.iota(jnp.int32, n)
        sort_key = jnp.where(sel, iota_n, iota_n | (1 << 30))
    if bins_words is None:
        bins_words = bins_to_words(bins_rows)
    W = bins_words.shape[1]

    blk = min(rows_per_block, 2048)
    sizes = []
    for d in buckets:
        s = _round_up(max(n // d, 1), blk)
        if s < n and s not in sizes:
            sizes.append(s)

    def full_branch(operands):
        return histogram_for_leaves_masked(
            bins_t, grad, hess, lor, leaves, None, n_bins=n_bins,
            rows_per_block=rows_per_block, hist_dtype=hist_dtype,
            hist_kernel=hist_kernel, bins_words_t=bins_words_t)

    def make_branch(S: int):
        def branch(operands):
            if payload is not None:
                key_, payload_ = operands
            else:
                key_, grad_, hess_, lor_ = operands
                # One payload matrix holding (bin words, grad, hess, leaf)
                # so the branch does a SINGLE contiguous row gather —
                # separate gathers are DMA-descriptor bound (~9 ns/row
                # each).  The bin words are the hoisted tree-invariant
                # view; only 12 bytes per row are fresh.  Built INSIDE the
                # branch so full-pass rounds skip the concat and the sort
                # entirely.  (The payload-fused partition kernel hands the
                # matrix in pre-built instead — ops/round_fuse.py.)
                payload_ = jnp.concatenate([
                    bins_words,
                    lax.bitcast_convert_type(grad_, jnp.int32)[:, None],
                    lax.bitcast_convert_type(hess_, jnp.int32)[:, None],
                    lor_[:, None],
                ], axis=1)                                    # [n, W+3] i32
            idxc = jnp.sort(key_, stable=False)[:S] & ((1 << 30) - 1)
            pc = payload_[idxc]                               # [S, W+3]
            if _use_payload_kernel():
                from .hist_pallas import histogram_payload_pallas
                return histogram_payload_pallas(
                    pc, leaves, cnt, num_f=num_f, n_bins=n_bins,
                    rows_per_block=min(rows_per_block,
                                       _pallas_blk(hist_dtype, n_bins)),
                    compute_dtype=jnp.dtype(hist_dtype).type,
                    interpret=not use_pallas())
            # XLA fallback (CPU tests / non-TPU): unpack and run the
            # generic rows path
            valid = lax.iota(jnp.int32, S) < cnt
            rows_c = lax.bitcast_convert_type(
                pc[:, :W], jnp.uint8).reshape(S, 4 * W)[:, :num_f]
            grad_c = lax.bitcast_convert_type(pc[:, W], jnp.float32)
            hess_c = lax.bitcast_convert_type(pc[:, W + 1], jnp.float32)
            lor_c = jnp.where(valid, pc[:, W + 2], -1)
            return _rows_leaves_hist(rows_c, grad_c, hess_c, lor_c,
                                     leaves, n_bins=n_bins,
                                     rows_per_block=rows_per_block,
                                     hist_dtype=hist_dtype)
        return branch

    branches = [full_branch] + [make_branch(s) for s in sizes]
    j = jnp.int32(0)
    for k, s in enumerate(sizes):  # sizes descending: smallest fit wins
        j = jnp.where(cnt <= s, jnp.int32(k + 1), j)
    operands = (sort_key, payload) if payload is not None \
        else (sort_key, grad, hess, lor)
    hist = lax.switch(j, branches, operands)
    return reduce_hist(hist, axis_name, overlap)


def histogram_for_leaf_bucketed(bins: jax.Array, grad: jax.Array,
                                hess: jax.Array, leaf_of_row: jax.Array,
                                leaf: jax.Array, leaf_count: jax.Array,
                                row_mask: Optional[jax.Array] = None, *,
                                n_bins: int = 256, rows_per_block: int = 4096,
                                min_bucket: int = 8192, hist_dtype: str = "float32",
                                axis_name: Optional[str] = None,
                                overlap: bool = False) -> jax.Array:
    """Histogram of one leaf touching only ~leaf_count rows.

    The TPU reformulation of the reference's ordered-index iteration
    (CUDADataPartition keeps rows physically grouped by leaf;
    dense_bin.hpp iterates data_indices): rows stay in place, but the
    leaf's row indices are compacted with a sized ``nonzero`` and gathered
    into the smallest power-of-two buffer that fits (``lax.switch`` over
    log2(n) precompiled bucket sizes), so histogram cost follows the
    smaller child's size instead of the full dataset — preserving the
    O(n log L) total work of leaf-wise growth with histogram subtraction
    (serial_tree_learner.cpp:364-378).

    ``leaf_count`` is the number of rows in ``leaf`` (device scalar).
    """
    n = bins.shape[0]
    mask = (leaf_of_row == leaf)
    if row_mask is not None:
        mask = mask & row_mask

    # bucket sizes n, n/2, n/4, ..., >= min_bucket
    sizes = []
    s = _round_up(n, 128)
    while True:
        sizes.append(s)
        if s <= min_bucket:
            break
        s = _round_up((s + 1) // 2, 128)
    # branch index: largest j with sizes[j] >= count
    count = jnp.maximum(leaf_count.astype(jnp.int32), 1)
    j = jnp.int32(0)
    for k, sz in enumerate(sizes):
        j = jnp.where(count <= sz, jnp.int32(k), j)

    def make_branch(sz: int):
        def branch(operands):
            mask_, grad_, hess_ = operands
            idx = jnp.nonzero(mask_, size=sz, fill_value=n)[0]
            valid = (idx < n).astype(grad_.dtype)
            idxc = jnp.minimum(idx, n - 1)
            b_sub = bins[idxc]
            g_sub = grad_[idxc] * valid
            h_sub = hess_[idxc] * valid
            vals = jnp.stack([g_sub, h_sub, valid, jnp.zeros_like(valid)],
                             axis=1)
            return histogram_rows(b_sub, vals, n_bins=n_bins,
                                  rows_per_block=rows_per_block,
                                  hist_dtype=hist_dtype)
        return branch

    hist = lax.switch(j, [make_branch(sz) for sz in sizes],
                      (mask, grad, hess))
    return reduce_hist(hist, axis_name, overlap)


def root_histogram(bins_t: jax.Array, grad: jax.Array, hess: jax.Array,
                   row_mask: Optional[jax.Array] = None, *,
                   n_bins: int = 256, rows_per_block: int = 4096,
                   hist_dtype: str = "float32",
                   axis_name: Optional[str] = None,
                   hist_kernel: str = "auto",
                   bins_words_t: Optional[jax.Array] = None,
                   overlap: bool = False) -> jax.Array:
    """Root histogram from the TRANSPOSED [F, n] bin matrix."""
    hist_kernel = resolve_hist_kernel(hist_kernel)
    if use_pallas() or _MODE_TEST_INTERPRET:
        # single-leaf delegation picks the mode kernel (radix single
        # under auto when bins allow, packed/radix2/flat otherwise)
        lor = jnp.zeros(grad.shape, jnp.int32)
        return histogram_for_leaf_masked(
            bins_t, grad, hess, lor, jnp.int32(0), row_mask, n_bins=n_bins,
            rows_per_block=rows_per_block, hist_dtype=hist_dtype,
            axis_name=axis_name, hist_kernel=hist_kernel,
            bins_words_t=bins_words_t, overlap=overlap)
    m = jnp.ones_like(grad) if row_mask is None else row_mask.astype(grad.dtype)
    vals_t = jnp.stack([jnp.where(m > 0, grad, 0.0),
                        jnp.where(m > 0, hess, 0.0), m,
                        jnp.zeros_like(m)], axis=0)
    hist = histogram_rows_t(bins_t, vals_t, n_bins=n_bins,
                            rows_per_block=rows_per_block,
                            hist_dtype=hist_dtype)
    return reduce_hist(hist, axis_name, overlap)
