"""Gradient/hessian histogram construction.

TPU-native re-design of the reference's hot kernel (reference:
src/treelearner/cuda/cuda_histogram_constructor.cu:18
``CUDAConstructHistogramDenseKernel`` — shared-memory atomic scatter-add; CPU
path src/io/dense_bin.hpp ``ConstructHistogram`` 4-way unrolled loops).

TPUs have no fast scatter-add, so the histogram is reformulated as a
contraction the MXU can run: for a block of rows, the per-feature one-hot of
the bin index contracted against the per-row value channels

    hist[f, b, c] = sum_r onehot(bins[r, f] == b) * vals[r, c]

which is ``dot_general`` with contracting dim r (one matmul per row block,
accumulated with ``lax.scan`` so the one-hot only ever exists for one block).
Channels are (grad, hess, count, pad) so a single contraction produces the
(g, h, n) triple the split finder needs — the reference interleaves grad/hess
the same way (train_share_states.h ordered gradients).

Leaf masking happens in ``vals`` (masked rows carry zeros), so one op serves
both the root pass and per-leaf passes; the caller implements the reference's
histogram-subtraction trick (serial_tree_learner.cpp:364-378) on top.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NUM_CHANNELS = 4  # grad, hess, count, pad

# test hook: lets the CPU suite exercise the grouped compaction path via the
# pallas interpreter (use_pallas() is False off-TPU)
_GROUPED_TEST_INTERPRET = False


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def use_pallas() -> bool:
    """Pallas kernel on TPU; XLA one-hot contraction elsewhere (CPU tests,
    fallback)."""
    try:
        from .hist_pallas import HAS_PALLAS
        return HAS_PALLAS and jax.default_backend() == "tpu"
    except ImportError:  # pragma: no cover
        return False


def histogram_rows(bins: jax.Array, vals: jax.Array, *, n_bins: int,
                   rows_per_block: int = 4096,
                   hist_dtype: str = "float32") -> jax.Array:
    """Backend-dispatched histogram over a row set.

    bins: uint8 [S, F]; vals: f32 [S, C] (masked rows zero).
    Returns f32 [F, n_bins, C].
    """
    return histogram_rows_t(bins.T, vals.T, n_bins=n_bins,
                            rows_per_block=rows_per_block,
                            hist_dtype=hist_dtype)


def histogram_rows_t(bins_t: jax.Array, vals_t: jax.Array, *, n_bins: int,
                     rows_per_block: int = 4096,
                     hist_dtype: str = "float32") -> jax.Array:
    """Histogram from TRANSPOSED operands — the layout the TPU kernel wants
    (row dim on lanes).  Callers on the hot path keep ``bins_t`` [F, n]
    resident so no per-call 28-byte-strided transpose happens.

    bins_t: uint8 [F, S]; vals_t: f32 [C, S].  Returns f32 [F, n_bins, C].
    """
    if use_pallas():
        from .hist_pallas import histogram_pallas
        return histogram_pallas(bins_t, vals_t, n_bins=n_bins,
                                rows_per_block=min(rows_per_block, 1024),
                                compute_dtype=jnp.dtype(hist_dtype).type)
    return build_histogram(bins_t.T, vals_t.T, n_bins=n_bins,
                           rows_per_block=rows_per_block)


@functools.partial(jax.jit, static_argnames=("n_bins", "rows_per_block",
                                             "feats_per_chunk"))
def build_histogram(bins: jax.Array, vals: jax.Array, *, n_bins: int = 256,
                    rows_per_block: int = 4096,
                    feats_per_chunk: int = 8) -> jax.Array:
    """hist[f, b, c] = sum over rows of onehot(bin) * vals.

    bins: uint8/int32 [n, F]; vals: f32 [n, C] (masked rows must be zero).
    Returns f32 [F, n_bins, C].
    """
    n, num_feat = bins.shape
    c = vals.shape[1]
    blk = min(rows_per_block, _round_up(max(n, 1), 128))
    n_pad = _round_up(max(n, 1), blk)
    if n_pad != n:
        bins = jnp.pad(bins, ((0, n_pad - n), (0, 0)))
        vals = jnp.pad(vals, ((0, n_pad - n), (0, 0)))  # zero vals: no effect
    nb = n_pad // blk
    fc = min(feats_per_chunk, num_feat)
    f_pad = _round_up(num_feat, fc)
    if f_pad != num_feat:
        bins = jnp.pad(bins, ((0, 0), (0, f_pad - num_feat)))
    bins_b = bins.astype(jnp.int32).reshape(nb, blk, f_pad)
    vals_b = vals.reshape(nb, blk, c)
    iota = lax.iota(jnp.int32, n_bins)

    def block_step(acc, xs):
        b_blk, v_blk = xs  # [blk, f_pad], [blk, c]
        parts = []
        for f0 in range(0, f_pad, fc):
            chunk = b_blk[:, f0:f0 + fc]                     # [blk, fc]
            onehot = (chunk[:, :, None] == iota).astype(vals.dtype)  # [blk, fc, B]
            lhs = onehot.reshape(blk, fc * n_bins)
            h = lax.dot_general(lhs, v_blk, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=lax.Precision.HIGHEST)
            parts.append(h.reshape(fc, n_bins, c))
        return acc + jnp.concatenate(parts, axis=0), None

    acc0 = jnp.zeros((f_pad, n_bins, c), dtype=jnp.float32)
    hist, _ = lax.scan(block_step, acc0, (bins_b, vals_b))
    return hist[:num_feat]


def _radix_ok(n_bins: int) -> bool:
    """The radix kernels decompose bin = 16*hi + lo (ops/hist_pallas.py
    ``_radix_shapes``); any other bin width falls back to the flat kernel.
    ``LGBMTPU_NO_RADIX=1`` disables them (perf A/B escape hatch)."""
    import os
    if os.environ.get("LGBMTPU_NO_RADIX"):
        return False
    return n_bins % 16 == 0 and n_bins >= 32


def histogram_for_leaf_masked(bins_t: jax.Array, grad: jax.Array,
                              hess: jax.Array, leaf_of_row: jax.Array,
                              leaf: jax.Array,
                              row_mask: Optional[jax.Array] = None, *,
                              n_bins: int = 256, rows_per_block: int = 4096,
                              hist_dtype: str = "float32",
                              axis_name: Optional[str] = None) -> jax.Array:
    """Leaf histogram by masking: one full-data pass with non-leaf rows
    zeroed.  O(n) per call but with NO compaction machinery.  On TPU the
    single-group radix kernel carries it (~1.7x the flat one-hot kernel,
    docs/PERF_NOTES.md round 3); ``bins_t`` is the TRANSPOSED [F, n]
    matrix."""
    if use_pallas() and _radix_ok(n_bins):
        from .hist_pallas import histogram_radix_single_pallas
        lor = jnp.asarray(leaf_of_row, jnp.int32)
        sel = lor == jnp.asarray(leaf, jnp.int32)
        if row_mask is not None:
            sel = sel & row_mask
        lor1 = jnp.where(sel, 0, -1)
        hist = histogram_radix_single_pallas(
            bins_t, grad, hess, lor1, n_bins=n_bins,
            rows_per_block=min(rows_per_block, 2048),
            compute_dtype=jnp.dtype(hist_dtype).type)
        if axis_name is not None:
            hist = lax.psum(hist, axis_name)
        return hist
    leaf_arr = jnp.asarray(leaf, jnp.int32).reshape(1)
    hist = histogram_for_leaves_masked(
        bins_t, grad, hess, leaf_of_row, leaf_arr, row_mask, n_bins=n_bins,
        rows_per_block=rows_per_block, hist_dtype=hist_dtype,
        axis_name=axis_name)
    return hist[0]


def histogram_for_leaves_masked(bins_t: jax.Array, grad: jax.Array,
                                hess: jax.Array, leaf_of_row: jax.Array,
                                leaves: jax.Array,
                                row_mask: Optional[jax.Array] = None, *,
                                n_bins: int = 256,
                                rows_per_block: int = 4096,
                                hist_dtype: str = "float32",
                                axis_name: Optional[str] = None
                                ) -> jax.Array:
    """Histograms of K leaves in ONE data pass -> f32 [K, F, B, C].

    The one-hot construction (the TPU kernel's dominant cost) is built once
    and contracted against K x C masked value channels, so K leaves cost
    barely more than one — the enabler of batched split rounds
    (learner/batch_grower.py).  Widening channels also fills the MXU's
    sublane dimension (M = 4K instead of 4).  ``leaves``: i32 [K]; invalid
    slots may repeat a leaf (their histograms are simply unused).
    """
    K = leaves.shape[0]
    leaves = jnp.asarray(leaves, jnp.int32)
    lor = jnp.asarray(leaf_of_row, jnp.int32)
    if row_mask is not None:
        lor = jnp.where(row_mask, lor, -1)
    if use_pallas() and _radix_ok(n_bins) and K <= 4:
        # joint (leaf, hi) radix kernel: measured 4.0/5.0/7.5 ms per 1M-row
        # pass at K=1/2/4 vs the flat kernel's K-independent ~9.8
        # (docs/PERF_NOTES.md round 3) — the warmup-round accelerator
        from .hist_pallas import histogram_radix_joint_pallas
        hist = histogram_radix_joint_pallas(
            bins_t, grad, hess, lor, leaves, n_bins=n_bins,
            rows_per_block=min(rows_per_block, 2048),
            compute_dtype=jnp.dtype(hist_dtype).type)
        if axis_name is not None:
            hist = lax.psum(hist, axis_name)
        return hist
    if use_pallas():
        from .hist_pallas import histogram_leaves_pallas
        hist = histogram_leaves_pallas(
            bins_t, grad, hess, lor, leaves, n_bins=n_bins,
            rows_per_block=min(rows_per_block, 1024),
            compute_dtype=jnp.dtype(hist_dtype).type)         # [K, F, B, C]
    else:
        sel = lor[None, :] == leaves[:, None]                 # [K, n]
        m = sel.astype(grad.dtype)
        # where(), not multiply: 0 * NaN = NaN would let one bad excluded
        # row poison the sums (matches the Pallas kernel's masking)
        vals_t = jnp.stack([jnp.where(sel, grad[None, :], 0.0),
                            jnp.where(sel, hess[None, :], 0.0), m,
                            jnp.zeros_like(m)], axis=0)
        C = vals_t.shape[0]
        vals_t = vals_t.reshape(C * K, -1)
        hist = histogram_rows_t(bins_t, vals_t, n_bins=n_bins,
                                rows_per_block=rows_per_block,
                                hist_dtype=hist_dtype)        # [F, B, C*K]
        F, B = hist.shape[0], hist.shape[1]
        hist = hist.reshape(F, B, C, K).transpose(3, 0, 1, 2)  # [K, F, B, C]
    if axis_name is not None:
        hist = lax.psum(hist, axis_name)
    return hist


def _rows_leaves_hist(bins_rows: jax.Array, grad: jax.Array,
                      hess: jax.Array, lor: jax.Array, leaves: jax.Array, *,
                      n_bins: int, rows_per_block: int,
                      hist_dtype: str) -> jax.Array:
    """[K, F, B, C] histograms from row-major bins (backend-dispatched)."""
    if use_pallas():
        from .hist_pallas import histogram_leaves_rows_pallas
        return histogram_leaves_rows_pallas(
            bins_rows, grad, hess, lor, leaves, n_bins=n_bins,
            rows_per_block=min(rows_per_block, 1024),
            compute_dtype=jnp.dtype(hist_dtype).type)
    return histogram_for_leaves_masked(
        jnp.asarray(bins_rows).T, grad, hess, lor, leaves, None,
        n_bins=n_bins, rows_per_block=rows_per_block, hist_dtype=hist_dtype)


def _grouped_layout(cnt: jax.Array, n: int, s_pad: int, blk: int, K: int):
    """Destination-side layout for the leaf-grouped kernel: where each
    padded destination slot reads from in the (rank, row)-sorted order,
    whether it is a real row, and each block's group id.

    Every group owns >= 1 block (its output tile must be written at least
    once) and a whole number of blocks, so consecutive-block accumulation
    in the kernel is exact."""
    pad_cnt = jnp.maximum((cnt + blk - 1) // blk, 1) * blk          # [K]
    P = jnp.concatenate([jnp.zeros(1, jnp.int32),
                         jnp.cumsum(pad_cnt)])[:K].astype(jnp.int32)
    cumc = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(cnt)])[:K].astype(jnp.int32)
    d = jnp.arange(s_pad, dtype=jnp.int32)
    k_of = jnp.sum((d[:, None] >= P[None, :]).astype(jnp.int32),
                   axis=1) - 1                                       # [s_pad]
    k_of = jnp.clip(k_of, 0, K - 1)
    off = d - P[k_of]
    valid = off < cnt[k_of]
    src_pos = jnp.clip(cumc[k_of] + jnp.minimum(
        off, jnp.maximum(cnt[k_of] - 1, 0)), 0, n - 1)
    bg = k_of[::blk]
    return src_pos, valid, bg


def histogram_for_leaves_auto(bins_rows: jax.Array, bins_t: jax.Array,
                              grad: jax.Array, hess: jax.Array,
                              leaf_of_row: jax.Array, leaves: jax.Array,
                              row_mask: Optional[jax.Array] = None, *,
                              n_bins: int = 256, rows_per_block: int = 2048,
                              hist_dtype: str = "float32",
                              axis_name: Optional[str] = None,
                              buckets=(4, 8, 16, 64),
                              grouped: bool = False,
                              counts: Optional[jax.Array] = None,
                              packed_rows: Optional[jax.Array] = None
                              ) -> jax.Array:
    """K-leaf histograms with frontier compaction -> f32 [K, F, B, C].

    The TPU reformulation of the reference's O(smaller-child) histogram cost
    (serial_tree_learner.cpp:364-378 iterates only the leaf's data indices):
    when the rows belonging to ``leaves`` fit a power-of-two bucket, they are
    compacted with a sized ``nonzero`` + contiguous row gather from the
    ROW-major bin matrix and the kernel runs on the bucket; otherwise one
    full masked pass (``histogram_for_leaves_masked``).  Total histogram work
    per tree drops from O(n x rounds) to ~O(n log L), which the flat masked
    pass cannot do.  Exact: the same rows contribute either way.

    ``bins_rows``: u8 [n, F] row-major; ``bins_t``: u8 [F, n] transposed.

    ``counts`` (f32 [K], optional): the caller's known masked row count per
    leaf slot (0 for dummy slots).  It enables the efficient grouped path:
    leaf ranks come from one fused compare-sum over the K slot ids and the
    per-slot count reductions disappear from every round.
    """
    n = grad.shape[0]
    leaves = jnp.asarray(leaves, jnp.int32)
    K = leaves.shape[0]
    lor = jnp.asarray(leaf_of_row, jnp.int32)
    if row_mask is not None:
        lor = jnp.where(row_mask, lor, -1)
    assert n < (1 << 30), "compaction packing needs n < 2^30 rows per shard"
    num_f = bins_rows.shape[1]

    rank_bits = max((K + 1).bit_length(), 1)
    # fall back to the masked/sorted paths (not an error) when the
    # (rank, row) key cannot pack into the i32 sort
    use_grouped = grouped and (use_pallas() or _GROUPED_TEST_INTERPRET) \
        and n < (1 << (30 - rank_bits))
    use_fast_grouped = use_grouped and counts is not None
    if use_fast_grouped:
        cnt = jnp.sum(counts).astype(jnp.int32)
        # fast-path branches never read sel; cheap stand-in keeps the
        # switch operand structure uniform
        sel = lor >= 0
    else:
        eq = lor[None, :] == leaves[:, None]                  # [K, n]
        sel = jnp.any(eq, axis=0)                             # [n]
        cnt = jnp.sum(sel.astype(jnp.int32))

    blk = min(rows_per_block, 2048)
    kblk = min(1024, blk)
    sizes = []
    for d in buckets:
        s = _round_up(max(n // d, 1), blk)
        if s < n and s not in sizes:
            sizes.append(s)

    def full_branch(_):
        return histogram_for_leaves_masked(
            bins_t, grad, hess, lor, leaves, None, n_bins=n_bins,
            rows_per_block=rows_per_block, hist_dtype=hist_dtype)

    def _grouped_hist_call(rows_c, g_c, h_c, vf, bg, kblk_b):
        """Backend-dispatched grouped kernel (radix when bins allow)."""
        if _radix_ok(n_bins):
            from .hist_pallas import histogram_radix_grouped_pallas
            return histogram_radix_grouped_pallas(
                rows_c, g_c, h_c, vf, bg, K, n_bins=n_bins,
                rows_per_block=kblk_b,
                compute_dtype=jnp.dtype(hist_dtype).type,
                interpret=not use_pallas())
        from .hist_pallas import histogram_grouped_pallas
        return histogram_grouped_pallas(
            rows_c, g_c, h_c, vf, bg, K, n_bins=n_bins,
            rows_per_block=kblk_b,
            compute_dtype=jnp.dtype(hist_dtype).type,
            interpret=not use_pallas())

    if use_fast_grouped:
        # Rank of each row among the K leaf slots.  Valid slots hold
        # DISTINCT leaves (the batch grower's children are distinct), so
        # first-match == sum-of-matches; dummy slots (count 0) are remapped
        # to an id no row carries.  XLA fuses the [K, n] compare-multiply
        # into one pass over lor — measured ~6x cheaper than a one-hot
        # table lookup per round (docs/PERF_NOTES.md round 3).
        counts_i = counts.astype(jnp.int32)
        slot = jnp.arange(K, dtype=jnp.int32)
        leaves_eff = jnp.where(counts_i > 0, leaves, -2)
        match = lor[None, :] == leaves_eff[:, None]           # [K, n]
        rank = jnp.sum(jnp.where(match, slot[:, None], 0), axis=0)
        rank = jnp.where(jnp.any(match, axis=0), rank, K)
        row_bits = 30 - rank_bits
        iota_n = lax.iota(jnp.int32, n)
        key = (rank << row_bits) | iota_n
        order_full = jnp.sort(key, stable=False)

    def make_fast_branch(S: int):
        def branch(operands):
            _, grad_, hess_, _ = operands
            if packed_rows is not None:
                # payload built ONCE per tree by the caller (bins/grad/hess
                # never change across rounds)
                packed_ = packed_rows
            else:
                packed_ = jnp.concatenate([
                    bins_rows,
                    lax.bitcast_convert_type(grad_, jnp.uint8),
                    lax.bitcast_convert_type(hess_, jnp.uint8),
                ], axis=1)                                   # [n, F+8]
            order = order_full[:S] & ((1 << row_bits) - 1)   # [S]
            # block size balancing per-group padding (<= S/4 total) against
            # kernel block overhead
            kblk_b = max(128, min(2048, S // max(4 * K, 1) // 128 * 128))
            s_pad = _round_up(S, kblk_b) + K * kblk_b
            src_pos, valid_d, bg = _grouped_layout(
                counts_i, n, s_pad, kblk_b, K)
            src_row = order[jnp.minimum(src_pos, S - 1)]
            pc = packed_[src_row]                            # [s_pad, F+8]
            rows_c = pc[:, :num_f]
            g_c = lax.bitcast_convert_type(
                pc[:, num_f:num_f + 4], jnp.float32)
            h_c = lax.bitcast_convert_type(
                pc[:, num_f + 4:num_f + 8], jnp.float32)
            vf = valid_d.astype(jnp.float32)
            # where(), not multiply: a NaN gradient on a pad-clipped row
            # must not poison sums
            g_c = jnp.where(valid_d, g_c, 0.0)
            h_c = jnp.where(valid_d, h_c, 0.0)
            return _grouped_hist_call(rows_c, g_c, h_c, vf, bg, kblk_b)
        return branch

    def make_branch(S: int):
        if use_fast_grouped:
            return make_fast_branch(S)
        if use_grouped:
            def branch(operands):
                # leaf-GROUPED compaction: sort by (leaf rank, row) so
                # each leaf's rows are contiguous, pad groups to whole
                # kernel blocks, and contract C=3 channels per block into
                # a scalar-prefetch-steered output tile.
                sel_, grad_, hess_, lor_ = operands
                # rank/count work lives INSIDE the branch so full-pass
                # rounds never pay the O(K*n) reductions
                eq_ = lor_[None, :] == leaves[:, None]
                sel_b = jnp.any(eq_, axis=0)
                # first-match rank (duplicate dummy leaves collapse onto
                # the first slot; their unused hist tiles come back zero)
                rank_of_row = jnp.where(
                    sel_b, jnp.argmax(eq_, axis=0).astype(jnp.int32), K)
                cnt_k = jax.vmap(lambda k: jnp.sum(
                    (rank_of_row == k).astype(jnp.int32)))(jnp.arange(K))
                row_bits = 30 - rank_bits
                iota_n = lax.iota(jnp.int32, n)
                key = (rank_of_row << row_bits) | iota_n
                order = jnp.sort(key, stable=False)[:S] \
                    & ((1 << row_bits) - 1)                  # [S]
                packed_ = jnp.concatenate([
                    bins_rows,
                    lax.bitcast_convert_type(grad_, jnp.uint8),
                    lax.bitcast_convert_type(hess_, jnp.uint8),
                ], axis=1)                                   # [n, F+8]
                # whole kernel blocks regardless of the bucket's blk
                # rounding (rows_per_block need not be a kblk multiple)
                s_pad = _round_up(S, kblk) + K * kblk
                src_pos, valid_d, bg = _grouped_layout(
                    cnt_k, n, s_pad, kblk, K)
                src_row = order[jnp.minimum(src_pos, S - 1)]
                pc = packed_[src_row]                        # [s_pad, F+8]
                rows_c = pc[:, :num_f]
                g_c = lax.bitcast_convert_type(
                    pc[:, num_f:num_f + 4], jnp.float32)
                h_c = lax.bitcast_convert_type(
                    pc[:, num_f + 4:num_f + 8], jnp.float32)
                vf = valid_d.astype(jnp.float32)
                # where(), not multiply: a NaN gradient on a pad-clipped
                # row must not poison sums
                g_c = jnp.where(valid_d, g_c, 0.0)
                h_c = jnp.where(valid_d, h_c, 0.0)
                return _grouped_hist_call(rows_c, g_c, h_c, vf, bg, kblk)
            return branch

        def branch(operands):
            sel_, grad_, hess_, lor_ = operands
            # One u8 payload matrix holding (bins row, grad, hess, leaf) so
            # the branch does a SINGLE contiguous row gather — separate
            # gathers are DMA-descriptor bound (~9 ns/row each) and XLA lays
            # an f32 [n, 4] stack out column-major, turning its row gather
            # into lane gathers (docs/PERF_NOTES.md).  Built INSIDE the
            # branch so full-pass rounds skip it and the sort entirely.
            packed_ = jnp.concatenate([
                bins_rows,
                lax.bitcast_convert_type(grad_, jnp.uint8),   # [n, 4]
                lax.bitcast_convert_type(hess_, jnp.uint8),
                lax.bitcast_convert_type(lor_, jnp.uint8),
            ], axis=1)                                        # [n, F+12]
            # frontier indices: pack (selected?, row) into ONE i32 and
            # single-sort — the first ``cnt`` entries are exactly the
            # selected rows in order.  A non-stable single-operand sort
            # costs ~0.4 ms/1M on TPU vs ~1.4 ms for stable argsort and
            # ~9 ms for sized ``nonzero`` (docs/PERF_NOTES.md).
            iota_n = lax.iota(jnp.int32, n)
            idxc = jnp.sort(jnp.where(sel_, iota_n, iota_n | (1 << 30)),
                            stable=False)[:S] & ((1 << 30) - 1)
            valid = lax.iota(jnp.int32, S) < cnt
            pc = packed_[idxc]                                # [S, F+12] u8
            rows_c = pc[:, :num_f]
            grad_c = lax.bitcast_convert_type(
                pc[:, num_f:num_f + 4], jnp.float32)
            hess_c = lax.bitcast_convert_type(
                pc[:, num_f + 4:num_f + 8], jnp.float32)
            lor_g = lax.bitcast_convert_type(
                pc[:, num_f + 8:num_f + 12], jnp.int32)
            lor_c = jnp.where(valid, lor_g, -1)
            return _rows_leaves_hist(rows_c, grad_c, hess_c, lor_c,
                                     leaves, n_bins=n_bins,
                                     rows_per_block=rows_per_block,
                                     hist_dtype=hist_dtype)
        return branch

    branches = [full_branch] + [make_branch(s) for s in sizes]
    j = jnp.int32(0)
    for k, s in enumerate(sizes):  # sizes descending: smallest fit wins
        j = jnp.where(cnt <= s, jnp.int32(k + 1), j)
    hist = lax.switch(j, branches, (sel, grad, hess, lor))
    if axis_name is not None:
        hist = lax.psum(hist, axis_name)
    return hist


def histogram_for_leaf_bucketed(bins: jax.Array, grad: jax.Array,
                                hess: jax.Array, leaf_of_row: jax.Array,
                                leaf: jax.Array, leaf_count: jax.Array,
                                row_mask: Optional[jax.Array] = None, *,
                                n_bins: int = 256, rows_per_block: int = 4096,
                                min_bucket: int = 8192, hist_dtype: str = "float32",
                                axis_name: Optional[str] = None) -> jax.Array:
    """Histogram of one leaf touching only ~leaf_count rows.

    The TPU reformulation of the reference's ordered-index iteration
    (CUDADataPartition keeps rows physically grouped by leaf;
    dense_bin.hpp iterates data_indices): rows stay in place, but the
    leaf's row indices are compacted with a sized ``nonzero`` and gathered
    into the smallest power-of-two buffer that fits (``lax.switch`` over
    log2(n) precompiled bucket sizes), so histogram cost follows the
    smaller child's size instead of the full dataset — preserving the
    O(n log L) total work of leaf-wise growth with histogram subtraction
    (serial_tree_learner.cpp:364-378).

    ``leaf_count`` is the number of rows in ``leaf`` (device scalar).
    """
    n = bins.shape[0]
    mask = (leaf_of_row == leaf)
    if row_mask is not None:
        mask = mask & row_mask

    # bucket sizes n, n/2, n/4, ..., >= min_bucket
    sizes = []
    s = _round_up(n, 128)
    while True:
        sizes.append(s)
        if s <= min_bucket:
            break
        s = _round_up((s + 1) // 2, 128)
    # branch index: largest j with sizes[j] >= count
    count = jnp.maximum(leaf_count.astype(jnp.int32), 1)
    j = jnp.int32(0)
    for k, sz in enumerate(sizes):
        j = jnp.where(count <= sz, jnp.int32(k), j)

    def make_branch(sz: int):
        def branch(operands):
            mask_, grad_, hess_ = operands
            idx = jnp.nonzero(mask_, size=sz, fill_value=n)[0]
            valid = (idx < n).astype(grad_.dtype)
            idxc = jnp.minimum(idx, n - 1)
            b_sub = bins[idxc]
            g_sub = grad_[idxc] * valid
            h_sub = hess_[idxc] * valid
            vals = jnp.stack([g_sub, h_sub, valid, jnp.zeros_like(valid)],
                             axis=1)
            return histogram_rows(b_sub, vals, n_bins=n_bins,
                                  rows_per_block=rows_per_block,
                                  hist_dtype=hist_dtype)
        return branch

    hist = lax.switch(j, [make_branch(sz) for sz in sizes],
                      (mask, grad, hess))
    if axis_name is not None:
        hist = lax.psum(hist, axis_name)
    return hist


def root_histogram(bins_t: jax.Array, grad: jax.Array, hess: jax.Array,
                   row_mask: Optional[jax.Array] = None, *,
                   n_bins: int = 256, rows_per_block: int = 4096,
                   hist_dtype: str = "float32",
                   axis_name: Optional[str] = None) -> jax.Array:
    """Root histogram from the TRANSPOSED [F, n] bin matrix."""
    if use_pallas():
        # single-leaf delegation picks the radix kernel when bins allow
        lor = jnp.zeros(grad.shape, jnp.int32)
        return histogram_for_leaf_masked(
            bins_t, grad, hess, lor, jnp.int32(0), row_mask, n_bins=n_bins,
            rows_per_block=rows_per_block, hist_dtype=hist_dtype,
            axis_name=axis_name)
    m = jnp.ones_like(grad) if row_mask is None else row_mask.astype(grad.dtype)
    vals_t = jnp.stack([jnp.where(m > 0, grad, 0.0),
                        jnp.where(m > 0, hess, 0.0), m,
                        jnp.zeros_like(m)], axis=0)
    hist = histogram_rows_t(bins_t, vals_t, n_bins=n_bins,
                            rows_per_block=rows_per_block,
                            hist_dtype=hist_dtype)
    if axis_name is not None:
        hist = lax.psum(hist, axis_name)
    return hist
