"""Phase timing (reference utils/common.h:973 ``Common::Timer`` /
``FunctionTimer`` — RAII accumulation per named phase, aggregate table
printed at exit when built with USE_TIMETAG).

Here timing is always available and cheap: a global accumulator with a
context manager, enabled per-run via ``Config.verbosity >= 2`` (the CLI
prints the table after training) or programmatically via
``global_timer.enable()``.  Device work is asynchronous under jit, so
phases that end with a host sync (eval, metric reads) absorb queued device
time — same caveat as any wall-clock profile of an async runtime; use
``jax.profiler`` traces for kernel-level attribution.
"""

from __future__ import annotations

import collections
import contextlib
import time
from typing import Dict, Iterator


class PhaseTimer:
    def __init__(self) -> None:
        self._acc: Dict[str, float] = collections.defaultdict(float)
        self._count: Dict[str, int] = collections.defaultdict(int)
        self.enabled = False

    def enable(self) -> None:
        self.enabled = True

    def reset(self) -> None:
        self._acc.clear()
        self._count.clear()

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._acc[name] += time.perf_counter() - t0
            self._count[name] += 1

    def summary(self) -> str:
        if not self._acc:
            return "no phases timed"
        width = max(len(k) for k in self._acc)
        lines = [f"{'phase'.ljust(width)}   total_s     calls   avg_ms"]
        for name, total in sorted(self._acc.items(), key=lambda kv: -kv[1]):
            c = self._count[name]
            lines.append(f"{name.ljust(width)}  {total:8.3f}  {c:8d}  "
                         f"{total / c * 1e3:7.2f}")
        return "\n".join(lines)


#: process-wide accumulator (reference ``global_timer``, gbdt.cpp:22)
global_timer = PhaseTimer()
