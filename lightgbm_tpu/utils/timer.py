"""Phase timing (reference utils/common.h:973 ``Common::Timer`` /
``FunctionTimer`` — RAII accumulation per named phase, aggregate table
printed at exit when built with USE_TIMETAG).

Here timing is always available and cheap: per-phase accumulators with a
context manager.  Two scopes exist since the telemetry round:

  * ``global_timer`` — the process-wide accumulator (reference
    ``global_timer``, gbdt.cpp:22), the CLI default: the CLI prints its
    table after training at ``verbosity >= 2``.
  * per-booster ``PhaseTimer`` instances (``GBDT.timer``) so concurrently
    alive boosters never clobber each other's tables; exposed through
    ``Booster.telemetry()``.

``phase(name, *timers)`` times one region into every ENABLED timer with a
single pair of clock reads, and — when a trace recorder is active
(obs/trace.py, ``trace_output=...``) — emits the same interval as a span
event.  Disabled timers with no active trace cost one tuple scan and an
``is None`` check.

Device work is asynchronous under jit, so phases that end with a host sync
(eval, metric reads) absorb queued device time — same caveat as any
wall-clock profile of an async runtime; use the ``profile_dir`` hook
(``jax.profiler`` traces) for kernel-level attribution.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Dict, Iterator

from ..obs import trace as _trace


@contextlib.contextmanager
def phase(name: str, *timers: "PhaseTimer") -> Iterator[None]:
    """Time one phase into every enabled timer AND the active trace."""
    on = [t for t in timers if t.enabled]
    tracing = _trace.active() is not None
    if not on and not tracing:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        for t in on:
            # the global timer is shared across concurrently training
            # boosters; an unlocked += drops accumulations under threads
            with t._lock:
                t._acc[name] += dt
                t._count[name] += 1
        if tracing:
            _trace.emit_complete(name, t0, dt)


class PhaseTimer:
    def __init__(self) -> None:
        self._acc: Dict[str, float] = collections.defaultdict(float)
        self._count: Dict[str, int] = collections.defaultdict(int)
        self._lock = threading.Lock()
        self.enabled = False

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        self._acc.clear()
        self._count.clear()

    def timer(self, name: str):
        """Context manager timing ``name`` into this accumulator (and the
        active trace, if any)."""
        return phase(name, self)

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        """``{phase: {"total_s", "count", "avg_ms"}}`` — the telemetry
        serialization of the aggregate table."""
        return {name: {"total_s": round(total, 6),
                       "count": self._count[name],
                       "avg_ms": round(total / self._count[name] * 1e3, 4)}
                for name, total in self._acc.items()}

    def summary(self) -> str:
        if not self._acc:
            return "no phases timed"
        width = max(len(k) for k in self._acc)
        lines = [f"{'phase'.ljust(width)}   total_s     calls   avg_ms"]
        for name, total in sorted(self._acc.items(), key=lambda kv: -kv[1]):
            c = self._count[name]
            lines.append(f"{name.ljust(width)}  {total:8.3f}  {c:8d}  "
                         f"{total / c * 1e3:7.2f}")
        return "\n".join(lines)


#: process-wide accumulator (reference ``global_timer``, gbdt.cpp:22)
global_timer = PhaseTimer()
