"""Output-path validation shared by every path-producing config key.

The failure-path contract (docs/OBSERVABILITY.md, docs/ROBUSTNESS.md):
a mistyped or unwritable output path (``trace_output``,
``telemetry_output``, ``checkpoint_dir``, ...) degrades the FEATURE to a
warning emitted before boosting round 1 — it must never surface as a
mid-training crash after hours of work, and it must never take the
trained booster down with it.  This module is the single implementation
of that probe; the per-feature call sites only differ in the key name
they put in the warning.
"""

from __future__ import annotations

import os

from . import log


def writable_file(path: str) -> bool:
    """Can ``path`` be created/appended as a file?"""
    try:
        with open(path, "a"):
            pass
        return True
    except OSError:
        return False


def writable_dir(path: str) -> bool:
    """Can ``path`` be used as a writable directory (created if absent)?"""
    try:
        os.makedirs(path, exist_ok=True)
        probe = os.path.join(path, f".probe_{os.getpid()}")
        with open(probe, "w"):
            pass
        os.remove(probe)
        return True
    except OSError:
        return False


def check_output_path(path: str, *, key: str, kind: str = "file") -> bool:
    """Probe ``path`` and warn (naming the config ``key``) when it is not
    writable.  Returns True when the feature may proceed."""
    ok = writable_dir(path) if kind == "dir" else writable_file(path)
    if not ok:
        log.warning(f"{key}={path!r} is not writable; {key} disabled "
                    "for this run")
    return ok
