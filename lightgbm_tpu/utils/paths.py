"""Output-path validation and the blessed atomic-write idiom.

Two contracts live here:

1. **Output-path probing** (docs/OBSERVABILITY.md, docs/ROBUSTNESS.md):
   a mistyped or unwritable output path (``trace_output``,
   ``telemetry_output``, ``checkpoint_dir``, ...) degrades the FEATURE
   to a warning emitted before boosting round 1 — it must never surface
   as a mid-training crash after hours of work, and it must never take
   the trained booster down with it.  This module is the single
   implementation of that probe; the per-feature call sites only differ
   in the key name they put in the warning.

2. **Crash-safe persistent writes** (docs/STATIC_ANALYSIS.md CRS6xx):
   every manifest/ledger/marker/registry rewrite in the repo flows
   through :func:`write_atomic` — write to a pid-suffixed temp sibling,
   fsync the file, ``os.replace`` into place, then (by default) fsync
   the parent directory so the rename itself is durable.  A reader
   never observes a torn file; a crashed writer leaves only a temp
   husk.  tpulint's CRS601/CRS602 rules recognize exactly this helper
   (by name) as the safe idiom — hand-rolling the temp+rename dance
   elsewhere is a lint finding.
"""

from __future__ import annotations

import os
from typing import Union

from . import log


def fsync_dir(path: str) -> None:
    """Flush a directory entry so a just-renamed file survives power
    loss.  Best-effort: not every filesystem supports fsync on a
    directory fd, and the rename's ATOMICITY never depends on it."""
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


# the keyword-only flag below shadows the function name inside
# write_atomic's scope; alias it so the call still resolves
_dir_fsync = fsync_dir


def write_atomic(path: str, data: Union[str, bytes], *,
                 fsync_dir: bool = True) -> None:
    """Atomically (and durably) replace ``path`` with ``data``.

    The temp sibling embeds the pid so concurrent writers (pytest-xdist
    workers, racing fleet survivors) cannot corrupt each other's
    staging file; the loser of an ``os.replace`` race is simply
    overwritten by the winner, which is the last-write-wins semantics
    every call site already assumes.  ``fsync_dir=False`` skips the
    directory flush for artifacts whose durability across power loss
    does not matter (claims, advisory markers) — the rename is atomic
    either way."""
    mode = "wb" if isinstance(data, bytes) else "w"
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, mode) as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync_dir:
        _dir_fsync(os.path.dirname(path) or ".")


def writable_file(path: str) -> bool:
    """Can ``path`` be created/appended as a file?"""
    try:
        with open(path, "a"):
            pass
        return True
    except OSError:
        return False


def writable_dir(path: str) -> bool:
    """Can ``path`` be used as a writable directory (created if absent)?"""
    try:
        os.makedirs(path, exist_ok=True)
        probe = os.path.join(path, f".probe_{os.getpid()}")
        with open(probe, "w"):
            pass
        os.remove(probe)
        return True
    except OSError:
        return False


def check_output_path(path: str, *, key: str, kind: str = "file") -> bool:
    """Probe ``path`` and warn (naming the config ``key``) when it is not
    writable.  Returns True when the feature may proceed."""
    ok = writable_dir(path) if kind == "dir" else writable_file(path)
    if not ok:
        log.warning(f"{key}={path!r} is not writable; {key} disabled "
                    "for this run")
    return ok
