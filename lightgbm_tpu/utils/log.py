"""Logging facade for lightgbm_tpu.

TPU-native re-design of the reference logger (reference: include/LightGBM/utils/log.h:78
``Log`` with levels Fatal/Warning/Info/Debug and a redirectable callback,
``Log::ResetCallBack`` log.h:97).  We keep the same user surface: four levels, a
process-global verbosity, and a pluggable callback (``register_logger`` in the
reference python package, basic.py:231).
"""

from __future__ import annotations

import sys
from typing import Callable, Optional


class LightGBMError(Exception):
    """Raised where the reference calls ``Log::Fatal`` (utils/log.h:117)."""


_LEVELS = {"fatal": -1, "warning": 0, "info": 1, "debug": 2}
_verbosity = 1  # matches reference config.h `verbosity` default (1 = Info)
_callback: Optional[Callable[[str], None]] = None


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = int(level)


def get_verbosity() -> int:
    return _verbosity


def register_logger(func: Optional[Callable[[str], None]]) -> None:
    """Redirect log output through ``func`` (reference c_api.h:73)."""
    global _callback
    _callback = func


def _emit(msg: str) -> None:
    if _callback is not None:
        _callback(msg)
    else:
        print(msg, file=sys.stderr, flush=True)


def debug(msg: str) -> None:
    if _verbosity >= 2:
        _emit(f"[LightGBM-TPU] [Debug] {msg}")


def info(msg: str) -> None:
    if _verbosity >= 1:
        _emit(f"[LightGBM-TPU] [Info] {msg}")


def warning(msg: str) -> None:
    if _verbosity >= 0:
        _emit(f"[LightGBM-TPU] [Warning] {msg}")


def fatal(msg: str) -> "None":
    raise LightGBMError(msg)
