"""Objective functions (gradient/hessian providers).

TPU-native re-design of the reference objective layer (reference:
include/LightGBM/objective_function.h:19 ``ObjectiveFunction`` — Init /
GetGradients / BoostFromScore / ConvertOutput / RenewTreeOutput; factory
src/objective/objective_function.cpp; CUDA twins src/objective/cuda/ keep
gradients on-device, which is the default here: ``get_gradients`` is jitted
XLA over the score array).

Implemented families (reference files cited per class):
  regression_objective.hpp : l2 (+reg_sqrt), l1, huber, fair, poisson,
                             quantile, mape, gamma, tweedie
  binary_objective.hpp     : binary logloss (sigmoid, is_unbalance,
                             scale_pos_weight)
  multiclass_objective.hpp : softmax (num_class trees/iter), ova
  xentropy_objective.hpp   : cross_entropy, cross_entropy_lambda
  rank_objective.hpp       : lambdarank (pairwise, |dNDCG| weights,
                             truncation, norm), rank_xendcg
``objective=none`` lets callers pass custom grad/hess per iteration
(reference c_api.h:793 LGBM_BoosterUpdateOneIterCustom).

Per-leaf output renewal for l1/quantile/mape (reference RenewTreeOutput
weighted-percentile) runs on host NumPy: it is a once-per-tree O(n log n)
pass whose result is L scalars.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .io.dataset import Metadata
from .obs.metrics import global_metrics
from .ops import compile_cache as cc
from .utils import log


def _weighted_percentile(values: np.ndarray, weights: Optional[np.ndarray],
                         alpha: float) -> float:
    """Weighted alpha-quantile (reference regression_objective.hpp
    PercentileFun/WeightedPercentileFun)."""
    if len(values) == 0:
        return 0.0
    order = np.argsort(values)
    v = values[order]
    if weights is None:
        pos = alpha * (len(v) - 1)
        lo = int(np.floor(pos))
        hi = min(lo + 1, len(v) - 1)
        frac = pos - lo
        return float(v[lo] * (1 - frac) + v[hi] * frac)
    w = weights[order]
    cw = np.cumsum(w)
    target = alpha * cw[-1]
    idx = int(np.searchsorted(cw, target))
    return float(v[min(idx, len(v) - 1)])


class ObjectiveFunction:
    """Base interface (reference objective_function.h:19)."""

    num_model_per_iteration: int = 1
    need_renew_tree_output: bool = False
    is_constant_hessian: bool = False
    need_convert_output: bool = False
    #: get_gradients is a PURE function of the score (no per-call mutable
    #: Python state), so the trainer may wrap it in one jax.jit.  Set
    #: False where a call mutates state (rank_xendcg's RNG split;
    #: lambdarank under position debiasing, whose bias factors update
    #: each iteration).
    jit_safe: bool = True

    def __init__(self, config: Config):
        self.config = config
        self.metadata: Optional[Metadata] = None
        self.num_data = 0
        #: booster-scoped MetricsRegistry, attached by the trainer AFTER
        #: init (GBDT builds its registry after objective.init runs);
        #: compile-cache bumps dual-scope through it when present
        self._metrics = None

    def attach_booster_metrics(self, registry) -> None:
        """Point telemetry at a booster's own registry and mirror any
        gauges the objective computed at init time (the ranking
        objectives publish ``rank_pad_rows`` / ``rank_bucket_count``)."""
        self._metrics = registry
        for gname in ("rank_pad_rows", "rank_bucket_count"):
            val = getattr(self, "_" + gname, None)
            if val is not None:
                registry.set_gauge(gname, val)

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.num_data = num_data
        self._label = jnp.asarray(metadata.label, jnp.float32)
        self._weight = None if metadata.weight is None else \
            jnp.asarray(metadata.weight, jnp.float32)
        # a cached gradient jit traced against the PREVIOUS dataset's
        # labels/weights must not survive re-init (reset_training_data
        # re-runs init on the same objective instance)
        if hasattr(self, "_grad_jit"):
            del self._grad_jit

    def get_gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def jitted_gradients(self, score: jax.Array
                         ) -> Tuple[jax.Array, jax.Array]:
        """``get_gradients`` under ONE ``jax.jit`` (cached per instance)
        when the objective declares itself pure — one device dispatch per
        iteration instead of one per op.  Eager per-op dispatch is ~free
        on a co-located host but costs ~100 ms EACH through a tunneled
        dev chip; lambdarank's ~40-op pairwise graph measured 13 s/iter
        eager vs sub-second jitted at 1M rows.  Falls back to the eager
        call for objectives with per-call mutable state (jit_safe)."""
        if not self.jit_safe:
            return self.get_gradients(score)
        if not hasattr(self, "_grad_jit"):
            self._grad_jit = jax.jit(self.get_gradients)
        return self._grad_jit(score)

    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0

    def convert_output(self, raw: jax.Array) -> jax.Array:
        return raw

    def renew_tree_output(self, score: np.ndarray, residual_fn, leaf_of_row:
                          np.ndarray, num_leaves: int) -> Optional[np.ndarray]:
        return None

    def _apply_weight(self, g, h):
        if self._weight is not None:
            return g * self._weight, h * self._weight
        return g, h

    @property
    def name(self) -> str:
        return type(self).NAME  # type: ignore[attr-defined]


# --------------------------------------------------------------- regression
class RegressionL2Loss(ObjectiveFunction):
    """reference regression_objective.hpp RegressionL2loss."""
    NAME = "regression"
    is_constant_hessian = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.config.reg_sqrt:
            lbl = np.asarray(metadata.label, np.float64)
            self._label = jnp.asarray(np.sign(lbl) * np.sqrt(np.abs(lbl)),
                                      jnp.float32)
        self.need_convert_output = bool(self.config.reg_sqrt)

    def get_gradients(self, score):
        g = score - self._label
        h = jnp.ones_like(score)
        return self._apply_weight(g, h)

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self._label, np.float64)
        w = None if self._weight is None else np.asarray(self._weight, np.float64)
        return float(np.average(lbl, weights=w))

    def convert_output(self, raw):
        if self.config.reg_sqrt:
            return jnp.sign(raw) * raw * raw
        return raw


class RegressionL1Loss(ObjectiveFunction):
    """reference regression_objective.hpp RegressionL1loss — leaf values are
    renewed to the weighted median of residuals."""
    NAME = "regression_l1"
    is_constant_hessian = True
    need_renew_tree_output = True
    _alpha = 0.5

    def get_gradients(self, score):
        g = jnp.sign(score - self._label)
        h = jnp.ones_like(score)
        return self._apply_weight(g, h)

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self._label, np.float64)
        w = None if self._weight is None else np.asarray(self._weight, np.float64)
        return _weighted_percentile(lbl, w, 0.5)

    def renew_tree_output(self, score, residual_fn, leaf_of_row, num_leaves):
        label = np.asarray(self._label, np.float64)
        resid = label - score
        w = None if self._weight is None else np.asarray(self._weight, np.float64)
        out = np.zeros(num_leaves)
        for leaf in range(num_leaves):
            m = leaf_of_row == leaf
            out[leaf] = _weighted_percentile(resid[m],
                                             None if w is None else w[m],
                                             self._alpha)
        return out


class RegressionHuberLoss(ObjectiveFunction):
    """reference regression_objective.hpp RegressionHuberLoss."""
    NAME = "huber"

    def get_gradients(self, score):
        a = self.config.alpha
        r = score - self._label
        g = jnp.where(jnp.abs(r) <= a, r, a * jnp.sign(r))
        h = jnp.ones_like(score)
        return self._apply_weight(g, h)

    boost_from_score = RegressionL2Loss.boost_from_score


class RegressionFairLoss(ObjectiveFunction):
    """reference regression_objective.hpp RegressionFairLoss."""
    NAME = "fair"

    def get_gradients(self, score):
        c = self.config.fair_c
        r = score - self._label
        g = c * r / (jnp.abs(r) + c)
        h = c * c / ((jnp.abs(r) + c) ** 2)
        return self._apply_weight(g, h)


class RegressionPoissonLoss(ObjectiveFunction):
    """reference regression_objective.hpp RegressionPoissonLoss — log link."""
    NAME = "poisson"
    need_convert_output = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if (np.asarray(metadata.label) < 0).any():
            log.fatal("[poisson]: at least one target label is negative")

    def get_gradients(self, score):
        ef = jnp.exp(score)
        g = ef - self._label
        h = jnp.exp(score + self.config.poisson_max_delta_step)
        return self._apply_weight(g, h)

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self._label, np.float64)
        w = None if self._weight is None else np.asarray(self._weight, np.float64)
        return float(np.log(max(np.average(lbl, weights=w), 1e-20)))

    def convert_output(self, raw):
        return jnp.exp(raw)


class RegressionQuantileLoss(ObjectiveFunction):
    """reference regression_objective.hpp RegressionQuantileloss."""
    NAME = "quantile"
    is_constant_hessian = True
    need_renew_tree_output = True

    def get_gradients(self, score):
        a = self.config.alpha
        g = jnp.where(score >= self._label, 1.0 - a, -a)
        h = jnp.ones_like(score)
        return self._apply_weight(g, h)

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self._label, np.float64)
        w = None if self._weight is None else np.asarray(self._weight, np.float64)
        return _weighted_percentile(lbl, w, self.config.alpha)

    def renew_tree_output(self, score, residual_fn, leaf_of_row, num_leaves):
        r = RegressionL1Loss.renew_tree_output
        self._alpha = self.config.alpha
        return r(self, score, residual_fn, leaf_of_row, num_leaves)


class RegressionMAPELoss(ObjectiveFunction):
    """reference regression_objective.hpp RegressionMAPELOSS — L1 with
    1/|label| weights and weighted-median renewal."""
    NAME = "mape"
    is_constant_hessian = True
    need_renew_tree_output = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.abs(np.asarray(metadata.label, np.float64))
        self._label_weight = jnp.asarray(1.0 / np.maximum(1.0, lbl), jnp.float32)

    def get_gradients(self, score):
        g = jnp.sign(score - self._label) * self._label_weight
        h = self._label_weight
        return self._apply_weight(g, h)

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self._label, np.float64)
        lw = np.asarray(self._label_weight, np.float64)
        w = lw if self._weight is None else lw * np.asarray(self._weight, np.float64)
        return _weighted_percentile(lbl, w, 0.5)

    def renew_tree_output(self, score, residual_fn, leaf_of_row, num_leaves):
        label = np.asarray(self._label, np.float64)
        resid = label - score
        lw = np.asarray(self._label_weight, np.float64)
        if self._weight is not None:
            lw = lw * np.asarray(self._weight, np.float64)
        out = np.zeros(num_leaves)
        for leaf in range(num_leaves):
            m = leaf_of_row == leaf
            out[leaf] = _weighted_percentile(resid[m], lw[m], 0.5)
        return out


class RegressionGammaLoss(ObjectiveFunction):
    """reference regression_objective.hpp RegressionGammaLoss — log link."""
    NAME = "gamma"
    need_convert_output = True

    def get_gradients(self, score):
        g = 1.0 - self._label * jnp.exp(-score)
        h = self._label * jnp.exp(-score)
        return self._apply_weight(g, h)

    boost_from_score = RegressionPoissonLoss.boost_from_score
    convert_output = RegressionPoissonLoss.convert_output


class RegressionTweedieLoss(ObjectiveFunction):
    """reference regression_objective.hpp RegressionTweedieLoss — log link."""
    NAME = "tweedie"
    need_convert_output = True

    def get_gradients(self, score):
        rho = self.config.tweedie_variance_power
        g = -self._label * jnp.exp((1.0 - rho) * score) + \
            jnp.exp((2.0 - rho) * score)
        h = -self._label * (1.0 - rho) * jnp.exp((1.0 - rho) * score) + \
            (2.0 - rho) * jnp.exp((2.0 - rho) * score)
        return self._apply_weight(g, h)

    boost_from_score = RegressionPoissonLoss.boost_from_score
    convert_output = RegressionPoissonLoss.convert_output


# ------------------------------------------------------------------- binary
class BinaryLogloss(ObjectiveFunction):
    """reference binary_objective.hpp BinaryLogloss."""
    NAME = "binary"
    need_convert_output = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label)
        if not np.isin(np.unique(lbl), (0, 1)).all():
            log.fatal("Binary objective requires 0/1 labels")
        # label weights (is_unbalance / scale_pos_weight,
        # binary_objective.hpp ctor)
        w = None if metadata.weight is None else np.asarray(metadata.weight)
        cnt_pos = float((lbl == 1).sum() if w is None else w[lbl == 1].sum())
        cnt_neg = float((lbl == 0).sum() if w is None else w[lbl == 0].sum())
        lw_pos, lw_neg = 1.0, 1.0
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                lw_neg = cnt_pos / cnt_neg
            else:
                lw_pos = cnt_neg / cnt_pos
        lw_pos *= self.config.scale_pos_weight
        self._lw_pos, self._lw_neg = lw_pos, lw_neg
        self._cnt_pos, self._cnt_neg = cnt_pos, cnt_neg
        self._sign = jnp.asarray(np.where(lbl == 1, 1.0, -1.0), jnp.float32)

    def get_gradients(self, score):
        s = self.config.sigmoid
        z = self._sign * s * score
        resp = -self._sign * s / (1.0 + jnp.exp(z))
        lw = jnp.where(self._sign > 0, self._lw_pos, self._lw_neg)
        g = resp * lw
        h = jnp.abs(resp) * (s - jnp.abs(resp)) * lw
        return self._apply_weight(g, h)

    def boost_from_score(self, class_id=0):
        s = self.config.sigmoid
        tot = self._cnt_pos * self._lw_pos + self._cnt_neg * self._lw_neg
        if tot <= 0:
            return 0.0
        p = np.clip(self._cnt_pos * self._lw_pos / tot, 1e-15, 1 - 1e-15)
        init = np.log(p / (1.0 - p)) / s
        log.info(f"[binary:BoostFromScore]: pavg={p:.6f} -> initscore={init:.6f}")
        return float(init)

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.config.sigmoid * raw))


# --------------------------------------------------------------- multiclass
class MulticlassSoftmax(ObjectiveFunction):
    """reference multiclass_objective.hpp MulticlassSoftmax — one tree per
    class per iteration; grad = p - y, hess = 2 p (1-p) (factor from ref)."""
    NAME = "multiclass"
    need_convert_output = True

    def __init__(self, config):
        super().__init__(config)
        self.num_model_per_iteration = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label).astype(np.int32)
        k = self.config.num_class
        if lbl.min() < 0 or lbl.max() >= k:
            log.fatal(f"Label must be in [0, {k}) for multiclass")
        self._onehot = jnp.asarray(np.eye(k, dtype=np.float32)[lbl])  # [n, K]

    def get_gradients(self, score):
        # score: [n, K]
        p = jax.nn.softmax(score, axis=1)
        g = p - self._onehot
        h = 2.0 * p * (1.0 - p)
        if self._weight is not None:
            g = g * self._weight[:, None]
            h = h * self._weight[:, None]
        return g, h

    def convert_output(self, raw):
        return jax.nn.softmax(raw, axis=-1)


class MulticlassOVA(ObjectiveFunction):
    """reference multiclass_objective.hpp MulticlassOVA — K independent
    binary-logloss problems."""
    NAME = "multiclassova"
    need_convert_output = True

    def __init__(self, config):
        super().__init__(config)
        self.num_model_per_iteration = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label).astype(np.int32)
        k = self.config.num_class
        self._sign = jnp.asarray(
            np.where(np.eye(k)[lbl] > 0, 1.0, -1.0), jnp.float32)  # [n, K]

    def get_gradients(self, score):
        s = self.config.sigmoid
        z = self._sign * s * score
        resp = -self._sign * s / (1.0 + jnp.exp(z))
        g = resp
        h = jnp.abs(resp) * (s - jnp.abs(resp))
        if self._weight is not None:
            g = g * self._weight[:, None]
            h = h * self._weight[:, None]
        return g, h

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.config.sigmoid * raw))


# ------------------------------------------------------------ cross-entropy
class CrossEntropy(ObjectiveFunction):
    """reference xentropy_objective.hpp CrossEntropy — probabilistic labels
    in [0, 1], logistic link."""
    NAME = "cross_entropy"
    need_convert_output = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label)
        if lbl.min() < 0 or lbl.max() > 1:
            log.fatal("[cross_entropy]: labels must be in [0, 1]")

    def get_gradients(self, score):
        p = jax.nn.sigmoid(score)
        g = p - self._label
        h = p * (1.0 - p)
        return self._apply_weight(g, h)

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self._label, np.float64)
        w = None if self._weight is None else np.asarray(self._weight, np.float64)
        p = np.clip(np.average(lbl, weights=w), 1e-15, 1 - 1e-15)
        return float(np.log(p / (1.0 - p)))

    def convert_output(self, raw):
        return jax.nn.sigmoid(raw)


class CrossEntropyLambda(ObjectiveFunction):
    """reference xentropy_objective.hpp CrossEntropyLambda — alternative
    parameterization with weights entering the link:
    z = log1p(w * exp(f)), p = 1 - exp(-z)."""
    NAME = "cross_entropy_lambda"
    need_convert_output = True

    def get_gradients(self, score):
        # link: p = 1 - exp(-w * softplus(f));
        # L = -y log p + (1-y) w softplus(f)
        # dL/df = w sig(f) (1 - y/p)
        # d2L/df2 = w sig(f)(1-sig(f))(1 - y/p) + w^2 sig(f)^2 y (1-p)/p^2
        y = self._label
        w = jnp.ones_like(score) if self._weight is None else self._weight
        sig = jax.nn.sigmoid(score)
        sp = jax.nn.softplus(score)
        one_m_p = jnp.exp(-w * sp)
        p = jnp.clip(1.0 - one_m_p, 1e-15, 1.0)
        g = w * sig * (1.0 - y / p)
        h = w * sig * (1.0 - sig) * (1.0 - y / p) + \
            (w * sig) ** 2 * y * one_m_p / (p * p)
        h = jnp.maximum(h, 1e-15)
        return g, h

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self._label, np.float64)
        p = max(np.average(lbl), 1e-15)
        return float(np.log(np.expm1(-np.log1p(-min(p, 1 - 1e-15))) + 1e-300))

    def convert_output(self, raw):
        return jnp.log1p(jnp.exp(raw))


# ------------------------------------------------------------------ ranking
def _pad_queries(boundaries: np.ndarray) -> Tuple[np.ndarray, np.ndarray, int]:
    """[nq, Q] doc-index matrix (padded with -1) + per-query counts."""
    sizes = np.diff(boundaries)
    q = int(sizes.max()) if len(sizes) else 1
    nq = len(sizes)
    idx = np.full((nq, q), -1, dtype=np.int32)
    for i in range(nq):
        s, e = boundaries[i], boundaries[i + 1]
        idx[i, :e - s] = np.arange(s, e, dtype=np.int32)
    return idx, sizes.astype(np.int32), q


def _rank_bucket_ladder(sizes: np.ndarray, spec) -> List[int]:
    """Query-length bucket caps, smallest to largest, covering every
    query.  ``spec`` is ``config.rank_query_buckets``: ``"auto"`` derives
    the next-power-of-two set of the observed lengths; an explicit list
    is used as-is (extended with the max length when it falls short).
    The ``LGBMTPU_NO_RANK_BUCKETS=1`` hatch collapses the ladder to one
    pad-to-max bucket — the pre-bucketing geometry, kept as the A/B
    baseline for bench.py and the parity tests."""
    qmax = int(sizes.max()) if len(sizes) else 1
    if os.environ.get("LGBMTPU_NO_RANK_BUCKETS"):
        return [qmax]
    if isinstance(spec, str):           # "auto"
        return sorted({1 << max(int(s) - 1, 0).bit_length() for s in sizes}) \
            or [qmax]
    caps = sorted({int(b) for b in spec})
    if caps[-1] < qmax:
        caps.append(qmax)
    return caps


def _rank_buckets(boundaries: np.ndarray, spec
                  ) -> Tuple[List[Tuple[int, np.ndarray, np.ndarray]], int]:
    """Group queries into length buckets.  Returns
    ``([(cap, query_ids[nq_b], qidx[nq_b, cap])...], pad_rows)`` where
    ``qidx`` is the padded doc-index matrix (-1 pads) of the queries
    assigned to that cap (the smallest cap >= the query's length) and
    ``pad_rows`` counts the padding slots across all buckets — the
    quantity the pad-to-max layout inflates to ``nq*qmax - ndocs``."""
    sizes = np.diff(np.asarray(boundaries)).astype(np.int64)
    caps = _rank_bucket_ladder(sizes, spec)
    assign = np.searchsorted(np.asarray(caps), sizes, side="left")
    out: List[Tuple[int, np.ndarray, np.ndarray]] = []
    pad_rows = 0
    for bi, cap in enumerate(caps):
        qids = np.flatnonzero(assign == bi)
        if not len(qids):
            continue
        idx = np.full((len(qids), cap), -1, np.int32)
        for r, qi in enumerate(qids):
            s, e = int(boundaries[qi]), int(boundaries[qi + 1])
            idx[r, :e - s] = np.arange(s, e, dtype=np.int32)
        pad_rows += int(len(qids) * cap - sizes[qids].sum())
        out.append((int(cap), qids.astype(np.int32), idx))
    return out, pad_rows


def _lambdarank_pair_accum(score, label, gain_doc, qidx, inv_dcg,
                           g_acc, h_acc, *, sigmoid: float, trunc: int,
                           norm: bool):
    """Pairwise |dNDCG| lambda gradients for ONE query-length bucket,
    scattered onto the per-doc accumulators.  Pure and shape-static in
    ``qidx`` ([nq_b, Q] padded with -1): the whole pair tensor is
    [nq_b, T, Q] with T = min(trunc, Q), so a bucket of short queries
    never pays the longest query's Q.  Each doc belongs to exactly one
    bucket, so chaining buckets through (g_acc, h_acc) accumulates
    exactly (the other buckets contribute +0.0 to its slot)."""
    s = sigmoid
    valid = qidx >= 0
    safe = jnp.maximum(qidx, 0)
    sc = jnp.where(valid, score[safe], -jnp.inf)      # [nq_b, Q]
    gains = jnp.where(valid, gain_doc[safe], 0.0)
    lbl = jnp.where(valid, label[safe], -1.0)

    # rank of each doc by descending score (ties by index, like ref sort)
    order = jnp.argsort(-sc, axis=1, stable=True)      # positions -> doc slot
    rank = jnp.argsort(order, axis=1)                  # doc slot -> position

    # -- truncation-aware pair enumeration in SORTED space.  The
    # reference (rank_objective.hpp:138-292) iterates i over sorted
    # positions [0, trunc) and j over (i, cnt): every pair has its
    # higher-scored member inside the truncation level, so the pair set
    # is O(Q * trunc), not O(Q^2).  Materializing [nq, T, Q] instead of
    # [nq, Q, Q] is what makes MS-LTR-scale query lengths (thousands of
    # docs) fit in memory (VERDICT r1 #7).
    Q = sc.shape[1]
    T = int(min(trunc, Q))
    s_srt = jnp.take_along_axis(sc, order, axis=1)      # [nq_b, Q] desc
    g_srt = jnp.take_along_axis(gains, order, axis=1)
    l_srt = jnp.take_along_axis(lbl, order, axis=1)
    v_srt = jnp.take_along_axis(valid, order, axis=1)
    disc = 1.0 / jnp.log2(jnp.arange(Q, dtype=jnp.float32) + 2.0)  # [Q]
    inv = inv_dcg[:, None, None]                         # [nq_b, 1, 1]

    sa = s_srt[:, :T, None]                              # [nq_b, T, 1]
    sb = s_srt[:, None, :]                               # [nq_b, 1, Q]
    ga_ = g_srt[:, :T, None]
    gb_ = g_srt[:, None, :]
    la_ = l_srt[:, :T, None]
    lb_ = l_srt[:, None, :]
    delta = jnp.abs((ga_ - gb_)
                    * (disc[None, :T, None] - disc[None, None, :])) \
        * inv                                            # [nq_b, T, Q]
    # each unordered pair once: position b strictly below position a
    tri = (jnp.arange(Q)[None, None, :]
           > jnp.arange(T)[None, :, None])
    pair_ok = (la_ != lb_) & tri & v_srt[:, :T, None] & v_srt[:, None, :]

    a_better = la_ > lb_
    diff_hl = jnp.where(a_better, sa - sb, sb - sa)      # s_high - s_low
    diff_hl = jnp.clip(diff_hl, -50.0 / s, 50.0 / s)
    rho = 1.0 / (1.0 + jnp.exp(s * diff_hl))
    lam = -s * rho * delta                    # dL/ds for the better doc
    hes = s * s * rho * (1.0 - rho) * delta
    lam = jnp.where(pair_ok, lam, 0.0)
    hes = jnp.where(pair_ok, hes, 0.0)

    # accumulate onto sorted positions: a gets +/-lam per label order,
    # b the negation; hessians add on both ends
    g_a = jnp.where(a_better, lam, -lam)
    g_pos = jnp.zeros_like(s_srt).at[:, :T].add(jnp.sum(g_a, axis=2))
    g_pos = g_pos - jnp.sum(g_a, axis=1)
    h_pos = jnp.zeros_like(s_srt).at[:, :T].add(jnp.sum(hes, axis=2))
    h_pos = h_pos + jnp.sum(hes, axis=1)

    if norm:
        # reference norm_: scale by log2(1 + |sum lambda|) / |sum lambda|
        sum_lam = jnp.sum(jnp.abs(lam), axis=(1, 2))
        nf = jnp.where(sum_lam > 0,
                       jnp.log2(1.0 + sum_lam) / jnp.maximum(sum_lam, 1e-20),
                       1.0)
        g_pos = g_pos * nf[:, None]
        h_pos = h_pos * nf[:, None]

    # sorted positions back to padded doc slots
    g_doc = jnp.take_along_axis(g_pos, rank, axis=1)
    h_doc = jnp.take_along_axis(h_pos, rank, axis=1)

    g_acc = g_acc.at[safe.reshape(-1)].add(
        jnp.where(valid, g_doc, 0.0).reshape(-1))
    h_acc = h_acc.at[safe.reshape(-1)].add(
        jnp.where(valid, h_doc, 0.0).reshape(-1))
    return g_acc, h_acc


def _xendcg_accum(score, label, gumbel, qidx, g_acc, h_acc):
    """XE-NDCG listwise gradients for ONE query-length bucket, scattered
    onto the per-doc accumulators.  ``gumbel`` is the PER-DOC noise
    vector ([n], drawn once per iteration) gathered through ``qidx`` —
    drawing per doc instead of per padded slot makes the perturbed
    targets identical across bucket geometries (bucketed == pad-to-max
    up to reduction order)."""
    valid = qidx >= 0
    safe = jnp.maximum(qidx, 0)
    sc = jnp.where(valid, score[safe], -1e30)
    lbl = jnp.where(valid, label[safe], 0.0)
    # Gumbel-perturbed relevance targets (XE-NDCG-MART, Bruch et al.):
    # phi = max(2^y - 1 + Gumbel(0,1), 0), renormalized per query
    gum = jnp.where(valid, gumbel[safe], 0.0)
    phi = jnp.maximum(jnp.power(2.0, lbl) - 1.0 + gum, 0.0)
    phi = jnp.where(valid, phi, 0.0)
    phi_sum = jnp.sum(phi, axis=1, keepdims=True)
    target = phi / jnp.maximum(phi_sum, 1e-20)
    p = jax.nn.softmax(sc, axis=1)
    p = jnp.where(valid, p, 0.0)
    g_doc = p - target
    h_doc = p * (1.0 - p)
    g_acc = g_acc.at[safe.reshape(-1)].add(
        jnp.where(valid, g_doc, 0.0).reshape(-1))
    h_acc = h_acc.at[safe.reshape(-1)].add(
        jnp.where(valid, jnp.maximum(h_doc, 1e-15), 0.0).reshape(-1))
    return g_acc, h_acc


def _pos_bias_newton(g, h, biases, positions, counts, *, lr: float,
                     reg: float):
    """Functional Newton step on per-position bias factors
    (rank_objective.hpp:295 UpdatePositionBiasFactors): utility
    derivative w.r.t. a position's bias is -sum(lambda) there,
    L2-regularized per instance.  Pure — returns the NEW bias vector so
    the update can live inside the same compiled program as the
    gradients (the carried-array formulation that makes position-debiased
    lambdarank fully device-resident)."""
    first = jnp.zeros_like(biases).at[positions].add(-g)
    second = jnp.zeros_like(biases).at[positions].add(-h)
    first = first - biases * reg * counts
    second = second - reg * counts
    return biases + lr * first / (jnp.abs(second) + 0.001)


class LambdarankNDCG(ObjectiveFunction):
    """reference rank_objective.hpp:138 LambdarankNDCG — pairwise lambda
    gradients weighted by |dNDCG|, truncation at
    ``lambdarank_truncation_level``, optional per-query normalization.

    Queries are grouped into power-of-two LENGTH BUCKETS
    (``rank_query_buckets``, the serving BucketLadder idiom applied to
    training): each bucket runs one batched pairwise kernel at its own
    [nq_b, T, Q_b] geometry, so padded-pair compute is
    sum_b nq_b*T*Q_b instead of the pad-to-max nq*T*qmax — a ~Q_max/Q̄
    win on skewed (MS-LTR-like) query-length distributions.  Bucket
    programs are keyed through ops/compile_cache.py with NO anchors and
    every data array a traced argument (``rank_compile_hits/misses``):
    identical geometry across boosters and iterations re-enters the same
    XLA executable, zero new lowerings.  Position debiasing threads its
    bias factors as explicit carried DEVICE arrays (functional Newton
    update inside the same program); the host ``_pos_biases`` copy is
    kept in sync only for checkpointing and inspection."""
    NAME = "lambdarank"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Lambdarank tasks require query information")
        self._init_rank_buckets(metadata.query_boundaries)
        lbl = np.asarray(metadata.label)
        gains = self.config.label_gain or [float((1 << i) - 1) for i in
                                           range(max(int(lbl.max()) + 1, 31))]
        self._label_gain = np.asarray(gains, np.float64)
        if int(lbl.max()) >= len(self._label_gain):
            log.fatal("label_gain shorter than max label")
        # inverse max DCG per query (rank_objective.hpp:165-177)
        bounds = np.asarray(metadata.query_boundaries)
        nq = len(bounds) - 1
        inv = np.zeros(nq, np.float64)
        trunc = self.config.lambdarank_truncation_level
        for i in range(nq):
            docs = np.arange(int(bounds[i]), int(bounds[i + 1]))
            g = np.sort(self._label_gain[lbl[docs].astype(int)])[::-1][:trunc]
            dcg = np.sum(g / np.log2(np.arange(2, len(g) + 2)))
            inv[i] = 1.0 / dcg if dcg > 0 else 0.0
        # per-bucket device arrays: (cap, qidx [nq_b, cap], inv_dcg [nq_b])
        self._buckets = [(cap, jnp.asarray(idx),
                          jnp.asarray(inv[qids], jnp.float32))
                         for cap, qids, idx in self._buckets_np]
        self._gain_of_doc = jnp.asarray(
            self._label_gain[lbl.astype(int)], jnp.float32)
        # position-debiased LTR (rank_objective.hpp:43-56,295: per-position
        # additive bias factors on the score, Newton-updated each iteration
        # with L2 regularization lambdarank_position_bias_regularization)
        self.jit_safe = True       # re-init may change the position state
        self._positions = None
        if metadata.position is not None:
            pos = np.asarray(metadata.position)
            ids, inv_idx = np.unique(pos, return_inverse=True)
            self._positions = inv_idx.astype(np.int32)
            self._positions_dev = jnp.asarray(self._positions)
            # the device f32 carry is the source of truth; the host f64
            # mirror below exists for checkpointing/inspection only
            self._pos_biases_dev = jnp.zeros(len(ids), jnp.float32)
            self._pos_biases = np.zeros(len(ids), np.float64)
            self._pos_counts_dev = jnp.asarray(
                np.bincount(inv_idx, minlength=len(ids)).astype(np.float32))
            self._pos_reg = float(
                self.config.lambdarank_position_bias_regularization)
            # the per-iteration bias carry keeps this objective off the
            # FUSED round scan (a scan-traced get_gradients would freeze
            # the carry as a constant); jitted_gradients below still runs
            # the whole update as one cached device program
            self.jit_safe = False

    def _init_rank_buckets(self, boundaries) -> None:
        """Build the query-length bucket plan + telemetry gauges (shared
        with RankXENDCG)."""
        bounds = np.asarray(boundaries)
        sizes = np.diff(bounds)
        self._qmax = int(sizes.max()) if len(sizes) else 1
        spec = getattr(self.config, "rank_query_buckets", "auto")
        self._buckets_np, self._rank_pad_rows = _rank_buckets(bounds, spec)
        self._rank_bucket_count = len(self._buckets_np)
        if self._qmax > 2048 and os.environ.get("LGBMTPU_NO_RANK_BUCKETS"):
            log.warning(
                f"Longest query has {self._qmax} docs and query-length "
                f"bucketing is disabled (LGBMTPU_NO_RANK_BUCKETS): the "
                f"pad-to-max pairwise lambda computation is "
                f"O(max_query_len^2) per query — unset the hatch to "
                f"restore the bucketed kernels (rank_query_buckets), or "
                f"lower lambdarank_truncation_level / split queries")
        global_metrics.set_gauge("rank_pad_rows", self._rank_pad_rows)
        global_metrics.set_gauge("rank_bucket_count",
                                 self._rank_bucket_count)
        if self._metrics is not None:
            self._metrics.set_gauge("rank_pad_rows", self._rank_pad_rows)
            self._metrics.set_gauge("rank_bucket_count",
                                    self._rank_bucket_count)

    def _bucket_geoms(self) -> tuple:
        return tuple((int(qidx.shape[0]), cap)
                     for cap, qidx, _ in self._buckets)

    def get_gradients(self, score):
        """Pure traceable composition over the bucket plan — the function
        the fused round scan traces inline (plain lambdarank) and tests
        call eagerly.  Training dispatch goes through jitted_gradients,
        which runs this same arithmetic as one cached program."""
        if self._positions is not None:
            score = score + self._pos_biases_dev[self._positions_dev]
        g = jnp.zeros_like(score)
        h = jnp.zeros_like(score)
        for cap, qidx, inv in self._buckets:
            g, h = _lambdarank_pair_accum(
                score, self._label, self._gain_of_doc, qidx, inv, g, h,
                sigmoid=float(self.config.sigmoid),
                trunc=int(self.config.lambdarank_truncation_level),
                norm=bool(self.config.lambdarank_norm))
        g, h = self._apply_weight(g, h)
        if self._positions is not None and \
                not isinstance(score, jax.core.Tracer):
            self._pos_biases_dev = _pos_bias_newton(
                g, h, self._pos_biases_dev, self._positions_dev,
                self._pos_counts_dev,
                lr=float(self.config.learning_rate), reg=self._pos_reg)
            self._pos_biases = np.asarray(self._pos_biases_dev, np.float64)
        return g, h

    def jitted_gradients(self, score):
        """One compile-cached program per bucket-geometry signature:
        score adjust (position bias), every bucket's pairwise kernel,
        weighting and the functional Newton bias update all lower as a
        SINGLE XLA executable, keyed only by geometry + hyperparameters
        (no anchors; labels/gains/biases are traced arguments), so a
        second booster over identical geometry is a pure
        ``rank_compile_hits`` path — zero new lowerings."""
        pos = self._positions is not None
        has_w = self._weight is not None
        statics = (int(self.num_data), self._bucket_geoms(),
                   float(self.config.sigmoid),
                   int(self.config.lambdarank_truncation_level),
                   bool(self.config.lambdarank_norm), has_w,
                   int(self._pos_biases_dev.shape[0]) if pos else 0,
                   float(self.config.learning_rate) if pos else 0.0,
                   float(self._pos_reg) if pos else 0.0)
        sigmoid, trunc, norm = statics[2], statics[3], statics[4]
        lr, reg = statics[7], statics[8]

        def builder():
            def run(score, label, gain_doc, weight, bias, positions,
                    counts, buckets):
                sc = score + bias[positions] if pos else score
                g = jnp.zeros_like(score)
                h = jnp.zeros_like(score)
                for qidx, inv in buckets:
                    g, h = _lambdarank_pair_accum(
                        sc, label, gain_doc, qidx, inv, g, h,
                        sigmoid=sigmoid, trunc=trunc, norm=norm)
                if has_w:
                    g, h = g * weight, h * weight
                if pos:
                    nb = _pos_bias_newton(g, h, bias, positions, counts,
                                          lr=lr, reg=reg)
                    return g, h, nb
                return g, h
            return jax.jit(run)

        fn = cc.get_or_build(("rank_grad", statics), builder, anchors=(),
                             metrics=self._metrics, counter_ns="rank")
        empty_f = jnp.zeros((0,), jnp.float32)
        empty_i = jnp.zeros((0,), jnp.int32)
        out = fn(score, self._label, self._gain_of_doc,
                 self._weight if has_w else empty_f,
                 self._pos_biases_dev if pos else empty_f,
                 self._positions_dev if pos else empty_i,
                 self._pos_counts_dev if pos else empty_f,
                 tuple((qidx, inv) for _, qidx, inv in self._buckets))
        if pos:
            g, h, nb = out
            self._pos_biases_dev = nb
            self._pos_biases = np.asarray(nb, np.float64)
            return g, h
        return out


class RankXENDCG(LambdarankNDCG):
    """reference rank_objective.hpp:378 RankXENDCG (XE-NDCG-MART, Bruch et
    al.) — listwise cross-entropy with Gumbel-perturbed relevance targets,
    over the same query-length bucket plan as lambdarank (one listwise
    program per bucket geometry; the Gumbel noise is drawn PER DOC so the
    targets do not depend on the bucket ladder)."""
    NAME = "rank_xendcg"
    # each call splits self._rng — per-call mutable HOST state; the split
    # stays on host (and off the fused scan) while the drawn key rides
    # into the cached device program as a traced argument
    jit_safe = False

    def init(self, metadata, num_data):
        ObjectiveFunction.init(self, metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Ranking tasks require query information")
        self._init_rank_buckets(metadata.query_boundaries)
        self._buckets = [(cap, jnp.asarray(idx), None)
                         for cap, qids, idx in self._buckets_np]
        self._positions = None
        self._rng = jax.random.PRNGKey(self.config.objective_seed)
        self._iter = 0

    def get_gradients(self, score):
        self._rng, key = jax.random.split(self._rng)
        gumbel = jax.random.gumbel(key, score.shape)
        g = jnp.zeros_like(score)
        h = jnp.zeros_like(score)
        for cap, qidx, _ in self._buckets:
            g, h = _xendcg_accum(score, self._label, gumbel, qidx, g, h)
        return self._apply_weight(g, h)

    def jitted_gradients(self, score):
        has_w = self._weight is not None
        statics = (int(self.num_data), self._bucket_geoms(), has_w)
        self._rng, key = jax.random.split(self._rng)

        def builder():
            def run(score, label, weight, rkey, buckets):
                gumbel = jax.random.gumbel(rkey, score.shape)
                g = jnp.zeros_like(score)
                h = jnp.zeros_like(score)
                for qidx in buckets:
                    g, h = _xendcg_accum(score, label, gumbel, qidx, g, h)
                if has_w:
                    g, h = g * weight, h * weight
                return g, h
            return jax.jit(run)

        fn = cc.get_or_build(("rank_xendcg", statics), builder, anchors=(),
                             metrics=self._metrics, counter_ns="rank")
        empty_f = jnp.zeros((0,), jnp.float32)
        return fn(score, self._label,
                  self._weight if has_w else empty_f, key,
                  tuple(qidx for _, qidx, _ in self._buckets))


# ------------------------------------------------------------------ factory
_OBJECTIVES = {
    "regression": RegressionL2Loss,
    "regression_l1": RegressionL1Loss,
    "huber": RegressionHuberLoss,
    "fair": RegressionFairLoss,
    "poisson": RegressionPoissonLoss,
    "quantile": RegressionQuantileLoss,
    "mape": RegressionMAPELoss,
    "gamma": RegressionGammaLoss,
    "tweedie": RegressionTweedieLoss,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
    "rank_xendcg": RankXENDCG,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """Factory (reference objective_function.cpp
    ObjectiveFunction::CreateObjectiveFunction)."""
    name = config.objective
    if name == "none":
        return None
    cls = _OBJECTIVES.get(name)
    if cls is None:
        log.fatal(f"Unknown objective type name: {name}")
    return cls(config)
