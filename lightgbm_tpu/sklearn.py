"""scikit-learn estimator API.

TPU-native re-design of the reference sklearn wrappers (reference:
python-package/lightgbm/sklearn.py — ``LGBMModel`` :486, ``LGBMRegressor``
:1285, ``LGBMClassifier`` :1344, ``LGBMRanker`` :1547).  Same constructor
surface and fit/predict semantics, backed by engine.train.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .callback import early_stopping as early_stopping_cb
from .engine import train as _train
from .utils import log


class LGBMModel:
    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None, class_weight=None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state=None, n_jobs: int = -1,
                 importance_type: str = "split", **kwargs: Any):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._n_classes = 1

    _default_objective = "regression"

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type, "num_leaves": self.num_leaves,
            "max_depth": self.max_depth, "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin,
            "objective": self.objective, "class_weight": self.class_weight,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample, "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha, "reg_lambda": self.reg_lambda,
            "random_state": self.random_state, "n_jobs": self.n_jobs,
            "importance_type": self.importance_type,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params: Any) -> "LGBMModel":
        for k, v in params.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self._other_params[k] = v
        return self

    def _train_params(self) -> Dict[str, Any]:
        p = self.get_params()
        p.pop("n_estimators", None)
        p.pop("class_weight", None)
        p.pop("importance_type", None)
        p.pop("n_jobs", None)
        obj = p.pop("objective", None) or self._default_objective
        p["objective"] = obj
        p["boosting"] = p.pop("boosting_type", "gbdt")
        p["num_leaves"] = self.num_leaves
        p["bagging_fraction"] = p.pop("subsample", 1.0)
        p["bagging_freq"] = p.pop("subsample_freq", 0)
        p["feature_fraction"] = p.pop("colsample_bytree", 1.0)
        p["lambda_l1"] = p.pop("reg_alpha", 0.0)
        p["lambda_l2"] = p.pop("reg_lambda", 0.0)
        p["min_gain_to_split"] = p.pop("min_split_gain", 0.0)
        p["min_sum_hessian_in_leaf"] = p.pop("min_child_weight", 1e-3)
        p["min_data_in_leaf"] = p.pop("min_child_samples", 20)
        p["bin_construct_sample_cnt"] = p.pop("subsample_for_bin", 200000)
        if p.pop("random_state", None) is not None:
            p["seed"] = self.random_state
        return {k: v for k, v in p.items() if v is not None}

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_group=None, eval_metric=None, early_stopping_rounds=None,
            feature_name="auto", categorical_feature="auto",
            callbacks=None) -> "LGBMModel":
        params = self._train_params()
        if eval_metric:
            params["metric"] = eval_metric
        if self.class_weight is not None and sample_weight is None:
            sample_weight = self._class_weights_to_sample_weight(y)
        train_ds = Dataset(X, label=y, weight=sample_weight,
                           init_score=init_score, group=group,
                           feature_name=feature_name,
                           categorical_feature=categorical_feature,
                           params={k: v for k, v in params.items()})
        valid_sets: List[Dataset] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                w = eval_sample_weight[i] if eval_sample_weight else None
                g = eval_group[i] if eval_group else None
                valid_sets.append(train_ds.create_valid(vx, label=vy, weight=w,
                                                        group=g))
        callbacks = list(callbacks or [])
        if early_stopping_rounds:
            callbacks.append(early_stopping_cb(early_stopping_rounds))
        self._evals_result: Dict[str, Dict[str, List[float]]] = {}
        if valid_sets:
            from .callback import record_evaluation
            callbacks.append(record_evaluation(self._evals_result))
        self._Booster = _train(params, train_ds,
                               num_boost_round=self.n_estimators,
                               valid_sets=valid_sets, valid_names=eval_names,
                               callbacks=callbacks)
        self._n_features = np.asarray(X).shape[1] if hasattr(X, "shape") else \
            len(X[0])
        return self

    def _class_weights_to_sample_weight(self, y) -> np.ndarray:
        y = np.asarray(y)
        if self.class_weight == "balanced":
            classes, counts = np.unique(y, return_counts=True)
            w = {c: len(y) / (len(classes) * cnt)
                 for c, cnt in zip(classes, counts)}
        else:
            w = dict(self.class_weight)
        return np.asarray([w.get(v, 1.0) for v in y], np.float64)

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs) -> np.ndarray:
        if self._Booster is None:
            raise RuntimeError("Estimator not fitted")
        return self._Booster.predict(X, raw_score=raw_score,
                                     start_iteration=start_iteration,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib)

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise RuntimeError("Estimator not fitted")
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        return self.booster_.best_iteration

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(self.importance_type)

    @property
    def n_features_(self) -> int:
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        """sklearn-convention alias (reference LGBMModel.n_features_in_)."""
        return self._n_features

    @property
    def best_score_(self):
        """reference LGBMModel.best_score_."""
        return dict(self.booster_.best_score)

    @property
    def evals_result_(self):
        """Per-iteration eval history recorded during fit (reference
        LGBMModel.evals_result_; empty when fit ran without eval_set)."""
        if getattr(self, "_evals_result", None) is None:
            raise RuntimeError("Estimator not fitted")
        return self._evals_result

    @property
    def feature_name_(self) -> List[str]:
        return self.booster_.feature_name()

    @property
    def feature_names_in_(self) -> np.ndarray:
        """sklearn-convention array form (reference
        LGBMModel.feature_names_in_)."""
        return np.asarray(self.booster_.feature_name())

    @property
    def n_estimators_(self) -> int:
        """Actual fitted tree rounds (reference LGBMModel.n_estimators_:
        best_iteration when early stopping fired, else all rounds)."""
        return int(self.booster_.best_iteration
                   if self.booster_.best_iteration > 0
                   else self.booster_.current_iteration())
    n_iter_ = n_estimators_

    @property
    def objective_(self) -> str:
        """Resolved objective of the fitted model (reference
        LGBMModel.objective_)."""
        from .config import resolve_objective_alias
        return resolve_objective_alias(
            self.objective or self._default_objective)


class LGBMRegressor(LGBMModel):
    _default_objective = "regression"


class LGBMClassifier(LGBMModel):
    _default_objective = "binary"

    def fit(self, X, y, **kwargs):
        y = np.asarray(y)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        if self._n_classes > 2:
            self.objective = self.objective or "multiclass"
            self._other_params["num_class"] = self._n_classes
        y_enc = np.searchsorted(self._classes, y)
        eval_set = kwargs.get("eval_set")
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            kwargs["eval_set"] = [
                (vx, np.searchsorted(self._classes, np.asarray(vy)))
                for vx, vy in eval_set]
        return super().fit(X, y_enc, **kwargs)

    def predict(self, X, raw_score: bool = False, **kwargs):
        res = super().predict(X, raw_score=raw_score, **kwargs)
        if raw_score or kwargs.get("pred_leaf") or kwargs.get("pred_contrib"):
            return res
        if self._n_classes > 2:
            return self._classes[np.argmax(res, axis=1)]
        return self._classes[(res > 0.5).astype(int)]

    def predict_proba(self, X, **kwargs) -> np.ndarray:
        res = super().predict(X, **kwargs)
        if self._n_classes > 2:
            return res
        return np.stack([1.0 - res, res], axis=1)

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes


class LGBMRanker(LGBMModel):
    _default_objective = "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            log.fatal("Should set group for ranking task")
        return super().fit(X, y, group=group, **kwargs)
