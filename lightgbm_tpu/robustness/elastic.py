"""Elastic multi-chip training: detect a silent worker, evict it,
reshape the mesh over the survivors, resume from the newest checkpoint.

Before this module, a post-startup-barrier worker death was terminal:
the cluster layer (parallel/cluster.py) classifies it "runtime" and
fail-fasts the whole job, and the virtual-mesh tiers had no notion of a
worker dying at all.  For long boosting runs on preemptible capacity
that turns one lost host into a full restart.  This module adds the two
missing layers:

  * **Liveness** — each live worker publishes a per-round heartbeat
    marker (:func:`publish_heartbeat`) on the same shared-file substrate
    as the startup-barrier ready markers: a tiny JSON blob written
    atomically (temp + rename, exactly the checkpoint-manifest idiom) to
    the coordination directory.  A :class:`HeartbeatMonitor` reads them
    back and classifies each rank per round:

        ``healthy``  — its marker for the current round has landed;
        ``suspect``  — lagging, but last seen under ``heartbeat_timeout_s``
                       ago: the monitor WAITS (bounded — see
                       :meth:`HeartbeatMonitor.wait_round`), warns once
                       per (rank, round) and bumps the
                       ``elastic_slow_worker_rounds`` counter.  A slow
                       worker is not a dead worker;
        ``dead``     — silent past ``heartbeat_timeout_s``: evicted.

  * **Mesh-reshape recovery** — on eviction (:class:`WorkerEvicted`)
    with ``elastic=on``, the :class:`ElasticSession` drops the dead
    rank, bumps the coordination epoch (fresh marker namespace — a
    stale heartbeat from a zombie cannot alias into the new incarnation),
    rebuilds the device mesh over the survivor window
    (parallel/mesh.py :func:`~..parallel.mesh.device_window` — the
    booster re-pads and re-shards rows through the exact machinery the
    uneven-rows path always used), and resumes from the newest valid
    checkpoint via ``train(resume="auto")``.  With ``elastic=off`` (the
    default) detection still happens but the job fails fast exactly as
    before this module existed.

Bit-identity contract (asserted by tools/fault_drill.py and
tests/test_elastic.py, explained in docs/ROBUSTNESS.md): under the
deterministic quantized config (``use_quantized_grad=true``,
``stochastic_rounding=false``, ``deterministic=true``) every histogram
sum is exact under any reduction order, so training is mesh-size
invariant — the continued run's model text is bit-for-bit identical to
an uninterrupted run at the reduced mesh size AND to the serial run,
even for the rounds trained before the eviction at the larger mesh.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs.events import emit_event
from ..obs.metrics import count_event
from ..utils import log
from .faults import FaultSpec

def model_core(text: str) -> str:
    """Model text minus the serialized-parameters trailer.

    Bit-identity comparisons across recovery scenarios must ignore the
    params block: the runs being compared *necessarily* differ in
    bookkeeping keys (``checkpoint_dir`` paths, ``tree_learner``,
    ``elastic``) while their trees/structure — the part that determines
    every prediction — must match byte-for-byte."""
    head, sep, rest = text.partition("parameters:")
    if not sep:
        return text
    _, _, tail = rest.partition("end of parameters")
    return head + tail


HEALTHY = "healthy"
SUSPECT = "suspect"
DEAD = "dead"

#: floor/ceiling for the monitor's poll cadence while waiting on a
#: lagging rank — fine enough to time detection, coarse enough to stay
#: off the filesystem's back
_POLL_MIN_S = 0.01
_POLL_MAX_S = 0.25


# ---------------------------------------------------------------------------
# heartbeat markers (liveness layer)
# ---------------------------------------------------------------------------

def heartbeat_path(coord_dir: str, epoch: int, rank: int) -> str:
    """Marker path for ``rank`` in coordination ``epoch``.  The epoch is
    part of the NAME, not the payload: after a reshape the survivors
    rendezvous on a fresh namespace and stale markers from the previous
    incarnation are simply never read."""
    return os.path.join(coord_dir, f"hb_e{int(epoch)}_r{int(rank)}.json")


def publish_heartbeat(coord_dir: str, epoch: int, rank: int,
                      round_idx: int, now: Optional[float] = None) -> str:
    """Atomically publish ``rank``'s heartbeat for ``round_idx``
    (temp + rename, the checkpoint-manifest idiom: a reader never sees a
    half-written marker, a crashed writer leaves only a ``.tmp`` husk)."""
    os.makedirs(coord_dir, exist_ok=True)
    path = heartbeat_path(coord_dir, epoch, rank)
    payload = {"rank": int(rank), "epoch": int(epoch),
               "round": int(round_idx),
               "unix_time": float(time.time() if now is None else now),
               "pid": os.getpid()}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def read_heartbeat(path: str) -> Optional[dict]:
    """Parse a heartbeat marker; ``None`` for missing/torn files (a torn
    read is treated as no-news, never as a crash of the MONITOR)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def age_state(age_s: float, *, interval_s: float, timeout_s: float) -> str:
    """Classify a heartbeat by wall-clock AGE alone (serving-replica
    liveness, serving/fleet.py).  Training liveness is round-anchored —
    :meth:`HeartbeatMonitor.classify` calls a marker carrying the
    expected round healthy regardless of age — but serving replicas beat
    on wall time with no round to anchor on, so state is pure staleness:
    fresh under two beat intervals is HEALTHY (one marker may always be
    in flight), silent past ``timeout_s`` is DEAD (evict + respawn), and
    the band between is SUSPECT — deprioritized by the router, not
    evicted."""
    if age_s >= float(timeout_s):
        return DEAD
    if age_s >= 2.0 * float(interval_s):
        return SUSPECT
    return HEALTHY


@dataclass
class LivenessReport:
    """One classification pass over the live ranks at a given round."""
    round_idx: int
    states: Dict[int, str]
    ages: Dict[int, float]

    @property
    def suspect(self) -> List[int]:
        return [r for r, s in self.states.items() if s == SUSPECT]

    @property
    def dead(self) -> List[int]:
        return [r for r, s in self.states.items() if s == DEAD]

    @property
    def all_healthy(self) -> bool:
        return all(s == HEALTHY for s in self.states.values())


class WorkerEvicted(Exception):
    """Raised by the monitor when rank(s) stay silent past
    ``heartbeat_timeout_s``.  Carries enough for the recovery layer (and
    the drill report) to act without re-reading markers."""

    def __init__(self, ranks: Sequence[int], round_idx: int,
                 detect_s: float):
        self.ranks = sorted(int(r) for r in ranks)
        self.round_idx = int(round_idx)
        self.detect_s = float(detect_s)
        super().__init__(
            f"worker(s) {self.ranks} silent past heartbeat timeout at "
            f"round {self.round_idx} (detected after {self.detect_s:.2f}s)")


class HeartbeatMonitor:
    """Reads the heartbeat markers of one coordination epoch and decides
    healthy / suspect / dead per rank.

    The monitor never blocks unboundedly: :meth:`wait_round` polls at
    most ``heartbeat_timeout_s`` of wall time with an explicit attempt
    cap, after which any rank still lagging has by construction aged
    past the timeout and is classified dead.
    """

    def __init__(self, coord_dir: str, ranks: Sequence[int], *,
                 epoch: int = 0, interval_s: float = 5.0,
                 timeout_s: float = 30.0, metrics=None):
        self.coord_dir = coord_dir
        self.ranks = [int(r) for r in ranks]
        self.epoch = int(epoch)
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.metrics = metrics
        self.slow_rounds = 0          # (rank, round) pairs seen slow
        self._t0 = time.time()        # grace reference: never-published
        self._warned: set = set()     # (rank, round) warned already

    def classify(self, expect_round: int,
                 now: Optional[float] = None) -> LivenessReport:
        """One non-blocking pass: where is every rank relative to
        ``expect_round``?"""
        now = time.time() if now is None else now
        states: Dict[int, str] = {}
        ages: Dict[int, float] = {}
        for r in self.ranks:
            hb = read_heartbeat(heartbeat_path(self.coord_dir,
                                               self.epoch, r))
            last = float(hb["unix_time"]) if hb else self._t0
            age = max(0.0, now - last)
            ages[r] = age
            if hb is not None and int(hb.get("round", -1)) >= expect_round:
                states[r] = HEALTHY
            elif age >= self.timeout_s:
                states[r] = DEAD
            else:
                states[r] = SUSPECT
        return LivenessReport(expect_round, states, ages)

    def _note_slow(self, report: LivenessReport) -> None:
        for r in report.suspect:
            # only count a rank as SLOW once its silence exceeds the
            # publish interval — below that it is merely "not yet
            # arrived this poll", which every rank transits every round
            if report.ages[r] < self.interval_s:
                continue
            key = (r, report.round_idx)
            if key in self._warned:
                continue
            self._warned.add(key)
            self.slow_rounds += 1
            count_event("elastic_slow_worker_rounds", 1, self.metrics)
            emit_event("heartbeat_suspect", rank=r,
                       round_idx=report.round_idx,
                       age_s=round(report.ages[r], 3),
                       timeout_s=self.timeout_s)
            log.warning(
                f"elastic: worker {r} slow at round {report.round_idx} "
                f"(last heartbeat {report.ages[r]:.2f}s ago, timeout "
                f"{self.timeout_s:.1f}s) — waiting, not evicting")

    def wait_round(self, expect_round: int, *,
                   tick: Optional[Callable[[], None]] = None,
                   sleep: Callable[[float], None] = time.sleep
                   ) -> LivenessReport:
        """Block (boundedly) until every rank has published
        ``expect_round`` or someone ages past the timeout.

        ``tick`` is called once per poll — the in-process session uses
        it to service scheduled deferred publishes (stall faults); the
        cluster parent passes the child-process liveness probe.

        Raises :class:`WorkerEvicted` for ranks classified dead.  The
        wait is bounded twice over: a wall-clock deadline of
        ``timeout_s`` past entry plus an explicit attempt cap, so a
        frozen clock cannot spin it forever.
        """
        # the wait's own deadline/elapsed arithmetic runs on the
        # monotonic clock (immune to wall steps); only marker AGING
        # (classify) uses wall time, the one clock all hosts share
        t_enter = time.monotonic()
        poll = min(max(self.interval_s / 10.0, _POLL_MIN_S), _POLL_MAX_S)
        max_attempts = int(self.timeout_s / poll) + 2
        deadline = t_enter + self.timeout_s + poll
        if tick is not None:
            tick()
        report = self.classify(expect_round)
        attempts = 0
        while (not report.all_healthy and not report.dead
               and attempts < max_attempts
               and time.monotonic() < deadline):
            self._note_slow(report)
            sleep(poll)
            attempts += 1
            if tick is not None:
                tick()
            report = self.classify(expect_round)
        if not report.all_healthy and not report.dead:
            # deadline/attempts exhausted with ranks still lagging: by
            # construction they have aged past timeout_s — reclassify so
            # the two bounds agree on the verdict
            report = self.classify(expect_round,
                                   now=time.time() + self.timeout_s)
        if report.dead:
            for r in report.dead:
                emit_event("heartbeat_dead", rank=r,
                           round_idx=expect_round,
                           age_s=round(report.ages.get(r, -1.0), 3),
                           timeout_s=self.timeout_s)
            raise WorkerEvicted(report.dead, expect_round,
                                time.monotonic() - t_enter)
        return report


# ---------------------------------------------------------------------------
# elastic session (mesh-reshape recovery layer)
# ---------------------------------------------------------------------------

@dataclass
class _EvictionRecord:
    ranks: List[int]
    round_idx: int
    detect_s: float
    epoch: int


@dataclass
class ElasticReport:
    """What a session did — the drill (tools/fault_drill.py) serializes
    this into its JSON report."""
    epochs: List[dict] = field(default_factory=list)
    evictions: List[dict] = field(default_factory=list)
    slow_rounds: int = 0
    resumes: int = 0
    final_mesh: int = 0

    def to_dict(self) -> dict:
        return {"epochs": self.epochs, "evictions": self.evictions,
                "slow_rounds": self.slow_rounds, "resumes": self.resumes,
                "final_mesh": self.final_mesh}


class ElasticSession:
    """In-process elastic trainer over the virtual mesh.

    Each live *virtual worker* owns one device slot of the mesh; worker
    ``r``'s liveness is represented by its per-round heartbeat marker.
    The session trains through the ordinary engine
    (``train(resume="auto")`` + checkpoints), with one extra callback
    that (a) publishes every live rank's heartbeat after each round —
    applying any scripted :class:`~.faults.FaultSpec` — and (b) runs the
    monitor's bounded wait.  A dead rank surfaces as
    :class:`WorkerEvicted` aborting the epoch mid-run, exactly where a
    real collective would have hung; recovery then reshapes and resumes.

    This is the layer the bit-identity drills run against.  The real
    multi-process cluster (parallel/cluster.py) reuses the same markers,
    monitor and config keys, but its recovery restarts workers from the
    rank-0 model snapshot rather than the full engine checkpoint — see
    docs/ROBUSTNESS.md for the contract each tier carries.
    """

    def __init__(self, params: dict, X, y, *, num_boost_round: int,
                 n_workers: int, workdir: str,
                 faults: Sequence[FaultSpec] = (),
                 callbacks: Optional[list] = None):
        from ..config import Config
        self.params = dict(params)
        self.params.setdefault("checkpoint_dir",
                               os.path.join(workdir, "ckpt"))
        cfg = Config(dict(self.params))
        self.interval_s = float(cfg.heartbeat_interval_s)
        self.timeout_s = float(cfg.heartbeat_timeout_s)
        self.elastic_on = str(cfg.elastic) == "on"
        # the SESSION owns the observability artifacts, not the inner
        # train() runs: one trace/journal must span every epoch, or the
        # eviction/reshape/resume events emitted BETWEEN epochs would be
        # dropped and each epoch's export would overwrite the last
        self.trace_output = str(getattr(cfg, "trace_output", "") or "")
        self.event_output = str(getattr(cfg, "event_output", "") or "")
        self.X, self.y = X, y
        self.num_boost_round = int(num_boost_round)
        self.n_workers = int(n_workers)
        self.coord_dir = os.path.join(workdir, "coord")
        self.faults = list(faults)
        self.user_callbacks = list(callbacks or [])
        self.report = ElasticReport()
        # stall faults become deferred publishes: (due_time, epoch,
        # rank, round); flushed by the monitor's per-poll tick
        self._deferred: List[Tuple[float, int, int, int]] = []

    # -- fault plan -----------------------------------------------------

    def _publish_or_fault(self, epoch: int, rank: int,
                          round_idx: int) -> None:
        for f in self.faults:
            if f.rank != rank:
                continue
            if f.kind in ("kill", "drop_heartbeats") \
                    and round_idx >= f.at_round:
                return      # silent from at_round on
            if f.kind == "stall" and round_idx == f.at_round:
                self._deferred.append(
                    (time.time() + f.seconds, epoch, rank, round_idx))
                return      # lands late, via _flush_deferred
        publish_heartbeat(self.coord_dir, epoch, rank, round_idx)

    def _flush_deferred(self) -> None:
        now = time.time()
        due = [d for d in self._deferred if d[0] <= now]
        self._deferred = [d for d in self._deferred if d[0] > now]
        for _, epoch, rank, round_idx in due:
            publish_heartbeat(self.coord_dir, epoch, rank, round_idx)

    def _survivors(self, live: List[int], dead: List[int]) -> List[int]:
        out = [r for r in live if r not in set(dead)]
        if not out:
            log.fatal("elastic: every worker evicted — no survivor set "
                      "to reshape onto")
        return out

    # -- per-epoch callback --------------------------------------------

    def _liveness_callback(self, live: List[int],
                           monitor: HeartbeatMonitor) -> Callable:
        epoch = monitor.epoch

        def _callback(env) -> None:
            for r in live:
                self._publish_or_fault(epoch, r, env.iteration)
            monitor.wait_round(env.iteration, tick=self._flush_deferred)
        # after the checkpoint callback (order 40): a kill detected on a
        # checkpoint round must not roll back that round's snapshot
        _callback.order = 60
        return _callback

    # -- the epoch loop -------------------------------------------------

    def train(self):
        """Run to ``num_boost_round`` rounds, reshaping through as many
        evictions as the fault plan (or real silence) produces.  Returns
        the final Booster; ``self.report`` holds the drill telemetry."""
        from ..obs import events as obs_events, trace as obs_trace
        from ..utils.paths import check_output_path
        trace_path = self.trace_output
        if trace_path and obs_trace.active() is None and \
                not check_output_path(trace_path, key="trace_output"):
            trace_path = ""
        event_path = self.event_output
        if event_path and obs_events.active() is None and \
                not check_output_path(event_path, key="event_output"):
            event_path = ""
        recorder = obs_trace.start(trace_path) if trace_path else None
        journal = obs_events.start(event_path) if event_path else None
        try:
            return self._train_epochs()
        finally:
            obs_events.stop(journal)
            try:
                obs_trace.stop(recorder, export_path=trace_path or None)
            except OSError as e:
                obs_trace.stop(recorder)
                log.warning(f"trace export to {trace_path!r} failed "
                            f"({type(e).__name__}: {e}); trace discarded")

    def _train_epochs(self):
        from ..basic import Dataset
        from ..engine import train as _train
        from ..obs import trace as obs_trace
        from ..parallel.mesh import device_window

        live = list(range(self.n_workers))
        epoch = 0
        while True:
            monitor = HeartbeatMonitor(
                self.coord_dir, live, epoch=epoch,
                interval_s=self.interval_s, timeout_s=self.timeout_s)
            cbs = self.user_callbacks + [
                self._liveness_callback(live, monitor)]
            self.report.epochs.append(
                {"epoch": epoch, "mesh": len(live), "ranks": list(live)})
            try:
                # each epoch is a nested scope on the merged timeline:
                # the reshape boundary shows as a span break
                with obs_trace.span("elastic_epoch", epoch=epoch,
                                    mesh=len(live)), \
                        device_window(len(live)):
                    ds = Dataset(self.X, label=self.y)
                    booster = _train(dict(self.params), ds,
                                     num_boost_round=self.num_boost_round,
                                     callbacks=cbs, resume="auto")
                self.report.slow_rounds = monitor.slow_rounds
                self.report.final_mesh = len(live)
                return booster
            except WorkerEvicted as ev:
                self.report.slow_rounds += monitor.slow_rounds
                if not self.elastic_on:
                    # elastic=off: detection exists, recovery does not —
                    # today's fail-fast contract, verbatim
                    log.fatal(
                        f"worker(s) {ev.ranks} lost at round "
                        f"{ev.round_idx} and elastic=off: failing fast "
                        "(set elastic=on to evict and resume)")
                survivors = self._survivors(live, ev.ranks)
                count_event("elastic_evictions", len(ev.ranks))
                count_event("elastic_reshapes", 1)
                count_event("elastic_resumes", 1)
                emit_event("worker_evicted", round_idx=ev.round_idx,
                           ranks=list(ev.ranks), epoch=epoch,
                           detect_s=round(ev.detect_s, 3))
                emit_event("mesh_reshape", round_idx=ev.round_idx,
                           epoch=epoch, mesh_from=len(live),
                           mesh_to=len(survivors))
                emit_event("training_resumed", round_idx=ev.round_idx,
                           epoch=epoch + 1, mesh=len(survivors))
                self.report.evictions.append(
                    {"ranks": ev.ranks, "round": ev.round_idx,
                     "detect_s": round(ev.detect_s, 3), "epoch": epoch})
                self.report.resumes += 1
                log.warning(
                    f"elastic: evicting worker(s) {ev.ranks} (silent at "
                    f"round {ev.round_idx}, detected in "
                    f"{ev.detect_s:.2f}s); reshaping mesh "
                    f"{len(live)}->{len(survivors)} and resuming from "
                    "the newest checkpoint")
                # faults against evicted ranks are spent; survivors keep
                # theirs (a stall can straddle a reshape)
                self.faults = [f for f in self.faults
                               if f.rank in survivors]
                live = survivors
                epoch += 1


def run_elastic_training(params: dict, X, y, *, num_boost_round: int,
                         n_workers: int, workdir: str,
                         faults: Sequence[FaultSpec] = (),
                         callbacks: Optional[list] = None):
    """Convenience wrapper: build an :class:`ElasticSession`, train,
    return ``(booster, report_dict)``."""
    session = ElasticSession(params, X, y,
                             num_boost_round=num_boost_round,
                             n_workers=n_workers, workdir=workdir,
                             faults=faults, callbacks=callbacks)
    booster = session.train()
    return booster, session.report.to_dict()
