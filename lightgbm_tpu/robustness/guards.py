"""Numeric guards: per-round finite checks with a configurable policy.

The reference has no runtime NaN policy — a pathological round (custom
``fobj`` returning inf, a diverging objective, bad label data) silently
poisons the score cache and every later tree.  Here each boosting round
can be checked before growth: one fused ``isfinite``-reduction over the
round's gradients, hessians and the incoming score cache (a single
device scalar, so the guard costs one sync per round — and nothing at
all at the default ``nan_policy=none``).

Policies (``nan_policy`` config key; docs/ROBUSTNESS.md):

  * ``none``  — no checks (default; the fused fast path stays eligible),
  * ``raise`` — fail fast with the offending round number in a
    ``LightGBMError``,
  * ``skip_round`` — log + count the round, grow no trees, continue,
  * ``halt_and_keep_best`` — stop training, keeping every completed
    round (the engine records the last good round as
    ``best_iteration``).

Every trip increments telemetry counters (obs/metrics.py) so a guarded
run's history is visible in ``Booster.telemetry()`` and the JSONL feed.
"""

from __future__ import annotations

from ..utils import log
from ..utils.log import LightGBMError

VALID_NAN_POLICIES = ("none", "raise", "skip_round", "halt_and_keep_best")


class NumericHalt(Exception):
    """Raised by ``nan_policy=halt_and_keep_best`` when a round fails the
    finite check; the engine catches it, keeps every completed round and
    stops training cleanly (never crossing the public API boundary)."""

    def __init__(self, iteration: int):
        super().__init__(f"numeric halt at boosting round {iteration}")
        self.iteration = iteration


def round_is_finite(*arrays) -> bool:
    """True when every given array is all-finite.  One fused device
    reduction — the arrays never cross to the host."""
    import jax.numpy as jnp
    ok = jnp.bool_(True)
    for a in arrays:
        if a is not None:
            ok = ok & jnp.isfinite(a).all()
    return bool(ok)


def enforce_nan_policy(gb, grad, hess) -> bool:
    """Check one round's (grad, hess, score-cache) triplet and apply the
    booster's ``nan_policy``.  Returns True when the round must be
    SKIPPED; raises for the ``raise`` / ``halt_and_keep_best`` policies;
    False when the round is clean (or the policy is ``none``)."""
    policy = getattr(gb, "nan_policy", "none")
    if policy == "none":
        return False
    if round_is_finite(grad, hess, gb.scores):
        return False
    it = gb.iter_
    gb._count("nan_guard_trips")
    from ..obs.events import emit_event
    emit_event("nan_policy_trip", round_idx=it, policy=policy)
    if policy == "raise":
        gb._count("nan_guard_raises")
        raise LightGBMError(
            f"nan_policy=raise: non-finite gradients/hessians/scores at "
            f"boosting round {it}")
    if policy == "skip_round":
        gb._count("nan_rounds_skipped")
        log.warning(f"non-finite gradients/hessians/scores at boosting "
                    f"round {it}; skipping the round "
                    "(nan_policy=skip_round)")
        return True
    gb._count("nan_guard_halts")
    log.warning(f"non-finite gradients/hessians/scores at boosting "
                f"round {it}; halting training and keeping the "
                f"{it} completed round(s) (nan_policy=halt_and_keep_best)")
    raise NumericHalt(it)
