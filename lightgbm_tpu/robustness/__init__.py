"""Fault tolerance: checkpoint/resume, numeric guards, fault injection.

Three pillars (docs/ROBUSTNESS.md):

  * :mod:`.checkpoint` — periodic atomic training checkpoints
    (``checkpoint_dir=`` / ``checkpoint_interval=`` / ``checkpoint_keep=``)
    and exact resume (``train(..., resume="auto")``): manifest + model
    text + score/RNG/eval-history state written via write-to-temp +
    rename, newest-valid-wins discovery that skips corrupt checkpoints
    with a warning,
  * :mod:`.guards` — per-round finite checks on gradients/hessians/
    scores with a ``nan_policy`` config
    (``raise`` | ``skip_round`` | ``halt_and_keep_best``),
  * :mod:`.faults` — the injection harness tests use to kill training
    mid-run, corrupt/truncate checkpoints, poison gradients and script
    worker faults, so the recovery paths above stay verifiable instead
    of theoretical,
  * :mod:`.elastic` — worker liveness (per-round heartbeat markers, a
    bounded-wait monitor distinguishing slow from dead) and elastic
    recovery (``elastic=on``: evict the silent worker, reshape the mesh
    over the survivors, resume from the newest checkpoint — bit-for-bit
    under the deterministic quantized config).

Everything is off by default: without ``checkpoint_dir`` no file is ever
written, and ``nan_policy=none`` adds zero per-iteration work (the guard
is gated before any device sync).
"""

from . import checkpoint, elastic, faults, guards
from .checkpoint import CheckpointManager, load_latest_checkpoint
from .elastic import ElasticSession, HeartbeatMonitor, WorkerEvicted, \
    run_elastic_training
from .guards import NumericHalt

__all__ = ["checkpoint", "guards", "faults", "elastic",
           "CheckpointManager", "load_latest_checkpoint", "NumericHalt",
           "ElasticSession", "HeartbeatMonitor", "WorkerEvicted",
           "run_elastic_training"]
