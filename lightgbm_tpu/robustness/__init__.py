"""Fault tolerance: checkpoint/resume, numeric guards, fault injection.

Three pillars (docs/ROBUSTNESS.md):

  * :mod:`.checkpoint` — periodic atomic training checkpoints
    (``checkpoint_dir=`` / ``checkpoint_interval=`` / ``checkpoint_keep=``)
    and exact resume (``train(..., resume="auto")``): manifest + model
    text + score/RNG/eval-history state written via write-to-temp +
    rename, newest-valid-wins discovery that skips corrupt checkpoints
    with a warning,
  * :mod:`.guards` — per-round finite checks on gradients/hessians/
    scores with a ``nan_policy`` config
    (``raise`` | ``skip_round`` | ``halt_and_keep_best``),
  * :mod:`.faults` — the injection harness tests use to kill training
    mid-run, corrupt/truncate checkpoints and poison gradients, so the
    recovery paths above stay verifiable instead of theoretical.

Everything is off by default: without ``checkpoint_dir`` no file is ever
written, and ``nan_policy=none`` adds zero per-iteration work (the guard
is gated before any device sync).
"""

from . import checkpoint, faults, guards
from .checkpoint import CheckpointManager, load_latest_checkpoint
from .guards import NumericHalt

__all__ = ["checkpoint", "guards", "faults", "CheckpointManager",
           "load_latest_checkpoint", "NumericHalt"]
