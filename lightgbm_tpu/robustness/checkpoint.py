"""Periodic atomic training checkpoints and exact resume.

The reference survives interruption via ``init_model`` continuation on a
saved model file (gbdt_model_text.cpp); that replays the MODEL but loses
the run: eval history, the f32 score caches (recomputed from f64
predictions, which differ by ulps from the incrementally-accumulated
caches), RNG state.  A checkpoint here captures the full training state,
so a resumed run grows bit-for-bit the same trees the uninterrupted run
would have:

``checkpoint_dir/ckpt_<iteration>/``
  * ``model.txt``   — the full model text (all trees, interop format),
  * ``state.npz``   — the f32 train/valid score caches, exactly as they
    sat on device,
  * ``state.json``  — iteration counters, valid-set names, numpy RNG
    states (booster + sampling strategy), the eval history,
  * ``manifest.json`` — byte sizes + sha256 of the files above; written
    last, so a manifest that parses and matches is the definition of a
    valid checkpoint.

Atomicity: everything is written into a dot-temp sibling directory and
``os.replace``-renamed into place, so a crash mid-write leaves a temp
dir (ignored and garbage-collected on the next save), never a
half-valid checkpoint.  Discovery (:func:`load_latest_checkpoint`) walks
checkpoints newest-first and SKIPS invalid ones with a warning instead
of crashing — a truncated newest checkpoint falls back to the previous
valid one.

Retention: the newest ``checkpoint_keep`` checkpoints survive; older
ones are pruned after each successful save.
"""

from __future__ import annotations

import copy
import hashlib
import json
import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils import log
from ..utils.paths import fsync_dir

CKPT_PREFIX = "ckpt_"
MANIFEST_NAME = "manifest.json"
MODEL_NAME = "model.txt"
STATE_NAME = "state.npz"
META_NAME = "state.json"
FORMAT_VERSION = 1


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


# one blessed implementation (utils/paths.py) for the whole repo; the
# old private name survives as an alias for its historical importers
_fsync_dir = fsync_dir


def _write_file(path: str, data) -> None:
    mode = "wb" if isinstance(data, bytes) else "w"
    with open(path, mode) as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def checkpoint_dirs(directory: str) -> List[Tuple[int, str]]:
    """All ``ckpt_*`` entries under ``directory`` as (iteration, path),
    newest first.  Non-conforming names are ignored."""
    out = []
    try:
        entries = os.listdir(directory)
    except OSError:
        return []
    for name in entries:
        if not name.startswith(CKPT_PREFIX):
            continue
        try:
            it = int(name[len(CKPT_PREFIX):])
        except ValueError:
            continue
        path = os.path.join(directory, name)
        if os.path.isdir(path):
            out.append((it, path))
    out.sort(key=lambda t: t[0], reverse=True)
    return out


def validate_checkpoint(path: str) -> Tuple[bool, str]:
    """Integrity check: the manifest parses and every file it names
    exists with the recorded size and sha256.  Returns (ok, reason)."""
    mpath = os.path.join(path, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except OSError as e:
        return False, f"manifest unreadable ({e})"
    except (json.JSONDecodeError, ValueError) as e:
        return False, f"manifest corrupt ({e})"
    if not isinstance(manifest, dict) or "files" not in manifest:
        return False, "manifest missing 'files'"
    try:
        for name, rec in manifest["files"].items():
            fpath = os.path.join(path, name)
            if not os.path.exists(fpath):
                return False, f"{name} missing"
            size = os.path.getsize(fpath)
            if size != int(rec.get("bytes", -1)):
                return False, (f"{name} size mismatch ({size} vs manifest "
                               f"{rec.get('bytes')})")
            if _sha256(fpath) != rec.get("sha256"):
                return False, f"{name} checksum mismatch"
    except (AttributeError, TypeError, ValueError, OSError) as e:
        # JSON-valid but structurally wrong manifest (files as a list,
        # non-numeric sizes, ...) is corruption, not a crash
        return False, f"manifest malformed ({type(e).__name__}: {e})"
    return True, "ok"


def read_manifest(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None


class CheckpointState:
    """A loaded checkpoint, ready to be applied onto a freshly built
    continuation booster (:meth:`restore_into`)."""

    def __init__(self, path: str, iteration: int, model_text: str,
                 scores: Optional[np.ndarray],
                 valid_scores: Dict[str, np.ndarray],
                 rng_state: Optional[dict], strategy_rng_state: Optional[dict],
                 history: Dict[str, Dict[str, List[float]]],
                 stopping_states: Optional[List[dict]] = None,
                 pos_biases: Optional[np.ndarray] = None):
        self.path = path
        self.iteration = iteration
        self.model_text = model_text
        self.scores = scores
        self.valid_scores = valid_scores
        self.rng_state = rng_state
        self.strategy_rng_state = strategy_rng_state
        self.history = history
        self.stopping_states = stopping_states or []
        #: position-debiased lambdarank bias-factor carry (f32) — saved
        #: from the objective's device array so a resumed run continues
        #: the Newton iteration bit-identically
        self.pos_biases = pos_biases

    def restore_into(self, booster, callbacks) -> None:
        """Overwrite the continuation booster's training state with the
        checkpointed one: the f32 score caches exactly as saved (the
        ``init_model`` path recomputes them from f64 predictions, which
        differs by ulps from the incrementally-accumulated caches and
        would break bit-for-bit resume), the RNG states, and the eval
        history of every ``record_evaluation`` callback."""
        import jax.numpy as jnp
        g = booster._gbdt
        k = g.num_tree_per_iteration
        if len(g.models) != self.iteration * k:
            # nan_policy=skip_round advances iter_ without growing trees,
            # so a skipped round makes these differ legitimately
            log.info(f"resume: model carries {len(g.models)} trees at "
                     f"checkpoint iteration {self.iteration} (skipped "
                     "rounds)")
        # iter_ follows the CHECKPOINT, not the tree count: sampling,
        # quantization and feature-mask draws are keyed on iter_, and the
        # engine's remaining-round arithmetic subtracts the checkpoint
        # iteration — a tree-count iter_ would shift every RNG stream
        # one round behind the uninterrupted run after a skipped round
        g.iter_ = self.iteration
        g.num_init_iteration = len(g.models) // k
        # the loaded trees already carry any boost-from-average bias
        # (folded into tree 0 at the original round 0); zero init_scores
        # so score-cache rebuilds never double-count it
        g.init_scores = np.zeros(k)
        train_match = (self.scores is not None
                       and tuple(self.scores.shape)
                       == tuple(g.scores.shape))
        if not train_match:
            # no exact cache (old/partial state, or a different dataset):
            # rebuild every score cache from the merged model — correct
            # (same raw predictions), just not ulp-identical to the
            # incremental accumulation, so bit-for-bit resume is off
            log.warning("resume: checkpointed train score cache is "
                        "missing or shaped "
                        f"{None if self.scores is None else self.scores.shape}"
                        f" vs dataset {tuple(g.scores.shape)}; rebuilding "
                        "score caches from the model")
            g.invalidate_score_cache()
        else:
            g.scores = jnp.asarray(self.scores)
        for vi, name in enumerate(g.valid_names):
            vsc = self.valid_scores.get(name)
            if vsc is not None and tuple(vsc.shape) \
                    == tuple(g.valid_scores[vi].shape):
                g.valid_scores[vi] = jnp.asarray(vsc)
            elif train_match:
                # full rebuild above already fixed the others
                log.warning(f"resume: no usable checkpointed scores for "
                            f"valid set {name!r}; rebuilding them from "
                            "the model")
                g.invalidate_score_cache(only_valid_index=vi)
        if self.rng_state:
            try:
                rng = np.random.default_rng()
                rng.bit_generator.state = self.rng_state
                g._rng = rng
            except (KeyError, ValueError, TypeError) as e:
                log.warning(f"resume: could not restore booster RNG state "
                            f"({e}); reseeding")
        if self.strategy_rng_state and hasattr(g.sample_strategy, "_rng"):
            try:
                rng = np.random.default_rng()
                rng.bit_generator.state = self.strategy_rng_state
                g.sample_strategy._rng = rng
            except (KeyError, ValueError, TypeError) as e:
                log.warning(f"resume: could not restore sampling RNG state "
                            f"({e}); reseeding")
        if self.pos_biases is not None and g.objective is not None and \
                getattr(g.objective, "_positions", None) is not None:
            if len(self.pos_biases) == \
                    len(np.asarray(g.objective._pos_biases_dev)):
                g.objective._pos_biases_dev = jnp.asarray(
                    self.pos_biases, jnp.float32)
                g.objective._pos_biases = np.asarray(
                    self.pos_biases, np.float64)
            else:
                log.warning(
                    f"resume: checkpointed position-bias vector has "
                    f"{len(self.pos_biases)} entries, dataset has "
                    f"{len(np.asarray(g.objective._pos_biases_dev))}; "
                    "bias factors restart from zero")
        for cb in callbacks or []:
            er = getattr(cb, "eval_result", None)
            if isinstance(er, dict):
                er.clear()
                er.update(copy.deepcopy(self.history))
        es_cbs = [cb for cb in callbacks or []
                  if getattr(cb, "stopping_state", None) is not None]
        if len(es_cbs) != len(self.stopping_states) and \
                (es_cbs or self.stopping_states):
            log.warning(f"resume: {len(self.stopping_states)} checkpointed "
                        f"early-stopping state(s) for {len(es_cbs)} "
                        "registered callback(s); unmatched callbacks "
                        "restart their patience at the resume point")
        for cb, saved in zip(es_cbs, self.stopping_states):
            cb.stopping_state.clear()
            cb.stopping_state.update(copy.deepcopy(saved))
            # survive the callback's begin-of-run reset (callback.py)
            cb.stopping_state["resume_ready"] = True
        g._count("checkpoint_resumes")
        log.info(f"resumed from checkpoint {self.path} "
                 f"(iteration {self.iteration})")


def load_latest_checkpoint(directory: str) -> Optional[CheckpointState]:
    """Newest VALID checkpoint under ``directory``, or None.  Invalid or
    partial checkpoints are skipped with a warning, never an error — a
    corrupt newest checkpoint falls back to the previous valid one."""
    from ..obs import count_event
    from ..obs.events import emit_event
    for it, path in checkpoint_dirs(directory):
        ok, reason = validate_checkpoint(path)
        if not ok:
            count_event("checkpoints_skipped_invalid")
            emit_event("checkpoint_corrupt_skipped", path=path,
                       reason=reason)
            log.warning(f"skipping invalid checkpoint {path}: {reason}")
            continue
        try:
            with open(os.path.join(path, MODEL_NAME)) as f:
                model_text = f.read()
            with open(os.path.join(path, META_NAME)) as f:
                meta = json.load(f)
            scores = None
            pos_biases = None
            valid_scores: Dict[str, np.ndarray] = {}
            state_path = os.path.join(path, STATE_NAME)
            if os.path.exists(state_path):
                with np.load(state_path) as z:
                    if "scores" in z:
                        scores = np.asarray(z["scores"])
                    if "pos_biases" in z:
                        pos_biases = np.asarray(z["pos_biases"])
                    for vi, name in enumerate(meta.get("valid_names", [])):
                        key = f"valid_{vi}"
                        if key in z:
                            valid_scores[name] = np.asarray(z[key])
        except (OSError, json.JSONDecodeError, ValueError, KeyError) as e:
            count_event("checkpoints_skipped_invalid")
            emit_event("checkpoint_corrupt_skipped", path=path,
                       reason=str(e))
            log.warning(f"skipping unreadable checkpoint {path}: {e}")
            continue
        return CheckpointState(
            path=path, iteration=int(meta.get("iteration", it)),
            model_text=model_text, scores=scores,
            valid_scores=valid_scores,
            rng_state=meta.get("rng_state"),
            strategy_rng_state=meta.get("strategy_rng_state"),
            history=meta.get("history") or {},
            stopping_states=meta.get("stopping_states") or [],
            pos_biases=pos_biases)
    return None


class CheckpointManager:
    """Writes periodic checkpoints from a training run.

    ``callback()`` returns the engine-registered training callback: it
    accumulates the per-iteration eval history and saves a checkpoint
    every ``interval`` iterations.  The callback is deliberately NOT
    ``fused_safe``: inside a fused chunk the score caches already sit at
    the end-of-chunk state while trees materialize round by round, so a
    mid-chunk snapshot would be inconsistent — checkpointing keeps the
    classic per-round loop.

    A failed save degrades to a warning (training is never taken down by
    its own safety net); the failure is counted in telemetry.
    """

    def __init__(self, directory: str, interval: int = 10, keep: int = 3,
                 history: Optional[Dict[str, Dict[str, List[float]]]] = None,
                 fresh: bool = False):
        self.directory = str(directory)
        self.interval = max(1, int(interval))
        self.keep = max(1, int(keep))
        self.history: Dict[str, Dict[str, List[float]]] = \
            copy.deepcopy(history) if history else {}
        self._warned_save_failure = False
        self.peer_callbacks: List[Callable] = []
        if fresh:
            # this run starts from scratch: leftover checkpoints belong
            # to a PREVIOUS run and would poison both retention (higher
            # iteration numbers outrank this run's) and a later
            # resume='auto' (restoring the old run's model against this
            # run's data) — clear them, loudly
            stale = checkpoint_dirs(self.directory)
            if stale:
                log.warning(
                    f"checkpoint_dir {self.directory!r} holds "
                    f"{len(stale)} checkpoint(s) from a previous run "
                    f"(up to iteration {stale[0][0]}); removing them — "
                    "pass resume='auto' to continue that run, or use a "
                    "fresh directory to keep it")
                for _, path in stale:
                    shutil.rmtree(path, ignore_errors=True)

    # ------------------------------------------------------------ callback
    def callback(self) -> Callable:
        def _callback(env) -> None:
            for item in (env.evaluation_result_list or []):
                name, metric, val = item[0], item[1], item[2]
                self.history.setdefault(name, {}).setdefault(
                    metric, []).append(float(val))
            if (env.iteration + 1) % self.interval == 0:
                self.save(env.model)
        _callback.order = 40
        _callback.checkpoint_manager = self
        return _callback

    # ---------------------------------------------------------------- save
    def save(self, booster) -> Optional[str]:
        """Write one atomic checkpoint of ``booster``; returns its path
        (None when the save failed and was degraded to a warning)."""
        g = booster._gbdt
        it = g.iter_
        final = os.path.join(self.directory, f"{CKPT_PREFIX}{it:07d}")
        tmp = os.path.join(self.directory,
                           f".tmp_{CKPT_PREFIX}{it:07d}_{os.getpid()}")
        try:
            path = self._write(booster, g, it, tmp, final)
        except OSError as e:
            shutil.rmtree(tmp, ignore_errors=True)
            g._count("checkpoint_write_failures")
            if not self._warned_save_failure:
                self._warned_save_failure = True
                log.warning(f"checkpoint save to {final} failed "
                            f"({type(e).__name__}: {e}); training "
                            "continues without this checkpoint")
            return None
        g._count("checkpoints_written")
        from ..obs.events import emit_event
        emit_event("checkpoint_written", round_idx=it, path=path)
        self._prune()
        return path

    def _write(self, booster, g, it: int, tmp: str, final: str) -> str:
        if os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        _write_file(os.path.join(tmp, MODEL_NAME),
                    booster.model_to_string(num_iteration=-1))
        arrays: Dict[str, np.ndarray] = {
            "scores": np.asarray(g.scores, np.float32)}
        for vi in range(len(g.valid_scores)):
            arrays[f"valid_{vi}"] = np.asarray(g.valid_scores[vi],
                                               np.float32)
        # position-debiased lambdarank: the bias-factor carry is training
        # state exactly like the score caches — an f32 device->npz->device
        # round-trip is bit-exact, so a killed run resumes the Newton
        # iteration on the same factors
        if g.objective is not None and \
                getattr(g.objective, "_positions", None) is not None:
            arrays["pos_biases"] = np.asarray(
                g.objective._pos_biases_dev, np.float32)
        state_path = os.path.join(tmp, STATE_NAME)
        with open(state_path, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        meta = {
            "format_version": FORMAT_VERSION,
            "iteration": int(it),
            "num_trees": len(g.models),
            "num_tree_per_iteration": int(g.num_tree_per_iteration),
            "valid_names": list(g.valid_names),
            "rng_state": _rng_state(getattr(g, "_rng", None)),
            "strategy_rng_state": _rng_state(
                getattr(g.sample_strategy, "_rng", None)),
            "history": self.history,
            # early-stopping patience state (callback.py stopping_state),
            # one entry per registered early_stopping callback in order,
            # so a resumed run stops at the same round the uninterrupted
            # one would
            "stopping_states": [
                dict(cb.stopping_state) for cb in self.peer_callbacks
                if getattr(cb, "stopping_state", None) is not None],
        }
        _write_file(os.path.join(tmp, META_NAME), json.dumps(meta))
        files = {}
        for name in (MODEL_NAME, STATE_NAME, META_NAME):
            p = os.path.join(tmp, name)
            files[name] = {"bytes": os.path.getsize(p),
                           "sha256": _sha256(p)}
        manifest = {
            "format_version": FORMAT_VERSION,
            "iteration": int(it),
            "unix_time": round(time.time(), 3),
            "num_trees": len(g.models),
            "files": files,
        }
        _write_file(os.path.join(tmp, MANIFEST_NAME), json.dumps(manifest))
        if os.path.exists(final):
            shutil.rmtree(final, ignore_errors=True)
        os.replace(tmp, final)
        _fsync_dir(self.directory)
        return final

    def save_final(self, booster) -> Optional[str]:
        """Guarantee a checkpoint at the booster's CURRENT iteration:
        saves one unless the newest on-disk checkpoint already is it.
        The continuous-learning pipeline (pipeline/trainer.py) calls
        this through ``train(..., final_checkpoint=True)`` so every
        cycle ends on a durable, resumable boundary even when
        ``checkpoint_interval`` does not divide the cycle length."""
        g = booster._gbdt
        dirs = checkpoint_dirs(self.directory)
        if dirs and int(dirs[0][0]) == int(g.iter_):
            return dirs[0][1]
        return self.save(booster)

    def _prune(self) -> None:
        """Keep the newest ``keep`` checkpoints; drop the rest and any
        orphaned temp dirs from interrupted saves."""
        for it, path in checkpoint_dirs(self.directory)[self.keep:]:
            shutil.rmtree(path, ignore_errors=True)
        try:
            for name in os.listdir(self.directory):
                if name.startswith(f".tmp_{CKPT_PREFIX}"):
                    full = os.path.join(self.directory, name)
                    # another live writer may own a fresh temp dir; only
                    # reap stale ones (>1h old)
                    try:
                        if time.time() - os.path.getmtime(full) > 3600:
                            shutil.rmtree(full, ignore_errors=True)
                    except OSError:
                        pass
        except OSError:
            pass


def _rng_state(rng) -> Optional[dict]:
    if rng is None:
        return None
    try:
        return rng.bit_generator.state
    except AttributeError:
        return None
