"""Fault-injection harness: make recovery paths testable.

A robustness subsystem that is never exercised is theoretical.  This
module provides the three injections the test suite (and any operator
drill) uses against a REAL training run:

  * :func:`kill_training` — a callback that raises :class:`KillTraining`
    once a given iteration completes, before its checkpoint cadence
    fires: the round's work is lost exactly like a preemption between
    checkpoints,
  * :func:`corrupt_checkpoint` — truncate / garbage / delete pieces of
    the newest checkpoint on disk, driving the skip-and-fall-back path,
  * :func:`poison_gradients` — a context manager that patches the
    gradient step to emit NaN/inf at one chosen round, driving the
    ``nan_policy`` guards,
  * :func:`kill_worker` / :func:`stall_worker` / :func:`drop_heartbeats`
    — scripted WORKER faults for the elastic-recovery drills
    (robustness/elastic.py, tools/fault_drill.py): declarative
    :class:`FaultSpec` records an elastic session (or the cluster
    launcher) applies to one virtual/real rank at a chosen round.

Only tests and drills import this module; nothing in the training stack
depends on it.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Callable, Iterator, Optional

from .checkpoint import (CKPT_PREFIX, MANIFEST_NAME, MODEL_NAME, STATE_NAME,
                         checkpoint_dirs)


class KillTraining(Exception):
    """The injected mid-run crash (stands in for preemption/OOM)."""


def kill_training(at_iteration: int) -> Callable:
    """Callback raising :class:`KillTraining` after iteration
    ``at_iteration`` (0-based; absolute, matching the engine's callback
    numbering — resumed runs continue from the checkpoint round)
    completes.  Ordered after the checkpoint callback, so a kill on a
    checkpoint round still persists that round first — like a crash
    landing between rounds."""
    def _callback(env) -> None:
        if env.iteration >= at_iteration:
            raise KillTraining(
                f"injected kill at iteration {env.iteration}")
    _callback.order = 100
    return _callback


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scripted worker fault.

    ``kind`` is ``"kill"`` (the rank dies: heartbeats stop forever and —
    on the real cluster — the process exits), ``"stall"`` (the rank is
    alive but its round-``at_round`` heartbeat lands ``seconds`` late:
    the monitor must warn and WAIT, not evict) or ``"drop_heartbeats"``
    (the rank keeps computing but never publishes again — from the
    monitor's file-level view this is indistinguishable from death, so
    it IS evicted; the drill that asserts this documents the monitor's
    observability boundary).

    Serving-fleet drills (tools/fault_drill.py ``serve_*`` scenarios)
    reuse the same record against replica PROCESSES: ``"kill_replica"``
    (SIGKILL the replica in slot ``rank`` after ``seconds`` of load —
    the router must fail requests over, evict within
    ``fleet_heartbeat_timeout_s``, respawn and re-warm) and
    ``"stall_replica"`` (SIGSTOP for ``seconds`` then SIGCONT: frozen
    heartbeats mark it suspect, requests route around it, and it must
    rejoin WITHOUT being evicted when the stall is under the timeout).
    ``at_round`` is meaningless for serving faults and stays 0.
    """
    kind: str
    rank: int
    at_round: int = 0
    seconds: float = 0.0


def kill_worker(rank: int, at_round: int) -> FaultSpec:
    """The worker at ``rank`` dies at boosting round ``at_round``
    (0-based, absolute): no heartbeat for that round or any later one.
    The elastic monitor must detect it within ``heartbeat_timeout_s``
    and evict; rounds since the newest checkpoint are lost, exactly like
    a preemption."""
    return FaultSpec("kill", int(rank), int(at_round))


def stall_worker(rank: int, seconds: float,
                 at_round: int = 1) -> FaultSpec:
    """The worker at ``rank`` stays ALIVE but publishes its round
    ``at_round`` heartbeat ``seconds`` late (a GC pause, a slow host,
    a congested interconnect).  With ``seconds`` below
    ``heartbeat_timeout_s`` the monitor must classify it *slow* —
    bounded wait + warning + ``elastic_slow_worker_rounds`` — and must
    NOT evict."""
    return FaultSpec("stall", int(rank), int(at_round), float(seconds))


def kill_replica(slot: int, after_s: float = 0.0) -> FaultSpec:
    """The serving replica in ``slot`` is SIGKILLed ``after_s`` seconds
    into the drill's open-loop load window.  The fleet contract under
    this fault: zero failed CLIENT requests (in-flight work on the dead
    replica fails over within its deadline budget), eviction within
    ``fleet_heartbeat_timeout_s``, then respawn -> warm-from-manifest ->
    rejoin — the journal narrates ``replica_dead -> replica_evicted ->
    replica_spawned -> replica_rejoined``."""
    return FaultSpec("kill_replica", int(slot), 0, float(after_s))


def stall_replica(slot: int, seconds: float) -> FaultSpec:
    """The serving replica in ``slot`` freezes (SIGSTOP) for ``seconds``
    then resumes (SIGCONT) — a GC pause or a host hiccup, not a death.
    With ``seconds`` under ``fleet_heartbeat_timeout_s`` the router must
    classify it SUSPECT (deprioritized; its requests fail over), must
    NOT evict, and must route to it again once its heartbeats resume."""
    return FaultSpec("stall_replica", int(slot), 0, float(seconds))


def drop_heartbeats(rank: int, at_round: int = 0) -> FaultSpec:
    """The worker at ``rank`` silently stops publishing heartbeats from
    round ``at_round`` on while still computing.  The monitor cannot
    tell this from death, so the rank is evicted after
    ``heartbeat_timeout_s`` — the drill asserting this pins down what
    the liveness layer can and cannot observe."""
    return FaultSpec("drop_heartbeats", int(rank), int(at_round))


def pipeline_kill_hook(boundary: str, cycle: int) -> Callable[[str, int], None]:
    """A ``ContinuousTrainer`` phase hook that SIGKILLs THIS process the
    moment the pipeline commits ``boundary`` of ``cycle`` (one of
    ``pipeline/cycle.py BOUNDARIES``: ingest / boost / checkpoint /
    export / publish).  A real, uncatchable SIGKILL with no cleanup —
    the strongest crash the cycle manifest's atomic-commit discipline
    must survive.  Used by ``tools/fault_drill.py pipeline_kill`` via
    the ``python -m lightgbm_tpu.pipeline.drill`` child driver."""
    import signal

    def _hook(b: str, c: int) -> None:
        if b == boundary and int(c) == int(cycle):
            os.kill(os.getpid(), signal.SIGKILL)
    return _hook


def sharded_stripe_kill_hook(stripe: int,
                             pass_tag: Optional[str] = None
                             ) -> Callable[[str, int], None]:
    """An ``io/sharded.py`` stripe hook that SIGKILLs THIS process right
    after stripe ``stripe`` of ``pass_tag`` (``p1``/``p2``/``c``; any
    pass when ``None``) is durably committed — the commit file exists
    but nothing downstream of it ran.  Installed as
    ``lightgbm_tpu.io.sharded._stripe_hook`` by the pipeline drill
    child to prove a sharded-ingest cycle resumes exactly-once: the
    committed stripe must NOT be re-read or double-counted on resume."""
    import signal

    def _hook(tag: str, s: int) -> None:
        if int(s) == int(stripe) and (pass_tag is None or tag == pass_tag):
            os.kill(os.getpid(), signal.SIGKILL)
    return _hook


def newest_checkpoint_path(directory: str) -> Optional[str]:
    dirs = checkpoint_dirs(directory)
    return dirs[0][1] if dirs else None


def corrupt_checkpoint(directory: str, mode: str = "truncate_model",
                       path: Optional[str] = None) -> str:
    """Damage the newest checkpoint under ``directory`` (or the given
    ``path``).  Modes:

      * ``truncate_model``   — cut ``model.txt`` to half its bytes,
      * ``garbage_manifest`` — overwrite the manifest with non-JSON,
      * ``missing_state``    — delete ``state.npz``,
      * ``flip_byte``        — flip one byte inside ``model.txt``
        (size-preserving; caught by the sha256 check).

    Returns the damaged checkpoint's path."""
    target = path or newest_checkpoint_path(directory)
    if target is None:
        raise FileNotFoundError(
            f"no {CKPT_PREFIX}* checkpoint under {directory}")
    if mode == "truncate_model":
        mpath = os.path.join(target, MODEL_NAME)
        size = os.path.getsize(mpath)
        with open(mpath, "r+b") as f:
            f.truncate(size // 2)
    elif mode == "garbage_manifest":
        with open(os.path.join(target, MANIFEST_NAME), "w") as f:
            f.write("{not json")
    elif mode == "missing_state":
        os.remove(os.path.join(target, STATE_NAME))
    elif mode == "flip_byte":
        mpath = os.path.join(target, MODEL_NAME)
        with open(mpath, "r+b") as f:
            f.seek(os.path.getsize(mpath) // 2)
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([b[0] ^ 0xFF]))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    return target


@contextlib.contextmanager
def poison_gradients(at_iteration: int, mode: str = "nan") -> Iterator[None]:
    """Patch ``GBDT.boosting_gradients`` so the round at absolute
    iteration ``at_iteration`` emits a non-finite gradient (``mode`` is
    ``nan`` or ``inf``), then restore the original.  The classic loop's
    per-round guard (robustness/guards.py) sees the poisoned values
    exactly as a diverging objective would produce them."""
    import jax.numpy as jnp
    from ..boosting.gbdt import GBDT
    bad = jnp.nan if mode == "nan" else jnp.inf
    orig = GBDT.boosting_gradients

    def patched(self):
        g, h = orig(self)
        if self.iter_ == at_iteration:
            g = g.at[0].set(bad)
        return g, h

    GBDT.boosting_gradients = patched
    try:
        yield
    finally:
        GBDT.boosting_gradients = orig
