"""Evaluation metrics.

TPU-native re-design of the reference metric layer (reference:
include/LightGBM/metric.h:24 ``Metric`` — Init/Eval/factor_to_bigger_better;
factory src/metric/metric.cpp:21-127).  Metrics run once per
``metric_freq`` iterations on host NumPy over the (converted) score array —
they are O(n) or O(n log n) passes whose cost is negligible next to training,
matching the reference where metrics are OpenMP host code even in CUDA mode.

Families (reference files): regression_metric.hpp, binary_metric.hpp,
multiclass_metric.hpp, rank_metric.hpp (+dcg_calculator.cpp), map_metric.hpp,
xentropy_metric.hpp.  Convention preserved: ``Eval`` returns values where
HIGHER ``factor * value`` is better; factor is -1 for losses, +1 for
auc/ndcg/map (metric.h factor_to_bigger_better).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .config import Config
from .io.dataset import Metadata
from .utils import log


class Metric:
    NAME = "none"
    bigger_is_better = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.num_data = num_data
        self.label = np.asarray(metadata.label, np.float64)
        self.weight = None if metadata.weight is None else \
            np.asarray(metadata.weight, np.float64)
        self.sum_weight = float(self.weight.sum()) if self.weight is not None \
            else float(num_data)

    def eval(self, score: np.ndarray, objective=None) -> List[Tuple[str, float]]:
        raise NotImplementedError

    def _avg(self, losses: np.ndarray) -> float:
        if self.weight is not None:
            return float(np.sum(losses * self.weight) / self.sum_weight)
        return float(np.mean(losses))

    def _convert(self, score: np.ndarray, objective) -> np.ndarray:
        if objective is not None and objective.need_convert_output:
            import jax.numpy as jnp
            return np.asarray(objective.convert_output(jnp.asarray(score)))
        return score


# ------------------------------------------------------------- regression
class _PointwiseRegression(Metric):
    def eval(self, score, objective=None):
        pred = self._convert(score, objective)
        return [(self.NAME, self._avg(self._loss(pred, self.label)))]


class L2Metric(_PointwiseRegression):
    NAME = "l2"
    def _loss(self, p, y): return (p - y) ** 2


class RMSEMetric(_PointwiseRegression):
    NAME = "rmse"
    def eval(self, score, objective=None):
        pred = self._convert(score, objective)
        return [(self.NAME, float(np.sqrt(self._avg((pred - self.label) ** 2))))]


class L1Metric(_PointwiseRegression):
    NAME = "l1"
    def _loss(self, p, y): return np.abs(p - y)


class QuantileMetric(_PointwiseRegression):
    NAME = "quantile"
    def _loss(self, p, y):
        a = self.config.alpha
        d = y - p
        return np.where(d >= 0, a * d, (a - 1.0) * d)


class HuberMetric(_PointwiseRegression):
    NAME = "huber"
    def _loss(self, p, y):
        a = self.config.alpha
        d = np.abs(p - y)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseRegression):
    NAME = "fair"
    def _loss(self, p, y):
        c = self.config.fair_c
        x = np.abs(p - y)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseRegression):
    NAME = "poisson"
    def _loss(self, p, y):
        eps = 1e-10
        return p - y * np.log(np.maximum(p, eps))


class MAPEMetric(_PointwiseRegression):
    NAME = "mape"
    def _loss(self, p, y):
        return np.abs((y - p) / np.maximum(1.0, np.abs(y)))


class GammaMetric(_PointwiseRegression):
    NAME = "gamma"
    def _loss(self, p, y):
        eps = 1e-10
        psafe = np.maximum(p, eps)
        return y / psafe + np.log(psafe) - 1.0 - np.log(np.maximum(y, eps))


class GammaDevianceMetric(_PointwiseRegression):
    NAME = "gamma_deviance"
    def _loss(self, p, y):
        eps = 1e-10
        r = y / np.maximum(p, eps)
        return 2.0 * (np.log(np.maximum(1.0 / np.maximum(r, eps), eps)) + r - 1.0)


class TweedieMetric(_PointwiseRegression):
    NAME = "tweedie"
    def _loss(self, p, y):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        psafe = np.maximum(p, eps)
        return -y * np.power(psafe, 1 - rho) / (1 - rho) + \
            np.power(psafe, 2 - rho) / (2 - rho)


# ----------------------------------------------------------------- binary
class BinaryLoglossMetric(Metric):
    NAME = "binary_logloss"

    def eval(self, score, objective=None):
        p = np.clip(self._convert(score, objective), 1e-15, 1 - 1e-15)
        y = self.label
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [(self.NAME, self._avg(loss))]


class BinaryErrorMetric(Metric):
    NAME = "binary_error"

    def eval(self, score, objective=None):
        p = self._convert(score, objective)
        err = (p > 0.5) != (self.label > 0)
        return [(self.NAME, self._avg(err.astype(np.float64)))]


def _weighted_auc(label: np.ndarray, score: np.ndarray,
                  weight: Optional[np.ndarray]) -> float:
    """Rank-based weighted AUC (reference binary_metric.hpp AUCMetric)."""
    if weight is None:
        weight = np.ones_like(label, dtype=np.float64)
    order = np.argsort(score, kind="mergesort")
    y, s, w = label[order], score[order], weight[order]
    pos_w = np.where(y > 0, w, 0.0)
    neg_w = np.where(y > 0, 0.0, w)
    # tie-aware: within tied score groups, credit half the pos x neg mass
    cum_neg = np.cumsum(neg_w)
    total_neg = cum_neg[-1] if len(cum_neg) else 0.0
    total_pos = pos_w.sum()
    if total_pos <= 0 or total_neg <= 0:
        return 1.0
    # group by unique score
    boundary = np.r_[True, s[1:] != s[:-1]]
    gid = np.cumsum(boundary) - 1
    ng = gid[-1] + 1
    gpos = np.bincount(gid, weights=pos_w, minlength=ng)
    gneg = np.bincount(gid, weights=neg_w, minlength=ng)
    neg_before = np.cumsum(gneg) - gneg
    auc = np.sum(gpos * (neg_before + 0.5 * gneg))
    return float(auc / (total_pos * total_neg))


class AUCMetric(Metric):
    NAME = "auc"
    bigger_is_better = True

    def eval(self, score, objective=None):
        return [(self.NAME, _weighted_auc(self.label, score, self.weight))]


class AveragePrecisionMetric(Metric):
    NAME = "average_precision"
    bigger_is_better = True

    def eval(self, score, objective=None):
        w = self.weight if self.weight is not None else \
            np.ones_like(self.label)
        order = np.argsort(-score, kind="mergesort")
        y, ww = self.label[order] > 0, w[order]
        tp = np.cumsum(np.where(y, ww, 0.0))
        fp = np.cumsum(np.where(y, 0.0, ww))
        prec = tp / np.maximum(tp + fp, 1e-20)
        total_pos = tp[-1] if len(tp) else 0.0
        if total_pos <= 0:
            return [(self.NAME, 1.0)]
        rec_delta = np.diff(np.r_[0.0, tp]) / total_pos
        return [(self.NAME, float(np.sum(prec * rec_delta)))]


# ------------------------------------------------------------- multiclass
class MultiLoglossMetric(Metric):
    NAME = "multi_logloss"

    def eval(self, score, objective=None):
        # score: [n, K] raw; convert via softmax/sigmoid per objective
        p = self._convert(score, objective)
        if objective is None or not objective.need_convert_output:
            ex = np.exp(score - score.max(axis=1, keepdims=True))
            p = ex / ex.sum(axis=1, keepdims=True)
        idx = self.label.astype(int)
        p_true = np.clip(p[np.arange(len(idx)), idx], 1e-15, None)
        if getattr(objective, "NAME", "") == "multiclassova":
            p_true = np.clip(p_true / np.maximum(p.sum(axis=1), 1e-15), 1e-15, None)
        return [(self.NAME, self._avg(-np.log(p_true)))]


class MultiErrorMetric(Metric):
    NAME = "multi_error"

    def eval(self, score, objective=None):
        k = self.config.multi_error_top_k
        idx = self.label.astype(int)
        true_score = score[np.arange(len(idx)), idx]
        # error when the true class is not within top-k (reference
        # multiclass_metric.hpp MultiErrorMetric)
        rank = (score > true_score[:, None]).sum(axis=1)
        err = rank >= k
        return [(self.NAME, self._avg(err.astype(np.float64)))]


class AucMuMetric(Metric):
    """Multiclass AUC-mu (reference multiclass_metric.hpp:368 AucMuMetric,
    Kleiman & Page 2019)."""
    NAME = "auc_mu"
    bigger_is_better = True

    def eval(self, score, objective=None):
        y = self.label.astype(int)
        k = self.config.num_class
        wmat = None
        if self.config.auc_mu_weights:
            wmat = np.asarray(self.config.auc_mu_weights, np.float64).reshape(k, k)
        aucs = []
        for a in range(k):
            for b in range(a + 1, k):
                m = (y == a) | (y == b)
                if m.sum() == 0 or (y[m] == a).all() or (y[m] == b).all():
                    continue
                # decision value: difference in class scores, weighted by the
                # partition weights when provided
                if wmat is not None:
                    d = score[m] @ (wmat[a] - wmat[b])
                    d = -d
                else:
                    d = score[m, a] - score[m, b]
                aucs.append(_weighted_auc((y[m] == a).astype(np.float64), d,
                                          None if self.weight is None
                                          else self.weight[m]))
        return [(self.NAME, float(np.mean(aucs)) if aucs else 1.0)]


# ---------------------------------------------------------------- ranking
def _dcg_at_k(labels: np.ndarray, order: np.ndarray, k: int,
              label_gain: np.ndarray) -> float:
    top = order[:k]
    gains = label_gain[labels[top].astype(int)]
    return float(np.sum(gains / np.log2(np.arange(2, len(top) + 2))))


class NDCGMetric(Metric):
    """reference rank_metric.hpp NDCGMetric + dcg_calculator.cpp."""
    NAME = "ndcg"
    bigger_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("NDCG metric requires query information")
        self.bounds = metadata.query_boundaries
        mx = int(self.label.max()) + 1 if len(self.label) else 1
        gains = self.config.label_gain or [float((1 << i) - 1)
                                           for i in range(max(mx, 31))]
        self.label_gain = np.asarray(gains, np.float64)
        self.ks = list(self.config.eval_at)

    def eval(self, score, objective=None):
        res = {k: [] for k in self.ks}
        qw = []
        for qi in range(len(self.bounds) - 1):
            s, e = self.bounds[qi], self.bounds[qi + 1]
            lbl = self.label[s:e]
            sc = score[s:e]
            order = np.argsort(-sc, kind="mergesort")
            ideal = np.argsort(-lbl, kind="mergesort")
            qw.append(1.0)
            for k in self.ks:
                idcg = _dcg_at_k(lbl, ideal, k, self.label_gain)
                if idcg <= 0:
                    res[k].append(1.0)
                else:
                    res[k].append(_dcg_at_k(lbl, order, k, self.label_gain) / idcg)
        return [(f"ndcg@{k}", float(np.average(res[k], weights=qw)))
                for k in self.ks]


class MapMetric(Metric):
    """reference map_metric.hpp MapMetric."""
    NAME = "map"
    bigger_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("MAP metric requires query information")
        self.bounds = metadata.query_boundaries
        self.ks = list(self.config.eval_at)

    def eval(self, score, objective=None):
        res = {k: [] for k in self.ks}
        for qi in range(len(self.bounds) - 1):
            s, e = self.bounds[qi], self.bounds[qi + 1]
            rel = (self.label[s:e] > 0).astype(np.float64)
            order = np.argsort(-score[s:e], kind="mergesort")
            rel_sorted = rel[order]
            hits = np.cumsum(rel_sorted)
            prec = hits / np.arange(1, len(rel_sorted) + 1)
            for k in self.ks:
                topk = slice(0, k)
                denom = min(k, int(rel.sum())) or 1
                ap = np.sum(prec[topk] * rel_sorted[topk]) / denom
                res[k].append(ap if rel.sum() > 0 else 1.0)
        return [(f"map@{k}", float(np.mean(res[k]))) for k in self.ks]


# --------------------------------------------------------------- xentropy
class CrossEntropyMetric(Metric):
    NAME = "cross_entropy"

    def eval(self, score, objective=None):
        p = np.clip(self._convert(score, objective), 1e-15, 1 - 1e-15)
        y = self.label
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [(self.NAME, self._avg(loss))]


class CrossEntropyLambdaMetric(Metric):
    NAME = "cross_entropy_lambda"

    def eval(self, score, objective=None):
        # p through the lambda link (see objectives.CrossEntropyLambda)
        w = self.weight if self.weight is not None else 1.0
        sp = np.logaddexp(0.0, score)
        p = np.clip(1.0 - np.exp(-w * sp), 1e-15, 1 - 1e-15)
        y = self.label
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [(self.NAME, float(np.mean(loss)))]


class KLDivergenceMetric(Metric):
    NAME = "kullback_leibler"

    def eval(self, score, objective=None):
        p = np.clip(self._convert(score, objective), 1e-15, 1 - 1e-15)
        y = np.clip(self.label, 1e-15, 1 - 1e-15)
        kl = y * np.log(y / p) + (1 - y) * np.log((1 - y) / (1 - p))
        return [(self.NAME, self._avg(kl))]


_METRICS = {
    "l1": L1Metric, "l2": L2Metric, "rmse": RMSEMetric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "mape": MAPEMetric, "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "average_precision": AveragePrecisionMetric,
    "multi_logloss": MultiLoglossMetric, "multi_error": MultiErrorMetric,
    "auc_mu": AucMuMetric,
    "ndcg": NDCGMetric, "map": MapMetric,
    "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KLDivergenceMetric,
}

_DEFAULT_METRIC_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "quantile": "quantile", "mape": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary_logloss",
    "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
    "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "lambdarank": "ndcg", "rank_xendcg": "ndcg",
}


def create_metrics(config: Config) -> List[Metric]:
    """Factory (reference metric.cpp:21-127): explicit list, or the
    objective's default metric when none requested."""
    names: Sequence[str] = config.metric
    if not names:
        default = _DEFAULT_METRIC_FOR_OBJECTIVE.get(config.objective)
        names = [default] if default else []
    out: List[Metric] = []
    for nm in names:
        if nm in ("none", ""):
            continue
        cls = _METRICS.get(nm)
        if cls is None:
            log.warning(f"Unknown metric: {nm}")
            continue
        out.append(cls(config))
    return out
