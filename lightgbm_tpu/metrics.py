"""Evaluation metrics.

TPU-native re-design of the reference metric layer (reference:
include/LightGBM/metric.h:24 ``Metric`` — Init/Eval/factor_to_bigger_better;
factory src/metric/metric.cpp:21-127).

Two evaluation paths:

- **Device** (``eval_device``): the big metrics (pointwise regression
  family, binary logloss/error, auc, ndcg) evaluate as jitted reductions on
  the default jax backend, so per-iteration eval moves only SCALARS across
  the device boundary instead of the full score array (the reference's CUDA
  metrics, src/metric/cuda/cuda_pointwise_metric.cu, reduce on device for
  the same reason).  f32 arithmetic; falls back to host automatically for
  unsupported configurations.
- **Host** (``eval``): float64 NumPy — exact, used for multiclass/xentropy/
  map and whenever ``deterministic=true`` pins bit-reproducible eval.

Families (reference files): regression_metric.hpp, binary_metric.hpp,
multiclass_metric.hpp, rank_metric.hpp (+dcg_calculator.cpp), map_metric.hpp,
xentropy_metric.hpp.  Convention preserved: ``Eval`` returns values where
HIGHER ``factor * value`` is better; factor is -1 for losses, +1 for
auc/ndcg/map (metric.h factor_to_bigger_better).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .config import Config
from .io.dataset import Metadata
from .utils import log


# --------------------------------------------------- jitted device kernels
# one compiled program per (metric, n) — reused every iteration

@functools.lru_cache(maxsize=None)
def _dev_pointwise(kind: str):
    import jax
    import jax.numpy as jnp

    def run(p, y, w, sw):
        if kind == "l2" or kind == "rmse":
            loss = (p - y) ** 2
        elif kind == "l1":
            loss = jnp.abs(p - y)
        elif kind == "binary_logloss":
            # f32-safe clip: 1 - 1e-15 is not representable in float32 (the
            # host path clips at 1e-15 in f64)
            pc = jnp.clip(p, 1e-7, 1 - 1e-7)
            loss = -(y * jnp.log(pc) + (1 - y) * jnp.log(1 - pc))
        elif kind == "binary_error":
            loss = ((p > 0.5) != (y > 0)).astype(jnp.float32)
        else:  # pragma: no cover
            raise ValueError(kind)
        avg = jnp.mean(loss) if w is None else jnp.sum(loss * w) / sw
        return jnp.sqrt(avg) if kind == "rmse" else avg
    return jax.jit(run, static_argnames=())


@functools.lru_cache(maxsize=None)
def _dev_auc():
    import jax
    import jax.numpy as jnp

    def run(score, y, w):
        n = score.shape[0]
        order = jnp.argsort(score, stable=True)
        ys = y[order]
        ws = jnp.ones_like(ys) if w is None else w[order]
        ss = score[order]
        pos_w = jnp.where(ys > 0, ws, 0.0)
        neg_w = jnp.where(ys > 0, 0.0, ws)
        total_pos = jnp.sum(pos_w)
        total_neg = jnp.sum(neg_w)
        # tie groups by score value; half credit inside a group (mirrors the
        # host _weighted_auc / reference binary_metric.hpp AUCMetric)
        boundary = jnp.concatenate([
            jnp.ones((1,), bool), ss[1:] != ss[:-1]])
        gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
        gpos = jax.ops.segment_sum(pos_w, gid, num_segments=n)
        gneg = jax.ops.segment_sum(neg_w, gid, num_segments=n)
        neg_before = jnp.cumsum(gneg) - gneg
        auc = jnp.sum(gpos * (neg_before + 0.5 * gneg))
        denom = total_pos * total_neg
        return jnp.where(denom > 0, auc / jnp.maximum(denom, 1e-30), 1.0)
    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _dev_ndcg_sums(ks: tuple):
    """Per-k NDCG SUMS over one query-length bucket's queries ([len(ks)]).
    The caller (NDCGMetric.eval_device_traced) runs this per bucket of the
    rank_query_buckets plan and divides the combined sum by the total
    query count — the bucketed twin of the old pad-to-max mean kernel, so
    the fused-eval path pays sum_b nq_b*Q_b sort work instead of
    nq*qmax.  jit's shape-keyed trace cache gives one lowering per bucket
    geometry, warm across iterations."""
    import jax
    import jax.numpy as jnp

    def run(score, qidx, gain_doc, idcgs, disc):
        valid = qidx >= 0
        safe = jnp.maximum(qidx, 0)
        sc = jnp.where(valid, score[safe], -jnp.inf)
        order = jnp.argsort(-sc, axis=1, stable=True)
        g = jnp.where(valid, gain_doc[safe], 0.0)
        g_srt = jnp.take_along_axis(g, order, axis=1)
        out = []
        for i, k in enumerate(ks):
            kk = min(k, sc.shape[1])
            dcg = jnp.sum(g_srt[:, :kk] * disc[None, :kk], axis=1)
            idcg = idcgs[i]
            out.append(jnp.sum(jnp.where(idcg > 0, dcg
                                         / jnp.maximum(idcg, 1e-30), 1.0)))
        return jnp.stack(out)
    return jax.jit(run)


class Metric:
    NAME = "none"
    bigger_is_better = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.num_data = num_data
        self.label = np.asarray(metadata.label, np.float64)
        self.weight = None if metadata.weight is None else \
            np.asarray(metadata.weight, np.float64)
        self.sum_weight = float(self.weight.sum()) if self.weight is not None \
            else float(num_data)

    def eval(self, score: np.ndarray, objective=None) -> List[Tuple[str, float]]:
        raise NotImplementedError

    #: device-kernel id (_dev_pointwise) — None means no pointwise device
    #: path; AUC/NDCG override eval_device with their own kernels
    _DEV_KIND: Optional[str] = None
    #: True when eval_device_traced accepts the FULL [n, k] score matrix
    #: (multiclass metrics); single-output device kernels take a [n]
    #: column, so the fused scan only hands multiclass score matrices to
    #: metrics that declare this (boosting/gbdt.py fused_valid_ok)
    _DEV_MULTI: bool = False

    def eval_device(self, score_dev, objective=None
                    ) -> Optional[List[Tuple[str, float]]]:
        """Device-path evaluation over the resident score array; returns
        None when this metric/config has no device path (the caller then
        falls back to host ``eval``)."""
        vals = self.eval_device_traced(score_dev, objective)
        if vals is None:
            return None
        import numpy as np
        host = np.asarray(vals)
        return [(name, float(host[i]))
                for i, name in enumerate(self.display_names())]

    def eval_device_traced(self, score_dev, objective=None):
        """Traceable device evaluation: a f32 [len(display_names())] array
        of metric values, or None when no device path exists.  Safe to
        call INSIDE jit (the fused training scan evaluates valid metrics
        per round with this); ``eval_device`` is the host wrapper."""
        if self._DEV_KIND is None:
            return None
        import jax.numpy as jnp
        y, w = self._dev_arrays()
        p = self._dev_convert(score_dev, objective)
        val = _dev_pointwise(self._DEV_KIND)(
            p, y, w, jnp.float32(self.sum_weight))
        return jnp.reshape(val, (1,))

    def display_names(self) -> List[str]:
        """Metric display names in eval() output order, computable WITHOUT
        running an evaluation (LGBM_BoosterGetEvalNames)."""
        return [self.NAME]

    def _dev_arrays(self):
        import jax
        import jax.numpy as jnp
        if not hasattr(self, "_label_dev"):
            label_dev = jnp.asarray(self.label, jnp.float32)
            weight_dev = None if self.weight is None else \
                jnp.asarray(self.weight, jnp.float32)
            if isinstance(label_dev, jax.core.Tracer):
                # called under an ABSTRACT trace (e.g. the fused scan's
                # eval_shape): caching a tracer would leak it into later
                # real evaluations — return uncached, cache on the first
                # concrete call
                return label_dev, weight_dev
            self._label_dev = label_dev
            self._weight_dev = weight_dev
        return self._label_dev, self._weight_dev

    def _dev_convert(self, score, objective):
        if objective is not None and objective.need_convert_output:
            return objective.convert_output(score)
        return score

    def _avg(self, losses: np.ndarray) -> float:
        if self.weight is not None:
            return float(np.sum(losses * self.weight) / self.sum_weight)
        return float(np.mean(losses))

    def _convert(self, score: np.ndarray, objective) -> np.ndarray:
        if objective is not None and objective.need_convert_output:
            import jax.numpy as jnp
            return np.asarray(objective.convert_output(jnp.asarray(score)))
        return score


# ------------------------------------------------------------- regression
class _PointwiseRegression(Metric):
    def eval(self, score, objective=None):
        pred = self._convert(score, objective)
        return [(self.NAME, self._avg(self._loss(pred, self.label)))]


class L2Metric(_PointwiseRegression):
    NAME = "l2"
    _DEV_KIND = "l2"
    def _loss(self, p, y): return (p - y) ** 2


class RMSEMetric(_PointwiseRegression):
    NAME = "rmse"
    _DEV_KIND = "rmse"
    def eval(self, score, objective=None):
        pred = self._convert(score, objective)
        return [(self.NAME, float(np.sqrt(self._avg((pred - self.label) ** 2))))]


class L1Metric(_PointwiseRegression):
    NAME = "l1"
    _DEV_KIND = "l1"
    def _loss(self, p, y): return np.abs(p - y)


class QuantileMetric(_PointwiseRegression):
    NAME = "quantile"
    def _loss(self, p, y):
        a = self.config.alpha
        d = y - p
        return np.where(d >= 0, a * d, (a - 1.0) * d)


class HuberMetric(_PointwiseRegression):
    NAME = "huber"
    def _loss(self, p, y):
        a = self.config.alpha
        d = np.abs(p - y)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseRegression):
    NAME = "fair"
    def _loss(self, p, y):
        c = self.config.fair_c
        x = np.abs(p - y)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseRegression):
    NAME = "poisson"
    def _loss(self, p, y):
        eps = 1e-10
        return p - y * np.log(np.maximum(p, eps))


class MAPEMetric(_PointwiseRegression):
    NAME = "mape"
    def _loss(self, p, y):
        return np.abs((y - p) / np.maximum(1.0, np.abs(y)))


class GammaMetric(_PointwiseRegression):
    NAME = "gamma"
    def _loss(self, p, y):
        eps = 1e-10
        psafe = np.maximum(p, eps)
        return y / psafe + np.log(psafe) - 1.0 - np.log(np.maximum(y, eps))


class GammaDevianceMetric(_PointwiseRegression):
    NAME = "gamma_deviance"
    def _loss(self, p, y):
        eps = 1e-10
        r = y / np.maximum(p, eps)
        return 2.0 * (np.log(np.maximum(1.0 / np.maximum(r, eps), eps)) + r - 1.0)


class TweedieMetric(_PointwiseRegression):
    NAME = "tweedie"
    def _loss(self, p, y):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        psafe = np.maximum(p, eps)
        return -y * np.power(psafe, 1 - rho) / (1 - rho) + \
            np.power(psafe, 2 - rho) / (2 - rho)


# ----------------------------------------------------------------- binary
class BinaryLoglossMetric(Metric):
    NAME = "binary_logloss"
    _DEV_KIND = "binary_logloss"

    def eval(self, score, objective=None):
        p = np.clip(self._convert(score, objective), 1e-15, 1 - 1e-15)
        y = self.label
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [(self.NAME, self._avg(loss))]


class BinaryErrorMetric(Metric):
    NAME = "binary_error"
    _DEV_KIND = "binary_error"

    def eval(self, score, objective=None):
        p = self._convert(score, objective)
        err = (p > 0.5) != (self.label > 0)
        return [(self.NAME, self._avg(err.astype(np.float64)))]


def _weighted_auc(label: np.ndarray, score: np.ndarray,
                  weight: Optional[np.ndarray]) -> float:
    """Rank-based weighted AUC (reference binary_metric.hpp AUCMetric)."""
    if weight is None:
        weight = np.ones_like(label, dtype=np.float64)
    order = np.argsort(score, kind="mergesort")
    y, s, w = label[order], score[order], weight[order]
    pos_w = np.where(y > 0, w, 0.0)
    neg_w = np.where(y > 0, 0.0, w)
    # tie-aware: within tied score groups, credit half the pos x neg mass
    cum_neg = np.cumsum(neg_w)
    total_neg = cum_neg[-1] if len(cum_neg) else 0.0
    total_pos = pos_w.sum()
    if total_pos <= 0 or total_neg <= 0:
        return 1.0
    # group by unique score
    boundary = np.r_[True, s[1:] != s[:-1]]
    gid = np.cumsum(boundary) - 1
    ng = gid[-1] + 1
    gpos = np.bincount(gid, weights=pos_w, minlength=ng)
    gneg = np.bincount(gid, weights=neg_w, minlength=ng)
    neg_before = np.cumsum(gneg) - gneg
    auc = np.sum(gpos * (neg_before + 0.5 * gneg))
    return float(auc / (total_pos * total_neg))


class AUCMetric(Metric):
    NAME = "auc"
    bigger_is_better = True

    def eval(self, score, objective=None):
        return [(self.NAME, _weighted_auc(self.label, score, self.weight))]

    def eval_device_traced(self, score_dev, objective=None):
        import jax.numpy as jnp
        y, w = self._dev_arrays()
        return jnp.reshape(_dev_auc()(score_dev, y, w), (1,))


class AveragePrecisionMetric(Metric):
    NAME = "average_precision"
    bigger_is_better = True

    def eval(self, score, objective=None):
        w = self.weight if self.weight is not None else \
            np.ones_like(self.label)
        order = np.argsort(-score, kind="mergesort")
        y, ww = self.label[order] > 0, w[order]
        tp = np.cumsum(np.where(y, ww, 0.0))
        fp = np.cumsum(np.where(y, 0.0, ww))
        prec = tp / np.maximum(tp + fp, 1e-20)
        total_pos = tp[-1] if len(tp) else 0.0
        if total_pos <= 0:
            return [(self.NAME, 1.0)]
        rec_delta = np.diff(np.r_[0.0, tp]) / total_pos
        return [(self.NAME, float(np.sum(prec * rec_delta)))]


# ------------------------------------------------------------- multiclass
class MultiLoglossMetric(Metric):
    NAME = "multi_logloss"

    def eval(self, score, objective=None):
        # score: [n, K] raw; convert via softmax/sigmoid per objective
        p = self._convert(score, objective)
        if objective is None or not objective.need_convert_output:
            ex = np.exp(score - score.max(axis=1, keepdims=True))
            p = ex / ex.sum(axis=1, keepdims=True)
        idx = self.label.astype(int)
        p_true = np.clip(p[np.arange(len(idx)), idx], 1e-15, None)
        if getattr(objective, "NAME", "") == "multiclassova":
            p_true = np.clip(p_true / np.maximum(p.sum(axis=1), 1e-15), 1e-15, None)
        return [(self.NAME, self._avg(-np.log(p_true)))]

    _DEV_MULTI = True

    def eval_device_traced(self, score_dev, objective=None):
        """Traced multiclass logloss over the [n, k] score matrix — the
        fused scan's per-round valid eval (round 6: multiclass rides the
        fused path).  Same formulation as host ``eval`` in device f32
        (the accepted device-eval precision class)."""
        import jax.numpy as jnp
        y, w = self._dev_arrays()
        idx = y.astype(jnp.int32)
        p = self._dev_convert(score_dev, objective)
        if objective is None or not objective.need_convert_output:
            ex = jnp.exp(score_dev - jnp.max(score_dev, axis=1,
                                             keepdims=True))
            p = ex / jnp.sum(ex, axis=1, keepdims=True)
        p_true = jnp.maximum(p[jnp.arange(p.shape[0]), idx], 1e-15)
        if getattr(objective, "NAME", "") == "multiclassova":
            p_true = jnp.maximum(
                p_true / jnp.maximum(jnp.sum(p, axis=1), 1e-15), 1e-15)
        losses = -jnp.log(p_true)
        val = jnp.mean(losses) if w is None else \
            jnp.sum(losses * w) / jnp.float32(self.sum_weight)
        return jnp.reshape(val.astype(jnp.float32), (1,))


class MultiErrorMetric(Metric):
    NAME = "multi_error"

    def eval(self, score, objective=None):
        k = self.config.multi_error_top_k
        idx = self.label.astype(int)
        true_score = score[np.arange(len(idx)), idx]
        # error when the true class is not within top-k (reference
        # multiclass_metric.hpp MultiErrorMetric)
        rank = (score > true_score[:, None]).sum(axis=1)
        err = rank >= k
        return [(self.NAME, self._avg(err.astype(np.float64)))]

    _DEV_MULTI = True

    def eval_device_traced(self, score_dev, objective=None):
        """Traced top-k multiclass error over the [n, k] score matrix
        (fused-scan valid eval; mirrors host ``eval`` — rank counting is
        integer-exact, so only ties at f32-vs-f64 score resolution can
        deviate, the same class as every other device metric)."""
        import jax.numpy as jnp
        topk = self.config.multi_error_top_k
        y, w = self._dev_arrays()
        idx = y.astype(jnp.int32)
        true_score = score_dev[jnp.arange(score_dev.shape[0]), idx]
        rank = jnp.sum(score_dev > true_score[:, None], axis=1)
        err = (rank >= topk).astype(jnp.float32)
        val = jnp.mean(err) if w is None else \
            jnp.sum(err * w) / jnp.float32(self.sum_weight)
        return jnp.reshape(val, (1,))


class AucMuMetric(Metric):
    """Multiclass AUC-mu (reference multiclass_metric.hpp:368 AucMuMetric,
    Kleiman & Page 2019)."""
    NAME = "auc_mu"
    bigger_is_better = True

    def eval(self, score, objective=None):
        y = self.label.astype(int)
        k = self.config.num_class
        wmat = None
        if self.config.auc_mu_weights:
            wmat = np.asarray(self.config.auc_mu_weights, np.float64).reshape(k, k)
        aucs = []
        for a in range(k):
            for b in range(a + 1, k):
                m = (y == a) | (y == b)
                if m.sum() == 0 or (y[m] == a).all() or (y[m] == b).all():
                    continue
                # decision value: difference in class scores, weighted by the
                # partition weights when provided
                if wmat is not None:
                    d = score[m] @ (wmat[a] - wmat[b])
                    d = -d
                else:
                    d = score[m, a] - score[m, b]
                aucs.append(_weighted_auc((y[m] == a).astype(np.float64), d,
                                          None if self.weight is None
                                          else self.weight[m]))
        return [(self.NAME, float(np.mean(aucs)) if aucs else 1.0)]


# ---------------------------------------------------------------- ranking
def _dcg_at_k(labels: np.ndarray, order: np.ndarray, k: int,
              label_gain: np.ndarray) -> float:
    top = order[:k]
    gains = label_gain[labels[top].astype(int)]
    return float(np.sum(gains / np.log2(np.arange(2, len(top) + 2))))


class NDCGMetric(Metric):
    """reference rank_metric.hpp NDCGMetric + dcg_calculator.cpp."""
    NAME = "ndcg"
    bigger_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("NDCG metric requires query information")
        self.bounds = metadata.query_boundaries
        mx = int(self.label.max()) + 1 if len(self.label) else 1
        gains = self.config.label_gain or [float((1 << i) - 1)
                                           for i in range(max(mx, 31))]
        self.label_gain = np.asarray(gains, np.float64)
        self.ks = list(self.config.eval_at)

    def eval(self, score, objective=None):
        res = {k: [] for k in self.ks}
        qw = []
        for qi in range(len(self.bounds) - 1):
            s, e = self.bounds[qi], self.bounds[qi + 1]
            lbl = self.label[s:e]
            sc = score[s:e]
            order = np.argsort(-sc, kind="mergesort")
            ideal = np.argsort(-lbl, kind="mergesort")
            qw.append(1.0)
            for k in self.ks:
                idcg = _dcg_at_k(lbl, ideal, k, self.label_gain)
                if idcg <= 0:
                    res[k].append(1.0)
                else:
                    res[k].append(_dcg_at_k(lbl, order, k, self.label_gain) / idcg)
        return [(f"ndcg@{k}", float(np.average(res[k], weights=qw)))
                for k in self.ks]

    def display_names(self):
        return [f"ndcg@{k}" for k in self.ks]

    def _ndcg_from_buckets(self, score_dev, dev_buckets, gain_dev):
        nq = len(self.bounds) - 1
        total = None
        for qidx_dev, idcg_dev, disc_dev in dev_buckets:
            part = _dev_ndcg_sums(tuple(self.ks))(
                score_dev, qidx_dev, gain_dev, idcg_dev, disc_dev)
            total = part if total is None else total + part
        return total / nq

    def eval_device_traced(self, score_dev, objective=None):
        import jax
        import jax.numpy as jnp
        if not hasattr(self, "_rank_dev_buckets"):
            from .objectives import _rank_buckets
            spec = getattr(self.config, "rank_query_buckets", "auto")
            buckets, _ = _rank_buckets(np.asarray(self.bounds), spec)
            gain_dev = jnp.asarray(
                self.label_gain[self.label.astype(int)], jnp.float32)
            idcgs = np.zeros((len(self.ks), len(self.bounds) - 1), np.float32)
            for qi in range(len(self.bounds) - 1):
                s, e = self.bounds[qi], self.bounds[qi + 1]
                lbl = self.label[s:e]
                ideal = np.argsort(-lbl, kind="mergesort")
                for i, k in enumerate(self.ks):
                    idcgs[i, qi] = _dcg_at_k(lbl, ideal, k, self.label_gain)
            dev_buckets = []
            for cap, qids, idx in buckets:
                dev_buckets.append((
                    jnp.asarray(idx),
                    jnp.asarray(idcgs[:, qids]),
                    jnp.asarray(1.0 / np.log2(np.arange(max(cap, 1)) + 2.0),
                                jnp.float32)))
            if isinstance(gain_dev, jax.core.Tracer) or (
                    dev_buckets and isinstance(dev_buckets[0][0],
                                               jax.core.Tracer)):
                # abstract trace (see Metric._dev_arrays): use uncached
                return self._ndcg_from_buckets(score_dev, dev_buckets,
                                               gain_dev)
            self._rank_dev_buckets = dev_buckets
            self._gain_dev = gain_dev
        return self._ndcg_from_buckets(score_dev, self._rank_dev_buckets,
                                       self._gain_dev)


class MapMetric(Metric):
    """reference map_metric.hpp MapMetric."""
    NAME = "map"
    bigger_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("MAP metric requires query information")
        self.bounds = metadata.query_boundaries
        self.ks = list(self.config.eval_at)

    def eval(self, score, objective=None):
        res = {k: [] for k in self.ks}
        for qi in range(len(self.bounds) - 1):
            s, e = self.bounds[qi], self.bounds[qi + 1]
            rel = (self.label[s:e] > 0).astype(np.float64)
            order = np.argsort(-score[s:e], kind="mergesort")
            rel_sorted = rel[order]
            hits = np.cumsum(rel_sorted)
            prec = hits / np.arange(1, len(rel_sorted) + 1)
            for k in self.ks:
                topk = slice(0, k)
                denom = min(k, int(rel.sum())) or 1
                ap = np.sum(prec[topk] * rel_sorted[topk]) / denom
                res[k].append(ap if rel.sum() > 0 else 1.0)
        return [(f"map@{k}", float(np.mean(res[k]))) for k in self.ks]

    def display_names(self):
        return [f"map@{k}" for k in self.ks]


# --------------------------------------------------------------- xentropy
class CrossEntropyMetric(Metric):
    NAME = "cross_entropy"

    def eval(self, score, objective=None):
        p = np.clip(self._convert(score, objective), 1e-15, 1 - 1e-15)
        y = self.label
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [(self.NAME, self._avg(loss))]


class CrossEntropyLambdaMetric(Metric):
    NAME = "cross_entropy_lambda"

    def eval(self, score, objective=None):
        # p through the lambda link (see objectives.CrossEntropyLambda)
        w = self.weight if self.weight is not None else 1.0
        sp = np.logaddexp(0.0, score)
        p = np.clip(1.0 - np.exp(-w * sp), 1e-15, 1 - 1e-15)
        y = self.label
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [(self.NAME, float(np.mean(loss)))]


class KLDivergenceMetric(Metric):
    NAME = "kullback_leibler"

    def eval(self, score, objective=None):
        p = np.clip(self._convert(score, objective), 1e-15, 1 - 1e-15)
        y = np.clip(self.label, 1e-15, 1 - 1e-15)
        kl = y * np.log(y / p) + (1 - y) * np.log((1 - y) / (1 - p))
        return [(self.NAME, self._avg(kl))]


_METRICS = {
    "l1": L1Metric, "l2": L2Metric, "rmse": RMSEMetric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "mape": MAPEMetric, "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "average_precision": AveragePrecisionMetric,
    "multi_logloss": MultiLoglossMetric, "multi_error": MultiErrorMetric,
    "auc_mu": AucMuMetric,
    "ndcg": NDCGMetric, "map": MapMetric,
    "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KLDivergenceMetric,
}

_DEFAULT_METRIC_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "quantile": "quantile", "mape": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary_logloss",
    "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
    "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "lambdarank": "ndcg", "rank_xendcg": "ndcg",
}


def create_metrics(config: Config) -> List[Metric]:
    """Factory (reference metric.cpp:21-127): explicit list, or the
    objective's default metric when none requested."""
    names: Sequence[str] = config.metric
    if not names:
        default = _DEFAULT_METRIC_FOR_OBJECTIVE.get(config.objective)
        names = [default] if default else []
    out: List[Metric] = []
    for nm in names:
        if nm in ("none", ""):
            continue
        cls = _METRICS.get(nm)
        if cls is None:
            log.warning(f"Unknown metric: {nm}")
            continue
        out.append(cls(config))
    return out
