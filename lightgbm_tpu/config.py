"""Parameter/config system.

TPU-native re-design of the reference's config layer (reference:
include/LightGBM/config.h:39 ``struct Config`` with 837 defaulted fields,
src/io/config.cpp ``Config::Set`` and the generated alias table in
src/io/config_auto.cpp).  The reference generates its alias table and setters
from structured comments; here a single declarative ``_PARAMS`` registry plays
that role (single source of truth for names, aliases, defaults and checks).

Semantics preserved:
  * alias resolution is first-wins per canonical name
    (reference application.cpp:79 ``KeepFirstValues``),
  * ``Config.set(params)`` accepts strings or typed values,
  * ``check`` constraints mirror the reference's ``// check = ...`` comments,
  * ``check_param_conflict`` fixes illegal combos (reference config.cpp).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple, Union

from .utils import log


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return bool(v)
    s = str(v).strip().lower()
    if s in ("true", "1", "yes", "+"):
        return True
    if s in ("false", "0", "no", "-"):
        return False
    raise ValueError(f"cannot parse bool from {v!r}")


def _parse_int_list(v: Any) -> List[int]:
    if v is None or v == "":
        return []
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    return [int(x) for x in str(v).split(",") if x != ""]


def _parse_float_list(v: Any) -> List[float]:
    if v is None or v == "":
        return []
    if isinstance(v, (list, tuple)):
        return [float(x) for x in v]
    return [float(x) for x in str(v).split(",") if x != ""]


def _parse_str_list(v: Any) -> List[str]:
    if v is None or v == "":
        return []
    if isinstance(v, (list, tuple)):
        return [str(x) for x in v]
    return [s for s in str(v).split(",") if s != ""]


# (name, default, aliases, check) — check is (op, bound) pairs like the
# reference's `// check = >0` annotations (config.h:202-253).
# Parameters that BIND a constructed Dataset's binning and cannot change
# afterwards (reference LGBM_DatasetUpdateParamChecking c_api.h:573 and the
# python package's _compare_params_for_warning list).
DATASET_BINDING_PARAMS = (
    "max_bin", "max_bin_by_feature", "min_data_in_bin",
    "bin_construct_sample_cnt", "enable_bundle", "linear_tree",
    "data_random_seed", "is_enable_sparse", "feature_pre_filter",
    "use_missing", "zero_as_missing", "categorical_feature",
    "forcedbins_filename", "precise_float_parser",
)

_PARAMS: List[Tuple[str, Any, Tuple[str, ...], Tuple[Tuple[str, float], ...]]] = [
    # --- core (config.h "Core Parameters") ---
    ("task", "train", ("task_type",), ()),
    ("output_model", "LightGBM_model.txt", ("model_output", "model_out"), ()),
    ("input_model", "", ("model_input", "model_in"), ()),
    ("output_result", "LightGBM_predict_result.txt",
     ("predict_result", "prediction_result", "predict_name", "pred_name",
      "name_pred", "prediction_name"), ()),
    ("saved_feature_importance_type", 0, (), ()),
    ("config", "", ("config_file",), ()),
    ("objective", "regression", ("objective_type", "app", "application", "loss"), ()),
    ("boosting", "gbdt", ("boosting_type", "boost"), ()),
    ("data_sample_strategy", "bagging", (), ()),
    ("data", "", ("train", "train_data", "train_data_file", "data_filename"), ()),
    ("valid", [], ("test", "valid_data", "valid_data_file", "test_data",
                   "test_data_file", "valid_filenames"), ()),
    ("num_iterations", 100, ("num_iteration", "n_iter", "num_tree", "num_trees",
                             "num_round", "num_rounds", "nrounds", "num_boost_round",
                             "n_estimators", "max_iter"), ((">=", 0),)),
    ("learning_rate", 0.1, ("shrinkage_rate", "eta"), ((">", 0.0),)),
    ("num_leaves", 31, ("num_leaf", "max_leaves", "max_leaf", "max_leaf_nodes"),
     ((">", 1),)),
    ("tree_learner", "serial", ("tree", "tree_type", "tree_learner_type"), ()),
    ("num_threads", 0, ("num_thread", "nthread", "nthreads", "n_jobs"), ()),
    ("device_type", "tpu", ("device",), ()),
    ("seed", None, ("random_seed", "random_state"), ()),
    ("deterministic", False, (), ()),
    # --- learning control ---
    ("force_col_wise", False, (), ()),
    ("force_row_wise", False, (), ()),
    ("histogram_pool_size", -1.0, ("hist_pool_size",), ()),
    ("max_depth", -1, (), ()),
    ("min_data_in_leaf", 20, ("min_data_per_leaf", "min_data", "min_child_samples",
                              "min_samples_leaf"), ((">=", 0),)),
    ("min_sum_hessian_in_leaf", 1e-3, ("min_sum_hessian_per_leaf", "min_sum_hessian",
                                       "min_hessian", "min_child_weight"), ((">=", 0.0),)),
    ("bagging_fraction", 1.0, ("sub_row", "subsample", "bagging"),
     ((">", 0.0), ("<=", 1.0))),
    ("pos_bagging_fraction", 1.0, ("pos_sub_row", "pos_subsample", "pos_bagging"),
     ((">", 0.0), ("<=", 1.0))),
    ("neg_bagging_fraction", 1.0, ("neg_sub_row", "neg_subsample", "neg_bagging"),
     ((">", 0.0), ("<=", 1.0))),
    ("bagging_freq", 0, ("subsample_freq",), ()),
    ("bagging_seed", 3, ("bagging_fraction_seed",), ()),
    ("bagging_by_query", False, (), ()),
    ("feature_fraction", 1.0, ("sub_feature", "colsample_bytree"),
     ((">", 0.0), ("<=", 1.0))),
    ("feature_fraction_bynode", 1.0, ("sub_feature_bynode", "colsample_bynode"),
     ((">", 0.0), ("<=", 1.0))),
    ("feature_fraction_seed", 2, (), ()),
    ("extra_trees", False, ("extra_tree",), ()),
    ("extra_seed", 6, (), ()),
    ("early_stopping_round", 0, ("early_stopping_rounds", "early_stopping",
                                 "n_iter_no_change"), ()),
    ("early_stopping_min_delta", 0.0, (), ((">=", 0.0),)),
    ("first_metric_only", False, (), ()),
    ("max_delta_step", 0.0, ("max_tree_output", "max_leaf_output"), ()),
    ("lambda_l1", 0.0, ("reg_alpha", "l1_regularization"), ((">=", 0.0),)),
    ("lambda_l2", 0.0, ("reg_lambda", "lambda", "l2_regularization"), ((">=", 0.0),)),
    ("linear_lambda", 0.0, (), ((">=", 0.0),)),
    ("min_gain_to_split", 0.0, ("min_split_gain",), ((">=", 0.0),)),
    ("drop_rate", 0.1, ("rate_drop",), ((">=", 0.0), ("<=", 1.0))),
    ("max_drop", 50, (), ()),
    ("skip_drop", 0.5, (), ((">=", 0.0), ("<=", 1.0))),
    ("xgboost_dart_mode", False, (), ()),
    ("uniform_drop", False, (), ()),
    ("drop_seed", 4, (), ()),
    ("top_rate", 0.2, (), ((">=", 0.0), ("<=", 1.0))),
    ("other_rate", 0.1, (), ((">=", 0.0), ("<=", 1.0))),
    ("min_data_per_group", 100, (), ((">", 0),)),
    ("max_cat_threshold", 32, (), ((">", 0),)),
    ("cat_l2", 10.0, (), ((">=", 0.0),)),
    ("cat_smooth", 10.0, (), ((">=", 0.0),)),
    ("max_cat_to_onehot", 4, (), ((">", 0),)),
    ("top_k", 20, ("topk",), ((">", 0),)),
    ("monotone_constraints", [], ("mc", "monotone_constraint", "monotonic_cst"), ()),
    ("monotone_constraints_method", "basic", ("monotone_constraining_method", "mc_method"), ()),
    ("monotone_penalty", 0.0, ("monotone_splits_penalty", "ms_penalty", "mc_penalty"),
     ((">=", 0.0),)),
    ("feature_contri", [], ("feature_contrib", "fc", "fp", "feature_penalty"), ()),
    ("forcedsplits_filename", "", ("fs", "forced_splits_filename", "forced_splits_file",
                                   "forced_splits"), ()),
    ("refit_decay_rate", 0.9, (), ((">=", 0.0), ("<=", 1.0))),
    ("cegb_tradeoff", 1.0, (), ((">=", 0.0),)),
    ("cegb_penalty_split", 0.0, (), ((">=", 0.0),)),
    ("cegb_penalty_feature_lazy", [], (), ()),
    ("cegb_penalty_feature_coupled", [], (), ()),
    ("path_smooth", 0.0, (), ((">=", 0.0),)),
    ("interaction_constraints", "", (), ()),
    ("verbosity", 1, ("verbose",), ()),
    ("snapshot_freq", -1, ("save_period",), ()),
    # --- observability (obs/; docs/OBSERVABILITY.md) ---
    ("trace_output", "", ("trace_file", "trace_out"), ()),        # Chrome trace-event JSON path (Perfetto-loadable)
    ("telemetry_output", "", ("telemetry_file",), ()),            # per-iteration telemetry JSONL path
    ("event_output", "", ("event_file", "event_journal"), ()),    # structured event-journal JSONL path (obs/events.py declared schema; lifecycle events: heartbeat/eviction/reshape/resume, checkpoint write/resume/corrupt-skip, nan_policy trips, serving hot-swap/overload)
    ("profile_dir", "", ("profiler_dir",), ()),                   # jax.profiler trace directory (device timeline)
    ("slo_config", "", ("slos",), ()),                            # declarative SLO watching (obs/slo.py SLOS table): ""/off = disabled; "on" = every declared SLO at default budget; or "name[:budget],name2" to pick/override (e.g. "serving_p99_ms:25,compile_miss_storm"); breaches emit slo_breach/slo_recovered journal events with multi-window burn-rate logic
    ("rollup_window_s", 60.0, ("rollup_window",), ((">", 0.0),)), # time-series rollup window length in seconds (obs/timeseries.py ring; feeds SLO evaluation and tools/obs_top.py)
    ("anomaly_detection", "off", (), ()),                         # baseline-relative training-loop anomaly detection: on|off (obs/anomaly.py; robust z on round time, eval divergence/plateau, compile-miss burst, host-RSS slope — journal events + counters, never hard failures)
    ("request_trace", "off", (), ()),                             # request-scoped distributed tracing across the serving tier (obs/reqtrace.py): off (default; zero per-request work) | errors (tail-based: keep failed/failed-over/deadline-breached/slowest-k traces only) | sample:<p> (errors + keep fraction p of healthy requests) | all; kept traces carry a per-request span tree (router dispatch, retry attempts, replica queue wait, admission, bucket pad, device run, value gather) merged onto the router's clock, plus exemplar trace ids on latency quantiles and a per-process crash flight recorder
    # --- robustness (robustness/; docs/ROBUSTNESS.md) ---
    ("checkpoint_dir", "", ("checkpoint_directory",), ()),        # periodic atomic training checkpoints under this directory; empty = off
    ("checkpoint_interval", 10, (), ((">", 0),)),                 # boosting rounds between checkpoints
    ("checkpoint_keep", 3, (), ((">", 0),)),                      # newest checkpoints retained (older ones pruned)
    ("nan_policy", "none", (), ()),                               # per-round finite guard on grad/hess/scores: none|raise|skip_round|halt_and_keep_best
    ("cluster_timeout_s", 3600.0, ("cluster_timeout",), ((">", 0.0),)),  # parallel.cluster.launch worker deadline
    ("heartbeat_interval_s", 5.0, (), ((">", 0.0),)),             # elastic liveness: seconds between per-round worker heartbeat markers (robustness/elastic.py; same file substrate as the startup-barrier ready markers)
    ("heartbeat_timeout_s", 30.0, (), ((">", 0.0),)),             # elastic liveness: a worker silent past this is DEAD (evicted); staleness between heartbeat_interval_s and this marks it SLOW (bounded wait + warn + elastic_slow_worker_rounds counter)
    ("elastic", "off", (), ()),                                   # worker-loss policy: on|off. off (default) = a post-barrier worker death fail-fasts the whole job (pre-PR-9 behavior); on = evict the silent worker, rebuild the mesh over the survivor set, re-shard rows, resume from the newest checkpoint (robustness/elastic.py, docs/ROBUSTNESS.md "Elastic recovery")
    ("publish_interval", 10, (), ((">", 0),)),                    # continuous-learning pipeline (pipeline/; docs/ROBUSTNESS.md "Continuous learning"): boosting rounds per train->publish cycle — every cycle boosts this many more rounds on the data seen so far, then exports and publishes the snapshot
    ("pipeline_workdir", "", (), ()),                             # continuous-learning pipeline: durable directory for the atomic cycle manifest, per-cycle checkpoints, model-text exports and the publish-provenance ledger; a SIGKILLed trainer resumes from it with ContinuousTrainer(..., resume="auto"); empty = pipeline unavailable (ContinuousTrainer requires it)
    ("publish_retry_budget", 2, (), ((">=", 0),)),                # continuous-learning pipeline: publishes retried per cycle after a mid-rollout abort (fleet RollingSwapAborted) before the failure propagates; each retry reuses the cycle's export-assigned version, never skipping forward
    ("use_quantized_grad", False, (), ()),
    ("num_grad_quant_bins", 4, (), ()),
    ("quant_train_renew_leaf", False, (), ()),
    ("stochastic_rounding", True, (), ()),
    # --- dataset (config.h "Dataset Parameters") ---
    ("max_bin", 255, ("max_bins",), ((">", 1),)),
    ("max_bin_by_feature", [], (), ()),
    ("min_data_in_bin", 3, (), ((">", 0),)),
    ("bin_construct_sample_cnt", 200000, ("subsample_for_bin",), ((">", 0),)),
    ("data_random_seed", 1, ("data_seed",), ()),
    ("is_enable_sparse", True, ("is_sparse", "enable_sparse", "sparse"), ()),
    ("enable_bundle", True, ("is_enable_bundle", "bundle"), ()),
    ("use_missing", True, (), ()),
    ("zero_as_missing", False, (), ()),
    ("feature_pre_filter", True, (), ()),
    ("pre_partition", False, ("is_pre_partition",), ()),
    ("two_round", False, ("two_round_loading", "use_two_round_loading"), ()),
    ("header", False, ("has_header",), ()),
    ("label_column", "", ("label",), ()),
    ("weight_column", "", ("weight",), ()),
    ("group_column", "", ("group", "group_id", "query_column", "query", "query_id"), ()),
    ("ignore_column", "", ("ignore_feature", "blacklist"), ()),
    ("categorical_feature", "", ("cat_feature", "categorical_column", "cat_column",
                                 "categorical_features"), ()),
    ("forcedbins_filename", "", (), ()),
    ("ingest_chunk_rows", 100000, (), ((">", 0),)),               # out-of-core streaming construction (io/streaming.py): rows per chunk in both the sketch pass and the bin+pack pass; peak host memory scales with this, not with the row count
    ("ingest_memory_budget_mb", 0.0, (), ((">=", 0.0),)),         # out-of-core streaming construction: soft ceiling on the chunk working set in MB (0 = off); ingest_chunk_rows is clamped down so one raw+binned chunk fits the budget
    ("ingest_sketch_accuracy", 0.001, (), ((">", 0.0), ("<", 0.5))),  # out-of-core streaming construction: relative accuracy alpha of the mergeable log-bucket quantile sketch used when a feature overflows the exact distinct tally; bin boundaries then sit within alpha relative error of the in-memory ones
    ("ingest_workers", 0, (), ((">=", 0),)),                      # elastic sharded ingest (io/sharded.py; docs/SCALING.md "Sharded ingestion"): worker hosts sharding pass 1/pass 2 over a stripe-ownership ledger; 0 (default) = single-host io/streaming.py path, no ledger, no extra files; 1 = delegate to the single-host path (byte-identical artifacts); >=2 = multi-process workers with heartbeat death detection and work-stealing — output stays bit-identical to the single-host build regardless of worker deaths (reuses heartbeat_interval_s / heartbeat_timeout_s for liveness)
    ("ingest_stripe_batch", 1, (), ((">", 0),)),                  # elastic sharded ingest: contiguous stripes a worker claims per ledger sweep; larger batches amortize claim-file round-trips, smaller ones spread reassignable work more evenly after a host death
    ("save_binary", False, ("is_save_binary", "is_save_binary_file"), ()),
    ("precise_float_parser", False, (), ()),
    ("parser_config_file", "", (), ()),
    ("linear_tree", False, ("linear_trees",), ()),
    # --- predict ---
    ("start_iteration_predict", 0, (), ()),
    ("num_iteration_predict", -1, (), ()),
    ("predict_raw_score", False, ("is_predict_raw_score", "predict_rawscore",
                                  "raw_score"), ()),
    ("predict_leaf_index", False, ("is_predict_leaf_index", "leaf_index"), ()),
    ("predict_contrib", False, ("is_predict_contrib", "contrib"), ()),
    ("predict_disable_shape_check", False, (), ()),
    ("pred_early_stop", False, (), ()),
    ("pred_early_stop_freq", 10, (), ()),
    ("pred_early_stop_margin", 10.0, (), ()),
    # --- convert ---
    ("convert_model_language", "", (), ()),
    ("convert_model", "gbdt_prediction.cpp", ("convert_model_file",), ()),
    # --- objective (config.h "Objective Parameters") ---
    ("objective_seed", 5, (), ()),
    ("num_class", 1, ("num_classes",), ((">", 0),)),
    ("is_unbalance", False, ("unbalance", "unbalanced_sets"), ()),
    ("scale_pos_weight", 1.0, (), ((">", 0.0),)),
    ("sigmoid", 1.0, (), ((">", 0.0),)),
    ("boost_from_average", True, (), ()),
    ("reg_sqrt", False, (), ()),
    ("alpha", 0.9, (), ((">", 0.0),)),
    ("fair_c", 1.0, (), ((">", 0.0),)),
    ("poisson_max_delta_step", 0.7, (), ((">", 0.0),)),
    ("tweedie_variance_power", 1.5, (), ((">=", 1.0), ("<", 2.0))),
    ("lambdarank_truncation_level", 30, (), ((">", 0),)),
    ("lambdarank_norm", True, (), ()),
    ("label_gain", [], (), ()),
    ("lambdarank_position_bias_regularization", 0.0, (), ((">=", 0.0),)),
    ("rank_query_buckets", "auto", (), ()),  # query-length bucket ladder for the device lambdarank/xendcg kernels (objectives.py): "auto" derives power-of-two buckets from the training query-length distribution; an explicit list (e.g. "16,64,256") pins the ladder (extended to cover the longest query); each bucket geometry lowers ONE pairwise program through ops/compile_cache.py (rank_compile_hits/misses), so padded-pair compute is sum_b nq_b*T*Q_b instead of nq*T*Qmax; LGBMTPU_NO_RANK_BUCKETS=1 is the pad-to-max A/B hatch
    # --- metric ---
    ("metric", [], ("metrics", "metric_types"), ()),
    ("metric_freq", 1, ("output_freq",), ((">", 0),)),
    ("is_provide_training_metric", False, ("training_metric", "is_training_metric",
                                           "train_metric"), ()),
    ("eval_at", [1, 2, 3, 4, 5], ("ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at"), ()),
    ("multi_error_top_k", 1, (), ((">", 0),)),
    ("auc_mu_weights", [], (), ()),
    # --- network (config.h:1086-1110); on TPU these describe the JAX mesh ---
    ("num_machines", 1, ("num_machine",), ((">", 0),)),
    ("local_listen_port", 12400, ("local_port", "port"), ()),
    ("time_out", 120, (), ((">", 0),)),
    ("machine_list_filename", "", ("machine_list_file", "machine_list", "mlist"), ()),
    ("machines", "", ("workers", "nodes"), ()),
    # --- device / TPU (replaces reference GPU params config.h:1113-1150) ---
    ("gpu_platform_id", -1, (), ()),
    ("gpu_device_id", -1, (), ()),
    ("gpu_use_dp", False, (), ()),
    ("num_gpu", 1, (), ((">", 0),)),
    ("tpu_hist_dtype", "float32", (), ()),       # hist product dtype; float32 = exact CPU/reference parity, bfloat16 = ~3x faster kernels, int8 = int8-MXU path (requires use_quantized_grad, ~1.6x bfloat16 kernel rate); AUTO POLICY: at >=100k rows and deterministic=false, an unset value engages int8 with exact quantized-grad levels (decision-identical; boosting/gbdt.py _resolve_auto_params); deterministic=true always forces float32
    ("tpu_debug_checks", False, (), ()),         # per-tree invariant checks (reference DEBUG CheckSplitValid)
    ("tpu_device_eval", True, (), ()),           # jitted device metric eval (l2/l1/rmse/logloss/error/auc/ndcg); host f64 when false or deterministic=true
    ("tpu_rows_per_block", 16384, (), ()),        # histogram kernel row tile
    ("tpu_leaf_hist", "masked", (), ()),          # per-leaf hist: masked|bucketed
    ("tpu_split_batch", 1, (), ((">", 0),)),      # splits per histogram pass; AUTO POLICY: unset at >=100k rows resolves to min(42, num_leaves-1)
    ("hist_kernel", "auto", (), ()),              # histogram build formulation: auto|onehot|packed|radix2 (ops/histogram.py HIST_KERNELS; all modes bit-identical — onehot = flat reference, packed = 4 bins per i32 lane SWAR compares, radix2 = shared hi/lo nibble planes reused across split-batch leaf channels)
    ("collective_overlap", "auto", (), ()),       # distributed histogram-reduction schedule: auto|on|off (ops/histogram.py reduce_hist; "on"/auto-under-data/voting splits each psum into two independent half-collectives — bit-identical sums — so XLA's latency-hiding scheduler can overlap wire time with local compute; LGBMTPU_NO_OVERLAP is the trace-time A/B hatch; data_gspmd ignores it, the partitioner owns its schedule)
    ("serving_buckets", [1, 8, 64, 512, 4096], (), ()),  # serving-tier row-count bucket ladder (lightgbm_tpu/serving/): requests are padded up to the smallest bucket >= n (oversize requests chunk by the largest), so every request re-enters an already-compiled program and XLA never lowers at steady state; sorted/deduped, all entries > 0
    ("predict_bucketing", "on", (), ()),          # batch Booster.predict shape-thrash fix: on|off (boosting/gbdt.py _device_predict_raw pads block tails up to a geometric ladder of tail-quantum multiples instead of the next exact multiple, bounding compiled program count at log2(block/quantum)+1 across ANY mix of row counts; bit-identical — padded rows are sliced off and the path-count matmuls are per-row exact; counters predict_bucketed_calls/predict_bucket_pad_rows)
    ("serving_telemetry_output", "", (), ()),     # serving per-request JSONL path (serving/server.py PredictionServer: one record per predict() with model/version, rows, buckets hit, pad rows, latency_s; "" disables)
    ("serving_max_inflight", 64, (), ((">", 0),)),  # serving-tier admission control: bound on concurrently served predict() requests (serving/server.py); a request arriving with the bound already in flight is rejected FAST (ServerOverloaded + serve_rejected_requests counter) instead of queueing unboundedly
    ("serving_replicas", 0, (), ((">=", 0),)),      # replicated serving fleet size (serving/fleet.py FleetServer): 0 (default) = OFF, single-process PredictionServer semantics with no extra processes or files; N >= 1 spawns N replica worker processes (each a full PredictionServer + warmed bucket ladder) behind a failover router
    ("serving_retry_budget", 2, (), ((">=", 0),)),  # fleet router failover bound: a request whose replica dies or misses its sub-deadline is transparently re-dispatched to a surviving replica at most this many times (request_failover journal events + fleet_request_failovers counter); 0 = no failover, first error surfaces
    ("fleet_heartbeat_interval_s", 0.5, (), ((">", 0.0),)),  # serving-replica liveness: seconds between a replica's heartbeat markers (same file substrate as training heartbeats, robustness/elastic.py; faster default than heartbeat_interval_s because serving replicas beat on wall time, not boosting rounds)
    ("fleet_heartbeat_timeout_s", 3.0, (), ((">", 0.0),)),   # serving-replica liveness: a replica silent past this is DEAD — evicted from the routing table, killed, respawned and re-warmed from the fleet manifest before it rejoins; staleness between ~2x fleet_heartbeat_interval_s and this marks it SUSPECT (deprioritized, not evicted)
    ("aot_store", "", (), ()),                      # disk-backed ahead-of-time executable store directory (ops/aot_store.py): serving predictors DESERIALIZE previously compiled bucket programs from it (zero XLA lowerings on warm) and persist fresh ones for later processes; "" = off for a standalone PredictionServer, while a FleetServer defaults its store to <workdir>/models/aot_store next to the fleet manifest and a ContinuousTrainer to <pipeline_workdir>/aot_store ("off" disables even those defaults); artifacts carry a backend/jax-version/device-topology fingerprint — stale or corrupt entries are evicted and rebuilt live, never loaded, and an unwritable path degrades to a warning (utils/paths.py probe)
    ("serving_autoscale", "off", (), ()),           # SLO-driven fleet elasticity: off|on (serving/fleet.py monitor): "on" lets watchtower breach/recover transitions on the serving SLOs (obs/slo.py serving_p99_ms / serving_error_rate over rollup windows) spawn replica slots up to serving_replicas_max under load and retire them back to serving_replicas_min after recovery — retirement drains the replica out of rotation first, so clients never see a failed request from a scale-down; enabling this without slo_config activates the serving SLOs at their default budgets
    ("serving_replicas_min", 0, (), ((">=", 0),)),  # autoscale floor on live replica slots (serving/fleet.py); 0 (default) = follow serving_replicas
    ("serving_replicas_max", 0, (), ((">=", 0),)),  # autoscale ceiling on live replica slots (serving/fleet.py); 0 (default) = follow serving_replicas; must be >= serving_replicas_min when both are explicit
]

# Reference-LightGBM parameters this port ACCEPTS but never reads: they
# exist so reference configs/sklearn kwargs parse cleanly, and their
# values change nothing on the jax/TPU execution path (no row/col-wise
# hist split, no CUDA device selection, no text-parser tuning; the
# DATASET_BINDING_PARAMS members below are still consulted *as names*
# for binding-change warnings, their values stay inert).  tpulint CFG202
# reads this literal: a key listed here is exempt from dead-key
# reporting, and gets re-flagged the moment code starts reading it (or
# if it leaves _PARAMS) so the list cannot rot.
_COMPAT_ONLY: Tuple[str, ...] = (
    "device_type",
    "num_threads",        # XLA owns threading; n_jobs accepted and dropped
    "saved_feature_importance_type",  # model-file importance not ported
    "force_col_wise", "force_row_wise",
    "feature_contri",
    "is_enable_sparse", "feature_pre_filter", "two_round", "ignore_column",
    "precise_float_parser", "parser_config_file",
    "predict_disable_shape_check",
    "time_out",
    "gpu_platform_id", "gpu_device_id", "gpu_use_dp", "num_gpu",
)

_CANONICAL: Dict[str, Any] = {name: default for name, default, _, _ in _PARAMS}
_ALIASES: Dict[str, str] = {}
for _name, _default, _aliases, _checks in _PARAMS:
    _ALIASES[_name] = _name
    for _a in _aliases:
        _ALIASES[_a] = _name
_CHECKS: Dict[str, Tuple[Tuple[str, float], ...]] = {
    name: checks for name, _, _, checks in _PARAMS if checks
}

# objective aliases resolved inside the objective string itself
# (reference config.cpp ParseObjectiveAlias)
_OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression", "l2": "regression",
    "mean_squared_error": "regression", "mse": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson", "quantile": "quantile",
    "mape": "mape", "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary", "binary_logloss": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank", "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg", "xe_ndcg": "rank_xendcg", "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}

_METRIC_ALIASES = {
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2", "regression": "l2",
    "regression_l2": "l2",
    "rmse": "rmse", "root_mean_squared_error": "rmse", "l2_root": "rmse",
    "quantile": "quantile", "huber": "huber", "fair": "fair", "poisson": "poisson",
    "mape": "mape", "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "gamma_deviance": "gamma_deviance", "tweedie": "tweedie",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "auc": "auc", "average_precision": "average_precision",
    "auc_mu": "auc_mu",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss", "ovr": "multi_logloss",
    "multi_error": "multi_error",
    "ndcg": "ndcg", "lambdarank": "ndcg", "rank_xendcg": "ndcg", "xendcg": "ndcg",
    "xe_ndcg": "ndcg", "xe_ndcg_mart": "ndcg", "xendcg_mart": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kullback_leibler", "kldiv": "kullback_leibler",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}


def resolve_objective_alias(name: str) -> str:
    return _OBJECTIVE_ALIASES.get(str(name).strip().lower(), str(name))


def resolve_metric_alias(name: str) -> str:
    return _METRIC_ALIASES.get(str(name).strip().lower(), str(name))


def normalize_params(params: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Resolve aliases first-wins into canonical names (application.cpp:79)."""
    out: Dict[str, Any] = {}
    if not params:
        return out
    for k, v in params.items():
        canon = _ALIASES.get(str(k).strip().lower())
        if canon is None:
            log.warning(f"Unknown parameter: {k}")
            continue
        if canon in out:
            log.warning(f"{k} is set={v}, {canon}={out[canon]} will be used. "
                        f"Current value: {canon}={out[canon]}")
            continue
        out[canon] = v
    return out


class Config:
    """Flat runtime config; attribute access for every canonical parameter."""

    def __init__(self, params: Optional[Dict[str, Any]] = None, **kwargs: Any):
        self._explicit: Dict[str, Any] = {}
        for name, default in _CANONICAL.items():
            object.__setattr__(self, name, default() if callable(default) else
                               (list(default) if isinstance(default, list) else default))
        merged = dict(params or {})
        merged.update(kwargs)
        self.set(merged)

    def set(self, params: Dict[str, Any]) -> "Config":
        canon = normalize_params(params)
        for name, value in canon.items():
            setattr(self, name, self._coerce(name, value))
            self._explicit[name] = getattr(self, name)
        self._post_process()
        return self

    def is_explicit(self, name: str) -> bool:
        return name in self._explicit

    @staticmethod
    def _coerce(name: str, value: Any) -> Any:
        default = _CANONICAL[name]
        try:
            if name == "seed":
                return None if value is None else int(value)
            if name == "rank_query_buckets":
                # str default ("auto") but list values are legal — keep
                # them as int lists instead of stringifying
                if isinstance(value, str) and \
                        value.strip().lower() in ("", "auto"):
                    return "auto"
                return _parse_int_list(value)
            if isinstance(default, bool):
                v: Any = _parse_bool(value)
            elif isinstance(default, int):
                v = int(float(value)) if not isinstance(value, int) else value
            elif isinstance(default, float):
                v = float(value)
            elif isinstance(default, list):
                if default and isinstance(default[0], int) or name in (
                        "eval_at", "max_bin_by_feature", "monotone_constraints"):
                    v = _parse_int_list(value)
                elif name in ("label_gain", "feature_contri", "auc_mu_weights",
                              "cegb_penalty_feature_lazy", "cegb_penalty_feature_coupled"):
                    v = _parse_float_list(value)
                else:
                    v = _parse_str_list(value)
            else:
                v = str(value)
        except (TypeError, ValueError) as e:
            log.fatal(f"Failed to parse parameter {name}={value!r}: {e}")
        for op, bound in _CHECKS.get(name, ()):
            ok = {"<": v < bound, "<=": v <= bound, ">": v > bound, ">=": v >= bound}[op]
            if not ok:
                log.fatal(f"Check failed: {name} {op} {bound}, got {v}")
        return v

    def _post_process(self) -> None:
        # resolve objective-style aliases
        self.objective = resolve_objective_alias(self.objective)
        self.boosting ={"gbdt": "gbdt", "gbrt": "gbdt", "dart": "dart",
                         "rf": "rf", "random_forest": "rf",
                         "goss": "gbdt"}.get(str(self.boosting).lower(), self.boosting)
        # reference: `boosting=goss` is sugar for data_sample_strategy=goss
        if str(self._explicit.get("boosting", "")).lower() == "goss":
            self.data_sample_strategy = "goss"
        if isinstance(self.metric, str):
            self.metric = _parse_str_list(self.metric)
        self.metric = [resolve_metric_alias(m) for m in self.metric]
        self.check_param_conflict()
        log.set_verbosity(self.verbosity)

    def check_param_conflict(self) -> None:
        """Mirror of reference Config::CheckParamConflict (config.cpp)."""
        if self.is_explicit("bagging_freq") and self.bagging_freq > 0 and \
                self.bagging_fraction >= 1.0 and not self.is_explicit("bagging_fraction") \
                and self.data_sample_strategy != "goss":
            pass  # bagging_freq without fraction is a no-op; keep silently like ref
        if self.boosting == "rf":
            if self.bagging_freq <= 0 or self.bagging_fraction >= 1.0 or \
                    self.bagging_fraction <= 0.0:
                log.warning("RF requires bagging; setting bagging_fraction=0.9, "
                            "bagging_freq=1")
                if self.bagging_freq <= 0:
                    self.bagging_freq = 1
                if not (0.0 < self.bagging_fraction < 1.0):
                    self.bagging_fraction = 0.9
        if self.objective in ("multiclass", "multiclassova") and self.num_class <= 1:
            log.fatal("Number of classes should be specified and greater than 1 "
                      "for multiclass training")
        if self.objective not in ("multiclass", "multiclassova", "none") and \
                self.num_class != 1:
            log.fatal("Number of classes must be 1 for non-multiclass training")
        if self.objective in ("lambdarank", "rank_xendcg") and \
                self.lambdarank_truncation_level <= 0:
            log.fatal("lambdarank_truncation_level must be positive")
        self.nan_policy = str(self.nan_policy or "none").strip().lower()
        if self.nan_policy not in ("none", "raise", "skip_round",
                                   "halt_and_keep_best"):
            log.fatal(f"unknown nan_policy={self.nan_policy!r} (expected "
                      "none/raise/skip_round/halt_and_keep_best)")
        self.predict_bucketing = str(self.predict_bucketing or "on") \
            .strip().lower()
        if self.predict_bucketing not in ("on", "off"):
            log.fatal(f"unknown predict_bucketing={self.predict_bucketing!r} "
                      "(expected on/off)")
        self.elastic = str(self.elastic or "off").strip().lower()
        if self.elastic not in ("on", "off"):
            log.fatal(f"unknown elastic={self.elastic!r} (expected on/off)")
        self.anomaly_detection = \
            str(self.anomaly_detection or "off").strip().lower()
        if self.anomaly_detection not in ("on", "off"):
            log.fatal(f"unknown anomaly_detection="
                      f"{self.anomaly_detection!r} (expected on/off)")
        if str(self.slo_config or "").strip():
            from .obs.slo import parse_slo_config
            try:
                parse_slo_config(self.slo_config)
            except ValueError as e:
                log.fatal(f"invalid slo_config={self.slo_config!r}: {e}")
        self.request_trace = \
            str(self.request_trace or "off").strip().lower()
        from .obs.reqtrace import parse_request_trace
        try:
            parse_request_trace(self.request_trace)
        except ValueError as e:
            log.fatal(f"invalid request_trace={self.request_trace!r}: {e}")
        if float(self.heartbeat_timeout_s) < float(self.heartbeat_interval_s):
            log.fatal(
                f"heartbeat_timeout_s={self.heartbeat_timeout_s} must be >= "
                f"heartbeat_interval_s={self.heartbeat_interval_s} (a worker "
                "cannot be declared dead faster than it is expected to "
                "publish)")
        if float(self.fleet_heartbeat_timeout_s) < \
                float(self.fleet_heartbeat_interval_s):
            log.fatal(
                f"fleet_heartbeat_timeout_s={self.fleet_heartbeat_timeout_s} "
                f"must be >= fleet_heartbeat_interval_s="
                f"{self.fleet_heartbeat_interval_s} (a replica cannot be "
                "declared dead faster than it is expected to beat)")
        self.serving_autoscale = \
            str(self.serving_autoscale or "off").strip().lower()
        if self.serving_autoscale not in ("on", "off"):
            log.fatal(f"unknown serving_autoscale="
                      f"{self.serving_autoscale!r} (expected on/off)")
        if int(self.serving_replicas_min) > 0 and \
                int(self.serving_replicas_max) > 0 and \
                int(self.serving_replicas_min) > \
                int(self.serving_replicas_max):
            log.fatal(
                f"serving_replicas_min={self.serving_replicas_min} must "
                f"be <= serving_replicas_max="
                f"{self.serving_replicas_max} (the autoscale floor "
                "cannot exceed the ceiling)")
        if not self.serving_buckets or \
                any(int(b) <= 0 for b in self.serving_buckets):
            log.fatal(f"serving_buckets must be a non-empty list of positive "
                      f"row counts, got {self.serving_buckets!r}")
        self.serving_buckets = sorted({int(b) for b in self.serving_buckets})
        rqb = self.rank_query_buckets
        if isinstance(rqb, str):
            rqb = rqb.strip().lower() or "auto"
            if rqb != "auto":
                try:
                    rqb = _parse_int_list(rqb)
                except (TypeError, ValueError):
                    log.fatal(f"unknown rank_query_buckets="
                              f"{self.rank_query_buckets!r} (expected "
                              "\"auto\" or a list of positive doc counts)")
        if isinstance(rqb, (list, tuple)):
            if not rqb or any(int(b) <= 0 for b in rqb):
                log.fatal(f"rank_query_buckets must be \"auto\" or a "
                          f"non-empty list of positive doc counts, got "
                          f"{self.rank_query_buckets!r}")
            rqb = sorted({int(b) for b in rqb})
        self.rank_query_buckets = rqb
        # max_depth implies a num_leaves cap when num_leaves not explicit
        if self.max_depth > 0 and not self.is_explicit("num_leaves"):
            full = 1 << min(self.max_depth, 30)
            self.num_leaves = min(self.num_leaves, full)

    def to_dict(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name in _CANONICAL}

    def __repr__(self) -> str:
        keys = sorted(self._explicit)
        inner = ", ".join(f"{k}={getattr(self, k)!r}" for k in keys)
        return f"Config({inner})"


ParamsLike = Union[Dict[str, Any], Config, None]


def as_config(params: ParamsLike) -> Config:
    if isinstance(params, Config):
        return params
    return Config(params or {})


def generate_parameter_docs() -> str:
    """Render docs/Parameters.md from the ``_PARAMS`` registry.

    The registry is the single source of truth for names, aliases, defaults
    and checks; the docs file is generated from it and CI-enforced to stay
    in sync (reference: .ci/parameter-generator.py renders
    docs/Parameters.rst from config.h structured comments, checked by
    .ci/test.sh:155-158).  Regenerate with
    ``python -m lightgbm_tpu.config``.
    """
    lines = [
        "# Parameters",
        "",
        "Generated from `lightgbm_tpu/config.py` `_PARAMS` — do not edit by",
        "hand; run `python -m lightgbm_tpu.config` after changing the",
        "registry (a test asserts this file is in sync).",
        "",
        "Alias resolution is first-wins per canonical name; values accept",
        "strings or typed values; constraints are enforced at `Config()`",
        "construction.",
        "",
        "| Parameter | Default | Aliases | Constraints |",
        "|---|---|---|---|",
    ]
    for name, default, aliases, checks in _PARAMS:
        d = repr(default) if default != "" else "`\"\"`"
        a = ", ".join(aliases) if aliases else "—"
        c = ", ".join(f"{op} {val:g}" for op, val in checks) if checks \
            else "—"
        lines.append(f"| `{name}` | {d} | {a} | {c} |")
    lines += [
        "",
        "## Objective aliases",
        "",
        "| Alias | Objective |",
        "|---|---|",
    ]
    for alias in sorted(_OBJECTIVE_ALIASES):
        lines.append(f"| `{alias}` | `{_OBJECTIVE_ALIASES[alias]}` |")
    lines.append("")
    return "\n".join(lines)


if __name__ == "__main__":  # pragma: no cover
    import pathlib
    out = pathlib.Path(__file__).resolve().parent.parent / "docs" / \
        "Parameters.md"
    out.write_text(generate_parameter_docs())
    print(f"wrote {out}")
