"""GBDT boosting driver.

TPU-native re-design of the reference boosting layer (reference:
src/boosting/gbdt.cpp — ``Init`` :53, ``TrainOneIter`` :344-452,
``Boosting()`` gradient step :220, score updating, boost-from-average
:308-342, train continuation).  One iteration = gradients (jitted XLA on
device, the CUDA-objective "boosting_on_gpu" path gbdt.cpp:104) → sampling
mask → one ``grow_tree`` per class (whole tree inside one jit) → shrinkage →
score update.  The train-score update is a pure gather through the returned
``leaf_of_row`` (the reference's DataPartition shortcut,
score_updater.hpp:21); valid scores update via the frontier traversal in
models/predict.py.

Boost-from-average folds the initial score into the first iteration's trees
via ``AddBias`` exactly like gbdt.cpp:404-420 (shrinkage first, bias after),
so saved models are self-contained.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from ..callback import EarlyStopException
from ..config import Config
from ..io.dataset import Dataset
from ..learner.grower import CegbInput, DeviceBundle, TreeArrays, grow_tree
from ..learner.linear import fit_linear_leaves, linear_leaf_scores
from ..metrics import Metric, create_metrics
from ..models.predict import predict_bins_leaf, predict_bins_tree
from ..models.tree import Tree
from ..objectives import ObjectiveFunction, create_objective
from ..ops.compile_cache import get_or_build as cc_get_or_build, sig as cc_sig
from ..ops.quantize import (discretize_gradients_levels,
                            renew_leaf_values)
from ..ops.split import SplitHyper
from ..obs import count_event, trace as obs_trace
from ..obs.metrics import MetricsRegistry
from ..utils import log
from ..utils.timer import PhaseTimer, global_timer, phase
from .sample_strategy import create_sample_strategy
from ..ops.table import take_small_table

GradFn = Callable[[np.ndarray, Any], Tuple[np.ndarray, np.ndarray]]


def _resolve_hist_dtype(cfg: Config) -> str:
    """Histogram contraction dtype with validity gating.

    ``deterministic=true`` pins exact float32.  ``int8`` (the v5e int8
    MXU path, ~1.6x the bf16 rate) is only meaningful when grad/hess
    carry small-integer quantized levels — real-valued gradients would be
    truncated — so without ``use_quantized_grad`` (or with a level count
    that cannot fit int8) it degrades to bfloat16 with a warning."""
    if cfg.deterministic:
        return "float32"
    dt = str(cfg.tpu_hist_dtype)
    if dt == "int8":
        if not bool(cfg.use_quantized_grad):
            log.warning("tpu_hist_dtype=int8 requires use_quantized_grad="
                        "true (integer gradient levels); using bfloat16")
            return "bfloat16"
        if int(cfg.num_grad_quant_bins) > 127:
            log.warning("tpu_hist_dtype=int8 needs num_grad_quant_bins "
                        "<= 127; using bfloat16")
            return "bfloat16"
    return dt


def _resolve_hist_kernel_cfg(cfg: Config) -> str:
    """Histogram-build formulation (ops/histogram.py HIST_KERNELS).  All
    modes are bit-identical, so no validity gating beyond the name check
    — the dispatcher itself falls back to the flat kernel where a
    forced mode's shape constraints don't hold."""
    from ..ops.histogram import resolve_hist_kernel
    return resolve_hist_kernel(cfg.hist_kernel)


def _hp_from_config(cfg: Config, n_bins: int) -> SplitHyper:
    return SplitHyper(
        num_leaves=max(2, int(cfg.num_leaves)),
        max_depth=int(cfg.max_depth),
        lambda_l1=float(cfg.lambda_l1),
        lambda_l2=float(cfg.lambda_l2),
        min_data_in_leaf=int(cfg.min_data_in_leaf),
        min_sum_hessian_in_leaf=float(cfg.min_sum_hessian_in_leaf),
        min_gain_to_split=float(cfg.min_gain_to_split),
        max_delta_step=float(cfg.max_delta_step),
        cat_l2=float(cfg.cat_l2),
        cat_smooth=float(cfg.cat_smooth),
        max_cat_threshold=int(cfg.max_cat_threshold),
        max_cat_to_onehot=int(cfg.max_cat_to_onehot),
        min_data_per_group=int(cfg.min_data_per_group),
        n_bins=n_bins,
        rows_per_block=int(cfg.tpu_rows_per_block),
        path_smooth=float(cfg.path_smooth),
        # deterministic=true pins the exact-parity contraction regardless of
        # the user's tpu_hist_dtype (ADVICE r1: bfloat16 silently broke the
        # deterministic contract)
        hist_dtype=_resolve_hist_dtype(cfg),
        hist_kernel=_resolve_hist_kernel_cfg(cfg),
        leaf_hist=str(cfg.tpu_leaf_hist),
        extra_trees=bool(cfg.extra_trees),
        feature_fraction_bynode=float(cfg.feature_fraction_bynode),
    )


def _parse_forced_splits(filename: str, dataset: Dataset, num_leaves: int):
    """forcedsplits_filename JSON -> (leaf, feature, bin_thr) i32 arrays in
    BFS order (reference serial_tree_learner.cpp:620 ForceSplits BFS; node
    format {"feature": orig_idx, "threshold": value, "left": ..., "right":
    ...}).  Leaf numbering matches the grower: at BFS step i the right child
    becomes leaf i+1."""
    import json
    with open(filename) as fh:
        root = json.load(fh)
    if not root:
        return None
    orig_to_packed = {int(o): p for p, o in enumerate(dataset.used_feature_idx)}
    K = num_leaves - 1
    f_leaf = np.full(K, -1, np.int32)
    f_feat = np.zeros(K, np.int32)
    f_thr = np.zeros(K, np.int32)
    queue = [(root, 0)]
    i = 0
    while queue and i < K:
        node, leaf = queue.pop(0)
        p = orig_to_packed.get(int(node["feature"]))
        if p is None:
            log.warning("forced split on unused feature %s ignored; "
                        "aborting remaining forced splits" % node["feature"])
            break
        mapper = dataset.mappers[int(node["feature"])]
        thr_bin = int(mapper.values_to_bins(
            np.array([float(node["threshold"])], np.float64))[0])
        f_leaf[i], f_feat[i], f_thr[i] = leaf, p, thr_bin
        if node.get("left"):
            queue.append((node["left"], leaf))
        if node.get("right"):
            queue.append((node["right"], i + 1))
        i += 1
    if i == 0:
        return None
    return (jnp.asarray(f_leaf), jnp.asarray(f_feat), jnp.asarray(f_thr))


def _parse_interaction_sets(spec, used_feature_idx) -> Optional[np.ndarray]:
    """interaction_constraints "[0,1,2],[2,3]" -> bool [S, F_packed]
    (reference config interaction_constraints_vector; col_sampler.hpp)."""
    if not spec:
        return None
    if isinstance(spec, str):
        import json
        sets = json.loads("[" + spec + "]")
    else:
        sets = [list(s) for s in spec]
    if not sets:
        return None
    orig_to_packed = {int(o): p for p, o in enumerate(used_feature_idx)}
    out = np.zeros((len(sets), len(used_feature_idx)), bool)
    for si, s in enumerate(sets):
        for f in s:
            p = orig_to_packed.get(int(f))
            if p is not None:
                out[si, p] = True
    return out


class GBDT:
    """Training driver (reference gbdt.h/gbdt.cpp ``GBDT``)."""

    def __init__(self, config: Config, train_set: Dataset,
                 objective: Optional[ObjectiveFunction] = None,
                 metrics: Optional[List[Metric]] = None):
        self.config = config
        self.train_set = train_set
        self.objective = objective if objective is not None else \
            create_objective(config)
        if self.objective is not None:
            self.objective.init(train_set.metadata, train_set.num_data)
        self.train_metrics = metrics if metrics is not None else \
            create_metrics(config)
        for m in self.train_metrics:
            m.init(train_set.metadata, train_set.num_data)

        # reference USE_TIMETAG phase table (utils/common.h Timer).  Each
        # booster owns its OWN accumulator so concurrently alive boosters
        # never clobber each other's tables; the process-global timer
        # remains the CLI default and is managed through the enable/
        # disable API (set unconditionally so a later non-verbose run
        # disables it again, and reset so its table covers only the most
        # recent training run).
        self.timer = PhaseTimer()
        self.metrics = MetricsRegistry()
        if self.objective is not None:
            # objective.init ran before this registry existed; attach it
            # now so rank compile-cache bumps land dual-scope and the
            # bucket-plan gauges mirror into Booster.telemetry()
            self.objective.attach_booster_metrics(self.metrics)
        #: the training-side watchtower (rollups + SLOs + anomaly
        #: detection) — attached by engine.train() only when slo_config/
        #: anomaly_detection is configured; None is the all-off default
        self.watchtower = None
        want_timing = (int(config.verbosity) >= 2
                       or bool(str(config.trace_output or ""))
                       or bool(str(config.telemetry_output or "")))
        if want_timing:
            self.timer.enable()
        if int(config.verbosity) >= 2:
            global_timer.enable()
        else:
            global_timer.disable()
        global_timer.reset()
        self.num_class = max(1, int(config.num_class))
        self.num_tree_per_iteration = (
            self.objective.num_model_per_iteration
            if self.objective is not None else self.num_class)
        self.shrinkage_rate = float(config.learning_rate)
        self.models: List[Tree] = []          # iter-major, one per class
        self.iter_ = 0
        self.num_init_iteration = 0
        self.best_iteration = -1

        # device operands
        self.bins = jnp.asarray(train_set.bins)
        self.num_bins_arr = jnp.asarray(train_set.num_bins_array())
        self.nan_bin_arr = jnp.asarray(train_set.nan_bin_array())
        self.is_cat_arr = jnp.asarray(train_set.categorical_array())
        self.num_features = train_set.num_features
        ba = train_set.device_bundle_arrays()
        self.bundle = None if ba is None else \
            DeviceBundle(*(jnp.asarray(a) for a in ba))

        # distributed tree learner over all visible devices
        # (reference tree_learner=serial/data/feature/voting,
        # tree_learner.cpp:15-57; here = shard_map over a device mesh)
        self.parallel_mode: Optional[str] = None
        self.mesh = None
        self._pad_rows = 0
        self._pad_cols = 0
        tl = {"data_parallel": "data", "voting_parallel": "voting",
              "feature_parallel": "feature",
              "gspmd": "data_gspmd"}.get(str(config.tree_learner),
                                         str(config.tree_learner))
        # active_devices(), not jax.devices(): after an elastic eviction
        # (robustness/elastic.py) the survivor window restricts every
        # fresh mesh — a resumed booster re-pads and re-shards its rows
        # over the reduced set through this one site
        from ..parallel.mesh import active_devices
        n_dev = len(active_devices())
        if tl in ("data", "voting", "feature", "data_gspmd") and n_dev > 1:
            from jax.sharding import Mesh
            from ..parallel.feature_parallel import FEATURE_AXIS
            from ..parallel.mesh import DATA_AXIS
            axis = FEATURE_AXIS if tl == "feature" else DATA_AXIS
            self.mesh = Mesh(np.array(active_devices()), (axis,))
            self.parallel_mode = tl
            if tl == "feature":
                if self.bundle is not None:
                    log.fatal("tree_learner=feature is incompatible with "
                              "enable_bundle=true (set enable_bundle=false)")
                if bool(config.use_quantized_grad):
                    log.fatal("use_quantized_grad does not compose with "
                              "tree_learner=feature (no level-scale "
                              "plumbing in that mode)")
                # unsupported-feature conflicts fail loudly (reference
                # CheckParamConflict style) instead of silently dropping
                if any(int(m) != 0 for m in (config.monotone_constraints
                                             or [])):
                    log.fatal("tree_learner=feature does not support "
                              "monotone_constraints")
                if config.forcedsplits_filename:
                    log.fatal("tree_learner=feature does not support "
                              "forcedsplits_filename")
                if config.interaction_constraints:
                    log.fatal("tree_learner=feature does not support "
                              "interaction_constraints")
                if bool(config.extra_trees) or \
                        float(config.feature_fraction_bynode) < 1.0:
                    log.warning("extra_trees/feature_fraction_bynode under "
                                "tree_learner=feature sample per feature "
                                "shard, not globally")
                # pad feature columns so F divides the mesh (trivial
                # single-bin columns can never be chosen for a split)
                pad_f = (-self.bins.shape[1]) % n_dev
                self._pad_cols = pad_f
                if pad_f:
                    self.bins = jnp.pad(self.bins, ((0, 0), (0, pad_f)))
                    self.num_bins_arr = jnp.pad(self.num_bins_arr,
                                                (0, pad_f),
                                                constant_values=1)
                    self.nan_bin_arr = jnp.pad(self.nan_bin_arr, (0, pad_f),
                                               constant_values=-1)
                    self.is_cat_arr = jnp.pad(self.is_cat_arr, (0, pad_f))
            elif tl == "data_gspmd":
                # GSPMD: no explicit shard_map — the ordinary serial code
                # paths run over row-sharded arrays and XLA's partitioner
                # inserts the collectives (parallel/gspmd.py).  No row
                # padding and no per-mode grower dispatch; when n does
                # not divide the mesh, placement falls back to
                # replicated (device_put refuses uneven shards) and the
                # program runs unpartitioned but correct.
                if train_set.num_data % n_dev:
                    log.warning(
                        f"tree_learner=data_gspmd: {train_set.num_data} "
                        f"rows do not divide the {n_dev}-device mesh; "
                        "arrays stay replicated (unpartitioned). Use "
                        "tree_learner=data for padded sharding of "
                        "uneven row counts.")
                self.bins = self._place_rows(self.bins)
            else:
                # pad rows so n divides the mesh (padded rows masked out)
                self._pad_rows = (-train_set.num_data) % n_dev
                if self._pad_rows:
                    self.bins = jnp.pad(self.bins,
                                        ((0, self._pad_rows), (0, 0)))
        elif tl not in ("serial",):
            log.warning(f"tree_learner={tl} requested but only {n_dev} "
                        "device(s) visible; using serial")

        # linear leaves (linear_tree=true): raw feature values on device
        # (reference LinearTreeLearner keeps Dataset raw_data_)
        self.linear = bool(config.linear_tree) and train_set.raw is not None
        self.raw_dev = jnp.asarray(train_set.raw) if self.linear else None
        self._valid_raw: List[Optional[jnp.ndarray]] = []

        # hp + constraint arrays, shared with reset_config (ADVICE r3: the
        # reference's GBDT::ResetConfig re-derives these too)
        self._derive_learner_state(config)

        n = train_set.num_data
        k = self.num_tree_per_iteration
        self.scores = self._place_rows(jnp.zeros((n, k), jnp.float32))
        self.init_scores = np.zeros(k)
        self._init_base_score()

        self.sample_strategy = create_sample_strategy(config, n)
        self._rng = np.random.default_rng(
            config.seed if config.seed is not None else config.data_random_seed)

        # validation sets
        self.valid_sets: List[Dataset] = []
        self.valid_names: List[str] = []
        self.valid_scores: List[jnp.ndarray] = []
        self.valid_metrics: List[List[Metric]] = []
        self._valid_bins: List[jnp.ndarray] = []
        self._valid_bins_t: List[Optional[jnp.ndarray]] = []

    # ------------------------------------------------------------- helpers
    def _phase(self, name: str):
        """Time one phase into this booster's table, the process-global
        table AND the active trace (utils/timer.py ``phase``)."""
        return phase(name, self.timer, global_timer)

    def _count(self, name: str, value: float = 1) -> None:
        """Bump a telemetry counter in this booster's registry and the
        process-global one (obs/metrics.py)."""
        count_event(name, value, self.metrics)

    def _place_rows(self, x):
        """Under ``tree_learner=data_gspmd``, place ``x`` with dim 0
        sharded over the data mesh (the GSPMD partitioner keys off input
        shardings — parallel/gspmd.py); identity in every other mode."""
        if self.parallel_mode == "data_gspmd" and self.mesh is not None \
                and x is not None:
            from ..parallel.gspmd import row_sharded
            return row_sharded(self.mesh, x)
        return x

    def _config_signature(self):
        """Canonical-config signature for process compile-cache keys:
        every registered parameter's repr, sorted.  Conservatively
        over-keyed — any config difference forces a fresh cache entry,
        which is always correct: the fused runner closes over booster
        state derived from (config, datasets) only, and the datasets
        enter the key as anchors (ops/compile_cache.py)."""
        from ..config import _CANONICAL
        c = self.config
        return tuple((name, repr(getattr(c, name, None)))
                     for name in sorted(_CANONICAL))

    def _hist_rounds_per_tree(self) -> int:
        """Analytic histogram-pass count one grown tree costs: the strict
        leaf-wise learner runs one build+split-find pass per split, the
        batched grower one per K-split round.  A host-side tally — the
        passes themselves run inside jit where counting would record
        compilations, not executions."""
        splits = max(1, self.hp.num_leaves - 1)
        if self._use_batched_grower():
            k = max(1, int(self.config.tpu_split_batch))
            return -(-splits // k)
        return splits

    def _collective_bytes_per_tree(self) -> int:
        """Analytic estimate of the bytes all-reduced growing ONE tree in
        the active parallel mode (psums run inside jit; XLA's actual
        schedule may reduce-scatter, so this is the logical payload, not
        wire traffic).  Per histogram pass: data mode psums the full
        [F, B, 3] f32 histogram; voting psums each shard's 2·top_k voted
        [B, 3] slices per split; feature mode all-gathers a 12-float
        SplitInfo per device plus one [n] partition psum per split."""
        if self.parallel_mode is None or self.mesh is None:
            return 0
        splits = max(1, self.hp.num_leaves - 1)
        rounds = self._hist_rounds_per_tree()
        B = self.hp.n_bins
        F = self.bins.shape[1]
        if self.parallel_mode in ("data", "data_gspmd"):
            # data_gspmd reduces the same logical histogram payload; the
            # partitioner, not shard_map, chooses the wire schedule
            return rounds * F * B * 3 * 4
        if self.parallel_mode == "voting":
            return splits * 2 * int(self.config.top_k) * B * 3 * 4
        if self.parallel_mode == "feature":
            n_dev = int(self.mesh.devices.size)
            return splits * (n_dev * 12 * 4 + self.bins.shape[0] * 4)
        return 0

    def telemetry(self) -> Dict[str, Any]:
        """This booster's telemetry snapshot: counters/gauges, the phase
        table, and a current memory sample (surfaced publicly as
        ``Booster.telemetry()``)."""
        from ..obs import memory as obs_memory
        snap = self.metrics.snapshot()
        return {"counters": snap["counters"], "gauges": snap["gauges"],
                "phases": self.timer.as_dict(),
                "memory": obs_memory.memory_snapshot()}

    def prometheus_text(self) -> str:
        """Training-side Prometheus exposition (obs/prom.py): telemetry
        counters/gauges, the watchtower's latest rollup gauges, and SLO
        state — the same format the serving tier scrapes, so one
        dashboard covers both halves."""
        from ..obs import prom
        snap = self.metrics.snapshot()
        rollup_gauges = None
        slo_state = None
        tower = self.watchtower
        if tower is not None:
            rollup_gauges = tower.rollup.latest_gauges()
            slo_state = tower.slo_state()
        return prom.training_text(snap["counters"], snap["gauges"],
                                  rollup_gauges, slo_state)

    def _resolve_auto_params(self, config: Config) -> None:
        """Fast-by-default policy (VERDICT r3 #3): at scale, a plain
        ``train()`` gets the batched grower and the exact quantized-grad
        bf16 kernel path without opting in — the same configuration the
        bench runs.  Decision-identity of that path vs the f32 kernel is
        proven (ops/quantize.py, tests/test_quantized.py); leaf values are
        renewed from true gradients.  Small runs keep the exact-f32 strict
        path: there the extra kernel compilations dominate and exactness
        is free.  Any explicit user setting, ``deterministic=true``,
        feature-parallel (no level-scale plumbing) win over the whole
        policy; linear trees opt out of the int8 half only (ridge fits
        need true gradients) and DO get the auto split batch."""
        at_scale = self.train_set.num_data >= 100_000
        # only auto-batch configurations the batched grower supports
        # (linear trees, CEGB and advanced monotone joined in round 4;
        # advanced-under-voting is downgraded to intermediate before
        # growth, so no monotone config blocks batching)
        # voting x categorical joined the batched grower in round 5 (the
        # winner's histogram column psums for the bitset)
        batchable = self.parallel_mode in (None, "data", "voting")
        if not config.is_explicit("tpu_split_batch"):
            if at_scale and batchable and int(config.num_leaves) >= 8:
                # 42: the flat kernel's 3K=126 channels still fit one MXU
                # tile and fewer rounds beat finer width-matching
                # (round-4 int8 sweep: K=28 83.2, K=42 76.9 ms/tree)
                config.tpu_split_batch = min(42, int(config.num_leaves) - 1)
        if (at_scale and not config.deterministic
                and self.parallel_mode != "feature"
                and not bool(config.linear_tree)
                and not config.is_explicit("tpu_hist_dtype")
                and not config.is_explicit("use_quantized_grad")):
            # int8: quantized levels on the int8 MXU path — exact like
            # the bf16-levels mode and ~12% faster end-to-end (round-4
            # sweep: 82 vs 93 ms/tree); off-TPU both fall back to the
            # exact f32 XLA contraction, so the choice is TPU-only
            config.tpu_hist_dtype = "int8"
            config.use_quantized_grad = True
            if not config.is_explicit("quant_train_renew_leaf"):
                config.quant_train_renew_leaf = True
            log.info("auto speed mode: tpu_split_batch=%d, exact "
                     "quantized-grad int8 kernels (set "
                     "tpu_hist_dtype=float32 or deterministic=true to "
                     "opt out)" % int(config.tpu_split_batch))

    def _derive_learner_state(self, config: Config) -> None:
        """Derive ``hp`` and the constraint/penalty device arrays from a
        config.  Called from ``__init__`` AND ``reset_config`` so a
        parameter reset re-applies the histogram-pool translation and
        refreshes monotone/interaction/forced/CEGB arrays exactly like the
        reference's ``GBDT::ResetConfig`` -> ``TreeLearner::ResetConfig``
        (gbdt.cpp, serial_tree_learner.cpp).  Requires ``parallel_mode``
        and the device bins to be set already."""
        train_set = self.train_set
        self._fused_cache = {}   # compiled fused-round runners (train_fused)
        self._batched_decision = None   # memoized _use_batched_grower
        self._collective_probed = False  # one-shot obs/collective probe
        # numeric guard policy (robustness/guards.py); validated by
        # Config.check_param_conflict, re-derived on reset_config
        self.nan_policy = str(config.nan_policy or "none")
        # collective_overlap (ISSUE 7): "on" forces the chunked
        # overlapped-psum schedule, "off" the single blocking psum,
        # "auto" engages it exactly where the explicit shard_map modes
        # issue per-round collectives the scheduler can hide.  The GSPMD
        # mode ignores it (the partitioner owns the schedule), and
        # LGBMTPU_NO_OVERLAP kills it at trace time either way
        # (ops/histogram.py reduce_hist).
        ov = str(config.collective_overlap or "auto")
        if ov not in ("auto", "on", "off"):
            log.warning("collective_overlap=%r not one of auto/on/off; "
                        "using 'auto'" % ov)
            ov = "auto"
        self._overlap = (ov == "on") or (
            ov == "auto" and self.parallel_mode in ("data", "voting"))
        self._resolve_auto_params(config)
        self.hp = _hp_from_config(config, train_set.device_n_bins())
        if bool(train_set.categorical_array().any()):
            self.hp = dataclasses.replace(self.hp, has_categorical=True)

        # monotone constraints: per-ORIGINAL-feature directions from config,
        # remapped to packed (used) features; categorical features forced 0
        self.monotone_arr = None
        mono_cfg = list(config.monotone_constraints or [])
        if any(int(m) != 0 for m in mono_cfg):
            full = np.zeros(train_set.num_total_features, np.int32)
            full[:len(mono_cfg)] = np.asarray(mono_cfg, np.int32)[
                :train_set.num_total_features]
            packed = full[np.asarray(train_set.used_feature_idx)]
            packed[np.asarray(train_set.categorical_array())] = 0
            self.monotone_arr = jnp.asarray(packed)
            method = str(config.monotone_constraints_method)
            if method not in ("basic", "intermediate", "advanced"):
                log.fatal("unknown monotone_constraints_method=%r (expected "
                          "basic/intermediate/advanced)" % method)
            if method == "advanced" and self.parallel_mode in ("voting",
                                                               "feature"):
                # the per-threshold bound arrays are not plumbed through the
                # voted-subset / cross-shard split sync; intermediate is the
                # sound conservative superset there
                log.warning("monotone_constraints_method=advanced is not "
                            "supported with voting/feature parallel modes; "
                            "using 'intermediate'")
                method = "intermediate"
            self.hp = dataclasses.replace(
                self.hp, use_monotone=True, monotone_method=method,
                monotone_penalty=float(config.monotone_penalty))

        isets = _parse_interaction_sets(config.interaction_constraints,
                                        train_set.used_feature_idx)
        self.interaction_sets = None if isets is None else jnp.asarray(isets)
        self._needs_node_rng = (self.hp.extra_trees
                                or self.hp.feature_fraction_bynode < 1.0)
        self.forced_splits = None
        if config.forcedsplits_filename:
            self.forced_splits = _parse_forced_splits(
                config.forcedsplits_filename, train_set, self.hp.num_leaves)

        # CEGB penalties (cost_effective_gradient_boosting.hpp): acquisition
        # state persists across ALL trees like the reference learner's (and
        # resets on reset_config, like its ResetConfig recreating CEGB)
        self.cegb: Optional[CegbInput] = None
        if (float(config.cegb_penalty_split) > 0.0
                or list(config.cegb_penalty_feature_lazy or [])
                or list(config.cegb_penalty_feature_coupled or [])):
            if self.parallel_mode is not None:
                log.fatal("cegb_* penalties are supported with "
                          "tree_learner=serial only")
            tr = float(config.cegb_tradeoff)

            def _vec(lst):
                full = np.zeros(train_set.num_total_features, np.float64)
                a = np.asarray(list(lst or []), np.float64)
                full[:len(a)] = a[:train_set.num_total_features]
                return full[np.asarray(train_set.used_feature_idx)] * tr

            lazy = _vec(config.cegb_penalty_feature_lazy)
            self.cegb = CegbInput(
                split_pen=jnp.float32(tr * float(config.cegb_penalty_split)),
                coupled_pen=jnp.asarray(
                    _vec(config.cegb_penalty_feature_coupled), jnp.float32),
                lazy_pen=jnp.asarray(lazy, jnp.float32),
                feature_used=jnp.zeros(self.num_features, bool),
                used_rows=jnp.zeros((train_set.num_data, self.num_features),
                                    bool) if (lazy != 0).any() else None)

        # bounded histogram pool (reference histogram_pool_size MB,
        # serial_tree_learner.cpp:36-47): translate the MB budget into
        # batched-grower pool slots; evicted parents re-histogram both
        # children directly (learner/batch_grower.py).  Composes with
        # categorical splits (cached winner bitsets) and with the strict
        # order via a batch=1 batched-grower route (_use_batched_grower);
        # derived LAST so the strict-only feature checks see final state.
        pool_mb = float(config.histogram_pool_size)
        n_cols = train_set.bins.shape[1]
        bytes_per_leaf = n_cols * self.hp.n_bins * 4 * 4
        full_state = bytes_per_leaf * self.hp.num_leaves
        if pool_mb <= 0 and not config.is_explicit("histogram_pool_size") \
                and full_state > (4 << 30) and self.parallel_mode is None:
            # wide-data guard: the reference's default (-1) keeps every
            # leaf's histogram resident, but [L, F, B, 4] f32 on an
            # Allstate-wide bundled matrix can exceed HBM before the
            # first tree finishes; cap the resident state at ~1 GB unless
            # the user explicitly asked for unlimited
            pool_mb = 1024.0
            log.info("histogram state would be %.1f GB; engaging the "
                     "bounded pool at 1 GB (set histogram_pool_size=-1 "
                     "to keep all leaves resident)"
                     % (full_state / (1 << 30)))
        if pool_mb > 0:
            slots = int(pool_mb * (1 << 20) // max(bytes_per_leaf, 1))
            kbatch = max(1, int(config.tpu_split_batch))
            slots = max(slots, 3 * kbatch + 2)
            if slots < self.hp.num_leaves:
                if self.parallel_mode == "feature":
                    # feature-parallel shards columns, not rows; its
                    # strict learner keeps full per-shard histograms
                    log.warning("histogram_pool_size ignored under "
                                "tree_learner=feature")
                    self._count("hist_pool_fallbacks")
                else:
                    # cegb / linear_tree / advanced monotone composed in
                    # round 4; forced splits joined in round 6 (the
                    # batched forced phase derives evicted leaves'
                    # columns directly — batch_grower.forced_col_hist)
                    self.hp = dataclasses.replace(
                        self.hp, hist_pool_slots=slots)

        # packed-word mirror (round-6 packed histogram mode): ship the
        # dataset's construction-time mirror ONCE per booster instead of
        # re-deriving the word view inside every traced tree; the
        # distributed modes pad rows/columns after construction, so they
        # keep the in-jit derivation
        self.bins_words = None
        if self.parallel_mode in (None, "data_gspmd"):
            # data_gspmd qualifies too: it never pads rows, so the
            # construction-time mirror stays valid (sharded like bins)
            from ..ops.histogram import wants_packed_mirror
            if wants_packed_mirror(self.hp.hist_kernel, self.hp.n_bins):
                self.bins_words = self._place_rows(
                    jnp.asarray(train_set.packed_mirror()))

    def _init_base_score(self) -> None:
        has_init_score = self.train_set.metadata.init_score is not None
        if self.objective is None or has_init_score:
            # reference gbdt.cpp:308 — no boost-from-average when the
            # dataset carries init scores (e.g. train continuation)
            init = np.zeros(self.num_tree_per_iteration)
        elif self.config.boost_from_average or \
                self.objective.NAME in ("mape",):
            init = np.array([self.objective.boost_from_score(k)
                             for k in range(self.num_tree_per_iteration)])
        else:
            init = np.zeros(self.num_tree_per_iteration)
        # boost_from_average only for supported objectives (ref gbdt.cpp:308)
        if self.objective is not None and self.objective.NAME in (
                "lambdarank", "rank_xendcg", "multiclass", "multiclassova"):
            init = np.zeros(self.num_tree_per_iteration)
        self.init_scores = init
        if np.any(init != 0):
            self.scores = self.scores + jnp.asarray(init, jnp.float32)[None, :]
        md = self.train_set.metadata
        if md.init_score is not None:
            isc = md.init_score.reshape(-1, self.num_tree_per_iteration, order="F") \
                if md.init_score.size != md.num_data else \
                md.init_score.reshape(-1, 1)
            self.scores = self.scores + jnp.asarray(isc, jnp.float32)

    def merge_from(self, trees: List[Tree]) -> None:
        """Seed this booster with an init model's trees (reference
        gbdt.h:70 ``MergeFrom``; train continuation).  The init model's
        predictions are already in ``scores`` via the dataset init_score,
        so only the model list and iteration counters move."""
        import copy
        k = self.num_tree_per_iteration
        if len(trees) % k != 0:
            log.fatal("init model has %d trees, not divisible by "
                      "num_tree_per_iteration=%d" % (len(trees), k))
        self.models = [copy.deepcopy(t) for t in trees] + self.models
        self.num_init_iteration = len(trees) // k
        self.iter_ = self.num_init_iteration

    def append_models(self, trees: List[Tree]) -> None:
        """Append another model's trees (reference LGBM_BoosterMerge ->
        GBDT::MergeFrom at the tail).  Score caches go stale and are
        rebuilt from the model list."""
        import copy
        k = self.num_tree_per_iteration
        if len(trees) % k != 0:
            log.fatal("merged model has %d trees, not divisible by "
                      "num_tree_per_iteration=%d" % (len(trees), k))
        self.models = self.models + [copy.deepcopy(t) for t in trees]
        self.iter_ = len(self.models) // k
        self.invalidate_score_cache()

    def invalidate_score_cache(self,
                               only_valid_index: Optional[int] = None
                               ) -> None:
        """Rebuild cached train/valid scores from the current model list
        (after leaf edits, merges or shuffles — the reference's
        ScoreUpdater is re-driven the same way on BoosterSetLeafValue).
        Linear-leaf trees contribute const + coeff·raw, not the plain leaf
        constant (ADVICE r3: the reference replays Tree::Predict, which
        takes the is_linear_ branch, tree.h:587).  ``only_valid_index``
        rebuilds a single valid set's scores (a late-added eval set),
        leaving the train/other caches untouched."""
        k = self.num_tree_per_iteration
        any_linear = any(t.is_linear for t in self.models)
        o2p = {int(o): p
               for p, o in enumerate(self.train_set.used_feature_idx)}

        def linear_adjust(t, arrs, bins_d, n, raw, base):
            """Replace the plain leaf constants with the linear-leaf
            output (host mirror of models/tree.py Tree.predict linear
            branch, on packed raw columns)."""
            leaf = np.asarray(predict_bins_leaf(
                arrs, bins_d, self.nan_bin_arr, self.bundle,
                self.hp.has_categorical))[:n]
            out = (t.leaf_const[leaf] - t.bias).astype(np.float32)
            nan_bad = np.zeros(n, bool)
            for l in range(t.num_leaves):
                feats = t.leaf_features[l]
                if not feats:
                    continue
                rows = leaf == l
                if not rows.any():
                    continue
                cols = [o2p[f] for f in feats]
                vals = raw[np.ix_(rows, cols)]
                nan_bad[rows] = np.isnan(vals).any(axis=1)
                out[rows] += (np.nan_to_num(vals)
                              @ np.asarray(t.leaf_coeff[l])).astype(
                                  np.float32)
            return np.where(nan_bad, base, out)

        def rebuild(n, bins_d, init_score, raw):
            sc = np.zeros((n, k), np.float32) + self.init_scores[None, :]
            if init_score is not None:
                sc += init_score.reshape(sc.shape, order="F") \
                    if init_score.size == sc.size else \
                    init_score.reshape(-1, 1)
            for i, t in enumerate(self.models):
                arrs = _tree_to_arrays_stub(t, self.train_set,
                                            exclude_bias=True)
                contrib = np.asarray(predict_bins_tree(
                    arrs, bins_d, self.nan_bin_arr, self.bundle,
                    self.hp.has_categorical), np.float32)[:n]
                if t.is_linear:
                    if raw is None:
                        log.fatal("score-cache rebuild for a linear_tree "
                                  "model needs the dataset's raw feature "
                                  "matrix (construct with linear_tree "
                                  "enabled)")
                    contrib = linear_adjust(t, arrs, bins_d, n, raw, contrib)
                sc[:, i % k] += contrib
            return jnp.asarray(sc)

        if only_valid_index is None:
            train_raw = self.train_set.raw if any_linear else None
            self.scores = rebuild(self.train_set.num_data, self.bins,
                                  self.train_set.metadata.init_score,
                                  train_raw)
            targets = range(len(self.valid_sets))
        else:
            targets = [only_valid_index]
        for vi in targets:
            vs = self.valid_sets[vi]
            self.valid_scores[vi] = rebuild(
                vs.num_data, self._valid_bins[vi], vs.metadata.init_score,
                vs.raw if any_linear else None)

    def reset_config(self, config: Config) -> None:
        """Swap learning-control parameters on the live booster
        (reference GBDT::ResetConfig gbdt.cpp): learner hyperparameters,
        pool translation, constraint arrays, shrinkage and the sampling
        strategy follow the new config; objective/metrics/dataset stay."""
        if bool(config.linear_tree) != bool(self.config.linear_tree):
            log.warning("linear_tree cannot be changed on a live booster; "
                        "keeping linear_tree=%s" % self.config.linear_tree)
            config.linear_tree = self.config.linear_tree
        self.config = config
        self.shrinkage_rate = float(config.learning_rate)
        self._derive_learner_state(config)
        self.sample_strategy = create_sample_strategy(
            config, self.train_set.num_data)

    def reset_training_data(self, train_set: Dataset) -> None:
        """Point the live booster at a new training set (reference
        GBDT::ResetTrainingData gbdt.cpp); existing trees are kept and
        their predictions rebuilt into the score cache.

        The new dataset must be BIN-ALIGNED with the current one (same
        mappers — construct it with ``create_valid``/``subset`` or from
        the serialized reference); the reference's CheckAlign enforces the
        same."""
        if train_set.num_features != self.num_features:
            log.fatal("new training data has %d features, model needs %d"
                      % (train_set.num_features, self.num_features))
        new_nb = np.asarray(train_set.num_bins_array())
        new_nan = np.asarray(train_set.nan_bin_array())
        new_cat = np.asarray(train_set.categorical_array())
        old_nb = np.asarray(self.num_bins_arr)[:len(new_nb)]
        old_nan = np.asarray(self.nan_bin_arr)[:len(new_nan)]
        old_cat = np.asarray(self.is_cat_arr)[:len(new_cat)]
        if not (np.array_equal(new_nb, old_nb)
                and np.array_equal(new_nan, old_nan)
                and np.array_equal(new_cat, old_cat)):
            log.fatal("reset_training_data: the new dataset's bin mappers "
                      "differ from the model's (construct it against the "
                      "same reference binning)")
        if self._pad_rows or self._pad_cols:
            log.fatal("reset_training_data is not supported in distributed "
                      "padded mode")
        self.train_set = train_set
        if self.objective is not None:
            self.objective.init(train_set.metadata, train_set.num_data)
            self.objective.attach_booster_metrics(self.metrics)
        for m in self.train_metrics:
            m.init(train_set.metadata, train_set.num_data)
        self.bins = self._place_rows(jnp.asarray(train_set.bins))
        if getattr(self, "bins_words", None) is not None:
            self.bins_words = self._place_rows(
                jnp.asarray(train_set.packed_mirror()))
        self.sample_strategy = create_sample_strategy(
            self.config, train_set.num_data)
        n = train_set.num_data
        k = self.num_tree_per_iteration
        self.scores = self._place_rows(jnp.zeros((n, k), jnp.float32))
        self._init_base_score()
        self.invalidate_score_cache()

    def add_valid(self, valid_set: Dataset, name: str) -> None:
        """reference GBDT::AddValidDataset (gbdt.cpp:184)."""
        self.valid_sets.append(valid_set)
        self.valid_names.append(name)
        ms = create_metrics(self.config)
        for m in ms:
            m.init(valid_set.metadata, valid_set.num_data)
        self.valid_metrics.append(ms)
        vsc = np.zeros((valid_set.num_data, self.num_tree_per_iteration),
                       np.float32) + self.init_scores[None, :]
        isc = valid_set.metadata.init_score
        if isc is not None:
            vsc += isc.reshape(vsc.shape, order="F") \
                if isc.size == vsc.size else isc.reshape(-1, 1)
        self.valid_scores.append(jnp.asarray(vsc))
        self._valid_bins.append(jnp.asarray(valid_set.bins))
        # transposed mirror for the matmul valid scorer (round 6): the
        # per-tree path-aggregation wants rows on lanes; cached once per
        # valid set, only for model classes the matmul path serves
        self._valid_bins_t.append(
            jnp.asarray(np.ascontiguousarray(valid_set.bins.T))
            if self._matmul_valid_ok() else None)
        self._valid_raw.append(jnp.asarray(valid_set.raw)
                               if self.linear and valid_set.raw is not None
                               else None)

    def _matmul_valid_ok(self) -> bool:
        """True when per-tree valid scoring can take the matmul
        path-aggregation (models/predict.py predict_bins_tree_matmul)
        instead of the frontier walk: numeric un-bundled non-linear
        models — categorical bitsets and EFB inverse tables are per-row
        gathers the matmul formulation has no cheap equivalent for, and
        linear leaves score through their own raw-feature path."""
        return (not self.hp.has_categorical and self.bundle is None
                and not self.linear)

    def _valid_tree_scores(self, arrays: TreeArrays, vi: int) -> jax.Array:
        """One tree's contribution to valid set ``vi``'s scores (leaf
        values must already be shrunk).  Matmul path aggregation where
        eligible (bit-identical to the walk — exactly one leaf matches
        per row); frontier walk otherwise."""
        if self._matmul_valid_ok() and self._valid_bins_t[vi] is not None:
            from ..models.predict import predict_bins_tree_matmul
            return predict_bins_tree_matmul(
                arrays, self._valid_bins_t[vi], self.nan_bin_arr)
        return predict_bins_tree(arrays, self._valid_bins[vi],
                                 self.nan_bin_arr, self.bundle,
                                 self.hp.has_categorical)

    # ------------------------------------------------------------ training
    def boosting_gradients(self) -> Tuple[jax.Array, jax.Array]:
        """reference GBDT::Boosting (gbdt.cpp:220).  Gradients run under
        one jit where the objective is pure (jitted_gradients) — through
        a tunneled chip the eager per-op dispatch of a large gradient
        graph (lambdarank's pairwise sort) otherwise dominates the
        iteration."""
        if self.objective is None:
            log.fatal("No objective; pass grad/hess to train_one_iter")
        if self.num_tree_per_iteration == 1:
            g, h = self.objective.jitted_gradients(self.scores[:, 0])
            return g[:, None], h[:, None]
        return self.objective.jitted_gradients(self.scores)

    def _debug_check_tree(self, arrays, leaf_of_row, row_mask) -> None:
        """Per-tree invariant checks (reference cuda_single_gpu_tree_learner
        DEBUG CheckSplitValid :571 and host/device cross-checks :93-95):
        leaf assignment bounds, leaf-count bookkeeping vs the actual
        partition, and child-pointer sanity.  Enabled by
        ``tpu_debug_checks=true``; costs one device->host sync per tree."""
        nl = int(arrays.num_leaves)
        lor = np.asarray(leaf_of_row)
        if lor.min() < 0 or lor.max() >= nl:
            log.fatal("debug check: leaf_of_row out of range [0, %d): "
                      "min=%d max=%d" % (nl, lor.min(), lor.max()))
        mask = np.ones(lor.shape[0], bool) if row_mask is None \
            else np.asarray(row_mask)
        counts = np.bincount(lor[mask], minlength=self.hp.num_leaves)
        stored = np.asarray(arrays.leaf_count)
        # rtol guards against f32-accumulated counts drifting by >0.5 on
        # very large leaves (>2^24 rows) — ADVICE r1
        if not np.allclose(counts[:nl], stored[:nl], rtol=1e-6, atol=0.5):
            bad = np.nonzero(~np.isclose(counts[:nl], stored[:nl],
                                         rtol=1e-6, atol=0.5))[0]
            log.fatal("debug check: leaf_count mismatch at leaves %s "
                      "(partition %s vs stored %s)"
                      % (bad[:5], counts[bad[:5]], stored[bad[:5]]))
        lc = np.asarray(arrays.left_child)[:nl - 1]
        rc = np.asarray(arrays.right_child)[:nl - 1]
        for side, arr in (("left", lc), ("right", rc)):
            # child encoding: negative = leaf (-(leaf+1)), positive = node
            if (arr >= nl - 1).any():
                log.fatal("debug check: %s child node index out of range"
                          % side)
            if (-arr - 1 >= nl).any():
                log.fatal("debug check: %s child leaf index out of range"
                          % side)

    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration (reference gbdt.cpp:344 TrainOneIter).
        Returns True when no tree could be grown (early finish)."""
        n = self.train_set.num_data
        k = self.num_tree_per_iteration
        if grad is None or hess is None:
            with self._phase("boosting_gradients"):
                g, h = self.boosting_gradients()
        else:
            g = jnp.asarray(np.asarray(grad, np.float32).reshape(n, k, order="F"))
            h = jnp.asarray(np.asarray(hess, np.float32).reshape(n, k, order="F"))

        if self.nan_policy != "none":
            # one fused isfinite-reduction over (g, h, scores); raises for
            # nan_policy=raise/halt_and_keep_best, True = skip this round
            from ..robustness.guards import enforce_nan_policy
            if enforce_nan_policy(self, g, h):
                self.iter_ += 1
                return False

        row_mask, g, h = self.sample_strategy.sample(self.iter_, g, h, self._rng,
                                                     self.train_set.metadata)
        feature_mask = self._feature_mask_for_tree()

        # gradient quantization (gradient_discretizer.cpp): tree STRUCTURE
        # is found on the discretized grid; leaf values optionally renewed
        # from the true gradients below
        g_true, h_true = g, h
        hist_scales = [None] * k
        if bool(self.config.use_quantized_grad):
            # integer-LEVEL quantization (ops/quantize.py): levels are
            # exact in the bf16 histogram kernel, so the fast kernel's
            # sums become bit-deterministic; the grower multiplies the
            # scales back in after each histogram pass
            with self._phase("quantize"):
                qkey = jax.random.PRNGKey(
                    (self.config.seed or 0) * 7919 + self.iter_)
                gq, hq = [], []
                for c in range(k):
                    gc, hc, gs, hs = discretize_gradients_levels(
                        g[:, c], h[:, c], jax.random.fold_in(qkey, c),
                        n_levels=int(self.config.num_grad_quant_bins),
                        stochastic=bool(self.config.stochastic_rounding),
                        constant_hessian=bool(
                            self.objective is not None
                            and self.objective.is_constant_hessian))
                    gq.append(gc)
                    hq.append(hc)
                    hist_scales[c] = jnp.stack([gs, hs])
                g = jnp.stack(gq, axis=1)
                h = jnp.stack(hq, axis=1)
            self._count("quantize_rounds")

        finished = True
        for cls_idx in range(k):
            node_key = None
            if self._needs_node_rng:
                node_key = jax.random.PRNGKey(
                    int(self.config.extra_seed) * 1000003
                    + self.iter_ * k + cls_idx)
            with self._phase("tree_growth"):
                arrays, leaf_of_row = self._grow(g[:, cls_idx],
                                                 h[:, cls_idx], row_mask,
                                                 feature_mask, node_key,
                                                 hist_scales[cls_idx])
            # no int(arrays.num_leaves) here: that scalar read blocks on
            # the whole grow computation and costs a tunnel round trip per
            # iteration (~0.15 s measured); `finished` is derived from the
            # host tree after from_arrays' single batched transfer, and
            # the renew gate moves device-side.  Paths that genuinely
            # need the host int early (debug checks, linear trees) keep
            # their own sync.
            if bool(self.config.tpu_debug_checks):
                self._debug_check_tree(arrays, leaf_of_row, row_mask)
            if bool(self.config.use_quantized_grad) and \
                    bool(self.config.quant_train_renew_leaf):
                renewed = renew_leaf_values(
                    leaf_of_row, g_true[:, cls_idx], h_true[:, cls_idx],
                    row_mask, num_leaves=self.hp.num_leaves,
                    lambda_l1=self.hp.lambda_l1, lambda_l2=self.hp.lambda_l2)
                # stump (no split found): keep the original leaf value
                arrays = arrays._replace(leaf_value=jnp.where(
                    arrays.num_leaves > 1, renewed, arrays.leaf_value))
            arrays = self._renew_leaves(arrays, leaf_of_row, cls_idx)
            lin = None
            if self.linear and int(arrays.num_leaves) > 1:
                # per-leaf ridge fit on the leaf's numeric path features
                # (reference LinearTreeLearner::CalculateLinear); TRUE
                # gradients, not quantized levels — the ridge solution is
                # not scale-invariant across g/h
                lin = fit_linear_leaves(
                    self.raw_dev, leaf_of_row, arrays.leaf_path,
                    ~self.is_cat_arr, g_true[:, cls_idx], h_true[:, cls_idx],
                    row_mask, arrays.leaf_value,
                    float(self.config.linear_lambda))
            if lin is not None:
                const, coeff = lin
                contrib = linear_leaf_scores(self.raw_dev, leaf_of_row, const,
                                             coeff, arrays.leaf_value)
                self.scores = self.scores.at[:, cls_idx].add(
                    self.shrinkage_rate * contrib)
                for vi in range(len(self.valid_sets)):
                    leaf_v = predict_bins_leaf(arrays, self._valid_bins[vi],
                                               self.nan_bin_arr, self.bundle,
                                               self.hp.has_categorical)
                    vraw = self._valid_raw[vi]
                    vc = linear_leaf_scores(vraw, leaf_v, const, coeff,
                                            arrays.leaf_value) \
                        if vraw is not None else arrays.leaf_value[leaf_v]
                    self.valid_scores[vi] = self.valid_scores[vi] \
                        .at[:, cls_idx].add(self.shrinkage_rate * vc)
            else:
                shrunk = arrays.leaf_value * self.shrinkage_rate
                # train score update: one-hot contraction beats the [n] table
                # gather ~25x on TPU (ops/table.py)
                self.scores = self.scores.at[:, cls_idx].add(
                    take_small_table(shrunk, leaf_of_row))
                # valid scores: matmul path aggregation where eligible,
                # frontier traversal otherwise (shrunk values either way)
                arrays_shrunk = arrays._replace(leaf_value=shrunk)
                for vi in range(len(self.valid_sets)):
                    contrib = self._valid_tree_scores(arrays_shrunk, vi)
                    self.valid_scores[vi] = \
                        self.valid_scores[vi].at[:, cls_idx].add(contrib)
            with self._phase("tree_finalize"):
                tree = Tree.from_arrays(arrays, self.train_set)
            if tree.num_leaves > 1:
                finished = False
            if lin is not None:
                tree.set_linear(np.asarray(lin[0], np.float64),
                                np.asarray(lin[1], np.float64),
                                self.train_set.used_feature_idx,
                                ~np.asarray(self.is_cat_arr))
            tree.apply_shrinkage(self.shrinkage_rate)
            if self.iter_ == 0 and abs(self.init_scores[cls_idx]) > 1e-10:
                tree.add_bias(self.init_scores[cls_idx])
            self.models.append(tree)
        self.iter_ += 1
        self._count("iterations")
        self._count("strict_rounds")
        self._count("trees_grown", k)
        self._count("hist_build_rounds", self._hist_rounds_per_tree() * k)
        return finished

    # ------------------------------------------------- fused iterations
    def supports_fused(self) -> bool:
        """True when whole boosting ROUNDS can run inside one jit
        (``train_fused``).  The fused path must be a pure device program:
        anything that reads or writes host state per iteration — custom
        objectives, l1/quantile leaf renewal, position-debias bias
        vectors, by-query bagging's host expansion, CEGB acquisition
        state, linear fits, DART drops — keeps the classic loop.  Since
        round 5, plain/pos-neg bagging and GOSS run in-jit (their masks
        derive from ``fold_in(PRNGKey(bagging_seed), iter)`` in BOTH
        paths — sample_strategy.py ``device_sample_fn``), and registered
        valid sets ride the scan when every valid metric has a device
        evaluation (``fused_valid_ok``)."""
        c = self.config
        return (type(self) is GBDT
                and self.objective is not None
                and not self.objective.need_renew_tree_output
                # the fused chunk jit-traces get_gradients; objectives
                # with per-call mutable state (rank_xendcg's RNG split,
                # lambdarank position-bias Newton updates) must stay on
                # the eager per-iteration loop — jit_safe is the single
                # source of that contract
                and self.objective.jit_safe
                # data_gspmd runs the fused scan over sharded inputs —
                # same serial program, partitioner-inserted collectives
                and self.parallel_mode in (None, "data_gspmd")
                and not self.linear
                and self.cegb is None
                # the per-round numeric guard is a host-side check; the
                # fused scan cannot surface a mid-chunk trip
                and self.nan_policy == "none"
                and not bool(c.tpu_debug_checks)
                and (not self.valid_sets or self.fused_valid_ok())
                and (self._sampling_is_noop()
                     or self._device_sample_fn() is not None)
                and self._use_batched_grower())

    def _device_sample_fn(self):
        """The sampling strategy's pure in-jit twin, or None (see
        sample_strategy.py ``device_sample_fn``)."""
        return self.sample_strategy.device_sample_fn(
            self.train_set.metadata)

    def fused_valid_ok(self) -> bool:
        """Valid sets can ride the fused scan when every registered valid
        metric has a traceable device evaluation (metrics.py
        ``eval_device_traced``).  Multiclass rides too (round 6 — the
        in-scan eval hands multi-output metrics the full [n, k] score
        matrix; multi_logloss / multi_error carry device kernels)."""
        from ..metrics import Metric as _MetricBase
        if bool(self.config.deterministic) or \
                not bool(self.config.tpu_device_eval):
            return False
        for ms in self.valid_metrics:
            if not ms:
                return False
            for m in ms:
                has_traced = (type(m).eval_device_traced
                              is not _MetricBase.eval_device_traced
                              or m._DEV_KIND is not None)
                if not has_traced:
                    return False
                if self.num_tree_per_iteration != 1 and not m._DEV_MULTI:
                    # the in-scan eval hands multiclass runs the full
                    # [n, k] matrix; single-column device kernels (l2,
                    # auc, ...) can't consume it
                    return False
        return True

    def _sampling_is_noop(self) -> bool:
        """No per-iteration row sampling: the default
        BaggingSampleStrategy no-ops unless bagging is actually
        configured (bagging.hpp's own is_use_subset gate)."""
        c = self.config
        if str(c.data_sample_strategy) == "goss":
            return False
        return (float(c.bagging_fraction) >= 1.0
                and float(c.pos_bagging_fraction) >= 1.0
                and float(c.neg_bagging_fraction) >= 1.0) \
            or int(c.bagging_freq) <= 0

    @staticmethod
    def fused_chunk_for(num_rounds: int) -> int:
        """Chunk length for ``train_fused``: the largest c <= 40 that
        divides ``num_rounds`` (>= 8), so the whole run reuses ONE
        compiled scan; 32 + a ragged tail otherwise."""
        for c in range(40, 7, -1):
            if num_rounds % c == 0:
                return c
        return 32

    @classmethod
    def fused_chunks(cls, num_rounds: int):
        """The exact scan-length sequence ``train_fused`` will run —
        the single source of truth shared with warmup code (bench.py)
        that precompiles each length."""
        c = cls.fused_chunk_for(num_rounds)
        out, done = [], 0
        while done < num_rounds:
            t = min(c, num_rounds - done)
            out.append(t)
            done += t
        return out

    def _fused_metric_layout(self):
        """Static (set_name, display_name, bigger) rows matching the
        concatenation order of the in-scan metric eval."""
        rows = []
        for vi, ms in enumerate(self.valid_metrics):
            for m in ms:
                for disp in m.display_names():
                    rows.append((self.valid_names[vi], disp,
                                 bool(m.bigger_is_better)))
        return rows

    def train_fused(self, num_rounds: int, chunk: int = 0,
                    cb_driver=None, es_params=None) -> bool:
        """Run ``num_rounds`` boosting iterations with the gradient step,
        row sampling, tree growth, score update, valid-set scoring and
        metric eval of every round inside ONE compiled scan (chunked so
        two compilations cover any round count).

        The per-iteration dispatch of the classic loop costs ~0.2 s
        through a tunneled dev chip and ~1 ms even on a co-located host —
        at Higgs scale that is 100 s of pure overhead over 500 rounds.
        The reference amortizes per-iteration launch overhead the same
        way on CUDA by keeping the whole iteration on-device
        (gbdt.cpp boosting_on_gpu / cuda gbdt path); here the rounds
        themselves fuse.  Trees materialize on the host from ONE stacked
        transfer per chunk.  Returns True if growth finished early (a
        stump round).

        ``cb_driver(iteration, evals)`` — optional host hook run once per
        round with the device-evaluated metric list (engine.py feeds the
        REAL callbacks through it, so early_stopping/log_evaluation/
        record_evaluation semantics are bit-for-bit the classic loop's).
        An EarlyStopException from it truncates this booster to the
        detection round (score caches rebuilt) and re-raises.

        ``es_params`` — optional (stopping_rounds, first_metric_only,
        min_delta) mirror of the early_stopping callback, enabling the
        IN-JIT stop flag: once the flag trips, remaining rounds in the
        chunk skip growth entirely (lax.cond), so a stopped run pays no
        overshoot compute.  Enabled only at min_delta == 0, where the
        in-jit f32 comparisons provably agree with the host callback's
        f64 comparisons of the same f32 values (strict >/< of identical
        floats); the host decision stays authoritative either way."""
        from ..learner.batch_grower import grow_tree_batched

        if chunk <= 0:
            chunk = self.fused_chunk_for(num_rounds)
        quant = bool(self.config.use_quantized_grad)
        renew = quant and bool(self.config.quant_train_renew_leaf)
        n_levels = int(self.config.num_grad_quant_bins)
        stoch = bool(self.config.stochastic_rounding)
        const_hess = bool(self.objective is not None
                          and self.objective.is_constant_hessian)
        seed_q = (self.config.seed or 0) * 7919
        seed_node = int(self.config.extra_seed) * 1000003
        shrink = self.shrinkage_rate
        frac = float(self.config.feature_fraction)
        if not hasattr(self, "_fused_cache"):
            self._fused_cache = {}

        k = self.num_tree_per_iteration
        nvalid = len(self.valid_sets)
        mrows = self._fused_metric_layout() if nvalid else []
        use_es = (es_params is not None and cb_driver is not None
                  and nvalid > 0 and float(es_params[2]) == 0.0)
        if use_es:
            es_rounds, es_first, _ = int(es_params[0]), bool(es_params[1]), 0
            bigger_arr = jnp.asarray([r[2] for r in mrows])
            if es_first:
                fam0 = mrows[0][1].split("@")[0]
                consider = jnp.asarray(
                    [r[1].split("@")[0] == fam0 for r in mrows])
            else:
                consider = jnp.ones((len(mrows),), bool)

        def make_runner(T: int, has_fm: bool):
            dev_sample = self._device_sample_fn() \
                if not self._sampling_is_noop() else None

            def eval_valid_traced(vsc):
                parts = []
                for vi, ms in enumerate(self.valid_metrics):
                    # single-output metrics see the [n] column, multi-
                    # output metrics the full [n, k] matrix (round 6)
                    sc = vsc[vi][:, 0] if k == 1 else vsc[vi]
                    for m in ms:
                        parts.append(jnp.asarray(
                            m.eval_device_traced(sc, self.objective),
                            jnp.float32))
                return jnp.concatenate(parts) if parts else \
                    jnp.zeros((0,), jnp.float32)

            def run(scores, bins, bwords, qkeys, nkeys, fmasks, iters,
                    vscores, es0):
                def round_real(carry, qkey_raw, node_keys, fm, it):
                    sc, vsc, es = carry
                    # sc: [n, k].  One gradient evaluation per round,
                    # then k per-class trees (one-vs-all, exactly the
                    # classic loop's class order) — all in this jit.
                    if k == 1:
                        g2, h2 = self.objective.get_gradients(sc[:, 0])
                        g2, h2 = g2[:, None], h2[:, None]
                    else:
                        g2, h2 = self.objective.get_gradients(sc)
                    if dev_sample is not None:
                        # in-jit bagging/GOSS draw — same key derivation
                        # as the classic loop (sample_strategy.py)
                        rmask, g2, h2 = dev_sample(it, g2, h2)
                    else:
                        rmask = None

                    def class_body(cs, xs):
                        # one-vs-all tree for one class — a lax.scan
                        # iteration, NOT a python unroll: the grower
                        # program compiles ONCE however large num_class
                        # is (an unrolled loop multiplied compile time
                        # and executable size by k)
                        sc_c, vsc_c = cs
                        g, h, nkey, cls = xs
                        g_t, h_t = g, h
                        hist_scale = None
                        if quant:
                            from ..ops.quantize import (
                                discretize_gradients_levels)
                            # per-class fold on the raw key words — the
                            # classic loop's fold_in(qkey, cls), in-jit
                            qkey = jax.random.fold_in(qkey_raw, cls)
                            g, h, gs, hs = discretize_gradients_levels(
                                g, h, qkey, n_levels=n_levels,
                                stochastic=stoch,
                                constant_hessian=const_hess)
                            hist_scale = jnp.stack([gs, hs])
                        arrays, lor = grow_tree_batched(
                            bins, g, h, rmask, self.num_bins_arr,
                            self.nan_bin_arr, self.is_cat_arr, fm, self.hp,
                            batch=int(self.config.tpu_split_batch),
                            bundle=self.bundle, monotone=self.monotone_arr,
                            hist_scale=hist_scale,
                            interaction_sets=self.interaction_sets,
                            rng_key=nkey, forced=self.forced_splits,
                            bins_words=bwords)
                        if renew:
                            renewed = renew_leaf_values(
                                lor, g_t, h_t, rmask,
                                num_leaves=self.hp.num_leaves,
                                lambda_l1=self.hp.lambda_l1,
                                lambda_l2=self.hp.lambda_l2)
                            arrays = arrays._replace(leaf_value=jnp.where(
                                arrays.num_leaves > 1, renewed,
                                arrays.leaf_value))
                        # shrink BEFORE the gather, exactly like the
                        # classic loop (train_one_iter: shrunk =
                        # leaf_value * rate, then take_small_table) — the
                        # other order differs by an ulp and cascades
                        # through the quantization grid
                        shrunk = arrays.leaf_value * shrink
                        sc_c = sc_c.at[:, cls].add(take_small_table(
                            shrunk, lor))
                        if nvalid:
                            # matmul path aggregation replaces the
                            # per-round frontier walk (round 6 — the walk
                            # cost ~107 ms/iter at 1M/200k, VERDICT r5 #4)
                            arrays_s = arrays._replace(leaf_value=shrunk)
                            vsc_c = tuple(
                                v.at[:, cls].add(
                                    self._valid_tree_scores(arrays_s, vi))
                                for vi, v in enumerate(vsc_c))
                        return (sc_c, vsc_c), arrays

                    (sc, vsc), stacked_cls = jax.lax.scan(
                        class_body, (sc, vsc),
                        (g2.T, h2.T, node_keys,
                         lax.iota(jnp.int32, k)))        # [k, ...] ys
                    mvals = eval_valid_traced(vsc) if nvalid else \
                        jnp.zeros((0,), jnp.float32)
                    if use_es:
                        best, best_it, seen, stopped = es
                        # a first evaluation ALWAYS improves (the host
                        # callback's `best is None` bootstrap — also the
                        # NaN case, where a float compare would say no)
                        improved = (jnp.where(bigger_arr, mvals > best,
                                              mvals < best) | ~seen) \
                            & consider
                        best = jnp.where(improved, mvals, best)
                        # best_it carries ABSOLUTE iteration indices and
                        # is always set from a real round before the
                        # stall test can trip (seen gate), so continued
                        # training (iter_ > 0 at entry) counts correctly
                        best_it = jnp.where(improved, it, best_it)
                        seen = seen | consider
                        trip = consider & seen & ~improved & \
                            (it - best_it >= es_rounds)
                        es = (best, best_it, seen, stopped | jnp.any(trip))
                    return (sc, vsc, es), (stacked_cls, mvals)

                def body(carry, xs):
                    if has_fm:
                        qkey_raw, node_keys, fm, it = xs
                    else:
                        (qkey_raw, node_keys, it), fm = xs, None

                    def real(c):
                        return round_real(c, qkey_raw, node_keys, fm, it)

                    if not use_es:
                        return real(carry)
                    # stop flag tripped: skip growth, emit zero ys (the
                    # host truncates at the detection round and never
                    # reads them)
                    ys_shape = jax.eval_shape(real, carry)[1]
                    zeros = jax.tree.map(
                        lambda s: jnp.zeros(s.shape, s.dtype), ys_shape)
                    return lax.cond(carry[2][3],
                                    lambda c: (c, zeros), real, carry)

                xs = (qkeys, nkeys, fmasks, iters) if has_fm else \
                    (qkeys, nkeys, iters)
                return jax.lax.scan(body, (scores, vscores, es0), xs)
            # donate the train/valid score buffers (args 0 and 7): both
            # are reassigned from the runner's outputs at the call site,
            # so the old buffers are dead the moment the call returns —
            # donation lets XLA update them in place instead of holding
            # two [n, k] copies live.  CPU buffers cannot be donated
            # (jax warns and ignores), so gate on accelerator backends.
            donate = (0, 7) if jax.default_backend() in ("tpu", "gpu") \
                else ()
            return jax.jit(run, donate_argnums=donate)

        finished = False
        done = 0
        has_fm = frac < 1.0
        # callbacks see RELATIVE round indices (the classic loop passes
        # `it` from range(num_boost_round)); continued training starts
        # iter_ at num_init_iteration, so the offset matters
        begin_iter = self.iter_
        # in-jit early-stop state persists ACROSS chunks (one callback
        # state machine per train() run, like the classic loop's)
        if use_es:
            M = len(mrows)
            es_host = (jnp.where(bigger_arr, -jnp.inf, jnp.inf),
                       jnp.zeros((M,), jnp.int32),
                       jnp.zeros((M,), bool), jnp.bool_(False))
        else:
            es_host = ()
        self._last_fused_evals = []
        while done < num_rounds and not finished:
            T = min(chunk, num_rounds - done)
            # es window parameters are baked into the runner's closure —
            # they must key the cache or a later train_fused call with a
            # different stopping window would reuse a stale in-jit flag
            key = (T, has_fm, nvalid,
                   (es_rounds, es_first) if use_es else None)
            if key not in self._fused_cache:
                # the booster dict is only a per-train view now; the
                # compiled runner itself lives in the PROCESS cache, so
                # a new booster (or reset_config re-derivation) over the
                # same datasets + config reuses the compiled program
                # instead of paying XLA again (ISSUE 7 satellite fix).
                # Keyed on the full config signature + array geometry;
                # the datasets enter as ANCHORS: their tokens extend the
                # key (a different dataset with identical shapes cannot
                # reuse a closure over the old one's device arrays) and
                # bound the entry's lifetime (no pinned dead HBM).
                fsig = None if self.forced_splits is None else tuple(
                    np.asarray(a).tobytes() for a in self.forced_splits)
                cc_key = ("train_fused", key, k, self._config_signature(),
                          fsig,
                          cc_sig((self.scores, self.bins, self.bins_words,
                                  tuple(self.valid_scores))))
                built = []

                def _build():
                    built.append(True)
                    return make_runner(T, has_fm)

                self._fused_cache[key] = cc_get_or_build(
                    cc_key, _build,
                    anchors=(self.train_set, *self.valid_sets),
                    metrics=self.metrics)
                if built:
                    self._count("fused_runner_cache_misses")
                else:
                    self._count("fused_runner_cache_hits")
            else:
                self._count("fused_runner_cache_hits")
            fmasks = None
            if has_fm:
                # per-ROUND masks: the seed is feature_fraction_seed +
                # iteration (matching the classic loop, where iter_
                # advances between draws) — drawing T masks at the same
                # iter_ would freeze the subset for the whole chunk
                fmasks = jnp.stack([
                    self._feature_mask_for_tree(self.iter_ + t)
                    for t in range(T)])
            # per-round PRNG keys: python-int seed arithmetic (no
            # traced-int32 overflow for large seeds) rendered straight
            # to threefry key words in numpy — PRNGKey(s) is exactly
            # [s >> 32, s & 0xffffffff] — so a chunk ships ONE [T, 2]
            # array instead of ~3T tiny per-round device dispatches;
            # the class fold_in(., 0) runs inside the jitted body
            def _key_words(vals):
                return np.array(
                    [[v >> 32 & 0xffffffff, v & 0xffffffff]
                     for v in vals], np.uint32)
            qkeys = jnp.asarray(_key_words(
                [seed_q + self.iter_ + t for t in range(T)]))
            # node keys per (round, class): the classic loop's
            # PRNGKey(extra_seed * 1000003 + iter * k + cls)
            nkeys = jnp.asarray(_key_words(
                [seed_node + (self.iter_ + t) * k + cls
                 for t in range(T) for cls in range(k)])
            ).reshape(T, k, 2)
            iters = jnp.arange(self.iter_, self.iter_ + T, dtype=jnp.int32)
            with self._phase("fused_round_scan"):
                (scores, vscores, es_host), (stacked, mvals) = \
                    self._fused_cache[key](
                        self.scores, self.bins, self.bins_words, qkeys,
                        nkeys, fmasks, iters,
                        tuple(self.valid_scores), es_host)
            self.scores = scores
            for vi in range(nvalid):
                self.valid_scores[vi] = vscores[vi]
            with self._phase("fused_chunk_transfer"):
                host = jax.device_get(stacked)  # ONE transfer per chunk
            mhost = np.asarray(jax.device_get(mvals)) if nvalid else None
            for t in range(T):
                stumps = 0
                for cls in range(k):
                    arrays_tc = jax.tree.map(lambda a: a[t, cls], host)
                    with self._phase("tree_finalize"):
                        tree = Tree.from_arrays(arrays_tc, self.train_set)
                    tree.apply_shrinkage(self.shrinkage_rate)
                    if self.iter_ == 0 and \
                            abs(self.init_scores[cls]) > 1e-10:
                        tree.add_bias(self.init_scores[cls])
                    self.models.append(tree)
                    if tree.num_leaves <= 1:
                        stumps += 1
                self.iter_ += 1
                done += 1
                self._count("iterations")
                self._count("fused_rounds")
                self._count("trees_grown", k)
                self._count("hist_build_rounds",
                            self._hist_rounds_per_tree() * k)
                if nvalid:
                    self._last_fused_evals = [
                        (mrows[j][0], mrows[j][1], float(mhost[t, j]),
                         mrows[j][2]) for j in range(len(mrows))]
                if cb_driver is not None:
                    try:
                        # feed the REAL callbacks this round's
                        # device-evaluated metrics — identical state
                        # machine to the classic loop's post-iteration
                        # callback pass; iteration is RELATIVE to this
                        # train() run, like the classic loop's range()
                        cb_driver(self.iter_ - 1 - begin_iter,
                                  self._last_fused_evals)
                    except EarlyStopException:
                        # models stop at the detection round (later
                        # rounds were never materialized); the device
                        # advanced the score caches by the whole chunk —
                        # rebuild from the kept models unless the stop
                        # landed exactly on the chunk's last round
                        if t + 1 < T:
                            self.invalidate_score_cache()
                        raise
                if stumps == k:
                    # the classic loop would have stopped here; drop any
                    # overrun rounds and rebuild scores without them
                    finished = True
                    if t + 1 < T:
                        self.invalidate_score_cache()
                    break
        return finished

    def _grow(self, g: jax.Array, h: jax.Array, row_mask, feature_mask,
              node_key, hist_scale=None) -> Tuple[TreeArrays, jax.Array]:
        """One tree via the configured tree learner (serial or a
        shard_map-distributed mode; reference CreateTreeLearner
        tree_learner.cpp:15).  ``hist_scale``: [2] (g, h) scales in
        quantized-levels mode."""
        if self.parallel_mode in (None, "data_gspmd"):
            if self.parallel_mode == "data_gspmd":
                # serial program over row-sharded inputs: GSPMD inserts
                # the same logical reductions the explicit path psums
                self._count("collective_allreduce_bytes_est",
                            self._collective_bytes_per_tree())
                self._maybe_measure_collective(self._overlap)
            args = (self.bins, g, h, row_mask, self.num_bins_arr,
                    self.nan_bin_arr, self.is_cat_arr, feature_mask, self.hp)
            if self._use_batched_grower():
                from ..learner.batch_grower import grow_tree_batched
                out = grow_tree_batched(
                    *args, batch=int(self.config.tpu_split_batch),
                    bundle=self.bundle, monotone=self.monotone_arr,
                    hist_scale=hist_scale,
                    interaction_sets=self.interaction_sets,
                    rng_key=node_key, forced=self.forced_splits,
                    cegb=self.cegb, bins_words=self.bins_words)
                if self.cegb is not None:
                    arrays, lor, self.cegb = out
                    return arrays, lor
                return out
            kwargs = dict(monotone=self.monotone_arr, rng_key=node_key,
                          interaction_sets=self.interaction_sets,
                          forced=self.forced_splits, bundle=self.bundle,
                          hist_scale=hist_scale,
                          bins_words=self.bins_words)
            if self.cegb is not None:
                arrays, lor, self.cegb = grow_tree(*args, cegb=self.cegb,
                                                   **kwargs)
                return arrays, lor
            return grow_tree(*args, **kwargs)
        self._count("collective_allreduce_bytes_est",
                    self._collective_bytes_per_tree())
        if self.parallel_mode == "feature":
            from ..parallel.feature_parallel import grow_tree_feature_parallel
            if feature_mask is not None and self._pad_cols:
                feature_mask = jnp.pad(feature_mask, (0, self._pad_cols))
            # quantized levels rejected at construction (__init__ fatal);
            # hist_scale is always None on this path
            with obs_trace.span("collective_grow_dispatch",
                                mode="feature"):
                arrays, lor = grow_tree_feature_parallel(
                    self.mesh, self.bins, g, h, row_mask, self.num_bins_arr,
                    self.nan_bin_arr, self.is_cat_arr, feature_mask, self.hp)
            return arrays, lor
        from ..parallel.data_parallel import (grow_tree_batched_sharded,
                                              grow_tree_sharded)
        p = self._pad_rows
        if p:
            g = jnp.pad(g, (0, p))
            h = jnp.pad(h, (0, p))
            row_mask = jnp.pad(jnp.ones(g.shape[0] - p, bool)
                               if row_mask is None else row_mask, (0, p))
        overlap = self._overlap
        if overlap:
            self._count("collective_overlap_rounds",
                        self._hist_rounds_per_tree())
        self._maybe_measure_collective(overlap)
        if self.parallel_mode in ("data", "voting") \
                and self._use_batched_grower():
            with obs_trace.span("collective_grow_dispatch",
                                mode=self.parallel_mode, batched=True):
                arrays, lor = grow_tree_batched_sharded(
                    self.mesh, self.bins, g, h, row_mask, self.num_bins_arr,
                    self.nan_bin_arr, self.is_cat_arr, feature_mask, self.hp,
                    batch=int(self.config.tpu_split_batch),
                    bundle=self.bundle,
                    monotone=self.monotone_arr, hist_scale=hist_scale,
                    interaction_sets=self.interaction_sets,
                    parallel_mode=self.parallel_mode,
                    top_k=int(self.config.top_k), overlap=overlap,
                    metrics=self.metrics)
            return arrays, (lor[:-p] if p else lor)
        with obs_trace.span("collective_grow_dispatch",
                            mode=self.parallel_mode, batched=False):
            arrays, lor = grow_tree_sharded(
                self.mesh, self.bins, g, h, row_mask, self.num_bins_arr,
                self.nan_bin_arr, self.is_cat_arr, feature_mask, self.hp,
                bundle=self.bundle, parallel_mode=self.parallel_mode,
                top_k=int(self.config.top_k), monotone=self.monotone_arr,
                rng_key=node_key, interaction_sets=self.interaction_sets,
                forced=self.forced_splits, hist_scale=hist_scale,
                overlap=overlap, metrics=self.metrics)
        return arrays, (lor[:-p] if p else lor)

    def _maybe_measure_collective(self, overlap: bool) -> None:
        """One-shot collective probe (obs/collective.py): measure this
        mesh's per-pass histogram all-reduce cost and overlap
        efficiency, gauged into the booster + global registries so
        telemetry JSONL rows and bench payloads carry them.  Runs ONLY
        when observability is configured (a trace recorder or event
        journal is active, or telemetry_output is set) — the no-outputs
        path never compiles a probe."""
        if self._collective_probed or self.mesh is None:
            return
        from ..obs import events as obs_events
        if obs_trace.active() is None and obs_events.active() is None \
                and not str(getattr(self.config, "telemetry_output", "")
                            or ""):
            return
        self._collective_probed = True
        try:
            from ..obs.collective import measure_collective
            res = measure_collective(
                self.mesh, (self.bins.shape[1], self.hp.n_bins, 4),
                overlap=overlap, metrics=self.metrics)
        except Exception as e:   # a probe failure must not stop training
            log.warning("collective probe failed (%s: %s); overlap "
                        "gauges unavailable this run"
                        % (type(e).__name__, e))
            return
        per_round = res["collective_s_per_pass"] * \
            self._hist_rounds_per_tree()
        from ..obs.metrics import global_metrics
        for registry in (self.metrics, global_metrics):
            registry.set_gauge("collective_s_per_round",
                               round(per_round, 9))

    def _use_batched_grower(self) -> bool:
        """Batched split rounds (learner/batch_grower.py) when requested and
        the tree uses only its supported feature set.  An active bounded
        pool routes through the batched grower even at tpu_split_batch=1
        (batch=1 rounds produce trees IDENTICAL to the strict learner, so
        histogram_pool_size composes with strict leaf-wise order).

        The decision is pure config state, memoized per
        ``_derive_learner_state`` so a fallback is warned about and
        counted ONCE per configuration (``batched_path_fallbacks`` in the
        telemetry registry — VERDICT Weak #5: silent slow-path training
        must be visible)."""
        if self._batched_decision is not None:
            return self._batched_decision
        pool_active = 0 < self.hp.hist_pool_slots < self.hp.num_leaves
        if int(self.config.tpu_split_batch) <= 1 and not pool_active:
            self._batched_decision = False
            return False
        # categorical splits, all three monotone methods, interaction
        # constraints, path smoothing, CEGB, linear trees and (since
        # round 6) forced splits x hist pool are batched-capable
        # (learner/batch_grower.py)
        # batched voting carries the PV-Tree protocol including
        # categorical splits (round 5: the winner's column psums for the
        # bitset, the strict learner's cadence) but not forced splits
        # (batch_grower asserts; advanced monotone is already downgraded
        # to intermediate under voting at construction)
        voting_unsupported = self.parallel_mode == "voting" and \
            self.forced_splits is not None
        # extra_trees / by-node sampling need per-node rng keys, which the
        # sharded batched wrapper does not plumb yet — serial only.
        # data_gspmd runs the SERIAL code path (keys plumb normally), so
        # it is exempt like serial.
        rng_parallel = self.parallel_mode not in (None, "data_gspmd") and (
            self.hp.extra_trees or self.hp.feature_fraction_bynode < 1.0
            or self.forced_splits is not None)
        # CEGB is batched-capable (batch_grower round-4 lift); it only
        # ever reaches this dispatch in serial mode — __init__ fatals on
        # cegb_* with any non-serial tree_learner (gbdt.py:401)
        reasons = [name for name, hit in (
            ("forced-splits-under-voting", voting_unsupported),
            ("extra_trees/bynode-sampling/forced-splits-under-"
             "distributed", rng_parallel),
            ("unsupported-parallel-mode",
             self.parallel_mode not in (None, "data", "voting",
                                        "data_gspmd")),
        ) if hit]
        if reasons:
            log.warning("tpu_split_batch > 1 ignored (%s): falling back "
                        "to the strict leaf-wise learner"
                        % ", ".join(reasons))
            self._count("batched_path_fallbacks")
            from ..obs.events import emit_event
            emit_event("strict_learner_fallback", reasons=reasons)
            if pool_active:
                # the pool lives in the batched grower only; the strict
                # learner keeps the full [L, F, B, 4] state resident, so
                # the user's memory cap is NOT honored on this path —
                # warn and tally like the feature-parallel case
                log.warning("histogram_pool_size inert under the strict "
                            "leaf-wise fallback (%s): full per-leaf "
                            "histogram state stays resident"
                            % ", ".join(reasons))
                self._count("hist_pool_fallbacks")
            self._batched_decision = False
            return False
        self._batched_decision = True
        return True

    def _renew_leaves(self, arrays: TreeArrays, leaf_of_row: jax.Array,
                      cls_idx: int) -> TreeArrays:
        """Leaf-output renewal for l1/quantile/mape (reference
        RenewTreeOutput); returns arrays with UNSHRUNK final leaf values."""
        if self.objective is not None and self.objective.need_renew_tree_output:
            lor = np.asarray(leaf_of_row)
            score_host = np.asarray(self.scores[:, cls_idx], np.float64)
            renewed = self.objective.renew_tree_output(
                score_host, None, lor, int(arrays.num_leaves))
            if renewed is not None:
                lv = np.asarray(arrays.leaf_value).copy()
                lv[:len(renewed)] = renewed
                arrays = arrays._replace(leaf_value=jnp.asarray(lv, jnp.float32))
        return arrays

    def _feature_mask_for_tree(self, iter_: Optional[int] = None
                               ) -> Optional[jax.Array]:
        frac = float(self.config.feature_fraction)
        if frac >= 1.0:
            return None
        f = self.num_features
        kf = max(1, int(np.ceil(frac * f)))
        rng = np.random.default_rng(
            self.config.feature_fraction_seed
            + (self.iter_ if iter_ is None else iter_))
        chosen = rng.choice(f, size=kf, replace=False)
        mask = np.zeros(f, bool)
        mask[chosen] = True
        return jnp.asarray(mask)

    # ------------------------------------------------------------- evaluate
    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        return self._eval_metric_list("training", self.train_metrics,
                                      self.scores)

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        for vi, ms in enumerate(self.valid_metrics):
            out.extend(self._eval_metric_list(
                self.valid_names[vi], ms, self.valid_scores[vi]))
        return out

    def _eval_metric_list(self, set_name, metrics, scores_dev):
        """Evaluate on device where supported (metrics.py eval_device —
        scalars cross the boundary, not score arrays); host f64 otherwise
        and always under deterministic=true."""
        use_dev = (bool(self.config.tpu_device_eval)
                   and not bool(self.config.deterministic)
                   and scores_dev.shape[1] == 1)
        out = []
        score_host = None
        for m in metrics:
            res = m.eval_device(scores_dev[:, 0], self.objective) \
                if use_dev else None
            if res is None:
                if score_host is None:
                    score_host = self._host_scores(scores_dev)
                res = m.eval(score_host, self.objective)
            for name, val in res:
                out.append((set_name, name, val, m.bigger_is_better))
        return out

    def _host_scores(self, scores: jax.Array) -> np.ndarray:
        s = np.asarray(scores, np.float64)
        return s[:, 0] if s.shape[1] == 1 else s

    # ------------------------------------------------------------- predict
    #: rows x trees above which predict_raw batches on the device; below
    #: it the host f64 walk wins (no binning pass, no compile) and keeps
    #: full-double accumulation for the tiny inputs tests compare
    #: bit-tightly.  At 1M rows x 100 trees the host walk measured 136 s
    #: vs ~1 s device (round 4).
    DEVICE_PREDICT_MIN_WORK = 20_000_000

    #: _device_predict_raw row-block geometry, as class attributes so
    #: tests can shrink them to exercise blocking/bucketing without
    #: million-row inputs.  BLOCK bounds the [ni, n] decision-bit
    #: transients (~0.5 GB bf16 per 1M rows at 255 leaves); QUANTUM is
    #: the tail padding grain.
    PREDICT_BLOCK_ROWS = 1_048_576
    PREDICT_TAIL_QUANTUM = 131_072

    def predict_raw(self, X: np.ndarray, start_iteration: int = 0,
                    num_iteration: int = -1, early=None) -> np.ndarray:
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        k = self.num_tree_per_iteration
        total_iters = len(self.models) // k
        end = total_iters if num_iteration <= 0 else \
            min(total_iters, start_iteration + num_iteration)
        n_trees = max(0, (end - start_iteration) * k)
        if (early is None and X.shape[0] * n_trees
                >= self.DEVICE_PREDICT_MIN_WORK):
            dev = self._device_predict_raw(X, start_iteration, end)
            if dev is not None:
                return dev
        out = np.zeros((X.shape[0], k))
        active = np.ones(X.shape[0], bool) if early is not None else None
        for it in range(start_iteration, end):
            for c in range(k):
                if early is not None:
                    out[active, c] += self.models[it * k + c].predict(X[active])
                else:
                    out[:, c] += self.models[it * k + c].predict(X)
            if early is not None and (it + 1) % early[1] == 0:
                from ..basic import _margin_reached
                active &= ~_margin_reached(out, early[2])
                if not active.any():
                    break
        return out[:, 0] if k == 1 else out

    def _device_predict_raw(self, X: np.ndarray, start_it: int,
                            end_it: int) -> Optional[np.ndarray]:
        """Batched on-device prediction: bin X once with the training
        mappers (a raw split ``value <= threshold`` is exactly
        ``bin <= threshold_bin`` under them) and run the matmul batch
        predictor — ``predict_numeric_forest`` for plain numeric
        models, ``predict_bitset_forest`` for categorical / EFB-bundled
        / linear models (round 5; these previously kept 15-30x-slower
        walks).  One compiled program instead of a per-tree host walk.
        """
        k = self.num_tree_per_iteration
        models = self.models[start_it * k:end_it * k]
        if not models:
            return None
        # row blocks bound the [ni, n] decision-bit transients of the
        # matmul predictors; ragged tails pad UP so a fresh shape per
        # remainder never pays seconds of XLA compile per distinct
        # predict size.  predict_bucketing=on (default) pads the tail to
        # a GEOMETRIC ladder of quantum multiples {q, 2q, 4q, ..., blk},
        # bounding the compiled program count at log2(blk/q)+1 across
        # ANY mix of request row counts; =off keeps the pre-serving
        # next-multiple-of-q padding (up to blk/q shapes).  Padded rows
        # are sliced off and the matmul predictors are per-row exact, so
        # outputs are bit-identical either way.
        blk = int(self.PREDICT_BLOCK_ROWS)
        tail_q = min(int(self.PREDICT_TAIL_QUANTUM), blk)
        bucketing = self.config.predict_bucketing == "on"
        general = (any(t.is_linear for t in models)
                   or bool(self.hp.has_categorical)
                   or self.bundle is not None)
        if general:
            # categorical / EFB-bundled / linear models: the BITSET
            # forest predictor (per-node decision bitsets over logical
            # bins; sentinel bins make unseen-category and NaN rows
            # match the host raw-space walk, so outputs never depend on
            # batch size)
            from ..models.predict import predict_bitset_forest
            fb, lin, cat_feats = self._forest_bitset_arrays(models, k)
            bins_np = self.train_set.bin_external_pred(X)
            raw_np = np.asarray(X, np.float32) if lin is not None else None
        else:
            from ..models.predict import predict_numeric_forest
            fa = self._forest_arrays(models, k)
            bins_np = self.train_set.bin_external(X)
        outs = []
        n_all = bins_np.shape[0]
        total_pad = 0
        for r0 in range(0, n_all, blk):
            chunk = bins_np[r0:r0 + blk]
            rows = chunk.shape[0]
            if bucketing:
                target = tail_q
                while target < rows:
                    target *= 2
                pad = min(target, blk) - rows
            else:
                pad = (-rows) % tail_q
            total_pad += pad
            if pad:
                chunk = np.concatenate(
                    [chunk, np.zeros((pad, chunk.shape[1]), chunk.dtype)])
            bins_t = jnp.asarray(np.ascontiguousarray(chunk.T))
            if general:
                raw_d = nan_d = None
                if lin is not None:
                    rchunk = raw_np[r0:r0 + blk]
                    if pad:
                        rchunk = np.concatenate(
                            [rchunk, np.zeros((pad, rchunk.shape[1]),
                                              rchunk.dtype)])
                    raw_d = jnp.asarray(np.nan_to_num(rchunk))
                    nan_d = jnp.asarray(
                        np.ascontiguousarray(np.isnan(rchunk).T),
                        jnp.bfloat16)
                # route the (module-jitted) predictor lookup through the
                # process compile cache so predict programs share the
                # round_compile_hits/misses telemetry with the round
                # bodies — a new shape is a counted miss, a repeat a hit
                fn = cc_get_or_build(
                    ("predict_bitset_forest",
                     cc_sig((fb, bins_t, k, cat_feats, lin, raw_d, nan_d))),
                    lambda: predict_bitset_forest, metrics=self.metrics)
                res = fn(fb, bins_t, k, cat_feats=cat_feats,
                         lin=lin, raw=raw_d, raw_nan=nan_d)
            else:
                fn = cc_get_or_build(
                    ("predict_numeric_forest", cc_sig((fa, bins_t, k))),
                    lambda: predict_numeric_forest, metrics=self.metrics)
                res = fn(fa, bins_t, k)
            outs.append(np.asarray(res, np.float64)[:rows])
        if bucketing:
            self._count("predict_bucketed_calls")
            if total_pad:
                self._count("predict_bucket_pad_rows", total_pad)
        out = np.concatenate(outs, axis=0)
        return out[:, 0] if k == 1 else out

    def _forest_bitset_arrays(self, models, k: int):
        """Host Tree list -> stacked BitsetForest (+ LinearLeaves when
        any tree is linear) for the GENERAL matmul predictor.  Numeric
        nodes (bundled or not) stay threshold compares in LOGICAL bin
        space; only true categorical nodes get bitsets, over the narrow
        categorical bin range plus the unseen/NaN sentinel bins of
        ``bin_external_pred``.  Returns (fb, lin, cat_feats)."""
        from ..models.predict import BitsetForest, LinearLeaves
        ds = self.train_set
        L = max(max(t.num_leaves for t in models), 2)
        ni = L - 1
        T = len(models)
        orig_to_packed = {o: p for p, o in enumerate(ds.used_feature_idx)}
        nan_bin_np = np.asarray(self.nan_bin_arr)
        is_cat_np = np.asarray(ds.categorical_array())
        cat_feats = tuple(int(p) for p in np.nonzero(is_cat_np)[0])
        # categorical one-hot width: widest cat feature + 2 sentinels
        Bc = max((ds.mappers[ds.used_feature_idx[p]].num_bin
                  for p in cat_feats), default=1) + 2
        # cat nodes per tree, padded to a shared width (>= 1)
        C = 1
        cat_nodes = []
        for t in models:
            nn = max(t.num_leaves - 1, 0)
            nodes = [nd for nd in range(nn)
                     if int(t.decision_type[nd]) & 1]
            cat_nodes.append(nodes)
            C = max(C, len(nodes))
        feat = np.zeros((T, ni), np.int32)
        thr = np.zeros((T, ni), np.int32)
        dl = np.zeros((T, ni), bool)
        nanb = np.full((T, ni), -2, np.int32)
        catn = np.full((T, C), ni, np.int32)   # ni = dead pad slot
        catf = np.zeros((T, C), np.int32)
        catb = np.zeros((T, C, Bc), np.float32)
        mpos = np.zeros((T, L, ni), np.float32)
        mneg = np.zeros((T, L, ni), np.float32)
        depth = np.full((T, L), -1, np.int32)
        value = np.zeros((T, L), np.float32)
        any_linear = any(t.is_linear for t in models)
        if any_linear:
            Fr = ds.num_total_features
            lconst = np.zeros((T, L), np.float32)
            lcoeff = np.zeros((T, L, Fr), np.float32)
            lmask = np.zeros((T, L, Fr), np.float32)
        for ti, t in enumerate(models):
            nn = max(t.num_leaves - 1, 0)
            value[ti, :t.num_leaves] = t.leaf_value[:t.num_leaves]
            _leaf_path_masks(t, mpos[ti], mneg[ti], depth[ti])
            if any_linear and t.is_linear:
                for l in range(t.num_leaves):
                    lconst[ti, l] = t.leaf_const[l]
                    for fi, f in enumerate(t.leaf_features[l]):
                        lcoeff[ti, l, f] = t.leaf_coeff[l][fi]
                        lmask[ti, l, f] = 1.0
            if nn:
                pf = np.array([orig_to_packed.get(int(f), 0)
                               for f in t.split_feature[:nn]], np.int32)
                feat[ti, :nn] = pf
                thr[ti, :nn] = t.threshold_bin[:nn]
                dl[ti, :nn] = (np.asarray(t.decision_type[:nn]) & 2) > 0
                nanb[ti, :nn] = nan_bin_np[pf]
            for ci, nd in enumerate(cat_nodes[ti]):
                p = int(feat[ti, nd])
                catn[ti, ci] = nd
                catf[ti, ci] = p
                csi = int(t.cat_split_index[nd])
                sets = set(t.cat_threshold[csi])
                mapper = ds.mappers[ds.used_feature_idx[p]]
                for b, c in enumerate(mapper.bin_2_categorical):
                    if c in sets:
                        catb[ti, ci, b] = 1.0
                # sentinels ride at this FEATURE's (num_bin, num_bin+1):
                # unseen -> right (stays 0); NaN -> cat_nan_left
                # (tree.cpp CategoricalDecision)
                if csi < len(t.cat_nan_left) and t.cat_nan_left[csi]:
                    catb[ti, ci, mapper.num_bin + 1] = 1.0
        fb = BitsetForest(
            feat=jnp.asarray(feat), thr=jnp.asarray(thr),
            dl=jnp.asarray(dl), nanb=jnp.asarray(nanb),
            catn=jnp.asarray(catn), catf=jnp.asarray(catf),
            catb=jnp.asarray(catb, jnp.bfloat16),
            mpos=jnp.asarray(mpos, jnp.bfloat16),
            mneg=jnp.asarray(mneg, jnp.bfloat16),
            depth=jnp.asarray(depth), value=jnp.asarray(value),
            cls=jnp.asarray(np.arange(T, dtype=np.int32) % k))
        lin = None
        if any_linear:
            lin = LinearLeaves(const=jnp.asarray(lconst),
                               coeff=jnp.asarray(lcoeff),
                               featmask=jnp.asarray(lmask, jnp.bfloat16))
        return fb, lin, cat_feats

    def _forest_arrays(self, models, k: int):
        """Host Tree list -> stacked ForestArrays for the matmul batch
        predictor: per tree, the per-node split operands plus each
        leaf's path-direction masks (which internal-node decisions, and
        in which direction, place a row in that leaf)."""
        from ..models.predict import ForestArrays
        L = max(max(t.num_leaves for t in models), 2)
        ni = L - 1
        T = len(models)
        orig_to_packed = {o: p for p, o in
                          enumerate(self.train_set.used_feature_idx)}
        nan_bin_np = np.asarray(self.nan_bin_arr)
        feat = np.zeros((T, ni), np.int32)
        thr = np.zeros((T, ni), np.int32)
        dl = np.zeros((T, ni), bool)
        nanb = np.full((T, ni), -2, np.int32)
        mpos = np.zeros((T, L, ni), np.float32)
        mneg = np.zeros((T, L, ni), np.float32)
        depth = np.full((T, L), -1, np.int32)
        value = np.zeros((T, L), np.float32)
        for ti, t in enumerate(models):
            nn = max(t.num_leaves - 1, 0)
            pf = np.array([orig_to_packed.get(int(f), 0)
                           for f in t.split_feature[:nn]], np.int32)
            feat[ti, :nn] = pf
            thr[ti, :nn] = t.threshold_bin[:nn]
            dl[ti, :nn] = (t.decision_type[:nn] & 2) > 0
            nanb[ti, :nn] = nan_bin_np[pf] if nn else 0
            value[ti, :t.num_leaves] = t.leaf_value[:t.num_leaves]
            _leaf_path_masks(t, mpos[ti], mneg[ti], depth[ti])
        return ForestArrays(
            feat=jnp.asarray(feat), thr=jnp.asarray(thr),
            dl=jnp.asarray(dl), nanb=jnp.asarray(nanb),
            mpos=jnp.asarray(mpos, jnp.bfloat16),
            mneg=jnp.asarray(mneg, jnp.bfloat16),
            depth=jnp.asarray(depth), value=jnp.asarray(value),
            cls=jnp.asarray(np.arange(T, dtype=np.int32) % k))

    def predict(self, X: np.ndarray, raw_score: bool = False,
                start_iteration: int = 0, num_iteration: int = -1,
                pred_leaf: bool = False, early=None) -> np.ndarray:
        if pred_leaf:
            X = np.asarray(X, dtype=np.float64)
            if X.ndim == 1:
                X = X.reshape(1, -1)
            k = self.num_tree_per_iteration
            total_iters = len(self.models) // k
            end = total_iters if num_iteration <= 0 else \
                min(total_iters, start_iteration + num_iteration)
            leaves = [self.models[it * k + c].predict_leaf_index(X)
                      for it in range(start_iteration, end) for c in range(k)]
            return np.stack(leaves, axis=1) if leaves else \
                np.zeros((X.shape[0], 0), np.int32)
        raw = self.predict_raw(X, start_iteration, num_iteration, early=early)
        if raw_score or self.objective is None or \
                not self.objective.need_convert_output:
            return raw
        return np.asarray(self.objective.convert_output(jnp.asarray(raw)))

    # -------------------------------------------------------------- export
    def num_trees(self) -> int:
        return len(self.models)

    def current_iteration(self) -> int:
        return self.iter_

    def rollback_one_iter(self) -> None:
        """reference GBDT::RollbackOneIter (gbdt.cpp:454) — pop the last
        iteration's trees and subtract their scores (excluding any folded
        boost-from-average bias, which self.scores tracks separately)."""
        if self.iter_ <= self.num_init_iteration:
            return
        k = self.num_tree_per_iteration
        for c in reversed(range(k)):
            tree = self.models.pop()
            arrays = _tree_to_arrays_stub(tree, self.train_set,
                                          exclude_bias=True)
            contrib = predict_bins_tree(
                arrays, self.bins, self.nan_bin_arr, self.bundle,
                self.hp.has_categorical)[:self.train_set.num_data]
            self.scores = self.scores.at[:, c].add(-contrib)
            # valid scores got this tree in train_one_iter; pop it there too
            for vi in range(len(self.valid_sets)):
                vc = predict_bins_tree(
                    arrays, self._valid_bins[vi], self.nan_bin_arr,
                    self.bundle, self.hp.has_categorical)
                self.valid_scores[vi] = \
                    self.valid_scores[vi].at[:, c].add(-vc)
        self.iter_ -= 1


def _leaf_path_masks(t: Tree, mpos: np.ndarray, mneg: np.ndarray,
                     depth: np.ndarray) -> None:
    """Fill one tree's leaf path-direction masks in place (shared by the
    matmul batch predictors): DFS from the root recording each leaf's
    (node, direction) path; children encode leaves as -(leaf_idx + 1).
    mpos/mneg: [L, ni]; depth: [L] (-1 stays for dead slots)."""
    if t.num_leaves <= 1:
        depth[0] = 0
        return
    stack = [(0, [])]
    while stack:
        node, path = stack.pop()
        for child, left in ((t.left_child[node], True),
                            (t.right_child[node], False)):
            p2 = path + [(node, left)]
            if child < 0:
                leaf = -int(child) - 1
                depth[leaf] = len(p2)
                for nd, lft in p2:
                    (mpos if lft else mneg)[leaf, nd] = 1.0
            else:
                stack.append((int(child), p2))


def _tree_to_arrays_stub(tree: Tree, dataset: Dataset,
                         exclude_bias: bool = False,
                         num_leaves_out: Optional[int] = None) -> TreeArrays:
    """Host Tree -> device TreeArrays (packed feature idx, bin thresholds).
    ``exclude_bias`` subtracts the folded boost-from-average bias so the
    result is the tree's own contribution to the score tensors.
    ``num_leaves_out`` pads every array to a common leaf capacity so
    trees of different sizes stack into one [T, ...] pytree."""
    L = max(num_leaves_out or tree.num_leaves, 2)
    ni = L - 1
    orig_to_packed = {o: p for p, o in enumerate(dataset.used_feature_idx)}
    sf = np.array([orig_to_packed.get(int(f), 0)
                   for f in tree.split_feature], np.int32)

    def pad(a, fill, dtype):
        out = np.full(ni, fill, dtype)
        out[:len(a)] = a[:ni]
        return out

    n_bins = dataset.device_n_bins()
    bitset = np.zeros((ni, n_bins), bool)
    for i in range(min(len(tree.split_feature), ni)):
        if not (tree.decision_type[i] & 1):
            continue
        csi = int(tree.cat_split_index[i])
        if csi < 0 or csi >= len(tree.cat_threshold):
            continue
        mapper = dataset.mappers[int(tree.split_feature[i])]
        table = mapper._cat_2_bin or {}
        for c in tree.cat_threshold[csi]:
            b = table.get(int(c))
            if b is not None and b < n_bins:
                bitset[i, b] = True

    return TreeArrays(
        split_feature=jnp.asarray(pad(sf, 0, np.int32)),
        split_bin=jnp.asarray(pad(tree.threshold_bin, 0, np.int32)),
        default_left=jnp.asarray(pad((tree.decision_type & 2) > 0, False, bool)),
        split_cat=jnp.asarray(pad((tree.decision_type & 1) > 0, False, bool)),
        left_child=jnp.asarray(pad(tree.left_child, -1, np.int32)),
        right_child=jnp.asarray(pad(tree.right_child, -1, np.int32)),
        split_gain=jnp.zeros(ni, jnp.float32),
        cat_bitset=jnp.asarray(bitset),
        internal_value=jnp.zeros(ni, jnp.float32),
        internal_count=jnp.zeros(ni, jnp.float32),
        leaf_value=jnp.asarray(np.concatenate(
            [tree.leaf_value - (tree.bias if exclude_bias else 0.0),
             np.zeros(L - tree.num_leaves)])[:L].astype(np.float32)),
        leaf_count=jnp.zeros(L, jnp.float32),
        leaf_weight=jnp.zeros(L, jnp.float32),
        leaf_depth=jnp.zeros(L, jnp.int32),
        leaf_path=jnp.zeros((L, dataset.num_features), bool),
        num_leaves=jnp.int32(tree.num_leaves),
    )
