"""Random-forest mode.

TPU-native re-design of the reference RF driver (reference:
src/boosting/rf.hpp ``RF : GBDT`` — bagging required, no shrinkage,
gradients always evaluated at the constant init score, ensemble output is
the AVERAGE of trees).  Averaging is materialized by scaling every tree by
1/num_iterations (known up front), which keeps saved models self-contained;
the reference instead re-normalizes scores incrementally.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .gbdt import GBDT


class RF(GBDT):
    def __init__(self, config, train_set, objective=None, metrics=None):
        super().__init__(config, train_set, objective, metrics)
        self.shrinkage_rate = 1.0 / max(1, int(config.num_iterations))
        # constant score at which gradients are evaluated
        self._grad_scores = self.scores

    def boosting_gradients(self):
        if self.num_tree_per_iteration == 1:
            g, h = self.objective.get_gradients(self._grad_scores[:, 0])
            return g[:, None], h[:, None]
        return self.objective.get_gradients(self._grad_scores)

    def _host_scores(self, scores):
        """Mid-training scores hold (sum of t trees)/T; rescale to the
        running average over t trees so metrics/early-stopping see the true
        ensemble (reference rf.hpp renormalizes incrementally)."""
        s = np.asarray(scores, np.float64)
        t = max(self.iter_, 1)
        T = max(1, int(self.config.num_iterations))
        init = self.init_scores[None, :]
        s = init + (s - init) * (T / t)
        return s[:, 0] if s.shape[1] == 1 else s
