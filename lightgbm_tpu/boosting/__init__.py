"""Boosting drivers (reference src/boosting/boosting.cpp:34 factory)."""

from ..config import Config
from ..io.dataset import Dataset
from .gbdt import GBDT


def create_boosting(config: Config, train_set: Dataset) -> GBDT:
    """reference Boosting::CreateBoosting — gbdt / dart / rf / goss."""
    from .dart import DART
    from .rf import RF
    kind = config.boosting
    if kind == "gbdt":
        return GBDT(config, train_set)
    if kind == "dart":
        return DART(config, train_set)
    if kind == "rf":
        return RF(config, train_set)
    from ..utils import log
    log.fatal(f"Unknown boosting type: {kind}")
