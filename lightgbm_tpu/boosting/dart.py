"""DART boosting (Dropouts meet Multiple Additive Regression Trees).

TPU-native re-design of the reference DART driver (reference:
src/boosting/dart.hpp ``DART : GBDT`` — per-iteration random tree dropout,
training against the residual of the non-dropped ensemble, then
normalization: dropped trees scaled k/(k+1), the new tree 1/(k+1)
(xgboost_dart_mode: k/(k+lr) and lr/(k+lr)); uniform_drop/skip_drop/max_drop
semantics follow dart.hpp).

Score bookkeeping is incremental on train AND valid tensors (the reference
re-adds via score updater the same way); tree contributions always exclude
the folded boost-from-average bias, which the score tensors track
separately, and rescaling uses ``Tree.scale_contribution`` so the bias
survives normalization.
"""

from __future__ import annotations

import numpy as np

from ..models.predict import predict_bins_tree
from .gbdt import GBDT, _tree_to_arrays_stub


class DART(GBDT):
    def __init__(self, config, train_set, objective=None, metrics=None):
        super().__init__(config, train_set, objective, metrics)
        self._drop_rng = np.random.default_rng(config.drop_seed)

    def _add_contrib(self, tree, cls_idx: int, factor: float) -> None:
        """Add ``factor`` x the tree's own contribution to train and valid
        score tensors."""
        arrs = _tree_to_arrays_stub(tree, self.train_set, exclude_bias=True)
        # self.bins may carry distributed-mode padding rows/columns
        contrib = predict_bins_tree(
            arrs, self.bins, self.nan_bin_arr, self.bundle,
            self.hp.has_categorical)[:self.train_set.num_data]
        self.scores = self.scores.at[:, cls_idx].add(contrib * factor)
        for vi in range(len(self.valid_sets)):
            vc = predict_bins_tree(arrs, self._valid_bins[vi], self.nan_bin_arr,
                                   self.bundle, self.hp.has_categorical)
            self.valid_scores[vi] = \
                self.valid_scores[vi].at[:, cls_idx].add(vc * factor)

    def train_one_iter(self, grad=None, hess=None) -> bool:
        drop_idx = self._select_drop()
        k = len(drop_idx)
        ktrees = self.num_tree_per_iteration
        for ti in drop_idx:
            self._add_contrib(self.models[ti], ti % ktrees, -1.0)

        start_model = len(self.models)
        finished = super().train_one_iter(grad, hess)

        if k > 0:
            lr = self.shrinkage_rate
            if self.config.xgboost_dart_mode:
                new_scale = lr / (k + lr)
                old_scale = k / (k + lr)
            else:
                new_scale = 1.0 / (k + 1.0)
                old_scale = k / (k + 1.0)
            # shrink the new trees' contribution from full lr to lr*new_scale
            for ti in range(start_model, len(self.models)):
                self._add_contrib(self.models[ti], ti % ktrees,
                                  new_scale - 1.0)
                self.models[ti].scale_contribution(new_scale)
            # scale dropped trees down, then re-add their reduced contribution
            for ti in drop_idx:
                self.models[ti].scale_contribution(old_scale)
                self._add_contrib(self.models[ti], ti % ktrees, 1.0)
        return finished

    def _select_drop(self):
        n_models = len(self.models)
        if n_models == 0:
            return []
        if self._drop_rng.random() < self.config.skip_drop:
            return []
        rate = self.config.drop_rate
        if self.config.uniform_drop:
            mask = self._drop_rng.random(n_models) < rate
            idx = np.nonzero(mask)[0]
        else:
            k = max(1, int(n_models * rate))
            idx = self._drop_rng.choice(n_models, size=min(k, n_models),
                                        replace=False)
        if self.config.max_drop > 0 and len(idx) > self.config.max_drop:
            idx = self._drop_rng.choice(idx, size=self.config.max_drop,
                                        replace=False)
        return sorted(int(i) for i in idx)
