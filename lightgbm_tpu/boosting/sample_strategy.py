"""Row sampling strategies: bagging and GOSS.

TPU-native re-design of the reference sampling layer (reference:
src/boosting/sample_strategy.{h,cpp} factory, src/boosting/bagging.hpp
``BaggingSampleStrategy``, src/boosting/goss.hpp ``GOSSStrategy``).

The reference materializes index subsets (``bag_data_indices_``) and
optionally copies a row subset of the Dataset; on TPU rows never move —
sampling is a boolean ``row_mask`` the histogram kernel folds into the value
channels, and GOSS's small-gradient amplification multiplies grad/hess
in place ((1-top_rate)/other_rate, goss.hpp:85-130).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import Metadata
from ..utils import log


class SampleStrategy:
    def __init__(self, config: Config, num_data: int):
        self.config = config
        self.num_data = num_data

    def sample(self, iter_: int, grad: jax.Array, hess: jax.Array,
               rng: np.random.Generator, metadata: Metadata
               ) -> Tuple[Optional[jax.Array], jax.Array, jax.Array]:
        """Returns (row_mask or None, grad, hess) — grad/hess possibly
        reweighted (GOSS)."""
        return None, grad, hess

    def device_sample_fn(self, metadata: Metadata):
        """A pure jit-safe ``(iter_idx, grad, hess) -> (row_mask or None,
        grad, hess)`` twin of ``sample`` for the fused training scan
        (GBDT.train_fused), or None when the strategy needs host state
        per iteration.  ``iter_idx`` may be a traced i32 scalar; grad and
        hess are [n, k].  Strategies that CAN run on device derive their
        per-iteration randomness from ``fold_in(PRNGKey(bagging_seed),
        iteration)`` in BOTH paths, so fused and classic training grow
        identical trees."""
        return None


class BaggingSampleStrategy(SampleStrategy):
    """bagging_fraction / bagging_freq / pos+neg bagging
    (reference bagging.hpp).

    The plain-fraction and pos/neg paths derive each resample from
    ``fold_in(PRNGKey(bagging_seed), resample_index)`` — a pure function
    of the iteration — so the fused scan (``device_sample_fn``) and the
    classic loop draw IDENTICAL masks.  By-query bagging keeps the host
    numpy draw (its query expansion is a host loop over boundaries)."""

    def __init__(self, config: Config, num_data: int):
        super().__init__(config, num_data)
        self._mask: Optional[jax.Array] = None
        self._mask_iter = -1
        self._use_pos_neg = (config.pos_bagging_fraction < 1.0 or
                             config.neg_bagging_fraction < 1.0)
        self._rng = np.random.default_rng(config.bagging_seed)

    def _active(self) -> bool:
        return self.config.bagging_freq > 0 and (
            self.config.bagging_fraction < 1.0 or self._use_pos_neg)

    def _by_query(self, metadata) -> bool:
        return (bool(self.config.bagging_by_query)
                and not self._use_pos_neg
                and metadata.query_boundaries is not None)

    def _device_mask(self, iter_idx, metadata: Metadata) -> jax.Array:
        """Pure per-iteration mask: freq-held resamples keyed on the
        resample index (iter // freq), matching bagging.hpp's cadence of
        resampling when ``iter % freq == 0`` and holding in between."""
        cfg = self.config
        n = self.num_data
        freq = max(int(cfg.bagging_freq), 1)
        key = jax.random.fold_in(jax.random.PRNGKey(cfg.bagging_seed),
                                 iter_idx // freq)
        u = jax.random.uniform(key, (n,))
        if self._use_pos_neg:
            if not hasattr(self, "_pos_dev"):
                self._pos_dev = jnp.asarray(
                    np.asarray(metadata.label) > 0)
            m = jnp.where(self._pos_dev,
                          u < cfg.pos_bagging_fraction,
                          u < cfg.neg_bagging_fraction)
        else:
            m = u < cfg.bagging_fraction
        # empty-mask rescue (bagging.hpp re-draws; here: deterministic)
        return jnp.where(jnp.any(m), m, m.at[0].set(True))

    def device_sample_fn(self, metadata):
        if not self._active():
            return None
        if self._by_query(metadata):
            return None

        def fn(iter_idx, grad, hess):
            return self._device_mask(iter_idx, metadata), grad, hess
        return fn

    def sample(self, iter_, grad, hess, rng, metadata):
        if not self._active():
            return None, grad, hess
        if not self._by_query(metadata):
            # same derivation as the fused path; recompute only at the
            # resample cadence
            freq = max(int(self.config.bagging_freq), 1)
            ridx = iter_ // freq
            if self._mask is None or ridx != self._mask_iter:
                self._mask = self._device_mask(jnp.int32(iter_), metadata)
                self._mask_iter = ridx
            return self._mask, grad, hess
        if iter_ % self.config.bagging_freq == 0 or self._mask is None:
            n = self.num_data
            qb = metadata.query_boundaries
            nq = len(qb) - 1
            qm = self._rng.random(nq) < self.config.bagging_fraction
            m = np.zeros(n, bool)
            for qi in np.nonzero(qm)[0]:
                m[qb[qi]:qb[qi + 1]] = True
            if not m.any():
                m[self._rng.integers(0, n)] = True
            self._mask = jnp.asarray(m)
        return self._mask, grad, hess


class GOSSStrategy(SampleStrategy):
    """Gradient-based one-side sampling (reference goss.hpp:18).

    Keep the top ``top_rate`` fraction by |g|*sqrt(h), uniformly sample
    ``other_rate`` of the rest and amplify their grad/hess by
    (1 - top_rate) / other_rate.
    """

    def __init__(self, config: Config, num_data: int):
        super().__init__(config, num_data)
        if config.top_rate + config.other_rate > 1.0:
            log.fatal("top_rate + other_rate cannot be larger than 1.0")

    def _warmup_iters(self) -> int:
        # reference starts GOSS after 1/learning_rate warmup iterations
        return min(int(1.0 / max(self.config.learning_rate, 1e-6)),
                   self.config.num_iterations // 2)

    def _goss_select(self, iter_idx, grad, hess):
        """Pure GOSS draw for one iteration: the per-iteration randomness
        is ``fold_in(PRNGKey(bagging_seed), iter)`` so the fused scan and
        the classic loop select identical rows."""
        n = self.num_data
        a, b = self.config.top_rate, self.config.other_rate
        top_k = max(1, int(n * a))
        score = jnp.sum(jnp.abs(grad) * jnp.sqrt(jnp.abs(hess) + 1e-12),
                        axis=1)
        # exact top-k membership (ties broken by index) — a >= threshold
        # test floods the top set when gradients tie (constant-|grad| l1)
        order = jnp.argsort(-score, stable=True)
        is_top = jnp.zeros(n, bool).at[order[:top_k]].set(True)
        if b <= 0.0:
            return is_top, grad, hess
        other_k = max(1, int(n * b))
        sub = jax.random.fold_in(
            jax.random.PRNGKey(self.config.bagging_seed), iter_idx)
        u = jax.random.uniform(sub, (n,))
        # sample from the non-top pool with probability other_k / pool_size
        pool = jnp.maximum(n - jnp.sum(is_top), 1)
        p_other = jnp.minimum(other_k / pool, 1.0)
        is_other = (~is_top) & (u < p_other)
        mask = is_top | is_other
        amp = (1.0 - a) / b
        mult = jnp.where(is_other, amp, 1.0)[:, None]
        return mask, grad * mult, hess * mult

    def device_sample_fn(self, metadata):
        warmup = self._warmup_iters()

        def fn(iter_idx, grad, hess):
            mask, g2, h2 = self._goss_select(iter_idx, grad, hess)
            # warmup rounds use the full data (all-ones mask, unscaled) —
            # a traced-iteration-safe jnp.where of the classic loop's
            # early-return
            active = iter_idx >= warmup
            mask = jnp.where(active, mask, True)
            g2 = jnp.where(active, g2, grad)
            h2 = jnp.where(active, h2, hess)
            return mask, g2, h2
        return fn

    def sample(self, iter_, grad, hess, rng, metadata):
        if iter_ < self._warmup_iters():
            return None, grad, hess
        return self._goss_select(jnp.int32(iter_), grad, hess)


def create_sample_strategy(config: Config, num_data: int) -> SampleStrategy:
    """Factory (reference sample_strategy.cpp:12-22)."""
    if config.data_sample_strategy == "goss":
        return GOSSStrategy(config, num_data)
    return BaggingSampleStrategy(config, num_data)
