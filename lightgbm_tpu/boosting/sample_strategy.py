"""Row sampling strategies: bagging and GOSS.

TPU-native re-design of the reference sampling layer (reference:
src/boosting/sample_strategy.{h,cpp} factory, src/boosting/bagging.hpp
``BaggingSampleStrategy``, src/boosting/goss.hpp ``GOSSStrategy``).

The reference materializes index subsets (``bag_data_indices_``) and
optionally copies a row subset of the Dataset; on TPU rows never move —
sampling is a boolean ``row_mask`` the histogram kernel folds into the value
channels, and GOSS's small-gradient amplification multiplies grad/hess
in place ((1-top_rate)/other_rate, goss.hpp:85-130).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import Metadata
from ..utils import log


class SampleStrategy:
    def __init__(self, config: Config, num_data: int):
        self.config = config
        self.num_data = num_data

    def sample(self, iter_: int, grad: jax.Array, hess: jax.Array,
               rng: np.random.Generator, metadata: Metadata
               ) -> Tuple[Optional[jax.Array], jax.Array, jax.Array]:
        """Returns (row_mask or None, grad, hess) — grad/hess possibly
        reweighted (GOSS)."""
        return None, grad, hess


class BaggingSampleStrategy(SampleStrategy):
    """bagging_fraction / bagging_freq / pos+neg bagging
    (reference bagging.hpp)."""

    def __init__(self, config: Config, num_data: int):
        super().__init__(config, num_data)
        self._mask: Optional[jax.Array] = None
        self._use_pos_neg = (config.pos_bagging_fraction < 1.0 or
                             config.neg_bagging_fraction < 1.0)
        self._rng = np.random.default_rng(config.bagging_seed)

    def _need_resample(self, iter_: int) -> bool:
        freq = self.config.bagging_freq
        if freq <= 0:
            return False
        full = (self.config.bagging_fraction < 1.0) or self._use_pos_neg
        if not full:
            return False
        return iter_ % freq == 0

    def sample(self, iter_, grad, hess, rng, metadata):
        if self.config.bagging_freq <= 0 or (
                self.config.bagging_fraction >= 1.0 and not self._use_pos_neg):
            return None, grad, hess
        if self._need_resample(iter_) or self._mask is None:
            n = self.num_data
            if self._use_pos_neg:
                lbl = np.asarray(metadata.label) > 0
                m = np.zeros(n, bool)
                m[lbl] = self._rng.random(int(lbl.sum())) < \
                    self.config.pos_bagging_fraction
                m[~lbl] = self._rng.random(int((~lbl).sum())) < \
                    self.config.neg_bagging_fraction
            elif self.config.bagging_by_query and \
                    metadata.query_boundaries is not None:
                qb = metadata.query_boundaries
                nq = len(qb) - 1
                qm = self._rng.random(nq) < self.config.bagging_fraction
                m = np.zeros(n, bool)
                for qi in np.nonzero(qm)[0]:
                    m[qb[qi]:qb[qi + 1]] = True
            else:
                m = self._rng.random(n) < self.config.bagging_fraction
            if not m.any():
                m[self._rng.integers(0, n)] = True
            self._mask = jnp.asarray(m)
        return self._mask, grad, hess


class GOSSStrategy(SampleStrategy):
    """Gradient-based one-side sampling (reference goss.hpp:18).

    Keep the top ``top_rate`` fraction by |g|*sqrt(h), uniformly sample
    ``other_rate`` of the rest and amplify their grad/hess by
    (1 - top_rate) / other_rate.
    """

    def __init__(self, config: Config, num_data: int):
        super().__init__(config, num_data)
        if config.top_rate + config.other_rate > 1.0:
            log.fatal("top_rate + other_rate cannot be larger than 1.0")
        self._key = jax.random.PRNGKey(config.bagging_seed)

    def sample(self, iter_, grad, hess, rng, metadata):
        # reference starts GOSS after 1/learning_rate warmup iterations
        warmup = min(int(1.0 / max(self.config.learning_rate, 1e-6)),
                     self.config.num_iterations // 2)
        if iter_ < warmup:
            return None, grad, hess
        n = self.num_data
        a, b = self.config.top_rate, self.config.other_rate
        top_k = max(1, int(n * a))
        score = jnp.sum(jnp.abs(grad) * jnp.sqrt(jnp.abs(hess) + 1e-12), axis=1)
        # exact top-k membership (ties broken by index) — a >= threshold test
        # floods the top set when gradients tie, e.g. constant-|grad| l1
        order = jnp.argsort(-score, stable=True)
        is_top = jnp.zeros(n, bool).at[order[:top_k]].set(True)
        if b <= 0.0:
            return is_top, grad, hess
        other_k = max(1, int(n * b))
        self._key, sub = jax.random.split(self._key)
        u = jax.random.uniform(sub, (n,))
        # sample from the non-top pool with probability other_k / pool_size
        pool = jnp.maximum(n - jnp.sum(is_top), 1)
        p_other = jnp.minimum(other_k / pool, 1.0)
        is_other = (~is_top) & (u < p_other)
        mask = is_top | is_other
        amp = (1.0 - a) / b
        mult = jnp.where(is_other, amp, 1.0)[:, None]
        return mask, grad * mult, hess * mult


def create_sample_strategy(config: Config, num_data: int) -> SampleStrategy:
    """Factory (reference sample_strategy.cpp:12-22)."""
    if config.data_sample_strategy == "goss":
        return GOSSStrategy(config, num_data)
    return BaggingSampleStrategy(config, num_data)
