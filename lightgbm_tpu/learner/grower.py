"""Leaf-wise (best-first) tree growth, fully on device.

TPU-native re-design of the reference single-device tree learner (reference:
src/treelearner/serial_tree_learner.cpp:179 ``Train`` and the CUDA blueprint
src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:158 — histogram →
subtract → best-split → partition per leaf).  Two deliberate departures:

  * The reference syncs ~1 SplitInfo device→host per split
    (cuda_single_gpu_tree_learner.cpp:276) — the latency bottleneck SURVEY.md
    §7 calls out.  Here the ENTIRE ``num_leaves - 1`` split loop runs inside
    one jitted ``lax.fori_loop``; early exit (no positive-gain split) becomes
    a sticky ``done`` flag that turns remaining iterations into no-ops.
  * The reference physically re-partitions row indices per split
    (cuda_data_partition.cu:288,907).  TPUs hate scatter, so rows never move:
    a dense ``leaf_of_row`` int32 map is updated with a masked ``where``, and
    per-leaf histograms mask through it.  The histogram-subtraction trick
    (serial_tree_learner.cpp:364-378) survives: only the SMALLER child gets a
    data pass, the sibling is parent − smaller.

Tree topology follows the reference array format (include/LightGBM/tree.h:26):
internal node i created at split i; left child keeps the parent's leaf index,
right child takes leaf index i+1; child pointers encode leaf l as ``-(l+1)``.

Under ``shard_map`` the same code runs data-parallel: histograms and root
stats are ``psum``-ed over the mesh axis, after which every device makes the
identical split decision — the TPU equivalent of the reference's
ReduceScatter/Allreduce dance (data_parallel_tree_learner.cpp:281,441).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.histogram import (bins_to_words, histogram_for_leaf_bucketed,
                             histogram_for_leaf_masked, overlap_enabled,
                             root_histogram, wants_packed_mirror)
from ..ops.split import (NEG_INF, VAR_CAT_BWD, VAR_CAT_FWD, VAR_CAT_ONEHOT,
                         VAR_NUM_RIGHT, SplitHyper, SplitResult,
                         categorical_left_bitset, find_best_split, leaf_gain,
                         leaf_output, smoothed_output)

_INF_BOUND = 3.0e38  # leaf-output bound sentinel (±"infinity" in f32)


class DeviceBundle(NamedTuple):
    """EFB expansion tables on device (io/bundling.py BundlePlan): the
    physical bin matrix / histograms cover bundle columns, these map them
    back to per-feature (virtual) bin space."""
    feat_col: jax.Array     # i32 [Fv] — physical column of each feature
    src_idx: jax.Array      # i32 [Fv, B] — virtual bin -> bundle bin
    valid: jax.Array        # bool [Fv, B]
    default_bin: jax.Array  # i32 [Fv] — implicit most-frequent bin
    inv_table: jax.Array    # i32 [Fv, B] — bundle value -> virtual bin


def _expand_hist(hist_b: jax.Array, bundle: DeviceBundle, sum_g, sum_h,
                 count) -> jax.Array:
    """Bundle-level leaf histogram [Fb, B, C] -> virtual [Fv, B, C].

    Each feature's stored bins are gathered from its bundle column; the
    implicit default bin is completed from the leaf totals (the reference's
    most-freq-bin completion, Dataset::FixHistogram dataset.h:760)."""
    B = hist_b.shape[1]
    hv = hist_b[bundle.feat_col[:, None], bundle.src_idx]       # [Fv, B, C]
    hv = hv * bundle.valid[..., None]
    rest = jnp.sum(hv, axis=1)                                  # [Fv, C]
    total = jnp.stack([sum_g, sum_h, count,
                       jnp.zeros_like(count)])                  # [C]
    onehot = (lax.iota(jnp.int32, B)[None, :]
              == bundle.default_bin[:, None])                   # [Fv, B]
    return hv + onehot[..., None] * (total[None, None, :] - rest[:, None, :])


def _expand_hist_col(hcol: jax.Array, bundle: DeviceBundle,
                     feat: jax.Array, sum_g, sum_h, count) -> jax.Array:
    """One feature's virtual histogram [B, C] from its bundle COLUMN hist.

    The column must already be globally reduced (psum) before expansion when
    the totals are global — the default-bin completion is total − rest and
    mixing global totals with a local rest double-counts."""
    hv = hcol[bundle.src_idx[feat]] * bundle.valid[feat][:, None]
    rest = jnp.sum(hv, axis=0)
    total = jnp.stack([sum_g, sum_h, count, jnp.zeros_like(count)])
    return hv.at[bundle.default_bin[feat]].add(total - rest)


def _feature_bin_of_rows(bins_t: jax.Array, bundle: Optional[DeviceBundle],
                         feat: jax.Array) -> jax.Array:
    """Virtual bin of every row for feature ``feat`` (partition step).
    ``bins_t`` is the TRANSPOSED [F, n] matrix so the dynamic column access
    is one contiguous row read, not an n-element strided gather."""
    if bundle is None:
        return jnp.take(bins_t, feat, axis=0).astype(jnp.int32)
    col = jnp.take(bins_t, bundle.feat_col[feat], axis=0).astype(jnp.int32)
    return bundle.inv_table[feat, col]


class TreeArrays(NamedTuple):
    """Struct-of-arrays tree (reference tree.h flat arrays)."""
    split_feature: jax.Array   # i32 [L-1] packed feature idx (-1 = unused node)
    split_bin: jax.Array       # i32 [L-1] bin threshold
    default_left: jax.Array    # bool [L-1]
    split_cat: jax.Array       # bool [L-1] one-hot categorical split
    left_child: jax.Array      # i32 [L-1]; >=0 node, negative -(leaf+1)
    right_child: jax.Array     # i32 [L-1]
    split_gain: jax.Array      # f32 [L-1]
    cat_bitset: jax.Array      # bool [L-1, B] — bins going left (cat splits)
    internal_value: jax.Array  # f32 [L-1] node output before split (SHAP)
    internal_count: jax.Array  # f32 [L-1]
    leaf_value: jax.Array      # f32 [L]
    leaf_count: jax.Array      # f32 [L]
    leaf_weight: jax.Array     # f32 [L] sum of hessians
    leaf_depth: jax.Array      # i32 [L]
    leaf_path: jax.Array       # bool [L, F] features on each leaf's path
    num_leaves: jax.Array      # i32 scalar — actual leaves grown


class CegbInput(NamedTuple):
    """Cost-Effective Gradient Boosting penalties + acquisition state
    (reference cost_effective_gradient_boosting.hpp): all pre-multiplied by
    cegb_tradeoff.  ``used_rows`` is None unless lazy penalties are set."""
    split_pen: jax.Array       # f32 scalar — cegb_penalty_split
    coupled_pen: jax.Array     # f32 [F] — once-per-feature penalty
    lazy_pen: jax.Array        # f32 [F] — per-(row,feature) penalty
    feature_used: jax.Array    # bool [F] — features already in the model
    used_rows: Optional[jax.Array]  # bool [n, F] — (row, feature) acquired


class _GrowState(NamedTuple):
    tree: TreeArrays
    leaf_of_row: jax.Array     # i32 [n]
    hist: jax.Array            # f32 [L, F, B, C]
    sum_g: jax.Array           # f32 [L]
    sum_h: jax.Array
    count: jax.Array
    best_gain: jax.Array       # f32 [L]
    best_feat: jax.Array       # i32 [L]
    best_thr: jax.Array
    best_dl: jax.Array         # bool [L]
    best_cat: jax.Array        # bool [L]
    best_var: jax.Array        # i32 [L] winning VAR_* variant
    best_lg: jax.Array         # f32 [L] left child sums of cached best split
    best_lh: jax.Array
    best_lc: jax.Array
    parent_node: jax.Array     # i32 [L] internal node owning this leaf (-1 root)
    parent_side: jax.Array     # i32 [L] 0 left / 1 right
    leaf_min: jax.Array        # f32 [L] output lower bound (monotone)
    leaf_max: jax.Array        # f32 [L] output upper bound
    leaf_lo: jax.Array         # i32 [L, F] bin-space box lower (intermediate
    leaf_hi: jax.Array         # i32 [L, F] monotone method; dummy [1,1] else)
    path_feats: jax.Array      # bool [L, F] features used on leaf's path
    force_failed: jax.Array    # bool scalar — forced-split BFS aborted
    done: jax.Array            # bool scalar
    cegb_used: jax.Array       # bool [F] (dummy [1] when CEGB off)
    cegb_rows: jax.Array       # bool [n, F] (dummy [1, 1] when off/no lazy)


def _empty_tree(num_leaves: int, n_bins: int, num_f: int) -> TreeArrays:
    li = num_leaves - 1
    return TreeArrays(
        split_feature=jnp.full((li,), -1, jnp.int32),
        split_bin=jnp.zeros((li,), jnp.int32),
        default_left=jnp.zeros((li,), bool),
        split_cat=jnp.zeros((li,), bool),
        left_child=jnp.full((li,), -1, jnp.int32),
        right_child=jnp.full((li,), -1, jnp.int32),
        split_gain=jnp.zeros((li,), jnp.float32),
        cat_bitset=jnp.zeros((li, n_bins), bool),
        internal_value=jnp.zeros((li,), jnp.float32),
        internal_count=jnp.zeros((li,), jnp.float32),
        leaf_value=jnp.zeros((num_leaves,), jnp.float32),
        leaf_count=jnp.zeros((num_leaves,), jnp.float32),
        leaf_weight=jnp.zeros((num_leaves,), jnp.float32),
        leaf_depth=jnp.zeros((num_leaves,), jnp.int32),
        leaf_path=jnp.zeros((num_leaves, num_f), bool),
        num_leaves=jnp.int32(1),
    )


def gather_forced_split(hf: jax.Array, pg, ph, pc, ft, is_cat_f, nan_bin_f,
                        hp: SplitHyper):
    """Stats/validity of a PRESCRIBED split from a leaf's histogram column
    (reference FeatureHistogram::GatherInfoForThreshold, invoked by
    ForceSplits serial_tree_learner.cpp:620).  ``hf``: f32 [B, C] expanded
    histogram of the forced feature.  Returns (lg, lh, lc, gain, ok) —
    the SINGLE implementation shared by the strict and batched learners.
    """
    b_i = lax.iota(jnp.int32, hp.n_bins)
    lm = jnp.where(is_cat_f, b_i == ft, (b_i <= ft) & (b_i != nan_bin_f))
    lmf = lm.astype(hf.dtype)
    lg = jnp.sum(hf[:, 0] * lmf)
    lh = jnp.sum(hf[:, 1] * lmf)
    lc = jnp.sum(hf[:, 2] * lmf)
    rg, rh, rc = pg - lg, ph - lh, pc - lc
    gain = (leaf_gain(lg, lh, hp.lambda_l1, hp.lambda_l2)
            + leaf_gain(rg, rh, hp.lambda_l1, hp.lambda_l2)
            - leaf_gain(pg, ph, hp.lambda_l1, hp.lambda_l2)
            - hp.min_gain_to_split)
    ok = ((lc >= hp.min_data_in_leaf) & (rc >= hp.min_data_in_leaf)
          & (lh >= hp.min_sum_hessian_in_leaf)
          & (rh >= hp.min_sum_hessian_in_leaf) & (gain > 0.0))
    return lg, lh, lc, gain, ok


def sample_features_bynode(mask: Optional[jax.Array], key: jax.Array,
                           frac: float, num_f: int) -> jax.Array:
    """Random per-node feature subset (reference col_sampler.hpp
    feature_fraction_bynode): keep ceil-ish frac of the allowed features,
    uniformly.  SINGLE implementation shared by the strict and batched
    growers so their sampling stays bit-identical."""
    base = jnp.ones((num_f,), bool) if mask is None else mask
    u = jax.random.uniform(key, (num_f,))
    u = jnp.where(base, u, -1.0)
    cnt = jnp.maximum((base.sum() * frac).astype(jnp.int32), 1)
    kth = jnp.sort(u)[num_f - cnt]
    return base & (u >= kth) & (u >= 0)


def pv_vote_best_split(h_phys, g_, h_, c_, depth, fm, parent_output, lmin,
                       lmax, key, *, hp, hp_vote, num_bins, nan_bin, is_cat,
                       monotone, bundle, num_f, top_k, axis_name
                       ) -> "SplitResult":
    """PV-Tree two-phase vote for ONE leaf (reference
    voting_parallel_tree_learner.cpp:151 GlobalVoting + :184
    CopyLocalHistogram), shared by the strict grower's voting branch and
    the batched grower's rounds so the protocol has one definition.

    ``h_phys`` is the leaf's LOCAL shard histogram; ``g_/h_/c_`` are the
    GLOBAL leaf totals.  Phase 1 scores every feature on the local
    histogram at the 1/num_shards-relaxed thresholds in ``hp_vote``;
    phase 2 psums each shard's top-``top_k`` proposals into a vote,
    reduces ONLY the 2·top_k winners' histogram slices globally, and
    finds the split there.  Returned ``feature`` is the global index and
    the gain carries the depth gate."""
    from ..ops.split import find_best_split as _fbs
    lg_ = jnp.sum(h_phys[0, :, 0])
    lh_ = jnp.sum(h_phys[0, :, 1])
    lc_ = jnp.sum(h_phys[0, :, 2])
    hv_local = h_phys if bundle is None else \
        _expand_hist(h_phys, bundle, lg_, lh_, lc_)
    pf: list = []
    _fbs(hv_local, lg_, lh_, lc_, num_bins, nan_bin, is_cat, fm, hp_vote,
         monotone=monotone, parent_output=parent_output, leaf_min=lmin,
         leaf_max=lmax, depth=depth, rng_key=key, per_feature_out=pf)
    gains_local = pf[0]                                        # [F]
    k = min(top_k, num_f)
    _, local_top = lax.top_k(gains_local, k)
    votes = lax.psum(jnp.zeros((num_f,), jnp.float32)
                     .at[local_top].set(1.0), axis_name)
    gain_sum = lax.psum(jnp.clip(gains_local, -1e9, 1e9), axis_name)
    score = votes * 1e12 + gain_sum
    sel_k = min(2 * top_k, num_f)
    _, sel = lax.top_k(score, sel_k)                           # [2k]
    h_sel = lax.psum(hv_local[sel], axis_name)                 # [2k, B, C]
    res = _fbs(h_sel, g_, h_, c_, num_bins[sel], nan_bin[sel], is_cat[sel],
               None if fm is None else fm[sel], hp,
               monotone=None if monotone is None else monotone[sel],
               parent_output=parent_output, leaf_min=lmin, leaf_max=lmax,
               depth=depth, rng_key=key)
    res = res._replace(feature=sel[res.feature])
    depth_ok = (hp.max_depth <= 0) | (depth < hp.max_depth)
    from ..ops.split import NEG_INF as _NEG_INF
    return res._replace(gain=jnp.where(depth_ok, res.gain, _NEG_INF))


def _child_best(hist: jax.Array, g: jax.Array, h: jax.Array, c: jax.Array,
                depth: jax.Array, num_bins, nan_bin, is_cat, feature_mask,
                hp: SplitHyper, monotone=None, parent_output=0.0,
                leaf_min=None, leaf_max=None, rng_key=None) -> SplitResult:
    res = find_best_split(hist, g, h, c, num_bins, nan_bin, is_cat,
                          feature_mask, hp, monotone=monotone,
                          parent_output=parent_output, leaf_min=leaf_min,
                          leaf_max=leaf_max, depth=depth, rng_key=rng_key)
    depth_ok = (hp.max_depth <= 0) | (depth < hp.max_depth)
    return res._replace(gain=jnp.where(depth_ok, res.gain, NEG_INF))


@functools.partial(jax.jit, static_argnames=("hp", "axis_name",
                                             "parallel_mode", "top_k",
                                             "num_shards", "overlap"))
def grow_tree(bins: jax.Array, grad: jax.Array, hess: jax.Array,
              row_mask: Optional[jax.Array], num_bins: jax.Array,
              nan_bin: jax.Array, is_cat: jax.Array,
              feature_mask: Optional[jax.Array], hp: SplitHyper,
              axis_name: Optional[str] = None,
              monotone: Optional[jax.Array] = None,
              rng_key: Optional[jax.Array] = None,
              interaction_sets: Optional[jax.Array] = None,
              forced: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
              bundle: Optional[DeviceBundle] = None,
              parallel_mode: str = "data", top_k: int = 20,
              num_shards: int = 1,
              cegb: Optional[CegbInput] = None,
              hist_scale: Optional[jax.Array] = None,
              bins_words: Optional[jax.Array] = None,
              overlap: bool = False):
    """Grow one tree; returns (TreeArrays, leaf_of_row).

    bins: uint8 [n, F]; grad/hess: f32 [n]; row_mask: bool [n] or None
    (bagging); num_bins/nan_bin: i32 [F]; is_cat: bool [F];
    feature_mask: bool [F] or None (feature_fraction).
    rng_key: PRNG key for per-node feature sampling / extra_trees (must be
    identical on all shards under shard_map).  interaction_sets: bool [S, F]
    allowed-together feature sets (reference col_sampler.hpp:91 GetByNode —
    a leaf may only split on features from sets containing its whole path).
    forced: (leaf, feature, bin_threshold) i32 [L-1] arrays (−1 padded) —
    host-precomputed BFS order of forcedsplits_filename JSON (reference
    serial_tree_learner.cpp:620 ForceSplits); a forced entry that fails
    validity (min_data / non-positive gain) aborts the remaining schedule,
    mirroring the reference's ignore-with-warning.
    ``leaf_of_row`` is returned for ALL rows (bagged-out rows included), so the
    boosting score update is a pure gather — the reference's train-score
    shortcut through DataPartition (score_updater.hpp).

    ``bundle``: EFB tables (io/bundling.py).  When set, ``bins`` holds the
    BUNDLED physical columns; histograms are built per bundle and expanded to
    per-feature space only for split finding.

    ``parallel_mode`` selects the distributed strategy under ``axis_name``
    (SURVEY.md §2.7; all three reference parallel learners):
      * "data"    — rows sharded; full-histogram psum (the reference's
                    ReduceScatter+Allreduce dataflow).
      * "voting"  — rows sharded; PV-Tree 2-phase vote: each shard proposes
                    its local top-``top_k`` features by gain, the vote picks
                    2·top_k candidates, and ONLY their histogram slices are
                    psum-ed (voting_parallel_tree_learner.cpp:151,184 —
                    O(top_k·bins) comm, independent of feature count).
                    ``num_shards`` must equal the mesh axis size; local
                    validity thresholds are scaled by 1/num_shards (:62-64).
      * "feature" — FEATURES sharded (bins/num_bins/... hold this shard's
                    columns; every shard holds ALL rows): local best split,
                    cross-shard argmax sync, owner broadcasts the partition
                    (feature_parallel_tree_learner.cpp:62-79
                    SyncUpGlobalBestSplit).  EFB/monotone/forced/interaction
                    are not supported in this mode.
    """
    n = bins.shape[0]
    num_f = bins.shape[1] if bundle is None else bundle.feat_col.shape[0]
    L = hp.num_leaves
    mask_f = jnp.ones_like(grad) if row_mask is None else row_mask.astype(grad.dtype)
    mode = parallel_mode if axis_name is not None else "data"
    if mode == "feature" and axis_name is not None:
        assert bundle is None and forced is None and monotone is None \
            and interaction_sets is None, \
            "feature-parallel composes only with the core split path"
    if cegb is not None:
        assert axis_name is None or mode == "data", \
            "CEGB composes with serial/data-parallel modes only"

    def cegb_penalty(used_f, used_rows, leaf_mask, leaf_count):
        """Per-feature gain penalty for one leaf (CEGB DeltaGain:
        split_pen scales with the leaf's data count)."""
        pen = cegb.split_pen * leaf_count \
            + jnp.where(used_f, 0.0, cegb.coupled_pen)
        if cegb.used_rows is not None:
            cnt = jnp.einsum("n,nf->f", leaf_mask.astype(jnp.float32),
                             (~used_rows).astype(jnp.float32))
            if axis_name is not None:
                cnt = lax.psum(cnt, axis_name)
            pen = pen + cegb.lazy_pen * cnt
        return pen
    # axis passed to histogram builders: only the data mode psums full hists
    hist_axis = axis_name if mode == "data" else None

    use_bynode = hp.feature_fraction_bynode < 1.0 and rng_key is not None

    def node_feature_mask(path_f: jax.Array, key) -> Optional[jax.Array]:
        """Per-node allowed features: tree-level mask ∧ interaction
        constraints ∧ by-node random subset."""
        m = feature_mask
        if interaction_sets is not None:
            fits = jnp.all(interaction_sets | ~path_f[None, :], axis=1)  # [S]
            allowed = jnp.any(interaction_sets & fits[:, None],
                              axis=0) | path_f
            m = allowed if m is None else (m & allowed)
        if use_bynode:
            m = sample_features_bynode(m, key, hp.feature_fraction_bynode,
                                       num_f)
        return m

    # transposed layout once per tree: the histogram kernel and the
    # partition column reads both want rows on the minor (lane) dimension.
    # optimization_barrier forces ONE materialization — without it XLA
    # rematerializes the 28-byte-strided transpose inside every split
    # iteration (measured 2.5x on the whole tree loop)
    bins_t = lax.optimization_barrier(bins.T)
    # packed-word mirror for the round-6 packed histogram mode (kept
    # resident per tree like bins_t; ``bins_words`` lets the booster ship
    # the dataset's construction-time mirror instead of re-deriving it)
    if wants_packed_mirror(hp.hist_kernel, hp.n_bins):
        words_t = lax.optimization_barrier(
            (bins_to_words(bins) if bins_words is None else bins_words).T)
    else:
        words_t = None
    # quantized-levels mode (ops/quantize.py): grad/hess hold integer
    # levels; one deterministic multiply restores real units right after
    # each exact integer histogram accumulation
    scale_vec = None
    if hist_scale is not None:
        scale_vec = jnp.concatenate(
            [hist_scale.astype(jnp.float32), jnp.ones((2,), jnp.float32)])

    def _scaled(h):
        return h if scale_vec is None else h * scale_vec

    hist0_b = _scaled(root_histogram(
        bins_t, grad, hess, row_mask, n_bins=hp.n_bins,
        rows_per_block=hp.rows_per_block,
        hist_dtype=hp.hist_dtype, axis_name=hist_axis,
        hist_kernel=hp.hist_kernel, bins_words_t=words_t,
        overlap=overlap))
    g0 = jnp.sum(grad * mask_f)
    h0 = jnp.sum(hess * mask_f)
    c0 = jnp.sum(mask_f)
    if hist_scale is not None:
        g0 = g0 * hist_scale[0]
        h0 = h0 * hist_scale[1]
    if axis_name is not None and mode != "feature":
        # feature mode holds ALL rows on every shard: sums already global
        if overlap_enabled(overlap):
            # one [3]-vector psum (bit-identical per-element sums),
            # one fewer blocking collective round-trip
            g0, h0, c0 = lax.psum(jnp.stack([g0, h0, c0]), axis_name)
        else:
            g0 = lax.psum(g0, axis_name)
            h0 = lax.psum(h0, axis_name)
            c0 = lax.psum(c0, axis_name)

    if mode == "voting" and axis_name is not None:
        # locally relaxed validity thresholds
        # (voting_parallel_tree_learner.cpp:62-64)
        hp_vote = dataclasses.replace(
            hp, min_data_in_leaf=max(1, hp.min_data_in_leaf // num_shards),
            min_sum_hessian_in_leaf=hp.min_sum_hessian_in_leaf / num_shards)

    def child_best(h_phys, g_, h_, c_, depth, fm, parent_output, lmin, lmax,
                   key, pen=None, adv=None) -> SplitResult:
        """Best split for one leaf from its PHYSICAL (bundle-column)
        histogram — local shard hist under voting/feature modes, global
        otherwise.  Returns a SplitResult whose ``feature`` is the virtual
        (voting) / global (feature-parallel) index."""
        if mode == "voting" and axis_name is not None:
            return pv_vote_best_split(
                h_phys, g_, h_, c_, depth, fm, parent_output, lmin, lmax,
                key, hp=hp, hp_vote=hp_vote, num_bins=num_bins,
                nan_bin=nan_bin, is_cat=is_cat, monotone=monotone,
                bundle=bundle, num_f=num_f, top_k=top_k,
                axis_name=axis_name)
        if mode == "feature" and axis_name is not None:
            res = _child_best(h_phys, g_, h_, c_, depth, num_bins, nan_bin,
                              is_cat, fm, hp, parent_output=parent_output,
                              leaf_min=lmin, leaf_max=lmax, rng_key=key)
            # cross-shard best-split argmax (SyncUpGlobalBestSplit,
            # feature_parallel_tree_learner.cpp:62-79): gather the packed
            # candidate of every shard, keep the best, globalize the index
            rank = lax.axis_index(axis_name)
            gfeat = res.feature + rank * num_f
            packed = jnp.stack([
                res.gain, gfeat.astype(jnp.float32),
                res.threshold.astype(jnp.float32),
                res.default_left.astype(jnp.float32),
                res.is_categorical.astype(jnp.float32),
                res.variant.astype(jnp.float32),
                res.left_sum_g, res.left_sum_h, res.left_count,
                res.right_sum_g, res.right_sum_h, res.right_count])
            allp = lax.all_gather(packed, axis_name)           # [d, 12]
            b = allp[jnp.argmax(allp[:, 0])]
            return SplitResult(
                gain=b[0], feature=b[1].astype(jnp.int32),
                threshold=b[2].astype(jnp.int32),
                default_left=b[3] > 0.5, is_categorical=b[4] > 0.5,
                variant=b[5].astype(jnp.int32),
                left_sum_g=b[6], left_sum_h=b[7], left_count=b[8],
                right_sum_g=b[9], right_sum_h=b[10], right_count=b[11])
        hv = h_phys if bundle is None else \
            _expand_hist(h_phys, bundle, g_, h_, c_)
        res = find_best_split(hv, g_, h_, c_, num_bins, nan_bin, is_cat,
                              fm, hp, monotone=monotone,
                              parent_output=parent_output, leaf_min=lmin,
                              leaf_max=lmax, depth=depth, rng_key=key,
                              gain_penalty=pen, adv_bounds=adv)
        depth_ok = (hp.max_depth <= 0) | (depth < hp.max_depth)
        return res._replace(gain=jnp.where(depth_ok, res.gain, NEG_INF))

    root_out = leaf_output(g0, h0, hp.lambda_l1, hp.lambda_l2,
                           hp.max_delta_step)
    inf = jnp.float32(_INF_BOUND)
    empty_path = jnp.zeros((num_f,), bool)
    if rng_key is not None:
        key_root, key_er = jax.random.split(jax.random.fold_in(rng_key, L))
    else:
        key_root = key_er = None
    fm_root = node_feature_mask(empty_path, key_root)
    if cegb is not None:
        cegb_used0 = cegb.feature_used
        cegb_rows0 = cegb.used_rows if cegb.used_rows is not None \
            else jnp.zeros((1, 1), bool)
        pen0 = cegb_penalty(cegb_used0, cegb_rows0, mask_f, c0)
    else:
        cegb_used0 = jnp.zeros((1,), bool)
        cegb_rows0 = jnp.zeros((1, 1), bool)
        pen0 = None
    best0 = child_best(hist0_b, g0, h0, c0, jnp.int32(0), fm_root,
                       root_out, -inf, inf, key_er, pen=pen0)

    use_boxes = hp.use_monotone and hp.monotone_method in ("intermediate", "advanced")
    use_adv = hp.use_monotone and hp.monotone_method == "advanced"
    tree = _empty_tree(L, hp.n_bins, num_f)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(root_out),
        leaf_count=tree.leaf_count.at[0].set(c0),
        leaf_weight=tree.leaf_weight.at[0].set(h0),
    )
    C = hist0_b.shape[-1]
    n_cols = bins.shape[1]  # physical histogram columns (== num_f unbundled)
    state = _GrowState(
        tree=tree,
        leaf_of_row=jnp.zeros((n,), jnp.int32),
        hist=jnp.zeros((L, n_cols, hp.n_bins, C),
                       jnp.float32).at[0].set(hist0_b),
        sum_g=jnp.zeros((L,), jnp.float32).at[0].set(g0),
        sum_h=jnp.zeros((L,), jnp.float32).at[0].set(h0),
        count=jnp.zeros((L,), jnp.float32).at[0].set(c0),
        best_gain=jnp.full((L,), NEG_INF, jnp.float32).at[0].set(best0.gain),
        best_feat=jnp.zeros((L,), jnp.int32).at[0].set(best0.feature),
        best_thr=jnp.zeros((L,), jnp.int32).at[0].set(best0.threshold),
        best_dl=jnp.zeros((L,), bool).at[0].set(best0.default_left),
        best_cat=jnp.zeros((L,), bool).at[0].set(best0.is_categorical),
        best_var=jnp.zeros((L,), jnp.int32).at[0].set(best0.variant),
        best_lg=jnp.zeros((L,), jnp.float32).at[0].set(best0.left_sum_g),
        best_lh=jnp.zeros((L,), jnp.float32).at[0].set(best0.left_sum_h),
        best_lc=jnp.zeros((L,), jnp.float32).at[0].set(best0.left_count),
        parent_node=jnp.full((L,), -1, jnp.int32),
        parent_side=jnp.zeros((L,), jnp.int32),
        leaf_min=jnp.full((L,), -_INF_BOUND, jnp.float32),
        leaf_max=jnp.full((L,), _INF_BOUND, jnp.float32),
        leaf_lo=(jnp.zeros((L, num_f), jnp.int32)
                 if use_boxes else jnp.zeros((1, 1), jnp.int32)),
        leaf_hi=(jnp.zeros((L, num_f), jnp.int32)
                 .at[0].set(num_bins.astype(jnp.int32))
                 if use_boxes else jnp.zeros((1, 1), jnp.int32)),
        path_feats=jnp.zeros((L, num_f), bool),
        force_failed=jnp.bool_(False),
        done=jnp.bool_(False),
        cegb_used=cegb_used0,
        cegb_rows=cegb_rows0,
    )

    def body(i, st: _GrowState) -> _GrowState:
        bl = jnp.argmax(st.best_gain).astype(jnp.int32)
        feat = st.best_feat[bl]
        thr = st.best_thr[bl]
        dl = st.best_dl[bl]
        catl = st.best_cat[bl]
        var = st.best_var[bl]
        gain_rec = st.best_gain[bl]
        ch_lg, ch_lh, ch_lc = st.best_lg[bl], st.best_lh[bl], st.best_lc[bl]
        do = (~st.done) & (gain_rec > 0.0)

        if forced is not None:
            f_leaf, f_feat, f_thr = forced
            f_active = (f_leaf[i] >= 0) & ~st.force_failed & ~st.done
            fl = jnp.maximum(f_leaf[i], 0)
            ff, ft = f_feat[i], f_thr[i]
            hf_col = st.hist[fl, ff if bundle is None
                             else bundle.feat_col[ff]]         # [B, C]
            if mode == "voting" and axis_name is not None:
                hf_col = lax.psum(hf_col, axis_name)  # local -> global
            hf = hf_col if bundle is None else \
                _expand_hist_col(hf_col, bundle, ff, st.sum_g[fl],
                                 st.sum_h[fl], st.count[fl])
            pgf, phf, pcf = st.sum_g[fl], st.sum_h[fl], st.count[fl]
            lgf, lhf, lcf, gf, ok_f = gather_forced_split(
                hf, pgf, phf, pcf, ft, is_cat[ff], nan_bin[ff], hp)
            use_f = f_active & ok_f
            st = st._replace(force_failed=st.force_failed
                             | (f_active & ~ok_f))
            bl = jnp.where(use_f, fl, bl)
            feat = jnp.where(use_f, ff, feat)
            thr = jnp.where(use_f, ft, thr)
            dl = jnp.where(use_f, False, dl)
            catl = jnp.where(use_f, is_cat[ff], catl)
            var = jnp.where(use_f,
                            jnp.where(is_cat[ff], VAR_CAT_ONEHOT,
                                      VAR_NUM_RIGHT), var)
            gain_rec = jnp.where(use_f, gf, gain_rec)
            ch_lg = jnp.where(use_f, lgf, st.best_lg[bl])
            ch_lh = jnp.where(use_f, lhf, st.best_lh[bl])
            ch_lc = jnp.where(use_f, lcf, st.best_lc[bl])
            do = (~st.done) & (use_f | (st.best_gain[bl] > 0.0))

        def no_split(st: _GrowState) -> _GrowState:
            return st._replace(done=jnp.bool_(True))

        def split(st: _GrowState) -> _GrowState:
            t = st.tree
            new_leaf = i + 1

            # feature-parallel: locate the owning shard of the winning
            # (global) feature; only it holds the column/histogram
            if mode == "feature" and axis_name is not None:
                rank = lax.axis_index(axis_name)
                f_local = feat - rank * num_f
                owns = (f_local >= 0) & (f_local < num_f)
                f_safe = jnp.clip(f_local, 0, num_f - 1)
            else:
                owns = jnp.bool_(True)
                f_safe = feat

            # left-category bitset, derived from the PARENT histogram (still
            # at st.hist[bl] at this point)
            if hp.has_categorical:
                pf_col = st.hist[bl, f_safe if bundle is None
                                 else bundle.feat_col[f_safe]]
                if mode == "voting" and axis_name is not None:
                    pf_col = lax.psum(pf_col, axis_name)
                hist_pf = pf_col if bundle is None else \
                    _expand_hist_col(pf_col, bundle, f_safe,
                                     st.sum_g[bl], st.sum_h[bl],
                                     st.count[bl])
                bitset = categorical_left_bitset(hist_pf,
                                                 num_bins[f_safe], var, thr,
                                                 hp)
                if mode == "feature" and axis_name is not None:
                    # owner broadcasts its bitset
                    bitset = lax.psum(
                        jnp.where(owns, bitset.astype(jnp.float32), 0.0),
                        axis_name) > 0.5
                bitset = bitset & catl
            else:
                bitset = jnp.zeros((hp.n_bins,), bool)

            # -- link the parent's child pointer to the new internal node i
            p = st.parent_node[bl]
            side = st.parent_side[bl]
            ps = jnp.maximum(p, 0)
            lc_arr = t.left_child.at[ps].set(
                jnp.where((p >= 0) & (side == 0), i, t.left_child[ps]))
            rc_arr = t.right_child.at[ps].set(
                jnp.where((p >= 0) & (side == 1), i, t.right_child[ps]))

            # -- record split at internal node i
            pg, ph, pc = st.sum_g[bl], st.sum_h[bl], st.count[bl]
            lc_arr = lc_arr.at[i].set(-(bl + 1))
            rc_arr = rc_arr.at[i].set(-(new_leaf + 1))
            t = t._replace(
                split_feature=t.split_feature.at[i].set(feat),
                split_bin=t.split_bin.at[i].set(thr),
                default_left=t.default_left.at[i].set(dl),
                split_cat=t.split_cat.at[i].set(catl),
                left_child=lc_arr, right_child=rc_arr,
                split_gain=t.split_gain.at[i].set(gain_rec),
                cat_bitset=t.cat_bitset.at[i].set(bitset),
                internal_value=t.internal_value.at[i].set(
                    leaf_output(pg, ph, hp.lambda_l1, hp.lambda_l2,
                                hp.max_delta_step)),
                internal_count=t.internal_count.at[i].set(pc),
                num_leaves=jnp.int32(i + 2),
            )

            # -- partition (dense map update, no data movement); under
            # feature-parallel only the owner has the column, so its go-left
            # vector is broadcast (the reference instead re-splits from the
            # synced SplitInfo since every rank holds all features' data —
            # here columns are truly sharded, so one [n] psum replaces it)
            col = _feature_bin_of_rows(bins_t, bundle, f_safe)
            nb = nan_bin[f_safe]
            go_left_num = jnp.where(col == nb, dl, col <= thr)
            # bitset[col] is an n-row table gather — skip it entirely on
            # all-numeric datasets (gathers are the slowest TPU primitive)
            go_left = jnp.where(catl, bitset[col], go_left_num) \
                if hp.has_categorical else go_left_num
            if mode == "feature" and axis_name is not None:
                go_left = lax.psum(
                    jnp.where(owns, go_left.astype(jnp.float32), 0.0),
                    axis_name) > 0.5
            active = st.leaf_of_row == bl
            leaf_of_row = jnp.where(
                active, jnp.where(go_left, bl, new_leaf), st.leaf_of_row)

            # -- children stats from the cached best split (or forced gather)
            lg, lh, lcn = ch_lg, ch_lh, ch_lc
            rg, rh, rcn = pg - lg, ph - lh, pc - lcn

            # -- children outputs: variant-dependent l2 (sorted-subset adds
            # cat_l2, feature_histogram.cpp:250), path smoothing toward the
            # parent, monotone [min,max] clipping (basic method)
            l2_eff = hp.lambda_l2 + jnp.where(
                (var == VAR_CAT_FWD) | (var == VAR_CAT_BWD), hp.cat_l2, 0.0)
            parent_out = t.leaf_value[bl]
            lo = smoothed_output(lg, lh, lcn, parent_out, hp.lambda_l1,
                                 l2_eff, hp)
            ro = smoothed_output(rg, rh, rcn, parent_out, hp.lambda_l1,
                                 l2_eff, hp)
            lmin_p, lmax_p = st.leaf_min[bl], st.leaf_max[bl]
            # use_boxes closes over grow_tree's definition — keep ONE source
            if hp.use_monotone:
                lo = jnp.clip(lo, lmin_p, lmax_p)
                ro = jnp.clip(ro, lmin_p, lmax_p)
            if hp.use_monotone and use_boxes:
                # sibling-ordering repair: clipping both children to the
                # parent's [min, max] can leave out[left] > out[right] under
                # mono>0 (or the mirror) when the raw outputs were inverted
                # but clipped equal at evaluation time; the box refresh below
                # bounds OTHER leaves but not this pair's relative order, so
                # collapse inverted siblings to their midpoint like the basic
                # method's swap (monotone_constraints.hpp BasicLeafConstraints)
                mono_sf = monotone[feat]
                inv = (~catl) & (((mono_sf > 0) & (lo > ro))
                                 | ((mono_sf < 0) & (lo < ro)))
                mid_sib = jnp.clip((lo + ro) * 0.5, lmin_p, lmax_p)
                lo = jnp.where(inv, mid_sib, lo)
                ro = jnp.where(inv, mid_sib, ro)
            if hp.use_monotone and not use_boxes:
                mono_f = monotone[feat]
                is_num = ~catl
                mid = (lo + ro) * 0.5
                lmax_l = jnp.where(is_num & (mono_f > 0),
                                   jnp.minimum(lmax_p, mid), lmax_p)
                lmin_l = jnp.where(is_num & (mono_f < 0),
                                   jnp.maximum(lmin_p, mid), lmin_p)
                lmin_r = jnp.where(is_num & (mono_f > 0),
                                   jnp.maximum(lmin_p, mid), lmin_p)
                lmax_r = jnp.where(is_num & (mono_f < 0),
                                   jnp.minimum(lmax_p, mid), lmax_p)
            else:
                lmin_l = lmin_r = lmin_p
                lmax_l = lmax_r = lmax_p

            # -- histogram: data pass over ONLY the smaller child's rows
            # (bucketed gather), subtract for the sibling
            smaller = jnp.where(lcn <= rcn, bl, new_leaf)
            if hp.leaf_hist == "masked":
                h_small = histogram_for_leaf_masked(
                    bins_t, grad, hess, leaf_of_row, smaller, row_mask,
                    n_bins=hp.n_bins, rows_per_block=hp.rows_per_block,
                    hist_dtype=hp.hist_dtype, axis_name=hist_axis,
                    hist_kernel=hp.hist_kernel, bins_words_t=words_t,
                    overlap=overlap)
            else:
                h_small = histogram_for_leaf_bucketed(
                    bins, grad, hess, leaf_of_row, smaller,
                    jnp.minimum(lcn, rcn), row_mask,
                    n_bins=hp.n_bins, rows_per_block=hp.rows_per_block,
                    hist_dtype=hp.hist_dtype, axis_name=hist_axis,
                    overlap=overlap)
            h_small = _scaled(h_small)
            h_parent = st.hist[bl]
            h_large = h_parent - h_small
            left_small = lcn <= rcn
            h_left = jnp.where(left_small, h_small, h_large)
            h_right = jnp.where(left_small, h_large, h_small)
            hist = st.hist.at[bl].set(h_left).at[new_leaf].set(h_right)

            d = t.leaf_depth[bl] + 1
            t = t._replace(
                leaf_depth=t.leaf_depth.at[bl].set(d).at[new_leaf].set(d),
                leaf_value=t.leaf_value.at[bl].set(lo).at[new_leaf].set(ro),
                leaf_count=t.leaf_count.at[bl].set(lcn).at[new_leaf].set(rcn),
                leaf_weight=t.leaf_weight.at[bl].set(lh).at[new_leaf].set(rh),
            )

            if use_boxes:
                # intermediate monotone: update bin-space boxes, then refresh
                # EVERY leaf's [min, max] from the actual current outputs via
                # dense box adjacency (learner/monotone.py — the TPU-native
                # equivalent of the reference's GoUp/GoDown constraint walks,
                # monotone_constraints.hpp:516+).  Cached best-split GAINS of
                # other leaves may lag one refresh (the reference re-queues
                # them); output CLIPPING always uses fresh bounds, so grown
                # trees stay monotone either way.
                from .monotone import box_bounds, split_boxes
                leaf_lo, leaf_hi = split_boxes(
                    st.leaf_lo, st.leaf_hi, bl, new_leaf, f_safe, thr, ~catl)
                mono_lower, mono_upper = box_bounds(
                    leaf_lo, leaf_hi, t.leaf_value, monotone,
                    jnp.int32(new_leaf) + 1)
                lmin_l, lmax_l = mono_lower[bl], mono_upper[bl]
                lmin_r, lmax_r = mono_lower[new_leaf], mono_upper[new_leaf]
                leaf_min_new = mono_lower
                leaf_max_new = mono_upper
            else:
                leaf_lo, leaf_hi = st.leaf_lo, st.leaf_hi
                leaf_min_new = st.leaf_min.at[bl].set(lmin_l) \
                                          .at[new_leaf].set(lmin_r)
                leaf_max_new = st.leaf_max.at[bl].set(lmax_l) \
                                          .at[new_leaf].set(lmax_r)

            child_path = st.path_feats[bl].at[f_safe].set(True)
            if rng_key is not None:
                k_l, k_r, k_el, k_er2 = jax.random.split(
                    jax.random.fold_in(rng_key, i), 4)
            else:
                k_l = k_r = k_el = k_er2 = None
            fm_l = node_feature_mask(child_path, k_l)
            fm_r = node_feature_mask(child_path, k_r)
            if cegb is not None:
                # this split acquires `feat` for the whole parent leaf —
                # the BAGGED-IN rows only: the reference's DataPartition
                # holds just the bag subset, so bagged-out rows never
                # traverse the split during training and their feature
                # stays un-acquired (cost_effective_gradient_boosting.hpp
                # iterates the partition's indices).  Masking here also
                # keeps batch_grower's round-batched update (same mask)
                # bit-identical at batch=1 under bagging.
                cegb_used = st.cegb_used.at[feat].set(True)
                if cegb.used_rows is not None:
                    in_parent = active & (mask_f > 0)
                    cegb_rows = st.cegb_rows | (
                        in_parent[:, None]
                        & (lax.iota(jnp.int32, num_f)[None, :] == feat))
                else:
                    cegb_rows = st.cegb_rows
                pen_l = cegb_penalty(cegb_used, cegb_rows,
                                     (leaf_of_row == bl) & (mask_f > 0), lcn)
                pen_r = cegb_penalty(cegb_used, cegb_rows,
                                     (leaf_of_row == new_leaf) & (mask_f > 0),
                                     rcn)
            else:
                cegb_used, cegb_rows = st.cegb_used, st.cegb_rows
                pen_l = pen_r = None
            if use_adv:
                # advanced monotone: per-(feature, threshold) bounds for
                # each child's upcoming split evaluation
                from .monotone import advanced_split_bounds
                adv_l = advanced_split_bounds(
                    leaf_lo, leaf_hi, t.leaf_value, monotone,
                    jnp.int32(i) + 2, bl, hp.n_bins)
                adv_r = advanced_split_bounds(
                    leaf_lo, leaf_hi, t.leaf_value, monotone,
                    jnp.int32(i) + 2, new_leaf, hp.n_bins)
            else:
                adv_l = adv_r = None
            bs_l = child_best(h_left, lg, lh, lcn, d, fm_l, lo, lmin_l,
                              lmax_l, k_el, pen=pen_l, adv=adv_l)
            bs_r = child_best(h_right, rg, rh, rcn, d, fm_r, ro, lmin_r,
                              lmax_r, k_er2, pen=pen_r, adv=adv_r)

            return st._replace(
                tree=t,
                leaf_of_row=leaf_of_row,
                hist=hist,
                sum_g=st.sum_g.at[bl].set(lg).at[new_leaf].set(rg),
                sum_h=st.sum_h.at[bl].set(lh).at[new_leaf].set(rh),
                count=st.count.at[bl].set(lcn).at[new_leaf].set(rcn),
                best_gain=st.best_gain.at[bl].set(bs_l.gain)
                                       .at[new_leaf].set(bs_r.gain),
                best_feat=st.best_feat.at[bl].set(bs_l.feature)
                                       .at[new_leaf].set(bs_r.feature),
                best_thr=st.best_thr.at[bl].set(bs_l.threshold)
                                     .at[new_leaf].set(bs_r.threshold),
                best_dl=st.best_dl.at[bl].set(bs_l.default_left)
                                   .at[new_leaf].set(bs_r.default_left),
                best_cat=st.best_cat.at[bl].set(bs_l.is_categorical)
                                     .at[new_leaf].set(bs_r.is_categorical),
                best_var=st.best_var.at[bl].set(bs_l.variant)
                                     .at[new_leaf].set(bs_r.variant),
                best_lg=st.best_lg.at[bl].set(bs_l.left_sum_g)
                                   .at[new_leaf].set(bs_r.left_sum_g),
                best_lh=st.best_lh.at[bl].set(bs_l.left_sum_h)
                                   .at[new_leaf].set(bs_r.left_sum_h),
                best_lc=st.best_lc.at[bl].set(bs_l.left_count)
                                   .at[new_leaf].set(bs_r.left_count),
                parent_node=st.parent_node.at[bl].set(i).at[new_leaf].set(i),
                parent_side=st.parent_side.at[bl].set(0).at[new_leaf].set(1),
                leaf_min=leaf_min_new,
                leaf_max=leaf_max_new,
                leaf_lo=leaf_lo,
                leaf_hi=leaf_hi,
                path_feats=st.path_feats.at[bl].set(child_path)
                                        .at[new_leaf].set(child_path),
                cegb_used=cegb_used,
                cegb_rows=cegb_rows,
            )

        return lax.cond(do, split, no_split, st)

    state = lax.fori_loop(0, L - 1, body, state)
    tree_out = state.tree._replace(leaf_path=state.path_feats)
    if cegb is not None:
        new_cegb = cegb._replace(
            feature_used=state.cegb_used,
            used_rows=None if cegb.used_rows is None else state.cegb_rows)
        return tree_out, state.leaf_of_row, new_cegb
    return tree_out, state.leaf_of_row
