"""Batched-round tree growth: K splits per data pass.

The strict leaf-wise learner (learner/grower.py, mirroring reference
serial_tree_learner.cpp) needs one data pass per split because the next
best leaf depends on the children of the last split.  On TPU that pass is
bound by one-hot construction in the histogram kernel, so 254 splits cost
254 passes regardless of leaf sizes.

This grower relaxes strict best-first order to BATCHED best-first: each
round splits the current top-``batch`` leaves by cached gain, then computes
all K smaller-child histograms in ONE widened-channel kernel pass
(ops/histogram.py ``histogram_for_leaves_masked``) — the one-hot work is
shared, so K splits cost ~one pass.  With batch=1 the trees are IDENTICAL
to the strict learner; with batch=k each round's selections are the same
leaves a strict learner would pick in its next k steps UNLESS a fresh child
out-gains a queued leaf mid-round — in practice metric curves track the
strict learner closely (tests/test_batch_grower.py) at up to ~k× the
throughput.  The reference has no counterpart; its CPU learner pays
O(child rows) per split and needs no such amortization.

Supported feature set: numerical splits with missing handling, categorical
splits (one-hot + sorted-subset, applied via per-split bitsets),
basic/intermediate monotone constraints, interaction constraints, path
smoothing, forced splits (K=1 prefix phase), extra_trees + per-node
feature sampling, EFB bundles, bagging row masks, per-tree feature
sampling, depth limits, data-parallel ``shard_map`` (axis psum),
voting-parallel (PV-Tree two-phase vote with local histogram state),
CEGB penalties (serial mode; split/coupled/lazy with round-batched
acquisition updates), all three monotone methods (advanced computes
per-(feature, threshold) child bounds for the whole round's kids from
the round-refreshed boxes), and linear trees (returned trees carry
leaf_path, so the post-growth ridge fit composes unchanged).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.histogram import (bins_to_words, histogram_for_leaves_auto,
                             ladder_profitable, overlap_enabled,
                             root_histogram, wants_packed_mirror)
from ..ops.round_fuse import (partition_payload_pallas,
                              partition_select_pallas, use_fused_partition,
                              use_fused_payload)
from ..ops.split import (NEG_INF, VAR_CAT_BWD, VAR_CAT_FWD, SplitHyper,
                         categorical_left_bitset, find_best_split,
                         leaf_output)
from .grower import (CegbInput, DeviceBundle, TreeArrays, _INF_BOUND,
                     _empty_tree, _expand_hist, _expand_hist_col,
                     _feature_bin_of_rows, pv_vote_best_split,
                     sample_features_bynode)

#: data size below which warmup width-matching is never worth its extra
#: kernel compilations (tests patch this to exercise the ladder cheaply)
_WARMUP_MIN_ROWS = 65536


@functools.partial(jax.jit, static_argnames=("hp", "batch", "axis_name",
                                             "warmup", "parallel_mode",
                                             "top_k", "num_shards",
                                             "overlap"))
def grow_tree_batched(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                      row_mask: Optional[jax.Array], num_bins: jax.Array,
                      nan_bin: jax.Array, is_cat: jax.Array,
                      feature_mask: Optional[jax.Array], hp: SplitHyper,
                      batch: int = 8,
                      bundle: Optional[DeviceBundle] = None,
                      monotone: Optional[jax.Array] = None,
                      axis_name: Optional[str] = None,
                      warmup: bool = True,
                      hist_scale: Optional[jax.Array] = None,
                      interaction_sets: Optional[jax.Array] = None,
                      rng_key: Optional[jax.Array] = None,
                      forced: Optional[Tuple[jax.Array, jax.Array,
                                             jax.Array]] = None,
                      parallel_mode: str = "data", top_k: int = 20,
                      num_shards: int = 1,
                      cegb: Optional[CegbInput] = None,
                      bins_words: Optional[jax.Array] = None,
                      overlap: bool = False):
    """Grow one tree with ``batch`` splits per histogram pass.

    Same operands and return contract as ``grow_tree`` (a 3-tuple with
    the updated ``CegbInput`` when ``cegb`` is passed).  Supports
    interaction constraints (per-leaf path-feature masks), ALL monotone
    methods (intermediate/advanced refresh every leaf's bounds from
    dense box adjacency after EACH split, the strict learner's cadence,
    so splits later in a round see earlier splits' outputs; advanced
    additionally threads per-(feature, threshold) child bounds into the
    round's split evaluations; cached candidate GAINS of unsplit leaves
    may lag a round, the same class of lag the strict learner
    documents), path smoothing, CEGB penalties (acquisitions batch per
    round), and linear trees (returned trees carry ``leaf_path``).

    Under ``axis_name`` with ``parallel_mode="voting"`` the rounds run
    the PV-Tree protocol (reference voting_parallel_tree_learner.cpp,
    round-4 lift of the batched-grower cliff): histogram state stays
    LOCAL per shard, each child's best split does a two-phase vote —
    local per-feature gains at 1/num_shards-relaxed thresholds, a
    ``psum`` vote over each shard's top-``top_k`` features, then a
    ``psum`` of ONLY the 2·top_k voted histogram slices — so per-round
    communication is O(K · top_k · bins), independent of feature count,
    while K splits still share one local histogram pass.
    """
    voting = parallel_mode == "voting" and axis_name is not None
    # collectives the histogram ops should use: none under voting (the
    # vote psums slices itself)
    hist_axis = None if voting else axis_name
    if hp.use_monotone:
        assert monotone is not None and hp.monotone_method in (
            "basic", "intermediate", "advanced"), \
            f"unknown monotone method {hp.monotone_method!r}"
    if voting:
        assert forced is None, "forced splits need the strict learner " \
            "under voting"
        assert not (hp.use_monotone
                    and hp.monotone_method == "advanced"), \
            "advanced monotone under voting needs the strict learner " \
            "(the vote path does not thread per-threshold bounds)"
    if cegb is not None:
        assert axis_name is None, \
            "batched CEGB runs the serial learner only (the distributed " \
            "modes route through the strict grower)"
    use_lazy = cegb is not None and cegb.used_rows is not None
    use_boxes = hp.use_monotone and hp.monotone_method in (
        "intermediate", "advanced")
    use_adv = hp.use_monotone and hp.monotone_method == "advanced"
    use_paths = interaction_sets is not None
    use_smooth = hp.path_smooth > 0.0
    use_bynode = hp.feature_fraction_bynode < 1.0 and rng_key is not None
    use_rng = rng_key is not None and (hp.extra_trees or use_bynode)
    n = bins.shape[0]
    num_f = bins.shape[1] if bundle is None else bundle.feat_col.shape[0]
    L = hp.num_leaves
    K = min(batch, L - 1)
    if use_lazy:
        # row-block geometry for the lazy-acquisition scans (bounds the
        # per-round f32 transients to [K, blk] instead of [K, n])
        cegb_blk = min(1 << 18, n)
        cegb_pad = (-n) % cegb_blk
        cegb_nb = (n + cegb_pad) // cegb_blk
    mask_f = jnp.ones_like(grad) if row_mask is None \
        else row_mask.astype(grad.dtype)
    bins_t = lax.optimization_barrier(bins.T)
    # tree-invariant i32 word view of the row-major bins, hoisted out of
    # the round loop: every compacted round's payload reuses it.  The
    # booster passes the dataset's construction-time packed mirror
    # (io/dataset.py packed_mirror) so serial trees skip even the
    # one-time bitcast; derived in-jit otherwise (distributed shards).
    bins_words = lax.optimization_barrier(
        bins_to_words(bins) if bins_words is None else bins_words)
    # transposed packed mirror for the round-6 packed histogram kernel
    words_t = lax.optimization_barrier(bins_words.T) \
        if wants_packed_mirror(hp.hist_kernel, hp.n_bins) else None
    # fused partition+key kernel (ops/round_fuse.py): numeric non-bundled
    # splits only — categorical bitsets / EFB inverse tables are per-row
    # gathers, kept on the XLA path
    fuse_partition = (use_fused_partition() and not hp.has_categorical
                      and bundle is None)
    # payload-emitting partition variant: only the non-pooled path
    # consumes the emitted matrix (the pooled path rebuilds its own keys
    # for its extended leaf set)
    pooled = 0 < hp.hist_pool_slots < hp.num_leaves
    fuse_payload = fuse_partition and not pooled and use_fused_payload()
    from ..ops.histogram import use_pallas as _use_pallas
    INF = jnp.float32(_INF_BOUND)

    def node_mask(path_f, key=None):
        """Per-leaf allowed features: interaction constraints (reference
        col_sampler.hpp:91 GetByNode — a leaf may split only on features
        from constraint sets containing its whole path) composed with the
        per-node random subset (feature_fraction_bynode)."""
        m = feature_mask
        if use_paths:
            fits = jnp.all(interaction_sets | ~path_f[None, :], axis=1)
            allowed = jnp.any(interaction_sets & fits[:, None],
                              axis=0) | path_f
            m = allowed if m is None else (m & allowed)
        if use_bynode and key is not None:
            m = sample_features_bynode(m, key, hp.feature_fraction_bynode,
                                       num_f)
        return m

    if voting:
        import dataclasses as _dc
        # locally relaxed validity thresholds
        # (voting_parallel_tree_learner.cpp:62-64)
        hp_vote = _dc.replace(
            hp, min_data_in_leaf=max(1, hp.min_data_in_leaf // num_shards),
            min_sum_hessian_in_leaf=hp.min_sum_hessian_in_leaf / num_shards)

    def cegb_penalty(used_f, used_rows_cnt, leaf_count):
        """Per-feature gain penalty for one leaf (CEGB DeltaGain,
        cost_effective_gradient_boosting.hpp — same math as the strict
        grower's cegb_penalty, with the lazy row count precomputed by
        the caller's batched matmul)."""
        pen = cegb.split_pen * leaf_count \
            + jnp.where(used_f, 0.0, cegb.coupled_pen)
        if use_lazy:
            pen = pen + cegb.lazy_pen * used_rows_cnt
        return pen

    def child_best(h_phys, g_, h_, c_, depth, lmin, lmax, fm, pout,
                   key=None, pen=None, adv=None):
        if voting:
            # PV-Tree two-phase vote per child — ONE protocol definition
            # shared with the strict grower (learner/grower.py
            # pv_vote_best_split)
            return pv_vote_best_split(
                h_phys, g_, h_, c_, depth, fm, pout, lmin, lmax, key,
                hp=hp, hp_vote=hp_vote, num_bins=num_bins,
                nan_bin=nan_bin, is_cat=is_cat, monotone=monotone,
                bundle=bundle, num_f=num_f, top_k=top_k,
                axis_name=axis_name)
        hv = h_phys if bundle is None else \
            _expand_hist(h_phys, bundle, g_, h_, c_)
        res = find_best_split(hv, g_, h_, c_, num_bins, nan_bin, is_cat,
                              fm, hp, monotone=monotone,
                              leaf_min=lmin, leaf_max=lmax, depth=depth,
                              parent_output=pout, rng_key=key,
                              gain_penalty=pen, adv_bounds=adv)
        depth_ok = (hp.max_depth <= 0) | (depth < hp.max_depth)
        return res._replace(gain=jnp.where(depth_ok, res.gain, NEG_INF))

    def forced_col_hist(ff, lor_now, fl):
        """[B, C] VIRTUAL histogram column of leaf ``fl`` for feature
        ``ff``, computed directly from the data in row blocks.

        The pooled forced phase uses this instead of ``st["hist"]``: a
        forced BFS schedule can prescribe a split for a leaf whose pool
        slot was evicted rounds ago, so the column is re-derived from
        the rows themselves (same exact sums; may differ from the
        subtraction-chain histogram only in f32 rounding — the same
        deviation class as the pool's direct child rebuilds).  Virtual
        bins via ``_feature_bin_of_rows`` make EFB default-bin
        completion unnecessary."""
        colv = _feature_bin_of_rows(bins_t, bundle, ff)
        selm = (lor_now == fl) & (mask_f > 0)
        iota_b = lax.iota(jnp.int32, hp.n_bins)
        blk_ = min(1 << 17, n)
        pad_ = (-n) % blk_
        nb_ = (n + pad_) // blk_

        def block(acc, xs):
            colv_b, g_b, h_b, sel_b = xs
            oh = (colv_b[None, :] == iota_b[:, None]).astype(jnp.float32)
            gm = jnp.where(sel_b, g_b, 0.0)
            hm = jnp.where(sel_b, h_b, 0.0)
            vals = jnp.stack([gm, hm, sel_b.astype(jnp.float32),
                              jnp.zeros_like(g_b)])          # [C, blk]
            return acc + lax.dot_general(
                vals, oh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST).T, None     # [B, C]

        acc0 = jnp.zeros((hp.n_bins, 4), jnp.float32)
        hf, _ = lax.scan(block, acc0, (
            jnp.pad(colv, (0, pad_), constant_values=-1)
            .reshape(nb_, blk_),
            jnp.pad(grad, (0, pad_)).reshape(nb_, blk_),
            jnp.pad(hess, (0, pad_)).reshape(nb_, blk_),
            jnp.pad(selm, (0, pad_)).reshape(nb_, blk_)))
        return _scaled(hf)

    def winner_bitset(h_phys, g_, h_, c_, feat, var, thr):
        """Left-category bitset of a CACHED best split, computed from the
        leaf's own histogram at best-split time (same inputs as the
        strict learner's split-time computation, so identical output).
        Caching it in state removes the record phase's parent-histogram
        read — the step that kept the bounded pool and categorical
        splits apart (an evicted parent has no histogram to read).
        Under voting the state holds LOCAL histograms, so the winning
        feature's column is psum-ed first — one [B, C] column per split,
        the strict learner's cadence (grower.py split())."""
        col_of = feat if bundle is None else bundle.feat_col[feat]
        pf_col = h_phys[col_of]
        if voting:
            pf_col = lax.psum(pf_col, axis_name)
        hist_col = pf_col if bundle is None else \
            _expand_hist_col(pf_col, bundle, feat, g_, h_, c_)
        return categorical_left_bitset(
            hist_col, num_bins[feat], var, thr, hp) & is_cat[feat]

    # quantized-levels mode (ops/quantize.py): grad/hess hold integer
    # levels; one deterministic multiply restores real units right after
    # each exact integer histogram accumulation
    scale_vec = None
    if hist_scale is not None:
        scale_vec = jnp.concatenate(
            [hist_scale.astype(jnp.float32), jnp.ones((2,), jnp.float32)])

    def _scaled(h):
        return h if scale_vec is None else h * scale_vec

    hist0_b = _scaled(root_histogram(
        bins_t, grad, hess, row_mask, n_bins=hp.n_bins,
        rows_per_block=hp.rows_per_block,
        hist_dtype=hp.hist_dtype, axis_name=hist_axis,
        hist_kernel=hp.hist_kernel, bins_words_t=words_t,
        overlap=overlap))
    g0 = jnp.sum(grad * mask_f)
    h0 = jnp.sum(hess * mask_f)
    c0 = jnp.sum(mask_f)
    if hist_scale is not None:
        g0 = g0 * hist_scale[0]
        h0 = h0 * hist_scale[1]
    if axis_name is not None:
        if overlap_enabled(overlap):
            # one [3]-vector psum instead of three scalar collectives:
            # same per-element sums (bit-identical), one less blocking
            # round-trip for the scheduler to hide
            g0, h0, c0 = lax.psum(jnp.stack([g0, h0, c0]), axis_name)
        else:
            g0 = lax.psum(g0, axis_name)
            h0 = lax.psum(h0, axis_name)
            c0 = lax.psum(c0, axis_name)
    root_out = leaf_output(g0, h0, hp.lambda_l1, hp.lambda_l2,
                           hp.max_delta_step)
    empty_path = jnp.zeros((num_f,), bool)
    key_root = jax.random.fold_in(rng_key, 0) if use_rng else None
    if cegb is not None:
        cnt0 = (jnp.einsum("n,nf->f", mask_f.astype(jnp.float32),
                           (~cegb.used_rows).astype(jnp.float32))
                if use_lazy else None)
        pen0 = cegb_penalty(cegb.feature_used, cnt0, c0)
    else:
        pen0 = None
    best0 = child_best(hist0_b, g0, h0, c0, jnp.int32(0), -INF, INF,
                       node_mask(empty_path, key_root), root_out, key_root,
                       pen=pen0)

    tree = _empty_tree(L, hp.n_bins, num_f)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(root_out),
        leaf_count=tree.leaf_count.at[0].set(c0),
        leaf_weight=tree.leaf_weight.at[0].set(h0))
    C = hist0_b.shape[-1]
    n_cols = bins.shape[1]
    # bounded histogram pool (SplitHyper.hist_pool_slots): P slots + one
    # trash row; leaf_slot/slot_leaf carry the mapping, with trash entries
    # at index L / P so masked scatters need no branches
    # (``pooled`` itself is derived up top, before the partition-fusion
    # gates)
    P = hp.hist_pool_slots
    if pooled:
        assert P >= 3 * K + 2, \
            "hist_pool_slots must be >= 3*batch+2 for worst-case rounds"
    state = dict(
        tree=tree,
        leaf_of_row=jnp.zeros((n,), jnp.int32),
        hist=(jnp.zeros((P + 1, n_cols, hp.n_bins, C), jnp.float32)
              .at[0].set(hist0_b) if pooled else
              jnp.zeros((L, n_cols, hp.n_bins, C),
                        jnp.float32).at[0].set(hist0_b)),
        sum_g=jnp.zeros((L,), jnp.float32).at[0].set(g0),
        sum_h=jnp.zeros((L,), jnp.float32).at[0].set(h0),
        count=jnp.zeros((L,), jnp.float32).at[0].set(c0),
        best_gain=jnp.full((L,), NEG_INF, jnp.float32).at[0].set(best0.gain),
        best_feat=jnp.zeros((L,), jnp.int32).at[0].set(best0.feature),
        best_thr=jnp.zeros((L,), jnp.int32).at[0].set(best0.threshold),
        best_dl=jnp.zeros((L,), bool).at[0].set(best0.default_left),
        best_var=jnp.zeros((L,), jnp.int32).at[0].set(best0.variant),
        best_lg=jnp.zeros((L,), jnp.float32).at[0].set(best0.left_sum_g),
        best_lh=jnp.zeros((L,), jnp.float32).at[0].set(best0.left_sum_h),
        best_lc=jnp.zeros((L,), jnp.float32).at[0].set(best0.left_count),
        leaf_min=jnp.full((L,), -INF, jnp.float32),
        leaf_max=jnp.full((L,), INF, jnp.float32),
        parent_node=jnp.full((L,), -1, jnp.int32),
        parent_side=jnp.zeros((L,), jnp.int32),
        n_splits=jnp.int32(0),
        progress=jnp.bool_(True),
    )
    if hp.has_categorical:
        state["best_bitset"] = jnp.zeros((L, hp.n_bins), bool).at[0].set(
            winner_bitset(hist0_b, g0, h0, c0, best0.feature,
                          best0.variant, best0.threshold))
    if cegb is not None:
        state["cegb_used"] = cegb.feature_used
        if use_lazy:
            state["cegb_rows"] = cegb.used_rows
    # leaf path features: tracked unconditionally ([L, F] bool is tiny)
    # so returned trees carry leaf_path like the strict learner's — the
    # linear-tree ridge fit (learner/linear.py fit_linear_leaves) selects
    # each leaf's numeric path features from it
    state["path_f"] = jnp.zeros((L, num_f), bool)
    if use_boxes:
        # bin-space boxes: root spans every bin (hi exclusive); dead slots
        # hold empty boxes so box_bounds ignores them
        state["leaf_lo"] = jnp.zeros((L, num_f), jnp.int32)
        state["leaf_hi"] = jnp.zeros((L, num_f), jnp.int32).at[0].set(
            num_bins.astype(jnp.int32))
    if forced is not None:
        # composes with the bounded pool since round 6: the forced phase
        # derives evicted leaves' columns directly (forced_col_hist)
        state["force_failed"] = jnp.bool_(False)
    if pooled:
        state["leaf_slot"] = jnp.full((L + 1,), -1, jnp.int32).at[0].set(0)
        state["slot_leaf"] = jnp.full((P + 1,), -1, jnp.int32).at[0].set(0)

    def make_round_body(Kr, use_forced=False):
      def round_body(st):
          if use_forced:
              # forced-split round (reference serial_tree_learner.cpp:620
              # ForceSplits; same math as the strict learner's forced
              # gather): entry index == split counter, stats gathered at
              # the PRESCRIBED threshold from the leaf's histogram, staged
              # into the cached-best slots so the normal record machinery
              # applies them
              from ..ops.split import VAR_CAT_ONEHOT, VAR_NUM_RIGHT
              from .grower import gather_forced_split
              f_leaf, f_feat, f_thr = forced
              i = jnp.minimum(st["n_splits"], f_leaf.shape[0] - 1)
              f_active = (f_leaf[i] >= 0) & ~st["force_failed"]
              fl = jnp.maximum(f_leaf[i], 0)
              ff, ft = f_feat[i], f_thr[i]
              if pooled:
                  # resident pool slot -> one [B, C] slot read (the
                  # common case: forced prefixes are shallow and the
                  # pool holds >= 3K+2 slots); evicted -> re-derive the
                  # virtual column from the data in row blocks
                  # (round-6 lift of the forced x hist-pool carve-out)
                  slot = st["leaf_slot"][fl]
                  resident = (slot >= 0) & (slot < P)

                  def hf_from_pool(_):
                      hc = st["hist"][jnp.clip(slot, 0, P),
                                      ff if bundle is None
                                      else bundle.feat_col[ff]]
                      return hc if bundle is None else \
                          _expand_hist_col(hc, bundle, ff,
                                           st["sum_g"][fl],
                                           st["sum_h"][fl],
                                           st["count"][fl])

                  hf = lax.cond(resident, hf_from_pool,
                                lambda _: forced_col_hist(
                                    ff, st["leaf_of_row"], fl), None)
              else:
                  hf_col = st["hist"][fl, ff if bundle is None
                                      else bundle.feat_col[ff]]  # [B, C]
                  hf = hf_col if bundle is None else \
                      _expand_hist_col(hf_col, bundle, ff,
                                       st["sum_g"][fl],
                                       st["sum_h"][fl], st["count"][fl])
              pgf, phf, pcf = st["sum_g"][fl], st["sum_h"][fl], \
                  st["count"][fl]
              lgf, lhf, lcf, gf, ok_f = gather_forced_split(
                  hf, pgf, phf, pcf, ft, is_cat[ff], nan_bin[ff], hp)
              use_f = f_active & ok_f
              st = dict(st)
              st["force_failed"] = st["force_failed"] | (f_active & ~ok_f)

              def sset(name, val):
                  st[name] = st[name].at[fl].set(
                      jnp.where(use_f, val, st[name][fl]))

              sset("best_gain", gf)
              sset("best_feat", ff)
              sset("best_thr", ft)
              sset("best_dl", jnp.bool_(False))
              sset("best_var", jnp.where(is_cat[ff], VAR_CAT_ONEHOT,
                                         VAR_NUM_RIGHT))
              sset("best_lg", lgf)
              sset("best_lh", lhf)
              sset("best_lc", lcf)
              if hp.has_categorical:
                  var_f = jnp.where(is_cat[ff], VAR_CAT_ONEHOT,
                                    VAR_NUM_RIGHT)
                  if pooled:
                      # same direct column carries the bitset (the pool
                      # may not hold this leaf's histogram)
                      bs_f = categorical_left_bitset(
                          hf, num_bins[ff], var_f, ft, hp) & is_cat[ff]
                  else:
                      bs_f = winner_bitset(st["hist"][fl], pgf, phf, pcf,
                                           ff, var_f, ft)
                  st["best_bitset"] = st["best_bitset"].at[fl].set(
                      jnp.where(use_f, bs_f, st["best_bitset"][fl]))
              forced_sel = (fl, use_f)
          else:
              forced_sel = None
          topg, parents = lax.top_k(st["best_gain"], Kr)          # [K]
          if forced_sel is not None:
              # the forced leaf is the round's ONLY candidate (Kr == 1)
              parents = jnp.where(forced_sel[1], forced_sel[0][None],
                                  parents)
              topg = jnp.where(forced_sel[1], st["best_gain"][parents[0]]
                               [None], topg)
          room = st["n_splits"] + lax.iota(jnp.int32, Kr) < L - 1
          valid = (topg > 0.0) & room
          if forced_sel is not None:
              valid = valid & forced_sel[1][None]
          rank = jnp.cumsum(valid.astype(jnp.int32)) - 1          # [K]
          node_ids = st["n_splits"] + rank                        # [K]
          new_leaves = node_ids + 1                               # [K]

          t = st["tree"]
          lor = st["leaf_of_row"]
          if not use_boxes:
              # ---- vectorized record: ONE batched scatter per array.
              # The sequential per-slot loop below (kept for the
              # box-based monotone methods, whose per-split bound
              # refresh makes later slots depend on earlier outputs)
              # cost ~17 ms/tree at K=42 in pure scatter-chain latency
              # (round-4 e2e profile); all its reads/writes touch
              # DISTINCT indices across slots — parents are distinct
              # top-k leaves, new node/leaf ids are distinct, and a
              # shared grandparent node is written on complementary
              # child sides — so the loop folds into masked scatters
              # (invalid slots aim out of bounds, mode="drop").
              ok = valid                                          # [K]
              bl = parents
              feat = st["best_feat"][bl]
              thr = st["best_thr"][bl]
              dl = st["best_dl"][bl]
              var = st["best_var"][bl]
              catl = is_cat[feat]
              pg, ph, pc = st["sum_g"][bl], st["sum_h"][bl], \
                  st["count"][bl]
              lg, lh, lcn = st["best_lg"][bl], st["best_lh"][bl], \
                  st["best_lc"][bl]
              rg, rh, rcn = pg - lg, ph - lh, pc - lcn
              if hp.has_categorical:
                  bitsets_arr = st["best_bitset"][bl]             # [K, B]
              else:
                  bitsets_arr = jnp.zeros((Kr, hp.n_bins), bool)

              ni = L - 1
              p, side = st["parent_node"][bl], st["parent_side"][bl]
              nid_m = jnp.where(ok, node_ids, ni)                 # drop idx
              lc = t.left_child.at[
                  jnp.where(ok & (p >= 0) & (side == 0), p, ni)
              ].set(node_ids, mode="drop")
              lc = lc.at[nid_m].set(-(bl + 1), mode="drop")
              rc = t.right_child.at[
                  jnp.where(ok & (p >= 0) & (side == 1), p, ni)
              ].set(node_ids, mode="drop")
              rc = rc.at[nid_m].set(-(new_leaves + 1), mode="drop")

              l2_eff = hp.lambda_l2 + jnp.where(
                  (var == VAR_CAT_FWD) | (var == VAR_CAT_BWD),
                  hp.cat_l2, 0.0)
              if use_smooth:
                  from ..ops.split import smoothed_output
                  pout_k = t.leaf_value[bl]
                  lo = smoothed_output(lg, lh, lcn, pout_k,
                                       hp.lambda_l1, l2_eff, hp)
                  ro = smoothed_output(rg, rh, rcn, pout_k,
                                       hp.lambda_l1, l2_eff, hp)
              else:
                  lo = leaf_output(lg, lh, hp.lambda_l1, l2_eff,
                                   hp.max_delta_step)
                  ro = leaf_output(rg, rh, hp.lambda_l1, l2_eff,
                                   hp.max_delta_step)
              if hp.use_monotone:
                  # basic method only here (box methods take the
                  # sequential branch): clip into the parent's range,
                  # tighten each child's box at the midpoint
                  lmin_p = st["leaf_min"][bl]
                  lmax_p = st["leaf_max"][bl]
                  lo = jnp.clip(lo, lmin_p, lmax_p)
                  ro = jnp.clip(ro, lmin_p, lmax_p)
                  mono_f = monotone[feat]
                  is_num = ~catl
                  mid = (lo + ro) * 0.5
                  lmax_l = jnp.where(is_num & (mono_f > 0),
                                     jnp.minimum(lmax_p, mid), lmax_p)
                  lmin_l = jnp.where(is_num & (mono_f < 0),
                                     jnp.maximum(lmin_p, mid), lmin_p)
                  lmin_r = jnp.where(is_num & (mono_f > 0),
                                     jnp.maximum(lmin_p, mid), lmin_p)
                  lmax_r = jnp.where(is_num & (mono_f < 0),
                                     jnp.minimum(lmax_p, mid), lmax_p)
              d = t.leaf_depth[bl] + 1

              # leaf-indexed arrays: one [2K] scatter (bl existing ids,
              # new_leaves fresh ids — provably disjoint)
              idx2 = jnp.concatenate([jnp.where(ok, bl, L),
                                      jnp.where(ok, new_leaves, L)])

              def w2(arr, vb, vn):
                  return arr.at[idx2].set(
                      jnp.concatenate([vb, vn]), mode="drop")

              new_path = st["path_f"][bl] | (
                  feat[:, None] == lax.iota(jnp.int32, num_f)[None, :])
              st["path_f"] = st["path_f"].at[idx2].set(
                  jnp.concatenate([new_path, new_path]), mode="drop")

              t = t._replace(
                  split_feature=t.split_feature.at[nid_m].set(
                      feat, mode="drop"),
                  split_bin=t.split_bin.at[nid_m].set(thr, mode="drop"),
                  default_left=t.default_left.at[nid_m].set(
                      dl, mode="drop"),
                  split_cat=t.split_cat.at[nid_m].set(catl, mode="drop"),
                  cat_bitset=t.cat_bitset.at[nid_m].set(
                      bitsets_arr, mode="drop"),
                  left_child=lc, right_child=rc,
                  split_gain=t.split_gain.at[nid_m].set(
                      st["best_gain"][bl], mode="drop"),
                  internal_value=t.internal_value.at[nid_m].set(
                      leaf_output(pg, ph, hp.lambda_l1, hp.lambda_l2,
                                  hp.max_delta_step), mode="drop"),
                  internal_count=t.internal_count.at[nid_m].set(
                      pc, mode="drop"),
                  leaf_depth=w2(t.leaf_depth, d, d),
                  leaf_value=w2(t.leaf_value, lo, ro),
                  leaf_count=w2(t.leaf_count, lcn, rcn),
                  leaf_weight=w2(t.leaf_weight, lh, rh),
                  num_leaves=t.num_leaves
                  + jnp.sum(valid.astype(jnp.int32)),
              )
              st["sum_g"] = w2(st["sum_g"], lg, rg)
              st["sum_h"] = w2(st["sum_h"], lh, rh)
              st["count"] = w2(st["count"], lcn, rcn)
              st["parent_node"] = w2(st["parent_node"], node_ids,
                                     node_ids)
              st["parent_side"] = w2(st["parent_side"],
                                     jnp.zeros((Kr,), jnp.int32),
                                     jnp.ones((Kr,), jnp.int32))
              if hp.use_monotone:
                  st["leaf_min"] = w2(st["leaf_min"], lmin_l, lmin_r)
                  st["leaf_max"] = w2(st["leaf_max"], lmax_l, lmax_r)
              st["best_gain"] = st["best_gain"].at[
                  jnp.where(ok, bl, L)].set(NEG_INF, mode="drop")

          # record + partition each slot (cheap [L]/[n] ops, no data
          # passes) — sequential branch for the box-based monotone
          # methods; MUST mirror the vectorized branch above
          bitsets = []
          for j in (range(Kr) if use_boxes else ()):
              ok = valid[j]
              bl = parents[j]
              nid = node_ids[j]
              nl = jnp.where(ok, new_leaves[j], L - 1)  # safe dummy index
              feat = st["best_feat"][bl]
              thr = st["best_thr"][bl]
              dl = st["best_dl"][bl]
              var = st["best_var"][bl]
              catl = is_cat[feat]
              pg, ph, pc = st["sum_g"][bl], st["sum_h"][bl], st["count"][bl]
              lg, lh, lcn = st["best_lg"][bl], st["best_lh"][bl], \
                  st["best_lc"][bl]
              rg, rh, rcn = pg - lg, ph - lh, pc - lcn

              # left-category bitset CACHED at best-split time (state
              # best_bitset, winner_bitset) — identical to computing it
              # from the parent histogram here, but works when the pool
              # evicted that histogram
              if hp.has_categorical:
                  bitset = st["best_bitset"][bl]
              else:
                  bitset = jnp.zeros((hp.n_bins,), bool)
              bitsets.append(bitset)

              p, side = st["parent_node"][bl], st["parent_side"][bl]
              ps = jnp.maximum(p, 0)
              lc_arr = t.left_child.at[ps].set(
                  jnp.where(ok & (p >= 0) & (side == 0), nid,
                            t.left_child[ps]))
              rc_arr = t.right_child.at[ps].set(
                  jnp.where(ok & (p >= 0) & (side == 1), nid,
                            t.right_child[ps]))
              lc_arr = lc_arr.at[nid].set(
                  jnp.where(ok, -(bl + 1), lc_arr[nid]))
              rc_arr = rc_arr.at[nid].set(
                  jnp.where(ok, -(nl + 1), rc_arr[nid]))

              # sorted-subset categorical children use l2 + cat_l2, matching
              # the strict learner and feature_histogram.cpp:250; path
              # smoothing pulls children toward the parent's output exactly
              # like the strict learner (grower.py smoothed_output)
              l2_eff = hp.lambda_l2 + jnp.where(
                  (var == VAR_CAT_FWD) | (var == VAR_CAT_BWD), hp.cat_l2, 0.0)
              if use_smooth:
                  from ..ops.split import smoothed_output
                  parent_out_j = t.leaf_value[bl]
                  lo = smoothed_output(lg, lh, lcn, parent_out_j,
                                       hp.lambda_l1, l2_eff, hp)
                  ro = smoothed_output(rg, rh, rcn, parent_out_j,
                                       hp.lambda_l1, l2_eff, hp)
              else:
                  lo = leaf_output(lg, lh, hp.lambda_l1, l2_eff,
                                   hp.max_delta_step)
                  ro = leaf_output(rg, rh, hp.lambda_l1, l2_eff,
                                   hp.max_delta_step)
              if hp.use_monotone:
                  # all methods clip children into the parent's box
                  # (monotone_constraints.hpp); basic additionally tightens
                  # each child's box at the midpoint along the split
                  # direction, intermediate/advanced refresh boxes per split
                  lmin_p, lmax_p = st["leaf_min"][bl], st["leaf_max"][bl]
                  lo = jnp.clip(lo, lmin_p, lmax_p)
                  ro = jnp.clip(ro, lmin_p, lmax_p)
                  if use_boxes:
                      # sibling-ordering repair (one source of truth with
                      # the strict learner, grower.py: clipping both
                      # children to the parent's range can inverse their
                      # order under the split feature's constraint;
                      # collapse inverted pairs to the midpoint)
                      mono_sf = monotone[feat]
                      inv = (~catl) & (((mono_sf > 0) & (lo > ro))
                                       | ((mono_sf < 0) & (lo < ro)))
                      mid_sib = jnp.clip((lo + ro) * 0.5, lmin_p, lmax_p)
                      lo = jnp.where(inv, mid_sib, lo)
                      ro = jnp.where(inv, mid_sib, ro)
                  if not use_boxes:
                      mono_f = monotone[feat]
                      is_num = ~catl
                      mid = (lo + ro) * 0.5
                      lmax_l = jnp.where(is_num & (mono_f > 0),
                                         jnp.minimum(lmax_p, mid), lmax_p)
                      lmin_l = jnp.where(is_num & (mono_f < 0),
                                         jnp.maximum(lmin_p, mid), lmin_p)
                      lmin_r = jnp.where(is_num & (mono_f > 0),
                                         jnp.maximum(lmin_p, mid), lmin_p)
                      lmax_r = jnp.where(is_num & (mono_f < 0),
                                         jnp.minimum(lmax_p, mid), lmax_p)
                  else:
                      lmin_l = lmin_r = lmin_p
                      lmax_l = lmax_r = lmax_p
              # children inherit the path plus the split feature
              new_path = st["path_f"][bl].at[feat].set(True)
              st["path_f"] = st["path_f"].at[bl].set(
                  jnp.where(ok, new_path, st["path_f"][bl]))
              st["path_f"] = st["path_f"].at[nl].set(
                  jnp.where(ok, new_path, st["path_f"][nl]))
              if use_boxes:
                  from .monotone import box_bounds, split_boxes
                  n_lo, n_hi = split_boxes(
                      st["leaf_lo"], st["leaf_hi"], bl, nl, feat, thr,
                      ~catl)
                  st["leaf_lo"] = jnp.where(ok, n_lo, st["leaf_lo"])
                  st["leaf_hi"] = jnp.where(ok, n_hi, st["leaf_hi"])
              d = t.leaf_depth[bl] + 1

              def w(arr, idx, val):
                  return arr.at[idx].set(jnp.where(ok, val, arr[idx]))

              t = t._replace(
                  split_feature=w(t.split_feature, nid, feat),
                  split_bin=w(t.split_bin, nid, thr),
                  default_left=w(t.default_left, nid, dl),
                  split_cat=w(t.split_cat, nid, catl),
                  cat_bitset=t.cat_bitset.at[nid].set(
                      jnp.where(ok, bitset, t.cat_bitset[nid])),
                  left_child=lc_arr, right_child=rc_arr,
                  split_gain=w(t.split_gain, nid, st["best_gain"][bl]),
                  internal_value=w(t.internal_value, nid,
                                   leaf_output(pg, ph, hp.lambda_l1,
                                               hp.lambda_l2,
                                               hp.max_delta_step)),
                  internal_count=w(t.internal_count, nid, pc),
                  leaf_depth=w(w(t.leaf_depth, bl, d), nl, d),
                  leaf_value=w(w(t.leaf_value, bl, lo), nl, ro),
                  leaf_count=w(w(t.leaf_count, bl, lcn), nl, rcn),
                  leaf_weight=w(w(t.leaf_weight, bl, lh), nl, rh),
                  num_leaves=jnp.where(ok, nl + 1, t.num_leaves),
              )
              st["sum_g"] = w(w(st["sum_g"], bl, lg), nl, rg)
              st["sum_h"] = w(w(st["sum_h"], bl, lh), nl, rh)
              st["count"] = w(w(st["count"], bl, lcn), nl, rcn)
              st["parent_node"] = w(w(st["parent_node"], bl, nid), nl, nid)
              st["parent_side"] = w(w(st["parent_side"], bl, 0), nl, 1)
              if hp.use_monotone:
                  st["leaf_min"] = w(w(st["leaf_min"], bl, lmin_l), nl, lmin_r)
                  st["leaf_max"] = w(w(st["leaf_max"], bl, lmax_l), nl, lmax_r)
              # split leaves' cached gains are consumed
              st["best_gain"] = st["best_gain"].at[bl].set(
                  jnp.where(ok, NEG_INF, st["best_gain"][bl]))
              if use_boxes:
                  # per-SPLIT bound refresh, same cadence as the strict
                  # learner: a leaf split later in this round sees the
                  # updated outputs of leaves split earlier (without this,
                  # two order-adjacent leaves split in one round could
                  # violate the constraint)
                  lower, upper = box_bounds(
                      st["leaf_lo"], st["leaf_hi"], t.leaf_value,
                      monotone, t.num_leaves)
                  st["leaf_min"] = jnp.where(ok, lower, st["leaf_min"])
                  st["leaf_max"] = jnp.where(ok, upper, st["leaf_max"])

          # smaller-child bookkeeping first: the fused partition kernel
          # emits the NEXT histogram pass's compaction keys, so it needs
          # the smaller-leaf set up front (state counts are already
          # updated by the record loop above)
          safe_nl = jnp.where(valid, new_leaves, L - 1)
          l_cnt = st["count"][parents]
          r_cnt = st["count"][safe_nl]
          smaller = jnp.where(l_cnt <= r_cnt, parents, safe_nl)

          if cegb is not None:
              # the round's K splits acquire their features for their
              # whole parent leaves (strict grower: cegb_used.at[feat],
              # cegb_rows |= in_parent & feat — here as one scatter-or +
              # one [n, K] x [K, F] matmul while ``lor`` still maps rows
              # to the split parents).  Splits later in this round see
              # earlier splits' acquisitions only at the NEXT round's
              # penalty refresh — the same one-round lag the batched
              # monotone/interaction paths document.
              feats_c = st["best_feat"][parents]                   # [K]
              st["cegb_used"] = st["cegb_used"].at[
                  jnp.where(valid, feats_c, 0)].max(valid)
              if use_lazy:
                  feat_oh = ((feats_c[:, None]
                              == lax.iota(jnp.int32, num_f)[None, :])
                             & valid[:, None]).astype(jnp.float32)  # [K, F]

                  # block-scanned [blk, K] x [K, F] matmuls: a single
                  # dense [K, n] f32 operand would be ~1.7 GB at 1e7
                  # rows x K=42 — the scan keeps the transient at
                  # [K, blk] while computing the identical result
                  def mark_block(_, xs):
                      lor_b, m_b = xs
                      ip = ((lor_b[None, :] == parents[:, None])
                            & valid[:, None]
                            & (m_b > 0)[None, :])                  # [K, blk]
                      return None, lax.dot_general(
                          ip.astype(jnp.float32).T, feat_oh,
                          (((1,), (0,)), ((), ()))) > 0.0          # [blk, F]

                  _, upd = lax.scan(
                      mark_block, None,
                      (jnp.pad(lor, (0, cegb_pad), constant_values=-1)
                       .reshape(cegb_nb, cegb_blk),
                       jnp.pad(mask_f, (0, cegb_pad))
                       .reshape(cegb_nb, cegb_blk)))
                  st["cegb_rows"] = st["cegb_rows"] | \
                      upd.reshape(-1, num_f)[:n]

          # ---- all K partitions in ONE widened pass (each row belongs to
          # at most one split parent, so the K moves compose by summation)
          sort_key = None
          payload = None
          with jax.named_scope("partition"):
              feats_k = st["best_feat"][parents]                      # [K]
              if fuse_partition and fuse_payload:
                  # payload-emitting variant: the next compacted round's
                  # [n, W+3] payload rides the partition pass instead of
                  # a separate XLA concat (round-6 glue elimination)
                  lor, sort_key, payload = partition_payload_pallas(
                      bins_t, bins_words, grad, hess, lor,
                      mask_f.astype(jnp.int32),
                      feats_k, st["best_thr"][parents],
                      st["best_dl"][parents].astype(jnp.int32),
                      nan_bin[feats_k].astype(jnp.int32),
                      parents, new_leaves, valid.astype(jnp.int32),
                      smaller, rows_per_block=min(hp.rows_per_block, 2048),
                      interpret=not _use_pallas())
              elif fuse_partition:
                  lor, sort_key = partition_select_pallas(
                      bins_t, lor, mask_f.astype(jnp.int32),
                      feats_k, st["best_thr"][parents],
                      st["best_dl"][parents].astype(jnp.int32),
                      nan_bin[feats_k].astype(jnp.int32),
                      parents, new_leaves, valid.astype(jnp.int32),
                      smaller, rows_per_block=min(hp.rows_per_block, 2048),
                      interpret=not _use_pallas())
              else:
                  cols_k = jax.vmap(
                      lambda f: _feature_bin_of_rows(bins_t, bundle, f))(
                          feats_k)
                  thr_k = st["best_thr"][parents][:, None]
                  dl_k = st["best_dl"][parents][:, None]
                  nanb_k = nan_bin[feats_k][:, None]
                  go_left_k = jnp.where(cols_k == nanb_k, dl_k,
                                        cols_k <= thr_k)
                  if hp.has_categorical:
                      bitsets_k = (jnp.stack(bitsets) if use_boxes
                                   else bitsets_arr)              # [K, B]
                      cat_k = is_cat[feats_k][:, None]                # [K, 1]
                      go_cat_k = jnp.take_along_axis(bitsets_k, cols_k,
                                                     axis=1)
                      go_left_k = jnp.where(cat_k, go_cat_k, go_left_k)
                  in_parent = (lor[None, :] == parents[:, None]) \
                      & valid[:, None]                                # [K, n]
                  move = in_parent & ~go_left_k                       # [K, n]
                  target = jnp.sum(move * new_leaves[:, None], axis=0)  # [n]
                  lor = jnp.where(jnp.any(move, axis=0), target, lor)

          st["tree"] = t
          st["leaf_of_row"] = lor
          st["n_splits"] = st["n_splits"] + jnp.sum(valid.astype(jnp.int32))
          st["progress"] = jnp.any(valid)

          # ---- ONE widened pass: histograms of the K smaller children
          with jax.named_scope("round_hist"):
              # masked row count of each smaller child (0 for invalid
              # slots) saves the membership reduction in the compaction
              # path.  Under shard_map the state counts are GLOBAL
              # (psum-ed) while compaction is per-shard, so pass no counts
              # there (recomputed locally).
              small_cnt = (jnp.where(valid, jnp.minimum(l_cnt, r_cnt), 0.0)
                           if axis_name is None else None)

              def hist_call(lv, cnts, skey=None, pay=None):
                  return _scaled(histogram_for_leaves_auto(
                      bins, bins_t, grad, hess, lor, lv, row_mask,
                      n_bins=hp.n_bins, rows_per_block=hp.rows_per_block,
                      hist_dtype=hp.hist_dtype, axis_name=hist_axis,
                      counts=cnts, bins_words=bins_words, sort_key=skey,
                      hist_kernel=hp.hist_kernel, bins_words_t=words_t,
                      payload=pay, overlap=overlap))

              left_small = (l_cnt <= r_cnt)[:, None, None, None]
              if not pooled:
                  # the fused kernel's keys target exactly the `smaller`
                  # set; the pooled path's extended leaf set rebuilds its
                  # own keys
                  h_small = hist_call(smaller, small_cnt, sort_key, payload)
                  h_parent = st["hist"][parents]
                  h_large = h_parent - h_small
                  h_left = jnp.where(left_small, h_small, h_large)
                  h_right = jnp.where(left_small, h_large, h_small)
                  hist = st["hist"]
                  hist = hist.at[parents].set(
                      jnp.where(valid[:, None, None, None], h_left,
                                hist[parents]))
                  hist = hist.at[safe_nl].set(
                      jnp.where(valid[:, None, None, None], h_right,
                                hist[safe_nl]))
                  st["hist"] = hist
              else:
                  # -- bounded pool: parents with an evicted histogram get
                  # BOTH children computed directly (no subtraction);
                  # the widened pass carries K smaller + up-to-K larger
                  p_slot = st["leaf_slot"][parents]            # [K]
                  present = (p_slot >= 0) & valid
                  larger = jnp.where(l_cnt <= r_cnt, safe_nl, parents)
                  need_direct = valid & ~present
                  large_cnt = jnp.where(need_direct,
                                        jnp.maximum(l_cnt, r_cnt), 0.0)
                  leaves_ext = jnp.concatenate(
                      [smaller, jnp.where(need_direct, larger, L - 1)])
                  # counts are GLOBAL under shard_map while compaction is
                  # per-shard — same gate as the non-pooled path: let the
                  # histogram op recompute local counts there
                  ext_cnt = (jnp.concatenate([small_cnt, large_cnt])
                             if axis_name is None else None)
                  h_ext = hist_call(leaves_ext, ext_cnt)
                  h_small = h_ext[:Kr]
                  h_parent = st["hist"][jnp.maximum(p_slot, 0)]
                  h_large = jnp.where(present[:, None, None, None],
                                      h_parent - h_small, h_ext[Kr:])
                  h_left = jnp.where(left_small, h_small, h_large)
                  h_right = jnp.where(left_small, h_large, h_small)

                  # -- slot allocation: free slots first, then evict the
                  # lowest-cached-gain occupants; this round's parent
                  # slots are locked (they become the left children's)
                  slot_leaf = st["slot_leaf"]                  # [P+1]
                  leaf_slot = st["leaf_slot"]                  # [L+1]
                  locked = jnp.zeros((P + 1,), bool).at[
                      jnp.where(present, p_slot, P)].set(True)[:P]
                  occ = slot_leaf[:P]
                  occ_gain = jnp.where(occ >= 0,
                                       st["best_gain"][jnp.maximum(occ, 0)],
                                       -jnp.inf)
                  order = jnp.argsort(
                      jnp.where(locked, jnp.inf, occ_gain))    # [P]
                  req = jnp.concatenate([need_direct, valid])  # [2K]
                  pos = jnp.cumsum(req.astype(jnp.int32)) - 1
                  alloc = jnp.where(req, order[jnp.clip(pos, 0, P - 1)], P)
                  # evict old occupants of granted slots
                  evicted = jnp.where(alloc < P,
                                      slot_leaf[jnp.minimum(alloc, P)], -1)
                  leaf_slot = leaf_slot.at[
                      jnp.where(evicted >= 0, evicted, L)].set(-1)
                  slot_l = jnp.where(present, p_slot, alloc[:Kr])
                  slot_r = alloc[Kr:]
                  tgt_l = jnp.where(valid, slot_l, P)
                  tgt_r = jnp.where(valid, slot_r, P)
                  hist = st["hist"].at[tgt_l].set(h_left)
                  hist = hist.at[tgt_r].set(h_right)
                  st["hist"] = hist
                  slot_leaf = slot_leaf.at[tgt_l].set(
                      jnp.where(valid, parents, -1))
                  slot_leaf = slot_leaf.at[tgt_r].set(
                      jnp.where(valid, safe_nl, -1))
                  leaf_slot = leaf_slot.at[
                      jnp.where(valid, parents, L)].set(slot_l)
                  leaf_slot = leaf_slot.at[
                      jnp.where(valid, safe_nl, L)].set(slot_r)
                  st["slot_leaf"] = slot_leaf.at[P].set(-1)
                  st["leaf_slot"] = leaf_slot.at[L].set(-1)

          # ---- child best splits, vmapped over the 2K children
          with jax.named_scope("find_splits"):
              kids = jnp.concatenate([parents, safe_nl])              # [2K]
              kid_hist = jnp.concatenate([h_left, h_right], axis=0)
              depths = st["tree"].leaf_depth[kids]
              # deterministic per-node keys folded on (split node id, side)
              # — unique per evaluation (a leaf id would COLLIDE between a
              # parent and its left child, freezing the by-node subset down
              # every left spine); same uniqueness source as the strict
              # learner's split-counter fold
              sides = jnp.concatenate([jnp.zeros((Kr,), jnp.int32),
                                       jnp.ones((Kr,), jnp.int32)])
              node2 = jnp.concatenate([node_ids, node_ids])
              keys = (jax.vmap(lambda nd, sd: jax.random.fold_in(
                          rng_key, nd * 2 + sd + 1))(node2, sides)
                      if use_rng else None)
              if use_paths or use_bynode:
                  paths_k = (st["path_f"][kids] if use_paths else
                             jnp.zeros((2 * Kr, num_f), bool))
                  fms = jax.vmap(node_mask)(
                      paths_k, keys) if use_bynode else \
                      jax.vmap(node_mask)(paths_k)
              else:
                  fms = (jnp.broadcast_to(feature_mask, (2 * Kr,)
                                          + feature_mask.shape)
                         if feature_mask is not None else None)
              pouts = st["tree"].leaf_value[kids]
              if cegb is not None:
                  # per-child penalty vectors from the round-updated
                  # acquisition state; the lazy not-yet-computed row
                  # counts for all 2K children come from block-scanned
                  # [2K, blk] x [blk, F] contractions over the
                  # POST-partition row map (bounded transients, same
                  # result as one [2K, n] x [n, F] matmul)
                  if use_lazy:
                      def count_block(acc, xs):
                          lor_b, m_b, rows_b = xs
                          ks = ((lor_b[None, :] == kids[:, None])
                                & (m_b > 0)[None, :])       # [2K, blk]
                          return acc + lax.dot_general(
                              ks.astype(jnp.float32),
                              (~rows_b).astype(jnp.float32),
                              (((1,), (0,)), ((), ()))), None

                      cnt_k, _ = lax.scan(
                          count_block,
                          jnp.zeros((2 * Kr, num_f), jnp.float32),
                          (jnp.pad(st["leaf_of_row"], (0, cegb_pad),
                                   constant_values=-1)
                           .reshape(cegb_nb, cegb_blk),
                           jnp.pad(mask_f, (0, cegb_pad))
                           .reshape(cegb_nb, cegb_blk),
                           jnp.pad(st["cegb_rows"],
                                   ((0, cegb_pad), (0, 0)),
                                   constant_values=True)
                           .reshape(cegb_nb, cegb_blk, num_f)))
                  else:
                      cnt_k = None
                  pens = jax.vmap(cegb_penalty, in_axes=(None, 0, 0))(
                      st["cegb_used"],
                      cnt_k if use_lazy else jnp.zeros((2 * Kr, 1)),
                      st["count"][kids])
              else:
                  pens = None
              if use_adv:
                  # advanced monotone: per-(feature, threshold) child
                  # bounds for each kid's upcoming split evaluation,
                  # from the round-refreshed boxes (strict learner
                  # computes the same right after each split; here the
                  # kids see ALL of this round's box updates)
                  from .monotone import advanced_split_bounds
                  advs = jax.vmap(
                      lambda lf: advanced_split_bounds(
                          st["leaf_lo"], st["leaf_hi"],
                          st["tree"].leaf_value, monotone,
                          st["tree"].num_leaves, lf, hp.n_bins))(kids)
              else:
                  advs = None
              res = jax.vmap(
                  child_best,
                  in_axes=(0, 0, 0, 0, 0, 0, 0,
                           None if fms is None else 0, 0,
                           None if keys is None else 0,
                           None if pens is None else 0,
                           None if advs is None else 0))(
                  kid_hist, st["sum_g"][kids],
                  st["sum_h"][kids], st["count"][kids],
                  depths, st["leaf_min"][kids],
                  st["leaf_max"][kids], fms, pouts, keys, pens, advs)
              ok2 = jnp.concatenate([valid, valid])
              gains2 = jnp.where(ok2, res.gain, st["best_gain"][kids])
              st["best_gain"] = st["best_gain"].at[kids].set(gains2)
              for name, field in (("best_feat", res.feature),
                                  ("best_thr", res.threshold),
                                  ("best_var", res.variant),
                                  ("best_lg", res.left_sum_g),
                                  ("best_lh", res.left_sum_h),
                                  ("best_lc", res.left_count)):
                  st[name] = st[name].at[kids].set(
                      jnp.where(ok2, field, st[name][kids]))
              st["best_dl"] = st["best_dl"].at[kids].set(
                  jnp.where(ok2, res.default_left, st["best_dl"][kids]))
              if hp.has_categorical:
                  kb = jax.vmap(winner_bitset)(
                      kid_hist, st["sum_g"][kids], st["sum_h"][kids],
                      st["count"][kids], res.feature, res.variant,
                      res.threshold)
                  st["best_bitset"] = st["best_bitset"].at[kids].set(
                      jnp.where(ok2[:, None], kb, st["best_bitset"][kids]))
          return st

      return round_body

    # Warmup: the masked histogram kernel's MXU cost scales with its 3*K
    # value channels, so rounds whose frontier holds < K splittable leaves
    # burn ~K/frontier of a full pass for nothing (profiled: the first ~5
    # rounds were 6 full-width passes = 35 ms of a 94 ms tree).  Early
    # rounds therefore run width-matched bodies (K=1,2,4,...) — identical
    # selection semantics, just fewer masked channels per pass.  Gated on
    # data size (static at trace time): each width is its own kernel
    # compilation, worth it only when passes are expensive.
    if forced is not None:
        # forced-split phase: one K=1 round per schedule entry, in BFS
        # order (entry index == split counter, as in the strict learner);
        # a failed entry aborts the remaining schedule
        f_leaf0 = forced[0]
        state = lax.while_loop(
            lambda st: (st["n_splits"] < L - 1) & ~st["force_failed"]
            & (f_leaf0[jnp.minimum(st["n_splits"],
                                   f_leaf0.shape[0] - 1)] >= 0),
            make_round_body(1, use_forced=True), state)
        # a failed/exhausted forced round leaves progress False; the
        # gain-based loops below must still run
        state["progress"] = jnp.bool_(True)
    if warmup and n >= _WARMUP_MIN_ROWS and forced is None \
            and ladder_profitable(hp.hist_kernel, hp.n_bins):
        # width QUADRUPLING (1, 4, 16, ...): each width always covers the
        # frontier (it at most doubles per round), and since kernel cost
        # is K-independent below 128 channels (docs/PERF_NOTES.md round
        # 3), fewer warmup rounds beat finer width matching — profiled
        # ~2 full passes saved per tree vs doubling.  Skipped after a
        # forced phase: the forced frontier can exceed the warmup widths.
        # Round 6: the ladder only pays where the K<=4 masked pass takes
        # the radix-JOINT kernel (auto dispatch at >= 128 bins); every
        # other mode's kernel is K-independent, so those configs SEED the
        # round loop at full width straight from the root histogram —
        # identical selections (top-k of a sub-K frontier picks the same
        # leaves at any width), ~2 fewer compiled round bodies and no
        # narrow warmup passes (ops/histogram.py ladder_profitable).
        kw = 1
        while kw < K:
            state = lax.cond(state["progress"] & (state["n_splits"] < L - 1),
                             make_round_body(kw), lambda st: st, state)
            kw *= 4
    # loop until the tree is full or a round makes no progress — a fixed
    # ceil((L-1)/K) budget would starve narrow-frontier (chain-shaped) trees
    # where only ~1 leaf per round carries positive gain
    state = lax.while_loop(
        lambda st: st["progress"] & (st["n_splits"] < L - 1),
        make_round_body(K), state)
    tree_out = state["tree"]._replace(leaf_path=state["path_f"])
    if cegb is not None:
        new_cegb = cegb._replace(
            feature_used=state["cegb_used"],
            used_rows=state["cegb_rows"] if use_lazy else None)
        return tree_out, state["leaf_of_row"], new_cegb
    return tree_out, state["leaf_of_row"]
