"""Linear-in-the-leaves model fitting (``linear_tree=true``).

TPU-native re-design of the reference linear tree learner (reference:
src/treelearner/linear_tree_learner.cpp:180 ``CalculateLinear`` — per-leaf
ridge regression over the leaf's path features, Eq. 3 of the GBDT-PL paper:
coeffs = −(XᵀHX + λI)⁻¹ Xᵀg with X = [raw path features | 1], solved with
Eigen on the CPU).  Here the per-leaf normal equations for ALL leaves are
accumulated in one pass with the same one-hot-matmul trick as the histogram
kernel (blockwise [rows → leaves] contraction on the MXU), then solved as one
batched ``jnp.linalg.solve`` over [L, K+1, K+1] systems.

Rows whose path features contain NaN are excluded from the fit and fall back
to the ordinary leaf output at prediction (reference tree.h:587-606).
Leaves with fewer usable rows than unknowns keep coeff 0 / const = leaf
output (linear_tree_learner.cpp:330-338).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(jax.jit, static_argnames=("max_feats", "rows_per_block"))
def fit_linear_leaves(raw: jax.Array, leaf_of_row: jax.Array,
                      leaf_path: jax.Array, is_numeric: jax.Array,
                      grad: jax.Array, hess: jax.Array,
                      row_mask, leaf_value: jax.Array,
                      linear_lambda: float, *, max_feats: int = 16,
                      rows_per_block: int = 4096
                      ) -> Tuple[jax.Array, jax.Array]:
    """Fit one linear model per leaf.

    raw: f32 [n, F] raw feature values (NaN preserved); leaf_path: bool
    [L, F]; is_numeric: bool [F]; grad/hess: f32 [n]; row_mask: bool [n] or
    None; leaf_value: f32 [L] fallback constants.  Returns (const [L],
    coeff [L, F] dense over packed features, zero where unused).
    """
    n, num_f = raw.shape
    L = leaf_path.shape[0]
    K = min(max_feats, num_f)

    # per-leaf numeric path features, padded to K with index F
    path_num = leaf_path & is_numeric[None, :]                     # [L, F]
    feat_idx = jax.vmap(
        lambda m: jnp.nonzero(m, size=K, fill_value=num_f)[0])(path_num)
    active = feat_idx < num_f                                      # [L, K]
    n_active = jnp.sum(active, axis=1)                             # [L]

    raw_pad = jnp.concatenate([raw, jnp.zeros((n, 1), raw.dtype)], axis=1)
    fi_row = feat_idx[leaf_of_row]                                 # [n, K]
    x = jnp.take_along_axis(raw_pad, fi_row, axis=1)               # [n, K]
    nan_row = jnp.any(jnp.isnan(x), axis=1)
    x = jnp.nan_to_num(x)
    xx = jnp.concatenate([x, jnp.ones((n, 1), x.dtype)], axis=1)   # [n, K+1]

    w = (~nan_row).astype(raw.dtype)
    if row_mask is not None:
        w = w * row_mask.astype(raw.dtype)

    # blockwise accumulation of XTHX [L, K+1, K+1], XTg [L, K+1], cnt [L]
    D = K + 1
    blk = min(rows_per_block, _round_up(max(n, 1), 128))
    n_pad = _round_up(n, blk)
    if n_pad != n:
        pad = ((0, n_pad - n),)
        xx = jnp.pad(xx, pad + ((0, 0),))
        w = jnp.pad(w, pad)
        grad = jnp.pad(grad, pad)
        hess = jnp.pad(hess, pad)
        leaf_of_row = jnp.pad(leaf_of_row, pad)
    nb = n_pad // blk
    xx_b = xx.reshape(nb, blk, D)
    w_b = w.reshape(nb, blk)
    g_b = (grad * w).reshape(nb, blk)
    h_b = (hess * w).reshape(nb, blk)
    lor_b = leaf_of_row.reshape(nb, blk)
    iota_l = lax.iota(jnp.int32, L)

    def block_step(acc, xs):
        xtx, xtg, cnt = acc
        xxb, wb, gb, hb, lb = xs
        onehot = (lb[:, None] == iota_l).astype(xxb.dtype)         # [blk, L]
        outer = (xxb[:, :, None] * xxb[:, None, :]
                 * hb[:, None, None]).reshape(blk, D * D)
        xtx = xtx + lax.dot_general(
            onehot, outer, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).reshape(L, D, D)
        xtg = xtg + lax.dot_general(
            onehot, xxb * gb[:, None], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        cnt = cnt + onehot.T @ wb
        return (xtx, xtg, cnt), None

    acc0 = (jnp.zeros((L, D, D), jnp.float32),
            jnp.zeros((L, D), jnp.float32), jnp.zeros((L,), jnp.float32))
    (xthx, xtg, cnt), _ = lax.scan(block_step, acc0,
                                   (xx_b, w_b, g_b, h_b, lor_b))

    # regularize + neutralize inactive dims (identity row/col, rhs 0 ⇒
    # coeff 0) so one batched solve covers every leaf's variable count
    am = jnp.concatenate([active, jnp.ones((L, 1), bool)], axis=1)  # [L, D]
    lam = jnp.concatenate([jnp.full((K,), linear_lambda, jnp.float32),
                           jnp.zeros((1,), jnp.float32)])
    a = xthx + jnp.diag(lam)[None, :, :]
    pair = am[:, :, None] & am[:, None, :]
    eye = jnp.eye(D, dtype=jnp.float32)[None, :, :]
    a = jnp.where(pair, a, eye)
    b = jnp.where(am, -xtg, 0.0)
    coefs = jnp.linalg.solve(a, b[..., None])[..., 0]               # [L, D]
    finite = jnp.all(jnp.isfinite(coefs), axis=1)

    ok = (cnt >= (n_active + 1).astype(cnt.dtype)) & finite & (n_active > 0)
    const = jnp.where(ok, coefs[:, K], leaf_value)
    coeff_k = jnp.where(ok[:, None] & active, coefs[:, :K], 0.0)
    coeff = jnp.zeros((L, num_f + 1), jnp.float32)
    coeff = coeff.at[jnp.arange(L)[:, None], feat_idx].set(coeff_k)[:, :num_f]
    return const, coeff


@jax.jit
def linear_leaf_scores(raw: jax.Array, leaf_of_row: jax.Array,
                       const: jax.Array, coeff: jax.Array,
                       leaf_value: jax.Array) -> jax.Array:
    """Per-row linear-tree contribution: const[leaf] + coeff[leaf]·raw, with
    NaN-in-used-feature rows falling back to the plain leaf output
    (reference tree.h:587 Predict is_linear_ branch)."""
    cf = coeff[leaf_of_row]                                        # [n, F]
    use = cf != 0.0
    nan_row = jnp.any(jnp.isnan(raw) & use, axis=1)
    contrib = jnp.sum(jnp.where(use, cf * jnp.nan_to_num(raw), 0.0), axis=1) \
        + const[leaf_of_row]
    return jnp.where(nan_row, leaf_value[leaf_of_row], contrib)
