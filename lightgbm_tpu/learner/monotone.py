"""Intermediate monotone constraints, TPU-native formulation.

The reference's ``monotone_constraints_method=intermediate``
(monotone_constraints.hpp:516 ``IntermediateLeafConstraints``) tightens each
leaf's output bounds with the ACTUAL outputs of the leaves it must stay
ordered against, and refreshes those bounds when new splits change outputs —
via recursive ``GoUpToFindLeavesToUpdate``/``GoDownToFindLeavesToUpdate``
tree walks.

Recursive pointer-chasing is the wrong shape for a TPU, and the walks are
just an incremental way of maintaining a quantity with a closed dense form:
every leaf is a box in bin space (``[lo_f, hi_f)`` per feature, from its
path).  Two DISTINCT leaves always have disjoint interiors, so if their
boxes intersect in every feature but ``f`` they are ORDERED along ``f`` —
and monotonicity requires their outputs ordered the same way.  Pairs
separated along several features need no direct constraint (a one-feature
path between them crosses intermediate leaves, and transitivity does the
rest).  So the per-leaf bounds are

    upper[i] = min out[j]  over pairs where i must stay below j
    lower[i] = max out[j]  over pairs where i must stay above j

computed in one [L, L, F] tensor pass (~1.8M bools at L=255, F=28 —
negligible) after every split, from the CURRENT outputs.  This is at least
as tight as the reference's incremental entries and never stale.

Categorical splits don't narrow boxes (a category subset isn't an
interval); children keep the parent box, which makes the scheme
conservative across categorical splits exactly like the reference (which
walks down through categorical splits unconditionally,
monotone_constraints.hpp:601-604).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_INF = 1e30  # python scalar: a module-level jnp constant captured across
# traces breaks the jit dispatch buffer count (missing hoisted-const buffer)


def box_bounds(leaf_lo: jax.Array, leaf_hi: jax.Array, out: jax.Array,
               monotone: jax.Array, num_leaves: jax.Array):
    """Fresh per-leaf output bounds from leaf boxes and current outputs.

    leaf_lo/leaf_hi: i32 [L, F] bin-space boxes (hi exclusive; unused slots
    must be empty boxes, lo == hi).  out: f32 [L] current leaf outputs.
    monotone: i32 [F] direction per feature.  num_leaves: live leaf count.

    Returns (lower, upper): f32 [L].
    """
    L, F = leaf_lo.shape
    live = jnp.arange(L) < num_leaves                          # [L]
    inter = (leaf_lo[:, None, :] < leaf_hi[None, :, :]) \
        & (leaf_lo[None, :, :] < leaf_hi[:, None, :])          # [L, L, F]
    n_inter = jnp.sum(inter.astype(jnp.int32), axis=2)         # [L, L]
    # boxes intersect everywhere but f AND are disjoint on f itself — boxes
    # that intersect in ALL features (siblings of a categorical split keep
    # identical boxes) are ordered along nothing and constrain nothing
    only_f_apart = ~inter & (n_inter[:, :, None] == (F - 1))
    i_below_j = leaf_hi[:, None, :] <= leaf_lo[None, :, :]     # [L, L, F]
    mono = monotone[None, None, :]
    # out[i] must stay <= out[j]:
    #   increasing f and i sits below j, or decreasing f and i sits above j
    i_under_j = only_f_apart & (((mono > 0) & i_below_j)
                                | ((mono < 0) & ~i_below_j))
    ids = jnp.arange(L)
    pair_ok = live[:, None] & live[None, :] \
        & (ids[:, None] != ids[None, :])                       # [L, L]
    under = jnp.any(i_under_j, axis=2) & pair_ok               # [L, L]
    upper = jnp.min(jnp.where(under, out[None, :], _INF), axis=1)
    lower = jnp.max(jnp.where(under.T, out[None, :], -_INF), axis=1)
    return lower, upper


def split_boxes(leaf_lo: jax.Array, leaf_hi: jax.Array, parent: jax.Array,
                new_leaf: jax.Array, feat: jax.Array, thr: jax.Array,
                is_numerical):
    """Box update for splitting ``parent`` into (parent, new_leaf) at
    bin threshold ``thr`` on ``feat`` (left = bins <= thr).

    Categorical splits leave both children on the parent box (conservative,
    see module docstring)."""
    p_lo = leaf_lo[parent]
    p_hi = leaf_hi[parent]
    cut = jnp.asarray(thr, jnp.int32) + 1
    left_hi = p_hi.at[feat].set(
        jnp.where(is_numerical, jnp.minimum(p_hi[feat], cut), p_hi[feat]))
    right_lo = p_lo.at[feat].set(
        jnp.where(is_numerical, jnp.maximum(p_lo[feat], cut), p_lo[feat]))
    leaf_hi = leaf_hi.at[parent].set(left_hi)
    leaf_lo = leaf_lo.at[new_leaf].set(right_lo)
    leaf_hi = leaf_hi.at[new_leaf].set(p_hi)
    return leaf_lo, leaf_hi
