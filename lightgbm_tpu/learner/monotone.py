"""Intermediate monotone constraints, TPU-native formulation.

The reference's ``monotone_constraints_method=intermediate``
(monotone_constraints.hpp:516 ``IntermediateLeafConstraints``) tightens each
leaf's output bounds with the ACTUAL outputs of the leaves it must stay
ordered against, and refreshes those bounds when new splits change outputs —
via recursive ``GoUpToFindLeavesToUpdate``/``GoDownToFindLeavesToUpdate``
tree walks.

Recursive pointer-chasing is the wrong shape for a TPU, and the walks are
just an incremental way of maintaining a quantity with a closed dense form:
every leaf is a box in bin space (``[lo_f, hi_f)`` per feature, from its
path).  Two DISTINCT leaves always have disjoint interiors, so if their
boxes intersect in every feature but ``f`` they are ORDERED along ``f`` —
and monotonicity requires their outputs ordered the same way.  Pairs
separated along several features need no direct constraint (a one-feature
path between them crosses intermediate leaves, and transitivity does the
rest).  So the per-leaf bounds are

    upper[i] = min out[j]  over pairs where i must stay below j
    lower[i] = max out[j]  over pairs where i must stay above j

computed in one [L, L, F] tensor pass (~1.8M bools at L=255, F=28 —
negligible) after every split, from the CURRENT outputs.  This is at least
as tight as the reference's incremental entries and never stale.

Categorical splits don't narrow boxes (a category subset isn't an
interval); children keep the parent box, which makes the scheme
conservative across categorical splits exactly like the reference (which
walks down through categorical splits unconditionally,
monotone_constraints.hpp:601-604).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_INF = 1e30  # python scalar: a module-level jnp constant captured across
# traces breaks the jit dispatch buffer count (missing hoisted-const buffer)


def box_bounds(leaf_lo: jax.Array, leaf_hi: jax.Array, out: jax.Array,
               monotone: jax.Array, num_leaves: jax.Array):
    """Fresh per-leaf output bounds from leaf boxes and current outputs.

    leaf_lo/leaf_hi: i32 [L, F] bin-space boxes (hi exclusive; unused slots
    must be empty boxes, lo == hi).  out: f32 [L] current leaf outputs.
    monotone: i32 [F] direction per feature.  num_leaves: live leaf count.

    Returns (lower, upper): f32 [L].
    """
    L, F = leaf_lo.shape
    live = jnp.arange(L) < num_leaves                          # [L]
    inter = (leaf_lo[:, None, :] < leaf_hi[None, :, :]) \
        & (leaf_lo[None, :, :] < leaf_hi[:, None, :])          # [L, L, F]
    n_inter = jnp.sum(inter.astype(jnp.int32), axis=2)         # [L, L]
    # boxes intersect everywhere but f AND are disjoint on f itself — boxes
    # that intersect in ALL features (siblings of a categorical split keep
    # identical boxes) are ordered along nothing and constrain nothing
    only_f_apart = ~inter & (n_inter[:, :, None] == (F - 1))
    i_below_j = leaf_hi[:, None, :] <= leaf_lo[None, :, :]     # [L, L, F]
    mono = monotone[None, None, :]
    # out[i] must stay <= out[j]:
    #   increasing f and i sits below j, or decreasing f and i sits above j
    i_under_j = only_f_apart & (((mono > 0) & i_below_j)
                                | ((mono < 0) & ~i_below_j))
    ids = jnp.arange(L)
    pair_ok = live[:, None] & live[None, :] \
        & (ids[:, None] != ids[None, :])                       # [L, L]
    under = jnp.any(i_under_j, axis=2) & pair_ok               # [L, L]
    upper = jnp.min(jnp.where(under, out[None, :], _INF), axis=1)
    lower = jnp.max(jnp.where(under.T, out[None, :], -_INF), axis=1)
    return lower, upper


def advanced_split_bounds(leaf_lo: jax.Array, leaf_hi: jax.Array,
                          out: jax.Array, monotone: jax.Array,
                          num_leaves: jax.Array, leaf, n_bins: int):
    """Per-(split-feature, threshold) child output bounds for splitting
    ``leaf`` — the TPU formulation of the reference's
    ``monotone_constraints_method=advanced``
    (monotone_constraints.hpp:858 ``AdvancedLeafConstraints``).

    The intermediate method applies a constraining neighbor's output to the
    WHOLE leaf; but a neighbor adjacent along monotone feature ``fj`` that
    only overlaps part of the leaf's range in split feature ``g`` bounds
    only the child that still overlaps it.  With leaf boxes this is a
    prefix/suffix structure over thresholds:

      left child [lo_g, t]:  j applies iff lo_g(j) <= t    (prefix)
      right child (t, hi_g): j applies iff hi_g(j) - 1 > t (suffix)

    (a neighbor adjacent along ``g`` itself bounds both children at every
    threshold).  Returns (lmin_left, lmax_left, lmin_right, lmax_right),
    each f32 [F, n_bins].
    """
    L, F = leaf_lo.shape
    inf = jnp.float32(_INF)
    i_lo = leaf_lo[leaf]                                      # [F]
    i_hi = leaf_hi[leaf]
    inter = (leaf_lo < i_hi[None, :]) & (i_lo[None, :] < leaf_hi)  # [L, F]
    n_inter = jnp.sum(inter.astype(jnp.int32), axis=1)        # [L]
    one_apart = (n_inter == F - 1)                            # [L]
    f_apart = jnp.argmax(~inter, axis=1)                      # [L]
    ids = jnp.arange(L)
    j_hi_f = jnp.take_along_axis(leaf_hi, f_apart[:, None], axis=1)[:, 0]
    j_lo_f = jnp.take_along_axis(leaf_lo, f_apart[:, None], axis=1)[:, 0]
    i_lo_f = i_lo[f_apart]
    i_hi_f = i_hi[f_apart]
    j_below = j_hi_f <= i_lo_f                                # [L]
    # sanity: one_apart & ~j_below implies j above (boxes are disjoint)
    mono_j = monotone[f_apart]                                # [L]
    valid = one_apart & (ids < num_leaves) & (ids != leaf) \
        & (mono_j != 0) & ((j_hi_f <= i_lo_f) | (j_lo_f >= i_hi_f))
    # leaf must stay <= out[j] ("under"): increasing fj with j above, or
    # decreasing fj with j below
    under = valid & (((mono_j > 0) & ~j_below) | ((mono_j < 0) & j_below))
    over = valid & (((mono_j > 0) & j_below) | ((mono_j < 0) & ~j_below))

    # threshold ranges per (neighbor, split feature): left child [lo_g, t]
    # overlaps j iff lo_g(j) <= t (prefix from ``starts``); right child
    # (t, hi_g) overlaps j iff hi_g(j) >= t + 2, i.e. suffix positions up
    # to hi_g(j) - 2 (``r_pos``); a neighbor adjacent along g itself bounds
    # both children at every threshold
    same_f = jax.nn.one_hot(f_apart, F, dtype=bool)           # [L, F]
    starts = jnp.where(same_f, 0,
                       jnp.clip(leaf_lo, 0, n_bins - 1))      # [L, F]
    r_pos = jnp.where(same_f, n_bins - 1,
                      jnp.clip(leaf_hi, 0, n_bins) - 2)       # [L, F]
    # r_pos == -1 (hi_g(j) <= 1) never matches a bin: j drops out, correct

    b_iota = jnp.arange(n_bins)

    def scatter_reduce(mask, at, red_init, reduce_min):
        # M[g, b] = reduce over j in mask with at[j, g] == b of out[j]
        oh = (at[:, :, None] == b_iota[None, None, :]) \
            & mask[:, None, None]                             # [L, F, B]
        vals = jnp.where(oh, out[:, None, None], red_init)
        return jnp.min(vals, axis=0) if reduce_min else jnp.max(vals, axis=0)

    cummin = lambda x: lax.associative_scan(jnp.minimum, x, axis=1)
    cummax = lambda x: lax.associative_scan(jnp.maximum, x, axis=1)

    # upper bounds from the "under" set
    m_left_u = scatter_reduce(under, starts, inf, True)        # [F, B]
    lmax_left = cummin(m_left_u)
    m_right_u = scatter_reduce(under, r_pos, inf, True)
    lmax_right = cummin(m_right_u[:, ::-1])[:, ::-1]
    # lower bounds from the "over" set
    m_left_o = scatter_reduce(over, starts, -inf, False)
    lmin_left = cummax(m_left_o)
    m_right_o = scatter_reduce(over, r_pos, -inf, False)
    lmin_right = cummax(m_right_o[:, ::-1])[:, ::-1]
    return lmin_left, lmax_left, lmin_right, lmax_right


def split_boxes(leaf_lo: jax.Array, leaf_hi: jax.Array, parent: jax.Array,
                new_leaf: jax.Array, feat: jax.Array, thr: jax.Array,
                is_numerical):
    """Box update for splitting ``parent`` into (parent, new_leaf) at
    bin threshold ``thr`` on ``feat`` (left = bins <= thr).

    Categorical splits leave both children on the parent box (conservative,
    see module docstring)."""
    p_lo = leaf_lo[parent]
    p_hi = leaf_hi[parent]
    cut = jnp.asarray(thr, jnp.int32) + 1
    left_hi = p_hi.at[feat].set(
        jnp.where(is_numerical, jnp.minimum(p_hi[feat], cut), p_hi[feat]))
    right_lo = p_lo.at[feat].set(
        jnp.where(is_numerical, jnp.maximum(p_lo[feat], cut), p_lo[feat]))
    leaf_hi = leaf_hi.at[parent].set(left_hi)
    leaf_lo = leaf_lo.at[new_leaf].set(right_lo)
    leaf_hi = leaf_hi.at[new_leaf].set(p_hi)
    return leaf_lo, leaf_hi
