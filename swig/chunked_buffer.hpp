/* ChunkedBuffer — streaming input staging for JVM consumers of the
 * lightgbm_tpu C ABI (counterpart of the reference's
 * swig/ChunkedArray_API_extensions.i over utils/chunked_array.hpp:
 * SynapseML-style embedders accumulate rows chunk by chunk without
 * knowing the final count, then hand the chunk table to
 * LGBMTPU_DatasetCreateFromMats / PushRows).
 *
 * Fresh TPU-side design, not a port: chunks are RAII-owned
 * (std::vector of std::unique_ptr<T[]>), the high-level add() API keeps
 * the insert cursor, and release is automatic — the reference's manual
 * release()/new_chunk() low-level surface collapses into clear().
 */
#ifndef LGBTPU_SWIG_CHUNKED_BUFFER_HPP_
#define LGBTPU_SWIG_CHUNKED_BUFFER_HPP_

#include <stdint.h>

#include <memory>
#include <vector>

template <typename T>
class ChunkedBuffer {
 public:
  explicit ChunkedBuffer(int64_t chunk_size)
      : chunk_size_(chunk_size > 0 ? chunk_size : 1), added_(0) {}

  /* append one value, growing by a chunk when the last one is full */
  void add(T value) {
    const int64_t pos = added_ % chunk_size_;
    if (pos == 0 && added_ / chunk_size_ >=
        static_cast<int64_t>(chunks_.size())) {
      chunks_.emplace_back(new T[chunk_size_]());
    }
    chunks_[added_ / chunk_size_][pos] = value;
    ++added_;
  }

  int64_t get_add_count() const { return added_; }
  int64_t get_chunk_size() const { return chunk_size_; }
  int64_t get_chunks_count() const {
    return static_cast<int64_t>(chunks_.size());
  }
  /* elements in the LAST chunk (it may be partially filled) */
  int64_t get_last_chunk_add_count() const {
    if (added_ == 0) return 0;
    const int64_t r = added_ % chunk_size_;
    return r == 0 ? chunk_size_ : r;
  }

  /* random access across chunk boundaries (bounds-unchecked hot path;
   * getitem() below is the checked SWIG-facing one) */
  T at(int64_t i) const {
    return chunks_[i / chunk_size_][i % chunk_size_];
  }
  int getitem(int64_t i, T* out) const {
    if (i < 0 || i >= added_ || out == nullptr) return -1;
    *out = at(i);
    return 0;
  }
  int setitem(int64_t i, T value) {
    if (i < 0 || i >= added_) return -1;
    chunks_[i / chunk_size_][i % chunk_size_] = value;
    return 0;
  }

  /* chunk table for the *FromMats-style ABI entries */
  T* chunk_ptr(int64_t c) const {
    if (c < 0 || c >= get_chunks_count()) return nullptr;
    return chunks_[c].get();
  }
  const T** chunk_table() {
    table_.clear();
    for (const auto& ch : chunks_) {
      table_.push_back(ch.get());
    }
    return table_.data();
  }

  /* copy everything into one contiguous destination */
  void coalesce_to(T* dst) const {
    int64_t left = added_;
    for (const auto& ch : chunks_) {
      const int64_t take = left < chunk_size_ ? left : chunk_size_;
      for (int64_t i = 0; i < take; ++i) dst[i] = ch[i];
      dst += take;
      left -= take;
      if (left <= 0) break;
    }
  }

  void clear() {
    chunks_.clear();
    table_.clear();
    added_ = 0;
  }

 private:
  int64_t chunk_size_;
  int64_t added_;
  std::vector<std::unique_ptr<T[]>> chunks_;
  std::vector<const T*> table_;
};

#endif  // LGBTPU_SWIG_CHUNKED_BUFFER_HPP_
