/* String-returning conveniences for JVM consumers — counterpart of the
 * reference's swig/StringArray.i + StringArray_API_extensions.i.  The
 * reference needed a managed char** helper class because its C API
 * fills caller-allocated string arrays; this ABI's name getters return
 * ONE newline-joined buffer (capi.h GetFeatureNames/GetEvalNames), so
 * the JVM side needs only sized-fetch wrappers — String.split("\n")
 * replaces the whole StringArray class. */

%newobject LGBMTPU_BoosterGetEvalNamesSWIG;
%newobject LGBMTPU_BoosterGetFeatureNamesSWIG;
%newobject LGBMTPU_DatasetGetFeatureNamesSWIG;
%newobject LGBMTPU_BoosterGetLoadedParamSWIG;
%newobject LGBMTPU_BoosterDumpModelSWIG;

%inline %{
#include <stdlib.h>

/* shared sized-fetch: call once for the length, once for the bytes */
typedef int (*lgbtpu_strfetch_t)(int64_t, char*, int64_t, int64_t*);

static char* lgbtpu_fetch_string_(int64_t handle, lgbtpu_strfetch_t fn) {
  int64_t need = 0;
  if (fn(handle, NULL, 0, &need) != 0 || need <= 0) return NULL;
  char* dst = (char*)malloc((size_t)need);
  if (!dst) return NULL;
  int64_t cap = need;
  if (fn(handle, dst, cap, &need) != 0) {
    free(dst);
    return NULL;
  }
  return dst;
}

static int lgbtpu_eval_names_(int64_t h, char* buf, int64_t len,
                              int64_t* need) {
  return LGBMTPU_BoosterGetEvalNames(h, buf, len, need);
}
static int lgbtpu_feat_names_(int64_t h, char* buf, int64_t len,
                              int64_t* need) {
  return LGBMTPU_BoosterGetFeatureNames(h, buf, len, need);
}
static int lgbtpu_ds_feat_names_(int64_t h, char* buf, int64_t len,
                                 int64_t* need) {
  return LGBMTPU_DatasetGetFeatureNames(h, buf, len, need);
}
static int lgbtpu_loaded_param_(int64_t h, char* buf, int64_t len,
                                int64_t* need) {
  return LGBMTPU_BoosterGetLoadedParam(h, buf, len, need);
}

/* newline-joined eval metric names (split on "\n" JVM-side) */
char* LGBMTPU_BoosterGetEvalNamesSWIG(int64_t booster) {
  return lgbtpu_fetch_string_(booster, lgbtpu_eval_names_);
}

/* newline-joined feature names of a trained booster */
char* LGBMTPU_BoosterGetFeatureNamesSWIG(int64_t booster) {
  return lgbtpu_fetch_string_(booster, lgbtpu_feat_names_);
}

/* newline-joined feature names of a dataset */
char* LGBMTPU_DatasetGetFeatureNamesSWIG(int64_t dataset) {
  return lgbtpu_fetch_string_(dataset, lgbtpu_ds_feat_names_);
}

/* JSON of the parameters a loaded model carries */
char* LGBMTPU_BoosterGetLoadedParamSWIG(int64_t booster) {
  return lgbtpu_fetch_string_(booster, lgbtpu_loaded_param_);
}

/* JSON dump of the model (num_iteration <= 0 = all) */
char* LGBMTPU_BoosterDumpModelSWIG(int64_t booster, int num_iteration) {
  int64_t need = 0;
  if (LGBMTPU_BoosterDumpModel(booster, num_iteration, NULL, 0,
                               &need) != 0 || need <= 0) {
    return NULL;
  }
  char* dst = (char*)malloc((size_t)need);
  if (!dst) return NULL;
  int64_t cap = need;
  if (LGBMTPU_BoosterDumpModel(booster, num_iteration, dst, cap,
                               &need) != 0) {
    free(dst);
    return NULL;
  }
  return dst;
}
%}
