/* ChunkedBuffer SWIG surface — the streaming-ingestion helpers JVM
 * consumers build on (counterpart of the reference's
 * swig/ChunkedArray_API_extensions.i).  Rows accumulate in fixed-size
 * chunks without a known final count; LGBMTPU_DatasetCreateFromChunks
 * hands the chunk table to the ABI's multi-matrix constructor. */

%{
#include "chunked_buffer.hpp"
%}

%include "chunked_buffer.hpp"

%template(doubleChunkedBuffer) ChunkedBuffer<double>;
%template(floatChunkedBuffer) ChunkedBuffer<float>;
%template(int32ChunkedBuffer) ChunkedBuffer<int32_t>;

%inline %{
#include <stdint.h>
#include <vector>

/* Create a Dataset straight from chunked staging buffers: the features
 * buffer must have been filled row-major with a chunk_size that is a
 * multiple of ncol (each chunk holds whole rows — the same contract the
 * reference documents for LGBM_DatasetCreateFromMats over ChunkedArray).
 * The label buffer is coalesced (labels are 8 bytes/row; the copy is
 * noise next to binning). */
int LGBMTPU_DatasetCreateFromChunks(ChunkedBuffer<double>* features,
                                    ChunkedBuffer<double>* labels,
                                    int64_t ncol, const char* params_json,
                                    int64_t* out) {
  if (!features || !labels || ncol <= 0 ||
      features->get_chunk_size() % ncol != 0 ||
      features->get_add_count() % ncol != 0 ||
      features->get_add_count() / ncol != labels->get_add_count()) {
    return -1;
  }
  const int nmat = (int)features->get_chunks_count();
  std::vector<int32_t> nrows((size_t)(nmat > 0 ? nmat : 1));
  const int64_t rows_per_chunk = features->get_chunk_size() / ncol;
  for (int c = 0; c < nmat; ++c) {
    nrows[(size_t)c] = (int32_t)rows_per_chunk;
  }
  if (nmat > 0) {
    nrows[(size_t)(nmat - 1)] =
        (int32_t)((features->get_add_count() / ncol) -
                  rows_per_chunk * (nmat - 1));
  }
  std::vector<double> label_flat((size_t)labels->get_add_count());
  labels->coalesce_to(label_flat.data());
  return LGBMTPU_DatasetCreateFromMats(
      nmat, features->chunk_table(), nrows.data(), ncol,
      label_flat.data(), params_json, out);
}

/* Streaming push of one staged chunk table into a pre-initialized
 * Dataset (LGBMTPU_DatasetInitStreaming + PushRows consumers): pushes
 * each chunk as a row block. */
int LGBMTPU_DatasetPushChunks(int64_t dataset,
                              ChunkedBuffer<double>* features,
                              ChunkedBuffer<double>* labels,
                              int64_t ncol) {
  if (!features || !labels || ncol <= 0 ||
      features->get_chunk_size() % ncol != 0 ||
      features->get_add_count() % ncol != 0 ||
      features->get_add_count() / ncol != labels->get_add_count()) {
    return -1;  // incl. rows/labels mismatch: never read past label_flat
  }
  std::vector<double> label_flat((size_t)labels->get_add_count());
  labels->coalesce_to(label_flat.data());
  const int64_t rows_per_chunk = features->get_chunk_size() / ncol;
  int64_t row0 = 0;
  const int64_t total_rows = features->get_add_count() / ncol;
  for (int64_t c = 0; c < features->get_chunks_count(); ++c) {
    int64_t rows = rows_per_chunk;
    if (row0 + rows > total_rows) rows = total_rows - row0;
    if (rows <= 0) break;
    const int rc = LGBMTPU_DatasetPushRows(
        dataset, features->chunk_ptr(c), rows, ncol,
        label_flat.data() + row0);
    if (rc != 0) return rc;
    row0 += rows;
  }
  return 0;
}
%}
