/* SWIG interface for the lightgbm_tpu C ABI — the JVM consumer path
 * (counterpart of the reference's swig/lightgbmlib.i, which SynapseML-style
 * JVM embedders build against).  Generates a Java (or other target)
 * binding over native/capi.h; link the result against liblgbtpu_capi.so.
 *
 *   swig -java -package io.lgbtpu -outdir java/ lgbtpulib.i
 *
 * The handle model is simpler than the reference's: every handle is an
 * opaque int64, so no pointer-manipulation helpers are needed — Java longs
 * carry handles directly, and carrays.i covers the numeric buffers.
 */
%module lgbtpulib

%{
#include "../lightgbm_tpu/native/capi.h"
%}

%include "carrays.i"
%include "cpointer.i"
%include "stdint.i"

/* primitive buffer helpers for JVM callers (reference .i uses the same
 * carrays pattern for its double/int arrays) */
%array_functions(double, doubleArray)
%array_functions(float, floatArray)
%array_functions(int, intArray)
%array_functions(int32_t, int32Array)
%array_functions(int64_t, int64Array)
%pointer_functions(int, intp)
%pointer_functions(int64_t, int64p)
%pointer_functions(double, doublep)

/* function-pointer-taking entries are driven from native embedders, not
 * the JVM; exclude them from the generated binding like the reference
 * ignores its non-JVM-safe entries */
%ignore LGBMTPU_RegisterLogCallback;
%ignore LGBMTPU_NetworkInitWithFunctions;
%ignore LGBMTPU_DatasetCreateFromCSRFunc;
%ignore LGBMTPU_DatasetCreateFromSampledColumn;
%ignore LGBMTPU_BoosterPredictForMats;
%ignore LGBMTPU_BoosterPredictSparseOutput;
%ignore LGBMTPU_BoosterFreePredictSparse;
%ignore LGBMTPU_DatasetCreateFromArrow;
%ignore LGBMTPU_DatasetSetFieldFromArrow;
%ignore LGBMTPU_BoosterPredictForArrow;

%include "../lightgbm_tpu/native/capi.h"

/* streaming-ingestion + string helpers for JVM consumers (counterparts
 * of the reference's ChunkedArray_API_extensions.i / StringArray.i) */
%include "chunked_api_extensions.i"
%include "string_api_extensions.i"

/* %newobject: SWIG's wrapper copies the returned string into the target
 * language and then free()s it — so the allocation below must be malloc. */
%newobject LGBMTPU_BoosterSaveModelToStringSWIG;

%inline %{
#include <stdlib.h>
/* buffer-sizing convenience mirroring the reference's
 * LGBM_BoosterSaveModelToStringSWIG.  (*out_len is in/out: capacity in,
 * required size incl. NUL out — capi_impl.booster_save_model_to_string.) */
char* LGBMTPU_BoosterSaveModelToStringSWIG(int64_t handle) {
  int64_t len = 0;
  if (LGBMTPU_BoosterSaveModelToString(handle, NULL, &len)) return NULL;
  int64_t cap = len;
  char* dst = (char*)malloc((size_t)cap);
  if (!dst) return NULL;
  if (LGBMTPU_BoosterSaveModelToString(handle, dst, &cap)) {
    free(dst);
    return NULL;
  }
  return dst;
}
%}
