# testthat suite for lightgbm.tpu — runnable wherever R + the built
# package exist (the repo CI image has no R; tests/test_r_package.py is
# the in-repo gate).

library(testthat)
library(lightgbm.tpu)

make_data <- function(n = 500L, f = 6L, seed = 7L) {
  set.seed(seed)
  X <- matrix(rnorm(n * f), ncol = f)
  colnames(X) <- paste0("feat", seq_len(f))
  y <- as.numeric(X[, 1L] + 0.5 * X[, 2L] + rnorm(n, sd = 0.1) > 0)
  list(X = X, y = y)
}

test_that("Dataset constructs from a matrix and reports dims", {
  d <- make_data()
  ds <- lgb.Dataset(d$X, label = d$y)
  lgb.Dataset.construct(ds)
  expect_equal(dim(ds), c(500L, 6L))
  expect_equal(length(get_field(ds, "label")), 500L)
})

test_that("train -> predict improves over chance and respects types", {
  d <- make_data()
  ds <- lgb.Dataset(d$X, label = d$y)
  bst <- lgb.train(list(objective = "binary", num_leaves = 15L),
                   ds, nrounds = 30L, verbose = 0L)
  p <- predict(bst, d$X)
  expect_true(all(p >= 0 & p <= 1))
  acc <- mean((p > 0.5) == (d$y > 0.5))
  expect_gt(acc, 0.9)
  raw <- predict(bst, d$X, type = "raw")
  expect_equal(1 / (1 + exp(-raw)), p, tolerance = 1e-5)
  leaves <- predict(bst, d$X, type = "leaf")
  expect_true(all(leaves == floor(leaves)))
  contrib <- predict(bst, d$X, type = "contrib")
  expect_equal(ncol(contrib), ncol(d$X) + 1L)
  expect_equal(rowSums(contrib), raw, tolerance = 1e-4)
})

test_that("save/load round-trips predictions", {
  d <- make_data()
  ds <- lgb.Dataset(d$X, label = d$y)
  bst <- lgb.train(list(objective = "regression"), ds, nrounds = 10L,
                   verbose = 0L)
  f <- tempfile(fileext = ".txt")
  lgb.save(bst, f)
  bst2 <- lgb.load(f)
  expect_equal(predict(bst2, d$X), predict(bst, d$X), tolerance = 1e-9)
  unlink(f)
})

test_that("early stopping sets best_iter", {
  d <- make_data(1000L)
  tr <- seq_len(700L)
  ds <- lgb.Dataset(d$X[tr, ], label = d$y[tr])
  dv <- lgb.Dataset.create.valid(ds, d$X[-tr, ], label = d$y[-tr])
  bst <- lgb.train(list(objective = "binary", learning_rate = 0.3),
                   ds, nrounds = 200L,
                   valids = list(va = dv),
                   early_stopping_rounds = 5L, verbose = 0L)
  expect_gt(bst$best_iter, 0L)
  expect_true(length(lgb.get.eval.result(bst, "va",
    names(bst$record_evals$va)[[1L]])) > 0L)
})

test_that("cv aggregates fold metrics", {
  d <- make_data()
  ds <- lgb.Dataset(d$X, label = d$y)
  cv <- lgb.cv(list(objective = "binary", metric = "binary_logloss"),
               ds, nrounds = 20L, nfold = 3L, verbose = 0L)
  expect_equal(length(cv$boosters), 3L)
  expect_gt(cv$best_iter, 0L)
  m1 <- names(cv$record_evals)[[1L]]
  expect_equal(length(cv$record_evals[[m1]]$mean), 20L)
})

test_that("importance and tree table are well-formed", {
  d <- make_data()
  ds <- lgb.Dataset(d$X, label = d$y)
  bst <- lgb.train(list(objective = "binary"), ds, nrounds = 5L,
                   verbose = 0L)
  imp <- lgb.importance(bst)
  expect_true(all(c("Feature", "Gain", "Cover", "Frequency")
                  %in% names(imp)))
  expect_equal(sum(imp$Gain), 1, tolerance = 1e-6)
  tt <- lgb.model.dt.tree(bst)
  expect_true(all(c("tree_index", "split_feature", "leaf_value")
                  %in% names(tt)))
  expect_true(any(!is.na(tt$leaf_value)))
})

test_that("serialization keep-alive survives saveRDS", {
  d <- make_data()
  ds <- lgb.Dataset(d$X, label = d$y)
  bst <- lgb.train(list(objective = "regression"), ds, nrounds = 5L,
                   verbose = 0L)
  lgb.make_serializable(bst)
  f <- tempfile(fileext = ".rds")
  saveRDS(bst, f)
  bst2 <- readRDS(f)
  bst2$handle <- NULL   # simulate a fresh session
  lgb.restore_handle(bst2)
  expect_equal(predict(bst2, d$X), predict(bst, d$X), tolerance = 1e-9)
  unlink(f)
})
