/* R glue for lightgbm_tpu — .Call wrappers over the LGBMTPU_* C ABI
 * (native/capi.h).  The counterpart of the reference's
 * R-package/src/lightgbm_R.cpp (which wraps LGBM_* the same way), but
 * written against this repo's ABI conventions: opaque int64 handles,
 * params as a JSON string, 0/-1 returns with LGBMTPU_GetLastError().
 *
 * Handle lifetime: every constructor wraps the int64 id in an R
 * external pointer whose finalizer calls LGBMTPU_FreeHandle, so R's GC
 * owns native resources (the reference reaches the same goal with
 * R_RegisterCFinalizerEx on booster/dataset handles).
 *
 * String outputs use the ABI's two-call protocol: call with a guess
 * buffer, re-call with the reported length when it didn't fit.
 */

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include <R.h>
#include <Rinternals.h>

#include "../../lightgbm_tpu/native/capi.h"

namespace {

void check(int rc) {
  if (rc != 0) {
    Rf_error("lightgbm.tpu: %s", LGBMTPU_GetLastError());
  }
}

int64_t handle_of(SEXP ptr) {
  if (TYPEOF(ptr) != EXTPTRSXP) {
    Rf_error("lightgbm.tpu: expected a handle (external pointer)");
  }
  void* p = R_ExternalPtrAddr(ptr);
  if (p == nullptr) {
    Rf_error("lightgbm.tpu: handle already freed");
  }
  // the id is stored in the pointer value itself (ids are small
  // sequential integers, never 0 for a live handle)
  return static_cast<int64_t>(reinterpret_cast<intptr_t>(p)) - 1;
}

void finalize_handle(SEXP ptr) {
  void* p = R_ExternalPtrAddr(ptr);
  if (p != nullptr) {
    LGBMTPU_FreeHandle(static_cast<int64_t>(reinterpret_cast<intptr_t>(p)) - 1);
    R_ClearExternalPtr(ptr);
  }
}

SEXP wrap_handle(int64_t id) {
  SEXP ptr = PROTECT(R_MakeExternalPtr(
      reinterpret_cast<void*>(static_cast<intptr_t>(id + 1)),
      R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ptr, finalize_handle, TRUE);
  UNPROTECT(1);
  return ptr;
}

const double* real_or_null(SEXP x) {
  return (Rf_isNull(x) || XLENGTH(x) == 0) ? nullptr : REAL(x);
}

// two-call string fetch: fn(buffer, buffer_len, &out_len)
template <typename F>
SEXP fetch_string(F fn) {
  int64_t need = 0;
  std::vector<char> buf(1 << 16);
  check(fn(buf.data(), static_cast<int64_t>(buf.size()), &need));
  if (need > static_cast<int64_t>(buf.size())) {
    buf.resize(static_cast<size_t>(need) + 1);
    check(fn(buf.data(), static_cast<int64_t>(buf.size()), &need));
  }
  return Rf_mkString(buf.data());
}

}  // namespace

extern "C" {

SEXP LGBTPU_R_GetLastError() {
  return Rf_mkString(LGBMTPU_GetLastError());
}

SEXP LGBTPU_R_HandleIsLive(SEXP ptr) {
  // readRDS deserializes external pointers as live-looking EXTPTRSXPs
  // with a NULL address; R-level is.null() cannot see that, so the R
  // side asks here before trusting a stored handle
  return Rf_ScalarLogical(TYPEOF(ptr) == EXTPTRSXP &&
                          R_ExternalPtrAddr(ptr) != nullptr);
}

/* ---------------- Dataset ---------------- */

SEXP LGBTPU_R_DatasetCreateFromMat(SEXP mat, SEXP nrow, SEXP ncol,
                                   SEXP label, SEXP params_json) {
  int64_t out = 0;
  check(LGBMTPU_DatasetCreateFromMat(
      REAL(mat), static_cast<int64_t>(Rf_asReal(nrow)),
      static_cast<int64_t>(Rf_asReal(ncol)), real_or_null(label),
      CHAR(STRING_ELT(params_json, 0)), &out));
  return wrap_handle(out);
}

SEXP LGBTPU_R_DatasetCreateFromFile(SEXP path, SEXP params_json) {
  int64_t out = 0;
  check(LGBMTPU_DatasetCreateFromFile(CHAR(STRING_ELT(path, 0)),
                                      CHAR(STRING_ELT(params_json, 0)),
                                      &out));
  return wrap_handle(out);
}

SEXP LGBTPU_R_DatasetCreateFromCSC(SEXP colptr, SEXP indices, SEXP data,
                                   SEXP ncol, SEXP nnz, SEXP nrow,
                                   SEXP label, SEXP params_json) {
  int64_t out = 0;
  check(LGBMTPU_DatasetCreateFromCSC(
      INTEGER(colptr), INTEGER(indices), REAL(data),
      static_cast<int64_t>(Rf_asReal(ncol)),
      static_cast<int64_t>(Rf_asReal(nnz)),
      static_cast<int64_t>(Rf_asReal(nrow)), real_or_null(label),
      CHAR(STRING_ELT(params_json, 0)), &out));
  return wrap_handle(out);
}

SEXP LGBTPU_R_DatasetCreateByReference(SEXP ref, SEXP num_total_row) {
  int64_t out = 0;
  check(LGBMTPU_DatasetCreateByReference(
      handle_of(ref), static_cast<int64_t>(Rf_asReal(num_total_row)),
      &out));
  return wrap_handle(out);
}

SEXP LGBTPU_R_DatasetGetSubset(SEXP ds, SEXP idx, SEXP params_json) {
  int64_t out = 0;
  check(LGBMTPU_DatasetGetSubset(handle_of(ds), INTEGER(idx),
                                 static_cast<int64_t>(XLENGTH(idx)),
                                 CHAR(STRING_ELT(params_json, 0)), &out));
  return wrap_handle(out);
}

SEXP LGBTPU_R_DatasetSetField(SEXP ds, SEXP field, SEXP vals) {
  check(LGBMTPU_DatasetSetField(handle_of(ds), CHAR(STRING_ELT(field, 0)),
                                real_or_null(vals),
                                static_cast<int64_t>(XLENGTH(vals))));
  return R_NilValue;
}

SEXP LGBTPU_R_DatasetGetField(SEXP ds, SEXP field) {
  int64_t n = 0;
  check(LGBMTPU_DatasetGetField(handle_of(ds), CHAR(STRING_ELT(field, 0)),
                                nullptr, &n));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, static_cast<R_xlen_t>(n)));
  if (n > 0) {
    check(LGBMTPU_DatasetGetField(handle_of(ds),
                                  CHAR(STRING_ELT(field, 0)), REAL(out),
                                  &n));
  }
  UNPROTECT(1);
  return out;
}

SEXP LGBTPU_R_DatasetGetNumData(SEXP ds) {
  int64_t out = 0;
  check(LGBMTPU_DatasetGetNumData(handle_of(ds), &out));
  return Rf_ScalarReal(static_cast<double>(out));
}

SEXP LGBTPU_R_DatasetGetNumFeature(SEXP ds) {
  int64_t out = 0;
  check(LGBMTPU_DatasetGetNumFeature(handle_of(ds), &out));
  return Rf_ScalarReal(static_cast<double>(out));
}

SEXP LGBTPU_R_DatasetSaveBinary(SEXP ds, SEXP path) {
  check(LGBMTPU_DatasetSaveBinary(handle_of(ds), CHAR(STRING_ELT(path, 0))));
  return R_NilValue;
}

SEXP LGBTPU_R_DatasetDumpText(SEXP ds, SEXP path) {
  check(LGBMTPU_DatasetDumpText(handle_of(ds), CHAR(STRING_ELT(path, 0))));
  return R_NilValue;
}

SEXP LGBTPU_R_DatasetSetFeatureNames(SEXP ds, SEXP names_json) {
  check(LGBMTPU_DatasetSetFeatureNames(handle_of(ds),
                                       CHAR(STRING_ELT(names_json, 0))));
  return R_NilValue;
}

SEXP LGBTPU_R_DatasetGetFeatureNames(SEXP ds) {
  int64_t h = handle_of(ds);
  return fetch_string([h](char* buf, int64_t len, int64_t* need) {
    return LGBMTPU_DatasetGetFeatureNames(h, buf, len, need);
  });
}

SEXP LGBTPU_R_DatasetUpdateParamChecking(SEXP old_json, SEXP new_json) {
  check(LGBMTPU_DatasetUpdateParamChecking(CHAR(STRING_ELT(old_json, 0)),
                                           CHAR(STRING_ELT(new_json, 0))));
  return R_NilValue;
}

/* ---------------- Booster ---------------- */

SEXP LGBTPU_R_BoosterCreate(SEXP train_ds, SEXP params_json) {
  int64_t out = 0;
  check(LGBMTPU_BoosterCreate(handle_of(train_ds),
                              CHAR(STRING_ELT(params_json, 0)), &out));
  return wrap_handle(out);
}

SEXP LGBTPU_R_BoosterCreateFromModelfile(SEXP path) {
  int64_t out = 0;
  check(LGBMTPU_BoosterCreateFromModelfile(CHAR(STRING_ELT(path, 0)),
                                           &out));
  return wrap_handle(out);
}

SEXP LGBTPU_R_BoosterLoadModelFromString(SEXP model_str) {
  int64_t out = 0;
  check(LGBMTPU_BoosterLoadModelFromString(CHAR(STRING_ELT(model_str, 0)),
                                           &out));
  return wrap_handle(out);
}

SEXP LGBTPU_R_BoosterAddValidData(SEXP bst, SEXP valid_ds) {
  check(LGBMTPU_BoosterAddValidData(handle_of(bst), handle_of(valid_ds)));
  return R_NilValue;
}

SEXP LGBTPU_R_BoosterResetTrainingData(SEXP bst, SEXP train_ds) {
  check(LGBMTPU_BoosterResetTrainingData(handle_of(bst),
                                         handle_of(train_ds)));
  return R_NilValue;
}

SEXP LGBTPU_R_BoosterResetParameter(SEXP bst, SEXP params_json) {
  check(LGBMTPU_BoosterResetParameter(handle_of(bst),
                                      CHAR(STRING_ELT(params_json, 0))));
  return R_NilValue;
}

SEXP LGBTPU_R_BoosterUpdateOneIter(SEXP bst) {
  int is_finished = 0;
  check(LGBMTPU_BoosterUpdateOneIter(handle_of(bst), &is_finished));
  return Rf_ScalarLogical(is_finished);
}

SEXP LGBTPU_R_BoosterUpdateOneIterCustom(SEXP bst, SEXP grad, SEXP hess) {
  int is_finished = 0;
  R_xlen_t n = XLENGTH(grad);
  std::vector<float> g(static_cast<size_t>(n)), h(static_cast<size_t>(n));
  const double* gd = REAL(grad);
  const double* hd = REAL(hess);
  for (R_xlen_t i = 0; i < n; ++i) {
    g[static_cast<size_t>(i)] = static_cast<float>(gd[i]);
    h[static_cast<size_t>(i)] = static_cast<float>(hd[i]);
  }
  check(LGBMTPU_BoosterUpdateOneIterCustom(handle_of(bst), g.data(),
                                           h.data(),
                                           static_cast<int64_t>(n),
                                           &is_finished));
  return Rf_ScalarLogical(is_finished);
}

SEXP LGBTPU_R_BoosterMerge(SEXP bst, SEXP other) {
  check(LGBMTPU_BoosterMerge(handle_of(bst), handle_of(other)));
  return R_NilValue;
}

SEXP LGBTPU_R_BoosterRollbackOneIter(SEXP bst) {
  check(LGBMTPU_BoosterRollbackOneIter(handle_of(bst)));
  return R_NilValue;
}

SEXP LGBTPU_R_BoosterGetCurrentIteration(SEXP bst) {
  int out = 0;
  check(LGBMTPU_BoosterGetCurrentIteration(handle_of(bst), &out));
  return Rf_ScalarInteger(out);
}

SEXP LGBTPU_R_BoosterGetNumClasses(SEXP bst) {
  int out = 0;
  check(LGBMTPU_BoosterNumClasses(handle_of(bst), &out));
  return Rf_ScalarInteger(out);
}

SEXP LGBTPU_R_BoosterGetNumFeature(SEXP bst) {
  int out = 0;
  check(LGBMTPU_BoosterGetNumFeature(handle_of(bst), &out));
  return Rf_ScalarInteger(out);
}

SEXP LGBTPU_R_BoosterNumTrees(SEXP bst) {
  int out = 0;
  check(LGBMTPU_BoosterNumTrees(handle_of(bst), &out));
  return Rf_ScalarInteger(out);
}

SEXP LGBTPU_R_BoosterNumModelPerIteration(SEXP bst) {
  int out = 0;
  check(LGBMTPU_BoosterNumModelPerIteration(handle_of(bst), &out));
  return Rf_ScalarInteger(out);
}

SEXP LGBTPU_R_BoosterGetFeatureNames(SEXP bst) {
  int64_t h = handle_of(bst);
  return fetch_string([h](char* buf, int64_t len, int64_t* need) {
    return LGBMTPU_BoosterGetFeatureNames(h, buf, len, need);
  });
}

SEXP LGBTPU_R_BoosterGetEvalNames(SEXP bst) {
  int64_t h = handle_of(bst);
  return fetch_string([h](char* buf, int64_t len, int64_t* need) {
    return LGBMTPU_BoosterGetEvalNames(h, buf, len, need);
  });
}

SEXP LGBTPU_R_BoosterGetEval(SEXP bst, SEXP data_idx) {
  int n_metrics = 0;
  check(LGBMTPU_BoosterGetEvalCounts(handle_of(bst), &n_metrics));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, n_metrics));
  int64_t n = n_metrics;
  if (n_metrics > 0) {
    check(LGBMTPU_BoosterGetEval(handle_of(bst), Rf_asInteger(data_idx),
                                 REAL(out), &n));
  }
  UNPROTECT(1);
  return out;
}

SEXP LGBTPU_R_BoosterPredictForMat(SEXP bst, SEXP mat, SEXP nrow,
                                   SEXP ncol, SEXP predict_type,
                                   SEXP start_iteration,
                                   SEXP num_iteration) {
  int64_t h = handle_of(bst);
  int64_t nr = static_cast<int64_t>(Rf_asReal(nrow));
  int64_t len = 0;
  check(LGBMTPU_BoosterCalcNumPredict(h, nr, Rf_asInteger(predict_type),
                                      Rf_asInteger(start_iteration),
                                      Rf_asInteger(num_iteration), &len));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, static_cast<R_xlen_t>(len)));
  check(LGBMTPU_BoosterPredictForMat2(
      h, REAL(mat), nr, static_cast<int64_t>(Rf_asReal(ncol)),
      Rf_asInteger(predict_type), Rf_asInteger(start_iteration),
      Rf_asInteger(num_iteration), REAL(out), &len));
  UNPROTECT(1);
  return out;
}

SEXP LGBTPU_R_BoosterPredictForCSC(SEXP bst, SEXP colptr, SEXP indices,
                                   SEXP data, SEXP nrow,
                                   SEXP predict_type,
                                   SEXP start_iteration,
                                   SEXP num_iteration) {
  int64_t h = handle_of(bst);
  int64_t nr = static_cast<int64_t>(Rf_asReal(nrow));
  int64_t len = 0;
  check(LGBMTPU_BoosterCalcNumPredict(h, nr, Rf_asInteger(predict_type),
                                      Rf_asInteger(start_iteration),
                                      Rf_asInteger(num_iteration), &len));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, static_cast<R_xlen_t>(len)));
  check(LGBMTPU_BoosterPredictForCSC(
      h, INTEGER(colptr), INTEGER(indices), REAL(data),
      static_cast<int64_t>(XLENGTH(colptr)),
      static_cast<int64_t>(XLENGTH(data)), nr,
      Rf_asInteger(predict_type), Rf_asInteger(start_iteration),
      Rf_asInteger(num_iteration), REAL(out), &len));
  UNPROTECT(1);
  return out;
}

SEXP LGBTPU_R_BoosterPredictForFile(SEXP bst, SEXP data_path,
                                    SEXP has_header, SEXP predict_type,
                                    SEXP start_iteration,
                                    SEXP num_iteration, SEXP result_path) {
  check(LGBMTPU_BoosterPredictForFile(
      handle_of(bst), CHAR(STRING_ELT(data_path, 0)),
      Rf_asLogical(has_header), Rf_asInteger(predict_type),
      Rf_asInteger(start_iteration), Rf_asInteger(num_iteration),
      CHAR(STRING_ELT(result_path, 0))));
  return R_NilValue;
}

SEXP LGBTPU_R_BoosterSaveModel(SEXP bst, SEXP path) {
  check(LGBMTPU_BoosterSaveModel(handle_of(bst),
                                 CHAR(STRING_ELT(path, 0))));
  return R_NilValue;
}

SEXP LGBTPU_R_BoosterSaveModelToString(SEXP bst) {
  int64_t h = handle_of(bst);
  // out_len is IN/OUT here (capacity in, required length out —
  // capi.cpp:368), unlike the (buffer, buffer_len, out_len) getters
  // fetch_string serves
  std::vector<char> buf(1 << 20);
  int64_t need = static_cast<int64_t>(buf.size());
  check(LGBMTPU_BoosterSaveModelToString(h, buf.data(), &need));
  if (need > static_cast<int64_t>(buf.size())) {
    buf.resize(static_cast<size_t>(need) + 1);
    need = static_cast<int64_t>(buf.size());
    check(LGBMTPU_BoosterSaveModelToString(h, buf.data(), &need));
  }
  return Rf_mkString(buf.data());
}

SEXP LGBTPU_R_BoosterDumpModel(SEXP bst, SEXP num_iteration) {
  int64_t h = handle_of(bst);
  int ni = Rf_asInteger(num_iteration);
  return fetch_string([h, ni](char* buf, int64_t len, int64_t* need) {
    return LGBMTPU_BoosterDumpModel(h, ni, buf, len, need);
  });
}

SEXP LGBTPU_R_BoosterFeatureImportance(SEXP bst, SEXP importance_type) {
  int64_t h = handle_of(bst);
  int nf = 0;
  check(LGBMTPU_BoosterGetNumFeature(h, &nf));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, nf));
  int64_t n = nf;
  check(LGBMTPU_BoosterFeatureImportance(h, Rf_asInteger(importance_type),
                                         REAL(out), &n));
  UNPROTECT(1);
  return out;
}

SEXP LGBTPU_R_BoosterGetLeafValue(SEXP bst, SEXP tree_idx, SEXP leaf_idx) {
  double out = 0.0;
  check(LGBMTPU_BoosterGetLeafValue(handle_of(bst), Rf_asInteger(tree_idx),
                                    Rf_asInteger(leaf_idx), &out));
  return Rf_ScalarReal(out);
}

SEXP LGBTPU_R_BoosterSetLeafValue(SEXP bst, SEXP tree_idx, SEXP leaf_idx,
                                  SEXP value) {
  check(LGBMTPU_BoosterSetLeafValue(handle_of(bst), Rf_asInteger(tree_idx),
                                    Rf_asInteger(leaf_idx),
                                    Rf_asReal(value)));
  return R_NilValue;
}

SEXP LGBTPU_R_BoosterGetLowerBoundValue(SEXP bst) {
  double out = 0.0;
  check(LGBMTPU_BoosterGetLowerBoundValue(handle_of(bst), &out));
  return Rf_ScalarReal(out);
}

SEXP LGBTPU_R_BoosterGetUpperBoundValue(SEXP bst) {
  double out = 0.0;
  check(LGBMTPU_BoosterGetUpperBoundValue(handle_of(bst), &out));
  return Rf_ScalarReal(out);
}

SEXP LGBTPU_R_BoosterGetLoadedParam(SEXP bst) {
  int64_t h = handle_of(bst);
  return fetch_string([h](char* buf, int64_t len, int64_t* need) {
    return LGBMTPU_BoosterGetLoadedParam(h, buf, len, need);
  });
}


SEXP LGBTPU_R_DumpParamAliases() {
  return fetch_string([](char* buf, int64_t len, int64_t* need) {
    return LGBMTPU_DumpParamAliases(buf, len, need);
  });
}

SEXP LGBTPU_R_SetMaxThreads(SEXP n) {
  check(LGBMTPU_SetMaxThreads(Rf_asInteger(n)));
  return R_NilValue;
}

SEXP LGBTPU_R_GetMaxThreads() {
  int out = -1;
  check(LGBMTPU_GetMaxThreads(&out));
  return Rf_ScalarInteger(out);
}

/* ---------------- registration ---------------- */

#define CALLDEF(name, n) {#name, (DL_FUNC)&name, n}

static const R_CallMethodDef kCallMethods[] = {
    CALLDEF(LGBTPU_R_GetLastError, 0),
    CALLDEF(LGBTPU_R_HandleIsLive, 1),
    CALLDEF(LGBTPU_R_DatasetCreateFromMat, 5),
    CALLDEF(LGBTPU_R_DatasetCreateFromFile, 2),
    CALLDEF(LGBTPU_R_DatasetCreateFromCSC, 8),
    CALLDEF(LGBTPU_R_DatasetCreateByReference, 2),
    CALLDEF(LGBTPU_R_DatasetGetSubset, 3),
    CALLDEF(LGBTPU_R_DatasetSetField, 3),
    CALLDEF(LGBTPU_R_DatasetGetField, 2),
    CALLDEF(LGBTPU_R_DatasetGetNumData, 1),
    CALLDEF(LGBTPU_R_DatasetGetNumFeature, 1),
    CALLDEF(LGBTPU_R_DatasetSaveBinary, 2),
    CALLDEF(LGBTPU_R_DatasetDumpText, 2),
    CALLDEF(LGBTPU_R_DatasetSetFeatureNames, 2),
    CALLDEF(LGBTPU_R_DatasetGetFeatureNames, 1),
    CALLDEF(LGBTPU_R_DatasetUpdateParamChecking, 2),
    CALLDEF(LGBTPU_R_BoosterCreate, 2),
    CALLDEF(LGBTPU_R_BoosterCreateFromModelfile, 1),
    CALLDEF(LGBTPU_R_BoosterLoadModelFromString, 1),
    CALLDEF(LGBTPU_R_BoosterAddValidData, 2),
    CALLDEF(LGBTPU_R_BoosterResetTrainingData, 2),
    CALLDEF(LGBTPU_R_BoosterResetParameter, 2),
    CALLDEF(LGBTPU_R_BoosterUpdateOneIter, 1),
    CALLDEF(LGBTPU_R_BoosterUpdateOneIterCustom, 3),
    CALLDEF(LGBTPU_R_BoosterMerge, 2),
    CALLDEF(LGBTPU_R_BoosterRollbackOneIter, 1),
    CALLDEF(LGBTPU_R_BoosterGetCurrentIteration, 1),
    CALLDEF(LGBTPU_R_BoosterGetNumClasses, 1),
    CALLDEF(LGBTPU_R_BoosterGetNumFeature, 1),
    CALLDEF(LGBTPU_R_BoosterNumTrees, 1),
    CALLDEF(LGBTPU_R_BoosterNumModelPerIteration, 1),
    CALLDEF(LGBTPU_R_BoosterGetFeatureNames, 1),
    CALLDEF(LGBTPU_R_BoosterGetEvalNames, 1),
    CALLDEF(LGBTPU_R_BoosterGetEval, 2),
    CALLDEF(LGBTPU_R_BoosterPredictForMat, 7),
    CALLDEF(LGBTPU_R_BoosterPredictForCSC, 8),
    CALLDEF(LGBTPU_R_BoosterPredictForFile, 7),
    CALLDEF(LGBTPU_R_BoosterSaveModel, 2),
    CALLDEF(LGBTPU_R_BoosterSaveModelToString, 1),
    CALLDEF(LGBTPU_R_BoosterDumpModel, 2),
    CALLDEF(LGBTPU_R_BoosterFeatureImportance, 2),
    CALLDEF(LGBTPU_R_BoosterGetLeafValue, 3),
    CALLDEF(LGBTPU_R_BoosterSetLeafValue, 4),
    CALLDEF(LGBTPU_R_BoosterGetLowerBoundValue, 1),
    CALLDEF(LGBTPU_R_BoosterGetUpperBoundValue, 1),
    CALLDEF(LGBTPU_R_BoosterGetLoadedParam, 1),
    CALLDEF(LGBTPU_R_DumpParamAliases, 0),
    CALLDEF(LGBTPU_R_SetMaxThreads, 1),
    CALLDEF(LGBTPU_R_GetMaxThreads, 0),
    {NULL, NULL, 0}};

void R_init_lightgbm_tpu(DllInfo* dll) {
  R_registerRoutines(dll, NULL, kCallMethods, NULL, NULL);
  R_useDynamicSymbols(dll, FALSE);
}

}  // extern "C"
