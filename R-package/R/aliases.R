# aliases — reference R-package/R/aliases.R counterpart: parameter
# alias resolution for the handful of parameters the R layer itself
# reads (early stopping, verbosity, metric).  The full 314-alias table
# lives ABI-side (config.py; LGBMTPU_DumpParamAliases mirrors
# c_api.h:100) and resolves every parameter passed through params; this
# file only normalizes the R-visible ones, querying the ABI's table so
# the two layers can never drift.

# cached alias map: canonical name -> character vector of aliases
.lgb_alias_env <- new.env(parent = emptyenv())

.lgb_param_aliases <- function() {
  if (is.null(.lgb_alias_env$map)) {
    txt <- .Call(LGBTPU_R_DumpParamAliases)
    .lgb_alias_env$map <- .lgb_json_parse(txt)
  }
  .lgb_alias_env$map
}

# first-wins alias resolution for one canonical parameter: returns the
# value found under the canonical name or any of its aliases, or NULL
.lgb_param_get <- function(params, canonical) {
  if (!is.null(params[[canonical]])) {
    return(params[[canonical]])
  }
  aliases <- .lgb_param_aliases()[[canonical]]
  for (a in aliases) {
    if (!is.null(params[[a]])) {
      return(params[[a]])
    }
  }
  NULL
}

# normalize the R-read parameters onto canonical keys (params passed to
# the ABI keep their original spelling; the ABI resolves them again)
.lgb_standardize_params <- function(params) {
  for (canonical in c("early_stopping_round", "metric", "verbosity",
                      "num_iterations")) {
    v <- .lgb_param_get(params, canonical)
    if (!is.null(v)) {
      params[[canonical]] <- v
    }
  }
  params
}
