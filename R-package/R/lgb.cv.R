# lgb.cv — k-fold cross-validation over lgb.train, mirroring the
# reference's R-package/R/lgb.cv.R surface (folds via lgb.slice.Dataset
# subsets sharing the parent's bin mappers, per-iteration mean/sd
# aggregation, optional early stopping on the aggregated metric).

#' Cross-validate a GBDT model
#'
#' @param params named list of parameters
#' @param data an lgb.Dataset (constructed from the full table)
#' @param nrounds boosting iterations per fold
#' @param nfold number of folds
#' @param label unused when data already carries its label
#' @param stratified stratify folds by label (classification)
#' @param folds optional explicit list of validation index vectors
#'   (1-based); overrides nfold/stratified
#' @param early_stopping_rounds stop when the aggregated first metric
#'   stops improving
#' @param eval_freq evaluate every k-th iteration
#' @param verbose <= 0 silences progress
#' @param ... additional parameters merged into params
#' @return list with class "lgb.CVBooster": boosters (per fold),
#'   record_evals ($<metric>$mean / $sd per evaluated iteration),
#'   best_iter, best_score
#' @export
lgb.cv <- function(params = list(), data, nrounds = 100L, nfold = 5L,
                   label = NULL, stratified = TRUE, folds = NULL,
                   early_stopping_rounds = NULL, eval_freq = 1L,
                   verbose = 1L, ...) {
  stopifnot(inherits(data, "lgb.Dataset"))
  params <- c(params, list(...))
  lgb.Dataset.construct(data)
  n <- dim(data)[[1L]]
  if (is.null(folds)) {
    y <- get_field(data, "label")
    folds <- .lgb_make_folds(n, nfold, if (stratified) y else NULL)
  }
  boosters <- vector("list", length(folds))
  histories <- vector("list", length(folds))
  for (k in seq_along(folds)) {
    test_idx <- folds[[k]]
    train_idx <- setdiff(seq_len(n), test_idx)
    dtrain <- lgb.slice.Dataset(data, train_idx)
    dtest <- lgb.slice.Dataset(data, test_idx)
    bst <- lgb.train(params, dtrain, nrounds = nrounds,
                     valids = list(valid = dtest), record = TRUE,
                     verbose = 0L, eval_freq = eval_freq)
    boosters[[k]] <- bst
    histories[[k]] <- bst$record_evals[["valid"]]
  }
  metric_names <- names(histories[[1L]])
  record_evals <- list()
  for (mn in metric_names) {
    vals <- do.call(cbind, lapply(histories, function(h) h[[mn]]))
    record_evals[[mn]] <- list(mean = rowMeans(vals),
                               sd = apply(vals, 1L, stats::sd))
    if (verbose > 0L) {
      last <- length(record_evals[[mn]]$mean)
      cat(sprintf("cv %s: %.6g +/- %.6g (final)\n", mn,
                  record_evals[[mn]]$mean[[last]],
                  record_evals[[mn]]$sd[[last]]))
    }
  }
  best_iter <- -1L
  best_score <- NA_real_
  if (length(metric_names) > 0L) {
    m1 <- metric_names[[1L]]
    curve <- record_evals[[m1]]$mean
    higher <- grepl("auc|ndcg|map|average_precision", m1)
    best_pos <- if (higher) which.max(curve) else which.min(curve)
    # lgb.train evaluates at multiples of eval_freq AND at nrounds, so
    # the history position -> iteration map must include that final
    # extra entry (eval_freq=3, nrounds=10 evaluates at 3,6,9,10)
    eval_iters <- unique(c(seq.int(max(eval_freq, 1L), nrounds,
                                   by = max(eval_freq, 1L)), nrounds))
    best_iter <- eval_iters[[best_pos]]
    best_score <- curve[[best_pos]]
    # fold boosters run to nrounds; the aggregated best iteration is
    # the cv result (the reference's cv early stop reduces to the same
    # reported best_iter)
  }
  structure(list(boosters = boosters, record_evals = record_evals,
                 best_iter = as.integer(best_iter),
                 best_score = best_score, folds = folds),
            class = "lgb.CVBooster")
}

.lgb_make_folds <- function(n, nfold, y = NULL) {
  if (!is.null(y) && length(unique(y)) <= max(32L, nfold)) {
    # stratified: deal each class round-robin across folds
    fold_of <- integer(n)
    for (cls in unique(y)) {
      idx <- sample(which(y == cls))
      fold_of[idx] <- rep_len(seq_len(nfold), length(idx))
    }
  } else {
    fold_of <- rep_len(seq_len(nfold), n)[sample.int(n)]
  }
  lapply(seq_len(nfold), function(k) which(fold_of == k))
}

#' @export
print.lgb.CVBooster <- function(x, ...) {
  cat(sprintf("<lgb.CVBooster (lightgbm.tpu): %d folds, best_iter %d>\n",
              length(x$boosters), x$best_iter))
  invisible(x)
}
