# lgb.train — the R training entry point, mirroring the reference's
# R-package/R/lgb.train.R surface over this framework's engine
# (engine.py train()): iteration loop via BoosterUpdateOneIter, eval
# recording, early stopping on validation metrics.

#' Train a GBDT model
#'
#' @param params named list of parameters (objective, num_leaves,
#'   learning_rate, ...; aliases resolve ABI-side exactly as in
#'   config.py)
#' @param data training lgb.Dataset
#' @param nrounds number of boosting iterations
#' @param valids named list of validation lgb.Datasets
#' @param obj optional custom objective: function(preds, dtrain) ->
#'   list(grad =, hess =)
#' @param record keep per-iteration eval results in
#'   booster$record_evals
#' @param verbose <= 0 silences per-iteration eval printing
#' @param eval_freq print/record every k-th iteration
#' @param early_stopping_rounds stop when no validation metric improves
#'   for this many rounds; sets best_iter on the booster
#' @param first_metric_only early-stop on the first metric family only
#' @param reset_parameter named list of per-iteration parameter
#'   schedules (vector or function(iter, total)), applied through
#'   BoosterResetParameter each round (reference reset_parameter
#'   callback)
#' @param init_model a Booster or model file to continue training from
#' @param callbacks list of functions(env) called after each iteration;
#'   env carries booster/iteration/nrounds/eval_list
#' @param reset_data unused compatibility argument
#' @param ... additional parameters merged into params
#' @export
lgb.train <- function(params = list(), data, nrounds = 100L,
                      valids = list(), obj = NULL, record = TRUE,
                      verbose = 1L, eval_freq = 1L,
                      early_stopping_rounds = NULL,
                      first_metric_only = FALSE, init_model = NULL,
                      callbacks = list(), reset_parameter = NULL,
                      reset_data = FALSE, ...) {
  stopifnot(inherits(data, "lgb.Dataset"))
  params <- c(params, list(...))
  if (!is.null(obj)) {
    params[["objective"]] <- "none"
  }
  booster <- lgb.Booster(data, params)
  if (!is.null(init_model)) {
    base <- if (inherits(init_model, "lgb.Booster")) {
      lgb.make_serializable(init_model)$raw
    } else {
      paste(readLines(init_model), collapse = "\n")
    }
    other <- .Call(LGBTPU_R_BoosterLoadModelFromString, base)
    # merge the previous model's trees in front, the ABI-side
    # continuation path (BoosterMerge is the reference's model-merge)
    .Call(LGBTPU_R_BoosterMerge, booster$handle, other)
  }
  if (length(valids) > 0L) {
    if (is.null(names(valids)) || any(!nzchar(names(valids)))) {
      stop("lgb.train: valids must be a NAMED list of lgb.Dataset")
    }
    for (vn in names(valids)) {
      v <- valids[[vn]]
      stopifnot(inherits(v, "lgb.Dataset"))
      if (is.null(v$reference)) v$reference <- data
      lgb.Dataset.construct(v)
      .Call(LGBTPU_R_BoosterAddValidData, booster$handle, v$handle)
    }
    booster$valid_sets <- valids
    booster$valid_names <- names(valids)
  }

  params <- .lgb_standardize_params(params)
  if (is.null(early_stopping_rounds) &&
      !is.null(params[["early_stopping_round"]])) {
    early_stopping_rounds <- as.integer(params[["early_stopping_round"]])
  }
  cbs <- .lgb_build_callbacks(
    verbose = verbose, eval_freq = eval_freq, record = record,
    early_stopping_rounds = early_stopping_rounds,
    first_metric_only = first_metric_only,
    reset_parameter = reset_parameter,
    user_callbacks = callbacks)
  pre <- Filter(function(cb) isTRUE(attr(cb, "pre_iteration")), cbs)
  post <- Filter(function(cb) !isTRUE(attr(cb, "pre_iteration")), cbs)
  eval_names <- NULL
  booster$stop_training <- FALSE

  for (i in seq_len(nrounds)) {
    for (cb in pre) {
      cb(list(booster = booster, iteration = i, begin_iteration = 1L,
              end_iteration = nrounds, eval_list = list(),
              eval_parts = list(), nrounds = nrounds))
    }
    if (is.null(obj)) {
      .Call(LGBTPU_R_BoosterUpdateOneIter, booster$handle)
    } else {
      preds <- predict(booster, .lgb_train_matrix(data), type = "raw")
      gh <- obj(preds, data)
      .Call(LGBTPU_R_BoosterUpdateOneIterCustom, booster$handle,
            as.numeric(gh$grad), as.numeric(gh$hess))
    }

    eval_list <- list()
    eval_parts <- list()     # (valid_name, metric_name) per entry
    if (length(booster$valid_names) > 0L &&
        (i %% max(eval_freq, 1L) == 0L || i == nrounds)) {
      if (is.null(eval_names)) {
        eval_names <- .lgb_split_names(
          .Call(LGBTPU_R_BoosterGetEvalNames, booster$handle))
      }
      for (vi in seq_along(booster$valid_names)) {
        vals <- .Call(LGBTPU_R_BoosterGetEval, booster$handle,
                      as.integer(vi))
        vn <- booster$valid_names[[vi]]
        for (mi in seq_along(vals)) {
          mn <- if (mi <= length(eval_names)) eval_names[[mi]] else
            paste0("metric", mi)
          eval_list[[paste(vn, mn, sep = "-")]] <- vals[[mi]]
          eval_parts[[length(eval_parts) + 1L]] <- list(vn, mn)
        }
      }
    }
    env <- list(booster = booster, iteration = i, begin_iteration = 1L,
                end_iteration = nrounds, eval_list = eval_list,
                eval_parts = eval_parts, nrounds = nrounds)
    for (cb in post) {
      cb(env)
    }
    if (isTRUE(booster$stop_training)) {
      break
    }
  }
  booster
}

# custom objectives need raw predictions on the training matrix; keep a
# handle to it (only for obj != NULL, where free_raw_data must be FALSE)
.lgb_train_matrix <- function(dataset) {
  if (is.null(dataset$raw_data)) {
    stop("custom objectives need the raw training data: create the ",
         "Dataset with free_raw_data = FALSE")
  }
  dataset$raw_data
}
