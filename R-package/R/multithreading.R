# multithreading — reference R-package/R/multithreading.R counterpart
# over the ABI's thread controls (LGBMTPU_SetMaxThreads /
# LGBMTPU_GetMaxThreads, the c_api.h:1603-1610 pair).  Device compute is
# scheduled by XLA; the budget governs the HOST side (parsers, binning).

#' Set the maximum number of host threads the library may use
#'
#' @param num_threads requested thread count; <= 0 resets to the default
#' @export
setLGBMthreads <- function(num_threads) {
  .Call(LGBTPU_R_SetMaxThreads, as.integer(num_threads))
  invisible(NULL)
}

#' Read the maximum number of host threads the library may use
#'
#' @return the configured budget, or -1 when unlimited/default
#' @export
getLGBMthreads <- function() {
  .Call(LGBTPU_R_GetMaxThreads)
}
