# Internal helpers for the lightgbm.tpu R package.
#
# The LGBMTPU_* ABI takes parameters as a JSON object (native/capi.h),
# not the reference's "key=value key2=value2" strings, so the package
# carries its own tiny JSON writer instead of depending on jsonlite.

.lgb_json_escape <- function(x) {
  x <- gsub("\\\\", "\\\\\\\\", x)
  x <- gsub("\"", "\\\\\"", x)
  x <- gsub("\n", "\\\\n", x)
  x
}

.lgb_json_scalar <- function(v) {
  if (is.logical(v)) {
    return(ifelse(v, "true", "false"))
  }
  if (is.numeric(v)) {
    if (is.finite(v) && v == floor(v) && abs(v) < 2^53) {
      return(sprintf("%.0f", v))
    }
    return(format(v, digits = 17, scientific = TRUE))
  }
  paste0("\"", .lgb_json_escape(as.character(v)), "\"")
}

.lgb_json_value <- function(v) {
  if (length(v) == 1L && is.null(names(v))) {
    return(.lgb_json_scalar(v))
  }
  paste0("[", paste(vapply(v, .lgb_json_scalar, character(1L)),
                    collapse = ","), "]")
}

# named list -> one-line JSON object understood by the ABI
.lgb_params_json <- function(params) {
  if (is.null(params) || length(params) == 0L) {
    return("{}")
  }
  stopifnot(!is.null(names(params)), all(nzchar(names(params))))
  fields <- vapply(seq_along(params), function(i) {
    paste0("\"", .lgb_json_escape(names(params)[[i]]), "\":",
           .lgb_json_value(params[[i]]))
  }, character(1L))
  paste0("{", paste(fields, collapse = ","), "}")
}

# JSON array of strings (feature names etc.)
.lgb_strings_json <- function(x) {
  paste0("[", paste(vapply(x, function(s) {
    paste0("\"", .lgb_json_escape(s), "\"")
  }, character(1L)), collapse = ","), "]")
}

# Merge categorical_feature / colnames information into a params list the
# way the reference resolves them before hitting the C API.
.lgb_resolve_categorical <- function(params, categorical_feature,
                                     colnames_) {
  if (is.null(categorical_feature) || length(categorical_feature) == 0L) {
    return(params)
  }
  if (is.character(categorical_feature)) {
    if (is.null(colnames_)) {
      stop("categorical_feature given by name but the data has no colnames")
    }
    idx <- match(categorical_feature, colnames_)
    if (anyNA(idx)) {
      stop("categorical_feature not found in colnames: ",
           paste(categorical_feature[is.na(idx)], collapse = ", "))
    }
  } else {
    idx <- as.integer(categorical_feature)
  }
  # ABI side is 0-based like the reference C API
  params[["categorical_feature"]] <- as.integer(idx - 1L)
  params
}

.lgb_check_handle <- function(x, what) {
  if (!inherits(x, "externalptr")) {
    stop(what, ": handle is not constructed (call lgb.Dataset.construct ",
         "or train first)")
  }
  x
}

# split the newline-joined name buffers the ABI's string getters produce
# (GetFeatureNames / GetEvalNames, mirroring c_api.h:826,845 semantics)
.lgb_split_names <- function(s) {
  if (is.null(s) || !nzchar(s)) {
    return(character(0L))
  }
  strsplit(s, "\n", fixed = TRUE)[[1L]]
}
