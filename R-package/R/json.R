# Minimal recursive-descent JSON reader in base R — enough for the
# booster's DumpModel output (objects, arrays, numbers, strings, bools,
# null).  Exists so the package needs no jsonlite dependency, the same
# trade the reference makes by parsing model JSON with data.table tools.
#
# Objects -> named lists, arrays -> unnamed lists, null -> NULL.

.lgb_json_parse <- function(txt) {
  chars <- strsplit(txt, "", fixed = TRUE)[[1L]]
  pos <- 1L
  n <- length(chars)

  peek <- function() if (pos <= n) chars[[pos]] else ""
  advance <- function() pos <<- pos + 1L
  skip_ws <- function() {
    while (pos <= n && chars[[pos]] %in% c(" ", "\t", "\n", "\r")) {
      advance()
    }
  }
  expect <- function(ch) {
    if (peek() != ch) {
      stop(sprintf("JSON parse error at %d: expected '%s', got '%s'",
                   pos, ch, peek()))
    }
    advance()
  }

  parse_string <- function() {
    expect("\"")
    out <- character(0L)
    while (pos <= n && chars[[pos]] != "\"") {
      ch <- chars[[pos]]
      if (ch == "\\") {
        advance()
        esc <- chars[[pos]]
        ch <- switch(esc, n = "\n", t = "\t", r = "\r", b = "\b",
                     f = "\f", "/" = "/", "\\" = "\\", "\"" = "\"",
                     u = {
                       code <- paste(chars[(pos + 1L):(pos + 4L)],
                                     collapse = "")
                       pos <<- pos + 4L
                       intToUtf8(strtoi(code, 16L))
                     },
                     esc)
      }
      out[[length(out) + 1L]] <- ch
      advance()
    }
    expect("\"")
    paste(out, collapse = "")
  }

  parse_number <- function() {
    start <- pos
    while (pos <= n &&
           (chars[[pos]] %in% c("-", "+", ".", "e", "E") ||
            grepl("[0-9]", chars[[pos]]))) {
      advance()
    }
    as.numeric(paste(chars[start:(pos - 1L)], collapse = ""))
  }

  parse_value <- function() {
    skip_ws()
    ch <- peek()
    if (ch == "{") return(parse_object())
    if (ch == "[") return(parse_array())
    if (ch == "\"") return(parse_string())
    if (ch == "t") { pos <<- pos + 4L; return(TRUE) }
    if (ch == "f") { pos <<- pos + 5L; return(FALSE) }
    if (ch == "n") { pos <<- pos + 4L; return(NULL) }
    parse_number()
  }

  parse_object <- function() {
    expect("{")
    out <- list()
    skip_ws()
    if (peek() == "}") { advance(); return(out) }
    repeat {
      skip_ws()
      key <- parse_string()
      skip_ws()
      expect(":")
      val <- parse_value()
      out[[key]] <- val
      skip_ws()
      if (peek() == ",") { advance() } else break
    }
    skip_ws()
    expect("}")
    out
  }

  parse_array <- function() {
    expect("[")
    out <- list()
    skip_ws()
    if (peek() == "]") { advance(); return(out) }
    repeat {
      out[[length(out) + 1L]] <- parse_value()
      skip_ws()
      if (peek() == ",") { advance() } else break
    }
    skip_ws()
    expect("]")
    out
  }

  val <- parse_value()
  skip_ws()
  val
}
