# lgb.convert_with_rules — reference
# R-package/R/lgb.convert_with_rules.R counterpart: deterministic
# factor/character -> numeric coding with reusable rules so train
# and test share one coding.

#' Map factor/character columns to numeric codes with reusable rules
#'
#' @param data a data.frame
#' @param rules optional rules list from a previous call (applied to new
#'   data so train and test share the same coding)
#' @return list(data = converted data.frame, rules = rules)
#' @export
lgb.convert_with_rules <- function(data, rules = NULL) {
  stopifnot(is.data.frame(data))
  out <- data
  new_rules <- rules %||% list()
  for (col in names(out)) {
    v <- out[[col]]
    if (is.factor(v) || is.character(v)) {
      v <- as.character(v)
      if (is.null(new_rules[[col]])) {
        lv <- sort(unique(v[!is.na(v)]))
        new_rules[[col]] <- stats::setNames(seq_along(lv), lv)
      }
      codes <- unname(new_rules[[col]][v])
      out[[col]] <- as.numeric(codes)
    } else if (is.logical(v)) {
      out[[col]] <- as.numeric(v)
    }
  }
  list(data = out, rules = new_rules)
}

