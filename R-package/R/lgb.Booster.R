# lgb.Booster — R front end of the framework's Booster (basic.py),
# a thin client of the LGBMTPU_Booster* ABI.  Environment-backed with
# S3 methods, covering the reference surface: predict, save/load/dump,
# eval tracking, serialization keep-alive.

.lgb_booster_new <- function(handle, train_set = NULL, params = list()) {
  env <- new.env(parent = emptyenv())
  env$handle <- handle
  env$train_set <- train_set
  env$params <- params
  env$valid_sets <- list()
  env$valid_names <- character(0L)
  env$record_evals <- list()
  env$best_iter <- -1L
  env$best_score <- NA_real_
  env$raw <- NULL            # serialized model kept by lgb.make_serializable
  class(env) <- "lgb.Booster"
  env
}

#' Create a Booster on a training Dataset
#' @param train_set an lgb.Dataset
#' @param params named list of training parameters
#' @export
lgb.Booster <- function(train_set, params = list()) {
  lgb.Dataset.construct(train_set)
  h <- .Call(LGBTPU_R_BoosterCreate, train_set$handle,
             .lgb_params_json(params))
  .lgb_booster_new(h, train_set, params)
}

# a handle read back by readRDS is an external pointer whose native
# address is NULL — R-level is.null() cannot detect that, the glue can
.lgb_handle_live <- function(h) {
  !is.null(h) && .Call(LGBTPU_R_HandleIsLive, h)
}

.lgb_booster_handle <- function(booster) {
  if (!.lgb_handle_live(booster$handle)) {
    lgb.restore_handle(booster)
  }
  booster$handle
}

#' Save a Booster to the interoperable text format
#' @param booster an lgb.Booster
#' @param filename output path
#' @param num_iteration unused (full model is saved)
#' @export
lgb.save <- function(booster, filename, num_iteration = NULL) {
  stopifnot(inherits(booster, "lgb.Booster"))
  .Call(LGBTPU_R_BoosterSaveModel, .lgb_booster_handle(booster), filename)
  invisible(booster)
}

#' Load a Booster from a text model file or model string
#' @param filename path to a saved model
#' @param model_str a model string (alternative to filename)
#' @export
lgb.load <- function(filename = NULL, model_str = NULL) {
  if (!is.null(filename)) {
    h <- .Call(LGBTPU_R_BoosterCreateFromModelfile, filename)
  } else if (!is.null(model_str)) {
    h <- .Call(LGBTPU_R_BoosterLoadModelFromString, model_str)
  } else {
    stop("lgb.load: give filename or model_str")
  }
  .lgb_booster_new(h)
}

#' Dump a Booster to JSON
#' @param booster an lgb.Booster
#' @param num_iteration how many iterations to include (-1 = all)
#' @export
lgb.dump <- function(booster, num_iteration = -1L) {
  .Call(LGBTPU_R_BoosterDumpModel, .lgb_booster_handle(booster),
        as.integer(num_iteration))
}

#' Fetch a recorded evaluation history
#' @param booster an lgb.Booster trained with record = TRUE
#' @param data_name validation set name
#' @param eval_name metric name
#' @param iters specific iterations (default all)
#' @export
lgb.get.eval.result <- function(booster, data_name, eval_name,
                                iters = NULL) {
  rec <- booster$record_evals[[data_name]][[eval_name]]
  if (is.null(rec)) {
    stop("no recorded evaluations for ", data_name, "/", eval_name,
         " (train with valids and record = TRUE)")
  }
  if (is.null(iters)) rec else rec[iters]
}

#' @export
print.lgb.Booster <- function(x, ...) {
  h <- tryCatch(.lgb_booster_handle(x), error = function(e) NULL)
  if (is.null(h)) {
    cat("<lgb.Booster (lightgbm.tpu), handle-less>\n")
    return(invisible(x))
  }
  nt <- .Call(LGBTPU_R_BoosterNumTrees, h)
  nc <- .Call(LGBTPU_R_BoosterGetNumClasses, h)
  it <- .Call(LGBTPU_R_BoosterGetCurrentIteration, h)
  cat(sprintf(
    "<lgb.Booster (lightgbm.tpu): %d trees, %d classes, iteration %d>\n",
    nt, nc, it))
  if (x$best_iter > 0L) {
    cat(sprintf("  best_iter: %d\n", x$best_iter))
  }
  invisible(x)
}

#' @export
summary.lgb.Booster <- function(object, ...) {
  print(object)
  invisible(object)
}
