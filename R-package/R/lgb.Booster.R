# lgb.Booster — R front end of the framework's Booster (basic.py),
# a thin client of the LGBMTPU_Booster* ABI.  Environment-backed with
# S3 methods, covering the reference surface: predict, save/load/dump,
# eval tracking, serialization keep-alive.

.lgb_booster_new <- function(handle, train_set = NULL, params = list()) {
  env <- new.env(parent = emptyenv())
  env$handle <- handle
  env$train_set <- train_set
  env$params <- params
  env$valid_sets <- list()
  env$valid_names <- character(0L)
  env$record_evals <- list()
  env$best_iter <- -1L
  env$best_score <- NA_real_
  env$raw <- NULL            # serialized model kept by lgb.make_serializable
  class(env) <- "lgb.Booster"
  env
}

#' Create a Booster on a training Dataset
#' @param train_set an lgb.Dataset
#' @param params named list of training parameters
#' @export
lgb.Booster <- function(train_set, params = list()) {
  lgb.Dataset.construct(train_set)
  h <- .Call(LGBTPU_R_BoosterCreate, train_set$handle,
             .lgb_params_json(params))
  .lgb_booster_new(h, train_set, params)
}

# a handle read back by readRDS is an external pointer whose native
# address is NULL — R-level is.null() cannot detect that, the glue can
.lgb_handle_live <- function(h) {
  !is.null(h) && .Call(LGBTPU_R_HandleIsLive, h)
}

.lgb_booster_handle <- function(booster) {
  if (!.lgb_handle_live(booster$handle)) {
    lgb.restore_handle(booster)
  }
  booster$handle
}

#' Predict with a Booster
#'
#' @param object an lgb.Booster
#' @param newdata matrix, dgCMatrix or file path
#' @param type "response" (transformed scores), "raw" (margins),
#'   "leaf" (leaf indices) or "contrib" (per-feature SHAP contributions
#'   plus bias column)
#' @param start_iteration,num_iteration iteration window (0 / -1 = all;
#'   when the booster has a best_iter from early stopping and
#'   num_iteration is NULL, the best iteration is used, matching the
#'   reference predict semantics)
#' @param header whether a file newdata has a header line
#' @param ... unused
#' @export
predict.lgb.Booster <- function(object, newdata,
                                type = c("response", "raw", "leaf",
                                         "contrib"),
                                start_iteration = 0L,
                                num_iteration = NULL, header = FALSE,
                                ...) {
  type <- match.arg(type)
  ptype <- switch(type, response = 0L, raw = 1L, leaf = 2L,
                  contrib = 3L)
  if (is.null(num_iteration)) {
    num_iteration <- if (object$best_iter > 0L) object$best_iter else -1L
  }
  h <- .lgb_booster_handle(object)
  if (is.character(newdata) && length(newdata) == 1L) {
    out_path <- tempfile(fileext = ".pred")
    .Call(LGBTPU_R_BoosterPredictForFile, h, newdata, header, ptype,
          as.integer(start_iteration), as.integer(num_iteration),
          out_path)
    preds <- as.numeric(readLines(out_path))
    unlink(out_path)
    return(preds)
  }
  if (inherits(newdata, "dgCMatrix")) {
    preds <- .Call(LGBTPU_R_BoosterPredictForCSC, h, newdata@p,
                   newdata@i, newdata@x, as.numeric(nrow(newdata)),
                   ptype, as.integer(start_iteration),
                   as.integer(num_iteration))
    nrow_ <- nrow(newdata)
  } else {
    m <- newdata
    if (is.data.frame(m)) m <- as.matrix(m)
    if (is.null(dim(m))) m <- matrix(m, nrow = 1L)
    storage.mode(m) <- "double"
    preds <- .Call(LGBTPU_R_BoosterPredictForMat, h, t(m),
                   as.numeric(nrow(m)), as.numeric(ncol(m)), ptype,
                   as.integer(start_iteration),
                   as.integer(num_iteration))
    nrow_ <- nrow(m)
  }
  # multi-output shapes come back row-major; fold into a matrix like the
  # reference's R predictor does
  per_row <- length(preds) / nrow_
  if (per_row > 1L) {
    return(matrix(preds, nrow = nrow_, byrow = TRUE))
  }
  preds
}

#' Save a Booster to the interoperable text format
#' @param booster an lgb.Booster
#' @param filename output path
#' @param num_iteration unused (full model is saved)
#' @export
lgb.save <- function(booster, filename, num_iteration = NULL) {
  stopifnot(inherits(booster, "lgb.Booster"))
  .Call(LGBTPU_R_BoosterSaveModel, .lgb_booster_handle(booster), filename)
  invisible(booster)
}

#' Load a Booster from a text model file or model string
#' @param filename path to a saved model
#' @param model_str a model string (alternative to filename)
#' @export
lgb.load <- function(filename = NULL, model_str = NULL) {
  if (!is.null(filename)) {
    h <- .Call(LGBTPU_R_BoosterCreateFromModelfile, filename)
  } else if (!is.null(model_str)) {
    h <- .Call(LGBTPU_R_BoosterLoadModelFromString, model_str)
  } else {
    stop("lgb.load: give filename or model_str")
  }
  .lgb_booster_new(h)
}

#' Dump a Booster to JSON
#' @param booster an lgb.Booster
#' @param num_iteration how many iterations to include (-1 = all)
#' @export
lgb.dump <- function(booster, num_iteration = -1L) {
  .Call(LGBTPU_R_BoosterDumpModel, .lgb_booster_handle(booster),
        as.integer(num_iteration))
}

#' Fetch a recorded evaluation history
#' @param booster an lgb.Booster trained with record = TRUE
#' @param data_name validation set name
#' @param eval_name metric name
#' @param iters specific iterations (default all)
#' @export
lgb.get.eval.result <- function(booster, data_name, eval_name,
                                iters = NULL) {
  rec <- booster$record_evals[[data_name]][[eval_name]]
  if (is.null(rec)) {
    stop("no recorded evaluations for ", data_name, "/", eval_name,
         " (train with valids and record = TRUE)")
  }
  if (is.null(iters)) rec else rec[iters]
}

#' Store the serialized model inside the R object so it survives
#' saveRDS/readRDS (the native handle does not)
#' @param booster an lgb.Booster
#' @export
lgb.make_serializable <- function(booster) {
  stopifnot(inherits(booster, "lgb.Booster"))
  booster$raw <- .Call(LGBTPU_R_BoosterSaveModelToString,
                       .lgb_booster_handle(booster))
  invisible(booster)
}

#' Drop the serialized copy stored by lgb.make_serializable
#' @param booster an lgb.Booster
#' @export
lgb.drop_serialized <- function(booster) {
  stopifnot(inherits(booster, "lgb.Booster"))
  booster$raw <- NULL
  invisible(booster)
}

#' Rebuild the native handle from the serialized copy (after readRDS)
#' @param booster an lgb.Booster with a stored raw model
#' @export
lgb.restore_handle <- function(booster) {
  stopifnot(inherits(booster, "lgb.Booster"))
  if (.lgb_handle_live(booster$handle)) {
    return(invisible(booster))
  }
  if (is.null(booster$raw)) {
    stop("booster has no native handle and no serialized copy; call ",
         "lgb.make_serializable before saveRDS")
  }
  booster$handle <- .Call(LGBTPU_R_BoosterLoadModelFromString,
                          booster$raw)
  invisible(booster)
}

#' @export
print.lgb.Booster <- function(x, ...) {
  h <- tryCatch(.lgb_booster_handle(x), error = function(e) NULL)
  if (is.null(h)) {
    cat("<lgb.Booster (lightgbm.tpu), handle-less>\n")
    return(invisible(x))
  }
  nt <- .Call(LGBTPU_R_BoosterNumTrees, h)
  nc <- .Call(LGBTPU_R_BoosterGetNumClasses, h)
  it <- .Call(LGBTPU_R_BoosterGetCurrentIteration, h)
  cat(sprintf(
    "<lgb.Booster (lightgbm.tpu): %d trees, %d classes, iteration %d>\n",
    nt, nc, it))
  if (x$best_iter > 0L) {
    cat(sprintf("  best_iter: %d\n", x$best_iter))
  }
  invisible(x)
}

#' @export
summary.lgb.Booster <- function(object, ...) {
  print(object)
  invisible(object)
}
