# lightgbm() — the one-call fitting interface (reference
# R-package/R/lightgbm.R): wraps matrix + label into an lgb.Dataset,
# picks a default objective from the label, trains via lgb.train.

#' Train a model in one call
#'
#' @param data matrix / dgCMatrix of features, or an lgb.Dataset
#' @param label response vector (ignored when data is an lgb.Dataset)
#' @param weights optional observation weights
#' @param params named list of parameters; objective defaults to
#'   "regression", or "binary" for a 0/1 label
#' @param nrounds boosting iterations
#' @param verbose <= 0 silences output
#' @param objective convenience override of params$objective
#' @param init_score optional initial scores
#' @param ... passed to lgb.train
#' @export
lightgbm <- function(data, label = NULL, weights = NULL,
                     params = list(), nrounds = 100L, verbose = 1L,
                     objective = NULL, init_score = NULL, ...) {
  if (inherits(data, "lgb.Dataset")) {
    dtrain <- data
  } else {
    if (is.null(label)) {
      stop("lightgbm: label is required when data is not an lgb.Dataset")
    }
    if (is.null(objective) && is.null(params[["objective"]])) {
      two_level <- length(unique(label)) == 2L &&
        all(label %in% c(0, 1))
      objective <- if (two_level) "binary" else "regression"
    }
    dtrain <- lgb.Dataset(data, params = list(), label = label,
                          weight = weights, init_score = init_score)
  }
  if (!is.null(objective)) {
    params[["objective"]] <- objective
  }
  bst <- lgb.train(params = params, data = dtrain, nrounds = nrounds,
                   verbose = verbose, ...)
  bst
}

#' Map factor/character columns to numeric codes with reusable rules
#'
#' @param data a data.frame
#' @param rules optional rules list from a previous call (applied to new
#'   data so train and test share the same coding)
#' @return list(data = converted data.frame, rules = rules)
#' @export
lgb.convert_with_rules <- function(data, rules = NULL) {
  stopifnot(is.data.frame(data))
  out <- data
  new_rules <- rules %||% list()
  for (col in names(out)) {
    v <- out[[col]]
    if (is.factor(v) || is.character(v)) {
      v <- as.character(v)
      if (is.null(new_rules[[col]])) {
        lv <- sort(unique(v[!is.na(v)]))
        new_rules[[col]] <- stats::setNames(seq_along(lv), lv)
      }
      codes <- unname(new_rules[[col]][v])
      out[[col]] <- as.numeric(codes)
    } else if (is.logical(v)) {
      out[[col]] <- as.numeric(v)
    }
  }
  list(data = out, rules = new_rules)
}

# The XLA runtime schedules its own parallelism; these exist for drop-in
# compatibility with scripts that tune the reference's OpenMP threads.

#' Set the native thread budget (advisory under XLA)
#' @param num_threads requested thread count
#' @export
setLGBMthreads <- function(num_threads) {
  Sys.setenv(LIGHTGBM_TPU_NUM_THREADS = as.character(num_threads))
  invisible(NULL)
}

#' Read the native thread budget
#' @export
getLGBMthreads <- function() {
  v <- Sys.getenv("LIGHTGBM_TPU_NUM_THREADS", unset = "")
  if (nzchar(v)) as.integer(v) else -1L
}

#' Pre-bind a fast single-row predict configuration
#'
#' A compatibility shim over the ABI's fast predict path
#' (LGBMTPU_BoosterPredictForMatSingleRowFastInit); ordinary predict()
#' on this framework already reuses its compiled predictor, so this
#' simply validates arguments and returns the booster.
#' @param model an lgb.Booster
#' @param csr unused
#' @param ... unused
#' @export
lgb.configure_fast_predict <- function(model, csr = FALSE, ...) {
  stopifnot(inherits(model, "lgb.Booster"))
  invisible(model)
}
