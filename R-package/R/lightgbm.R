# lightgbm() — the one-call fitting interface (reference
# R-package/R/lightgbm.R): wraps matrix + label into an lgb.Dataset,
# picks a default objective from the label, trains via lgb.train.

#' Train a model in one call
#'
#' @param data matrix / dgCMatrix of features, or an lgb.Dataset
#' @param label response vector (ignored when data is an lgb.Dataset)
#' @param weights optional observation weights
#' @param params named list of parameters; objective defaults to
#'   "regression", or "binary" for a 0/1 label
#' @param nrounds boosting iterations
#' @param verbose <= 0 silences output
#' @param objective convenience override of params$objective
#' @param init_score optional initial scores
#' @param ... passed to lgb.train
#' @export
lightgbm <- function(data, label = NULL, weights = NULL,
                     params = list(), nrounds = 100L, verbose = 1L,
                     objective = NULL, init_score = NULL, ...) {
  rules <- NULL
  if (inherits(data, "lgb.Dataset")) {
    dtrain <- data
  } else {
    if (is.null(label)) {
      stop("lightgbm: label is required when data is not an lgb.Dataset")
    }
    # data.frames route through the DataProcessor: factor/character
    # columns become categorical features with reusable coding rules
    # (lgb.DataProcessor.R), so predict() on a data.frame codes new
    # data identically
    proc <- .lgb_data_processor_prepare(data)
    if (is.null(objective) && is.null(params[["objective"]])) {
      two_level <- length(unique(label)) == 2L &&
        all(label %in% c(0, 1))
      objective <- if (two_level) "binary" else "regression"
    }
    dtrain <- lgb.Dataset(proc$data, params = list(), label = label,
                          weight = weights, init_score = init_score,
                          categorical_feature = proc$categorical_feature)
    rules <- proc$rules
  }
  if (!is.null(objective)) {
    params[["objective"]] <- objective
  }
  bst <- lgb.train(params = params, data = dtrain, nrounds = nrounds,
                   verbose = verbose, ...)
  bst$data_rules <- rules
  bst
}

#' Pre-bind a fast single-row predict configuration
#'
#' A compatibility shim over the ABI's fast predict path
#' (LGBMTPU_BoosterPredictForMatSingleRowFastInit); ordinary predict()
#' on this framework already reuses its compiled predictor, so this
#' simply validates arguments and returns the booster.
#' @param model an lgb.Booster
#' @param csr unused
#' @param ... unused
#' @export
lgb.configure_fast_predict <- function(model, csr = FALSE, ...) {
  stopifnot(inherits(model, "lgb.Booster"))
  invisible(model)
}
