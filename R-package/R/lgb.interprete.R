# lgb.interprete — per-prediction feature contributions (reference
# R-package/R/lgb.interprete.R) served by the ABI's SHAP predict
# (pred_contrib) instead of an R-side tree walk.

#' Per-prediction feature contributions for selected rows
#'
#' @param model an lgb.Booster
#' @param data matrix of rows to explain
#' @param idxset 1-based row indices to explain
#' @return list of data.frames (Feature, Contribution), one per row,
#'   sorted by absolute contribution
#' @export
lgb.interprete <- function(model, data, idxset) {
  stopifnot(inherits(model, "lgb.Booster"))
  m <- data[idxset, , drop = FALSE]
  contrib <- predict(model, m, type = "contrib")
  if (is.null(dim(contrib))) {
    contrib <- matrix(contrib, nrow = length(idxset), byrow = TRUE)
  }
  nf <- ncol(contrib) - 1L  # last column is the bias
  feat_names <- colnames(data) %||% paste0("Column_", seq_len(nf) - 1L)
  lapply(seq_along(idxset), function(i) {
    v <- contrib[i, seq_len(nf)]
    ord <- order(-abs(v))
    data.frame(Feature = feat_names[ord], Contribution = v[ord],
               stringsAsFactors = FALSE)
  })
}

