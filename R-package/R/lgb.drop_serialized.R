# lgb.drop_serialized — reference R-package/R/lgb.drop_serialized.R counterpart (model
# serialization keep-alive; the native handle does not survive
# saveRDS/readRDS, the stored text model does).

#' Drop the serialized copy stored by lgb.make_serializable
#' @param booster an lgb.Booster
#' @export
lgb.drop_serialized <- function(booster) {
  stopifnot(inherits(booster, "lgb.Booster"))
  booster$raw <- NULL
  invisible(booster)
}

