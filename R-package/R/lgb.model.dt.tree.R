# lgb.model.dt.tree — flat per-node table of the model (reference
# R-package/R/lgb.model.dt.tree.R), built from the booster's JSON
# dump with the package's base-R JSON reader (json.R).

# parse the booster's JSON dump once (base-R JSON reader below; the
# package avoids a jsonlite dependency the same way the ABI avoided it)
.lgb_model_dump <- function(model) {
  txt <- lgb.dump(model)
  .lgb_json_parse(txt)
}

#' Flat per-node table of every tree in the model
#'
#' @param model an lgb.Booster
#' @return data.frame with one row per node/leaf: tree_index,
#'   split_feature, split_gain, threshold, internal_value,
#'   internal_count, leaf_index, leaf_value, leaf_count, depth
#' @export
lgb.model.dt.tree <- function(model) {
  dump <- .lgb_model_dump(model)
  feat_names <- vapply(dump$feature_names, as.character, character(1L))
  rows <- list()
  walk <- function(node, tree_idx, depth) {
    if (!is.null(node$leaf_index)) {
      rows[[length(rows) + 1L]] <<- data.frame(
        tree_index = tree_idx, depth = depth,
        split_feature = NA_character_, split_gain = NA_real_,
        threshold = NA_real_, internal_value = NA_real_,
        internal_count = NA_real_,
        leaf_index = as.integer(node$leaf_index),
        leaf_value = as.numeric(node$leaf_value),
        leaf_count = as.numeric(node$leaf_count %||% NA_real_),
        stringsAsFactors = FALSE)
      return(invisible(NULL))
    }
    fi <- as.integer(node$split_feature) + 1L
    rows[[length(rows) + 1L]] <<- data.frame(
      tree_index = tree_idx, depth = depth,
      split_feature = if (fi >= 1L && fi <= length(feat_names))
        feat_names[[fi]] else as.character(fi - 1L),
      split_gain = as.numeric(node$split_gain %||% NA_real_),
      threshold = as.numeric(node$threshold %||% NA_real_),
      internal_value = as.numeric(node$internal_value %||% NA_real_),
      internal_count = as.numeric(node$internal_count %||% NA_real_),
      leaf_index = NA_integer_, leaf_value = NA_real_,
      leaf_count = NA_real_, stringsAsFactors = FALSE)
    walk(node$left_child, tree_idx, depth + 1L)
    walk(node$right_child, tree_idx, depth + 1L)
  }
  for (ti in seq_along(dump$tree_info)) {
    walk(dump$tree_info[[ti]]$tree_structure, ti - 1L, 0L)
  }
  do.call(rbind, rows)
}

