# Model-inspection helpers: feature importance, the flat tree table,
# and per-prediction interpretation — reference R-package counterparts
# of lgb.importance.R / lgb.model.dt.tree.R / lgb.interprete.R, served
# by the ABI (FeatureImportance, DumpModel) instead of parsing model
# text R-side.

#' Feature importance table
#'
#' @param model an lgb.Booster
#' @param percentage normalize each column to sum to 1
#' @return data.frame with Feature / Gain / Cover / Frequency, sorted
#'   by Gain
#' @export
lgb.importance <- function(model, percentage = TRUE) {
  stopifnot(inherits(model, "lgb.Booster"))
  h <- .lgb_booster_handle(model)
  splits <- .Call(LGBTPU_R_BoosterFeatureImportance, h, 0L)
  gains <- .Call(LGBTPU_R_BoosterFeatureImportance, h, 1L)
  feat_names <- tryCatch(
    .lgb_split_names(.Call(LGBTPU_R_BoosterGetFeatureNames, h)),
    error = function(e) character(0L))
  if (length(feat_names) != length(splits)) {
    feat_names <- paste0("Column_", seq_along(splits) - 1L)
  }
  gain <- gains
  freq <- splits
  cover <- freq  # cover (sum hessian) not tracked separately; mirrors freq
  if (percentage) {
    norm <- function(v) if (sum(v) > 0) v / sum(v) else v
    gain <- norm(gain)
    freq <- norm(freq)
    cover <- norm(cover)
  }
  ord <- order(-gain)
  data.frame(Feature = feat_names[ord], Gain = gain[ord],
             Cover = cover[ord], Frequency = freq[ord],
             stringsAsFactors = FALSE)
}

