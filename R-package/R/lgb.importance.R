# Model-inspection helpers: feature importance, the flat tree table,
# and per-prediction interpretation — reference R-package counterparts
# of lgb.importance.R / lgb.model.dt.tree.R / lgb.interprete.R, served
# by the ABI (FeatureImportance, DumpModel) instead of parsing model
# text R-side.

#' Feature importance table
#'
#' @param model an lgb.Booster
#' @param percentage normalize each column to sum to 1
#' @return data.frame with Feature / Gain / Cover / Frequency, sorted
#'   by Gain
#' @export
lgb.importance <- function(model, percentage = TRUE) {
  stopifnot(inherits(model, "lgb.Booster"))
  h <- .lgb_booster_handle(model)
  splits <- .Call(LGBTPU_R_BoosterFeatureImportance, h, 0L)
  gains <- .Call(LGBTPU_R_BoosterFeatureImportance, h, 1L)
  feat_names <- tryCatch(
    .lgb_split_names(.Call(LGBTPU_R_BoosterGetFeatureNames, h)),
    error = function(e) character(0L))
  if (length(feat_names) != length(splits)) {
    feat_names <- paste0("Column_", seq_along(splits) - 1L)
  }
  gain <- gains
  freq <- splits
  cover <- freq  # cover (sum hessian) not tracked separately; mirrors freq
  if (percentage) {
    norm <- function(v) if (sum(v) > 0) v / sum(v) else v
    gain <- norm(gain)
    freq <- norm(freq)
    cover <- norm(cover)
  }
  ord <- order(-gain)
  data.frame(Feature = feat_names[ord], Gain = gain[ord],
             Cover = cover[ord], Frequency = freq[ord],
             stringsAsFactors = FALSE)
}

# parse the booster's JSON dump once (base-R JSON reader below; the
# package avoids a jsonlite dependency the same way the ABI avoided it)
.lgb_model_dump <- function(model) {
  txt <- lgb.dump(model)
  .lgb_json_parse(txt)
}

#' Flat per-node table of every tree in the model
#'
#' @param model an lgb.Booster
#' @return data.frame with one row per node/leaf: tree_index,
#'   split_feature, split_gain, threshold, internal_value,
#'   internal_count, leaf_index, leaf_value, leaf_count, depth
#' @export
lgb.model.dt.tree <- function(model) {
  dump <- .lgb_model_dump(model)
  feat_names <- vapply(dump$feature_names, as.character, character(1L))
  rows <- list()
  walk <- function(node, tree_idx, depth) {
    if (!is.null(node$leaf_index)) {
      rows[[length(rows) + 1L]] <<- data.frame(
        tree_index = tree_idx, depth = depth,
        split_feature = NA_character_, split_gain = NA_real_,
        threshold = NA_real_, internal_value = NA_real_,
        internal_count = NA_real_,
        leaf_index = as.integer(node$leaf_index),
        leaf_value = as.numeric(node$leaf_value),
        leaf_count = as.numeric(node$leaf_count %||% NA_real_),
        stringsAsFactors = FALSE)
      return(invisible(NULL))
    }
    fi <- as.integer(node$split_feature) + 1L
    rows[[length(rows) + 1L]] <<- data.frame(
      tree_index = tree_idx, depth = depth,
      split_feature = if (fi >= 1L && fi <= length(feat_names))
        feat_names[[fi]] else as.character(fi - 1L),
      split_gain = as.numeric(node$split_gain %||% NA_real_),
      threshold = as.numeric(node$threshold %||% NA_real_),
      internal_value = as.numeric(node$internal_value %||% NA_real_),
      internal_count = as.numeric(node$internal_count %||% NA_real_),
      leaf_index = NA_integer_, leaf_value = NA_real_,
      leaf_count = NA_real_, stringsAsFactors = FALSE)
    walk(node$left_child, tree_idx, depth + 1L)
    walk(node$right_child, tree_idx, depth + 1L)
  }
  for (ti in seq_along(dump$tree_info)) {
    walk(dump$tree_info[[ti]]$tree_structure, ti - 1L, 0L)
  }
  do.call(rbind, rows)
}

#' Per-prediction feature contributions for selected rows
#'
#' @param model an lgb.Booster
#' @param data matrix of rows to explain
#' @param idxset 1-based row indices to explain
#' @return list of data.frames (Feature, Contribution), one per row,
#'   sorted by absolute contribution
#' @export
lgb.interprete <- function(model, data, idxset) {
  stopifnot(inherits(model, "lgb.Booster"))
  m <- data[idxset, , drop = FALSE]
  contrib <- predict(model, m, type = "contrib")
  if (is.null(dim(contrib))) {
    contrib <- matrix(contrib, nrow = length(idxset), byrow = TRUE)
  }
  nf <- ncol(contrib) - 1L  # last column is the bias
  feat_names <- colnames(data) %||% paste0("Column_", seq_len(nf) - 1L)
  lapply(seq_along(idxset), function(i) {
    v <- contrib[i, seq_len(nf)]
    ord <- order(-abs(v))
    data.frame(Feature = feat_names[ord], Contribution = v[ord],
               stringsAsFactors = FALSE)
  })
}

#' Barplot of feature importance
#' @param tree_imp output of lgb.importance
#' @param top_n how many features to show
#' @param measure "Gain", "Cover" or "Frequency"
#' @param ... passed to graphics::barplot
#' @export
lgb.plot.importance <- function(tree_imp, top_n = 10L,
                                measure = "Gain", ...) {
  top <- utils::head(tree_imp[order(-tree_imp[[measure]]), ], top_n)
  graphics::barplot(rev(top[[measure]]), names.arg = rev(top$Feature),
                    horiz = TRUE, las = 1L, main = measure, ...)
  invisible(top)
}

#' Barplot of one row's feature contributions
#' @param tree_interpretation one element of lgb.interprete's output
#' @param top_n how many features to show
#' @param ... passed to graphics::barplot
#' @export
lgb.plot.interpretation <- function(tree_interpretation, top_n = 10L,
                                    ...) {
  top <- utils::head(tree_interpretation, top_n)
  graphics::barplot(rev(top$Contribution), names.arg = rev(top$Feature),
                    horiz = TRUE, las = 1L, main = "Contribution", ...)
  invisible(top)
}
