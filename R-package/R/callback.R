# callback — reference R-package/R/callback.R counterpart: formal
# per-iteration callback constructors driven by lgb.train / lgb.cv.
# Each callback is a function(env) where env is a list carrying
# booster, iteration, begin_iteration, end_iteration and eval_list
# (named metric values of the round).  User callbacks passed via
# lgb.train(callbacks =) receive the same env, after the built-ins.

#' @noRd
cb_print_evaluation <- function(period = 1L) {
  function(env) {
    if (period > 0L && length(env$eval_list) > 0L &&
        (env$iteration %% period == 0L ||
         env$iteration == env$end_iteration)) {
      cat(sprintf("[%d]\t%s\n", env$iteration,
                  paste(sprintf("%s: %.6g", names(env$eval_list),
                                unlist(env$eval_list)),
                        collapse = "\t")))
    }
  }
}

#' @noRd
cb_record_evaluation <- function() {
  function(env) {
    # env$eval_parts carries (valid_name, metric_name) pairs aligned
    # with eval_list — re-splitting the display key would mis-key any
    # valid-set name containing "-"
    for (i in seq_along(env$eval_list)) {
      vn <- env$eval_parts[[i]][[1L]]
      mn <- env$eval_parts[[i]][[2L]]
      env$booster$record_evals[[vn]][[mn]] <-
        c(env$booster$record_evals[[vn]][[mn]], env$eval_list[[i]])
    }
  }
}

#' @noRd
cb_early_stop <- function(stopping_rounds, first_metric_only = FALSE,
                          verbose = TRUE) {
  # PER-METRIC best tracking (the reference/python callback semantics):
  # each (valid, metric) entry keeps its own best and stall counter;
  # training stops when ANY considered entry stalls for
  # ``stopping_rounds`` — a single shared best would let the metric with
  # the smallest normalized value mask every other metric's improvement
  state <- new.env(parent = emptyenv())
  state$best_score <- list()    # per entry key, orientation-normalized
  state$best_raw <- list()
  state$best_iter <- list()
  state$stale <- list()
  function(env) {
    if (length(env$eval_list) == 0L) {
      return(invisible(NULL))
    }
    consider <- seq_along(env$eval_list)
    if (first_metric_only) {
      # every valid set's entry for the FIRST metric family — the same
      # family semantics as the python callback and the fused in-jit
      # early stop (boosting/gbdt.py), so both frontends stop at the
      # same iteration on multi-valid runs
      fam <- function(m) sub("@.*$", "", m)
      first_fam <- fam(env$eval_parts[[1L]][[2L]])
      consider <- which(vapply(env$eval_parts, function(p) {
        fam(p[[2L]]) == first_fam
      }, logical(1L)))
    }
    for (i in consider) {
      nm <- names(env$eval_list)[[i]]
      v <- env$eval_list[[i]]
      score <- if (.lgb_metric_higher_better(nm)) -v else v
      if (is.null(state$best_score[[nm]]) ||
          score < state$best_score[[nm]]) {
        state$best_score[[nm]] <- score
        state$best_raw[[nm]] <- v
        state$best_iter[[nm]] <- env$iteration
        state$stale[[nm]] <- 0L
      } else {
        state$stale[[nm]] <- state$stale[[nm]] + 1L
        if (state$stale[[nm]] >= stopping_rounds) {
          if (verbose) {
            cat(sprintf("early stopping at iteration %d (best %d)\n",
                        env$iteration, state$best_iter[[nm]]))
          }
          env$booster$best_iter <- state$best_iter[[nm]]
          env$booster$best_score <- state$best_raw[[nm]]
          env$booster$stop_training <- TRUE
          return(invisible(NULL))
        }
      }
    }
    if (env$iteration == env$end_iteration &&
        env$booster$best_iter < 0L && length(state$best_iter) > 0L) {
      first <- names(env$eval_list)[[consider[[1L]]]]
      env$booster$best_iter <- state$best_iter[[first]]
      env$booster$best_score <- state$best_raw[[first]]
    }
    invisible(NULL)
  }
}

#' @noRd
cb_reset_parameter <- function(new_params) {
  # new_params: named list; each entry is a vector (one value per
  # iteration) or function(iteration, total) -> value — the reference
  # reset_parameter callback's contract.  Runs BEFORE the iteration
  # (reference before_iteration = TRUE; python frontend callback.py),
  # so iteration i trains with schedule value i.
  cb <- function(env) {
    upd <- list()
    for (nm in names(new_params)) {
      spec <- new_params[[nm]]
      if (is.function(spec)) {
        v <- spec(env$iteration, env$end_iteration)
      } else {
        if (length(spec) < env$end_iteration) {
          stop("reset_parameter: length of '", nm, "' (", length(spec),
               ") must cover every iteration (", env$end_iteration, ")")
        }
        v <- spec[[env$iteration]]
      }
      upd[[nm]] <- v
    }
    if (length(upd) > 0L) {
      .Call(LGBTPU_R_BoosterResetParameter,
            .lgb_booster_handle(env$booster), .lgb_params_json(upd))
    }
    invisible(NULL)
  }
  attr(cb, "pre_iteration") <- TRUE
  cb
}

# assemble the built-in callback pipeline the way engine.py orders its
# callbacks: reset_parameter carries attr pre_iteration = TRUE and runs
# BEFORE BoosterUpdateOneIter (lgb.train splits on the attribute), then
# printing, recording and early stopping run after the iteration
.lgb_build_callbacks <- function(verbose, eval_freq, record,
                                 early_stopping_rounds,
                                 first_metric_only = FALSE,
                                 reset_parameter = NULL,
                                 user_callbacks = list()) {
  cbs <- list()
  if (!is.null(reset_parameter)) {
    cbs[[length(cbs) + 1L]] <- cb_reset_parameter(reset_parameter)
  }
  if (verbose > 0L) {
    cbs[[length(cbs) + 1L]] <- cb_print_evaluation(max(eval_freq, 1L))
  }
  if (record) {
    cbs[[length(cbs) + 1L]] <- cb_record_evaluation()
  }
  if (!is.null(early_stopping_rounds) && early_stopping_rounds > 0L) {
    cbs[[length(cbs) + 1L]] <- cb_early_stop(
      as.integer(early_stopping_rounds), first_metric_only,
      verbose > 0L)
  }
  c(cbs, user_callbacks)
}
