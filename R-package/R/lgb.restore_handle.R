# lgb.restore_handle — reference R-package/R/lgb.restore_handle.R counterpart (model
# serialization keep-alive; the native handle does not survive
# saveRDS/readRDS, the stored text model does).

#' Rebuild the native handle from the serialized copy (after readRDS)
#' @param booster an lgb.Booster with a stored raw model
#' @export
lgb.restore_handle <- function(booster) {
  stopifnot(inherits(booster, "lgb.Booster"))
  if (.lgb_handle_live(booster$handle)) {
    return(invisible(booster))
  }
  if (is.null(booster$raw)) {
    stop("booster has no native handle and no serialized copy; call ",
         "lgb.make_serializable before saveRDS")
  }
  booster$handle <- .Call(LGBTPU_R_BoosterLoadModelFromString,
                          booster$raw)
  invisible(booster)
}

