# lgb.make_serializable — reference R-package/R/lgb.make_serializable.R counterpart (model
# serialization keep-alive; the native handle does not survive
# saveRDS/readRDS, the stored text model does).

#' Store the serialized model inside the R object so it survives
#' saveRDS/readRDS (the native handle does not)
#' @param booster an lgb.Booster
#' @export
lgb.make_serializable <- function(booster) {
  stopifnot(inherits(booster, "lgb.Booster"))
  booster$raw <- .Call(LGBTPU_R_BoosterSaveModelToString,
                       .lgb_booster_handle(booster))
  invisible(booster)
}

