# metrics — reference R-package/R/metrics.R counterpart: the explicit
# table of metrics where LARGER values mean better models, driving the
# early-stopping orientation in lgb.train / lgb.cv (the reference keeps
# the same list; metric.h factor_to_bigger_better is the C side).

.METRICS_HIGHER_BETTER <- c(
  "auc" = TRUE,
  "auc_mu" = TRUE,
  "average_precision" = TRUE,
  "ndcg" = TRUE,
  "map" = TRUE
)

# TRUE when a reported metric name (possibly "ndcg@5"-style) is
# higher-is-better
.lgb_metric_higher_better <- function(name) {
  base <- sub("@.*$", "", name)
  # eval names arrive as "<metric>" or "<valid>-<metric>"
  base <- sub("^.*-", "", base)
  isTRUE(.METRICS_HIGHER_BETTER[[base]])
}
