# lgb.plot.importance — reference R-package/R/lgb.plot.importance.R
# counterpart over base graphics (no ggplot dependency).

#' Barplot of feature importance
#' @param tree_imp output of lgb.importance
#' @param top_n how many features to show
#' @param measure "Gain", "Cover" or "Frequency"
#' @param ... passed to graphics::barplot
#' @export
lgb.plot.importance <- function(tree_imp, top_n = 10L,
                                measure = "Gain", ...) {
  top <- utils::head(tree_imp[order(-tree_imp[[measure]]), ], top_n)
  graphics::barplot(rev(top[[measure]]), names.arg = rev(top$Feature),
                    horiz = TRUE, las = 1L, main = measure, ...)
  invisible(top)
}

