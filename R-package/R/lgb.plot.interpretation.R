# lgb.plot.interpretation — reference
# R-package/R/lgb.plot.interpretation.R counterpart.

#' Barplot of one row's feature contributions
#' @param tree_interpretation one element of lgb.interprete's output
#' @param top_n how many features to show
#' @param ... passed to graphics::barplot
#' @export
lgb.plot.interpretation <- function(tree_interpretation, top_n = 10L,
                                    ...) {
  top <- utils::head(tree_interpretation, top_n)
  graphics::barplot(rev(top$Contribution), names.arg = rev(top$Feature),
                    horiz = TRUE, las = 1L, main = "Contribution", ...)
  invisible(top)
}
