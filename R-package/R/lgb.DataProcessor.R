# lgb.DataProcessor — reference R-package/R/lgb.DataProcessor.R
# counterpart: data.frame preprocessing for the high-level lightgbm()
# interface.  Factor/character columns are coded to numeric with
# deterministic, reusable rules (lgb.convert_with_rules) and flagged as
# categorical_feature; the rules ride on the returned booster so
# predict() on a data.frame codes new data identically.  Levels unseen
# at training time code to NA and route through the predictor's
# missing-category branch — the same treatment the reference's
# rules-based conversion gives unseen levels (its stored-rules apply
# also yields NA; true go-right "not in set" semantics exist only for
# numeric-coded categoricals, where the raw value survives to predict).

# prepare a data.frame/matrix for training: returns
# list(data = numeric matrix, categorical_feature = 1-based column
#      indices as lgb.Dataset consumes them (it converts to the ABI's
#      0-based form itself) or NULL, rules = coding rules or NULL)
.lgb_data_processor_prepare <- function(data) {
  if (!is.data.frame(data)) {
    return(list(data = data, categorical_feature = NULL, rules = NULL))
  }
  cat_cols <- names(data)[vapply(data, function(v) {
    is.factor(v) || is.character(v)
  }, logical(1L))]
  conv <- lgb.convert_with_rules(data)
  m <- as.matrix(conv$data)
  storage.mode(m) <- "double"
  cats <- match(cat_cols, names(data))
  list(data = m,
       categorical_feature = if (length(cats)) as.integer(cats) else NULL,
       rules = if (length(cat_cols)) conv$rules else NULL)
}

# apply stored rules to new prediction data (data.frame in, matrix out);
# unseen levels become NA, which the predictor routes like the
# reference's unseen-category branch
.lgb_data_processor_apply <- function(newdata, rules) {
  if (!is.data.frame(newdata)) {
    return(newdata)
  }
  if (is.null(rules) || length(rules) == 0L) {
    m <- as.matrix(newdata)
    storage.mode(m) <- "double"
    return(m)
  }
  conv <- lgb.convert_with_rules(newdata, rules = rules)
  m <- as.matrix(conv$data)
  storage.mode(m) <- "double"
  m
}
