# lgb.Predictor — the prediction path of the R binding (reference
# R-package/R/lgb.Predictor.R): routes matrix / dgCMatrix / file inputs
# to the matching LGBMTPU_BoosterPredictFor* ABI entry, folds
# multi-output row-major buffers into matrices, and applies the
# data.frame conversion rules stored by the DataProcessor so factor
# columns code identically at train and predict time.

#' Predict with a Booster
#'
#' @param object an lgb.Booster
#' @param newdata matrix, dgCMatrix or file path
#' @param type "response" (transformed scores), "raw" (margins),
#'   "leaf" (leaf indices) or "contrib" (per-feature SHAP contributions
#'   plus bias column)
#' @param start_iteration,num_iteration iteration window (0 / -1 = all;
#'   when the booster has a best_iter from early stopping and
#'   num_iteration is NULL, the best iteration is used, matching the
#'   reference predict semantics)
#' @param header whether a file newdata has a header line
#' @param ... unused
#' @export
predict.lgb.Booster <- function(object, newdata,
                                type = c("response", "raw", "leaf",
                                         "contrib"),
                                start_iteration = 0L,
                                num_iteration = NULL, header = FALSE,
                                ...) {
  type <- match.arg(type)
  ptype <- switch(type, response = 0L, raw = 1L, leaf = 2L,
                  contrib = 3L)
  if (is.null(num_iteration)) {
    num_iteration <- if (object$best_iter > 0L) object$best_iter else -1L
  }
  h <- .lgb_booster_handle(object)
  if (is.character(newdata) && length(newdata) == 1L) {
    out_path <- tempfile(fileext = ".pred")
    .Call(LGBTPU_R_BoosterPredictForFile, h, newdata, header, ptype,
          as.integer(start_iteration), as.integer(num_iteration),
          out_path)
    preds <- as.numeric(readLines(out_path))
    unlink(out_path)
    return(preds)
  }
  if (inherits(newdata, "dgCMatrix")) {
    preds <- .Call(LGBTPU_R_BoosterPredictForCSC, h, newdata@p,
                   newdata@i, newdata@x, as.numeric(nrow(newdata)),
                   ptype, as.integer(start_iteration),
                   as.integer(num_iteration))
    nrow_ <- nrow(newdata)
  } else {
    m <- newdata
    if (is.data.frame(m)) {
      m <- .lgb_data_processor_apply(m, object$data_rules)
    }
    if (is.null(dim(m))) m <- matrix(m, nrow = 1L)
    storage.mode(m) <- "double"
    preds <- .Call(LGBTPU_R_BoosterPredictForMat, h, t(m),
                   as.numeric(nrow(m)), as.numeric(ncol(m)), ptype,
                   as.integer(start_iteration),
                   as.integer(num_iteration))
    nrow_ <- nrow(m)
  }
  # multi-output shapes come back row-major; fold into a matrix like the
  # reference's R predictor does
  per_row <- length(preds) / nrow_
  if (per_row > 1L) {
    return(matrix(preds, nrow = nrow_, byrow = TRUE))
  }
  preds
}

