# lgb.Dataset — R front end of the framework's Dataset (io/dataset.py),
# a thin client of the LGBMTPU_Dataset* ABI like the reference's
# R-package/R/lgb.Dataset.R is of LGBM_Dataset*.
#
# The object is an environment with class "lgb.Dataset", constructed
# LAZILY: data and parameters are recorded at creation, the native
# handle is built on first use (construct), matching the reference's
# two-phase design so set_field / categorical settings made before
# training are folded into construction.

#' Create a lightgbm.tpu Dataset
#'
#' @param data matrix, dgCMatrix (Matrix package) or path to a text file
#' @param params named list of dataset parameters (max_bin, ...)
#' @param reference another lgb.Dataset whose bin mappers to reuse
#'   (validation sets must be binned like their training set)
#' @param colnames feature names
#' @param categorical_feature names or 1-based indices of categorical
#'   features
#' @param label,weight,group,init_score metadata vectors
#' @param free_raw_data drop the R-side copy after construction
#' @export
lgb.Dataset <- function(data, params = list(), reference = NULL,
                        colnames = NULL, categorical_feature = NULL,
                        label = NULL, weight = NULL, group = NULL,
                        init_score = NULL, free_raw_data = TRUE) {
  if (!is.null(reference) && !inherits(reference, "lgb.Dataset")) {
    stop("lgb.Dataset: reference must be an lgb.Dataset")
  }
  env <- new.env(parent = emptyenv())
  env$raw_data <- data
  env$params <- params
  env$reference <- reference
  env$colnames <- colnames %||% (if (is.matrix(data)) colnames(data))
  env$categorical_feature <- categorical_feature
  env$fields <- list()
  if (!is.null(label)) env$fields[["label"]] <- as.numeric(label)
  if (!is.null(weight)) env$fields[["weight"]] <- as.numeric(weight)
  if (!is.null(group)) env$fields[["group"]] <- as.numeric(group)
  if (!is.null(init_score)) {
    env$fields[["init_score"]] <- as.numeric(init_score)
  }
  env$free_raw_data <- isTRUE(free_raw_data)
  env$handle <- NULL
  class(env) <- "lgb.Dataset"
  env
}

`%||%` <- function(a, b) if (is.null(a)) b else a

#' Construct the native dataset (no-op when already constructed)
#' @param dataset an lgb.Dataset
#' @export
lgb.Dataset.construct <- function(dataset) {
  stopifnot(inherits(dataset, "lgb.Dataset"))
  if (!is.null(dataset$handle)) {
    return(invisible(dataset))
  }
  params <- .lgb_resolve_categorical(dataset$params,
                                     dataset$categorical_feature,
                                     dataset$colnames)
  pj <- .lgb_params_json(params)
  data <- dataset$raw_data
  label <- dataset$fields[["label"]]
  if (is.character(data) && length(data) == 1L) {
    h <- .Call(LGBTPU_R_DatasetCreateFromFile, data, pj)
  } else if (inherits(data, "dgCMatrix")) {
    h <- .Call(LGBTPU_R_DatasetCreateFromCSC,
               data@p, data@i, data@x,
               as.numeric(ncol(data)), as.numeric(length(data@x)),
               as.numeric(nrow(data)),
               as.numeric(label %||% numeric(0L)), pj)
  } else {
    m <- data
    if (is.data.frame(m)) m <- as.matrix(m)
    storage.mode(m) <- "double"
    # ABI expects row-major [nrow, ncol]; R matrices are column-major
    h <- .Call(LGBTPU_R_DatasetCreateFromMat, t(m),
               as.numeric(nrow(m)), as.numeric(ncol(m)),
               as.numeric(label %||% numeric(0L)), pj)
  }
  dataset$handle <- h
  if (!is.null(dataset$colnames)) {
    .Call(LGBTPU_R_DatasetSetFeatureNames, h,
          .lgb_strings_json(dataset$colnames))
  }
  for (field in setdiff(names(dataset$fields), "label")) {
    .Call(LGBTPU_R_DatasetSetField, h, field,
          dataset$fields[[field]])
  }
  if (isTRUE(dataset$free_raw_data)) {
    dataset$raw_data <- NULL
  }
  invisible(dataset)
}

#' Create a validation Dataset binned like its training set
#' @param dataset the training lgb.Dataset (becomes the reference)
#' @param data validation data (matrix / dgCMatrix / file path)
#' @param ... passed to lgb.Dataset
#' @export
lgb.Dataset.create.valid <- function(dataset, data, ...) {
  stopifnot(inherits(dataset, "lgb.Dataset"))
  lgb.Dataset(data, params = dataset$params, reference = dataset, ...)
}

#' Save a Dataset to the framework's binary format
#' @param dataset an lgb.Dataset
#' @param fname output path
#' @export
lgb.Dataset.save <- function(dataset, fname) {
  lgb.Dataset.construct(dataset)
  .Call(LGBTPU_R_DatasetSaveBinary, dataset$handle, fname)
  invisible(dataset)
}

#' Declare categorical features (before construction)
#' @param dataset an lgb.Dataset
#' @param categorical_feature names or 1-based indices
#' @export
lgb.Dataset.set.categorical <- function(dataset, categorical_feature) {
  stopifnot(inherits(dataset, "lgb.Dataset"))
  if (!is.null(dataset$handle)) {
    stop("set.categorical must be called before the dataset is constructed")
  }
  dataset$categorical_feature <- categorical_feature
  invisible(dataset)
}

#' Set the bin-mapper reference of a validation Dataset
#' @param dataset the validation lgb.Dataset
#' @param reference the training lgb.Dataset
#' @export
lgb.Dataset.set.reference <- function(dataset, reference) {
  stopifnot(inherits(dataset, "lgb.Dataset"),
            inherits(reference, "lgb.Dataset"))
  if (!is.null(dataset$handle)) {
    stop("set.reference must be called before the dataset is constructed")
  }
  dataset$reference <- reference
  invisible(dataset)
}

#' Subset a Dataset by row indices (shares bin mappers, like cv folds)
#' @param dataset an lgb.Dataset
#' @param idxset 1-based row indices
#' @param ... unused
#' @export
lgb.slice.Dataset <- function(dataset, idxset, ...) {
  lgb.Dataset.construct(dataset)
  sub <- new.env(parent = emptyenv())
  sub$handle <- .Call(LGBTPU_R_DatasetGetSubset, dataset$handle,
                      as.integer(idxset - 1L),
                      .lgb_params_json(dataset$params))
  sub$params <- dataset$params
  sub$reference <- dataset
  sub$colnames <- dataset$colnames
  sub$fields <- list()
  sub$free_raw_data <- TRUE
  class(sub) <- "lgb.Dataset"
  sub
}

#' Read a metadata field from a Dataset
#' @param dataset an lgb.Dataset
#' @param field_name "label", "weight", "group" or "init_score"
#' @export
get_field <- function(dataset, field_name) {
  UseMethod("get_field")
}

#' @export
get_field.lgb.Dataset <- function(dataset, field_name) {
  if (is.null(dataset$handle)) {
    return(dataset$fields[[field_name]])
  }
  .Call(LGBTPU_R_DatasetGetField, dataset$handle, field_name)
}

#' Set a metadata field on a Dataset
#' @param dataset an lgb.Dataset
#' @param field_name "label", "weight", "group" or "init_score"
#' @param data numeric vector
#' @export
set_field <- function(dataset, field_name, data) {
  UseMethod("set_field")
}

#' @export
set_field.lgb.Dataset <- function(dataset, field_name, data) {
  dataset$fields[[field_name]] <- as.numeric(data)
  if (!is.null(dataset$handle)) {
    .Call(LGBTPU_R_DatasetSetField, dataset$handle, field_name,
          as.numeric(data))
  }
  invisible(dataset)
}

#' @export
dim.lgb.Dataset <- function(x) {
  if (is.null(x$handle)) {
    if (is.matrix(x$raw_data) || inherits(x$raw_data, "dgCMatrix")) {
      return(dim(x$raw_data))
    }
    lgb.Dataset.construct(x)
  }
  c(.Call(LGBTPU_R_DatasetGetNumData, x$handle),
    .Call(LGBTPU_R_DatasetGetNumFeature, x$handle))
}

#' @export
dimnames.lgb.Dataset <- function(x) {
  list(NULL, x$colnames)
}

#' @export
print.lgb.Dataset <- function(x, ...) {
  constructed <- if (is.null(x$handle)) "not constructed" else "constructed"
  d <- tryCatch(dim(x), error = function(e) c(NA, NA))
  cat(sprintf("<lgb.Dataset (lightgbm.tpu), %s, %s x %s>\n", constructed,
              d[1L], d[2L]))
  invisible(x)
}
