"""Export the e2e bench's synthetic Higgs-shaped dataset as CSV for the
reference CLI (same-host baseline capture, VERDICT r4 next-round #2).

Reproduces bench.py ``_synth_higgs`` draws EXACTLY (same seed, same rng
call order): train = _synth_higgs(N, 28, rng), test =
_synth_higgs(200_000, 28, rng, w=w).  Label is column 0, no header —
the reference CLI's default CSV layout (docs/Parameters label_column).

Usage:  BENCH_ROWS=10500000 python tools/make_baseline_data.py OUTDIR
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import BENCH_ROWS, _synth_higgs  # noqa: E402

outdir = sys.argv[1] if len(sys.argv) > 1 else ".refbuild"
os.makedirs(outdir, exist_ok=True)
rng = np.random.default_rng(0)
n, f = BENCH_ROWS, 28
feat, label, w = _synth_higgs(n, f, rng)
feat_te, label_te, _ = _synth_higgs(200_000, f, rng, w=w)


def write_csv(path, X, y, chunk=200_000):
    with open(path, "w") as fh:
        for s in range(0, len(y), chunk):
            e = min(s + chunk, len(y))
            block = np.column_stack([y[s:e], X[s:e]])
            np.savetxt(fh, block, fmt="%.7g", delimiter=",")
            print(f"{path}: {e}/{len(y)}", flush=True)


write_csv(os.path.join(outdir, "higgs_synth.train"), feat, label)
write_csv(os.path.join(outdir, "higgs_synth.test"), feat_te, label_te)
print("done")
