#!/usr/bin/env python
"""One pane of glass over a training run's observability artifacts.

    python tools/run_report.py [--trace T.json] [--events E.jsonl]
                               [--telemetry TEL.jsonl]
                               [--quick] [--format text|json]

Joins the three artifact families one run can emit — the Chrome trace
(``trace_output``), the structured event journal (``event_output``,
obs/events.py) and the telemetry JSONL (``telemetry_output``) — into a
single report: top phases, the event timeline, the final counter
snapshot with the compile-cache and collective-overlap columns pulled
out.  Any subset of the artifacts may be given; at least one must be.

A journal carrying continuous-learning records (pipeline/trainer.py)
additionally gets a pipeline section joining the trainer's cycle events
with the serving tier's hot-swap events: cycles completed, per-cycle
publish latency, resumes — and a cycle that started but never published
is a finding (``--quick`` exits 1: the workdir holds an unfinished,
resumable cycle).  Journals with sharded-ingest stripe records
(io/sharded.py) get a stripe-ledger section — claims joined against
commits — where a claimed-but-never-committed stripe is likewise a
``--quick`` finding: the merged dataset under that ledger is
incomplete.

``--quick`` is the CI gate mode: it only validates that every provided
artifact parses and carries its expected schema (trace has span
events, journal has records, telemetry has rows) and reports findings
without the full join.

Exit codes (tools/_report.py convention): 0 — every provided artifact
is present and non-degenerate, 1 — findings (an artifact parsed but is
empty/spanless), 2 — an artifact is unreadable or not its format (or
no artifact was given at all).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _report import (EXIT_ERROR, EXIT_FINDINGS, EXIT_OK,  # noqa: E402
                     add_format_arg, emit)
import trace_report  # noqa: E402

#: final-snapshot counters surfaced as the "compile" join column
_COMPILE_COUNTERS = (
    "round_compile_hits", "round_compile_misses",
    "fused_runner_cache_hits", "fused_runner_cache_misses",
    "xla_compile_events", "xla_program_lowerings",
    "serve_compile_hits", "serve_compile_misses",
    "rank_compile_hits", "rank_compile_misses",
)

#: final-snapshot gauges surfaced as the "collective" join column
_COLLECTIVE_GAUGES = (
    "collective_s_per_pass", "collective_s_blocked",
    "collective_s_per_round", "overlap_efficiency", "overlap_on",
)

#: final-snapshot gauges surfaced as the "rank" join column (query
#: bucketing geometry: padded-row overhead and ladder width)
_RANK_GAUGES = ("rank_pad_rows", "rank_bucket_count")

#: final-snapshot counters surfaced as the "watchtower" join column
_WATCHTOWER_COUNTERS = (
    "rollup_windows_closed", "slo_breaches", "slo_recoveries",
    "anomalies_detected",
)


def slo_stats(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Replay the journal's watchtower records into an SLO ledger.

    Breach/recover events carry the SLO name in ``payload.slo``; a name
    whose LAST transition is a breach is "unrecovered" — the signal the
    ``--quick`` CI gate turns into a nonzero exit."""
    last_state: Dict[str, str] = {}
    breaches = recoveries = anomalies = 0
    anomaly_kinds: Dict[str, int] = {}
    for rec in events:
        name = rec.get("event")
        payload = rec.get("payload") or {}
        slo = payload.get("slo") if isinstance(payload, dict) else None
        if name == "slo_breach":
            breaches += 1
            if isinstance(slo, str):
                last_state[slo] = "breached"
        elif name == "slo_recovered":
            recoveries += 1
            if isinstance(slo, str):
                last_state[slo] = "ok"
        elif name == "anomaly_detected":
            anomalies += 1
            kind = payload.get("kind") if isinstance(payload, dict) \
                else None
            if isinstance(kind, str):
                anomaly_kinds[kind] = anomaly_kinds.get(kind, 0) + 1
    unrecovered = sorted(n for n, s in last_state.items()
                         if s == "breached")
    return {
        "breaches": breaches,
        "recoveries": recoveries,
        "anomalies": anomalies,
        "anomaly_kinds": anomaly_kinds,
        "last_state": last_state,
        "unrecovered": unrecovered,
    }


def ingest_stats(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Replay streaming-ingest records (io/streaming.py) into a ledger.

    ``None`` when the journal holds no ingest events.  An ingest that
    started (or resumed) but never logged ``ingest_completed`` is the
    CI-gate signal — the dataset on disk is partial."""
    started = completed = resumed = 0
    shards: Dict[str, int] = {}
    rows = features = None
    for rec in events:
        name = rec.get("event")
        payload = rec.get("payload") or {}
        if not isinstance(payload, dict):
            payload = {}
        if name == "ingest_started":
            started += 1
        elif name == "ingest_resumed":
            resumed += 1
        elif name == "ingest_shard_done":
            stage = str(payload.get("stage", "?"))
            shards[stage] = shards.get(stage, 0) + 1
        elif name == "ingest_completed":
            completed += 1
            rows = payload.get("rows", rows)
            features = payload.get("features", features)
    if not (started or resumed or completed or shards):
        return None
    return {
        "started": started, "resumed": resumed, "completed": completed,
        "shards": shards, "rows": rows, "features": features,
        "unfinished": (started + resumed) > 0 and completed == 0,
    }


#: claim/steal records carry the ledger pass tag; commit records carry
#: the human stage name — the join key between the two families
_STAGE_TO_TAG = {"sketch": "p1", "bin": "p2", "collect": "c"}


def sharded_stats(events: List[Dict[str, Any]]) \
        -> Optional[Dict[str, Any]]:
    """Replay sharded-ingest records (io/sharded.py) into a stripe
    ledger: claims (first-claim + steals) joined against commits.

    ``None`` when the journal holds no stripe events.  A stripe that
    was claimed (or reassigned) but never committed is ORPHANED — the
    CI-gate signal that a worker died holding work nobody finished,
    so the merged dataset under that ledger is incomplete."""
    claims: Dict[Any, int] = {}
    done = set()
    reassigned = deaths = merges = 0
    workers = None
    dead_ranks = set()
    for rec in events:
        name = rec.get("event")
        payload = rec.get("payload") or {}
        if not isinstance(payload, dict):
            payload = {}
        if name == "ingest_stripe_claimed":
            k = (str(payload.get("stage")), payload.get("stripe"))
            claims[k] = claims.get(k, 0) + 1
        elif name == "ingest_stripe_reassigned":
            reassigned += 1
            k = (str(payload.get("stage")), payload.get("stripe"))
            claims[k] = claims.get(k, 0) + 1
        elif name == "ingest_worker_dead":
            deaths += 1
            if payload.get("dead_rank") is not None:
                dead_ranks.add(int(payload["dead_rank"]))
        elif name == "ingest_merge_completed":
            merges += 1
            workers = payload.get("workers", workers)
        elif name == "ingest_shard_done":
            tag = _STAGE_TO_TAG.get(str(payload.get("stage")))
            if tag is not None:
                done.add((tag, payload.get("shard")))
    if not (claims or reassigned or deaths or merges):
        return None
    orphaned = sorted(f"{tag}:{stripe}" for tag, stripe in claims
                      if (tag, stripe) not in done)
    return {
        "stripes_claimed": len(claims),
        "stripes_committed": len(done),
        "stripes_reassigned": reassigned,
        "worker_deaths": deaths,
        "dead_ranks": sorted(dead_ranks),
        "merges_completed": merges,
        "workers": workers,
        "orphaned_stripes": orphaned,
    }


def pipeline_stats(events: List[Dict[str, Any]]) \
        -> Optional[Dict[str, Any]]:
    """Replay continuous-learning records (pipeline/trainer.py) into a
    cycle ledger, joining the trainer's side of the journal
    (``cycle_started`` .. ``cycle_published``) with the serving side
    (``serve_hot_swap``) the same publishes produced.

    ``None`` when the journal holds no pipeline events.  A cycle that
    started but never published (nor was refused as stale) is the
    CI-gate signal — the pipeline workdir holds an unfinished cycle.
    Latencies are wall-clock (``unix_time``), not ``t_mono``, because a
    resumed cycle's records span trainer processes."""
    started: Dict[int, Any] = {}
    published: Dict[int, Dict[str, Any]] = {}
    resumes = stale = swaps = 0
    for rec in events:
        name = rec.get("event")
        payload = rec.get("payload") or {}
        if not isinstance(payload, dict):
            payload = {}
        c = payload.get("cycle")
        if name == "cycle_started" and c is not None:
            started.setdefault(int(c), rec.get("unix_time"))
        elif name == "cycle_resumed":
            resumes += 1
        elif name == "serve_hot_swap":
            swaps += 1
        elif name == "cycle_published" and c is not None:
            published[int(c)] = {"version": payload.get("version"),
                                 "t": rec.get("unix_time")}
        elif name == "publish_skipped_stale" and c is not None:
            stale += 1
            published.setdefault(int(c), {
                "version": payload.get("version"),
                "t": rec.get("unix_time"), "stale": True})
    if not (started or published or resumes):
        return None
    cycles = []
    for c in sorted(published):
        t0, t1 = started.get(c), published[c].get("t")
        lat = round(t1 - t0, 6) if t0 and t1 and t1 >= t0 else None
        cycles.append({"cycle": c, "version": published[c].get("version"),
                       "publish_latency_s": lat,
                       "stale_skipped": bool(published[c].get("stale"))})
    unfinished = sorted(set(started) - set(published))
    return {
        "cycles_completed": len(published), "resumes": resumes,
        "stale_publishes_refused": stale, "hot_swaps": swaps,
        "cycles": cycles, "unfinished_cycles": unfinished,
        "unfinished": bool(unfinished),
    }


def load_telemetry(path: str) -> List[Dict[str, Any]]:
    """Telemetry JSONL rows (one per round); torn lines are skipped."""
    rows: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict):
                rows.append(row)
    return rows


def telemetry_stats(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Final-state summary of the per-round telemetry stream."""
    counters: Dict[str, Any] = {}
    gauges: Dict[str, Any] = {}
    iters = []
    for row in rows:
        if isinstance(row.get("counters"), dict):
            counters = row["counters"]
        if isinstance(row.get("gauges"), dict):
            gauges = row["gauges"]
        it = row.get("iteration")
        if isinstance(it, (int, float)):
            iters.append(int(it))
    return {
        "rows": len(rows),
        "first_round": min(iters) if iters else None,
        "last_round": max(iters) if iters else None,
        "counters": counters,
        "gauges": gauges,
        "compile": {k: counters[k] for k in _COMPILE_COUNTERS
                    if k in counters},
        "collective": {k: gauges[k] for k in _COLLECTIVE_GAUGES
                       if k in gauges},
        "rank": {k: gauges[k] for k in _RANK_GAUGES if k in gauges},
        "watchtower": {k: counters[k] for k in _WATCHTOWER_COUNTERS
                       if k in counters},
    }


def build_report(trace_doc: Optional[Dict[str, Any]],
                 events: Optional[List[Dict[str, Any]]],
                 telemetry: Optional[List[Dict[str, Any]]],
                 paths: Dict[str, str],
                 quick: bool = False) -> Dict[str, Any]:
    payload: Dict[str, Any] = {"tool": "run_report", "quick": quick,
                               "sources": paths}
    findings: List[str] = []
    if trace_doc is not None:
        phases = trace_report.phase_stats(trace_doc)
        if not phases:
            findings.append("trace has no complete (ph=X) span events")
        if quick:
            payload["trace"] = {"span_kinds": len(phases)}
        else:
            tr = trace_report.build_report(trace_doc,
                                           trace=paths.get("trace", ""))
            tr.pop("tool", None)
            payload["trace"] = tr
    if events is not None:
        if not events:
            findings.append("event journal holds no records")
        stats = trace_report.event_stats(events)
        if not quick:
            stats["timeline"] = [
                {"event": r.get("event"), "rank": r.get("rank"),
                 "round": r.get("round"),
                 "severity": r.get("severity")} for r in events]
        payload["events"] = stats
        slo = slo_stats(events)
        if slo["breaches"] or slo["recoveries"] or slo["anomalies"]:
            payload["slo"] = slo
        # fires in quick AND full mode: an unrecovered breach is the
        # one journal state that should fail a CI gate outright
        if slo["unrecovered"]:
            findings.append("run ends with unrecovered slo_breach: "
                            + ", ".join(slo["unrecovered"]))
        ingest = ingest_stats(events)
        if ingest is not None:
            payload["ingest"] = ingest
            if ingest["unfinished"]:
                findings.append(
                    "streaming ingest started but never completed — the "
                    "dataset in its workdir is partial (resumable)")
        shd = sharded_stats(events)
        if shd is not None:
            payload["sharded"] = shd
            if shd["orphaned_stripes"]:
                findings.append(
                    "sharded-ingest stripe(s) "
                    + ", ".join(shd["orphaned_stripes"])
                    + " claimed but never committed — a worker died "
                    "holding work no survivor finished; the merged "
                    "dataset under that ledger is incomplete")
        pipe = pipeline_stats(events)
        if pipe is not None:
            payload["pipeline"] = pipe
            if pipe["unfinished"]:
                findings.append(
                    "continuous-learning cycle(s) "
                    + ", ".join(str(c) for c in pipe["unfinished_cycles"])
                    + " started but never published — the pipeline "
                    "workdir holds an unfinished cycle (resumable)")
    if telemetry is not None:
        if not telemetry:
            findings.append("telemetry stream holds no rows")
        payload["telemetry"] = telemetry_stats(telemetry) if not quick \
            else {"rows": len(telemetry)}
    payload["findings"] = findings
    return payload


def _render_report(payload: Dict[str, Any]) -> str:
    lines = []
    for f in payload["findings"]:
        lines.append(f"FINDING: {f}")
    tr = payload.get("trace")
    if tr and "phases" in tr:
        sub = dict(tr)
        sub.setdefault("tool", "trace_report")
        lines.append(trace_report._render_report(sub))
    elif tr is not None:
        lines.append(f"trace: {tr.get('span_kinds', 0)} span kind(s)")
    ev = payload.get("events")
    if ev is not None:
        lines.append("")
        lines.append(f"event journal: {ev['count']} record(s)")
        for name in sorted(ev.get("by_name", {})):
            lines.append(f"  {name}: {ev['by_name'][name]}")
    slo = payload.get("slo")
    if slo is not None:
        lines.append("")
        lines.append(f"watchtower: {slo['breaches']} breach(es), "
                     f"{slo['recoveries']} recovery(ies), "
                     f"{slo['anomalies']} anomaly(ies)")
        for name in sorted(slo.get("last_state", {})):
            state = slo["last_state"][name]
            flag = "UNRECOVERED" if state == "breached" else "ok"
            lines.append(f"  slo {name}: {flag}")
        for kind in sorted(slo.get("anomaly_kinds", {})):
            lines.append(f"  anomaly {kind}: "
                         f"{slo['anomaly_kinds'][kind]}")
    ingest = payload.get("ingest")
    if ingest is not None:
        lines.append("")
        state = "complete" if ingest["completed"] else (
            "UNFINISHED" if ingest["unfinished"] else "idle")
        lines.append(f"streaming ingest: {state} "
                     f"({ingest['started']} started, "
                     f"{ingest['resumed']} resumed)")
        for stage in sorted(ingest.get("shards", {})):
            lines.append(f"  {stage} shards: {ingest['shards'][stage]}")
        if ingest.get("rows") is not None:
            lines.append(f"  rows: {ingest['rows']}  features: "
                         f"{ingest.get('features')}")
    shd = payload.get("sharded")
    if shd is not None:
        lines.append("")
        state = "ORPHANED STRIPES" if shd["orphaned_stripes"] else "clean"
        lines.append(f"sharded ingest: {state} "
                     f"({shd['stripes_claimed']} stripe(s) claimed, "
                     f"{shd['stripes_committed']} committed, "
                     f"{shd['stripes_reassigned']} reassigned)")
        if shd.get("workers") is not None:
            lines.append(f"  workers: {shd['workers']}  merges: "
                         f"{shd['merges_completed']}")
        if shd["worker_deaths"]:
            ranks = ", ".join(str(r) for r in shd["dead_ranks"]) or "?"
            lines.append(f"  worker deaths: {shd['worker_deaths']} "
                         f"(rank(s) {ranks})")
        for s in shd["orphaned_stripes"]:
            lines.append(f"  orphaned stripe {s}")
    pipe = payload.get("pipeline")
    if pipe is not None:
        lines.append("")
        state = "UNFINISHED" if pipe["unfinished"] else "complete"
        lines.append(f"continuous pipeline: {state} "
                     f"({pipe['cycles_completed']} cycle(s) published, "
                     f"{pipe['resumes']} resume(s), "
                     f"{pipe['hot_swaps']} hot swap(s))")
        for c in pipe.get("cycles", []):
            lat = c.get("publish_latency_s")
            lat_s = f"{lat:.3f}s" if lat is not None else "?"
            note = "  STALE-SKIPPED" if c.get("stale_skipped") else ""
            lines.append(f"  cycle {c['cycle']}: version {c['version']} "
                         f"published after {lat_s}{note}")
        if pipe.get("stale_publishes_refused"):
            lines.append(f"  stale publishes refused: "
                         f"{pipe['stale_publishes_refused']}")
    tel = payload.get("telemetry")
    if tel is not None:
        lines.append("")
        lines.append(f"telemetry: {tel['rows']} row(s)")
        if tel.get("last_round") is not None:
            lines.append(f"  rounds {tel['first_round']}"
                         f"..{tel['last_round']}")
        for section in ("compile", "collective", "rank", "watchtower"):
            vals = tel.get(section) or {}
            if vals:
                lines.append(f"  {section}:")
                for k in sorted(vals):
                    lines.append(f"    {k}: {vals[k]}")
    if not payload["findings"]:
        lines.append("")
        lines.append("run artifacts healthy")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", default=None,
                    help="Chrome trace JSON (trace_output=...)")
    ap.add_argument("--events", default=None,
                    help="event-journal JSONL (event_output=...)")
    ap.add_argument("--telemetry", default=None,
                    help="telemetry JSONL (telemetry_output=...)")
    ap.add_argument("--quick", action="store_true",
                    help="schema-validation gate only (CI mode)")
    add_format_arg(ap)
    args = ap.parse_args(argv)
    if not (args.trace or args.events or args.telemetry):
        print("run_report: no artifacts given — pass at least one of "
              "--trace/--events/--telemetry", file=sys.stderr)
        return EXIT_ERROR
    paths = {}
    try:
        trace_doc = None
        if args.trace:
            trace_doc = trace_report.load_trace(args.trace)
            paths["trace"] = args.trace
        events = None
        if args.events:
            events = trace_report.load_events(args.events)
            paths["events"] = args.events
        telemetry = None
        if args.telemetry:
            telemetry = load_telemetry(args.telemetry)
            paths["telemetry"] = args.telemetry
    except (OSError, ValueError) as e:
        print(f"run_report: {e}", file=sys.stderr)
        return EXIT_ERROR
    payload = build_report(trace_doc, events, telemetry, paths,
                           quick=args.quick)
    emit(payload, args.format, _render_report)
    return EXIT_FINDINGS if payload["findings"] else EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
