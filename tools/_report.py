"""Shared report emission for the repo CLIs.

All three tools (tpulint, trace_report, checkpoint_inspect) speak the
same ``--format {text,json}`` surface and the same exit-code
convention so CI can drive any of them uniformly:

  * ``EXIT_OK`` (0)       — clean / healthy,
  * ``EXIT_FINDINGS`` (1) — the tool found something actionable (lint
    violations, an empty checkpoint directory),
  * ``EXIT_ERROR`` (2)    — unusable input or an invalid newest
    artifact (unparseable trace, corrupt newest checkpoint).

JSON output is a single object on stdout with a ``tool`` tag so piped
consumers can dispatch on it.
"""

from __future__ import annotations

import argparse
import json
from typing import Any, Callable, Dict

EXIT_OK = 0
EXIT_FINDINGS = 1
EXIT_ERROR = 2


def add_format_arg(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--format", choices=("text", "json"),
                        default="text",
                        help="report format (default: text)")


def emit(payload: Dict[str, Any], fmt: str,
         text_renderer: Callable[[Dict[str, Any]], str]) -> None:
    """Print ``payload`` as JSON, or through ``text_renderer`` for the
    human view.  The payload must already carry a ``tool`` tag."""
    if fmt == "json":
        print(json.dumps(payload, indent=2, sort_keys=True, default=str))
    else:
        print(text_renderer(payload))
