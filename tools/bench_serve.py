#!/usr/bin/env python3
"""Serving-tier latency harness: p50/p95/p99 + rows/s per bucket.

Drives ``lightgbm_tpu.serving.PredictionServer`` with a mixed-shape
request stream (sizes spread across every bucket of the ladder) and
reports, per bucket:

  * ``p50_ms`` / ``p95_ms`` / ``p99_ms`` request latency,
  * ``rows_per_s`` steady-state throughput,
  * ``compile_s`` — the cold warmup compile cost the bucket paid ONCE
    at publish (the cost a live request never sees), split into
    ``lower_s`` (live XLA lowering) vs ``aot_load_s`` (deserialized
    from a disk AOT store — pass ``--aot-store DIR`` and run twice
    against the same directory to measure the warm-from-disk path),
  * ``run_s`` / ``requests`` — total warm time and request count.

The payload-level ``cold_warm_s`` sums the per-bucket warmup cost —
the cold-start tax a (re)spawned replica pays before it can serve,
which tools/bench_compare.py gates alongside p99.

It also captures ``steady_lowerings``: the ``xla_program_lowerings``
delta over the whole timed stream, which the serving contract says must
be ZERO (every request re-enters an already-compiled bucket program).

The JSON payload is tagged ``kind="serve"`` and feeds
tools/bench_compare.py, which gates on per-bucket p99 (lower is
better) with the usual 0/1/2 exit convention.

``--open-loop`` switches from the closed loop (next request leaves when
the previous one returns — measures service time) to an OPEN loop:
requests arrive on a fixed wall-clock schedule (``--rate`` per second)
regardless of completions, each dispatched from its own thread — the
queueing regime a real front-end sees, where a slow server builds
backlog instead of slowing the offered load.  With ``--replicas N`` the
open loop drives a ``serving.FleetServer`` (N replica processes behind
the failover router) instead of an in-process ``PredictionServer``; the
payload gains ``errors`` (requests that failed outright — the fleet
contract says 0) and ``achieved_rps``, and keeps the same
``overall``/``buckets`` p99 shape so bench_compare's serve gate reads
it unchanged.

Usage:
  python tools/bench_serve.py --requests 200 --trees 20 \
      --buckets 1,8,64,512 --out /tmp/SERVE_new.json --format json
  python tools/bench_serve.py --open-loop --rate 80 --replicas 3 \
      --requests 400 --buckets 1,8,64
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _report  # noqa: E402

import numpy as np  # noqa: E402


def _pcts(lat_s: List[float]) -> Dict[str, float]:
    a = np.asarray(lat_s, np.float64) * 1e3
    return {"p50_ms": float(np.percentile(a, 50)),
            "p95_ms": float(np.percentile(a, 95)),
            "p99_ms": float(np.percentile(a, 99))}


#: payload column -> span name (obs/reqtrace.py SPANS) it sums
_STAGE_SPANS = {"queue_ms": "replica_queue_wait",
                "pad_ms": "bucket_pad",
                "device_ms": "device_run",
                "gather_ms": "value_gather"}


def _stage_breakdown(traces: List[Dict[str, Any]]
                     ) -> Dict[int, Dict[str, float]]:
    """Mean per-stage milliseconds per bucket from kept request span
    trees (``PredictionServer.recent_traces``).  A request is attributed
    to the largest bucket its ``bucket_pad`` spans touched; stage time
    is the SUM of that span name's durations within the request (a
    request larger than the top bucket runs several chunks)."""
    acc: Dict[int, Dict[str, float]] = {}
    for t in traces:
        spans = t.get("spans") or []
        touched = [s["args"]["bucket"] for s in spans
                   if s.get("name") == "bucket_pad"
                   and "bucket" in (s.get("args") or {})]
        if not touched:
            continue
        b = max(touched)
        sums = {col: 0.0 for col in _STAGE_SPANS}
        for s in spans:
            for col, name in _STAGE_SPANS.items():
                if s.get("name") == name:
                    sums[col] += float(s.get("dur", 0.0)) / 1000.0
        row = acc.setdefault(b, dict({c: 0.0 for c in _STAGE_SPANS},
                                     n=0))
        row["n"] += 1
        for col in _STAGE_SPANS:
            row[col] += sums[col]
    out: Dict[int, Dict[str, float]] = {}
    for b, row in acc.items():
        n = max(row.pop("n"), 1)
        out[b] = {col: row[col] / n for col in _STAGE_SPANS}
    return out


def _request_sizes(buckets: List[int], requests: int,
                   rng: np.random.Generator) -> List[int]:
    """A request stream that exercises every bucket: sizes drawn
    uniformly from each bucket's (prev_bucket, bucket] range,
    interleaved so no bucket is measured only cold-cache."""
    ranges = []
    lo = 1
    for b in buckets:
        ranges.append((lo, b))
        lo = b + 1
    sizes = []
    for i in range(requests):
        lo_i, hi_i = ranges[i % len(ranges)]
        sizes.append(int(rng.integers(lo_i, hi_i + 1)))
    rng.shuffle(sizes)
    return sizes


def run(requests: int, features: int, trees: int, leaves: int,
        buckets: List[int], seed: int, raw_score: bool,
        aot_store: str = "") -> Dict[str, Any]:
    import jax

    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import compile_events
    from lightgbm_tpu.obs.metrics import global_metrics
    from lightgbm_tpu.serving import PredictionServer

    compile_events.install()

    def lowerings() -> int:
        return int(global_metrics.counter("xla_program_lowerings"))

    rng = np.random.default_rng(seed)
    n_train = max(4000, 4 * leaves)
    Xt = rng.normal(size=(n_train, features))
    y = np.sum(Xt[:, : max(1, features // 2)], axis=1) \
        + rng.normal(scale=0.1, size=n_train)
    booster = lgb.train(
        {"objective": "regression", "num_iterations": trees,
         "num_leaves": leaves, "min_data_in_leaf": 5, "verbosity": -1},
        lgb.Dataset(Xt, label=y))

    # tracing is on for the whole stream so every bucket row can carry
    # its queue/pad/device/gather breakdown (span sums are measured
    # INSIDE the request, so the percentile columns still time the same
    # code path operators serve with when they enable request_trace)
    params: Dict[str, Any] = {"serving_buckets": buckets,
                              "request_trace": "all"}
    if aot_store:
        params["aot_store"] = aot_store
    server = PredictionServer(params)
    t0 = time.perf_counter()
    server.publish("bench", booster=booster, warmup=True)
    publish_s = time.perf_counter() - t0
    compile_s = server.entry_compile_s()
    warm_detail = server.entry_warm_detail()

    sizes = _request_sizes(buckets, requests, rng)
    max_n = max(sizes)
    X = rng.normal(size=(max_n, features))

    # one extra pass over every bucket so the timed stream is pure
    # steady state, then assert zero lowerings across the whole stream
    for b in buckets:
        server.predict("bench", X[:b], raw_score=raw_score)
    base_lowerings = lowerings()

    per_bucket_lat: Dict[int, List[float]] = {b: [] for b in buckets}
    per_bucket_rows: Dict[int, int] = {b: 0 for b in buckets}
    all_lat: List[float] = []
    t_stream0 = time.perf_counter()
    for n in sizes:
        t1 = time.perf_counter()
        server.predict("bench", X[:n], raw_score=raw_score)
        dt = time.perf_counter() - t1
        b = server.ladder.bucket_for(n)
        per_bucket_lat[b].append(dt)
        per_bucket_rows[b] += n
        all_lat.append(dt)
    stream_s = time.perf_counter() - t_stream0
    steady = lowerings() - base_lowerings

    stages = _stage_breakdown(server.recent_traces())
    bucket_rows: Dict[str, Any] = {}
    for b in buckets:
        lat = per_bucket_lat[b]
        if not lat:
            continue
        run_s = float(sum(lat))
        row = _pcts(lat)
        row.update({
            "requests": len(lat),
            "rows": per_bucket_rows[b],
            "rows_per_s": per_bucket_rows[b] / run_s if run_s > 0 else 0.0,
            "run_s": run_s,
            "compile_s": float(compile_s.get(b, 0.0)),
            "lower_s": float(warm_detail.get(b, {}).get("lower_s", 0.0)),
            "aot_load_s": float(
                warm_detail.get(b, {}).get("aot_load_s", 0.0)),
        })
        if b in stages:
            row["stage_ms"] = {col: round(v, 4)
                               for col, v in stages[b].items()}
        bucket_rows[str(b)] = row
    overall = _pcts(all_lat)
    overall.update({"requests": len(all_lat),
                    "rows": int(sum(per_bucket_rows.values())),
                    "rows_per_s": sum(per_bucket_rows.values()) / stream_s
                    if stream_s > 0 else 0.0,
                    "run_s": stream_s})
    return {
        "tool": "bench_serve",
        "kind": "serve",
        "metric": "serve_latency_f%d_t%d_l%d" % (features, trees, leaves),
        "platform": jax.default_backend(),
        "requests": requests,
        "raw_score": raw_score,
        "buckets": bucket_rows,
        "overall": overall,
        "publish_s": publish_s,
        "compile_s_total": float(sum(compile_s.values())),
        "cold_warm_s": float(sum(d["total_s"]
                                 for d in warm_detail.values())),
        "steady_lowerings": int(steady),
        "counters": server.stats()["counters"],
    }


def run_open_loop(requests: int, features: int, trees: int, leaves: int,
                  buckets: List[int], seed: int, raw_score: bool,
                  rate: float, replicas: int) -> Dict[str, Any]:
    """Open-loop arrival generator: request ``i`` is dispatched at
    ``t0 + i/rate`` from its own thread whether or not earlier requests
    returned.  Latency therefore includes QUEUEING under backlog, which
    is the number an operator's p99 SLO is actually about."""
    import threading

    import jax

    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import BucketLadder

    rng = np.random.default_rng(seed)
    n_train = max(4000, 4 * leaves)
    Xt = rng.normal(size=(n_train, features))
    y = np.sum(Xt[:, : max(1, features // 2)], axis=1) \
        + rng.normal(scale=0.1, size=n_train)
    booster = lgb.train(
        {"objective": "regression", "num_iterations": trees,
         "num_leaves": leaves, "min_data_in_leaf": 5, "verbosity": -1},
        lgb.Dataset(Xt, label=y))

    params: Dict[str, Any] = {"serving_buckets": buckets}
    if replicas > 0:
        from lightgbm_tpu.serving import FleetServer
        params["serving_replicas"] = replicas
        target = FleetServer(params)
    else:
        from lightgbm_tpu.serving import PredictionServer
        target = PredictionServer(params)
    try:
        t0 = time.perf_counter()
        target.publish("bench", booster=booster)
        publish_s = time.perf_counter() - t0

        sizes = _request_sizes(buckets, requests, rng)
        X = rng.normal(size=(max(sizes), features))
        for b in buckets:            # steady state before the clock runs
            target.predict("bench", X[:b], raw_score=raw_score)

        ladder = BucketLadder(buckets)
        lock = threading.Lock()
        done: List[Any] = []         # (n, latency_s, error_or_None)

        def _one(n: int) -> None:
            t1 = time.perf_counter()
            err = None
            try:
                target.predict("bench", X[:n], raw_score=raw_score)
            except Exception as e:
                err = f"{type(e).__name__}: {e}"
            with lock:
                done.append((n, time.perf_counter() - t1, err))

        threads: List[threading.Thread] = []
        t_stream0 = time.perf_counter()
        for i, n in enumerate(sizes):
            due = t_stream0 + i / rate
            wait = due - time.perf_counter()
            if wait > 0:
                time.sleep(wait)
            th = threading.Thread(target=_one, args=(n,), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=60.0)
        stream_s = time.perf_counter() - t_stream0

        ok = [(n, dt) for n, dt, err in done if err is None]
        errors = [err for _, _, err in done if err is not None]
        per_bucket_lat: Dict[int, List[float]] = {b: [] for b in buckets}
        per_bucket_rows: Dict[int, int] = {b: 0 for b in buckets}
        for n, dt in ok:
            b = ladder.bucket_for(n)
            per_bucket_lat[b].append(dt)
            per_bucket_rows[b] += n
        bucket_rows: Dict[str, Any] = {}
        for b in buckets:
            lat = per_bucket_lat[b]
            if not lat:
                continue
            row = _pcts(lat)
            row.update({"requests": len(lat),
                        "rows": per_bucket_rows[b],
                        "rows_per_s": per_bucket_rows[b] / stream_s
                        if stream_s > 0 else 0.0,
                        "run_s": float(sum(lat)),
                        "compile_s": 0.0})
            bucket_rows[str(b)] = row
        overall = _pcts([dt for _, dt in ok]) if ok else \
            {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
        overall.update({"requests": len(ok),
                        "rows": int(sum(per_bucket_rows.values())),
                        "rows_per_s": sum(per_bucket_rows.values())
                        / stream_s if stream_s > 0 else 0.0,
                        "run_s": stream_s})
        return {
            "tool": "bench_serve",
            "kind": "serve",
            "mode": "open_loop",
            "metric": "serve_openloop_f%d_t%d_l%d_r%g"
                      % (features, trees, leaves, rate),
            "platform": jax.default_backend(),
            "requests": requests,
            "raw_score": raw_score,
            "rate_rps": float(rate),
            "achieved_rps": len(done) / stream_s if stream_s > 0 else 0.0,
            "replicas": int(replicas),
            "errors": len(errors),
            "error_samples": errors[:5],
            "buckets": bucket_rows,
            "overall": overall,
            "publish_s": publish_s,
            # warm cost and the recompile contract are measured by the
            # closed loop (in-process counters); replicas own their own
            "cold_warm_s": 0.0,
            "steady_lowerings": 0,
            "counters": {},
        }
    finally:
        if replicas > 0:
            target.close()


def _render_text(payload: Dict[str, Any]) -> str:
    lines = ["bench_serve: %s on %s (%d requests)"
             % (payload["metric"], payload["platform"],
                payload["requests"])]
    has_stages = any("stage_ms" in r
                     for r in payload["buckets"].values())
    hdr = "  %-8s %6s %9s %9s %9s %12s %9s" \
          % ("bucket", "reqs", "p50_ms", "p95_ms", "p99_ms",
             "rows_per_s", "compile_s")
    if has_stages:
        hdr += " %9s %8s %9s %9s" % ("queue_ms", "pad_ms",
                                     "device_ms", "gather_ms")
    lines.append(hdr)
    for b in sorted(payload["buckets"], key=int):
        r = payload["buckets"][b]
        row = "  %-8s %6d %9.3f %9.3f %9.3f %12.0f %9.3f" \
              % (b, r["requests"], r["p50_ms"], r["p95_ms"],
                 r["p99_ms"], r["rows_per_s"], r["compile_s"])
        st = r.get("stage_ms")
        if st is not None:
            row += " %9.3f %8.3f %9.3f %9.3f" \
                   % (st["queue_ms"], st["pad_ms"], st["device_ms"],
                      st["gather_ms"])
        elif has_stages:
            row += " %9s %8s %9s %9s" % ("-", "-", "-", "-")
        lines.append(row)
    o = payload["overall"]
    lines.append("  %-8s %6d %9.3f %9.3f %9.3f %12.0f"
                 % ("overall", o["requests"], o["p50_ms"], o["p95_ms"],
                    o["p99_ms"], o["rows_per_s"]))
    if payload.get("mode") == "open_loop":
        lines.append("  open loop: offered %.1f rps, achieved %.1f rps, "
                     "%d replica(s), %d error(s)"
                     % (payload["rate_rps"], payload["achieved_rps"],
                        payload["replicas"], payload["errors"]))
    else:
        lower = sum(r.get("lower_s", 0.0)
                    for r in payload["buckets"].values())
        aot = sum(r.get("aot_load_s", 0.0)
                  for r in payload["buckets"].values())
        lines.append("  cold warm: %.3fs (lowered %.3fs / aot-loaded "
                     "%.3fs)" % (payload.get("cold_warm_s", 0.0),
                                 lower, aot))
        lines.append("  steady-state lowerings: %d (contract: 0)"
                     % payload["steady_lowerings"])
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Serving-tier latency capture (p50/p95/p99 per "
                    "bucket); JSON feeds tools/bench_compare.py.")
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--features", type=int, default=8)
    ap.add_argument("--trees", type=int, default=20)
    ap.add_argument("--leaves", type=int, default=31)
    ap.add_argument("--buckets", default="1,8,64,512",
                    help="comma-separated serving bucket ladder")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--converted", action="store_true",
                    help="serve converted scores instead of raw margins")
    ap.add_argument("--open-loop", action="store_true",
                    help="fixed-rate arrivals (queueing regime) instead "
                         "of the closed measurement loop")
    ap.add_argument("--rate", type=float, default=50.0,
                    help="open-loop offered load, requests per second")
    ap.add_argument("--replicas", type=int, default=0,
                    help="open-loop only: drive a FleetServer with this "
                         "many replica processes (0 = in-process server)")
    ap.add_argument("--aot-store", default="",
                    help="closed loop only: warm serve programs through "
                         "this disk AOT store (run twice against the "
                         "same dir to measure the warm-from-disk path)")
    ap.add_argument("--out", default="",
                    help="also write the JSON payload to this path")
    _report.add_format_arg(ap)
    args = ap.parse_args(argv)
    try:
        buckets = sorted({int(b) for b in args.buckets.split(",") if b})
        if not buckets or any(b <= 0 for b in buckets):
            raise ValueError("--buckets needs positive row counts")
        if args.open_loop:
            if args.rate <= 0:
                raise ValueError("--rate needs a positive request rate")
            payload = run_open_loop(
                args.requests, args.features, args.trees, args.leaves,
                buckets, args.seed, raw_score=not args.converted,
                rate=args.rate, replicas=max(0, args.replicas))
        else:
            payload = run(args.requests, args.features, args.trees,
                          args.leaves, buckets, args.seed,
                          raw_score=not args.converted,
                          aot_store=args.aot_store)
    except ValueError as e:
        print("bench_serve: error: %s" % e, file=sys.stderr)
        return _report.EXIT_ERROR
    if args.out:
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
    _report.emit(payload, args.format, _render_text)
    # actionable findings: a broken zero-recompile contract (closed
    # loop) or failed client requests (open loop — the fleet contract
    # says failover absorbs replica faults)
    return _report.EXIT_FINDINGS \
        if (payload["steady_lowerings"] > 0
            or payload.get("errors", 0) > 0) else _report.EXIT_OK


if __name__ == "__main__":
    raise SystemExit(main())
