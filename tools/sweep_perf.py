"""Hardware perf sweep over grower configurations.

Usage:  python tools/sweep_perf.py k=28 k=28,dtype=float32

Each spec is comma-joined key=value pairs: k (split batch),
dtype (bfloat16/float32), warmup (0/1), iters, leaves.  Timing is
scan-chained inside one jit (docs/PERF_NOTES.md methodology).
"""
import json
import os
import sys
import time

import numpy as np

# run as `python tools/sweep_perf.py`: sys.path[0] is tools/, not the repo
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("BENCH_ROWS", "1000000")

import jax
import jax.numpy as jnp
from lightgbm_tpu.learner.batch_grower import grow_tree_batched
from lightgbm_tpu.ops.split import SplitHyper
from lightgbm_tpu.ops.table import take_small_table

N = int(os.environ["BENCH_ROWS"])
ITERS = int(os.environ.get("BENCH_ITERS", "5"))
# BENCH_BIN=63 exercises the reference GPU doc's speed configuration
# (docs/GPU-Performance.rst:100-123); bin width rounds up to a power of two
MAX_BIN = int(os.environ.get("BENCH_BIN", "255"))
from lightgbm_tpu.io.dataset import device_bins_pow2
N_BINS = device_bins_pow2(MAX_BIN)

rng = np.random.default_rng(0)
f = 28
w = rng.normal(size=f)
feat = rng.normal(size=(N, f)).astype(np.float32)
logits = feat @ w * 0.5
label = (logits + rng.normal(scale=1.0, size=N) > 0).astype(np.float32)
qs = np.quantile(feat[:100_000], np.linspace(0, 1, MAX_BIN)[1:-1], axis=0)
bins = np.empty((N, f), np.uint8)
for j in range(f):
    bins[:, j] = np.searchsorted(qs[:, j], feat[:, j]).astype(np.uint8)

bins_d = jnp.asarray(bins)
label_d = jnp.asarray(label)
num_bins = jnp.full((f,), MAX_BIN, jnp.int32)
nan_bin = jnp.full((f,), -1, jnp.int32)
is_cat = jnp.zeros((f,), bool)


def run_config(k, dtype="bfloat16", warmup=True, iters=ITERS,
               leaves=255):
    hp = SplitHyper(num_leaves=leaves, min_data_in_leaf=0,
                    min_sum_hessian_in_leaf=100.0, n_bins=N_BINS,
                    rows_per_block=8192, hist_dtype=dtype)

    # int8 kernels consume INTEGER gradient levels (the use_quantized_grad
    # contract) — raw logistic grads in (-1, 1) would truncate to zero,
    # collapse every tree and report a fantasy ms/tree.  Mirror the
    # production path: discretize to levels inside the step.
    quantize = dtype == "int8"
    if quantize:
        from lightgbm_tpu.ops.quantize import discretize_gradients_levels

    @jax.jit
    def run(scores, bins_a, label_a):
        def step(carry, i):
            scores = carry
            sign = jnp.where(label_a > 0, 1.0, -1.0)
            resp = -sign / (1.0 + jnp.exp(sign * scores))
            grad = resp
            hess = jnp.abs(resp) * (1.0 - jnp.abs(resp))
            hist_scale = None
            if quantize:
                key = jax.random.fold_in(jax.random.PRNGKey(7), i)
                grad, hess, gs, hs = discretize_gradients_levels(
                    grad, hess, key, n_levels=4, stochastic=True)
                hist_scale = jnp.stack([gs, hs])
            tree, leaf_of_row = grow_tree_batched(
                bins_a, grad, hess, None, num_bins, nan_bin, is_cat,
                None, hp, batch=k, warmup=warmup, hist_scale=hist_scale)
            return scores + 0.1 * take_small_table(tree.leaf_value,
                                                   leaf_of_row), None
        scores, _ = jax.lax.scan(step, scores, jnp.arange(iters))
        return scores

    scores = jnp.zeros(N, jnp.float32)
    t0 = time.time()
    out = run(scores, bins_d, label_d)
    float(out[0])
    compile_s = time.time() - t0
    t0 = time.time()
    out = run(scores, bins_d, label_d)
    float(out[0])
    elapsed = time.time() - t0
    ms_per_tree = elapsed / iters * 1000
    print(json.dumps({"k": k, "dtype": dtype,
                      "warmup": warmup, "ms_per_tree": round(ms_per_tree, 2),
                      "compile_s": round(compile_s, 1)}), flush=True)
    # A successful on-chip sweep is evidence worth keeping: persist it in
    # the bench cache (bench.py stale-fallback) — but ONLY when the config
    # is comparable to the headline bench (255-leaf trees at bench scale);
    # a small-tree sweep would inflate vs_baseline.
    try:
        if (jax.devices()[0].platform != "cpu" and leaves == 255
                and N >= 1_000_000 and warmup and MAX_BIN == 255):
            import bench as _bench
            _bench.record_cache({
                "metric": f"higgs_synth_{N}rows_{iters}iters_leaves{leaves}"
                          f"_sweep_k{k}",
                "value": round(elapsed, 3), "unit": "seconds",
                "vs_baseline": round(
                    _bench.BASELINE_S_PER_ROW_ITER * N * iters / elapsed, 4),
                "platform": jax.devices()[0].platform,
            }, mode="sweep")
    except Exception:
        pass
    return ms_per_tree


if __name__ == "__main__":
    for spec in sys.argv[1:]:
        parts = dict(p.split("=") for p in spec.split(","))
        run_config(int(parts.get("k", 20)),
                   parts.get("dtype", "bfloat16"),
                   parts.get("warmup", "1") == "1",
                   int(parts.get("iters", ITERS)),
                   int(parts.get("leaves", 255)))
