#!/usr/bin/env python
"""Scripted fault drill: kill -> detect -> reshape -> resume -> verify.

    python tools/fault_drill.py [--quick] [--rounds N] [--workers N]
                                [--format text|json]

Runs the elastic-recovery machinery (robustness/elastic.py) against
scripted faults (robustness/faults.py) on the virtual CPU mesh and
verifies the recovery CONTRACT, not just survival: the continued run's
model text must be bit-for-bit identical (modulo the serialized-params
trailer — ``model_core()``) to an uninterrupted run at the reduced mesh
size AND to the serial learner, and every checkpoint manifest in the
chain the resume walked must sha256-validate
(tools/checkpoint_inspect.py ``--verify-all`` semantics).

Scenarios (``--quick`` runs the first training one, the first serving
one AND the pipeline kill chain — together the tier-1 CI gate):

  kill        worker killed mid-run -> heartbeat silence -> eviction ->
              mesh reshape -> checkpoint resume -> bit-identity verify
  stall       worker pauses one round -> warned + counted
              (``elastic_slow_worker_rounds``), NOT evicted; final model
              identical to the undisturbed full-mesh run
  drop        worker stops publishing heartbeats but keeps computing ->
              evicted (observationally identical to death — documents
              the monitor's observability boundary)
  corrupt     newest checkpoint corrupted between kill and resume ->
              recovery falls back to the older checkpoint and STILL
              reproduces the reduced-mesh model bit-for-bit
  fail_fast   same kill with ``elastic=off`` -> today's fail-fast error,
              no recovery attempted

Serving-fleet scenarios (serving/fleet.py, PR 12):

  serve_kill        SIGKILL one of 3 replicas under client load ->
                    ZERO failed requests (in-flight work fails over),
                    eviction within ``fleet_heartbeat_timeout_s``,
                    respawn + warm-from-manifest + rejoin; the journal
                    narrates ``replica_dead -> replica_evicted ->
                    replica_spawned -> replica_rejoined``, and the
                    rejoining incarnation warms its whole bucket
                    ladder from the AOT executable store — its
                    journal-recorded ``warm_lowerings`` is 0
  serve_stall       SIGSTOP a replica for LESS than the heartbeat
                    timeout -> requests route around it, NO eviction,
                    replica serves again after SIGCONT
  serve_swap_abort  kill a replica mid rolling hot-swap -> rollout
                    aborts (``rolling_swap_aborted``), already-swapped
                    replicas roll back, every response carries exactly
                    one model version, fleet converges on the OLD one

Continuous-learning pipeline scenarios (pipeline/, PR 15):

  pipeline_kill       one workdir, a CHAIN of trainer processes: run i
                      is SIGKILLed (by itself, robustness/faults.py
                      ``pipeline_kill_hook``) the instant boundary i of
                      cycle 0 commits — ingest, boost, checkpoint,
                      export, publish — each successor resumes from the
                      cycle manifest, and the final run completes every
                      cycle.  Verified from durable artifacts: exports
                      bit-identical to an unkilled reference run, the
                      provenance version sequence 1..C with no gaps or
                      regressions, ZERO failed client requests across
                      every lifetime, the journal narrating each resume,
                      and the full checkpoint->export->publish sha chain
                      (checkpoint_inspect cycle mode).  Part of --quick.
  pipeline_swap_abort mid-rollout replica death while the PIPELINE is
                      publishing a cycle to a fleet -> rollout aborts,
                      the fence rolls the fleet back, and the SAME cycle
                      retries the SAME version after the fleet heals
                      (``pipeline_publish_retries``) — never skipping
                      forward

Sharded-ingest scenarios (io/sharded.py, PR 18; both part of --quick):

  ingest_host_kill      SIGKILL 1 of 3 ingest workers mid-pass-1 AND a
                        second mid-pass-2 -> survivors declare each dead
                        within ``heartbeat_timeout_s`` and steal the
                        orphaned stripes; zero stripes lost, every
                        stripe committed exactly once, and bins +
                        packed mirror + trained model bit-identical to
                        an unkilled single-host build of the same CSV
  pipeline_kill_sharded the pipeline kill chain with ``ingest_workers=1``:
                        run 0 SIGKILLs itself right after a stripe
                        commit inside cycle 0's collect, run 1 at the
                        ingest boundary — each successor resumes by
                        LOADING committed stripes (exactly-once across
                        lifetimes, journal-proven), exports bit-identical
                        to an unkilled sharded reference, version
                        sequence unchanged

Exit codes (tools/_report.py convention):
  0 — every scenario passed
  1 — a scenario's verification failed (recovery broken)
  2 — drill could not run (internal error)
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the drill needs a >1-device virtual mesh; both knobs must be set
# before jax (transitively: lightgbm_tpu) is imported
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=4 "
                               + os.environ.get("XLA_FLAGS", ""))

from _report import (EXIT_ERROR, EXIT_FINDINGS, EXIT_OK,  # noqa: E402
                     add_format_arg, emit)

#: deterministic quantized config — the regime ROBUSTNESS.md documents
#: as mesh-size-invariant, which is what makes bit-identity checkable
BASE_PARAMS = dict(objective="binary", num_leaves=7, learning_rate=0.5,
                   min_data_in_leaf=5, deterministic=True, seed=7,
                   use_quantized_grad=True, stochastic_rounding=False,
                   tree_learner="data", checkpoint_interval=2,
                   heartbeat_interval_s=0.2, heartbeat_timeout_s=1.0,
                   elastic="on", verbosity=-1,
                   # watchtower riding along: purely observational, so
                   # the bit-identity checks below still hold
                   slo_config="on", anomaly_detection="on",
                   rollup_window_s=0.5)

#: the watchtower knobs above — stripped from reference runs
_WATCHTOWER_KEYS = ("slo_config", "anomaly_detection", "rollup_window_s")


def _watchtower_summary(tail: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Breach/recovery/anomaly tallies for one scenario's journal tail."""
    import run_report
    slo = run_report.slo_stats(tail)
    return {"breaches": slo["breaches"], "recoveries": slo["recoveries"],
            "anomalies": slo["anomalies"],
            "unrecovered": slo["unrecovered"]}


def _data():
    import numpy as np
    rng = np.random.RandomState(0)
    X = rng.randint(0, 8, size=(200, 5)).astype(np.float64)
    y = (X[:, 0] + X[:, 1] > 7).astype(np.float64)
    return X, y


def _ref_model(X, y, rounds: int, mesh: int) -> str:
    """Uninterrupted reference at a fixed mesh size (serial when 1)."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.parallel.mesh import device_window
    from lightgbm_tpu.robustness.elastic import model_core
    p = {k: v for k, v in BASE_PARAMS.items()
         if k not in ("checkpoint_interval", "heartbeat_interval_s",
                      "heartbeat_timeout_s", "elastic")
         + _WATCHTOWER_KEYS}
    if mesh <= 1:
        p["tree_learner"] = "serial"
        booster = lgb.train(p, lgb.Dataset(X, label=y),
                            num_boost_round=rounds)
    else:
        with device_window(mesh):
            booster = lgb.train(p, lgb.Dataset(X, label=y),
                                num_boost_round=rounds)
    return model_core(booster.model_to_string())


def _verify_checkpoints(workdir: str) -> Dict[str, Any]:
    """checkpoint_inspect --verify-all over the session's chain."""
    import checkpoint_inspect
    payload = checkpoint_inspect.build_report(os.path.join(workdir, "ckpt"))
    return {"count": len(payload["checkpoints"]),
            "all_valid": bool(payload["all_valid"]),
            "invalid_count": payload["invalid_count"]}


def _run(X, y, rounds, workers, workdir, faults, **over):
    from lightgbm_tpu.obs.events import journal_tail
    from lightgbm_tpu.robustness.elastic import (model_core,
                                                 run_elastic_training)
    ev_path = os.path.join(workdir, "events.jsonl")
    params = dict(BASE_PARAMS, event_output=ev_path, **over)
    booster, rep = run_elastic_training(
        params, X, y, num_boost_round=rounds, n_workers=workers,
        workdir=workdir, faults=faults)
    return (model_core(booster.model_to_string()), rep,
            journal_tail(ev_path))


def scenario_kill(X, y, rounds, workers, corrupt_newest=False):
    from lightgbm_tpu.robustness.faults import (corrupt_checkpoint,
                                                kill_worker)
    kill_at = max(1, rounds // 2)
    callbacks = []
    if corrupt_newest:
        # corrupt the newest checkpoint the moment the kill lands, so
        # the recovery's resume="auto" must fall back one step
        def _corruptor(workdir):
            state = {"done": False}

            def _cb(env):
                if env.iteration >= kill_at and not state["done"]:
                    state["done"] = True
                    corrupt_checkpoint(os.path.join(workdir, "ckpt"),
                                       mode="garbage_manifest")
            _cb.order = 55    # after checkpoint (40), before liveness (60)
            return _cb
    with tempfile.TemporaryDirectory() as td:
        faults = [kill_worker(workers - 2, at_round=kill_at)]
        from lightgbm_tpu.obs.events import journal_tail
        from lightgbm_tpu.robustness.elastic import (ElasticSession,
                                                     model_core)
        cbs = [_corruptor(td)] if corrupt_newest else None
        ev_path = os.path.join(td, "events.jsonl")
        session = ElasticSession(dict(BASE_PARAMS, event_output=ev_path),
                                 X, y, num_boost_round=rounds,
                                 n_workers=workers, workdir=td,
                                 faults=faults, callbacks=cbs)
        booster = session.train()
        core = model_core(booster.model_to_string())
        rep = session.report.to_dict()
        ckpt = _verify_checkpoints(td)
        tail = journal_tail(ev_path)
    ref_reduced = _ref_model(X, y, rounds, workers - 1)
    ref_serial = _ref_model(X, y, rounds, 1)
    journaled = {e.get("event") for e in tail}
    checks = {
        "evicted": len(rep["evictions"]) == 1,
        "reshaped": rep["final_mesh"] == workers - 1,
        "resumed": rep["resumes"] >= 1,
        "bit_identical_reduced_mesh": core == ref_reduced,
        "bit_identical_serial": core == ref_serial,
        # the structured journal must narrate the same recovery the
        # elastic report claims (obs/events.py)
        "journal_narrates_recovery": {"worker_evicted", "mesh_reshape",
                                      "training_resumed"} <= journaled,
        # on the corrupt drill the newest checkpoint is broken BY DESIGN;
        # what matters is that recovery still landed bit-exact off the
        # older one — so the chain check is only asserted when clean
        "checkpoint_chain_valid": (True if corrupt_newest
                                   else ckpt["all_valid"]),
    }
    return {"name": "corrupt" if corrupt_newest else "kill",
            "kill_at_round": kill_at, "checks": checks,
            "checkpoints": ckpt, "elastic_report": rep,
            "journal_tail": tail,
            "watchtower": _watchtower_summary(tail),
            "passed": all(checks.values())}


def scenario_stall(X, y, rounds, workers):
    from lightgbm_tpu.robustness.faults import stall_worker
    with tempfile.TemporaryDirectory() as td:
        core, rep, tail = _run(X, y, rounds, workers, td,
                               [stall_worker(1, seconds=0.5, at_round=2)])
    ref_full = _ref_model(X, y, rounds, workers)
    checks = {
        "warned_not_evicted": rep["slow_rounds"] >= 1,
        "no_eviction": not rep["evictions"],
        "bit_identical_full_mesh": core == ref_full,
    }
    return {"name": "stall", "checks": checks, "elastic_report": rep,
            "journal_tail": tail,
            "watchtower": _watchtower_summary(tail),
            "passed": all(checks.values())}


def scenario_drop(X, y, rounds, workers):
    from lightgbm_tpu.robustness.faults import drop_heartbeats
    with tempfile.TemporaryDirectory() as td:
        core, rep, tail = _run(X, y, rounds, workers, td,
                               [drop_heartbeats(workers - 1, at_round=2)])
    ref_reduced = _ref_model(X, y, rounds, workers - 1)
    checks = {
        "evicted": len(rep["evictions"]) == 1,
        "bit_identical_reduced_mesh": core == ref_reduced,
    }
    return {"name": "drop", "checks": checks, "elastic_report": rep,
            "journal_tail": tail,
            "watchtower": _watchtower_summary(tail),
            "passed": all(checks.values())}


def scenario_fail_fast(X, y, rounds, workers):
    from lightgbm_tpu.robustness.faults import kill_worker
    from lightgbm_tpu.utils.log import LightGBMError
    failed_fast, detail = False, ""
    tail: List[Dict[str, Any]] = []
    try:
        with tempfile.TemporaryDirectory() as td:
            _run(X, y, rounds, workers, td,
                 [kill_worker(0, at_round=1)], elastic="off")
        detail = "no error raised"
    except LightGBMError as e:
        failed_fast, detail = True, str(e)
    checks = {"failed_fast": failed_fast,
              "no_recovery_attempted": "elastic=on" in detail}
    return {"name": "fail_fast", "detail": detail, "checks": checks,
            "journal_tail": tail,
            "watchtower": _watchtower_summary(tail),
            "passed": all(checks.values())}


# ------------------------------------------------------------ serving fleet
#: 3 replicas, sub-second liveness, tiny two-bucket ladder — the
#: smallest fleet where "kill one" leaves a quorum to fail over to
_SERVE_PARAMS = dict(serving_buckets=[1, 8], serving_replicas=3,
                     serving_retry_budget=2,
                     fleet_heartbeat_interval_s=0.2,
                     fleet_heartbeat_timeout_s=1.0,
                     slo_config="on", rollup_window_s=0.5,
                     request_trace="errors", verbosity=-1)


def _failover_trace(traces):
    """The first kept span tree showing a completed failover: ≥2
    attempt spans, the first erroring, a later one succeeding on a
    DIFFERENT slot, with the winning attempt's replica-side
    ``replica_serve`` span grafted under it (obs/reqtrace.py)."""
    for t in traces:
        spans = t.get("spans") or []
        attempts = sorted((s for s in spans if s.get("name") == "attempt"),
                          key=lambda s: s.get("ts", 0.0))
        if len(attempts) < 2:
            continue
        first, ok_att = attempts[0], None
        if (first.get("args") or {}).get("outcome") != "error":
            continue
        for a in attempts[1:]:
            args = a.get("args") or {}
            if args.get("outcome") == "ok" and \
                    args.get("slot") != (first.get("args") or {}).get("slot"):
                ok_att = a
                break
        if ok_att is None:
            continue
        served = [s for s in spans if s.get("name") == "replica_serve"
                  and s.get("parent") == ok_att.get("span_id")]
        if served:
            return t, first, ok_att
    return None, None, None


def _serve_boosters(X, y):
    """Two tiny distinguishable models: v1 to serve, v2 to roll to."""
    import lightgbm_tpu as lgb
    p = dict(objective="binary", num_leaves=7, min_data_in_leaf=5,
             deterministic=True, seed=7, verbosity=-1)
    b1 = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=3)
    b2 = lgb.train(dict(p, learning_rate=0.3), lgb.Dataset(X, label=y),
                   num_boost_round=3)
    return b1, b2


def _journal_events(path: str) -> List[str]:
    from lightgbm_tpu.obs.events import read_journal
    return [e.get("event", "?") for e in read_journal(path)]


def _rejoin_lowerings(path: str) -> List[int]:
    """``warm_lowerings`` of every journal ``replica_rejoined`` whose
    incarnation is a respawn (>= 1).  The AOT-store rejoin contract
    says each is 0: the replica warmed its whole bucket ladder from
    the disk store, paying zero XLA lowerings."""
    from lightgbm_tpu.obs.events import read_journal
    out: List[int] = []
    for e in read_journal(path):
        if e.get("event") != "replica_rejoined":
            continue
        p = e.get("payload") or {}
        if int(p.get("incarnation", 0)) >= 1:
            out.append(int(p.get("warm_lowerings", -1)))
    return out


def _eviction_ordered(evs: List[str]) -> bool:
    """``replica_dead -> replica_evicted -> replica_spawned ->
    replica_rejoined`` in order, starting the search at the death (the
    startup ``replica_spawned`` burst precedes it and must not
    satisfy the respawn step)."""
    i = 0
    try:
        for name in ("replica_dead", "replica_evicted",
                     "replica_spawned", "replica_rejoined"):
            i = evs.index(name, i) + 1
    except ValueError:
        return False
    return True


def scenario_serve_kill(X, y):
    import time

    from lightgbm_tpu.robustness.faults import kill_replica
    from lightgbm_tpu.serving import FleetServer
    b1, _ = _serve_boosters(X, y)
    errs: List[str] = []
    versions = set()
    evict_s = None
    with tempfile.TemporaryDirectory() as td:
        ev = os.path.join(td, "serve_events.jsonl")
        fleet = FleetServer(dict(_SERVE_PARAMS, event_output=ev),
                            workdir=td)
        try:
            fleet.publish("m", booster=b1)
            timeout_s = fleet.hb_timeout_s
            t0 = time.monotonic()
            killed_at = None
            while time.monotonic() - t0 < 45.0:
                try:
                    r = fleet.predict_ex("m", X[:3], deadline_ms=10_000)
                    versions.add(r["version"])
                except Exception as e:          # noqa: BLE001 — tallied
                    errs.append(f"{type(e).__name__}: {e}")
                now = time.monotonic()
                if killed_at is None and now - t0 >= 0.5:
                    fleet.inject(kill_replica(0))
                    killed_at = now
                    # burst back-to-back requests into the detection
                    # window so at least one is routed AT the dead slot
                    # and visibly fails over (the span tree the PR13
                    # checks below read); pacing 0.02s per request
                    # would race the monitor's process-exit poll
                    while (fleet.metrics.counter(
                               "fleet_request_failovers") < 1
                           and fleet.states().get(0) == "healthy"
                           and time.monotonic() - killed_at < 5.0):
                        try:
                            r = fleet.predict_ex("m", X[:3],
                                                 deadline_ms=10_000)
                            versions.add(r["version"])
                        except Exception as e:  # noqa: BLE001
                            errs.append(f"{type(e).__name__}: {e}")
                if killed_at is not None and evict_s is None and \
                        fleet.metrics.counter(
                            "fleet_replica_respawns") >= 1:
                    evict_s = now - killed_at
                if evict_s is not None and all(
                        s == "healthy"
                        for s in fleet.states().values()):
                    break                       # respawn rejoined
                time.sleep(0.02)
            recovered = all(s == "healthy"
                            for s in fleet.states().values())
            failovers = int(fleet.metrics.counter(
                "fleet_request_failovers"))
            traces = fleet.recent_traces()
        finally:
            fleet.close()
        evs = _journal_events(ev)
        rejoin_low = _rejoin_lowerings(ev)
        from lightgbm_tpu.obs.events import journal_tail
        tail = journal_tail(ev)
        # the victim's crash flight recorder: slot 0 died in its first
        # incarnation, so the dump (written by the replica's SIGTERM
        # handler, or by the router on kill detection from the last
        # heartbeat snapshot) lands at flight/flight.e0.r0.json
        from lightgbm_tpu.obs.reqtrace import read_snapshot
        dump_path = os.path.join(td, "flight", "flight.e0.r0.json")
        flight = read_snapshot(dump_path)
    ftrace, att_fail, att_ok = _failover_trace(traces)
    checks = {
        "zero_failed_requests": not errs,
        "failover_absorbed_kill": failovers >= 1
        and "request_failover" in evs,
        "evicted_within_timeout": evict_s is not None
        and evict_s <= timeout_s + 1.0,
        "respawned_and_rejoined": recovered
        and "replica_rejoined" in evs,
        "journal_ordered": _eviction_ordered(evs),
        "single_version_responses": versions == {1},
        # PR13: the kept span tree must SHOW the failover — attempt 1
        # erroring on the killed slot, a later attempt succeeding on a
        # different replica with its grafted replica-side spans
        "trace_shows_failover": ftrace is not None
        and (att_fail.get("args") or {}).get("slot") == 0,
        "flight_dump_recovered": flight is not None
        and (flight.get("meta") or {}).get("slot") == 0
        and (flight.get("meta") or {}).get("incarnation") == 0,
        # PR16: the respawn must rejoin through the AOT executable
        # store — its warm pass re-lowers NOTHING (journal-recorded
        # xla_program_lowerings delta of the rejoining incarnation)
        "rejoined_via_aot_store": bool(rejoin_low)
        and all(n == 0 for n in rejoin_low),
    }
    out = {"name": "serve_kill", "checks": checks,
           "eviction_latency_s": evict_s, "failovers": failovers,
           "rejoin_warm_lowerings": rejoin_low,
           "request_errors": errs[:5], "journal_tail": tail,
           "watchtower": _watchtower_summary(tail),
           "passed": all(checks.values())}
    if ftrace is not None:
        out["failover_trace"] = {
            "trace_id": ftrace.get("trace_id"),
            "keep_reason": ftrace.get("keep_reason"),
            "attempts": sum(1 for s in ftrace.get("spans", ())
                            if s.get("name") == "attempt"),
            "failed_slot": (att_fail.get("args") or {}).get("slot"),
            "served_slot": (att_ok.get("args") or {}).get("slot"),
        }
    if flight is not None:
        # the victim's final seconds, embedded for the postmortem
        meta = flight.get("meta") or {}
        out["flight_dump"] = {
            "reason": flight.get("reason"),
            "slot": meta.get("slot"),
            "incarnation": meta.get("incarnation"),
            "pid": meta.get("pid"),
            "spans": len(flight.get("spans") or ()),
            "events": len(flight.get("events") or ()),
            "last_events": [e.get("event") for e in
                            (flight.get("events") or [])[-5:]],
        }
    return out


def scenario_serve_stall(X, y):
    import time

    from lightgbm_tpu.robustness.faults import stall_replica
    from lightgbm_tpu.serving import FleetServer
    b1, _ = _serve_boosters(X, y)
    errs: List[str] = []
    with tempfile.TemporaryDirectory() as td:
        ev = os.path.join(td, "serve_events.jsonl")
        fleet = FleetServer(dict(_SERVE_PARAMS, event_output=ev),
                            workdir=td)
        try:
            fleet.publish("m", booster=b1)
            fleet.inject(stall_replica(1, seconds=0.5))
            t0 = time.monotonic()
            while time.monotonic() - t0 < 3.0:   # through stall + resume
                try:
                    fleet.predict("m", X[:3], deadline_ms=10_000)
                except Exception as e:          # noqa: BLE001 — tallied
                    errs.append(f"{type(e).__name__}: {e}")
                time.sleep(0.02)
            respawns = int(fleet.metrics.counter(
                "fleet_replica_respawns"))
            healthy_after = all(s == "healthy"
                                for s in fleet.states().values())
        finally:
            fleet.close()
        evs = _journal_events(ev)
        from lightgbm_tpu.obs.events import journal_tail
        tail = journal_tail(ev)
    checks = {
        "zero_failed_requests": not errs,
        "not_evicted": respawns == 0 and "replica_evicted" not in evs,
        "serves_after_resume": healthy_after,
    }
    return {"name": "serve_stall", "checks": checks,
            "request_errors": errs[:5], "journal_tail": tail,
            "watchtower": _watchtower_summary(tail),
            "passed": all(checks.values())}


def scenario_serve_swap_abort(X, y):
    import threading
    import time

    from lightgbm_tpu.robustness.faults import kill_replica
    from lightgbm_tpu.serving import FleetServer, RollingSwapAborted
    b1, b2 = _serve_boosters(X, y)
    errs: List[str] = []
    versions = set()
    outcome: Dict[str, Any] = {}
    with tempfile.TemporaryDirectory() as td:
        ev = os.path.join(td, "serve_events.jsonl")
        fleet = FleetServer(dict(_SERVE_PARAMS, event_output=ev),
                            workdir=td)
        try:
            v1 = fleet.publish("m", booster=b1)

            stop = threading.Event()

            def _load() -> None:
                while not stop.is_set():
                    try:
                        r = fleet.predict_ex("m", X[:3],
                                             deadline_ms=10_000)
                        versions.add(r["version"])
                    except Exception as e:      # noqa: BLE001 — tallied
                        errs.append(f"{type(e).__name__}: {e}")
                    time.sleep(0.01)

            loader = threading.Thread(target=_load, daemon=True)
            loader.start()

            # the drill seam fires after each per-replica swap: the
            # moment slot 0 took v2, kill slot 2 — the rollout MUST
            # notice (dead socket or bumped incarnation) and abort
            killed = {"done": False}

            def _mid_swap_kill(slot: int) -> None:
                if slot == 0 and not killed["done"]:
                    killed["done"] = True
                    fleet.inject(kill_replica(2))

            fleet.swap_fault_hook = _mid_swap_kill
            try:
                outcome["version"] = fleet.publish("m", booster=b2)
            except RollingSwapAborted as e:
                outcome["aborted"] = str(e)
            finally:
                fleet.swap_fault_hook = None

            # convergence: killed replica respawns warming the OLD
            # manifest (the abort never committed v2)
            deadline = time.monotonic() + 45.0
            while time.monotonic() < deadline:
                if all(s == "healthy"
                       for s in fleet.states().values()):
                    break
                time.sleep(0.1)
            stop.set()
            loader.join(timeout=15.0)
            live = fleet.replica_versions()
            manifest = fleet.registry.current("m")
        finally:
            fleet.close()
        evs = _journal_events(ev)
        from lightgbm_tpu.obs.events import journal_tail
        tail = journal_tail(ev)
    checks = {
        "rollout_aborted": "aborted" in outcome,
        "journal_has_abort": "rolling_swap_aborted" in evs,
        "manifest_kept_old_version":
            manifest is not None and int(manifest["version"]) == v1,
        "fleet_converged_on_old_version":
            bool(live) and all(m.get("m") == v1 for m in live.values()),
        "zero_failed_requests": not errs,
        # the version fence: every response is entirely one version —
        # v1 before/after, possibly v2 from an already-swapped replica
        # mid-rollout, never anything else
        "single_version_responses": versions <= {1, 2} and 1 in versions,
    }
    return {"name": "serve_swap_abort", "checks": checks,
            "outcome": outcome, "versions_observed": sorted(versions),
            "request_errors": errs[:5], "journal_tail": tail,
            "watchtower": _watchtower_summary(tail),
            "passed": all(checks.values())}


# ------------------------------------------------- sharded ingest
def _read_all_journals(path: str) -> List[Dict[str, Any]]:
    """The coordinator journal plus every per-rank worker journal the
    sharded ingest derived from it, concatenated."""
    from lightgbm_tpu.obs.events import read_journal
    from lightgbm_tpu.obs.merge import find_rank_files
    events = list(read_journal(path)) if os.path.exists(path) else []
    for rank_path in find_rank_files(path):
        events.extend(read_journal(rank_path))
    return events


def scenario_ingest_host_kill():
    """SIGKILL one of three sharded-ingest workers mid-pass-1 and a
    second mid-pass-2 (io/sharded.py).  The survivors must declare each
    dead within ``heartbeat_timeout_s``, steal its orphaned stripes,
    and the merged dataset — bins, packed mirror, trained model — must
    be bit-identical to an unkilled single-host build of the same CSV:
    the stripe ledger's order-invariance contract."""
    import time

    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.sharded import (PASS_BIN, PASS_SKETCH,
                                         committed_stripes,
                                         shard_stream_dataset)
    from lightgbm_tpu.io.streaming import TextStripeSource, stream_dataset
    from lightgbm_tpu.obs import events as obs_events
    from lightgbm_tpu.obs.events import journal_tail, read_journal
    from lightgbm_tpu.obs.merge import rank_file_path
    from lightgbm_tpu.robustness.elastic import model_core
    timeout_s = float(BASE_PARAMS["heartbeat_timeout_s"])
    ingest_params = dict(verbosity=-1,
                         heartbeat_interval_s=BASE_PARAMS[
                             "heartbeat_interval_s"],
                         heartbeat_timeout_s=timeout_s)
    train_params = dict(objective="binary", num_leaves=7,
                        min_data_in_leaf=5, deterministic=True, seed=7,
                        verbosity=-1)
    rng = np.random.RandomState(5)
    X = rng.normal(size=(1200, 5))
    yv = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    with tempfile.TemporaryDirectory() as td:
        csv = os.path.join(td, "drill.csv")
        with open(csv, "w") as fh:
            for i in range(X.shape[0]):
                fh.write(",".join([f"{yv[i]:.0f}"]
                                  + [f"{v:.6f}" for v in X[i]]) + "\n")
        stripe_bytes = 6000        # ~10 stripes over the ~55KB file
        ev = os.path.join(td, "ingest_events.jsonl")
        sh_wd = os.path.join(td, "sharded")
        # arm on the FIRST claim of the pass: the go barrier guarantees
        # every worker enters the claim race, so a first-claim kill
        # always fires (a later-claim kill can be starved out when the
        # survivors drain the stripe universe first)
        faults = {0: {"pass": PASS_SKETCH, "after_stripes": 0},
                  1: {"pass": PASS_BIN, "after_stripes": 0}}
        with obs_events.session(ev):
            src = TextStripeSource(csv, Config(dict(ingest_params)),
                                   stripe_bytes=stripe_bytes)
            ds = shard_stream_dataset(
                src, params=dict(ingest_params, ingest_workers=3),
                workdir=sh_wd, faults=faults)
        booster = lgb.train(train_params, ds, num_boost_round=5)
        core = model_core(booster.model_to_string())

        src_ref = TextStripeSource(csv, Config(dict(ingest_params)),
                                   stripe_bytes=stripe_bytes)
        ref_wd = os.path.join(td, "single")
        ds_ref = stream_dataset(src_ref, params=dict(ingest_params),
                                workdir=ref_wd)
        booster_ref = lgb.train(train_params, ds_ref, num_boost_round=5)
        core_ref = model_core(booster_ref.model_to_string())

        def _bytes(wd, name):
            with open(os.path.join(wd, name), "rb") as fh:
                return fh.read()
        bins_identical = _bytes(sh_wd, "bins.u8") == _bytes(ref_wd,
                                                            "bins.u8")
        packed_identical = _bytes(sh_wd, "packed.i32") == \
            _bytes(ref_wd, "packed.i32")

        import json as _json
        with open(os.path.join(sh_wd, "stripe_ledger.json")) as fh:
            S = int(_json.load(fh)["num_stripes"])
        p1_done = committed_stripes(sh_wd, PASS_SKETCH, S)
        p2_done = committed_stripes(sh_wd, PASS_BIN, S)

        events = _read_all_journals(ev)
        tail = journal_tail(ev)
        # reassignment latency per killed rank: from its journal's last
        # record (the moment it went silent) to the survivor's steal
        latency = {}
        for dead_rank in faults:
            rank_ev = list(read_journal(rank_file_path(ev, 0, dead_rank)))
            last = max((e.get("unix_time") or 0.0) for e in rank_ev) \
                if rank_ev else None
            steal = min((e.get("unix_time") or 0.0) for e in events
                        if e.get("event") == "ingest_stripe_reassigned"
                        and (e.get("payload") or {}).get("from_rank")
                        == dead_rank) if any(
                e.get("event") == "ingest_stripe_reassigned"
                and (e.get("payload") or {}).get("from_rank") == dead_rank
                for e in events) else None
            latency[dead_rank] = (round(steal - last, 3)
                                  if last and steal else None)
    done = [(str((e.get("payload") or {}).get("stage")),
             (e.get("payload") or {}).get("shard"))
            for e in events if e.get("event") == "ingest_shard_done"]
    reassigned = [e for e in events
                  if e.get("event") == "ingest_stripe_reassigned"]
    dead_ranks = {(e.get("payload") or {}).get("dead_rank")
                  for e in events
                  if e.get("event") == "ingest_worker_dead"}
    checks = {
        # every stripe of both passes committed exactly once — none
        # lost with its dead owner, none redone after its commit
        "zero_stripes_lost": p1_done == set(range(S))
        and p2_done == set(range(S)),
        "exactly_once_commits": len(done) == 2 * S
        and len(set(done)) == 2 * S,
        "both_workers_declared_dead": {0, 1} <= dead_ranks,
        "orphans_reassigned": len(reassigned) >= 2
        and {0, 1} <= {(e.get("payload") or {}).get("from_rank")
                       for e in reassigned},
        # steal landed within the liveness budget (heartbeat_timeout_s
        # + scheduling slack: the survivor steals on its next sweep)
        "reassigned_within_timeout": all(
            v is not None and v <= timeout_s + 2.0
            for v in latency.values()),
        "bins_bit_identical": bins_identical,
        "packed_bit_identical": packed_identical,
        "model_bit_identical": core == core_ref,
    }
    return {"name": "ingest_host_kill", "stripes": S,
            "reassignment_latency_s": latency,
            "reassigned": len(reassigned), "checks": checks,
            "journal_tail": tail,
            "watchtower": _watchtower_summary(tail),
            "passed": all(checks.values())}


# ------------------------------------------------- continuous pipeline
#: tiny deterministic continuation config for the pipeline drills: 2
#: rounds per cycle, checkpoint every round, 3 chunks of 96 rows
_PIPE_PARAMS = dict(objective="binary", num_leaves=4, min_data_in_leaf=5,
                    deterministic=True, seed=3, verbosity=-1,
                    publish_interval=2, checkpoint_interval=1)
_PIPE_CYCLES = 3


def _pipeline_spec(td: str, workdir: str, kill=None,
                   extra_params=None) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "seed": 11, "num_chunks": _PIPE_CYCLES, "rows_per_chunk": 96,
        "num_features": 5, "name": "pipe", "num_cycles": _PIPE_CYCLES,
        "chunks_per_cycle": 1,
        "client_log": os.path.join(td, "client.jsonl"),
        "params": dict(_PIPE_PARAMS, pipeline_workdir=workdir,
                       event_output=os.path.join(td, "pipe_events.jsonl")),
    }
    if extra_params:
        spec["params"].update(extra_params)
    if kill is not None:
        spec["kill"] = kill
    return spec


def _pipeline_child(td: str, i: int, spec: Dict[str, Any]):
    """One trainer lifetime as a real OS process (so the armed SIGKILL
    is a true no-cleanup crash).  Returns (returncode, stdout)."""
    import json
    import subprocess
    spath = os.path.join(td, f"spec_{i}.json")
    with open(spath, "w") as fh:
        json.dump(spec, fh)
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.pipeline.drill", spath],
        capture_output=True, text=True, timeout=300)
    return proc.returncode, proc.stdout


def _client_observations(path: str):
    """Parse the hammer log, skipping a final line torn by the SIGKILL
    (a half-written record is evidence of the crash, not of a failed
    request)."""
    import json
    obs = []
    if os.path.exists(path):
        with open(path) as fh:
            for line in fh:
                try:
                    obs.append(json.loads(line))
                except ValueError:
                    continue
    return obs


def _published_versions(events) -> List[int]:
    return [int((e.get("payload") or {}).get("version", -1))
            for e in events if e.get("event") == "cycle_published"]


def scenario_pipeline_kill():
    import json
    import signal

    import checkpoint_inspect
    from lightgbm_tpu.obs.events import journal_tail, read_journal
    from lightgbm_tpu.pipeline import BOUNDARIES
    from lightgbm_tpu.pipeline.drill import run_spec
    boundaries_hit: List[Dict[str, Any]] = []
    with tempfile.TemporaryDirectory() as td:
        wd = os.path.join(td, "wd")
        # the kill chain: run i nukes itself at boundary i of cycle 0,
        # its successor resumes from the manifest and dies at the next
        # boundary; the last run finishes every cycle
        for i, boundary in enumerate(BOUNDARIES):
            rc, _ = _pipeline_child(
                td, i, _pipeline_spec(td, wd,
                                      kill={"boundary": boundary,
                                            "cycle": 0}))
            boundaries_hit.append({"boundary": boundary, "rc": rc,
                                   "sigkilled": rc == -signal.SIGKILL})
        rc, out = _pipeline_child(td, len(BOUNDARIES),
                                  _pipeline_spec(td, wd))
        summary = {}
        if rc == 0 and out.strip():
            summary = json.loads(out.strip().splitlines()[-1])
        # the unkilled reference: same spec, fresh workdir, in-process
        ref_td = os.path.join(td, "ref")
        os.makedirs(ref_td)
        ref_wd = os.path.join(ref_td, "wd")
        ref_spec = _pipeline_spec(ref_td, ref_wd)
        ref_spec.pop("client_log")
        run_spec(ref_spec)

        def _export(base, c):
            p = os.path.join(base, "exports", f"cycle_{c:04d}.txt")
            with open(p) as fh:
                return fh.read()
        bit_identical = all(
            _export(wd, c) == _export(ref_wd, c)
            for c in range(_PIPE_CYCLES))
        prov = json.load(open(os.path.join(wd, "provenance.json")))
        versions = sorted(int(v) for v in
                          (prov.get("models", {}).get("pipe") or {}))
        obs = _client_observations(os.path.join(td, "client.jsonl"))
        client_errs = [o for o in obs if not o.get("ok")]
        served = [int(o["version"]) for o in obs if o.get("ok")]
        ev_path = os.path.join(td, "pipe_events.jsonl")
        events = read_journal(ev_path)
        names = [e.get("event") for e in events]
        tail = journal_tail(ev_path)
        chain = checkpoint_inspect.build_pipeline_report(wd)
    want = list(range(1, _PIPE_CYCLES + 1))
    checks = {
        # (every armed run must die by ITS OWN SIGKILL, not exit)
        "killed_at_every_boundary":
            all(b["sigkilled"] for b in boundaries_hit),
        "resume_completed_all_cycles": rc == 0
        and summary.get("cycles_completed") == _PIPE_CYCLES,
        # (a): resumed lineage's exports == unkilled run's, bit-for-bit
        "bit_identical_exports": bit_identical,
        # (b): version sequence strictly monotone, no gaps/regressions
        "versions_monotone_no_gaps": versions == want
        and _published_versions(events) == want
        and served == sorted(served),
        # (c): zero client requests failed across every lifetime
        "zero_failed_requests": not client_errs and bool(served),
        "journal_narrates_resumes":
            names.count("cycle_resumed") >= len(BOUNDARIES)
        and names.index("cycle_started") < names.index("cycle_ingested")
        < names.index("cycle_published"),
        "cycle_chain_valid": bool(chain["all_valid"]),
    }
    return {"name": "pipeline_kill", "boundaries": boundaries_hit,
            "cycles": summary.get("cycles_completed"),
            "versions": versions, "client_requests": len(obs),
            "client_errors": [o.get("error") for o in client_errs[:5]],
            "checks": checks, "journal_tail": tail,
            "watchtower": _watchtower_summary(tail),
            "passed": all(checks.values())}


def scenario_pipeline_kill_sharded():
    """The pipeline kill chain with sharded ingest on
    (``ingest_workers=1``): run 0 SIGKILLs itself right after a stripe
    COMMIT inside cycle 0's collect (the ``ingest_stripe`` boundary the
    phase hook cannot reach), run 1 resumes — it must LOAD the committed
    stripe, never re-stream it — and dies at the ingest boundary, and
    the final run completes every cycle.  Exactly-once is asserted from
    the journal (one ``ingest_shard_done`` per ledger+stripe across
    every lifetime) and the exports must be bit-identical to an
    unkilled sharded reference."""
    import json
    import signal

    import checkpoint_inspect
    from lightgbm_tpu.obs.events import journal_tail, read_journal
    from lightgbm_tpu.pipeline.drill import run_spec
    extra = {"ingest_workers": 1}
    kills = [{"boundary": "ingest_stripe", "cycle": 0, "stripe": 0},
             {"boundary": "ingest", "cycle": 0}]
    boundaries_hit: List[Dict[str, Any]] = []
    with tempfile.TemporaryDirectory() as td:
        wd = os.path.join(td, "wd")
        for i, kill in enumerate(kills):
            rc, _ = _pipeline_child(
                td, i, _pipeline_spec(td, wd, kill=kill,
                                      extra_params=extra))
            boundaries_hit.append({"boundary": kill["boundary"],
                                   "rc": rc,
                                   "sigkilled": rc == -signal.SIGKILL})
        rc, out = _pipeline_child(td, len(kills),
                                  _pipeline_spec(td, wd,
                                                 extra_params=extra))
        summary = {}
        if rc == 0 and out.strip():
            summary = json.loads(out.strip().splitlines()[-1])
        ref_td = os.path.join(td, "ref")
        os.makedirs(ref_td)
        ref_wd = os.path.join(ref_td, "wd")
        ref_spec = _pipeline_spec(ref_td, ref_wd, extra_params=extra)
        ref_spec.pop("client_log")
        run_spec(ref_spec)

        def _export(base, c):
            p = os.path.join(base, "exports", f"cycle_{c:04d}.txt")
            with open(p) as fh:
                return fh.read()
        bit_identical = all(
            _export(wd, c) == _export(ref_wd, c)
            for c in range(_PIPE_CYCLES))
        obs = _client_observations(os.path.join(td, "client.jsonl"))
        client_errs = [o for o in obs if not o.get("ok")]
        events = read_journal(os.path.join(td, "pipe_events.jsonl"))
        tail = journal_tail(os.path.join(td, "pipe_events.jsonl"))
        ledgers = sorted(os.listdir(os.path.join(wd, "ingest"))) \
            if os.path.isdir(os.path.join(wd, "ingest")) else []
        chain = checkpoint_inspect.build_pipeline_report(wd)
    commits = [((e.get("payload") or {}).get("ledger"),
                (e.get("payload") or {}).get("shard"))
               for e in events if e.get("event") == "ingest_shard_done"
               and (e.get("payload") or {}).get("stage") == "collect"]
    collect_resumes = sum(
        1 for e in events if e.get("event") == "ingest_resumed"
        and (e.get("payload") or {}).get("stage") == "collect")
    versions = _published_versions(events)
    checks = {
        "killed_at_every_boundary":
            all(b["sigkilled"] for b in boundaries_hit),
        "resume_completed_all_cycles": rc == 0
        and summary.get("cycles_completed") == _PIPE_CYCLES,
        "bit_identical_exports": bit_identical,
        # the heart of the drill: across three trainer lifetimes no
        # (cycle ledger, stripe) pair was ever committed twice — the
        # resumed runs LOADED the crashed runs' commits
        "exactly_once_stripe_commits": bool(commits)
        and len(commits) == len(set(commits)),
        "resumed_from_ledger": collect_resumes >= 1,
        "one_ledger_per_cycle": ledgers == [
            f"cycle_{c:04d}" for c in range(_PIPE_CYCLES)],
        "versions_monotone_no_gaps":
            versions == list(range(1, _PIPE_CYCLES + 1)),
        "zero_failed_requests": not client_errs,
        # pipeline-mode checkpoint_inspect now folds per-cycle stripe
        # ledgers into the chain verdict
        "cycle_chain_valid": bool(chain["all_valid"]),
    }
    return {"name": "pipeline_kill_sharded", "boundaries": boundaries_hit,
            "cycles": summary.get("cycles_completed"),
            "versions": versions, "stripe_commits": len(commits),
            "collect_resumes": collect_resumes, "checks": checks,
            "journal_tail": tail,
            "watchtower": _watchtower_summary(tail),
            "passed": all(checks.values())}


def scenario_pipeline_swap_abort():
    import json

    from lightgbm_tpu.obs.events import journal_tail, read_journal
    from lightgbm_tpu.obs.metrics import global_metrics
    from lightgbm_tpu.pipeline import ContinuousTrainer, FleetTarget
    from lightgbm_tpu.pipeline.drill import make_drift_stream
    from lightgbm_tpu.robustness.faults import kill_replica
    from lightgbm_tpu.serving import FleetServer
    Xs, ys = make_drift_stream(13, 2, 96, 5)
    retries0 = global_metrics.counter("pipeline_publish_retries")
    killed = {"done": False}
    with tempfile.TemporaryDirectory() as td:
        ev = os.path.join(td, "pipe_events.jsonl")
        wd = os.path.join(td, "wd")
        fleet = FleetServer(dict(_SERVE_PARAMS, event_output=ev),
                            workdir=td)
        try:
            def _mid_swap_kill(slot: int) -> None:
                if slot == 0 and not killed["done"]:
                    killed["done"] = True
                    fleet.inject(kill_replica(2))

            # cycle 0's publish is the initial (non-rolling) rollout;
            # arm the mid-swap kill only once cycle 1's export commits,
            # so it lands inside cycle 1's ROLLING publish of version 2
            def _arm(boundary: str, cycle: int) -> None:
                if boundary == "export" and cycle == 1:
                    fleet.swap_fault_hook = _mid_swap_kill

            trainer = ContinuousTrainer(
                dict(_PIPE_PARAMS, pipeline_workdir=wd, event_output=ev,
                     publish_retry_budget=2),
                Xs, FleetTarget(fleet), label=ys, name="pipe",
                chunk_rows=96, phase_hook=_arm)
            summary = trainer.run(num_cycles=2)
            fleet.swap_fault_hook = None
            live = fleet.replica_versions()
            manifest = fleet.registry.current("pipe")
        finally:
            fleet.close()
        retries = global_metrics.counter(
            "pipeline_publish_retries") - retries0
        prov = json.load(open(os.path.join(wd, "provenance.json")))
        versions = sorted(int(v) for v in
                          (prov.get("models", {}).get("pipe") or {}))
        events = read_journal(ev)
        names = [e.get("event") for e in events]
        tail = journal_tail(ev)
    checks = {
        "mid_swap_kill_fired": killed["done"],
        "rollout_aborted": "rolling_swap_aborted" in names
        and retries >= 1,
        # the SAME cycle retried the SAME version: exactly versions 1,2
        # were ever assigned, and cycle 1 still published as version 2
        "same_cycle_same_version_retried": versions == [1, 2]
        and _published_versions(events) == [1, 2]
        and summary["cycles_completed"] == 2,
        "fleet_converged_on_new_version":
            manifest is not None and int(manifest["version"]) == 2
        and bool(live) and all(m.get("pipe") == 2 for m in live.values()),
    }
    return {"name": "pipeline_swap_abort", "checks": checks,
            "publish_retries": int(retries), "versions": versions,
            "journal_tail": tail,
            "watchtower": _watchtower_summary(tail),
            "passed": all(checks.values())}


def run_drill(quick: bool, rounds: int, workers: int) -> Dict[str, Any]:
    X, y = _data()
    scenarios: List[Dict[str, Any]] = [scenario_kill(X, y, rounds, workers)]
    if not quick:
        scenarios.append(scenario_stall(X, y, rounds, workers))
        scenarios.append(scenario_drop(X, y, rounds, workers))
        scenarios.append(scenario_kill(X, y, rounds, workers,
                                       corrupt_newest=True))
        scenarios.append(scenario_fail_fast(X, y, rounds, workers))
    # the serving-fleet gate: kill-one-of-three under load is part of
    # --quick (tier-1); the stall and swap-abort drills ride the full run
    scenarios.append(scenario_serve_kill(X, y))
    if not quick:
        scenarios.append(scenario_serve_stall(X, y))
        scenarios.append(scenario_serve_swap_abort(X, y))
    # the pipeline crash-safety gate: the SIGKILL-at-every-boundary
    # chain is part of --quick (tier-1); the fleet swap-abort pipeline
    # drill rides the full run
    scenarios.append(scenario_pipeline_kill())
    # the sharded-ingest gates (PR 18): both part of --quick — the
    # worker-kill stripe-steal drill with its bit-identity contract,
    # and the exactly-once SIGKILL-mid-collect pipeline chain
    scenarios.append(scenario_ingest_host_kill())
    scenarios.append(scenario_pipeline_kill_sharded())
    if not quick:
        scenarios.append(scenario_pipeline_swap_abort())
    return {"tool": "fault_drill", "mode": "quick" if quick else "full",
            "rounds": rounds, "workers": workers,
            "scenarios": scenarios,
            "passed": all(s["passed"] for s in scenarios)}


def _render(payload: Dict[str, Any]) -> str:
    lines = [f"fault drill ({payload['mode']}): "
             f"{payload['workers']} workers x {payload['rounds']} rounds"]
    for s in payload["scenarios"]:
        verdict = "PASS" if s["passed"] else "FAIL"
        checks = " ".join(f"{k}={'ok' if v else 'FAIL'}"
                          for k, v in s["checks"].items())
        lines.append(f"  {s['name']:<10} {verdict}  {checks}")
        wt = s.get("watchtower")
        if wt is not None:
            col = (f"slo {wt['breaches']}b/{wt['recoveries']}r "
                   f"anomalies={wt['anomalies']}")
            if wt["unrecovered"]:
                col += " UNRECOVERED:" + ",".join(wt["unrecovered"])
            lines.append(f"             watchtower: {col}")
        tail = s.get("journal_tail") or []
        if tail:
            # breach/anomaly records always make the cut, even when
            # routine events crowd the last 8 slots
            hot = {"slo_breach", "slo_recovered", "anomaly_detected"}
            extra = [e for e in tail[:-8] if e.get("event") in hot]
            keep = 8 - min(8, len(extra))
            shown = extra[-8:] + (tail[-keep:] if keep else [])
            seq = " -> ".join(e.get("event", "?") for e in shown)
            lines.append(f"             journal: {seq}")
    lines.append("drill: " + ("PASS" if payload["passed"] else "FAIL"))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="kill + serve_kill + pipeline_kill + "
                         "ingest_host_kill + pipeline_kill_sharded "
                         "scenarios only (tier-1 CI gate)")
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--workers", type=int, default=4)
    add_format_arg(ap)
    args = ap.parse_args(argv)
    if args.workers < 2:
        print("fault_drill: need --workers >= 2 (one to lose)",
              file=sys.stderr)
        return EXIT_ERROR
    try:
        payload = run_drill(args.quick, args.rounds, args.workers)
    except Exception as e:   # drill infrastructure broke, not a finding
        print(f"fault_drill: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return EXIT_ERROR
    emit(payload, args.format, _render)
    return EXIT_OK if payload["passed"] else EXIT_FINDINGS


if __name__ == "__main__":
    sys.exit(main())
