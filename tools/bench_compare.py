#!/usr/bin/env python3
"""Compare two BENCH_r*.json captures and flag throughput regressions.

Round-6 satellite of the bench capture protocol: VERDICT r5 #2 showed a
round could quietly ship a flagship number 2x off its re-runs.  With
bench.py now refusing noisy captures outright, this tool closes the
other half of the loop — CI (or a human) diffs the new round's capture
against the previous one and gets a nonzero exit when the headline (or
any shared sub-measurement) regressed beyond tolerance.

Accepts either the driver wrapper layout ({"parsed": {...}}, the
BENCH_r*.json files at the repo root) or a bare bench.py payload line.
Comparable metrics: the headline ``vs_baseline`` (higher = faster,
normalized against the fixed reference-CPU anchor so two captures of
different rounds stay comparable) and ``speed_mode_bins63.vs_baseline``
when both captures carry it.

Round-8 serving tier: also accepts ``kind="serve"`` payloads from
tools/bench_serve.py.  Serve captures gate on request LATENCY, not
throughput-vs-anchor: the compared series are per-bucket (and overall)
``p99_ms``, LOWER is better, and a rise beyond --threshold is the
regression.  When both sides carry ``cold_warm_s`` (publish -> full
ladder warm, the respawn cold-start tax) it gates under the same
threshold — an AOT-store regression shows there first.  Both sides
must be serve captures of the same metric.

Exit codes (tools/_report.py convention):
  0 — comparable, no regression beyond --threshold,
  1 — at least one regression beyond --threshold,
  2 — unusable input (missing file, unparseable JSON, no headline, a
      refused/noisy capture, or mismatched metric names).
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _report  # noqa: E402


def load_payload(path: str) -> Dict[str, Any]:
    """BENCH_r*.json wrapper or bare bench payload -> the payload dict.

    Raises ValueError with a reason for every unusable shape."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except OSError as e:
        raise ValueError("cannot read %s: %s" % (path, e))
    except json.JSONDecodeError as e:
        raise ValueError("unparseable JSON in %s: %s" % (path, e))
    if not isinstance(obj, dict):
        raise ValueError("%s: top-level JSON is not an object" % path)
    payload = obj.get("parsed", obj)
    if not isinstance(payload, dict) or "metric" not in payload:
        raise ValueError("%s: no bench payload (missing 'metric')" % path)
    if payload.get("kind") == "serve":
        # serving captures gate on p99 latency, not vs_baseline
        if not _serve_series(payload):
            raise ValueError("%s: serve payload carries no positive "
                             "p99_ms series" % path)
        return payload
    if payload.get("kind") == "ingest":
        # ingest captures (tools/bench_ingest.py) gate on construction
        # throughput per variant, not vs_baseline
        if not _ingest_series(payload):
            raise ValueError("%s: ingest payload carries no positive "
                             "rows_per_s series" % path)
        return payload
    if payload.get("kind") == "rank":
        # rank captures (BENCH_RANK=1) gate on lambdarank training
        # iters/s per A/B arm plus the bucketed pad-waste ratio
        if not _rank_series(payload):
            raise ValueError("%s: rank payload carries no positive "
                             "iters_per_s series" % path)
        return payload
    if payload.get("quality") == "noisy":
        raise ValueError("%s: capture was refused as noisy "
                         "(rejected_value=%s) — not comparable evidence"
                         % (path, payload.get("rejected_value")))
    if not isinstance(payload.get("vs_baseline"), (int, float)) \
            or payload["vs_baseline"] <= 0:
        raise ValueError("%s: no positive vs_baseline headline "
                         "(value=%r)" % (path, payload.get("vs_baseline")))
    return payload


def _series(payload: Dict[str, Any]) -> List[Tuple[str, float]]:
    """(name, vs_baseline) rows this payload carries, headline first."""
    rows = [("headline", float(payload["vs_baseline"]))]
    sub = payload.get("speed_mode_bins63")
    if isinstance(sub, dict) and \
            isinstance(sub.get("vs_baseline"), (int, float)) \
            and sub["vs_baseline"] > 0:
        rows.append(("speed_mode_bins63", float(sub["vs_baseline"])))
    return rows


def _serve_series(payload: Dict[str, Any]) -> List[Tuple[str, float]]:
    """(name, p99_ms) rows of a kind="serve" payload: overall first,
    then one per bucket.  LOWER is better."""
    rows: List[Tuple[str, float]] = []
    ov = payload.get("overall")
    if isinstance(ov, dict) and isinstance(ov.get("p99_ms"),
                                           (int, float)) \
            and ov["p99_ms"] > 0:
        rows.append(("overall", float(ov["p99_ms"])))
    buckets = payload.get("buckets")
    if isinstance(buckets, dict):
        for b in sorted(buckets, key=lambda s: int(s)):
            r = buckets[b]
            if isinstance(r, dict) and isinstance(r.get("p99_ms"),
                                                  (int, float)) \
                    and r["p99_ms"] > 0:
                rows.append(("bucket%s" % b, float(r["p99_ms"])))
    return rows


def _ingest_series(payload: Dict[str, Any]) -> List[Tuple[str, float]]:
    """(variant, rows_per_s) rows of a kind="ingest" payload
    (tools/bench_ingest.py), in_memory first then streamed variants by
    chunk size.  HIGHER is better."""
    rows: List[Tuple[str, float]] = []
    variants = payload.get("variants")
    if not isinstance(variants, dict):
        return rows

    def _key(name: str):
        return (0, 0) if name == "in_memory" else \
            (1, int(name.rsplit("_", 1)[-1])
             if name.rsplit("_", 1)[-1].isdigit() else 0)

    for name in sorted(variants, key=_key):
        r = variants[name]
        if isinstance(r, dict) and \
                isinstance(r.get("rows_per_s"), (int, float)) \
                and r["rows_per_s"] > 0:
            rows.append((name, float(r["rows_per_s"])))
    return rows


def _rank_series(payload: Dict[str, Any]) -> List[Tuple[str, float]]:
    """(arm, iters_per_s) rows of a kind="rank" payload (BENCH_RANK=1):
    the bucketed arm first, then the pad-to-max control.  HIGHER is
    better."""
    rows: List[Tuple[str, float]] = []
    for arm in ("bucketed", "padded"):
        r = payload.get(arm)
        if isinstance(r, dict) and \
                isinstance(r.get("iters_per_s"), (int, float)) \
                and r["iters_per_s"] > 0:
            rows.append((arm, float(r["iters_per_s"])))
    return rows


def _compare_rank(old: Dict[str, Any], new: Dict[str, Any],
                  threshold: float) -> Dict[str, Any]:
    old_rows = dict(_rank_series(old))
    rows = []
    for name, new_ips in _rank_series(new):
        if name not in old_rows:
            continue
        old_ips = old_rows[name]
        # training throughput: LOWER is the regression direction
        change = new_ips / old_ips - 1.0
        rows.append({
            "series": name,
            "old_iters_per_s": old_ips,
            "new_iters_per_s": new_ips,
            "change_pct": round(100.0 * change, 2),
            "regression": bool(change < -threshold),
        })
    if not rows:
        raise ValueError("rank captures share no iters_per_s series")
    # bucketed pad waste gates alongside throughput: a ladder-choice
    # regression shows up as growing padding long before wall-clock does
    old_pw = (old.get("bucketed") or {}).get("pad_waste_ratio")
    new_pw = (new.get("bucketed") or {}).get("pad_waste_ratio")
    if isinstance(old_pw, (int, float)) and old_pw > 0 \
            and isinstance(new_pw, (int, float)):
        change = float(new_pw) / float(old_pw) - 1.0
        rows.append({
            "series": "pad_waste",
            "old_pad_waste_ratio": float(old_pw),
            "new_pad_waste_ratio": float(new_pw),
            "change_pct": round(100.0 * change, 2),
            "regression": bool(change > threshold),
        })
    return {
        "tool": "bench_compare",
        "kind": "rank",
        "metric": new.get("metric"),
        "threshold_pct": round(100.0 * threshold, 2),
        "old_platform": old.get("platform"),
        "new_platform": new.get("platform"),
        "rows": rows,
        "regressions": [r["series"] for r in rows if r["regression"]],
    }


def _compare_ingest(old: Dict[str, Any], new: Dict[str, Any],
                    threshold: float) -> Dict[str, Any]:
    old_rows = dict(_ingest_series(old))
    rows = []
    for name, new_rps in _ingest_series(new):
        if name not in old_rows:
            continue
        old_rps = old_rows[name]
        # throughput: LOWER is the regression direction
        change = new_rps / old_rps - 1.0
        rows.append({
            "series": name,
            "old_rows_per_s": old_rps,
            "new_rows_per_s": new_rps,
            "change_pct": round(100.0 * change, 2),
            "regression": bool(change < -threshold),
        })
    if not rows:
        raise ValueError("ingest captures share no variant series "
                         "(different chunk-size ladders?)")
    return {
        "tool": "bench_compare",
        "kind": "ingest",
        "metric": new.get("metric"),
        "threshold_pct": round(100.0 * threshold, 2),
        "old_platform": old.get("platform"),
        "new_platform": new.get("platform"),
        "rows": rows,
        "regressions": [r["series"] for r in rows if r["regression"]],
    }


def _compare_serve(old: Dict[str, Any], new: Dict[str, Any],
                   threshold: float) -> Dict[str, Any]:
    old_rows = dict(_serve_series(old))
    rows = []
    for name, new_p99 in _serve_series(new):
        if name not in old_rows:
            continue
        old_p99 = old_rows[name]
        # latency: HIGHER is the regression direction
        change = new_p99 / old_p99 - 1.0
        rows.append({
            "series": name,
            "old_p99_ms": old_p99,
            "new_p99_ms": new_p99,
            "change_pct": round(100.0 * change, 2),
            "regression": bool(change > threshold),
        })
    if not rows:
        raise ValueError("serve captures share no p99 series "
                         "(different bucket ladders?)")
    # cold-start warm cost (publish -> full ladder ready) gates
    # alongside p99: an AOT-store regression shows up here long before
    # it shows up in any steady-state latency percentile
    old_cw = old.get("cold_warm_s")
    new_cw = new.get("cold_warm_s")
    if isinstance(old_cw, (int, float)) and old_cw > 0 \
            and isinstance(new_cw, (int, float)) and new_cw > 0:
        change = float(new_cw) / float(old_cw) - 1.0
        rows.append({
            "series": "cold_warm",
            "old_cold_warm_s": float(old_cw),
            "new_cold_warm_s": float(new_cw),
            "change_pct": round(100.0 * change, 2),
            "regression": bool(change > threshold),
        })
    return {
        "tool": "bench_compare",
        "kind": "serve",
        "metric": new.get("metric"),
        "threshold_pct": round(100.0 * threshold, 2),
        "old_platform": old.get("platform"),
        "new_platform": new.get("platform"),
        "rows": rows,
        "regressions": [r["series"] for r in rows if r["regression"]],
    }


def compare(old: Dict[str, Any], new: Dict[str, Any],
            threshold: float) -> Dict[str, Any]:
    if old.get("metric") != new.get("metric"):
        raise ValueError(
            "metric mismatch: %r vs %r — different bench configurations "
            "are not comparable" % (old.get("metric"), new.get("metric")))
    if old.get("kind") in ("serve", "ingest", "rank") \
            or new.get("kind") in ("serve", "ingest", "rank"):
        if old.get("kind") != new.get("kind"):
            raise ValueError("cannot compare a %s capture against a %s "
                             "capture" % (old.get("kind") or "training",
                                          new.get("kind") or "training"))
        if new.get("kind") == "ingest":
            return _compare_ingest(old, new, threshold)
        if new.get("kind") == "rank":
            return _compare_rank(old, new, threshold)
        return _compare_serve(old, new, threshold)
    old_rows = dict(_series(old))
    rows = []
    for name, new_vb in _series(new):
        if name not in old_rows:
            continue
        old_vb = old_rows[name]
        # vs_baseline is work/seconds against a FIXED anchor, so the
        # ratio of two captures is the throughput ratio
        change = new_vb / old_vb - 1.0
        rows.append({
            "series": name,
            "old_vs_baseline": old_vb,
            "new_vs_baseline": new_vb,
            "change_pct": round(100.0 * change, 2),
            "regression": bool(change < -threshold),
        })
    return {
        "tool": "bench_compare",
        "metric": new.get("metric"),
        "threshold_pct": round(100.0 * threshold, 2),
        "old_platform": old.get("platform"),
        "new_platform": new.get("platform"),
        "rows": rows,
        "regressions": [r["series"] for r in rows if r["regression"]],
    }


# ------------------------------------------------------------------ trend
_ROUND_RE = re.compile(r"BENCH_r(\d+)", re.IGNORECASE)


def expand_captures(args: List[str]) -> List[str]:
    """Each argument may be a file, a directory (its BENCH_r*.json
    members), or a glob.  The union is ordered by embedded round number
    (``BENCH_r(\\d+)``), then name, deduplicated."""
    paths: List[str] = []
    for a in args:
        if os.path.isdir(a):
            paths.extend(_glob.glob(os.path.join(a, "BENCH_r*.json")))
        elif any(c in a for c in "*?["):
            paths.extend(_glob.glob(a))
        else:
            paths.append(a)
    seen = set()
    uniq = []
    for p in paths:
        key = os.path.abspath(p)
        if key not in seen:
            seen.add(key)
            uniq.append(p)

    def _key(p: str):
        m = _ROUND_RE.search(os.path.basename(p))
        return (int(m.group(1)) if m else 1 << 30, os.path.basename(p))

    return sorted(uniq, key=_key)


def trend(paths: List[str], threshold: float) -> Dict[str, Any]:
    """Cross-round trajectory over a sequence of captures: one row per
    file (usable or not, with the refusal reason), regression flags
    between CONSECUTIVE usable rows beyond ``threshold``.  Raises
    ValueError when no capture in the set is usable."""
    rows: List[Dict[str, Any]] = []
    prev_vb: Optional[float] = None
    prev_round: Optional[Any] = None
    regressions: List[str] = []
    usable = 0
    for path in paths:
        base = os.path.basename(path)
        m = _ROUND_RE.search(base)
        rnd = int(m.group(1)) if m else None
        row: Dict[str, Any] = {"round": rnd, "file": base}
        try:
            payload = load_payload(path)
        except ValueError as e:
            row.update(usable=False, reason=str(e).split(": ", 1)[-1])
            rows.append(row)
            continue
        if payload.get("kind") in ("serve", "ingest", "rank"):
            row.update(usable=False,
                       reason="%s capture (trend tracks training "
                              "vs_baseline)" % payload["kind"])
            rows.append(row)
            continue
        usable += 1
        vb = float(payload["vs_baseline"])
        row.update(usable=True, vs_baseline=vb,
                   metric=payload.get("metric"),
                   platform=payload.get("platform"),
                   quality=payload.get("quality"))
        for extra in ("compile_s", "run_s"):
            if isinstance(payload.get(extra), (int, float)):
                row[extra] = payload[extra]
        sub = payload.get("speed_mode_bins63")
        if isinstance(sub, dict) and \
                isinstance(sub.get("vs_baseline"), (int, float)):
            row["speed_mode_bins63"] = float(sub["vs_baseline"])
        if prev_vb is not None:
            change = vb / prev_vb - 1.0
            row["change_pct"] = round(100.0 * change, 2)
            if change < -threshold:
                row["regression"] = True
                label = "r%s->r%s" % (prev_round, rnd) \
                    if prev_round is not None and rnd is not None \
                    else base
                regressions.append(label)
        prev_vb, prev_round = vb, rnd
        rows.append(row)
    if not usable:
        raise ValueError("no usable capture in the set (%d files)"
                         % len(paths))
    return {
        "tool": "bench_compare",
        "mode": "trend",
        "threshold_pct": round(100.0 * threshold, 2),
        "captures": len(paths),
        "usable": usable,
        "rows": rows,
        "regressions": regressions,
    }


def _render_trend(payload: Dict[str, Any]) -> str:
    lines = ["bench trend: %d captures, %d usable (threshold %.1f%%)"
             % (payload["captures"], payload["usable"],
                payload["threshold_pct"])]
    lines.append("  %-6s %-22s %-12s %-9s %-8s %s"
                 % ("round", "file", "vs_baseline", "change", "bins63",
                    "notes"))
    for r in payload["rows"]:
        rnd = "r%02d" % r["round"] if r.get("round") is not None else "-"
        if not r.get("usable"):
            lines.append("  %-6s %-22s %-12s %-9s %-8s unusable: %s"
                         % (rnd, r["file"], "-", "-", "-",
                            r.get("reason", "?")))
            continue
        change = "%+.2f%%" % r["change_pct"] \
            if "change_pct" in r else "-"
        bins63 = "%.4f" % r["speed_mode_bins63"] \
            if "speed_mode_bins63" in r else "-"
        notes = []
        if r.get("regression"):
            notes.append("REGRESSION")
        if r.get("quality") and r["quality"] != "ok":
            notes.append("quality=%s" % r["quality"])
        if r.get("compile_s") is not None:
            notes.append("compile_s=%.2f" % r["compile_s"])
        if r.get("run_s") is not None:
            notes.append("run_s=%.2f" % r["run_s"])
        lines.append("  %-6s %-22s %-12.4f %-9s %-8s %s"
                     % (rnd, r["file"], r["vs_baseline"], change, bins63,
                        " ".join(notes)))
    if payload["regressions"]:
        lines.append("  regressions: " + ", ".join(payload["regressions"]))
    return "\n".join(lines)


def _render_text(payload: Dict[str, Any]) -> str:
    lines = ["bench_compare: %s (threshold %.1f%%)"
             % (payload["metric"], payload["threshold_pct"])]
    for r in payload["rows"]:
        flag = "REGRESSION" if r["regression"] else "ok"
        if "old_p99_ms" in r:
            lines.append("  %-18s %8.3f ms -> %8.3f ms  (%+.2f%%)  %s"
                         % (r["series"], r["old_p99_ms"],
                            r["new_p99_ms"], r["change_pct"], flag))
        elif "old_cold_warm_s" in r:
            lines.append("  %-18s %8.3f s  -> %8.3f s   (%+.2f%%)  %s"
                         % (r["series"], r["old_cold_warm_s"],
                            r["new_cold_warm_s"], r["change_pct"], flag))
        elif "old_rows_per_s" in r:
            lines.append("  %-18s %10.0f rows/s -> %10.0f rows/s  "
                         "(%+.2f%%)  %s"
                         % (r["series"], r["old_rows_per_s"],
                            r["new_rows_per_s"], r["change_pct"], flag))
        elif "old_iters_per_s" in r:
            lines.append("  %-18s %8.4f iters/s -> %8.4f iters/s  "
                         "(%+.2f%%)  %s"
                         % (r["series"], r["old_iters_per_s"],
                            r["new_iters_per_s"], r["change_pct"], flag))
        elif "old_pad_waste_ratio" in r:
            lines.append("  %-18s %8.4f -> %8.4f  (%+.2f%%)  %s"
                         % (r["series"], r["old_pad_waste_ratio"],
                            r["new_pad_waste_ratio"], r["change_pct"],
                            flag))
        else:
            lines.append("  %-18s %8.4f -> %8.4f  (%+.2f%%)  %s"
                         % (r["series"], r["old_vs_baseline"],
                            r["new_vs_baseline"], r["change_pct"], flag))
    if not payload["rows"]:
        lines.append("  (no shared series)")
    if payload["old_platform"] != payload["new_platform"]:
        lines.append("  note: platforms differ (%s vs %s)"
                     % (payload["old_platform"], payload["new_platform"]))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff two BENCH_r*.json captures (default), or chart "
                    "a whole directory of them with --trend; nonzero exit "
                    "on a throughput regression beyond the threshold.")
    ap.add_argument("captures", nargs="+",
                    help="two BENCH_r*.json files (compare mode), or any "
                         "mix of files/dirs/globs with --trend")
    ap.add_argument("--trend", action="store_true",
                    help="cross-round trajectory over every capture "
                         "instead of a two-file diff")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="relative regression tolerance (default 0.05)")
    _report.add_format_arg(ap)
    args = ap.parse_args(argv)
    if args.trend:
        paths = expand_captures(args.captures)
        if not paths:
            print("bench_compare: error: no captures matched",
                  file=sys.stderr)
            return _report.EXIT_ERROR
        try:
            result = trend(paths, args.threshold)
        except ValueError as e:
            print("bench_compare: error: %s" % e, file=sys.stderr)
            return _report.EXIT_ERROR
        _report.emit(result, args.format, _render_trend)
        return _report.EXIT_FINDINGS if result["regressions"] \
            else _report.EXIT_OK
    if len(args.captures) != 2:
        print("bench_compare: error: compare mode takes exactly two "
              "captures (got %d); did you mean --trend?"
              % len(args.captures), file=sys.stderr)
        return _report.EXIT_ERROR
    try:
        old = load_payload(args.captures[0])
        new = load_payload(args.captures[1])
        result = compare(old, new, args.threshold)
    except ValueError as e:
        print("bench_compare: error: %s" % e, file=sys.stderr)
        return _report.EXIT_ERROR
    _report.emit(result, args.format, _render_text)
    return _report.EXIT_FINDINGS if result["regressions"] \
        else _report.EXIT_OK


if __name__ == "__main__":
    raise SystemExit(main())
