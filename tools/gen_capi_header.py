"""Regenerate lightgbm_tpu/native/capi.h from capi.cpp's definitions.

The header is the SWIG/JVM + C-consumer surface (the counterpart of the
reference's include/LightGBM/c_api.h); run after adding ABI entries."""
import os
import re

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(ROOT, "lightgbm_tpu", "native", "capi.cpp")
DST = os.path.join(ROOT, "lightgbm_tpu", "native", "capi.h")

HEADER = '''/* C ABI header for lightgbm_tpu (native/capi.cpp) — the counterpart of
 * the reference's include/LightGBM/c_api.h.  Conventions: every function
 * returns 0 on success / -1 on failure, with LGBMTPU_GetLastError()
 * holding the message (thread-local).  Handles are opaque int64 ids.
 *
 * Generated from capi.cpp's definitions; regenerate with
 * tools/gen_capi_header.py after adding entries. */
#ifndef LIGHTGBM_TPU_CAPI_H_
#define LIGHTGBM_TPU_CAPI_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

'''

FOOTER = '''

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* LIGHTGBM_TPU_CAPI_H_ */
'''


def generate() -> str:
    src = open(SRC).read()
    pat = re.compile(r'^([A-Za-z_][A-Za-z0-9_ ]*?\**)\s+(LGBMTPU_\w+)'
                     r'\(([^{]*?)\)\s*\{', re.M | re.S)
    decls = []
    emitted = set()
    for m in pat.finditer(src):
        ret, name, args = m.group(1), m.group(2), " ".join(m.group(3).split())
        decls.append(f"{ret} {name}({args});")
        emitted.add(name)
    # completeness gate: every LGBMTPU_ symbol mentioned in capi.cpp must
    # be declared — a silently dropped definition would surface as an
    # implicit-declaration error at some consumer instead of here
    mentioned = set(re.findall(r"\b(LGBMTPU_\w+)\s*\(", src))
    missing = mentioned - emitted
    if missing:
        raise SystemExit(f"capi.h generation missed definitions: "
                         f"{sorted(missing)}")
    return HEADER + "\n".join(decls) + FOOTER


if __name__ == "__main__":
    open(DST, "w").write(generate())
    print(f"wrote {DST}")
