#!/usr/bin/env python
"""Inspect and verify lightgbm_tpu training checkpoints.

    python tools/checkpoint_inspect.py <checkpoint_dir> [--verify]
                                       [--format text|json]

Prints one line per checkpoint under ``checkpoint_dir`` (newest first):
iteration, wall-clock timestamp, model size, tree count, and an
OK/INVALID verdict with the failure reason (manifest integrity: file
presence, byte sizes, sha256 — robustness/checkpoint.py
``validate_checkpoint``).

Exit codes (tools/_report.py convention):
  0 — at least one checkpoint exists and the NEWEST one is valid,
  1 — the directory holds no checkpoints at all,
  2 — the newest checkpoint is invalid (resume would fall back to an
      older one — or fail entirely when none validates).

``--verify-all`` hardens the gate for elastic recovery (docs/
ROBUSTNESS.md): EVERY manifest must sha256-validate, not just the
newest.  An eviction-triggered resume falls back through the chain when
the newest checkpoint is corrupt, so a rotting older checkpoint is a
latent recovery failure even while normal resumes still succeed — with
``--verify-all`` any invalid checkpoint exits 2.

Pointed at a continuous-learning PIPELINE workdir (a directory holding
``pipeline_manifest.json``, pipeline/cycle.py) the tool switches to
cycle-chain verification: every acked cycle's checkpoint -> export ->
publish sha256 chain must hold — the export file on disk hashes to the
manifest's recorded sha, the publish-provenance ledger names the same
sha for the same version, versions run 1..N with no gaps — and the
in-flight cycle's committed artifacts (its export record, its per-cycle
checkpoint directory) must validate too.  Any broken link is a TORN
cycle: exit 1.

Sharded-ingest workdirs (io/sharded.py; a directory holding
``stripe_ledger.json``) get stripe-ledger verification: the ledger must
parse (a torn ledger exits 1 — no resume can trust the stripe
universe) and the commit chain must hold (every commit file loads; a
COMPLETE ledger holds one commit per stripe per pass).  ``--verify-all``
— and pipeline mode always — additionally discovers ledgers nested
under the target (a pipeline workdir keeps one per cycle under
``ingest/cycle_NNNN``) and folds their findings in.

AOT executable stores (ops/aot_store.py) join the verification
surface: pointed directly at a store directory (one holding
``aot_store.json``) the tool verifies every artifact's sha256 against
its sidecar meta and the fingerprint chain (one backend/jax-version/
topology fingerprint per store); ``--verify-all`` — and pipeline mode
always — additionally discovers stores nested under the target
directory and folds their findings in.  A torn or stale store exits 1:
the serving tier would evict-and-relower (never crash), but a respawn
loses its zero-lowering warm path.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Any, Dict

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _report import (EXIT_ERROR, EXIT_FINDINGS, EXIT_OK,  # noqa: E402
                     add_format_arg, emit)
from lightgbm_tpu.ops.aot_store import (  # noqa: E402
    find_aot_stores, is_aot_store, verify_store)
from lightgbm_tpu.robustness.checkpoint import (  # noqa: E402
    MODEL_NAME, checkpoint_dirs, read_manifest, validate_checkpoint)


def build_report(directory: str) -> Dict[str, Any]:
    """Payload for one checkpoint directory (newest first)."""
    entries = []
    for it, path in checkpoint_dirs(directory):
        ok, reason = validate_checkpoint(path)
        manifest = read_manifest(path) or {}
        mpath = os.path.join(path, MODEL_NAME)
        entries.append({
            "iteration": it,
            "path": path,
            "valid": ok,
            "reason": reason,
            "unix_time": manifest.get("unix_time"),
            "model_bytes": os.path.getsize(mpath)
            if os.path.exists(mpath) else 0,
            "num_trees": manifest.get("num_trees"),
            "manifest": manifest,
        })
    return {
        "tool": "checkpoint_inspect",
        "directory": directory,
        "checkpoints": entries,
        "newest_valid": entries[0]["valid"] if entries else None,
        "all_valid": all(e["valid"] for e in entries) if entries else None,
        "invalid_count": sum(1 for e in entries if not e["valid"]),
    }


def build_aot_report(directory: str) -> Dict[str, Any]:
    """Integrity payload for one AOT executable store directory."""
    rep = verify_store(directory)
    return {"tool": "checkpoint_inspect", "mode": "aot_store",
            "directory": directory, "store": rep,
            "findings": list(rep["findings"]),
            "all_valid": bool(rep["valid"])}


def _store_findings(root: str) -> list:
    """Findings from every AOT store discovered under ``root`` (used by
    --verify-all and pipeline mode), prefixed with the store path."""
    findings = []
    for store in find_aot_stores(root):
        rep = verify_store(store)
        for f in rep["findings"]:
            findings.append(f"aot store {store}: {f}")
    return findings


def is_sharded_workdir(directory: str) -> bool:
    return os.path.exists(os.path.join(directory, "stripe_ledger.json"))


def _find_stripe_ledgers(root: str) -> list:
    """Sharded-ingest workdirs nested under ``root`` (pipeline cycle
    ledgers live at ``<workdir>/ingest/cycle_NNNN``), excluding ``root``
    itself."""
    found = []
    for dirpath, dirnames, filenames in os.walk(root):
        if dirpath != root and "stripe_ledger.json" in filenames:
            found.append(dirpath)
            dirnames[:] = []    # a ledger dir never nests another
    return sorted(found)


def build_sharded_report(workdir: str) -> Dict[str, Any]:
    """Integrity payload for one sharded-ingest workdir (a directory
    holding ``stripe_ledger.json``, io/sharded.py).

    The ledger itself must parse (a torn ledger is a hard finding: no
    resume can trust the stripe universe), and the commit chain must
    hold: every commit file present must load, and a COMPLETE ledger
    must hold a commit for every stripe of every pass.  Missing commits
    under an incomplete ledger are progress, not damage — the next run
    resumes them."""
    import json

    from lightgbm_tpu.io import sharded
    findings: list = []
    led = sharded.read_ledger(workdir)
    if led is None:
        return {"tool": "checkpoint_inspect", "mode": "sharded_ingest",
                "directory": workdir, "ledger": None,
                "findings": [f"torn or unreadable stripe ledger under "
                             f"{workdir} — the stripe universe cannot "
                             "be trusted; re-run the ingest"],
                "all_valid": False}
    stripes = int(led.get("num_stripes", 0))
    passes = [str(p) for p in led.get("passes", [])]
    complete = bool(led.get("complete"))
    chain: Dict[str, Dict[str, int]] = {}
    for tag in passes:
        committed = torn = 0
        for s in range(stripes):
            cpath = sharded.commit_path(workdir, tag, s)
            if not os.path.exists(cpath):
                if complete:
                    findings.append(
                        f"pass {tag} stripe {s}: ledger says complete "
                        "but the commit file is missing")
                continue
            try:
                if cpath.endswith(".json"):
                    with open(cpath) as fh:
                        json.load(fh)
                else:
                    import numpy as _np
                    with _np.load(cpath) as z:
                        z.files
                committed += 1
            except Exception as e:
                torn += 1
                findings.append(f"pass {tag} stripe {s}: commit file "
                                f"unreadable ({type(e).__name__}: {e})")
        chain[tag] = {"committed": committed, "torn": torn,
                      "missing": stripes - committed - torn}
    return {"tool": "checkpoint_inspect", "mode": "sharded_ingest",
            "directory": workdir,
            "ledger": {"fingerprint": sharded.ledger_fingerprint(led),
                       "num_stripes": stripes, "passes": passes,
                       "complete": complete,
                       "workers": led.get("ingest_workers")},
            "commits": chain, "findings": findings,
            "all_valid": not findings}


def _ledger_findings(root: str) -> list:
    """Findings from every sharded-ingest ledger discovered under
    ``root`` (used by --verify-all and pipeline mode), prefixed with
    the ledger path."""
    findings = []
    for wd in _find_stripe_ledgers(root):
        rep = build_sharded_report(wd)
        for f in rep["findings"]:
            findings.append(f"stripe ledger {wd}: {f}")
    return findings


def _render_sharded(payload: Dict[str, Any]) -> str:
    led = payload.get("ledger")
    if led is None:
        lines = [f"sharded ingest {payload['directory']}: TORN LEDGER"]
    else:
        state = "complete" if led["complete"] else "in progress"
        lines = [f"sharded ingest {payload['directory']}: "
                 f"{led['num_stripes']} stripe(s), "
                 f"passes {'+'.join(led['passes'])}, {state}"]
        for tag in led["passes"]:
            c = payload["commits"].get(tag, {})
            lines.append(f"  pass {tag}: {c.get('committed', 0)} "
                         f"committed, {c.get('missing', 0)} missing, "
                         f"{c.get('torn', 0)} torn")
        lines.append(f"  ledger fingerprint: {led['fingerprint'][:16]}…")
    for f in payload["findings"]:
        lines.append(f"  FINDING: {f}")
    lines.append("ledger: " + ("OK" if payload["all_valid"] else "TORN"))
    return "\n".join(lines)


def build_pipeline_report(workdir: str) -> Dict[str, Any]:
    """Cycle-chain verification payload for a pipeline workdir.

    Each acked cycle contributes one entry with the per-link verdicts;
    ``findings`` collects every broken link (a torn cycle).  The
    in-flight cycle is checked for whatever it has durably committed.
    """
    import json

    from lightgbm_tpu.pipeline.cycle import (MANIFEST_NAME, CycleManifest,
                                             sha256_text)
    from lightgbm_tpu.serving.registry import PublishProvenance
    man = CycleManifest.load(workdir)
    findings: list = []
    if man is None:
        return {"tool": "checkpoint_inspect", "mode": "pipeline",
                "directory": workdir, "cycles": [], "all_valid": False,
                "findings": [f"unreadable {MANIFEST_NAME} under {workdir}"]}
    prov = PublishProvenance(os.path.join(workdir, "provenance.json"))
    name = man.state.get("name", "")

    def _export_sha(path: str):
        try:
            with open(path) as fh:
                return sha256_text(fh.read()), None
        except OSError as e:
            return None, f"{type(e).__name__}: {e}"

    entries = []
    expect_version = 1
    for h in man.state.get("history", []):
        c, v = int(h["cycle"]), int(h["version"])
        got_sha, err = _export_sha(h["path"])
        ledger = prov.lookup(name, v)
        entry = {
            "cycle": c, "version": v, "iteration": h.get("iteration"),
            "export_readable": err is None,
            "export_sha_matches": got_sha == h["sha256"],
            "ledger_recorded": ledger is not None,
            "ledger_sha_matches": bool(ledger)
            and ledger.get("sha256") == h["sha256"],
            "version_in_sequence": v == expect_version,
        }
        entry["valid"] = all(entry[k] for k in
                             ("export_readable", "export_sha_matches",
                              "ledger_recorded", "ledger_sha_matches",
                              "version_in_sequence"))
        if not entry["valid"]:
            bad = [k for k in ("export_readable", "export_sha_matches",
                               "ledger_recorded", "ledger_sha_matches",
                               "version_in_sequence") if not entry[k]]
            findings.append(f"cycle {c} (version {v}) torn: "
                            + ", ".join(bad) + (f" [{err}]" if err else ""))
        entries.append(entry)
        expect_version = v + 1

    current: Dict[str, Any] = {"cycle": man.cycle, "phase": man.phase}
    exp = man.state.get("export")
    if exp:
        got_sha, err = _export_sha(exp["path"])
        current["export_sha_matches"] = got_sha == exp["sha256"]
        if not current["export_sha_matches"]:
            findings.append(
                f"in-flight cycle {man.cycle}: committed export torn"
                + (f" [{err}]" if err else ""))
    if man.state.get("model_sha256") and exp and \
            exp["sha256"] != man.state["model_sha256"]:
        findings.append(f"in-flight cycle {man.cycle}: export sha differs "
                        "from the checkpointed model sha")
    ckpt_dir = os.path.join(workdir, "cycles", f"cycle_{man.cycle:04d}")
    if os.path.isdir(ckpt_dir):
        dirs = checkpoint_dirs(ckpt_dir)
        if dirs:
            ok, reason = validate_checkpoint(dirs[0][1])
            current["newest_checkpoint_valid"] = ok
            if not ok:
                findings.append(f"in-flight cycle {man.cycle}: newest "
                                f"checkpoint invalid ({reason})")
    # a pipeline workdir owns an AOT store by default (pipeline/
    # trainer.py keeps one under <workdir>/aot_store): a torn store is
    # part of the recovery surface this mode exists to verify
    findings.extend(_store_findings(workdir))
    # ... and, with sharded ingest on (ingest_workers >= 1), per-cycle
    # stripe ledgers under <workdir>/ingest/cycle_NNNN: a torn ledger or
    # commit breaks the exactly-once resume of its cycle
    findings.extend(_ledger_findings(workdir))
    return {"tool": "checkpoint_inspect", "mode": "pipeline",
            "directory": workdir, "name": name, "cycles": entries,
            "current": current, "findings": findings,
            "all_valid": not findings}


def _render_pipeline(payload: Dict[str, Any]) -> str:
    lines = [f"pipeline workdir {payload['directory']} "
             f"(model {payload.get('name', '?')!r})"]
    for e in payload["cycles"]:
        verdict = "OK" if e["valid"] else "TORN"
        lines.append(f"cycle={e['cycle']:<4d} version={e['version']:<4d} "
                     f"iter={e['iteration']!s:>5}  {verdict}")
    cur = payload.get("current") or {}
    lines.append(f"in-flight: cycle={cur.get('cycle')} "
                 f"phase={cur.get('phase')}")
    for f in payload["findings"]:
        lines.append(f"  FINDING: {f}")
    lines.append("chain: " + ("OK" if payload["all_valid"] else "TORN"))
    return "\n".join(lines)


def _render_aot(payload: Dict[str, Any]) -> str:
    rep = payload["store"]
    lines = [f"aot store {payload['directory']}: "
             f"{len(rep.get('artifacts', []))} artifact(s)"]
    for f in payload["findings"]:
        lines.append(f"  FINDING: {f}")
    lines.append("store: " + ("OK" if payload["all_valid"]
                              else "TORN/STALE"))
    return "\n".join(lines)


def _render_report(payload: Dict[str, Any]) -> str:
    entries = payload["checkpoints"]
    lines = []
    if not entries:
        lines.append(f"no checkpoints under {payload['directory']}")
        entries = []
    for e in entries:
        ts = e["unix_time"]
        when = time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(ts)) if ts else "?"
        verdict = "OK" if e["valid"] else f"INVALID ({e['reason']})"
        trees = e["num_trees"] if e["num_trees"] is not None else "?"
        lines.append(f"iter={e['iteration']:<8d} time={when}  "
                     f"model={e['model_bytes']:>9d}B  trees={trees!s:>5}  "
                     f"{verdict}  {os.path.basename(e['path'])}")
    for f in payload.get("store_findings", []):
        lines.append(f"  FINDING: {f}")
    return "\n".join(lines)


def exit_code(payload: Dict[str, Any], verify_all: bool = False) -> int:
    if not payload["checkpoints"]:
        return EXIT_FINDINGS
    ok = payload["all_valid"] if verify_all else payload["newest_valid"]
    return EXIT_OK if ok else EXIT_ERROR


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("checkpoint_dir")
    ap.add_argument("--verify", action="store_true",
                    help="exit nonzero unless the newest checkpoint "
                         "validates (the default behavior; kept as an "
                         "explicit flag for CI readability)")
    ap.add_argument("--verify-all", action="store_true",
                    help="exit nonzero unless EVERY checkpoint's manifest "
                         "sha256-validates — guards the whole fallback "
                         "chain an elastic recovery may walk, not just "
                         "the newest entry")
    add_format_arg(ap)
    ap.add_argument("--json", action="store_true",
                    help="deprecated spelling of --format json (NOTE: "
                         "output is one report object now, no longer "
                         "one JSON line per checkpoint)")
    args = ap.parse_args(argv)
    fmt = "json" if args.json else args.format
    if is_aot_store(args.checkpoint_dir):
        payload = build_aot_report(args.checkpoint_dir)
        emit(payload, fmt, _render_aot)
        return EXIT_OK if payload["all_valid"] else EXIT_FINDINGS
    if is_sharded_workdir(args.checkpoint_dir):
        payload = build_sharded_report(args.checkpoint_dir)
        emit(payload, fmt, _render_sharded)
        return EXIT_OK if payload["all_valid"] else EXIT_FINDINGS
    if os.path.exists(os.path.join(args.checkpoint_dir,
                                   "pipeline_manifest.json")):
        payload = build_pipeline_report(args.checkpoint_dir)
        emit(payload, fmt, _render_pipeline)
        return EXIT_OK if payload["all_valid"] else EXIT_FINDINGS
    payload = build_report(args.checkpoint_dir)
    if args.verify_all:
        payload["store_findings"] = (_store_findings(args.checkpoint_dir)
                                     + _ledger_findings(args.checkpoint_dir))
    emit(payload, fmt, _render_report)
    code = exit_code(payload, verify_all=args.verify_all)
    if code == EXIT_OK and payload.get("store_findings"):
        # torn/stale AOT store: serving degrades to live lowering, the
        # respawn warm path is gone — a finding, not a hard error
        code = EXIT_FINDINGS
    return code


if __name__ == "__main__":
    sys.exit(main())
