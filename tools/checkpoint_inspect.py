#!/usr/bin/env python
"""Inspect and verify lightgbm_tpu training checkpoints.

    python tools/checkpoint_inspect.py <checkpoint_dir> [--verify]
                                       [--format text|json]

Prints one line per checkpoint under ``checkpoint_dir`` (newest first):
iteration, wall-clock timestamp, model size, tree count, and an
OK/INVALID verdict with the failure reason (manifest integrity: file
presence, byte sizes, sha256 — robustness/checkpoint.py
``validate_checkpoint``).

Exit codes (tools/_report.py convention):
  0 — at least one checkpoint exists and the NEWEST one is valid,
  1 — the directory holds no checkpoints at all,
  2 — the newest checkpoint is invalid (resume would fall back to an
      older one — or fail entirely when none validates).

``--verify-all`` hardens the gate for elastic recovery (docs/
ROBUSTNESS.md): EVERY manifest must sha256-validate, not just the
newest.  An eviction-triggered resume falls back through the chain when
the newest checkpoint is corrupt, so a rotting older checkpoint is a
latent recovery failure even while normal resumes still succeed — with
``--verify-all`` any invalid checkpoint exits 2.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Any, Dict

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _report import (EXIT_ERROR, EXIT_FINDINGS, EXIT_OK,  # noqa: E402
                     add_format_arg, emit)
from lightgbm_tpu.robustness.checkpoint import (  # noqa: E402
    MODEL_NAME, checkpoint_dirs, read_manifest, validate_checkpoint)


def build_report(directory: str) -> Dict[str, Any]:
    """Payload for one checkpoint directory (newest first)."""
    entries = []
    for it, path in checkpoint_dirs(directory):
        ok, reason = validate_checkpoint(path)
        manifest = read_manifest(path) or {}
        mpath = os.path.join(path, MODEL_NAME)
        entries.append({
            "iteration": it,
            "path": path,
            "valid": ok,
            "reason": reason,
            "unix_time": manifest.get("unix_time"),
            "model_bytes": os.path.getsize(mpath)
            if os.path.exists(mpath) else 0,
            "num_trees": manifest.get("num_trees"),
            "manifest": manifest,
        })
    return {
        "tool": "checkpoint_inspect",
        "directory": directory,
        "checkpoints": entries,
        "newest_valid": entries[0]["valid"] if entries else None,
        "all_valid": all(e["valid"] for e in entries) if entries else None,
        "invalid_count": sum(1 for e in entries if not e["valid"]),
    }


def _render_report(payload: Dict[str, Any]) -> str:
    entries = payload["checkpoints"]
    if not entries:
        return f"no checkpoints under {payload['directory']}"
    lines = []
    for e in entries:
        ts = e["unix_time"]
        when = time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(ts)) if ts else "?"
        verdict = "OK" if e["valid"] else f"INVALID ({e['reason']})"
        trees = e["num_trees"] if e["num_trees"] is not None else "?"
        lines.append(f"iter={e['iteration']:<8d} time={when}  "
                     f"model={e['model_bytes']:>9d}B  trees={trees!s:>5}  "
                     f"{verdict}  {os.path.basename(e['path'])}")
    return "\n".join(lines)


def exit_code(payload: Dict[str, Any], verify_all: bool = False) -> int:
    if not payload["checkpoints"]:
        return EXIT_FINDINGS
    ok = payload["all_valid"] if verify_all else payload["newest_valid"]
    return EXIT_OK if ok else EXIT_ERROR


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("checkpoint_dir")
    ap.add_argument("--verify", action="store_true",
                    help="exit nonzero unless the newest checkpoint "
                         "validates (the default behavior; kept as an "
                         "explicit flag for CI readability)")
    ap.add_argument("--verify-all", action="store_true",
                    help="exit nonzero unless EVERY checkpoint's manifest "
                         "sha256-validates — guards the whole fallback "
                         "chain an elastic recovery may walk, not just "
                         "the newest entry")
    add_format_arg(ap)
    ap.add_argument("--json", action="store_true",
                    help="deprecated spelling of --format json (NOTE: "
                         "output is one report object now, no longer "
                         "one JSON line per checkpoint)")
    args = ap.parse_args(argv)
    payload = build_report(args.checkpoint_dir)
    fmt = "json" if args.json else args.format
    emit(payload, fmt, _render_report)
    return exit_code(payload, verify_all=args.verify_all)


if __name__ == "__main__":
    sys.exit(main())
