#!/usr/bin/env python
"""Inspect and verify lightgbm_tpu training checkpoints.

    python tools/checkpoint_inspect.py <checkpoint_dir> [--verify]

Prints one line per checkpoint under ``checkpoint_dir`` (newest first):
iteration, wall-clock timestamp, model size, tree count, and an
OK/INVALID verdict with the failure reason (manifest integrity: file
presence, byte sizes, sha256 — robustness/checkpoint.py
``validate_checkpoint``).

Exit codes (CI-friendly):
  0 — at least one checkpoint exists and the NEWEST one is valid,
  1 — the directory holds no checkpoints at all,
  2 — the newest checkpoint is invalid (resume would fall back to an
      older one — or fail entirely when none validates).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from lightgbm_tpu.robustness.checkpoint import (  # noqa: E402
    MODEL_NAME, checkpoint_dirs, read_manifest, validate_checkpoint)


def inspect_dir(directory: str) -> int:
    ckpts = checkpoint_dirs(directory)
    if not ckpts:
        print(f"no checkpoints under {directory}")
        return 1
    newest_ok = None
    for it, path in ckpts:
        ok, reason = validate_checkpoint(path)
        if newest_ok is None:
            newest_ok = ok
        manifest = read_manifest(path) or {}
        ts = manifest.get("unix_time")
        when = time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(ts)) if ts else "?"
        mpath = os.path.join(path, MODEL_NAME)
        msize = os.path.getsize(mpath) if os.path.exists(mpath) else 0
        verdict = "OK" if ok else f"INVALID ({reason})"
        print(f"iter={it:<8d} time={when}  model={msize:>9d}B  "
              f"trees={manifest.get('num_trees', '?'):>5}  {verdict}  "
              f"{os.path.basename(path)}")
    return 0 if newest_ok else 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("checkpoint_dir")
    ap.add_argument("--verify", action="store_true",
                    help="exit nonzero unless the newest checkpoint "
                         "validates (the default behavior; kept as an "
                         "explicit flag for CI readability)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object per checkpoint instead of "
                         "the human table")
    args = ap.parse_args(argv)
    if args.json:
        ckpts = checkpoint_dirs(args.checkpoint_dir)
        if not ckpts:
            print(json.dumps({"checkpoints": 0}))
            return 1
        rc = 1
        for i, (it, path) in enumerate(ckpts):
            ok, reason = validate_checkpoint(path)
            if i == 0:
                rc = 0 if ok else 2
            print(json.dumps({"iteration": it, "path": path, "valid": ok,
                              "reason": reason,
                              "manifest": read_manifest(path)}))
        return rc
    return inspect_dir(args.checkpoint_dir)


if __name__ == "__main__":
    sys.exit(main())
