"""Profile the bench training body on a live chip and aggregate
device-lane HLO durations per tree.

Usage:  PK=28 PROWS=1000000 python tools/profile_bench.py

Knobs (env): PK split batch, PROWS rows, PLEAVES
leaves.  Methodology notes in docs/PERF_NOTES.md — in particular, only
scan-chained in-one-jit timing is trustworthy through the axon tunnel.
"""
import glob
import gzip
import json
import os
import sys
from collections import defaultdict

import numpy as np

# run as `python tools/profile_bench.py`: sys.path[0] is tools/, not the repo
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K = int(os.environ.get("PK", "20"))
N = int(os.environ.get("PROWS", "1000000"))
LEAVES = int(os.environ.get("PLEAVES", "255"))
PBIN = int(os.environ.get("PBIN", "255"))

import jax
import jax.numpy as jnp
from lightgbm_tpu.learner.batch_grower import grow_tree_batched
from lightgbm_tpu.ops.split import SplitHyper
from lightgbm_tpu.ops.table import take_small_table

rng = np.random.default_rng(0)
f = 28
MAX_BIN = PBIN
from lightgbm_tpu.io.dataset import device_bins_pow2
N_BINS = device_bins_pow2(MAX_BIN)
w = rng.normal(size=f)
feat = rng.normal(size=(N, f)).astype(np.float32)
logits = feat @ w * 0.5
label = (logits + rng.normal(scale=1.0, size=N) > 0).astype(np.float32)
qs = np.quantile(feat[:100_000], np.linspace(0, 1, MAX_BIN)[1:-1], axis=0)
bins = np.empty((N, f), np.uint8)
for j in range(f):
    bins[:, j] = np.searchsorted(qs[:, j], feat[:, j]).astype(np.uint8)

bins_d = jnp.asarray(bins)
label_d = jnp.asarray(label)
num_bins = jnp.full((f,), MAX_BIN, jnp.int32)
nan_bin = jnp.full((f,), -1, jnp.int32)
is_cat = jnp.zeros((f,), bool)

hp = SplitHyper(num_leaves=LEAVES, min_data_in_leaf=0,
                min_sum_hessian_in_leaf=100.0, n_bins=N_BINS,
                rows_per_block=8192,
                hist_dtype=os.environ.get("PDTYPE", "int8"))

ITERS = 3
QUANTIZE = hp.hist_dtype == "int8"
if QUANTIZE:
    from lightgbm_tpu.ops.quantize import discretize_gradients_levels


@jax.jit
def run(scores, bins_a, label_a):
    def step(scores, i):
        sign = jnp.where(label_a > 0, 1.0, -1.0)
        resp = -sign / (1.0 + jnp.exp(sign * scores))
        grad = resp
        hess = jnp.abs(resp) * (1.0 - jnp.abs(resp))
        hist_scale = None
        if QUANTIZE:
            key = jax.random.fold_in(jax.random.PRNGKey(7), i)
            grad, hess, gs, hs = discretize_gradients_levels(
                grad, hess, key, n_levels=4, stochastic=True)
            hist_scale = jnp.stack([gs, hs])
        tree, leaf_of_row = grow_tree_batched(
            bins_a, grad, hess, None, num_bins, nan_bin, is_cat,
            None, hp, batch=K, hist_scale=hist_scale)
        return scores + 0.1 * take_small_table(tree.leaf_value,
                                               leaf_of_row), None
    scores, _ = jax.lax.scan(step, scores, jnp.arange(ITERS))
    return scores


scores = jnp.zeros(N, jnp.float32)
out = run(scores, bins_d, label_d)
float(out[0])

tdir = "/tmp/jaxprof"
os.system(f"rm -rf {tdir}")
with jax.profiler.trace(tdir):
    out = run(scores, bins_d, label_d)
    float(out[0])

# parse trace
files = glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True)
assert files, os.popen(f"find {tdir} | head -50").read()
with gzip.open(files[0], "rt") as fh:
    trace = json.load(fh)

events = trace["traceEvents"]
# find device lanes: pid whose process name mentions TPU/device
pid_names = {}
tid_names = {}
for e in events:
    if e.get("ph") == "M":
        if e.get("name") == "process_name":
            pid_names[e["pid"]] = e["args"].get("name", "")
        if e.get("name") == "thread_name":
            tid_names[(e["pid"], e["tid"])] = e["args"].get("name", "")

agg = defaultdict(float)
cnt = defaultdict(int)
total = 0.0
for e in events:
    if e.get("ph") != "X":
        continue
    pname = pid_names.get(e["pid"], "")
    tname = tid_names.get((e["pid"], e["tid"]), "")
    if "TPU" not in pname and "tpu" not in pname.lower():
        continue
    if "step" in tname.lower():
        continue  # step lane duplicates
    name = e.get("name", "?")
    dur = e.get("dur", 0) / 1e3  # ms
    agg[name] += dur
    cnt[name] += 1
    total += dur

print(f"# lanes: {set(pid_names.values())}")
print(f"# total device time: {total:.1f} ms over {ITERS} iters "
      f"=> {total/ITERS:.1f} ms/tree  (K={K})")
rows = sorted(agg.items(), key=lambda kv: -kv[1])[:45]
for name, ms in rows:
    print(f"{ms/ITERS:9.2f} ms/tree  x{cnt[name]//ITERS:<5} {name[:110]}")
