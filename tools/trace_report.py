#!/usr/bin/env python
"""Summarize a Chrome trace JSON produced by ``trace_output=<path>``.

    python tools/trace_report.py TRACE.json [--top N]

Prints the top phases by total time (total / count / avg / max), the
span-tree depth, and — when the trace carries ``memory`` counter events
(telemetry_output set alongside trace_output) — the memory high-water
marks.  The numbers here are host wall-clock spans (dispatch + any host
sync); use a ``profile_dir`` jax.profiler capture for device-side kernel
attribution.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: empty or not JSON ({e}) — was the "
                             "trace session exported?") from e
    if isinstance(doc, list):          # bare event-array form is also legal
        doc = {"traceEvents": doc}
    if "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome trace (no traceEvents)")
    return doc


def phase_stats(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Aggregate complete (``ph: X``) events by name."""
    agg: Dict[str, Dict[str, float]] = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") != "X":
            continue
        dur = float(ev.get("dur", 0.0))
        s = agg.setdefault(ev["name"], {"total_us": 0.0, "count": 0,
                                        "max_us": 0.0})
        s["total_us"] += dur
        s["count"] += 1
        s["max_us"] = max(s["max_us"], dur)
    rows = []
    for name, s in agg.items():
        rows.append({
            "name": name,
            "total_s": s["total_us"] / 1e6,
            "count": int(s["count"]),
            "avg_ms": s["total_us"] / s["count"] / 1e3,
            "max_ms": s["max_us"] / 1e3,
        })
    rows.sort(key=lambda r: -r["total_s"])
    return rows


def memory_high_water(doc: Dict[str, Any]) -> Dict[str, float]:
    """Max of each ``memory`` counter-track series (``ph: C``)."""
    high: Dict[str, float] = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") != "C" or ev.get("name") != "memory":
            continue
        for k, v in (ev.get("args") or {}).items():
            if isinstance(v, (int, float)):
                high[k] = max(high.get(k, float("-inf")), float(v))
    return high


def render(doc: Dict[str, Any], top: int = 15) -> str:
    rows = phase_stats(doc)
    lines = []
    if not rows:
        lines.append("no complete (ph=X) span events in trace")
    else:
        width = max(len(r["name"]) for r in rows[:top])
        lines.append(f"{'phase'.ljust(width)}   total_s   count    avg_ms"
                     f"    max_ms")
        for r in rows[:top]:
            lines.append(f"{r['name'].ljust(width)}  {r['total_s']:8.3f}"
                         f"  {r['count']:6d}  {r['avg_ms']:8.2f}"
                         f"  {r['max_ms']:8.2f}")
        if len(rows) > top:
            lines.append(f"... {len(rows) - top} more phases "
                         f"(--top {len(rows)} for all)")
    high = memory_high_water(doc)
    if high:
        lines.append("")
        lines.append("memory high-water marks:")
        for k in sorted(high):
            v = high[k]
            unit = " MB" if k.endswith("_mb") else \
                (" bytes" if "bytes" in k else "")
            lines.append(f"  {k}: {v:,.2f}{unit}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="Chrome trace JSON (trace_output=...)")
    ap.add_argument("--top", type=int, default=15,
                    help="phases to show (default 15)")
    args = ap.parse_args(argv)
    print(render(load_trace(args.trace), top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
