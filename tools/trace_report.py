#!/usr/bin/env python
"""Summarize a Chrome trace JSON produced by ``trace_output=<path>``.

    python tools/trace_report.py TRACE.json [--top N] [--format text|json]

Prints the top phases by total time (total / count / avg / max), the
span-tree depth, and — when the trace carries ``memory`` counter events
(telemetry_output set alongside trace_output) — the memory high-water
marks.  The numbers here are host wall-clock spans (dispatch + any host
sync); use a ``profile_dir`` jax.profiler capture for device-side kernel
attribution.

Exit codes (tools/_report.py convention): 0 — trace has span events,
1 — parseable but empty trace (no ``ph: X`` events), 2 — unreadable or
not a Chrome trace.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _report import (EXIT_ERROR, EXIT_FINDINGS, EXIT_OK,  # noqa: E402
                     add_format_arg, emit)


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: empty or not JSON ({e}) — was the "
                             "trace session exported?") from e
    if isinstance(doc, list):          # bare event-array form is also legal
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome trace (no traceEvents)")
    return doc


def phase_stats(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Aggregate complete (``ph: X``) events by name."""
    agg: Dict[str, Dict[str, float]] = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") != "X":
            continue
        dur = float(ev.get("dur", 0.0))
        s = agg.setdefault(ev["name"], {"total_us": 0.0, "count": 0,
                                        "max_us": 0.0})
        s["total_us"] += dur
        s["count"] += 1
        s["max_us"] = max(s["max_us"], dur)
    rows = []
    for name, s in agg.items():
        rows.append({
            "name": name,
            "total_s": s["total_us"] / 1e6,
            "count": int(s["count"]),
            "avg_ms": s["total_us"] / s["count"] / 1e3,
            "max_ms": s["max_us"] / 1e3,
        })
    rows.sort(key=lambda r: -r["total_s"])
    return rows


def memory_high_water(doc: Dict[str, Any]) -> Dict[str, float]:
    """Max of each ``memory`` counter-track series (``ph: C``)."""
    high: Dict[str, float] = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") != "C" or ev.get("name") != "memory":
            continue
        for k, v in (ev.get("args") or {}).items():
            if isinstance(v, (int, float)):
                high[k] = max(high.get(k, float("-inf")), float(v))
    return high


def build_report(doc: Dict[str, Any], trace: str = "",
                 top: int = 15) -> Dict[str, Any]:
    """The full report payload (all phases — ``top`` only trims text)."""
    return {
        "tool": "trace_report",
        "trace": trace,
        "phases": phase_stats(doc),
        "memory_high_water": memory_high_water(doc),
        "top": top,
    }


def _render_report(payload: Dict[str, Any]) -> str:
    rows = payload["phases"]
    top = payload.get("top", 15)
    lines = []
    if not rows:
        lines.append("no complete (ph=X) span events in trace")
    else:
        width = max(len(r["name"]) for r in rows[:top])
        lines.append(f"{'phase'.ljust(width)}   total_s   count    avg_ms"
                     f"    max_ms")
        for r in rows[:top]:
            lines.append(f"{r['name'].ljust(width)}  {r['total_s']:8.3f}"
                         f"  {r['count']:6d}  {r['avg_ms']:8.2f}"
                         f"  {r['max_ms']:8.2f}")
        if len(rows) > top:
            lines.append(f"... {len(rows) - top} more phases "
                         f"(--top {len(rows)} for all)")
    high = payload["memory_high_water"]
    if high:
        lines.append("")
        lines.append("memory high-water marks:")
        for k in sorted(high):
            v = high[k]
            unit = " MB" if k.endswith("_mb") else \
                (" bytes" if "bytes" in k else "")
            lines.append(f"  {k}: {v:,.2f}{unit}")
    return "\n".join(lines)


def render(doc: Dict[str, Any], top: int = 15) -> str:
    """Back-compat helper: text report straight from a loaded trace."""
    return _render_report(build_report(doc, top=top))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="Chrome trace JSON (trace_output=...)")
    ap.add_argument("--top", type=int, default=15,
                    help="phases to show (default 15)")
    add_format_arg(ap)
    args = ap.parse_args(argv)
    try:
        doc = load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"trace_report: {e}", file=sys.stderr)
        return EXIT_ERROR
    payload = build_report(doc, trace=args.trace, top=args.top)
    emit(payload, args.format, _render_report)
    return EXIT_OK if payload["phases"] else EXIT_FINDINGS


if __name__ == "__main__":
    sys.exit(main())
