#!/usr/bin/env python
"""Summarize a Chrome trace JSON produced by ``trace_output=<path>``.

    python tools/trace_report.py TRACE.json [--top N] [--events E.jsonl]
                                            [--format text|json]

Prints the top phases by total time (total / count / avg / max), the
span-tree depth, and — when the trace carries ``memory`` counter events
(telemetry_output set alongside trace_output) — the memory high-water
marks.  The numbers here are host wall-clock spans (dispatch + any host
sync); use a ``profile_dir`` jax.profiler capture for device-side kernel
attribution.

Merged multi-rank traces (obs/merge.py — the coordinator writes one
when per-rank cluster traces exist) carry an ``lgbtpu`` metadata block;
the report then adds the rank/epoch inventory and a per-rank span
breakdown.  ``--events journal.jsonl`` overlays the structured event
journal (obs/events.py): event counts by name/severity and the
error-severity timeline.

Exit codes (tools/_report.py convention): 0 — trace has span events,
1 — parseable but empty trace (no ``ph: X`` events), 2 — unreadable or
not a Chrome trace (or an unreadable --events file).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _report import (EXIT_ERROR, EXIT_FINDINGS, EXIT_OK,  # noqa: E402
                     add_format_arg, emit)


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: empty or not JSON ({e}) — was the "
                             "trace session exported?") from e
    if isinstance(doc, list):          # bare event-array form is also legal
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome trace (no traceEvents)")
    return doc


def phase_stats(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Aggregate complete (``ph: X``) events by name."""
    agg: Dict[str, Dict[str, float]] = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") != "X":
            continue
        dur = float(ev.get("dur", 0.0))
        s = agg.setdefault(ev["name"], {"total_us": 0.0, "count": 0,
                                        "max_us": 0.0})
        s["total_us"] += dur
        s["count"] += 1
        s["max_us"] = max(s["max_us"], dur)
    rows = []
    for name, s in agg.items():
        rows.append({
            "name": name,
            "total_s": s["total_us"] / 1e6,
            "count": int(s["count"]),
            "avg_ms": s["total_us"] / s["count"] / 1e3,
            "max_ms": s["max_us"] / 1e3,
        })
    rows.sort(key=lambda r: -r["total_s"])
    return rows


def load_events(path: str) -> List[Dict[str, Any]]:
    """Journal rows from an obs/events.py JSONL file.  Torn trailing
    lines (a writer killed mid-append) are skipped, matching
    ``events.read_journal``; this stays stdlib-only so the report tools
    never import the package."""
    rows: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict) and "event" in row:
                rows.append(row)
    return rows


def event_stats(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    by_name: Dict[str, int] = {}
    by_severity: Dict[str, int] = {}
    errors: List[Dict[str, Any]] = []
    for row in rows:
        name = str(row.get("event"))
        sev = str(row.get("severity", "info"))
        by_name[name] = by_name.get(name, 0) + 1
        by_severity[sev] = by_severity.get(sev, 0) + 1
        if sev == "error":
            errors.append({"event": name, "rank": row.get("rank"),
                           "round": row.get("round"),
                           "unix_time": row.get("unix_time")})
    return {"count": len(rows), "by_name": by_name,
            "by_severity": by_severity, "errors": errors}


def rank_stats(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Per-rank span totals of a merged multi-rank trace (pid == rank;
    pid -1 is the coordinator's journal overlay)."""
    agg: Dict[int, Dict[str, float]] = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") != "X" or "pid" not in ev:
            continue
        s = agg.setdefault(int(ev["pid"]), {"total_us": 0.0, "count": 0})
        s["total_us"] += float(ev.get("dur", 0.0))
        s["count"] += 1
    return [{"rank": rank, "span_total_s": s["total_us"] / 1e6,
             "span_count": int(s["count"])}
            for rank, s in sorted(agg.items())]


def memory_high_water(doc: Dict[str, Any]) -> Dict[str, float]:
    """Max of each ``memory`` counter-track series (``ph: C``)."""
    high: Dict[str, float] = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") != "C" or ev.get("name") != "memory":
            continue
        for k, v in (ev.get("args") or {}).items():
            if isinstance(v, (int, float)):
                high[k] = max(high.get(k, float("-inf")), float(v))
    return high


def build_report(doc: Dict[str, Any], trace: str = "",
                 top: int = 15,
                 events: Optional[List[Dict[str, Any]]] = None
                 ) -> Dict[str, Any]:
    """The full report payload (all phases — ``top`` only trims text)."""
    payload = {
        "tool": "trace_report",
        "trace": trace,
        "phases": phase_stats(doc),
        "memory_high_water": memory_high_water(doc),
        "top": top,
    }
    side = doc.get("lgbtpu")
    if isinstance(side, dict) and side.get("merged"):
        payload["merged"] = {"ranks": side.get("ranks", []),
                             "epochs": side.get("epochs", []),
                             "sources": side.get("sources", [])}
        payload["per_rank"] = rank_stats(doc)
    if events is not None:
        payload["events"] = event_stats(events)
    return payload


def _render_report(payload: Dict[str, Any]) -> str:
    rows = payload["phases"]
    top = payload.get("top", 15)
    lines = []
    if not rows:
        lines.append("no complete (ph=X) span events in trace")
    else:
        width = max(len(r["name"]) for r in rows[:top])
        lines.append(f"{'phase'.ljust(width)}   total_s   count    avg_ms"
                     f"    max_ms")
        for r in rows[:top]:
            lines.append(f"{r['name'].ljust(width)}  {r['total_s']:8.3f}"
                         f"  {r['count']:6d}  {r['avg_ms']:8.2f}"
                         f"  {r['max_ms']:8.2f}")
        if len(rows) > top:
            lines.append(f"... {len(rows) - top} more phases "
                         f"(--top {len(rows)} for all)")
    high = payload["memory_high_water"]
    if high:
        lines.append("")
        lines.append("memory high-water marks:")
        for k in sorted(high):
            v = high[k]
            unit = " MB" if k.endswith("_mb") else \
                (" bytes" if "bytes" in k else "")
            lines.append(f"  {k}: {v:,.2f}{unit}")
    merged = payload.get("merged")
    if merged:
        lines.append("")
        lines.append(f"merged multi-rank trace: ranks {merged['ranks']}, "
                     f"elastic epochs {merged['epochs']}")
        for r in payload.get("per_rank", []):
            who = "coordinator" if r["rank"] < 0 else f"rank {r['rank']}"
            lines.append(f"  {who}: {r['span_count']} spans, "
                         f"{r['span_total_s']:.3f}s total")
    ev = payload.get("events")
    if ev is not None:
        lines.append("")
        lines.append(f"event journal: {ev['count']} record(s)")
        for name in sorted(ev["by_name"]):
            lines.append(f"  {name}: {ev['by_name'][name]}")
        if ev["errors"]:
            lines.append("  error-severity timeline:")
            for e in ev["errors"]:
                lines.append(f"    {e['event']} (rank {e['rank']}, "
                             f"round {e['round']})")
    return "\n".join(lines)


def render(doc: Dict[str, Any], top: int = 15) -> str:
    """Back-compat helper: text report straight from a loaded trace."""
    return _render_report(build_report(doc, top=top))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="Chrome trace JSON (trace_output=...)")
    ap.add_argument("--top", type=int, default=15,
                    help="phases to show (default 15)")
    ap.add_argument("--events", default=None, metavar="JOURNAL",
                    help="overlay an event-journal JSONL "
                         "(event_output=...)")
    add_format_arg(ap)
    args = ap.parse_args(argv)
    try:
        doc = load_trace(args.trace)
        events = load_events(args.events) if args.events else None
    except (OSError, ValueError) as e:
        print(f"trace_report: {e}", file=sys.stderr)
        return EXIT_ERROR
    payload = build_report(doc, trace=args.trace, top=args.top,
                           events=events)
    emit(payload, args.format, _render_report)
    return EXIT_OK if payload["phases"] else EXIT_FINDINGS


if __name__ == "__main__":
    sys.exit(main())
