#!/usr/bin/env python3
"""Live "top"-style watchtower dashboard over the repo's JSONL streams.

Tails the per-iteration telemetry, per-request serving telemetry, and
event-journal JSONL files a run was configured with (``--telemetry`` /
``--serving`` / ``--events``), rank-merged via the ``<root>.e<E>.r<R>``
convention (obs/merge.py naming — the base path plus every per-rank
sibling is followed).  Rows feed the same rollup/SLO machinery the
package uses in-process (obs/timeseries.py + obs/slo.py, loaded here BY
FILE PATH — this tool never imports jax, or the package, so it runs
beside a live cluster without stealing a device or recompiling
anything).

Renders four panes in-terminal: training rounds (round_s, compile
hits/misses, eval metrics), serving (latency percentiles, throughput,
inflight/queue), SLO state (per-name ok/BREACHED with burn-rate
violation counts), and the most recent journal events.  ``--fleet
<workdir>`` adds a per-replica pane over a serving fleet's
incarnation-namespaced telemetry siblings and lists any crash
flight-recorder dumps found under ``<workdir>/flight``.

Modes: default is a live loop redrawn every ``--interval`` seconds;
``--once`` renders one frame and exits (CI artifact / smoke check);
``--html`` writes a static HTML render to the given path.  Exit codes
follow tools/_report.py: 0 healthy, 1 at least one SLO currently
breached (``--once`` only), 2 no usable input.
"""

from __future__ import annotations

import argparse
import glob
import html as _html
import importlib.util
import json
import os
import re
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OBS_DIR = os.path.join(REPO_ROOT, "lightgbm_tpu", "obs")

#: obs/merge.py rank-file convention, re-implemented locally: importing
#: the package would import jax (lightgbm_tpu/__init__.py)
_RANK_RE = re.compile(r"\.e(\d+)\.r(\d+)(\.[^.]+)?$")

EXIT_OK = 0
EXIT_FINDINGS = 1
EXIT_ERROR = 2


def _load_obs_module(name: str):
    """Load lightgbm_tpu/obs/<name>.py standalone by file path.  The
    modules are stdlib-only by contract (asserted in
    tests/test_watchtower.py under a jax-poisoned interpreter)."""
    key = f"_obs_top_{name}"
    if key in sys.modules:
        return sys.modules[key]
    spec = importlib.util.spec_from_file_location(
        key, os.path.join(_OBS_DIR, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[key] = mod
    spec.loader.exec_module(mod)
    return mod


timeseries = _load_obs_module("timeseries")
slo_mod = _load_obs_module("slo")


# ------------------------------------------------------------ file tailing
def expand_rank_files(base: str) -> List[str]:
    """``base`` plus every ``<root>.e<E>.r<R><ext>`` sibling, sorted by
    (epoch, rank) — the merged view obs/merge.py produces at rest."""
    out = [base] if os.path.exists(base) else []
    root, ext = os.path.splitext(base)
    ranked: List[Tuple[int, int, str]] = []
    for path in glob.glob(glob.escape(root) + ".e*.r*" + ext):
        m = _RANK_RE.search(path)
        if m:
            ranked.append((int(m.group(1)), int(m.group(2)), path))
    out.extend(p for _, _, p in sorted(ranked))
    return out


class Tail:
    """Incremental JSONL reader over a base path + rank siblings.
    Re-globs on every poll (ranks appear mid-run under elastic
    reshapes) and remembers a byte offset per file; a shrunk file
    (truncation/rewrite) is re-read from the top."""

    def __init__(self, base: str) -> None:
        self.base = base
        self._offsets: Dict[str, int] = {}
        self.files_seen = 0

    def poll(self) -> List[Dict[str, Any]]:
        rows: List[Dict[str, Any]] = []
        files = expand_rank_files(self.base) if self.base else []
        self.files_seen = len(files)
        for path in files:
            try:
                size = os.path.getsize(path)
                off = self._offsets.get(path, 0)
                if size < off:
                    off = 0
                if size == off:
                    continue
                with open(path, "r", encoding="utf-8") as fh:
                    fh.seek(off)
                    chunk = fh.read()
                    self._offsets[path] = fh.tell()
            except OSError:
                continue
            for line in chunk.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue   # torn tail write — picked up next poll
        return rows


class FleetView:
    """Per-replica pane state over a serving fleet's workdir
    (serving/fleet.py layout, obs/merge.py ``find_fleet_artifacts``
    naming re-implemented locally — importing the package would import
    jax).  Replica telemetry siblings live at
    ``<workdir>/obs/serving.jsonl.e<incarnation>.r<slot>`` and crash
    flight-recorder dumps at ``<workdir>/flight/flight.e*.r*.json``."""

    def __init__(self, workdir: str) -> None:
        self.workdir = workdir
        self._tails: Dict[str, Tail] = {}
        #: (slot, incarnation) -> {"rows", "last"} aggregated per file
        self.replicas: Dict[Tuple[int, int], Dict[str, Any]] = {}

    def _scan(self, base: str) -> List[Tuple[int, int, str]]:
        root, ext = os.path.splitext(base)
        found = []
        for path in glob.glob(glob.escape(root) + ".e*.r*" + ext):
            m = _RANK_RE.search(path)
            if m:           # epoch position carries the incarnation
                found.append((int(m.group(2)), int(m.group(1)), path))
        return sorted(found)

    def flight_dumps(self) -> List[Tuple[int, int, str]]:
        return self._scan(os.path.join(self.workdir, "flight",
                                       "flight.json"))

    def poll(self) -> int:
        files = self._scan(os.path.join(self.workdir, "obs",
                                        "serving.jsonl"))
        for slot, inc, path in files:
            tail = self._tails.get(path)
            if tail is None:
                tail = self._tails[path] = Tail(path)
            agg = self.replicas.setdefault(
                (slot, inc), {"rows": 0, "last": None})
            for row in tail.poll():
                agg["rows"] += 1
                agg["last"] = row
        return len(files)


# ----------------------------------------------------------- aggregation
class Watch:
    """The dashboard's state: one rollup fed from all three streams,
    an SLO evaluator over its windows, and the raw tails for the
    training/serving/events panes."""

    def __init__(self, telemetry: str = "", serving: str = "",
                 events: str = "", window_s: float = 10.0,
                 slo_spec: str = "on", fleet: str = "") -> None:
        self.fleet = FleetView(fleet) if fleet else None
        if fleet and not serving:
            # the fleet's default per-replica telemetry base feeds the
            # aggregate SERVING pane too
            serving = os.path.join(fleet, "obs", "serving.jsonl")
        self.tails = {"telemetry": Tail(telemetry),
                      "serving": Tail(serving),
                      "events": Tail(events)}
        self.rollup = timeseries.Rollup(window_s=window_s,
                                        max_windows=720)
        self.slo = slo_mod.SloEvaluator(slo_spec)
        for name in self.slo.enabled:
            self.slo.watch_slo(name)
        self.last_training: Optional[Dict[str, Any]] = None
        self.last_serving: Optional[Dict[str, Any]] = None
        self.recent_events: List[Dict[str, Any]] = []
        self.rows_total = 0

    def poll(self, force_flush: bool = False) -> None:
        if self.fleet is not None:
            self.fleet.poll()
        for row in self.tails["telemetry"].poll():
            timeseries.feed_telemetry_row(self.rollup, row)
            self.last_training = row
            self.rows_total += 1
        for row in self.tails["serving"].poll():
            timeseries.feed_serving_row(self.rollup, row)
            self.last_serving = row
            self.rows_total += 1
        for rec in self.tails["events"].poll():
            timeseries.feed_journal_record(self.rollup, rec)
            self.recent_events.append(rec)
            self.rows_total += 1
        self.recent_events = self.recent_events[-200:]
        # close the live window once its span is over on the WALL clock
        # (a stalled stream must not park a breach in a never-closed
        # window); --once flushes unconditionally so historical fixture
        # sets evaluate their final window too
        cur = self.rollup.current()
        if cur is not None and (force_flush
                                or cur["t_end"] <= time.time()):
            self.rollup.flush()
        self.slo.evaluate(self.rollup.completed())

    def inputs_seen(self) -> int:
        n = sum(t.files_seen for t in self.tails.values())
        if self.fleet is not None:
            n += len(self.fleet.replicas)
        return n

    def breached(self) -> List[str]:
        return self.slo.breached()


# -------------------------------------------------------------- rendering
def _fmt(v: Any, nd: int = 3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _series(watch: Watch, kind: str, name: str) -> Optional[Dict[str, Any]]:
    """Latest value row for a gauge/sample/counter across the ring
    (newest window that observed it), preferring the live window."""
    windows = watch.rollup.completed()
    cur = watch.rollup.current()
    if cur is not None:
        windows = windows + [cur]
    for w in reversed(windows):
        row = (w.get(kind) or {}).get(name)
        if row is not None:
            return row
    return None


def render_frame(watch: Watch, now: Optional[float] = None) -> str:
    now = time.time() if now is None else now
    lines: List[str] = []
    lines.append("lgbtpu obs_top — %s   windows=%d   rows=%d"
                 % (time.strftime("%H:%M:%S", time.localtime(now)),
                    len(watch.rollup.completed()), watch.rows_total))

    lines.append("")
    lines.append("TRAINING")
    tr = watch.last_training
    if tr is None:
        lines.append("  (no telemetry rows)")
    else:
        rs = _series(watch, "samples", "round_s") or {}
        counters = tr.get("counters") or {}
        lines.append("  round=%s  round_s p50=%s p99=%s max=%s"
                     % (_fmt(tr.get("iteration")), _fmt(rs.get("p50")),
                        _fmt(rs.get("p99")), _fmt(rs.get("max"))))
        lines.append("  compile hits/misses=%s/%s  fused hits/misses=%s/%s"
                     "  nan_trips=%s"
                     % (_fmt(counters.get("round_compile_hits", 0)),
                        _fmt(counters.get("round_compile_misses", 0)),
                        _fmt(counters.get("fused_runner_cache_hits", 0)),
                        _fmt(counters.get("fused_runner_cache_misses", 0)),
                        _fmt(counters.get("nan_guard_trips", 0))))
        evals = tr.get("evals") or {}
        if evals:
            parts = []
            for k in sorted(evals)[:4]:
                v = evals[k]
                v = v[0] if isinstance(v, (list, tuple)) else v
                parts.append(f"{k}={_fmt(v, 6)}")
            lines.append("  evals: " + "  ".join(parts))

    lines.append("")
    lines.append("SERVING")
    lat = _series(watch, "samples", "latency_ms")
    if lat is None:
        lines.append("  (no serving rows)")
    else:
        req = _series(watch, "counters", "serve_requests") or {}
        inflight = _series(watch, "gauges", "serve_inflight") or {}
        queue = _series(watch, "gauges", "serve_queue_depth") or {}
        lines.append("  latency_ms p50=%s p95=%s p99=%s max=%s (n=%s)"
                     % (_fmt(lat.get("p50")), _fmt(lat.get("p95")),
                        _fmt(lat.get("p99")), _fmt(lat.get("max")),
                        _fmt(lat.get("count"))))
        lines.append("  req/s=%s  inflight=%s  queue=%s"
                     % (_fmt(req.get("rate")), _fmt(inflight.get("last")),
                        _fmt(queue.get("last"))))

    if watch.fleet is not None:
        lines.append("")
        lines.append("FLEET REPLICAS (%s)" % watch.fleet.workdir)
        if not watch.fleet.replicas:
            lines.append("  (no replica telemetry yet)")
        for (slot, inc) in sorted(watch.fleet.replicas):
            agg = watch.fleet.replicas[(slot, inc)]
            last = agg["last"] or {}
            tid = last.get("trace_id")
            lat = last.get("latency_s")
            lines.append("  slot=%d inc=%d  rows=%d  last latency_ms=%s"
                         "  rows/req=%s%s"
                         % (slot, inc, agg["rows"],
                            _fmt(lat * 1000.0 if isinstance(
                                lat, (int, float)) else None),
                            _fmt(last.get("rows")),
                            f"  trace={tid}" if tid else ""))
        dumps = watch.fleet.flight_dumps()
        if dumps:
            lines.append("  flight dumps: " + "  ".join(
                "%s (slot %d inc %d)" % (os.path.basename(p), s, i)
                for s, i, p in dumps))

    lines.append("")
    lines.append("SLO")
    state = watch.slo.state()
    if not state:
        lines.append("  (no SLOs enabled)")
    for name in sorted(state):
        st = state[name]
        flag = "ok      " if st["ok"] else "BREACHED"
        lines.append("  %-26s %s  last=%-10s budget=%s(%s)  "
                     "violations=%d/%d"
                     % (name, flag, _fmt(st["last_value"]),
                        _fmt(st["budget"]), st["direction"],
                        st["violations"], st["history_windows"]))

    lines.append("")
    lines.append("EVENTS (last %d)" % min(len(watch.recent_events), 8))
    if not watch.recent_events:
        lines.append("  (no journal records)")
    for rec in watch.recent_events[-8:]:
        t = rec.get("unix_time")
        stamp = time.strftime("%H:%M:%S", time.localtime(t)) \
            if isinstance(t, (int, float)) else "--:--:--"
        payload = rec.get("payload") or {}
        extra = " ".join(f"{k}={payload[k]}" for k in sorted(payload)[:3])
        lines.append("  %s  %-9s %-24s %s"
                     % (stamp, str(rec.get("severity", "")),
                        str(rec.get("event", "?")), extra))
    return "\n".join(lines) + "\n"


def render_html(watch: Watch) -> str:
    frame = render_frame(watch)
    breached = watch.breached()
    color = "#b00020" if breached else "#2e7d32"
    status = ("BREACHED: " + ", ".join(breached)) if breached else "healthy"
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>lgbtpu obs_top</title></head><body "
            "style='font-family:monospace;background:#111;color:#ddd'>"
            f"<h2 style='color:{color}'>watchtower: "
            f"{_html.escape(status)}</h2>"
            f"<pre>{_html.escape(frame)}</pre></body></html>\n")


# ------------------------------------------------------------------- main
def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="obs_top.py",
        description="live watchtower dashboard over telemetry/serving/"
                    "journal JSONL (stdlib-only; never imports jax)")
    ap.add_argument("--telemetry", default="",
                    help="telemetry_output base path (rank siblings "
                         "<root>.e<E>.r<R> are followed)")
    ap.add_argument("--serving", default="",
                    help="serving_telemetry_output base path")
    ap.add_argument("--events", default="",
                    help="event_output journal base path")
    ap.add_argument("--fleet", default="",
                    help="serving fleet workdir — adds a per-replica "
                         "pane (incarnation-namespaced telemetry under "
                         "<dir>/obs plus crash flight-recorder dumps "
                         "under <dir>/flight)")
    ap.add_argument("--window", type=float, default=10.0,
                    help="rollup window seconds (default 10)")
    ap.add_argument("--slo", default="on",
                    help="slo_config spec to evaluate while tailing "
                         "(default: on = every declared SLO)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="live-mode redraw seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (exit 1 if an SLO "
                         "is currently breached, 2 if no inputs)")
    ap.add_argument("--html", default="",
                    help="also write a static HTML render to this path")
    args = ap.parse_args(argv)

    if not (args.telemetry or args.serving or args.events or args.fleet):
        print("obs_top: no inputs — pass --telemetry/--serving/--events"
              "/--fleet", file=sys.stderr)
        return EXIT_ERROR
    try:
        watch = Watch(args.telemetry, args.serving, args.events,
                      window_s=args.window, slo_spec=args.slo,
                      fleet=args.fleet)
    except ValueError as e:
        print(f"obs_top: {e}", file=sys.stderr)
        return EXIT_ERROR

    if args.once:
        watch.poll(force_flush=True)
        if watch.inputs_seen() == 0:
            print("obs_top: no input files found", file=sys.stderr)
            return EXIT_ERROR
        sys.stdout.write(render_frame(watch))
        if args.html:
            with open(args.html, "w", encoding="utf-8") as fh:
                fh.write(render_html(watch))
        return EXIT_FINDINGS if watch.breached() else EXIT_OK

    try:
        while True:
            watch.poll()
            frame = render_frame(watch)
            sys.stdout.write("\x1b[2J\x1b[H" + frame)
            sys.stdout.flush()
            if args.html:
                with open(args.html, "w", encoding="utf-8") as fh:
                    fh.write(render_html(watch))
            time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
