#!/usr/bin/env python
"""tpulint — JAX/TPU-aware static analysis over this repo (jax-free).

    python tools/tpulint.py [paths...] [--format text|json]
    python tools/tpulint.py --list-rules

Loads ``lightgbm_tpu/analysis`` by FILE PATH (never importing
``lightgbm_tpu/__init__``, which pulls in jax), so the whole lint gate
is pure-stdlib AST work and runs in seconds on one CPU.  ``python -m
lightgbm_tpu.analysis`` is the equivalent package entry point.

Exit codes (tools/_report.py convention): 0 clean, 1 findings,
2 usage/internal error.
"""

from __future__ import annotations

import importlib.util
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG_NAME = "_tpulint_analysis"


def load_analysis():
    """Import lightgbm_tpu/analysis as a standalone package.

    The synthetic package name keeps relative imports inside analysis/
    working while bypassing ``lightgbm_tpu/__init__`` entirely.
    """
    if _PKG_NAME in sys.modules:
        return sys.modules[_PKG_NAME]
    pkg_dir = os.path.join(REPO_ROOT, "lightgbm_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        _PKG_NAME, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[_PKG_NAME] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    analysis = load_analysis()
    return analysis.main(argv)


if __name__ == "__main__":
    sys.exit(main())
