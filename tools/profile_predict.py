"""Profile the device batch predictor on a live chip (device-lane HLO
aggregation, same parsing as profile_bench).

Usage: PCAT=1 PROWS=1000000 PTREES=100 python tools/profile_predict.py

PSERVE=1 profiles the serving tier instead: requests of mixed sizes
stream through a warmed PredictionServer (bucket ladder from PBUCKETS,
default "64,4096,65536"), so the trace shows the bucket-padded
leaf-index programs rather than the raw batch predictor.
"""
import glob
import gzip
import json
import os
import sys
from collections import defaultdict

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CAT = bool(int(os.environ.get("PCAT", "1")))
N = int(os.environ.get("PROWS", "1000000"))
TREES = int(os.environ.get("PTREES", "100"))
SERVE = bool(int(os.environ.get("PSERVE", "0")))
BUCKETS = sorted({int(b) for b in
                  os.environ.get("PBUCKETS", "64,4096,65536").split(",")})

import jax
import lightgbm_tpu as lgb

rng = np.random.default_rng(5)
n_train = 200_000
if CAT:
    Xt = np.concatenate([rng.normal(size=(n_train, 24)),
                         rng.integers(0, 30, size=(n_train, 4)).astype(float)],
                        axis=1)
    p = {"objective": "binary", "verbose": -1, "num_leaves": 255,
         "categorical_feature": [24, 25, 26, 27], "min_data_in_leaf": 20}
else:
    Xt = rng.normal(size=(n_train, 28))
    p = {"objective": "binary", "verbose": -1, "num_leaves": 255,
         "min_data_in_leaf": 20}
y = (Xt[:, 0] + rng.normal(scale=0.5, size=n_train) > 0.5).astype(np.float64)
bst = lgb.train(p, lgb.Dataset(Xt, label=y, params=p),
                num_boost_round=TREES)
gb = bst._gbdt
X = np.concatenate([rng.normal(size=(N, 24)),
                    rng.integers(0, 32, size=(N, 4)).astype(float)],
                   axis=1) if CAT else rng.normal(size=(N, 28))
if SERVE:
    from lightgbm_tpu.serving import PredictionServer
    srv = PredictionServer({"serving_buckets": BUCKETS})
    srv.publish("prof", booster=bst, warmup=True)   # warm = all buckets
    # mixed request sizes, one per bucket range, repeated
    sizes = [max(1, b - b // 3) for b in BUCKETS if b <= N] * 4

    def profiled():
        for n in sizes:
            srv.predict("prof", X[:n])
else:
    def profiled():
        gb.predict_raw(X)
    profiled()             # warm

tdir = "/tmp/jaxprof_pred"
os.system(f"rm -rf {tdir}")
with jax.profiler.trace(tdir):
    profiled()

files = glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True)
with gzip.open(files[0], "rt") as fh:
    trace = json.load(fh)
events = trace["traceEvents"]
pid_names, tid_names = {}, {}
for e in events:
    if e.get("ph") == "M":
        if e.get("name") == "process_name":
            pid_names[e["pid"]] = e["args"].get("name", "")
        if e.get("name") == "thread_name":
            tid_names[(e["pid"], e["tid"])] = e["args"].get("name", "")
agg, cnt, total = defaultdict(float), defaultdict(int), 0.0
for e in events:
    if e.get("ph") != "X":
        continue
    if "TPU" not in pid_names.get(e["pid"], ""):
        continue
    if "step" in tid_names.get((e["pid"], e["tid"]), "").lower():
        continue
    agg[e.get("name", "?")] += e.get("dur", 0) / 1e3
    cnt[e.get("name", "?")] += 1
    total += e.get("dur", 0) / 1e3
print(f"# total device time: {total:.1f} ms ({TREES} trees)")
for name, ms in sorted(agg.items(), key=lambda kv: -kv[1])[:25]:
    print(f"{ms:9.1f} ms  x{cnt[name]:<6} {name[:100]}")
