#!/bin/bash
# Build the reference LightGBM CLI from /root/reference source for the
# same-host baseline capture (BASELINE.json reference_same_host_same_data).
#
# Why not cmake: the reference requires cmake >= 3.28; this image ships
# 3.25.  Why shims: the vendored fmt / fast_double_parser submodules are
# EMPTY in this checkout; the reference uses exactly one fmt API
# (format_to_n with "{}"/"{:g}"/"{:.17g}", utils/common.h:1203) and one
# fast_double_parser API (parse_number, utils/common.h:356), which
# tools/ref_shims/ implements freshly (snprintf / strtod).  Eigen (for
# linear_tree_learner.cpp) comes from TensorFlow's bundled copy.
#
# Usage: tools/build_reference_cli.sh [outdir=.refbuild]
set -euo pipefail
cd "$(dirname "$0")/.."
OUT=${1:-.refbuild}
mkdir -p "$OUT"
rm -rf "$OUT/shim" && cp -r tools/ref_shims "$OUT/shim"
EIGEN=/opt/venv/lib/python3.12/site-packages/tensorflow/include
g++ -O3 -std=c++17 -fopenmp -DUSE_SOCKET -DMM_MALLOC=1 -DEIGEN_MPL2_ONLY \
  -I"$OUT/shim" -I/root/reference/include -I"$EIGEN" \
  /root/reference/src/boosting/*.cpp /root/reference/src/io/*.cpp \
  /root/reference/src/metric/*.cpp /root/reference/src/network/*.cpp \
  /root/reference/src/objective/objective_function.cpp \
  /root/reference/src/treelearner/*.cpp \
  /root/reference/src/utils/openmp_wrapper.cpp \
  /root/reference/src/application/application.cpp \
  /root/reference/src/main.cpp \
  -o "$OUT/lightgbm-ref" -lpthread
echo "built $OUT/lightgbm-ref"
