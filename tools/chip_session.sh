#!/bin/bash
# One-shot on-chip measurement battery for when the axon tunnel answers.
# Captures, in order of evidence value:
#   1. headline kernel bench (seeds bench_cache.json for the driver)
#   2. fused-kernel A/B (round-4 payload + partition kernels vs XLA paths)
#   3. K sweep spot checks
#   4. auto-speed-mode e2e train() bench
# Every section appends to docs/CHIP_SESSION.log; safe to re-run.
set -u
cd "$(dirname "$0")/.."
LOG=docs/CHIP_SESSION.log
stamp() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

echo "=== chip session $(stamp) ===" >> "$LOG"

echo "[probe]" | tee -a "$LOG"
if ! timeout 120 python -c "
import jax.numpy as jnp
y=(jnp.ones((256,256))@jnp.ones((256,256))); y.block_until_ready()
print('TUNNEL_ALIVE')" >> "$LOG" 2>&1; then
  echo "tunnel dead, aborting $(stamp)" | tee -a "$LOG"
  exit 1
fi

echo "[1/4 headline bench $(stamp)]" | tee -a "$LOG"
timeout 2400 python bench.py 2>&1 | tail -1 | tee -a "$LOG"

echo "[2/4 fuse A/B $(stamp)]" | tee -a "$LOG"
for mode in "" "LGBMTPU_NO_PAYLOAD_KERNEL=1" \
            "LGBMTPU_NO_FUSED_PARTITION=1" \
            "LGBMTPU_NO_PAYLOAD_KERNEL=1 LGBMTPU_NO_FUSED_PARTITION=1"; do
  echo "-- env: [$mode]" | tee -a "$LOG"
  env $mode timeout 1800 python tools/sweep_perf.py k=28 2>&1 | tail -1 \
    | tee -a "$LOG"
done

echo "[3/4 K sweep $(stamp)]" | tee -a "$LOG"
timeout 2400 python tools/sweep_perf.py k=16 k=20 k=32 2>&1 | tail -3 \
  | tee -a "$LOG"

echo "[4/4 e2e auto-mode $(stamp)]" | tee -a "$LOG"
BENCH_E2E=1 BENCH_ROWS=1000000 BENCH_ITERS=20 timeout 3600 \
  python bench.py 2>&1 | tail -1 | tee -a "$LOG"

echo "=== done $(stamp) ===" | tee -a "$LOG"
