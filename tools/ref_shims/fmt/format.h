// Minimal fmt shim for building the reference CLI without its vendored
// submodule (empty in this checkout).  The reference uses exactly one fmt
// API: fmt::format_to_n(buf, n, fmt, value) with format strings "{}",
// "{:g}" and "{:.17g}" (include/LightGBM/utils/common.h:1203).
#pragma once
#include <cstdio>
#include <cstring>
#include <string>
#include <type_traits>

namespace fmt {

struct format_to_n_result_shim { size_t size; };

template <typename T>
inline format_to_n_result_shim format_to_n(char* buf, size_t n,
                                           const char* f, T value) {
  char out[512];
  int len;
  if (std::strcmp(f, "{:.17g}") == 0) {
    len = snprintf(out, sizeof(out), "%.17g", static_cast<double>(value));
  } else if (std::strcmp(f, "{:g}") == 0) {
    len = snprintf(out, sizeof(out), "%g", static_cast<double>(value));
  } else if (std::is_floating_point<T>::value) {
    len = snprintf(out, sizeof(out), "%.17g", static_cast<double>(value));
  } else if (std::is_signed<T>::value) {
    len = snprintf(out, sizeof(out), "%lld",
                   static_cast<long long>(value));
  } else {
    len = snprintf(out, sizeof(out), "%llu",
                   static_cast<unsigned long long>(value));
  }
  size_t m = static_cast<size_t>(len) < n ? static_cast<size_t>(len) : n;
  std::memcpy(buf, out, m);
  return format_to_n_result_shim{static_cast<size_t>(len)};
}

}  // namespace fmt
