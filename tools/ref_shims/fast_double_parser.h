// Minimal fast_double_parser shim (vendored submodule empty in this
// checkout).  API used: parse_number(p, out) -> end pointer or nullptr
// (include/LightGBM/utils/common.h:356).  strtod is slower but exact.
#pragma once
#include <cstdlib>

namespace fast_double_parser {

inline const char* parse_number(const char* p, double* out) {
  char* end = nullptr;
  *out = std::strtod(p, &end);
  return end == p ? nullptr : end;
}

}  // namespace fast_double_parser
