#!/usr/bin/env python
"""Benchmark dataset construction: in-memory vs out-of-core streaming.

    JAX_PLATFORMS=cpu python tools/bench_ingest.py \
        [--rows N] [--features F] [--chunk-sizes 50000,100000,200000]

Builds the same synthetic dataset through ``Dataset.from_data`` (whole
matrix in RAM) and through ``io/streaming.py`` at several chunk sizes,
capturing rows/s and PEAK memory footprint per variant.  Each variant
runs in its own subprocess so the high-water mark is that variant's, not
the max over earlier variants — the same isolation the CI memory-ceiling
gate leans on (tests/test_streaming.py).  The footprint is VmRSS+VmSwap
polled by a sampler thread, NOT ``ru_maxrss``: a forked child inherits
the parent's high-water (a worker spawned from a fat pytest process
reports the parent's peak), and zram swap on a loaded host deflates the
RSS high-water while the array still exists in swap.

``--hosts N`` (N >= 2) adds a sharded multi-process variant per chunk
size: the stripe-ledger build (io/sharded.py) with N real ingest worker
processes, reported as the AGGREGATE rows/s the coordinator observed —
the scaling headline docs/SCALING.md "Sharded ingestion" quotes.

Emits a ``kind="ingest"`` payload (``"metric"`` headline per the bench
capture protocol) that tools/bench_compare.py gates: rows/s per variant,
HIGHER is better, exit 0/1/2 per tools/_report.py.

Worker mode (internal, one variant per process):

    python tools/bench_ingest.py --worker streamed --rows N \
        --features F --chunk-rows C
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _report import EXIT_ERROR, EXIT_OK, add_format_arg, emit  # noqa: E402

#: columns 0..F/2 are low-cardinality (exact-tally path), the rest are
#: continuous (overflowing the tally into the sketch at 2M-row scale)
_LOW_CARD = 100


def _ru_maxrss_mb() -> float:
    ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # linux reports KB, macOS bytes
    return ru / 1024.0 if sys.platform.startswith("linux") \
        else ru / (1024.0 * 1024.0)


def _footprint_mb() -> float:
    """Current VmRSS+VmSwap of THIS process.  Two reasons not to trust
    ``ru_maxrss`` here: (1) a forked child inherits the parent's
    high-water, so a worker spawned from a fat pytest process reports
    the *parent's* peak and every delta against the baseline collapses;
    (2) zram swap on a loaded host steals pages mid-build, deflating the
    RSS high-water while the array still exists (in swap)."""
    try:
        vals = {"VmRSS": 0.0, "VmSwap": 0.0}
        with open("/proc/self/status") as fh:
            for line in fh:
                key = line.split(":", 1)[0]
                if key in vals:
                    vals[key] = float(line.split()[1])  # kB
        return (vals["VmRSS"] + vals["VmSwap"]) / 1024.0
    except (OSError, IndexError, ValueError):
        return _ru_maxrss_mb()


class _FootprintSampler:
    """Daemon thread polling the footprint every few ms: numpy releases
    the GIL inside large ops, so the poll catches the peak while the
    build is in flight."""

    def __init__(self, interval_s: float = 0.005):
        import threading
        self.peak = 0.0
        self._stop = threading.Event()
        self._interval = interval_s
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.sample()

    def sample(self) -> None:
        self.peak = max(self.peak, _footprint_mb())

    def stop(self) -> float:
        self._stop.set()
        self._thread.join(timeout=1.0)
        self.sample()
        return self.peak


def synth_chunk(chunk_idx: int, rows: int, features: int) -> "Any":
    """One deterministic synthetic chunk: identical bytes every time a
    pass re-streams chunk ``chunk_idx`` (the re-streamability contract
    of ChunkSource), without ever materializing the full matrix."""
    import numpy as np
    rng = np.random.default_rng(10_000 + chunk_idx)
    data = rng.normal(size=(rows, features))
    for j in range(features // 2):
        data[:, j] = rng.integers(0, _LOW_CARD, rows)
    return data


class SyntheticSource:
    """Generator-backed ChunkSource over ``synth_chunk`` — the streamed
    variants' input, O(chunk) resident."""

    kind = "synthetic"

    def __init__(self, num_rows: int, num_features: int, chunk_rows: int):
        self.num_rows = int(num_rows)
        self.num_features = int(num_features)
        self.chunk_rows = int(chunk_rows)

    def fingerprint(self) -> Dict[str, Any]:
        return {"kind": self.kind, "num_rows": self.num_rows,
                "num_features": self.num_features,
                "chunk_rows": self.chunk_rows}

    def chunks(self, start_chunk: int = 0):
        from lightgbm_tpu.io.streaming import RawChunk
        idx = start_chunk
        lo = start_chunk * self.chunk_rows
        while lo < self.num_rows:
            rows = min(self.chunk_rows, self.num_rows - lo)
            yield RawChunk(synth_chunk(idx, rows, self.num_features))
            lo += rows
            idx += 1


def run_worker(variant: str, rows: int, features: int,
               chunk_rows: Optional[int],
               hosts: int = 0) -> Dict[str, Any]:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np  # noqa: F401  (baseline includes numpy+package)
    import lightgbm_tpu  # noqa: F401
    rss_base = _footprint_mb()
    if variant == "baseline":
        return {"peak_rss_mb": rss_base, "rss_base_mb": rss_base}
    t0 = time.perf_counter()
    sampler = _FootprintSampler()
    if variant == "in_memory":
        from lightgbm_tpu.io.dataset import Dataset
        parts = [synth_chunk(i, min(chunk_rows or rows, rows - lo),
                             features)
                 for i, lo in enumerate(range(0, rows,
                                              chunk_rows or rows))]
        data = np.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
        sampler.sample()
        del parts
        label = (data[:, -1] > 0).astype(np.float64)
        ds = Dataset.from_data(data, label, {})
        sampler.sample()
        ds.packed_mirror()
    elif variant == "streamed":
        from lightgbm_tpu.io.streaming import stream_inner_dataset
        assert chunk_rows, "streamed worker needs --chunk-rows"
        src = SyntheticSource(rows, features, chunk_rows)
        ds = stream_inner_dataset(src, label=np.zeros(rows), config={},
                                  chunk_rows=chunk_rows)
        binned = list(ds.bins.shape)
    elif variant == "sharded":
        # multi-host mode: the stripe-ledger build (io/sharded.py) with
        # ``hosts`` real worker processes; this process coordinates and
        # merges, so rows/s here is the AGGREGATE ingest rate
        import tempfile

        from lightgbm_tpu.io.sharded import (SyntheticChunkSource,
                                             shard_stream_inner_dataset)
        assert chunk_rows, "sharded worker needs --chunk-rows"
        assert hosts >= 2, "sharded worker needs --hosts >= 2"
        src = SyntheticChunkSource(rows, features, chunk_rows)
        with tempfile.TemporaryDirectory() as td:
            ds = shard_stream_inner_dataset(
                src, config={"ingest_workers": hosts, "verbosity": -1},
                workdir=td, chunk_rows=chunk_rows)
            binned = list(ds.bins.shape)
            wall = time.perf_counter() - t0
            peak = max(rss_base, sampler.stop())
            return {
                "wall_s": round(wall, 3),
                "rows_per_s": round(rows / wall, 1),
                "peak_rss_mb": round(peak, 1),
                "rss_base_mb": round(rss_base, 1),
                "hosts": hosts,
                "binned_shape": binned,
            }
    else:
        raise SystemExit(f"unknown worker variant {variant!r}")
    if variant == "in_memory":
        binned = list(ds.bins.shape)
    wall = time.perf_counter() - t0
    peak = max(rss_base, sampler.stop())
    return {
        "wall_s": round(wall, 3),
        "rows_per_s": round(rows / wall, 1),
        "peak_rss_mb": round(peak, 1),
        "rss_base_mb": round(rss_base, 1),
        "binned_shape": binned,
    }


def spawn_worker(variant: str, rows: int, features: int,
                 chunk_rows: Optional[int] = None,
                 hosts: int = 0) -> Dict[str, Any]:
    cmd = [sys.executable, os.path.abspath(__file__), "--worker", variant,
           "--rows", str(rows), "--features", str(features)]
    if chunk_rows:
        cmd += ["--chunk-rows", str(chunk_rows)]
    if hosts:
        cmd += ["--hosts", str(hosts)]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(cmd, capture_output=True, text=True, env=env)
    if out.returncode != 0:
        raise RuntimeError(f"worker {variant} failed "
                           f"(rc={out.returncode}):\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def _render(payload: Dict[str, Any]) -> str:
    lines = [f"bench_ingest: {payload['rows']} rows x "
             f"{payload['features']} features"]
    lines.append("  %-18s %12s %12s %10s"
                 % ("variant", "rows/s", "peak RSS MB", "wall s"))
    for name, r in payload["variants"].items():
        lines.append("  %-18s %12.0f %12.1f %10.2f"
                     % (name, r.get("rows_per_s", 0),
                        r.get("peak_rss_mb", 0), r.get("wall_s", 0)))
    base = payload.get("rss_base_mb")
    if base is not None:
        lines.append(f"  (import-only baseline RSS: {base:.1f} MB)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rows", type=int, default=500_000)
    ap.add_argument("--features", type=int, default=16)
    ap.add_argument("--chunk-sizes", default="50000,100000",
                    help="comma-separated streamed chunk sizes")
    ap.add_argument("--hosts", type=int, default=0,
                    help="also run the sharded multi-process build "
                         "(io/sharded.py stripe ledger) with N ingest "
                         "worker processes; rows/s is the aggregate "
                         "rate the coordinator observed")
    ap.add_argument("--worker", default=None,
                    help=argparse.SUPPRESS)  # internal: run ONE variant
    ap.add_argument("--chunk-rows", type=int, default=None,
                    help=argparse.SUPPRESS)
    add_format_arg(ap)
    args = ap.parse_args(argv)

    if args.worker:
        res = run_worker(args.worker, args.rows, args.features,
                         args.chunk_rows, hosts=args.hosts)
        print(json.dumps(res))
        return EXIT_OK

    chunk_sizes = [int(s) for s in args.chunk_sizes.split(",") if s]
    try:
        base = spawn_worker("baseline", args.rows, args.features)
        variants: Dict[str, Any] = {
            "in_memory": spawn_worker("in_memory", args.rows,
                                      args.features, chunk_sizes[0]),
        }
        for cs in chunk_sizes:
            variants[f"streamed_{cs}"] = spawn_worker(
                "streamed", args.rows, args.features, cs)
        if args.hosts >= 2:
            for cs in chunk_sizes:
                variants[f"sharded_{args.hosts}h_{cs}"] = spawn_worker(
                    "sharded", args.rows, args.features, cs,
                    hosts=args.hosts)
    except (RuntimeError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_ingest: error: {e}", file=sys.stderr)
        return EXIT_ERROR
    payload = {
        "tool": "bench_ingest",
        "kind": "ingest",
        "metric": f"ingest_construct_{args.rows}x{args.features}",
        "platform": sys.platform,
        "rows": args.rows,
        "features": args.features,
        "rss_base_mb": base.get("peak_rss_mb"),
        "variants": variants,
    }
    emit(payload, args.format, _render)
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
